"""Batched JAX codec vs the scalar oracle (which is golden-validated)."""

import base64
import json
import math

import numpy as np
import pytest

from tests.conftest import DATA_DIR
from m3_tpu.encoding.m3tsz import decode_series, encode_series
from m3_tpu.encoding.m3tsz_jax import decode_batch, encode_batch

START = 1_600_000_000 * 10**9


def _mk_batch(T=200, seed=0):
    rng = np.random.default_rng(seed)
    S = 8
    ts = np.tile(START + (np.arange(1, T + 1) * 10 * 10**9), (S, 1)).astype(np.int64)
    vals = np.zeros((S, T))
    starts = np.full(S, START, np.int64)
    vals[0] = np.arange(T) % 50
    vals[1] = 42.0
    vals[2] = np.round(rng.normal(100, 10, T), 2)
    vals[3] = rng.normal(0, 1, T)
    vals[4] = np.where(np.arange(T) % 3 == 0, 1.5, 7.0)
    vals[5] = np.cumsum(rng.integers(0, 100, T)).astype(float)
    vals[6] = 0.0
    vals[7] = rng.choice([1e9, 2.25, -5.0, 0.001], T)
    return ts, vals, starts


def test_encode_batch_bit_exact_vs_oracle():
    ts, vals, starts = _mk_batch()
    streams, fb = encode_batch(ts, vals, starts, out_words=400)
    assert not fb.any()
    for s in range(len(streams)):
        want = encode_series(list(zip(ts[s].tolist(), vals[s].tolist())), start=START)
        assert streams[s] == want, f"series {s} not bit-exact"


def test_encode_sig_tracker_grow_keeps_lower_streak():
    """Regression (round-5 bench device-vs-native byte stage): the sig
    hysteresis tracker must NOT reset its lower-sig streak counter on a
    GROW step — Go's TrackNewSig (int_sig_bits_tracker.go:68-91) only
    resets on the within-threshold branch.  Resetting on grow desynced
    the shrink timing on grow-interleaved diff streams (22/2000 corpus
    series encoded valid-but-different bytes)."""
    # The start of the corpus series that exposed it: 2-decimal gauge
    # jitter whose scaled diffs alternate 12/13-bit sigs with occasional
    # small (shrink-eligible) diffs.
    v = [788.5, 788.3, 781.61, 809.0, 772.39, 737.82, 818.48, 763.77,
         791.88, 811.21, 780.2, 768.78, 804.75, 749.49, 793.32, 782.65,
         776.91, 749.03, 772.37, 772.22, 781.1, 821.35, 796.27, 817.2,
         761.17, 771.68, 795.72, 798.38, 801.82, 773.14, 819.55, 745.29]
    T = len(v)
    ts = (START + np.arange(1, T + 1) * 10 * 10**9)[None, :].astype(np.int64)
    vals = np.asarray(v)[None, :]
    streams, fb = encode_batch(ts, vals, np.full(1, START, np.int64),
                               out_words=120)
    assert not fb.any()
    want = encode_series(list(zip(ts[0].tolist(), vals[0].tolist())),
                         start=START)
    assert streams[0] == want


def test_encode_batch_hard_cases():
    T = 120
    rng = np.random.default_rng(3)
    S = 6
    ts = np.tile(START + (np.arange(1, T + 1) * 10**9), (S, 1)).astype(np.int64)
    starts = np.full(S, START, np.int64)
    vals = np.zeros((S, T))
    vals[0] = np.where(np.arange(T) % 7 == 0, np.nan, 3.0)
    vals[1] = rng.choice([0.1, 0.25, 1 / 3, 123456.789], T)
    ts[2] = START + np.cumsum(rng.choice([10**9, 2 * 10**9, 60 * 10**9], T))
    vals[2] = 5.0
    starts[3] = START + 123  # unaligned start -> TU marker on first datapoint
    ts[3] = starts[3] + np.cumsum(np.full(T, 10**9))
    vals[3] = np.arange(T).astype(float)
    ts[4, 50:] -= 5 * 10**9  # negative delta-of-delta
    vals[4] = 17.0
    vals[5] = np.repeat(rng.normal(50, 5, T // 4).round(4), 4)[:T]
    streams, fb = encode_batch(ts, vals, starts, out_words=400)
    assert not fb.any()
    for s in range(S):
        want = encode_series(list(zip(ts[s].tolist(), vals[s].tolist())),
                             start=int(starts[s]))
        assert streams[s] == want, f"hard series {s} not bit-exact"


def test_encode_variable_counts():
    ts, vals, starts = _mk_batch(T=100)
    counts = np.array([100, 50, 10, 99, 1, 100, 3, 77])
    streams, fb = encode_batch(ts, vals, starts, counts=counts, out_words=400)
    assert not fb.any()
    for s in range(len(streams)):
        n = counts[s]
        want = encode_series(list(zip(ts[s, :n].tolist(), vals[s, :n].tolist())),
                             start=START)
        assert streams[s] == want


def test_encode_overflow_flags_fallback():
    # random floats at ~70 bits/pt cannot fit a 16-bit/pt budget
    rng = np.random.default_rng(1)
    T = 500
    ts = np.tile(START + np.arange(1, T + 1) * 10**9, (2, 1)).astype(np.int64)
    vals = rng.normal(0, 1, (2, T))
    streams, fb = encode_batch(ts, vals, np.full(2, START, np.int64))
    assert fb.all()
    assert streams[0] == b""


def test_encode_precision_limit_flags_fallback():
    T = 4
    ts = np.tile(START + np.arange(1, T + 1) * 10**9, (1, 1)).astype(np.int64)
    vals = np.full((1, T), float(2**60))
    _, fb = encode_batch(ts, vals, np.full(1, START, np.int64), out_words=50)
    assert fb.all()


def test_decode_batch_golden_corpus():
    with open(DATA_DIR / "m3tsz_sample_series.json") as f:
        streams = [base64.b64decode(s) for s in json.load(f)]
    ts, vals, counts, fb = decode_batch(streams, max_points=1500)
    assert not fb.any()
    for i, s in enumerate(streams):
        want = decode_series(s)
        n = int(counts[i])
        assert n == len(want)
        assert ts[i][:n].tolist() == [d.timestamp for d in want]
        for a, b in zip(vals[i][:n].tolist(), (d.value for d in want)):
            assert a == b or (math.isnan(a) and math.isnan(b))


def test_roundtrip_batched():
    ts, vals, starts = _mk_batch(T=150, seed=5)
    streams, fb = encode_batch(ts, vals, starts, out_words=400)
    assert not fb.any()
    ts2, vals2, counts, fb2 = decode_batch(streams, max_points=200)
    assert not fb2.any()
    assert (counts == 150).all()
    assert (ts2[:, :150] == ts).all()
    assert np.allclose(vals2[:, :150], vals, rtol=0, atol=0, equal_nan=True)


def test_decode_annotation_stream_default_flags_fallback():
    """By default annotated streams still flag fallback (the annotation
    BYTES are skipped on device and callers may need them)."""
    from m3_tpu.core.xtime import Unit
    from m3_tpu.encoding.m3tsz import Datapoint, Encoder

    enc = Encoder(START)
    enc.encode(Datapoint(START + 10**9, 1.0, Unit.SECOND, b"schema"))
    enc.encode(Datapoint(START + 2 * 10**9, 2.0, Unit.SECOND))
    _, _, _, fb = decode_batch([enc.stream()], max_points=10)
    assert fb.all()


def test_decode_annotation_stream_rides_device_path():
    """With annotations_fallback=False, annotated streams decode on
    device: values/timestamps exact, each annotation eats one slot."""
    from m3_tpu.core.xtime import Unit
    from m3_tpu.encoding.m3tsz import Datapoint, Encoder

    enc = Encoder(START)
    enc.encode(Datapoint(START + 10**9, 1.5, Unit.SECOND, b"schema-v1"))
    enc.encode(Datapoint(START + 2 * 10**9, 2.5, Unit.SECOND))
    # mid-stream annotation CHANGE plus more points
    enc.encode(Datapoint(START + 3 * 10**9, 3.5, Unit.SECOND, b"schema-v2"))
    enc.encode(Datapoint(START + 4 * 10**9, 4.5, Unit.SECOND))
    ts, vals, counts, fb = decode_batch(
        [enc.stream()], max_points=12, annotations_fallback=False)
    assert not fb.any()
    n = int(counts[0])
    assert n == 4
    assert ts[0][:n].tolist() == [START + (k + 1) * 10**9 for k in range(4)]
    assert vals[0][:n].tolist() == [1.5, 2.5, 3.5, 4.5]


def test_decode_large_annotation_window_jump():
    """An annotation bigger than the decoder's 2048-bit window forces
    the full window-reload path; the stream must still decode."""
    from m3_tpu.core.xtime import Unit
    from m3_tpu.encoding.m3tsz import Datapoint, Encoder

    big = bytes(range(256)) * 3  # 768 bytes = 6144 bits >> window
    enc = Encoder(START)
    enc.encode(Datapoint(START + 10**9, 7.25, Unit.SECOND, big))
    for k in range(2, 40):
        enc.encode(Datapoint(START + k * 10**9, float(k), Unit.SECOND))
    ts, vals, counts, fb = decode_batch(
        [enc.stream()], max_points=50, annotations_fallback=False)
    assert not fb.any()
    assert int(counts[0]) == 39
    assert vals[0][0] == 7.25 and vals[0][38] == 39.0


def test_encode_first_datapoint_annotation_bit_exact():
    """encode_batch(annotations=...) must produce byte-identical streams
    to the scalar encoder writing the same first-dp annotation."""
    from m3_tpu.core.xtime import Unit
    from m3_tpu.encoding.m3tsz import Datapoint, Encoder

    T = 30
    ts = np.tile(START + np.arange(1, T + 1) * 10**9, (3, 1)).astype(np.int64)
    vals = np.round(np.arange(3)[:, None] + np.arange(T)[None, :] * 0.5, 1)
    anns = [b"proto-schema-A", None, b"x" * 100]
    streams, fb = encode_batch(ts, vals, np.full(3, START, np.int64),
                               out_words=200, annotations=anns)
    assert not fb.any()
    for i in range(3):
        enc = Encoder(START)
        for k in range(T):
            enc.encode(Datapoint(int(ts[i, k]), float(vals[i, k]),
                                 Unit.SECOND, anns[i] or b""))
        assert streams[i] == enc.stream(), f"series {i} not bit-exact"
    # and the scalar decoder returns the annotation from the batched bytes
    from m3_tpu.encoding.m3tsz import decode_series as _ds
    pts = _ds(streams[0])
    assert pts[0].annotation == b"proto-schema-A"


def test_saturated_int64_values_flag_fallback():
    # Integral |v| >= 2^63 saturates to INT64_MIN and aliases distinct values;
    # must be routed to the scalar codec (regression).
    T = 3
    ts = np.tile(START + np.arange(1, T + 1) * 10**9, (1, 1)).astype(np.int64)
    vals = np.array([[-1e300, -2e300, -1e300]])
    _, fb = encode_batch(ts, vals, np.full(1, START, np.int64), out_words=50)
    assert fb.all()


def test_dod_32bit_overflow_flags_fallback():
    # > 2^31 seconds between points overflows the 32-bit default bucket; the
    # reference raises OverflowError, the device path must flag fallback.
    ts = np.array([[START + 10**9, START + 10**9 + (2**32) * 10**9]])
    vals = np.ones((1, 2))
    _, fb = encode_batch(ts, vals, np.full(1, START, np.int64), out_words=80)
    assert fb.all()


def test_decode_exactly_max_points_not_flagged():
    dps = [(START + (i + 1) * 10**9, float(i)) for i in range(5)]
    stream = encode_series(dps, start=START)
    ts, vals, counts, fb = decode_batch([stream], max_points=5)
    assert not fb.any()
    assert counts[0] == 5
    assert ts[0].tolist() == [t for t, _ in dps]


def test_encode_zero_count_series_empty():
    ts, vals, starts = _mk_batch(T=10)
    counts = np.array([10, 0, 5, 0, 10, 10, 10, 10])
    streams, fb = encode_batch(ts, vals, starts, counts=counts, out_words=50)
    assert not fb.any()
    assert streams[1] == b"" and streams[3] == b""
    assert streams[0] != b""


def test_batched_decode_mixed_unit_streams():
    """Streams produced by the per-datapoint-unit encoder (round-4
    precision fix: TU markers mid-stream) decode exactly on the device
    path too — the is_tu branch handles every switch."""
    from m3_tpu.encoding.m3tsz import encode_series
    from m3_tpu.encoding.m3tsz_jax import decode_batch

    SEC = 10**9
    start = 1_699_992_000 * SEC
    pts = [(start + 10**10, 1.0), (start + 2 * 10**10 + 7, 2.0),
           (start + 3 * 10**10, 3.0), (start + 4 * 10**10 + 7000, 4.5)]
    blob = encode_series(pts, start=start)
    ts, vals, counts, fb = decode_batch([blob], max_points=16)
    assert not fb[0] and counts[0] == 4
    got = list(zip(ts[0, :4].tolist(), vals[0, :4].tolist()))
    assert got == pts


def test_encode_gather_placement_byte_identical(monkeypatch):
    """The TPU (gather/cumsum) word-placement form must produce the
    SAME bytes as the scatter form, validated against the scalar
    oracle.  u64 cumsum-diff is exact under wraparound, so identity
    must hold bit for bit.  This used to need a SUBPROCESS because
    M3_ENCODE_PLACE was read under the tracer and in-process flips
    were silently frozen at the first compile; round 7 moved the
    resolution into the host wrapper (resolved_place -> static arg),
    so the same coverage now runs in-process with a monkeypatched
    env."""
    import numpy as np

    from m3_tpu.encoding.m3tsz import encode_series
    from m3_tpu.encoding.m3tsz_jax import encode_batch, resolved_place

    monkeypatch.setenv("M3_ENCODE_PLACE", "gather")
    assert resolved_place() == "gather"
    rng = np.random.default_rng(2)
    S, T = 16, 360
    start = 1_700_000_000 * 10**9
    ts = start + np.cumsum(rng.integers(1, 3, (S, T)), axis=1) * 10**10
    vals = np.round(rng.normal(50, 20, (S, T)), 2)
    streams, fb = encode_batch(ts, vals, np.full(S, start, np.int64),
                               out_words=T * 40 // 64 + 8)
    assert not fb.any()
    for i in range(S):
        oracle = encode_series(list(zip(ts[i].tolist(), vals[i].tolist())),
                               start=start)
        assert streams[i] == oracle, f"series {i} diverged"


def test_encode_place_env_flip_works_in_process(monkeypatch):
    """Round-7 retrace-risk regression: M3_ENCODE_PLACE used to be
    read UNDER the tracer, so an in-process env flip after the first
    encode changed NOTHING (the jit cache keyed on the static args,
    not the env).  The seam now resolves in the host wrapper and rides
    as a static argument: flipping the env must actually select the
    other placement (observable as a fresh compile cache entry) and
    stay byte-identical."""
    import numpy as np

    from m3_tpu.encoding import m3tsz_jax as mj

    rng = np.random.default_rng(5)
    S, T = 4, 48
    start = 1_700_000_000 * 10**9
    ts = start + np.cumsum(rng.integers(1, 3, (S, T)), axis=1) * 10**10
    vals = np.round(rng.normal(50, 20, (S, T)), 2)
    starts = np.full(S, start, np.int64)

    monkeypatch.delenv("M3_ENCODE_PLACE", raising=False)
    # tests pin the CPU backend: auto = the scatter-free gather form
    # (pallas only ever auto-resolves on a real TPU backend)
    assert mj.resolved_place() == "gather"
    a, fb_a = mj.encode_batch(ts, vals, starts, out_words=T * 40 // 64 + 8)
    size_gather = mj._encode_batch_device._cache_size()

    monkeypatch.setenv("M3_ENCODE_PLACE", "scatter")
    assert mj.resolved_place() == "scatter"
    b, fb_b = mj.encode_batch(ts, vals, starts, out_words=T * 40 // 64 + 8)
    # the flip actually took: the scatter form is a new static signature
    assert mj._encode_batch_device._cache_size() > size_gather
    assert not fb_a.any() and not fb_b.any()
    assert a == b  # placement forms are byte-identical by contract

    monkeypatch.setenv("M3_ENCODE_PLACE", "bogus")
    import pytest

    with pytest.raises(ValueError, match="M3_ENCODE_PLACE"):
        mj.resolved_place()


def test_encoder_bytes_pinned_across_dtype_hardening():
    """Bit-identity pin for the m3lint explicit-dtype hardening: this
    fixed batch was verified byte-identical before/after dtype= was
    made explicit in m3tsz_jax.py, and the digest pins it forever.
    Any change to a constructor's effective dtype — including a future
    x64-default flip the explicit dtypes now guard against — shows up
    here as a byte diff, not as a silent re-encode.

    Inputs are pure integer/dyadic arithmetic (no RNG, no libm): every
    value is exactly representable, so the batch is bit-stable across
    NumPy versions and platforms — the digest depends on the encoder
    alone."""
    import hashlib

    from m3_tpu.encoding.m3tsz_jax import pack_streams

    SEC = 10**9
    S0 = 1_600_000_000 * SEC
    S, T = 8, 64
    i = np.arange(S, dtype=np.int64)[:, None]
    j = np.arange(T, dtype=np.int64)[None, :]
    deltas = ((i * 37 + j * 11) % 29 + 1) * SEC        # 1..29s steps
    ts = S0 + np.cumsum(deltas, axis=1)
    vals = ((i * 131 + j * 17) % 4001 - 2000) / 8.0    # dyadic: exact f64
    vals[2] = np.float64((j[0] * 7) % 1000)            # int-optimized lane
    vals[5, 10:] = vals[5, 9]                          # repeated-value lane
    streams, fb = encode_batch(ts, vals, np.full(S, S0, np.int64),
                               out_words=200)
    assert not fb.any(), fb
    words, nbits = pack_streams(streams)
    assert words.dtype == np.uint64 and nbits.dtype == np.int64
    h = hashlib.sha256()
    h.update(np.ascontiguousarray(words).tobytes())
    h.update(np.ascontiguousarray(nbits).tobytes())
    assert h.hexdigest() == PINNED_ENCODE_DIGEST


# sha256 over (packed words || nbits) of the arithmetic batch above,
# captured on BOTH the pre-dtype-hardening tree (HEAD file) and the
# hardened tree — identical, proving the hardening was a no-op on the
# bytes.
PINNED_ENCODE_DIGEST = (
    "27ea67c4b75585a1e2bffa6cfeae5e5faeefbaca75de4d5c4c559f15d89ccc18")
