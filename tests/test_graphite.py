"""Graphite engine: parser, glob resolution, render functions, HTTP.

Reference model: `src/query/graphite` (lexer/native engine, ~100 fns)
and the carbon `__g{i}__` tag convention shared with the ingest path.
"""

import json
import math
import urllib.parse
import urllib.request

import numpy as np
import pytest

from m3_tpu.metrics.carbon import path_to_document
from m3_tpu.query.graphite import (
    Call, GraphiteEngine, GraphiteStorage, ParseError, PathExpr,
    glob_component_regex, parse_graphite_time, parse_target,
    supported_functions,
)
from m3_tpu.storage.database import Database, DatabaseOptions, NamespaceOptions

BLOCK = 2 * 3600 * 10**9
START = (1_700_000_000 * 10**9) // BLOCK * BLOCK
STEP = 10 * 10**9
NS = NamespaceOptions(num_shards=2, slot_capacity=1 << 10,
                      sample_capacity=1 << 12)


class TestParser:
    def test_nested_calls(self):
        ast = parse_target("scale(sumSeries(a.b.*, c.d), 2)")
        assert isinstance(ast, Call) and ast.name == "scale"
        inner = ast.args[0]
        assert inner.name == "sumSeries"
        assert inner.args == (PathExpr("a.b.*"), PathExpr("c.d"))
        assert ast.args[1] == 2

    def test_strings_kwargs_and_floats(self):
        ast = parse_target('summarize(a.b, "1h", func="max")')
        assert ast.args[1] == "1h"
        assert dict(ast.kwargs) == {"func": "max"}
        assert parse_target("scale(a, -0.5)").args[1] == -0.5

    def test_bad_input(self):
        for bad in ("f(", "a.b)", "f(a,)", 'alias(a, "x'):
            with pytest.raises(ParseError):
                parse_target(bad)

    def test_glob_translation(self):
        assert glob_component_regex("web*") == "web[^.]*"
        assert glob_component_regex("w?b") == "w[^.]b"
        assert glob_component_regex("{web,db}01") == "(?:web|db)01"
        assert glob_component_regex("host[0-9]") == "host[0-9]"

    def test_time_parsing(self):
        now = 1000 * 10**9
        assert parse_graphite_time("now", now) == now
        assert parse_graphite_time("-1h", now) == now - 3600 * 10**9
        assert parse_graphite_time("500", now) == 500 * 10**9

    def test_leading_digit_paths(self):
        ast = parse_target("sumSeries(404.count, 5xx.rate)")
        assert ast.args == (PathExpr("404.count"), PathExpr("5xx.rate"))
        # plain numbers still parse as numbers
        assert parse_target("scale(a, 2)").args[1] == 2

    def test_signed_durations(self):
        from m3_tpu.query.graphite import _duration_nanos

        assert _duration_nanos("1h") == 3600 * 10**9
        assert _duration_nanos("-1h") == -3600 * 10**9


def _seed_db(tmp_path):
    db = Database(DatabaseOptions(root=str(tmp_path)),
                  namespaces={"default": NS})
    paths = [b"servers.web01.cpu", b"servers.web02.cpu",
             b"servers.db01.cpu", b"servers.web01.mem"]
    T = 30
    for k, p in enumerate(paths):
        docs = [path_to_document(p)] * T
        ts = START + np.arange(T, dtype=np.int64) * STEP
        vals = (k + 1) * np.ones(T) * np.arange(1, T + 1)
        db.write_tagged_batch("default", docs, ts, vals)
    return db


class TestStorageResolution:
    def test_glob_fetch(self, tmp_path):
        db = _seed_db(tmp_path)
        st = GraphiteStorage(db)
        series = st.fetch("servers.web*.cpu", START, START + 30 * STEP, STEP)
        assert [s.path for s in series] == [
            "servers.web01.cpu", "servers.web02.cpu"
        ]
        # exactly-N-components: 'servers.*' must not match 3-part paths
        assert st.fetch("servers.*", START, START + STEP, STEP) == []
        db.close()

    def test_brace_alternation(self, tmp_path):
        db = _seed_db(tmp_path)
        st = GraphiteStorage(db)
        series = st.fetch("servers.{web01,db01}.cpu", START,
                          START + 30 * STEP, STEP)
        assert [s.path for s in series] == [
            "servers.db01.cpu", "servers.web01.cpu"
        ]
        db.close()

    def test_find(self, tmp_path):
        db = _seed_db(tmp_path)
        st = GraphiteStorage(db)
        assert st.find("servers.*") == [
            ("db01", False, True), ("web01", False, True),
            ("web02", False, True),
        ]
        assert st.find("servers.web01.*") == [
            ("cpu", True, False), ("mem", True, False)
        ]
        db.close()

    def test_find_node_both_leaf_and_branch(self, tmp_path):
        db = _seed_db(tmp_path)
        # a.b is a metric AND a branch of a.b.c
        for p in (b"a.b", b"a.b.c"):
            docs = [path_to_document(p)]
            db.write_tagged_batch("default", docs,
                                  np.asarray([START], np.int64),
                                  np.asarray([1.0]))
        st = GraphiteStorage(db)
        assert st.find("a.*") == [("b", True, True)]
        db.close()

    def test_render_grid_cap(self, tmp_path):
        db = _seed_db(tmp_path)
        st = GraphiteStorage(db, max_points=100)
        with pytest.raises(ParseError, match="grid too large"):
            st.fetch("servers.web01.cpu", START, START + 200 * STEP, STEP)
        with pytest.raises(ParseError, match="positive"):
            st.fetch("servers.web01.cpu", START, START + STEP, 0)
        db.close()


class TestFunctions:
    def _engine(self, tmp_path):
        return GraphiteEngine(GraphiteStorage(_seed_db(tmp_path)))

    def test_sum_and_scale(self, tmp_path):
        eng = self._engine(tmp_path)
        out = eng.render("scale(sumSeries(servers.*.cpu), 0.5)",
                         START, START + 10 * STEP, STEP)
        assert len(out) == 1
        # series k values: (k+1)*i for i=1.. ; cpu series k=0,1,2 → sum=6i
        np.testing.assert_allclose(out[0].values, 3.0 * np.arange(1, 11))

    def test_derivative_and_persecond(self, tmp_path):
        eng = self._engine(tmp_path)
        out = eng.render("perSecond(servers.web01.cpu)",
                         START, START + 10 * STEP, STEP)
        v = out[0].values
        assert math.isnan(v[0])
        np.testing.assert_allclose(v[1:], 0.1)  # +1 per 10s

    def test_alias_by_node_and_group(self, tmp_path):
        eng = self._engine(tmp_path)
        out = eng.render("aliasByNode(servers.*.cpu, 1)",
                         START, START + 5 * STEP, STEP)
        assert sorted(s.name for s in out) == ["db01", "web01", "web02"]
        grouped = eng.render('groupByNode(servers.*.*, 1, "sum")',
                             START, START + 5 * STEP, STEP)
        assert [s.name for s in grouped] == ["db01", "web01", "web02"]
        # web01 group = cpu (1x) + mem (4x) = 5x
        np.testing.assert_allclose(
            [s for s in grouped if s.name == "web01"][0].values,
            5.0 * np.arange(1, 6),
        )

    def test_selection(self, tmp_path):
        eng = self._engine(tmp_path)
        out = eng.render("highestMax(servers.*.cpu, 1)",
                         START, START + 10 * STEP, STEP)
        assert len(out) == 1 and out[0].path == "servers.db01.cpu"
        out2 = eng.render("maximumAbove(servers.*.cpu, 15)",
                          START, START + 10 * STEP, STEP)
        assert {s.path for s in out2} == {
            "servers.db01.cpu", "servers.web02.cpu"
        }

    def test_summarize(self, tmp_path):
        eng = self._engine(tmp_path)
        out = eng.render('summarize(servers.web01.cpu, "1min", "sum")',
                         START, START + 12 * STEP, STEP)
        s = out[0]
        assert s.step_nanos == 6 * STEP
        np.testing.assert_allclose(s.values[0], sum(range(1, 7)))

    def test_moving_average_and_keep_last(self, tmp_path):
        eng = self._engine(tmp_path)
        out = eng.render("movingAverage(servers.web01.cpu, 3)",
                         START, START + 10 * STEP, STEP)
        v = out[0].values
        np.testing.assert_allclose(v[4], (3 + 4 + 5) / 3)

    def test_timeshift_applies_inner_functions(self, tmp_path):
        """timeShift(scale(x,10),'1h') must scale the SHIFTED data —
        the evaluator shifts the whole inner expression's window."""
        eng = self._engine(tmp_path)
        base = eng.render("scale(servers.web01.cpu, 10)",
                          START + 3600 * 10**9, START + 3600 * 10**9 + 5 * STEP,
                          STEP)
        # from one hour later, shifted back 1h -> the original window
        shifted = eng.render('timeShift(scale(servers.web01.cpu, 10), "1h")',
                             START + 3600 * 10**9,
                             START + 3600 * 10**9 + 5 * STEP, STEP)
        # original window has data (base window, 1h after START, is empty)
        assert np.isnan(base[0].values).all()
        np.testing.assert_allclose(shifted[0].values,
                                   10.0 * np.arange(1, 6))
        assert shifted[0].name.startswith("timeShift(")

    def test_sort_by_maxima_with_empty_series(self, tmp_path):
        """An all-NaN series must sort last, not crash (review fix)."""
        db = _seed_db(tmp_path)
        # a series with no points in the window
        db.write_tagged_batch(
            "default", [path_to_document(b"servers.idle.cpu")],
            np.asarray([START + 3600 * 10**9], np.int64), np.asarray([1.0]),
        )
        eng = GraphiteEngine(GraphiteStorage(db))
        out = eng.render("sortByMaxima(servers.*.cpu)",
                         START, START + 5 * STEP, STEP)
        assert out[-1].path == "servers.idle.cpu"
        assert out[0].path == "servers.db01.cpu"
        db.close()

    def test_function_inventory(self):
        fns = supported_functions()
        assert len(fns) >= 30
        for must in ("sumSeries", "perSecond", "aliasByNode", "summarize",
                     "highestMax", "groupByNode", "timeShift"):
            assert must in fns


class TestHTTP:
    def test_render_and_find_endpoints(self, tmp_path):
        from m3_tpu.server.http_api import ApiContext, serve_background

        db = _seed_db(tmp_path)
        srv = serve_background(ApiContext(db))
        port = srv.server_address[1]
        t0 = START // 10**9
        q = urllib.parse.urlencode({
            "target": "sumSeries(servers.web*.cpu)",
            "from": str(t0), "until": str(t0 + 100), "step": "10s",
        })
        out = json.load(urllib.request.urlopen(
            f"http://127.0.0.1:{port}/render?{q}"
        ))
        assert len(out) == 1
        dp = out[0]["datapoints"]
        assert dp[0] == [3.0, t0]  # web01 1*1 + web02 2*1
        find = json.load(urllib.request.urlopen(
            f"http://127.0.0.1:{port}/metrics/find?query=servers.*"
        ))
        assert {f["text"] for f in find} == {"web01", "web02", "db01"}
        assert all(f["expandable"] for f in find)
        srv.shutdown()
        db.close()


class TestFunctionParityTable:
    """Checked-in parity table vs the reference's builtin registry
    (`src/query/graphite/native/builtin_functions.go` registers ~107
    functions).  Presentational and synthetic-data builtins are
    deliberately out of scope; everything else must resolve."""

    # The reference registry, partitioned by our support policy.
    # Round 4 closed the last gaps: every function in the reference
    # registry is implemented.
    OUT_OF_SCOPE: set = set()
    REFERENCE_REGISTRY = {
        "absolute", "aggregate", "aggregateLine", "aggregateWithWildcards",
        "alias", "aliasByMetric", "aliasByNode", "aliasSub", "applyByNode",
        "asPercent", "averageAbove", "averageBelow", "averageSeries",
        "averageSeriesWithWildcards", "cactiStyle", "changed",
        "consolidateBy", "constantLine", "countSeries", "cumulative",
        "currentAbove", "currentBelow", "dashed", "delay", "derivative",
        "diffSeries", "divideSeries", "divideSeriesLists", "exclude",
        "exponentialMovingAverage", "fallbackSeries", "filterSeries",
        "grep", "group", "groupByNode", "groupByNodes", "highest",
        "highestAverage", "highestCurrent", "highestMax", "hitcount",
        "holtWintersAberration", "holtWintersConfidenceBands",
        "holtWintersForecast", "identity", "integral", "integralByInterval",
        "interpolate", "invert", "isNonNull", "keepLastValue",
        "legendValue", "limit", "logarithm", "lowest", "lowestAverage",
        "lowestCurrent", "maxSeries", "maximumAbove", "minSeries",
        "minimumAbove", "mostDeviant", "movingAverage", "movingMax",
        "movingMedian", "movingMin", "movingSum", "movingWindow",
        "multiplySeries", "multiplySeriesWithWildcards", "nPercentile",
        "nonNegativeDerivative", "offset", "offsetToZero", "perSecond",
        "percentileOfSeries", "pow", "powSeries", "randomWalkFunction",
        "rangeOfSeries", "removeAbovePercentile", "removeAboveValue",
        "removeBelowPercentile", "removeBelowValue", "removeEmptySeries",
        "scale", "scaleToSeconds", "smartSummarize", "sortBy",
        "sortByMaxima", "sortByMinima", "sortByName", "sortByTotal",
        "squareRoot", "stddevSeries", "stdev", "substr", "sumSeries",
        "sumSeriesWithWildcards", "summarize", "sustainedAbove",
        "sustainedBelow", "threshold", "timeFunction", "timeShift",
        "timeSlice", "transformNull", "useSeriesAbove", "weightedAverage",
        "aliasByTags", "minimumBelow", "maximumBelow", "round",
    }

    def test_in_scope_functions_all_supported(self):
        from m3_tpu.query.graphite import supported_functions

        # timeShift is evaluator-intercepted but still registered.
        supported = set(supported_functions())
        in_scope = self.REFERENCE_REGISTRY - self.OUT_OF_SCOPE
        missing = sorted(in_scope - supported)
        assert not missing, f"unsupported in-scope builtins: {missing}"
        assert len(supported) >= 70, len(supported)


class TestBreadthTierFunctions:
    """Behavior spot-checks of the round-3 breadth additions."""

    def _series(self, name, vals, step=10 * 10**9, start=0):
        from m3_tpu.query.graphite import GraphiteSeries
        import numpy as np

        return GraphiteSeries(name, name, np.asarray(vals, np.float64),
                              step, start)

    def _ctx(self):
        from m3_tpu.query.graphite import _Ctx

        return _Ctx(None, 0, 80 * 10**9, 10 * 10**9)

    def test_as_percent_of_total(self):
        from m3_tpu.query.graphite import _FUNCS
        import numpy as np

        a = self._series("a.x", [1, 1, 3])
        b = self._series("b.x", [3, 1, 1])
        out = _FUNCS["asPercent"](self._ctx(), [a, b])
        np.testing.assert_allclose(out[0].values, [25.0, 50.0, 75.0])
        np.testing.assert_allclose(out[1].values, [75.0, 50.0, 25.0])

    def test_divide_series(self):
        from m3_tpu.query.graphite import _FUNCS
        import numpy as np

        a = self._series("a", [4, 9, 0])
        d = self._series("d", [2, 3, 0])
        (out,) = _FUNCS["divideSeries"](self._ctx(), [a], [d])
        np.testing.assert_allclose(out.values[:2], [2.0, 3.0])
        assert np.isnan(out.values[2])  # x/0 -> null, graphite-style

    def test_moving_median_and_window(self):
        from m3_tpu.query.graphite import _FUNCS
        import numpy as np

        s = self._series("m", [1, 9, 5, 3, 7])
        (out,) = _FUNCS["movingMedian"](self._ctx(), [s], 3)
        np.testing.assert_allclose(out.values[2:], [5.0, 5.0, 5.0])
        (out2,) = _FUNCS["movingWindow"](self._ctx(), [s], 3, "median")
        np.testing.assert_allclose(out2.values[2:], out.values[2:])

    def test_group_by_nodes(self):
        from m3_tpu.query.graphite import _FUNCS
        import numpy as np

        series = [
            self._series("svc.a.east.req", [1, 2]),
            self._series("svc.a.west.req", [10, 20]),
            self._series("svc.b.east.req", [100, 200]),
        ]
        out = _FUNCS["groupByNodes"](self._ctx(), series, "sum", 1)
        got = {s.name: s.values.tolist() for s in out}
        assert got == {"a": [11.0, 22.0], "b": [100.0, 200.0]}

    def test_alias_by_tags_path_components(self):
        from m3_tpu.query.graphite import _FUNCS

        s = self._series("svc.api.host1", [1])
        (out,) = _FUNCS["aliasByTags"](self._ctx(), [s], "__g1__", "__g2__")
        assert out.name == "api.host1"

    def test_transform_null_and_is_non_null(self):
        from m3_tpu.query.graphite import _FUNCS, NAN
        import numpy as np

        s = self._series("m", [1, NAN, 3])
        (out,) = _FUNCS["transformNull"](self._ctx(), [s], -1)
        np.testing.assert_allclose(out.values, [1, -1, 3])
        (nn,) = _FUNCS["isNonNull"](self._ctx(), [s])
        np.testing.assert_allclose(nn.values, [1, 0, 1])

    def test_remove_above_percentile(self):
        from m3_tpu.query.graphite import _FUNCS
        import numpy as np

        s = self._series("m", list(range(1, 11)))
        (out,) = _FUNCS["removeAbovePercentile"](self._ctx(), [s], 50)
        assert np.isnan(out.values[-1])
        assert out.values[0] == 1.0

    def test_weighted_average(self):
        from m3_tpu.query.graphite import _FUNCS
        import numpy as np

        avg = [self._series("lat.a.avg", [10, 20]),
               self._series("lat.b.avg", [30, 40])]
        w = [self._series("lat.a.count", [1, 1]),
             self._series("lat.b.count", [3, 1])]
        (out,) = _FUNCS["weightedAverage"](self._ctx(), avg, w, 1)
        np.testing.assert_allclose(out.values, [(10 + 90) / 4.0, 30.0])

    def test_sum_series_with_wildcards(self):
        from m3_tpu.query.graphite import _FUNCS
        import numpy as np

        series = [
            self._series("svc.h1.req", [1, 2]),
            self._series("svc.h2.req", [10, 20]),
        ]
        out = _FUNCS["sumSeriesWithWildcards"](self._ctx(), series, 1)
        assert len(out) == 1
        assert out[0].name == "svc.req"
        np.testing.assert_allclose(out[0].values, [11.0, 22.0])

    def test_ema_sma_seed_and_decay(self):
        """graphite-web EMA: null until the window fills, seeds with the
        SMA of the first window, then decays with alpha=2/(n+1)."""
        from m3_tpu.query.graphite import _FUNCS
        import numpy as np

        s = self._series("m", [10, 20, 30, 40])
        (out,) = _FUNCS["exponentialMovingAverage"](self._ctx(), [s], 3)
        assert np.isnan(out.values[:2]).all()
        np.testing.assert_allclose(out.values[2], 20.0)  # avg(10,20,30)
        np.testing.assert_allclose(out.values[3], 0.5 * 40 + 0.5 * 20.0)

    def test_highest_rejects_unknown_func(self):
        from m3_tpu.query.graphite import _FUNCS, ParseError
        import pytest

        s = self._series("m", [1, 2])
        with pytest.raises(ParseError, match="unknown aggregation"):
            _FUNCS["highest"](self._ctx(), [s], 1, "bogus")
        # sum is a real aggregation and must select by sum, not average
        a = self._series("a", [10, 0, 0])   # sum 10, avg 3.33
        b = self._series("b", [4, 4, 0])    # sum 8, avg 2.67
        c = self._series("c", [0, 0, 9])    # sum 9, avg 3
        out = _FUNCS["highest"](self._ctx(), [a, b, c], 2, "sum")
        assert [s.name for s in out] == ["a", "c"]

    def test_interpolate_gap_length_limit(self):
        from m3_tpu.query.graphite import _FUNCS, NAN
        import numpy as np

        s = self._series("m", [1, NAN, NAN, NAN, NAN, 6, NAN, 8])
        (out,) = _FUNCS["interpolate"](self._ctx(), [s], 2)
        # the 4-long gap exceeds limit=2: left fully null
        assert np.isnan(out.values[1:5]).all()
        # the 1-long gap fills linearly
        np.testing.assert_allclose(out.values[6], 7.0)


class TestAdvisedSemantics:
    """Round-4 ADVICE fixes: hitcount alignment, stdev window tolerance."""

    def _series(self, name, vals, step=10 * 10**9, start=0):
        from m3_tpu.query.graphite import GraphiteSeries

        return GraphiteSeries(name, name, np.asarray(vals, np.float64),
                              step, start)

    def _ctx(self):
        from m3_tpu.query.graphite import _Ctx

        return _Ctx(None, 0, 80 * 10**9, 10 * 10**9)

    def test_hitcount_end_anchored_default(self):
        from m3_tpu.query.graphite import _FUNCS

        # graphite-web anchors buckets at the series END: 8 points
        # @10s from t=30 end at t=110; two 60s buckets run back from
        # 110, so the FIRST bucket is the partial one (t=[-10,50): the
        # 2 points at 30/40), the second holds the 6 at 50..100.
        s = self._series("h", [1.0] * 8, start=30 * 10**9)
        (out,) = _FUNCS["hitcount"](self._ctx(), [s], "1min")
        assert out.start_nanos == -10 * 10**9
        np.testing.assert_allclose(out.values, [20.0, 60.0])

    def test_hitcount_align_to_interval(self):
        from m3_tpu.query.graphite import _FUNCS

        # alignToInterval=True truncates the start to the calendar
        # minute: buckets [0,60) and [60,120) hold 3 and 5 points.
        s = self._series("h", [1.0] * 8, start=30 * 10**9)
        (out,) = _FUNCS["hitcount"](self._ctx(), [s], "1min", True)
        assert out.start_nanos == 0
        np.testing.assert_allclose(out.values, [30.0, 50.0])
        assert ",true)" in out.name

    def test_stdev_window_tolerance(self):
        from m3_tpu.query.graphite import _FUNCS

        vals = [2.0, 4.0, float("nan"), float("nan"), float("nan")]
        s = self._series("sd", vals)
        # tolerance 0.5 over a 4-point window: indices with <2 valid
        # points in their trailing window go null.
        (out,) = _FUNCS["stdev"](self._ctx(), [s], 4, 0.5)
        np.testing.assert_allclose(out.values[1], 1.0)  # std([2,4])
        assert np.isnan(out.values[0])   # 1/4 valid < 0.5
        assert np.isnan(out.values[4])   # window [4,nan,nan,nan]: 1/4
        # default tolerance 0.1 keeps single-valid windows
        (out2,) = _FUNCS["stdev"](self._ctx(), [s], 4)
        assert out2.values[0] == 0.0


class TestRound4Breadth:
    def _series(self, name, vals, step=10 * 10**9, start=0):
        from m3_tpu.query.graphite import GraphiteSeries

        return GraphiteSeries(name, name, np.asarray(vals, np.float64),
                              step, start)

    def _ctx(self, storage=None, start=0, end=80 * 10**9):
        from m3_tpu.query.graphite import _Ctx

        return _Ctx(storage, start, end, 10 * 10**9)

    def test_random_walk_stable_and_sized(self):
        from m3_tpu.query.graphite import _FUNCS

        (a,) = _FUNCS["randomWalkFunction"](self._ctx(end=600 * 10**9),
                                            "rw.test", 60)
        (b,) = _FUNCS["randomWalkFunction"](self._ctx(end=600 * 10**9),
                                            "rw.test", 60)
        assert len(a.values) == 10 and a.step_nanos == 60 * 10**9
        np.testing.assert_array_equal(a.values, b.values)  # seeded

    def test_time_slice_nulls_outside_window(self):
        from m3_tpu.query.graphite import _FUNCS

        s = self._series("ts", [1.0] * 8)
        (out,) = _FUNCS["timeSlice"](self._ctx(end=80 * 10**9), [s],
                                     "-60s", "-30s")
        t = np.arange(8) * 10
        expect_live = (t >= 20) & (t <= 50)
        assert np.array_equal(~np.isnan(out.values), expect_live)

    def test_cacti_style_and_legend_value(self):
        from m3_tpu.query.graphite import _FUNCS

        s = self._series("web.cpu", [1.0, 3.0, 2.0])
        (c,) = _FUNCS["cactiStyle"](self._ctx(), [s])
        assert c.name == "web.cpu Current:2 Max:3 Min:1"
        (l,) = _FUNCS["legendValue"](self._ctx(), [s], "avg", "last")
        assert l.name == "web.cpu (avg: 2) (last: 2)"
        # unknown value types degrade with a "?" like graphite-web
        (u,) = _FUNCS["legendValue"](self._ctx(), [s], "p99")
        assert u.name == "web.cpu (?)"

    def test_use_series_above(self, tmp_path):
        db = _seed_db(tmp_path)
        eng = GraphiteEngine(GraphiteStorage(db))
        # db01.cpu peaks at 3*30=90 > 50 -> fetch its .mem counterpart?
        # only web01 has .mem; use web threshold instead: web02 peaks 60.
        out = eng.render(
            'useSeriesAbove(servers.web01.cpu, 5, "cpu", "mem")',
            START, START + 10 * STEP, STEP)
        assert [s.path for s in out] == ["servers.web01.mem"]
        db.close()


    def test_apply_by_node(self, tmp_path):
        db = _seed_db(tmp_path)
        eng = GraphiteEngine(GraphiteStorage(db))
        out = eng.render(
            'applyByNode(servers.*.cpu, 1, "sumSeries(%.*)", "%.total")',
            START, START + 5 * STEP, STEP)
        names = sorted(s.name for s in out)
        assert names == ["servers.db01.total", "servers.web01.total",
                         "servers.web02.total"]
        # web01 total = cpu (1x) + mem (4x) = 5x
        web = [s for s in out if s.name == "servers.web01.total"][0]
        np.testing.assert_allclose(web.values, 5.0 * np.arange(1, 6))
        db.close()


class TestHoltWintersFamily:
    """Pinned against a verbatim port of graphite-web's sequential
    holtWintersAnalysis loop (the reference spec), plus behavioral
    checks on a daily-seasonal corpus."""

    def _reference_analysis(self, values, step_nanos):
        """Straight port of graphite-web functions.py holtWintersAnalysis
        (None -> NaN), kept independent of the implementation."""
        alpha = gamma = 0.1
        beta = 0.0035
        season = max(1, int((24 * 3600 * 10**9) // step_nanos))
        intercepts, slopes, seasonals = [], [], []
        predictions, deviations = [], []
        next_pred = None
        for i, actual in enumerate(values):
            if math.isnan(actual):
                intercepts.append(None)
                slopes.append(0.0)
                seasonals.append(0.0)
                predictions.append(next_pred)
                deviations.append(0.0)
                next_pred = None
                continue
            if i == 0:
                last_intercept, last_slope, prediction = actual, 0.0, actual
            else:
                last_intercept = intercepts[-1]
                last_slope = slopes[-1]
                if last_intercept is None:
                    last_intercept = actual
                prediction = next_pred
            gl = lambda j: (seasonals[j - season]
                            if 0 <= j - season < len(seasonals) else 0.0)
            gd = lambda j: (deviations[j - season]
                            if j - season >= 0 else 0.0)
            ls, next_ls, lsd = gl(i), gl(i + 1), gd(i)
            intercept = alpha * (actual - ls) + (1 - alpha) * (
                last_intercept + last_slope)
            slope = beta * (intercept - last_intercept) + (1 - beta) * last_slope
            seasonal = gamma * (actual - intercept) + (1 - gamma) * ls
            next_pred = intercept + slope + next_ls
            p = 0.0 if prediction is None else prediction
            deviations.append(gamma * abs(actual - p) + (1 - gamma) * lsd)
            intercepts.append(intercept)
            slopes.append(slope)
            seasonals.append(seasonal)
            predictions.append(prediction)
        to_nan = lambda xs: np.asarray(
            [math.nan if x is None else x for x in xs])
        return to_nan(predictions), np.asarray(deviations)

    def test_analysis_matches_reference_port(self):
        from m3_tpu.query.graphite import _holt_winters_analysis

        rng = np.random.default_rng(4)
        step = 3600 * 10**9  # 1h -> season of 24 points
        n = 24 * 9
        t = np.arange(n)
        vals = 100 + 20 * np.sin(2 * np.pi * t / 24) + rng.normal(0, 1, n)
        vals[40] = np.nan  # a gap exercises the restart path
        got_p, got_d = _holt_winters_analysis(vals, step)
        want_p, want_d = self._reference_analysis(vals, step)
        np.testing.assert_allclose(got_p, want_p, rtol=1e-12, equal_nan=True)
        np.testing.assert_allclose(got_d, want_d, rtol=1e-12)

    def test_forecast_bands_and_aberration(self, tmp_path):
        from m3_tpu.metrics.carbon import path_to_document

        db = Database(DatabaseOptions(root=str(tmp_path)),
                      namespaces={"default": NamespaceOptions(
                          num_shards=1, slot_capacity=1 << 10,
                          sample_capacity=1 << 15)})
        # 9 days of clean daily-seasonal data at 1h steps, one spike.
        step = 3600 * 10**9
        n = 24 * 9
        t0 = START
        t = t0 + np.arange(n, dtype=np.int64) * step
        vals = 100 + 20 * np.sin(2 * np.pi * np.arange(n) / 24)
        spike_i = n - 5
        vals[spike_i] += 500.0
        docs = [path_to_document(b"hw.metric")] * n
        db.write_tagged_batch("default", docs, t, vals)
        eng = GraphiteEngine(GraphiteStorage(db))
        # render the last day with a 7d bootstrap
        rstart = t0 + (n - 24) * step
        rend = t0 + n * step
        (fc,) = eng.render('holtWintersForecast(hw.metric, "7d")',
                           rstart, rend, step)
        assert fc.name == "holtWintersForecast(hw.metric)"
        assert len(fc.values) == 24
        # with 8 days of warm-up the forecast tracks the pattern UP TO
        # the anomaly (the spike rightly disturbs later predictions)
        actual = vals[-24:]
        s_pre = spike_i - (n - 24)
        pre = np.abs(fc.values - actual)[:s_pre]
        assert np.nanmax(pre[~np.isnan(pre)]) < 15
        bands = eng.render('holtWintersConfidenceBands(hw.metric, 3, "7d")',
                           rstart, rend, step)
        assert [b.name.split("(")[0] for b in bands] == [
            "holtWintersConfidenceUpper", "holtWintersConfidenceLower"]
        (ab,) = eng.render('holtWintersAberration(hw.metric, 3, "7d")',
                           rstart, rend, step)
        s_idx = spike_i - (n - 24)
        assert ab.values[s_idx] > 0  # the spike breaks the upper band
        others = np.delete(ab.values, s_idx)
        assert np.nanmax(np.abs(others[~np.isnan(others)])) < 60
        db.close()
