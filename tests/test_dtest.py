"""dtest scenarios: real node subprocesses, kill -9, recovery.

Reference model: `src/cmd/tools/dtest` scenarios over `src/m3em` agents
(seed a node, kill it mid-stream, restart, verify bootstrap recovers).
These are the slowest tests in the suite (each node start pays JAX
compile in a fresh process) — kept to the two essential scenarios.
"""

import json
import urllib.request
from pathlib import Path

import pytest

from m3_tpu.dtest.harness import NodeProcess

BLOCK = 2 * 3600 * 10**9
START_S = (1_700_000_000 * 10**9) // BLOCK * BLOCK // 10**9


def _node(tmp_path) -> NodeProcess:
    root = tmp_path / "data"
    cfg = tmp_path / "node.yaml"
    cfg.write_text(f"""
db:
  root: {root}
  namespaces:
    default: {{num_shards: 2}}
coordinator: {{listen_port: 0}}
mediator: {{enabled: false}}
""")
    root.mkdir(parents=True, exist_ok=True)
    return NodeProcess(str(cfg), str(root))


def _samples(n, t0=START_S):
    return [
        {"tags": {"__name__": "dt", "host": f"h{i % 2}"},
         "timestamp": t0 + i * 10, "value": float(i)}
        for i in range(n)
    ]


@pytest.mark.slow
class TestDtestScenarios:
    def test_crash_recovery_via_real_process(self, tmp_path):
        """Seed → kill -9 → restart → the data is back (WAL replay
        through an actual process crash, not an in-process simulation)."""
        node = _node(tmp_path)
        node.start()
        try:
            assert node.write_json(_samples(40)) == 40
            before = node.query_range("sum(dt)", START_S, START_S + 400)
            assert before
            node.kill()  # no flush, no graceful close
            assert not node.alive()
            node.start()
            after = node.query_range("sum(dt)", START_S, START_S + 400)
            assert after == before
        finally:
            node.kill()

    def test_graceful_stop_then_restart(self, tmp_path):
        node = _node(tmp_path)
        node.start()
        try:
            node.write_json(_samples(10))
            rc = node.stop()
            assert rc == 0
            assert not (tmp_path / "data" / "node.json").exists()
            node.start()
            out = node.query_range("dt", START_S, START_S + 100)
            assert len(out) == 2  # both hosts
        finally:
            node.kill()
