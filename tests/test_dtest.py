"""dtest scenarios: real node subprocesses, kill -9, recovery.

Reference model: `src/cmd/tools/dtest` scenarios over `src/m3em` agents
(seed a node, kill it mid-stream, restart, verify bootstrap recovers).
These are the slowest tests in the suite (each node start pays JAX
compile in a fresh process) — kept to the two essential scenarios.
"""

import json
import socket
import urllib.request
from pathlib import Path

import numpy as np
import pytest

from m3_tpu.dtest.harness import NodeProcess

BLOCK = 2 * 3600 * 10**9
START_S = (1_700_000_000 * 10**9) // BLOCK * BLOCK // 10**9
SEC = 10**9
T0 = START_S * SEC


def _node(tmp_path) -> NodeProcess:
    root = tmp_path / "data"
    cfg = tmp_path / "node.yaml"
    cfg.write_text(f"""
db:
  root: {root}
  namespaces:
    default: {{num_shards: 2}}
coordinator: {{listen_port: 0}}
mediator: {{enabled: false}}
""")
    root.mkdir(parents=True, exist_ok=True)
    return NodeProcess(str(cfg), str(root))


def _samples(n, t0=START_S):
    return [
        {"tags": {"__name__": "dt", "host": f"h{i % 2}"},
         "timestamp": t0 + i * 10, "value": float(i)}
        for i in range(n)
    ]


def _free_ports(n: int) -> list:
    socks, ports = [], []
    for _ in range(n):
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        socks.append(s)
        ports.append(s.getsockname()[1])
    for s in socks:
        s.close()
    return ports


def _cluster_nodes(tmp_path, n=3):
    """n node processes wired as an RF=n replica set: each serves the
    socket RPC and peers-bootstraps from the others on startup."""
    ports = _free_ports(n)
    nodes = []
    for k in range(n):
        root = tmp_path / f"n{k}" / "data"
        cfg = tmp_path / f"n{k}" / "node.yaml"
        peers = [f"127.0.0.1:{p}" for i, p in enumerate(ports) if i != k]
        cfg.parent.mkdir(parents=True, exist_ok=True)
        cfg.write_text(f"""
db:
  root: {root}
  rpc_listen_port: {ports[k]}
  peers: [{", ".join(repr(p) for p in peers)}]
  bootstrap_peers: true
  namespaces:
    default: {{num_shards: 2}}
coordinator: {{listen_port: 0}}
mediator: {{enabled: false}}
""")
        root.mkdir(parents=True, exist_ok=True)
        nodes.append(NodeProcess(str(cfg), str(root)))
    return nodes, ports


@pytest.mark.slow
class TestQuorumCluster:
    def test_majority_write_kill_rejoin_via_wire_bootstrap(self, tmp_path):
        """The reference's write_quorum_test family as a real 3-process
        scenario: write at Majority, SIGKILL one replica, keep writing
        at Majority, read back at Majority, then the killed node rejoins
        and backfills over the socket RPC (wire peers bootstrap), after
        which repair reports convergence."""
        from m3_tpu.client.session import ConsistencyLevel, ReplicatedSession
        from m3_tpu.cluster.placement import Instance, initial_placement
        from m3_tpu.server.rpc import RemoteDatabase
        from m3_tpu.storage.repair import repair_namespace

        nodes, ports = _cluster_nodes(tmp_path)
        remotes = {}
        try:
            for nd in nodes:
                nd.start()
            remotes = {
                f"i{k}": RemoteDatabase(("127.0.0.1", ports[k]))
                for k in range(3)
            }
            placement = initial_placement(
                [Instance(f"i{k}") for k in range(3)], num_shards=2, rf=3
            )
            session = ReplicatedSession(
                placement, dict(remotes),
                write_level=ConsistencyLevel.MAJORITY,
                read_level=ConsistencyLevel.MAJORITY,
            )

            ids = [b"qd-%d" % i for i in range(6)]
            ts1 = np.full(len(ids), T0 + SEC, np.int64)
            session.write_batch("default", ids, ts1,
                                np.arange(len(ids), dtype=np.float64),
                                now_nanos=T0 + SEC)

            nodes[2].kill()  # SIGKILL: no flush, no graceful close
            assert not nodes[2].alive()

            # Majority writes still succeed with 2/3 replicas up.
            ts2 = np.full(len(ids), T0 + 2 * SEC, np.int64)
            session.write_batch("default", ids, ts2,
                                np.arange(len(ids), dtype=np.float64) + 100,
                                now_nanos=T0 + 2 * SEC)

            # Majority reads return both rounds of writes.
            for i, sid in enumerate(ids):
                pts = session.fetch("default", sid, T0, T0 + BLOCK)
                assert pts == [(T0 + SEC, float(i)),
                               (T0 + 2 * SEC, float(i) + 100)]

            # Flush the live replicas so their blocks exist as filesets.
            for k in (0, 1):
                remotes[f"i{k}"].tick(T0 + 2 * BLOCK)

            # The killed node rejoins: local WAL replay + wire peers
            # bootstrap from the live replicas pulls the flushed blocks.
            nodes[2].start()
            r2 = remotes["i2"]
            for i, sid in enumerate(ids):
                pts = r2.read("default", sid, T0, T0 + BLOCK)
                assert pts == [(T0 + SEC, float(i)),
                               (T0 + 2 * SEC, float(i) + 100)], (sid, pts)

            # Anti-entropy over the wire handles reports convergence
            # once the rejoined node also flushes its merged state.
            r2.tick(T0 + 2 * BLOCK)
            rep = repair_namespace(list(remotes.values()), "default",
                                   num_shards=2)
            if not rep.converged:
                rep = repair_namespace(list(remotes.values()), "default",
                                       num_shards=2)
            assert rep.converged, rep
        finally:
            for r in remotes.values():
                r.close()
            for nd in nodes:
                nd.kill()


@pytest.mark.slow
class TestDtestScenarios:
    def test_crash_recovery_via_real_process(self, tmp_path):
        """Seed → kill -9 → restart → the data is back (WAL replay
        through an actual process crash, not an in-process simulation)."""
        node = _node(tmp_path)
        node.start()
        try:
            assert node.write_json(_samples(40)) == 40
            before = node.query_range("sum(dt)", START_S, START_S + 400)
            assert before
            node.kill()  # no flush, no graceful close
            assert not node.alive()
            node.start()
            after = node.query_range("sum(dt)", START_S, START_S + 400)
            assert after == before
        finally:
            node.kill()

    def test_graceful_stop_then_restart(self, tmp_path):
        node = _node(tmp_path)
        node.start()
        try:
            node.write_json(_samples(10))
            rc = node.stop()
            assert rc == 0
            assert not (tmp_path / "data" / "node.json").exists()
            node.start()
            out = node.query_range("dt", START_S, START_S + 100)
            assert len(out) == 2  # both hosts
        finally:
            node.kill()


@pytest.mark.slow
class TestAgentLifecycle:
    """m3em-agent scenario: the dtest driver manages a node purely
    through the agent's HTTP surface (reference m3em operator verbs)."""

    def test_setup_start_crash_restart_teardown(self, tmp_path):
        from m3_tpu.dtest.agent import AgentClient, serve_agent_background

        srv = serve_agent_background(str(tmp_path / "agent"))
        client = AgentClient(srv.server_address)
        try:
            cfg = """
db:
  root: {root}
  namespaces:
    default: {{num_shards: 2}}
coordinator: {{listen_port: 0}}
mediator: {{enabled: false}}
"""
            out = client.setup("n1", cfg.format(root=tmp_path / "agent" / "n1" / "data"))
            assert out["name"] == "n1"
            st = client.start("n1")
            assert st["alive"] and st["port"]
            port = st["port"]

            # write through the node's own HTTP API
            import urllib.request
            req = urllib.request.Request(
                f"http://127.0.0.1:{port}/api/v1/json/write",
                data=json.dumps([{"tags": {"__name__": "am"},
                                  "timestamp": START_S + 10,
                                  "value": 5.0}]).encode(),
                headers={"Content-Type": "application/json"},
            )
            with urllib.request.urlopen(req, timeout=30) as r:
                assert json.load(r)["written"] == 1

            # crash + heartbeat shows it dead; logs are retrievable
            client.kill("n1")
            assert not client.status()["nodes"]["n1"]["alive"]
            assert isinstance(client.logs("n1"), bytes)

            # restart through the agent: WAL recovery inside the node
            st2 = client.start("n1")
            assert st2["alive"]
            url = (f"http://127.0.0.1:{st2['port']}/api/v1/query_range?"
                   f"query=am&start={START_S}&end={START_S + 100}&step=10s")
            with urllib.request.urlopen(url, timeout=60) as r:
                out = json.load(r)
            assert out["data"]["result"], out

            client.teardown("n1")
            assert "n1" not in client.status()["nodes"]
            assert not (tmp_path / "agent" / "n1").exists()
        finally:
            srv.agent.close()
            srv.shutdown()
            srv.server_close()


class TestAgentNameSafety:
    def test_path_escaping_names_rejected(self, tmp_path):
        from m3_tpu.dtest.agent import Agent

        a = Agent(str(tmp_path / "w"))
        for bad in ("../x", "a/b", "..", "", "x" * 65, "a\x00b"):
            with pytest.raises(ValueError):
                a.setup(bad, "db: {}")
            with pytest.raises((ValueError, KeyError)):
                a.teardown(bad)
        a.close()
