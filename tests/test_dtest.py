"""dtest scenarios: real node subprocesses, kill -9, recovery.

Reference model: `src/cmd/tools/dtest` scenarios over `src/m3em` agents
(seed a node, kill it mid-stream, restart, verify bootstrap recovers).
These are the slowest tests in the suite (each node start pays JAX
compile in a fresh process) — kept to the two essential scenarios.
"""

import json
import socket
import threading
import urllib.request
from pathlib import Path

import numpy as np
import pytest

from m3_tpu.dtest.harness import NodeProcess

BLOCK = 2 * 3600 * 10**9
START_S = (1_700_000_000 * 10**9) // BLOCK * BLOCK // 10**9
SEC = 10**9
T0 = START_S * SEC


def _node(tmp_path) -> NodeProcess:
    root = tmp_path / "data"
    cfg = tmp_path / "node.yaml"
    cfg.write_text(f"""
db:
  root: {root}
  namespaces:
    default: {{num_shards: 2}}
coordinator: {{listen_port: 0}}
mediator: {{enabled: false}}
""")
    root.mkdir(parents=True, exist_ok=True)
    return NodeProcess(str(cfg), str(root))


def _samples(n, t0=START_S):
    return [
        {"tags": {"__name__": "dt", "host": f"h{i % 2}"},
         "timestamp": t0 + i * 10, "value": float(i)}
        for i in range(n)
    ]


def _free_ports(n: int) -> list:
    socks, ports = [], []
    for _ in range(n):
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        socks.append(s)
        ports.append(s.getsockname()[1])
    for s in socks:
        s.close()
    return ports


def _cluster_nodes(tmp_path, n=3, admin=False):
    """n node processes wired as an RF=n replica set: each serves the
    socket RPC and peers-bootstraps from the others on startup.
    ``admin=True`` also opens each node's admin API on an ephemeral
    port (published as ``admin_port`` in node.json)."""
    ports = _free_ports(n)
    coord = ("{listen_port: 0, admin_listen_port: 0}" if admin
             else "{listen_port: 0}")
    nodes = []
    for k in range(n):
        root = tmp_path / f"n{k}" / "data"
        cfg = tmp_path / f"n{k}" / "node.yaml"
        peers = [f"127.0.0.1:{p}" for i, p in enumerate(ports) if i != k]
        cfg.parent.mkdir(parents=True, exist_ok=True)
        cfg.write_text(f"""
db:
  root: {root}
  rpc_listen_port: {ports[k]}
  peers: [{", ".join(repr(p) for p in peers)}]
  bootstrap_peers: true
  namespaces:
    default: {{num_shards: 2}}
coordinator: {coord}
mediator: {{enabled: false}}
""")
        root.mkdir(parents=True, exist_ok=True)
        nodes.append(NodeProcess(str(cfg), str(root)))
    return nodes, ports


@pytest.mark.slow
class TestQuorumCluster:
    def test_majority_write_kill_rejoin_via_wire_bootstrap(self, tmp_path):
        """The reference's write_quorum_test family as a real 3-process
        scenario: write at Majority, SIGKILL one replica, keep writing
        at Majority, read back at Majority, then the killed node rejoins
        and backfills over the socket RPC (wire peers bootstrap), after
        which repair reports convergence."""
        from m3_tpu.client.session import ConsistencyLevel, ReplicatedSession
        from m3_tpu.cluster.placement import Instance, initial_placement
        from m3_tpu.server.rpc import RemoteDatabase
        from m3_tpu.storage.repair import repair_namespace

        nodes, ports = _cluster_nodes(tmp_path)
        remotes = {}
        try:
            for nd in nodes:
                nd.start()
            remotes = {
                f"i{k}": RemoteDatabase(("127.0.0.1", ports[k]))
                for k in range(3)
            }
            placement = initial_placement(
                [Instance(f"i{k}") for k in range(3)], num_shards=2, rf=3
            )
            session = ReplicatedSession(
                placement, dict(remotes),
                write_level=ConsistencyLevel.MAJORITY,
                read_level=ConsistencyLevel.MAJORITY,
            )

            ids = [b"qd-%d" % i for i in range(6)]
            ts1 = np.full(len(ids), T0 + SEC, np.int64)
            session.write_batch("default", ids, ts1,
                                np.arange(len(ids), dtype=np.float64),
                                now_nanos=T0 + SEC)

            nodes[2].kill()  # SIGKILL: no flush, no graceful close
            assert not nodes[2].alive()

            # Majority writes still succeed with 2/3 replicas up.
            ts2 = np.full(len(ids), T0 + 2 * SEC, np.int64)
            session.write_batch("default", ids, ts2,
                                np.arange(len(ids), dtype=np.float64) + 100,
                                now_nanos=T0 + 2 * SEC)

            # Majority reads return both rounds of writes.
            for i, sid in enumerate(ids):
                pts = session.fetch("default", sid, T0, T0 + BLOCK)
                assert pts == [(T0 + SEC, float(i)),
                               (T0 + 2 * SEC, float(i) + 100)]

            # Flush the live replicas so their blocks exist as filesets.
            for k in (0, 1):
                remotes[f"i{k}"].tick(T0 + 2 * BLOCK)

            # The killed node rejoins: local WAL replay + wire peers
            # bootstrap from the live replicas pulls the flushed blocks.
            nodes[2].start()
            r2 = remotes["i2"]
            for i, sid in enumerate(ids):
                pts = r2.read("default", sid, T0, T0 + BLOCK)
                assert pts == [(T0 + SEC, float(i)),
                               (T0 + 2 * SEC, float(i) + 100)], (sid, pts)

            # Anti-entropy over the wire handles reports convergence
            # once the rejoined node also flushes its merged state.
            r2.tick(T0 + 2 * BLOCK)
            rep = repair_namespace(list(remotes.values()), "default",
                                   num_shards=2)
            if not rep.converged:
                rep = repair_namespace(list(remotes.values()), "default",
                                       num_shards=2)
            assert rep.converged, rep
        finally:
            for r in remotes.values():
                r.close()
            for nd in nodes:
                nd.kill()


class TestKVFlapScenario:
    """dtest scenario (in-process sockets, so it runs in tier 1): the
    KV control plane flaps while a placement watch is live and
    drop+delay faults are armed at the kv_remote socket boundary.  The
    watch must re-establish through the retry substrate and deliver the
    post-flap placement change — with nonzero retry/fault counters."""

    def test_kv_flap_during_placement_watch_with_faults(self, tmp_path):
        import time as _time

        from m3_tpu.cluster.kv_remote import (
            RemoteKVStore, serve_kv_background,
        )
        from m3_tpu.cluster.placement import (
            Instance, PlacementService, initial_placement,
        )
        from m3_tpu.x import fault
        from m3_tpu.x import retry as xretry

        fault.reset_counters()
        fast = xretry.RetryOptions(
            initial_backoff_s=0.01, max_backoff_s=0.1, max_attempts=8)
        root = tmp_path / "kv"
        root.mkdir(parents=True, exist_ok=True)
        srv = serve_kv_background(root=str(root))
        port = srv.port
        kv = RemoteKVStore(("127.0.0.1", port), watch_poll_s=0.05,
                           retry_options=fast)
        versions = []
        other = None
        try:
            ps = PlacementService(kv)
            ps.set(initial_placement([Instance("i0"), Instance("i1")],
                                     num_shards=4, rf=2))
            kv.watch("placement", lambda v: versions.append(v.version))
            assert versions == [1]  # initial fire
            with fault.armed("kv_remote.call", "drop", p=0.3, seed=11) as fd, \
                 fault.armed("kv_remote.call", "delay", delay_ms=2,
                             p=0.5, seed=12):
                # Flap: the server dies under the live watch...
                srv.shutdown()
                srv.server_close()
                _time.sleep(0.3)  # a few watch polls fail + back off
                # ...and comes back on the same port with the same
                # (file-backed) store.
                srv = serve_kv_background(root=str(root), port=port)
                # A DIFFERENT client moves the placement (the
                # cross-process operator shape), through the same
                # armed faults.
                other = RemoteKVStore(("127.0.0.1", port),
                                      retry_options=fast)
                ps2 = PlacementService(other)
                p1 = ps2.get()
                ps2.set(p1)  # version bump is the observable change
                # Drive the RETRIED call path under the armed faults
                # (the watch poll deliberately runs single-attempt —
                # its backoff lives in the loop, not the retrier).
                for _ in range(20):
                    assert kv.get("placement") is not None
                deadline = _time.monotonic() + 15
                while 2 not in versions and _time.monotonic() < deadline:
                    _time.sleep(0.02)
            assert 2 in versions, versions  # watch re-established
            # The scenario genuinely exercised the substrate:
            assert fd.triggers > 0
            fc = fault.counters()
            assert fc["kv_remote.call.drop_triggers"] > 0
            assert fc["kv_remote.call.delay_triggers"] > 0
            rc = xretry.counters()
            assert rc.get("kv_remote.retries", 0) > 0
        finally:
            if other is not None:
                other.close()
            kv.close()
            srv.shutdown()
            srv.server_close()


@pytest.mark.slow
class TestFaultedQuorumScenario:
    """dtest scenario: replicated writes under injected drop+delay
    faults at the rpc socket boundary while one replica is SIGKILLed
    mid-stream.  Every ACKNOWLEDGED write (write_batch returned) must
    be readable after the killed node rejoins and the cluster must
    converge — with nonzero fault/retry counters proving the faults
    actually fired through the retry substrate."""

    def test_ingest_faults_sigkill_no_acked_loss(self, tmp_path):
        from m3_tpu.client.session import (
            ConsistencyLevel, ReplicatedSession,
        )
        from m3_tpu.cluster.placement import Instance, initial_placement
        from m3_tpu.server.rpc import RemoteDatabase
        from m3_tpu.storage.repair import repair_namespace
        from m3_tpu.x import fault
        from m3_tpu.x import retry as xretry

        fault.reset_counters()
        nodes, ports = _cluster_nodes(tmp_path)
        remotes = {}
        acked = []  # (sid, ts, value) the session acknowledged
        try:
            for nd in nodes:
                nd.start()
            remotes = {
                f"i{k}": RemoteDatabase(("127.0.0.1", ports[k]))
                for k in range(3)
            }
            placement = initial_placement(
                [Instance(f"i{k}") for k in range(3)], num_shards=2, rf=3
            )
            session = ReplicatedSession(
                placement, dict(remotes),
                write_level=ConsistencyLevel.MAJORITY,
                read_level=ConsistencyLevel.MAJORITY,
                retry_options=xretry.RetryOptions(
                    initial_backoff_s=0.02, max_backoff_s=0.2,
                    max_attempts=4),
            )
            ids = [b"fq-%d" % i for i in range(4)]
            with fault.armed("rpc.call", "drop", p=0.15, seed=21) as fd, \
                 fault.armed("rpc.call", "delay", delay_ms=5,
                             p=0.3, seed=22):
                for rnd in range(6):
                    if rnd == 3:
                        nodes[2].kill()  # SIGKILL mid-write-stream
                    ts = np.full(len(ids), T0 + (rnd + 1) * SEC, np.int64)
                    vals = np.arange(len(ids), dtype=np.float64) + 10 * rnd
                    try:
                        session.write_batch("default", ids, ts, vals,
                                            now_nanos=T0 + (rnd + 1) * SEC)
                    except Exception:
                        continue  # unacknowledged: no durability claim
                    for i, sid in enumerate(ids):
                        acked.append((sid, int(ts[i]), float(vals[i])))
            assert not nodes[2].alive()
            # Majority kept acknowledging through faults + a dead node.
            assert len(acked) >= 4 * 4, len(acked)
            assert fd.triggers > 0
            assert fault.counters()["rpc.call.drop_triggers"] > 0
            assert xretry.counters().get("replication.retries", 0) > 0

            # Flush live replicas so their blocks exist as filesets,
            # then the killed node rejoins and backfills over the wire.
            for k in (0, 1):
                remotes[f"i{k}"].tick(T0 + 2 * BLOCK)
            nodes[2].start()

            # Zero lost acknowledged samples (read at MAJORITY).
            want = {}
            for sid, t, v in acked:
                want.setdefault(sid, {})[t] = v
            for sid, pts in want.items():
                got = dict(session.fetch("default", sid, T0, T0 + BLOCK))
                for t, v in pts.items():
                    assert got.get(t) == v, (sid, t, v, got)

            # Convergence: anti-entropy reports all replicas equal.
            remotes["i2"].tick(T0 + 2 * BLOCK)
            rep = repair_namespace(list(remotes.values()), "default",
                                   num_shards=2)
            if not rep.converged:
                rep = repair_namespace(list(remotes.values()), "default",
                                       num_shards=2)
            assert rep.converged, rep
        finally:
            fault.disarm()
            for r in remotes.values():
                r.close()
            for nd in nodes:
                nd.kill()


@pytest.mark.slow
class TestCorruptionQuarantineRepairScenario:
    """dtest scenario for the corruption-resilience subsystem: one
    replica's flushed fileset is byte-flipped on disk (its WAL is also
    wiped, so only peers can heal it).  The node must bootstrap
    cleanly, cluster queries must stay correct throughout the
    degradation, the corrupt volume must land in quarantine/ with a
    reason file, and an admin-triggered scrub must restore
    bit-identical M3TSZ block bytes from the intact replicas
    (sha256-compared)."""

    def test_byte_flip_bootstrap_quarantine_peer_repair(self, tmp_path):
        import hashlib
        import shutil
        import urllib.request

        from m3_tpu.client.session import ConsistencyLevel, ReplicatedSession
        from m3_tpu.cluster.placement import Instance, initial_placement
        from m3_tpu.server.rpc import RemoteDatabase
        from m3_tpu.storage.database import shard_for_id

        nodes, ports = _cluster_nodes(tmp_path, n=3, admin=True)
        remotes = {}
        try:
            for nd in nodes:
                nd.start()
            remotes = {
                f"i{k}": RemoteDatabase(("127.0.0.1", ports[k]))
                for k in range(3)
            }
            placement = initial_placement(
                [Instance(f"i{k}") for k in range(3)], num_shards=2, rf=3
            )
            session = ReplicatedSession(
                placement, dict(remotes),
                write_level=ConsistencyLevel.ALL,
                read_level=ConsistencyLevel.MAJORITY,
            )
            ids = [b"cq-%d" % i for i in range(8)]
            ts = {sid: [T0 + (i + 1) * SEC for i in range(4)]
                  for sid in ids}
            for i in range(4):
                t = np.full(len(ids), T0 + (i + 1) * SEC, np.int64)
                session.write_batch("default", ids, t,
                                    np.arange(len(ids), dtype=np.float64) + i,
                                    now_nanos=T0 + (i + 1) * SEC)
            for k in range(3):
                remotes[f"i{k}"].tick(T0 + 2 * BLOCK)  # flush filesets

            # Pick a flushed data file on n2 and byte-flip it; wipe
            # n2's WAL so local replay CANNOT heal — only peers can.
            n2root = tmp_path / "n2" / "data"
            victims = sorted(
                p for p in n2root.glob("data/default/*/fileset-*-data.db")
                if p.stat().st_size > 0
            )
            assert victims, "no flushed data files on n2"
            victim = victims[0]
            shard = int(victim.parent.name)
            block_start = int(victim.stem.split("-")[1])
            want_sha = hashlib.sha256(
                (tmp_path / "n0" / "data" / victim.relative_to(n2root)
                 ).read_bytes()).hexdigest()
            assert hashlib.sha256(
                victim.read_bytes()).hexdigest() == want_sha  # replicas equal

            nodes[2].kill()
            shutil.rmtree(n2root / "commitlogs", ignore_errors=True)
            raw = bytearray(victim.read_bytes())
            raw[len(raw) // 2] ^= 0xFF
            victim.write_bytes(bytes(raw))

            # (1) clean bootstrap despite the rotten volume on disk
            nodes[2].start()

            # (2) cluster queries stay correct during degradation: the
            # corrupt replica degrades per-source (quarantining as it
            # goes), the healthy replicas fill the union.
            for i, sid in enumerate(ids):
                pts = session.fetch("default", sid, T0, T0 + BLOCK)
                assert pts == [(t, float(i) + k)
                               for k, t in enumerate(ts[sid])], (sid, pts)

            # A direct read on the degraded node triggered quarantine
            # for the corrupt (shard, block); make sure we exercised it.
            sid_hit = next(s for s in ids if shard_for_id(s, 2) == shard)
            remotes["i2"].read("default", sid_hit, T0, T0 + BLOCK)

            # (3) the volume is in quarantine/ with a reason file
            reasons = list((n2root / "quarantine").rglob("reason.json"))
            assert reasons, "quarantine tree not populated"
            reason = json.loads(reasons[0].read_text())
            assert reason["namespace"] == "default"
            assert reason["shard"] == shard
            assert reason["block_start"] == block_start
            assert reason["check"] == "digest:data"
            assert (reasons[0].parent
                    / f"fileset-{block_start}-0-data.db").exists()

            # /health on the degraded node reports the inventory
            status = json.loads((n2root / "node.json").read_text())
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{status['port']}/health",
                    timeout=10) as r:
                health = json.load(r)
            assert health["ok"] and health["quarantine"]["entries"] >= 1

            # (4) admin-triggered scrub sweep: peer-assisted repair
            # restores the block bit-identically from the replicas.
            req = urllib.request.Request(
                f"http://127.0.0.1:{status['admin_port']}"
                "/api/v1/database/scrub",
                data=b"{}", headers={"Content-Type": "application/json"},
            )
            with urllib.request.urlopen(req, timeout=60) as r:
                out = json.load(r)
            assert out["scrub"]["repaired"] >= 1, out
            assert hashlib.sha256(
                victim.read_bytes()).hexdigest() == want_sha

            # the healed node answers alone now
            for i, sid in enumerate(ids):
                pts = remotes["i2"].read("default", sid, T0, T0 + BLOCK)
                assert pts == [(t, float(i) + k)
                               for k, t in enumerate(ts[sid])], (sid, pts)

            # scrub counters are visible on the node's /metrics
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{status['port']}/metrics",
                    timeout=10) as r:
                metrics = r.read().decode()
            assert "m3tpu_scrub_volumes_checked" in metrics
            assert "m3tpu_scrub_repairs_completed" in metrics
            assert "m3tpu_db_corruption_quarantined" in metrics
        finally:
            for r in remotes.values():
                r.close()
            for nd in nodes:
                nd.kill()


@pytest.mark.slow
class TestRollingReplaceScenario:
    """The headline topology dtest: a 3-node RF=3 cluster under
    sustained ingest gets a rolling node REPLACE — i3 joins, streams
    i2's shards from the donor over the RPC surface, CAS-flips them
    AVAILABLE, the donor grace-drops its data and SIGTERM-drains, and
    the operator deletes the empty entry — with ZERO acked-sample loss
    and correct Majority reads at every phase, the whole migration
    visible in /metrics (topology_*) and /health."""

    def _configs(self, tmp_path, kv_port, rpc_ports, n=4):
        nodes = []
        for k in range(n):
            root = tmp_path / f"n{k}" / "data"
            cfg = tmp_path / f"n{k}" / "node.yaml"
            peers = [f"127.0.0.1:{p}" for i, p in enumerate(rpc_ports)
                     if i != k]
            cfg.parent.mkdir(parents=True, exist_ok=True)
            cfg.write_text(f"""
db:
  root: {root}
  instance_id: i{k}
  kv_endpoint: 127.0.0.1:{kv_port}
  rpc_listen_port: {rpc_ports[k]}
  peers: [{", ".join(repr(p) for p in peers)}]
  bootstrap_peers: true
  namespaces:
    default: {{num_shards: 2}}
coordinator: {{listen_port: 0, admin_listen_port: 0}}
mediator:
  enabled: true
  tick_interval: 300ms
  snapshot_every: 10
  cleanup_every: 10
  scrub_volumes: 0
  migrate_blocks: 2
  migrate_grace_ticks: 1
""")
            root.mkdir(parents=True, exist_ok=True)
            nodes.append(NodeProcess(str(cfg), str(root),
                                     env={"M3_DRAIN_TIMEOUT_S": "20"}))
        return nodes

    def test_rolling_node_replace_zero_acked_loss(self, tmp_path):
        import time as _time

        from m3_tpu.client.session import (
            ConsistencyError, ConsistencyLevel, ReplicatedSession,
        )
        from m3_tpu.cluster.kv_remote import (
            RemoteKVStore, serve_kv_background,
        )
        from m3_tpu.cluster.placement import PlacementService
        from m3_tpu.server.rpc import RemoteDatabase

        (tmp_path / "kv").mkdir(exist_ok=True)
        kv_srv = serve_kv_background(root=str(tmp_path / "kv"))
        rpc_ports = _free_ports(4)
        nodes = self._configs(tmp_path, kv_srv.port, rpc_ports)
        endpoints = {f"i{k}": f"127.0.0.1:{rpc_ports[k]}" for k in range(4)}
        kv = RemoteKVStore(("127.0.0.1", kv_srv.port), watch_poll_s=0.2)
        sess = None
        ingest_stop = threading.Event()
        acked = {}          # sid -> {ts: value}, only session-acked writes
        acked_lock = threading.Lock()
        # wall-clock-anchored history, two blocks back: inside
        # retention, outside the warm window -> the mediator flushes it
        T_HIST = (_time.time_ns() // BLOCK - 2) * BLOCK

        def resolve(inst):
            h, _, p = inst.endpoint.rpartition(":")
            return RemoteDatabase((h, int(p)))

        def admin(k, method, path, body=None):
            status = json.loads(
                (tmp_path / f"n{k}" / "data" / "node.json").read_text())
            req = urllib.request.Request(
                f"http://127.0.0.1:{status['admin_port']}{path}",
                method=method,
                data=json.dumps(body).encode() if body is not None else None,
            )
            with urllib.request.urlopen(req, timeout=30) as r:
                return json.load(r)

        def node_http(k, path):
            status = json.loads(
                (tmp_path / f"n{k}" / "data" / "node.json").read_text())
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{status['port']}{path}",
                    timeout=10) as r:
                body = r.read()
            try:
                return json.loads(body)
            except ValueError:
                return body.decode()

        def verify_acked(phase):
            with acked_lock:
                want = {sid: dict(pts) for sid, pts in acked.items()}
            for sid, pts in want.items():
                got = dict(sess.fetch("default", sid, T_HIST,
                                      _time.time_ns() + BLOCK))
                for t, v in pts.items():
                    assert got.get(t) == v, (phase, sid, t, v)

        try:
            for nd in nodes[:3]:
                nd.start()

            # placement init THROUGH the admin API, endpoints included
            # (the migrators dial donors by placement endpoint).
            out = admin(0, "POST", "/api/v1/services/m3db/placement/init", {
                "instances": [
                    {"id": f"i{k}", "isolation_group": f"g{k}",
                     "endpoint": endpoints[f"i{k}"]}
                    for k in range(3)
                ],
                "num_shards": 2, "rf": 3,
            })
            assert set(out["instances"]) == {"i0", "i1", "i2"}

            sess = ReplicatedSession.dynamic(
                kv, resolve,
                write_level=ConsistencyLevel.MAJORITY,
                read_level=ConsistencyLevel.MAJORITY,
            )

            # ---- phase 1: historical corpus that FLUSHES to filesets
            ids = [b"roll-%d" % i for i in range(6)]
            for r in range(4):
                t = np.full(len(ids), T_HIST + (r + 1) * 10**9, np.int64)
                v = np.arange(len(ids), dtype=np.float64) + 10 * r
                sess.write_batch("default", ids, t, v, now_nanos=int(t[0]))
                with acked_lock:
                    for i, sid in enumerate(ids):
                        acked.setdefault(sid, {})[int(t[i])] = float(v[i])

            def hist_flushed(k):
                return sorted((tmp_path / f"n{k}" / "data").glob(
                    "data/default/*/fileset-*-data.db"))

            deadline = _time.monotonic() + 180
            while _time.monotonic() < deadline:
                if all(hist_flushed(k) for k in range(3)):
                    break
                _time.sleep(0.5)
            assert all(hist_flushed(k) for k in range(3)), \
                "historical block did not flush on every node"
            verify_acked("after-flush")

            # ---- phase 2: sustained ingest at wall-clock timestamps
            def ingest():
                r = 0
                while not ingest_stop.is_set():
                    t_ns = _time.time_ns()
                    t = np.full(len(ids), t_ns, np.int64)
                    v = np.arange(len(ids), dtype=np.float64) + 1000 * r
                    try:
                        sess.write_batch("default", ids, t, v,
                                         now_nanos=t_ns)
                    except (ConsistencyError, ConnectionError):
                        r += 1
                        continue  # unacknowledged: no durability claim
                    with acked_lock:
                        for i, sid in enumerate(ids):
                            acked.setdefault(sid, {})[int(t[i])] = float(v[i])
                    r += 1
                    _time.sleep(0.15)

            ingest_t = threading.Thread(target=ingest, daemon=True)
            ingest_t.start()

            # ---- phase 3: the replacement node joins (not in the
            # placement yet -> placement-scoped bootstrap copies NOTHING)
            nodes[3].start()
            assert not list((tmp_path / "n3" / "data").glob(
                "data/default/*/fileset-*")), \
                "out-of-placement node must not peer-copy any shard"

            out = admin(0, "POST",
                        "/api/v1/services/m3db/placement/replace", {
                            "leaving_id": "i2",
                            "instance": {"id": "i3", "isolation_group": "g3",
                                         "endpoint": endpoints["i3"]},
                        })
            assert all(st == "I" and src == "i2"
                       for st, src in out["instances"]["i3"]["shards"].values())

            # ---- phase 4: donor shards stream + CAS-flip AVAILABLE
            ps = PlacementService(kv)
            deadline = _time.monotonic() + 180
            while _time.monotonic() < deadline:
                p = ps.get()
                i3 = p.instances.get("i3")
                done = (i3 is not None and i3.shards
                        and all(a.state.value == "A"
                                for a in i3.shards.values())
                        and not p.instances["i2"].shards)
                if done:
                    break
                _time.sleep(0.5)
            else:
                pytest.fail(f"migration did not complete: {ps.get().to_json()}")
            verify_acked("after-cutover")

            # the newcomer really streamed the flushed history
            assert hist_flushed(3), "streamed filesets missing on i3"
            metrics = node_http(3, "/metrics")
            assert "m3tpu_topology_blocks_streamed" in metrics
            assert "m3tpu_topology_placement_version" in metrics
            health = node_http(3, "/health")
            assert health["topology"]["in_placement"]
            assert set(map(int, health["topology"]["shards"]["available"])) \
                == {0, 1}

            # ---- phase 5: the donor grace-drops its shard data
            deadline = _time.monotonic() + 60
            while _time.monotonic() < deadline:
                if not hist_flushed(2):
                    break
                _time.sleep(0.5)
            assert not hist_flushed(2), "donor kept its dropped filesets"
            health2 = node_http(2, "/health")
            assert health2["topology"]["shards"]["available"] == []

            # ---- phase 6: donor drains (SIGTERM is a true drain) and
            # the operator removes the empty entry
            rc = nodes[2].stop(timeout_s=90)
            assert rc == 0
            out = admin(0, "DELETE", "/api/v1/services/m3db/placement/i2")
            assert "i2" not in out["instances"]

            ingest_stop.set()
            ingest_t.join(20)

            # ---- final: ZERO acked-sample loss at Majority over the
            # surviving topology {i0, i1, i3}
            with acked_lock:
                n_acked = sum(len(p) for p in acked.values())
            # 24 historical points plus at least a couple of live
            # rounds: Majority ingest must have kept acking throughout
            assert n_acked >= 4 * len(ids) + 2 * len(ids), n_acked
            verify_acked("final")
        finally:
            ingest_stop.set()
            if sess is not None:
                sess.close()
            kv.close()
            for nd in nodes:
                nd.kill()
            kv_srv.shutdown()
            kv_srv.server_close()


@pytest.mark.slow
class TestDtestScenarios:
    def test_crash_recovery_via_real_process(self, tmp_path):
        """Seed → kill -9 → restart → the data is back (WAL replay
        through an actual process crash, not an in-process simulation)."""
        node = _node(tmp_path)
        node.start()
        try:
            assert node.write_json(_samples(40)) == 40
            before = node.query_range("sum(dt)", START_S, START_S + 400)
            assert before
            node.kill()  # no flush, no graceful close
            assert not node.alive()
            node.start()
            after = node.query_range("sum(dt)", START_S, START_S + 400)
            assert after == before
        finally:
            node.kill()

    def test_graceful_stop_then_restart(self, tmp_path):
        node = _node(tmp_path)
        node.start()
        try:
            node.write_json(_samples(10))
            rc = node.stop()
            assert rc == 0
            assert not (tmp_path / "data" / "node.json").exists()
            node.start()
            out = node.query_range("dt", START_S, START_S + 100)
            assert len(out) == 2  # both hosts
        finally:
            node.kill()


@pytest.mark.slow
class TestAgentLifecycle:
    """m3em-agent scenario: the dtest driver manages a node purely
    through the agent's HTTP surface (reference m3em operator verbs)."""

    def test_setup_start_crash_restart_teardown(self, tmp_path):
        from m3_tpu.dtest.agent import AgentClient, serve_agent_background

        srv = serve_agent_background(str(tmp_path / "agent"))
        client = AgentClient(srv.server_address)
        try:
            cfg = """
db:
  root: {root}
  namespaces:
    default: {{num_shards: 2}}
coordinator: {{listen_port: 0}}
mediator: {{enabled: false}}
"""
            out = client.setup("n1", cfg.format(root=tmp_path / "agent" / "n1" / "data"))
            assert out["name"] == "n1"
            st = client.start("n1")
            assert st["alive"] and st["port"]
            port = st["port"]

            # write through the node's own HTTP API
            import urllib.request
            req = urllib.request.Request(
                f"http://127.0.0.1:{port}/api/v1/json/write",
                data=json.dumps([{"tags": {"__name__": "am"},
                                  "timestamp": START_S + 10,
                                  "value": 5.0}]).encode(),
                headers={"Content-Type": "application/json"},
            )
            with urllib.request.urlopen(req, timeout=30) as r:
                assert json.load(r)["written"] == 1

            # crash + heartbeat shows it dead; logs are retrievable
            client.kill("n1")
            assert not client.status()["nodes"]["n1"]["alive"]
            assert isinstance(client.logs("n1"), bytes)

            # restart through the agent: WAL recovery inside the node
            st2 = client.start("n1")
            assert st2["alive"]
            url = (f"http://127.0.0.1:{st2['port']}/api/v1/query_range?"
                   f"query=am&start={START_S}&end={START_S + 100}&step=10s")
            with urllib.request.urlopen(url, timeout=60) as r:
                out = json.load(r)
            assert out["data"]["result"], out

            client.teardown("n1")
            assert "n1" not in client.status()["nodes"]
            assert not (tmp_path / "agent" / "n1").exists()
        finally:
            srv.agent.close()
            srv.shutdown()
            srv.server_close()


class TestAgentNameSafety:
    def test_path_escaping_names_rejected(self, tmp_path):
        from m3_tpu.dtest.agent import Agent

        a = Agent(str(tmp_path / "w"))
        for bad in ("../x", "a/b", "..", "", "x" * 65, "a\x00b"):
            with pytest.raises(ValueError):
                a.setup(bad, "db: {}")
            with pytest.raises((ValueError, KeyError)):
                a.teardown(bad)
        a.close()
