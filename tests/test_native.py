"""Native C++ codec vs the golden-validated Python scalar codec:
byte-identical encode, identical decode, correct fallback signaling."""

import numpy as np
import pytest

from m3_tpu import native
from m3_tpu.encoding.m3tsz import Datapoint, decode_series, encode_series

START = 1_700_000_000 * 10**9

pytestmark = pytest.mark.skipif(
    not native.available(), reason="native toolchain unavailable"
)


def _cases():
    rng = np.random.default_rng(11)
    T = 300
    ts_reg = START + np.arange(1, T + 1) * 10 * 10**9
    out = []
    out.append(("int-ramp", ts_reg, (np.arange(T) % 97).astype(float)))
    out.append(("const", ts_reg, np.full(T, 42.0)))
    out.append(("decimal2", ts_reg, np.round(rng.normal(100, 10, T), 2)))
    out.append(("floats", ts_reg, rng.normal(0, 1, T)))
    out.append(("mixed", ts_reg, np.where(np.arange(T) % 7 == 0,
                                          rng.normal(0, 1, T),
                                          np.round(rng.uniform(0, 50, T), 1))))
    out.append(("big-counter", ts_reg, np.cumsum(rng.integers(0, 10**6, T)).astype(float)))
    out.append(("negative", ts_reg, -np.round(rng.uniform(0, 1000, T), 3)))
    # irregular timestamps crossing every dod bucket
    gaps = np.concatenate([
        np.full(50, 10), rng.integers(1, 60, 50), rng.integers(60, 2000, 30),
        rng.integers(2000, 300000, 10),
    ]) * 10**9
    ts_irr = START + np.cumsum(gaps)
    v = rng.normal(10, 1, len(ts_irr))
    out.append(("irregular-ts", ts_irr, v))
    out.append(("single", ts_reg[:1], np.array([3.5])))
    return out


@pytest.mark.parametrize("name,ts,vals", _cases(), ids=[c[0] for c in _cases()])
def test_encode_byte_identical(name, ts, vals):
    want = encode_series(list(zip(ts.tolist(), vals.tolist())), start=START)
    got = native.encode_series(ts, vals, START)
    assert got == want, f"{name}: native encode differs"


@pytest.mark.parametrize("name,ts,vals", _cases(), ids=[c[0] for c in _cases()])
def test_decode_matches(name, ts, vals):
    blob = encode_series(list(zip(ts.tolist(), vals.tolist())), start=START)
    out = native.decode_series(blob)
    assert out is not None
    dts, dvals = out
    np.testing.assert_array_equal(dts, ts)
    np.testing.assert_array_equal(dvals, vals)


def test_misaligned_start_falls_back():
    ts = START + 5 + np.arange(1, 10) * 10**10
    assert native.encode_series(ts, np.ones(9), START + 5) is None


def test_annotation_stream_falls_back():
    from m3_tpu.encoding.m3tsz import Encoder
    enc = Encoder(START)
    enc.encode(Datapoint(START + 10**10, 1.0, annotation=b"schema1"))
    enc.encode(Datapoint(START + 2 * 10**10, 2.0))
    assert native.decode_series(enc.stream()) is None


def test_corrupt_stream_raises():
    blob = encode_series([(START + 10**10, 1.0)], start=START)
    with pytest.raises(ValueError):
        native.decode_series(blob[:6])


def test_roundtrip_fuzz():
    rng = np.random.default_rng(7)
    for trial in range(25):
        n = int(rng.integers(1, 200))
        gaps = rng.integers(1, 100, n) * 10**9
        ts = START + np.cumsum(gaps)
        kind = trial % 3
        if kind == 0:
            vals = rng.integers(-(10**6), 10**6, n).astype(float)
        elif kind == 1:
            vals = np.round(rng.normal(0, 100, n), int(rng.integers(0, 5)))
        else:
            vals = rng.normal(0, 1e9, n)
        want = encode_series(list(zip(ts.tolist(), vals.tolist())), start=START)
        got = native.encode_series(ts, vals, START)
        assert got == want, f"trial {trial}"
        dts, dvals = native.decode_series(got)
        np.testing.assert_array_equal(dts, ts)
        # Contract: identical to the Python decoder.  (Not to the raw
        # input: the int optimization's nextafter tolerance may snap a
        # near-decimal float by 1 ulp — reference m3tsz.go:78-118 — and
        # both decoders must agree on that snapped value.)
        py_vals = np.array([d.value for d in decode_series(got)])
        np.testing.assert_array_equal(dvals, py_vals)


def test_batch_roundtrip_matches_single():
    """Batched encode/decode agree with the single-series entry points
    (and therefore with the Python oracle) across mixed value shapes."""
    rng = np.random.default_rng(13)
    S, T = 64, 97
    ts = np.tile(START + np.arange(1, T + 1) * 10 * 10**9, (S, 1)).astype(np.int64)
    vals = np.empty((S, T))
    vals[0::3] = rng.integers(-(10**6), 10**6, ((S + 2) // 3, T)).astype(float)
    vals[1::3] = np.round(rng.normal(0, 100, ((S + 1) // 3, T)), 2)
    vals[2::3] = rng.normal(0, 1e9, (S // 3, T))
    starts = np.full(S, START, np.int64)
    counts = rng.integers(1, T + 1, S)

    streams, fb = native.encode_batch(ts, vals, starts, counts=counts)
    assert not fb.any()
    for i in (0, 1, 2, 31, S - 1):
        n = int(counts[i])
        assert streams[i] == native.encode_series(ts[i, :n], vals[i, :n], START)

    dts, dvals, dcounts, dfb = native.decode_batch(streams, T + 1)
    assert not dfb.any()
    np.testing.assert_array_equal(dcounts, counts)
    for i in range(S):
        n = int(counts[i])
        sts, svals = native.decode_series(streams[i], max_points=T + 1)
        np.testing.assert_array_equal(dts[i, :n], sts)
        np.testing.assert_array_equal(dvals[i, :n], svals)


def test_batch_flags_bad_streams_and_continues():
    """A rejected or truncated stream flags fallback without poisoning
    its neighbours."""
    ts = START + np.arange(1, 9) * 10**10
    good = native.encode_series(ts, np.arange(8.0), START)

    from m3_tpu.encoding.m3tsz import Encoder
    enc = Encoder(START)
    enc.encode(Datapoint(START + 10**10, 1.0, annotation=b"s1"))
    annotated = enc.stream()

    streams = [good, annotated, good[:5], good]
    dts, dvals, counts, fb = native.decode_batch(streams, 16)
    assert list(fb) == [False, True, True, False]
    assert counts[0] == 8 and counts[3] == 8
    np.testing.assert_array_equal(dts[0, :8], ts)
    np.testing.assert_array_equal(dts[3, :8], ts)


def test_batch_threaded_matches_inline():
    rng = np.random.default_rng(5)
    S, T = 40, 50
    ts = np.tile(START + np.arange(1, T + 1) * 10**10, (S, 1)).astype(np.int64)
    vals = np.round(rng.normal(0, 50, (S, T)), 1)
    starts = np.full(S, START, np.int64)
    s1, _ = native.encode_batch(ts, vals, starts, nthreads=1)
    s4, _ = native.encode_batch(ts, vals, starts, nthreads=4)
    assert s1 == s4
    out1 = native.decode_batch(s1, T + 1, nthreads=1)
    out4 = native.decode_batch(s1, T + 1, nthreads=4)
    for a, b in zip(out1, out4):
        np.testing.assert_array_equal(a, b)
