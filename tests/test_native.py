"""Native C++ codec vs the golden-validated Python scalar codec:
byte-identical encode, identical decode, correct fallback signaling."""

import numpy as np
import pytest

from m3_tpu import native
from m3_tpu.encoding.m3tsz import Datapoint, decode_series, encode_series

START = 1_700_000_000 * 10**9

pytestmark = pytest.mark.skipif(
    not native.available(), reason="native toolchain unavailable"
)


def _cases():
    rng = np.random.default_rng(11)
    T = 300
    ts_reg = START + np.arange(1, T + 1) * 10 * 10**9
    out = []
    out.append(("int-ramp", ts_reg, (np.arange(T) % 97).astype(float)))
    out.append(("const", ts_reg, np.full(T, 42.0)))
    out.append(("decimal2", ts_reg, np.round(rng.normal(100, 10, T), 2)))
    out.append(("floats", ts_reg, rng.normal(0, 1, T)))
    out.append(("mixed", ts_reg, np.where(np.arange(T) % 7 == 0,
                                          rng.normal(0, 1, T),
                                          np.round(rng.uniform(0, 50, T), 1))))
    out.append(("big-counter", ts_reg, np.cumsum(rng.integers(0, 10**6, T)).astype(float)))
    out.append(("negative", ts_reg, -np.round(rng.uniform(0, 1000, T), 3)))
    # irregular timestamps crossing every dod bucket
    gaps = np.concatenate([
        np.full(50, 10), rng.integers(1, 60, 50), rng.integers(60, 2000, 30),
        rng.integers(2000, 300000, 10),
    ]) * 10**9
    ts_irr = START + np.cumsum(gaps)
    v = rng.normal(10, 1, len(ts_irr))
    out.append(("irregular-ts", ts_irr, v))
    out.append(("single", ts_reg[:1], np.array([3.5])))
    return out


@pytest.mark.parametrize("name,ts,vals", _cases(), ids=[c[0] for c in _cases()])
def test_encode_byte_identical(name, ts, vals):
    want = encode_series(list(zip(ts.tolist(), vals.tolist())), start=START)
    got = native.encode_series(ts, vals, START)
    assert got == want, f"{name}: native encode differs"


@pytest.mark.parametrize("name,ts,vals", _cases(), ids=[c[0] for c in _cases()])
def test_decode_matches(name, ts, vals):
    blob = encode_series(list(zip(ts.tolist(), vals.tolist())), start=START)
    out = native.decode_series(blob)
    assert out is not None
    dts, dvals = out
    np.testing.assert_array_equal(dts, ts)
    np.testing.assert_array_equal(dvals, vals)


def test_misaligned_start_falls_back():
    ts = START + 5 + np.arange(1, 10) * 10**10
    assert native.encode_series(ts, np.ones(9), START + 5) is None


def test_annotation_stream_falls_back():
    from m3_tpu.encoding.m3tsz import Encoder
    enc = Encoder(START)
    enc.encode(Datapoint(START + 10**10, 1.0, annotation=b"schema1"))
    enc.encode(Datapoint(START + 2 * 10**10, 2.0))
    assert native.decode_series(enc.stream()) is None


def test_corrupt_stream_raises():
    blob = encode_series([(START + 10**10, 1.0)], start=START)
    with pytest.raises(ValueError):
        native.decode_series(blob[:6])


def test_roundtrip_fuzz():
    rng = np.random.default_rng(7)
    for trial in range(25):
        n = int(rng.integers(1, 200))
        gaps = rng.integers(1, 100, n) * 10**9
        ts = START + np.cumsum(gaps)
        kind = trial % 3
        if kind == 0:
            vals = rng.integers(-(10**6), 10**6, n).astype(float)
        elif kind == 1:
            vals = np.round(rng.normal(0, 100, n), int(rng.integers(0, 5)))
        else:
            vals = rng.normal(0, 1e9, n)
        want = encode_series(list(zip(ts.tolist(), vals.tolist())), start=START)
        got = native.encode_series(ts, vals, START)
        assert got == want, f"trial {trial}"
        dts, dvals = native.decode_series(got)
        np.testing.assert_array_equal(dts, ts)
        # Contract: identical to the Python decoder.  (Not to the raw
        # input: the int optimization's nextafter tolerance may snap a
        # near-decimal float by 1 ulp — reference m3tsz.go:78-118 — and
        # both decoders must agree on that snapped value.)
        py_vals = np.array([d.value for d in decode_series(got)])
        np.testing.assert_array_equal(dvals, py_vals)
