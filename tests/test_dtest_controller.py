"""Round-18 acceptance dtest: the SLO-burn controller closes the loop
on a live cluster — sustained fault → shed → recovery → relax back.

3 real node processes (rf=3, shared remote KV, placement via the admin
API) under sustained Majority ingest, self-monitoring AND the
x/controller control plane riding every mediator tick.  One
``sustained`` chaos event (the round-18 verb: arm + hold + auto-disarm
as a single timeline entry) drops 40% of node 1's rpc write frames,
which must drive the full loop:

* the dedicated ``ingest-errors`` burn rule FIRES on node 1 (its own
  self-stored drop/ingest series, through the ordinary PromQL engine),
* the controller sheds through the typed actuator registry — the
  ``query_slots`` actuator leaves baseline, the decision lands in the
  ``/health`` ``controller`` section,
* the fault auto-disarms, the windows wash out, the verdict RECOVERS
  below the clear threshold, and the controller relaxes every
  actuator back to baseline with half-open discipline,
* ZERO acked-sample loss throughout (the soak ledger's regenerate-
  and-reread verify at Majority),
* the whole act→relax sequence is retro-queryable as PromQL over the
  ``_m3_selfmon`` ``controller_action`` history FROM A PEER (node 0
  fleet-scraped node 1's emission gauges).
"""

import json
import threading
import time
import urllib.request

import numpy as np
import pytest

from m3_tpu.dtest.soak import (
    NS, Ledger, SoakCluster, SoakConfig, WorkloadGen, _verify,
)
from m3_tpu.x.chaos import ChaosEvent, ChaosScheduler


def _health(cluster, k):
    with urllib.request.urlopen(
            f"http://127.0.0.1:{cluster.http_port(k)}/health",
            timeout=30) as r:
        return json.load(r)


def _controller(cluster, k):
    return _health(cluster, k).get("controller") or {}


def _rule_firing(cluster, k, rule):
    doc = (_health(cluster, k).get("slo") or {}).get("rules", {}).get(rule)
    return doc is not None and doc.get("firing") is True


@pytest.mark.slow
class TestSelfHealingScenario:
    def test_sustained_fault_shed_recover_relax(self, tmp_path):
        cfg = SoakConfig(
            nodes=3, series=4000, batch=1000, num_shards=4,
            slot_capacity=1 << 16, churn=0.0, smoke=True,  # 1s ticks
            replace=False, selfmon_budget=4000,
            controller_fire_ticks=2, controller_clear_ticks=3,
            controller_hold_ticks=1, controller_min_interval="2s",
        )
        cluster = SoakCluster(cfg, tmp_path / "cluster")
        scheduler = None
        try:
            cluster.start()
            gen = WorkloadGen(cfg.series, cfg.churn, cfg.seed)
            ledger = Ledger(gen)
            stop = threading.Event()

            def ingest():
                sweep = 0
                while not stop.is_set():
                    for lo in range(0, cfg.series, cfg.batch):
                        if stop.is_set():
                            break
                        hi = min(lo + cfg.batch, cfg.series)
                        ids = gen.ids(sweep, lo, hi)
                        vals = gen.values(sweep, lo, hi)
                        ts = time.time_ns()
                        tsa = np.full(hi - lo, ts, np.int64)
                        try:
                            rejected = cluster.session.write_batch(
                                NS, ids, tsa, vals, now_nanos=ts)
                        except Exception:  # noqa: BLE001 — unacked
                            stop.wait(0.2)
                            continue
                        if not rejected:
                            ledger.ack_bulk(sweep, lo, hi, ts)
                    sweep += 1

            t = threading.Thread(target=ingest, daemon=True)
            t.start()

            # -- baseline: controller live, bound, and QUIET ----------
            deadline = time.monotonic() + 120
            while time.monotonic() < deadline:
                ctl = _controller(cluster, 1)
                if ctl.get("enabled") and "ingest-burn" in ctl.get(
                        "bindings", {}):
                    break
                time.sleep(1.0)
            else:
                pytest.fail("controller never appeared on node 1's "
                            f"/health: {_controller(cluster, 1)}")
            assert _controller(cluster, 1)["actions_total"] == 0
            assert not _rule_firing(cluster, 1, "ingest-errors")

            # -- ONE sustained event: arm 40% drops on node 1, hold,
            #    auto-disarm — the scheduler sees only the expansion
            scheduler = ChaosScheduler(
                [ChaosEvent(1.0, "sustained", node=1,
                            arg="rpc.server=drop:p=0.4", hold_s=35.0)],
                cluster, seed=7)
            scheduler.start()

            # -- the loop must CLOSE: burn fires, controller sheds ----
            deadline = time.monotonic() + 120
            shed_seen = None
            while time.monotonic() < deadline:
                ctl = _controller(cluster, 1)
                recent = ctl.get("recent", [])
                if any(a["action"] == "shed" for a in recent):
                    shed_seen = recent
                    break
                time.sleep(1.0)
            else:
                pytest.fail(
                    "controller never shed on the faulted node; "
                    f"health={_controller(cluster, 1)}")
            assert any(a["actuator"] == "query_slots"
                       and a["rule"] == "ingest-errors"
                       for a in shed_seen)
            # the mutation is typed and bounds-clamped: the actuator
            # moved off baseline but never past its shed limit
            act = _controller(cluster, 1)["actuators"]["query_slots"]
            assert act["at_baseline"] is False
            lo = min(act["baseline"], act["shed_limit"])
            hi = max(act["baseline"], act["shed_limit"])
            assert lo <= act["value"] <= hi

            # -- recovery: disarm (automatic), burn clears, controller
            #    relaxes EVERYTHING back to baseline ------------------
            deadline = time.monotonic() + 240
            while time.monotonic() < deadline:
                ctl = _controller(cluster, 1)
                acts = ctl.get("actuators", {})
                if acts and all(a["at_baseline"] for a in acts.values()):
                    break
                time.sleep(2.0)
            else:
                pytest.fail("actuators never relaxed back to baseline; "
                            f"health={_controller(cluster, 1)}")
            assert not _rule_firing(cluster, 1, "ingest-errors")
            recent = _controller(cluster, 1)["recent"]
            actions = [a["action"] for a in recent]
            assert "shed" in actions and "relax" in actions
            assert actions.index("shed") < len(actions) - 1 - \
                actions[::-1].index("relax")  # shed happened, relax after

            # -- zero acked-sample loss throughout --------------------
            stop.set()
            t.join(60)
            assert ledger.acked_samples > 0
            for k in cluster.alive_nodes():
                cluster.nodes[k].wait_healthy(120)
            verdict = _verify(cluster, ledger, cfg)
            assert verdict["zero_acked_loss"], verdict

            # -- the whole sequence is one PromQL query away from a
            #    PEER: node 0 answers for node 1's controller history
            deadline = time.monotonic() + 90
            got = set()
            while time.monotonic() < deadline:
                rows = cluster.promql(
                    0, 'max_over_time(m3tpu_controller_action'
                       '{instance="i1",actuator="query_slots"}[15m])',
                    namespace="_m3_selfmon")
                got = {r["metric"].get("action") for r in rows}
                if {"shed", "relax"} <= got:
                    break
                time.sleep(2.0)
            assert {"shed", "relax"} <= got, (
                f"peer-readable controller_action history incomplete: "
                f"{got}")
        finally:
            if scheduler is not None:
                scheduler.stop()
            cluster.close()
