"""Corruption-resilience tier: typed errors, quarantine, per-source read
degradation, bootstrap survival, the background scrubber, and
peer-assisted recovery — the disk edge's mirror of PR 1's wire fault
substrate (reference: checksum-verify-on-read + repair-from-peers,
`src/dbnode/persist/fs/read.go`, `src/dbnode/storage/repair.go`)."""

import hashlib
import json

import numpy as np
import pytest

from m3_tpu import instrument
from m3_tpu.encoding.m3tsz import encode_series
from m3_tpu.persist import quarantine as quar
from m3_tpu.persist import snapshot as snap
from m3_tpu.persist.commitlog import (
    CommitLogWriter, FsyncPolicy, list_commitlogs, read_commitlog,
)
from m3_tpu.persist.corruption import (
    ChecksumMismatch, CorruptionError, FormatCorruption,
)
from m3_tpu.persist.fs import (
    DataFileSetReader, DataFileSetWriter, fileset_path, list_fileset_volumes,
    list_filesets,
)
from m3_tpu.storage.database import (
    Database, DatabaseOptions, NamespaceOptions, shard_for_id,
)
from m3_tpu.storage.scrub import Scrubber, scrub_root, verify_volume
from m3_tpu.x import fault

BLOCK = 2 * 3600 * 10**9
START = (1_700_000_000 * 10**9) // BLOCK * BLOCK  # block-aligned
SEC = 10**9


def _ns_opts(**kw):
    defaults = dict(
        block_size_nanos=BLOCK,
        retention_nanos=48 * 3600 * 10**9,
        buffer_past_nanos=10 * 60 * 10**9,
        buffer_future_nanos=2 * 60 * 10**9,
        num_shards=2,
        slot_capacity=1 << 10,
        sample_capacity=1 << 12,
    )
    defaults.update(kw)
    return NamespaceOptions(**defaults)


def _flip(path, offset=None):
    raw = bytearray(path.read_bytes())
    assert raw, path
    i = len(raw) // 2 if offset is None else offset
    raw[i] ^= 0xFF
    path.write_bytes(bytes(raw))


def _truncate(path, frac=0.5):
    raw = path.read_bytes()
    assert raw, path
    path.write_bytes(raw[: max(1, int(len(raw) * frac))])


def _write_fileset(root, ns="ns", shard=0, block_start=START, volume=0, n=5):
    series = [
        (b"series-%03d" % i,
         encode_series([(block_start + (j + 1) * SEC, float(i + j))
                        for j in range(4)], start=block_start))
        for i in range(n)
    ]
    DataFileSetWriter(root, ns, shard, block_start, BLOCK,
                      volume=volume).write_all(series)
    return series


class TestTypedErrors:
    @pytest.mark.parametrize("ftype,mangle", [
        ("checkpoint", _flip),
        ("digest", _flip),
        ("data", _flip),
        ("index", _truncate),   # torn index → digest:index mismatch
        ("info", _flip),
        ("summaries", _flip),
        ("bloom", _flip),
    ])
    def test_reader_raises_typed_corruption(self, tmp_path, ftype, mangle):
        _write_fileset(tmp_path)
        mangle(fileset_path(tmp_path, "ns", 0, START, 0, ftype))
        with pytest.raises(CorruptionError) as ei:
            DataFileSetReader(tmp_path, "ns", 0, START, 0)
        err = ei.value
        assert isinstance(err, ValueError)  # back-compat contract
        assert err.component == "fileset"
        assert err.check
        assert err.path

    def test_fileset_read_corrupt_faultpoint(self, tmp_path):
        series = _write_fileset(tmp_path)
        r = DataFileSetReader(tmp_path, "ns", 0, START, 0)
        sid = series[0][0]
        assert r.read(sid) == series[0][1]  # clean before arming
        with fault.armed("fileset.read", "corrupt", seed=3):
            with pytest.raises(ChecksumMismatch) as ei:
                r.read(sid)
            assert ei.value.check == "segment-checksum"
            with pytest.raises(ChecksumMismatch):
                list(r.read_all())
        assert r.read(sid) == series[0][1]  # disk untouched
        r.close()

    def test_snapshot_metadata_typed(self, tmp_path):
        snap.commit_snapshot(tmp_path, 0, 3)
        p = snap.meta_path(tmp_path, 0)
        _flip(p, offset=10)
        with pytest.raises(CorruptionError):
            snap.SnapshotMetadata.from_bytes(p.read_bytes(), path=p)
        assert snap.list_snapshots(tmp_path) == []  # still skipped, no raise

    def test_truncated_checkpoint_is_format_corruption(self, tmp_path):
        _write_fileset(tmp_path)
        p = fileset_path(tmp_path, "ns", 0, START, 0, "checkpoint")
        p.write_bytes(p.read_bytes()[:2])
        with pytest.raises(FormatCorruption):
            DataFileSetReader(tmp_path, "ns", 0, START, 0)

    def test_missing_file_with_checkpoint_is_corruption_not_race(self, tmp_path):
        """Deletion removes the checkpoint FIRST, so a volume whose
        checkpoint exists but whose data file is gone is damage — it
        must be typed (and hence scrubbed/quarantined), not skipped as
        a cleanup race."""
        _write_fileset(tmp_path, ns="default", shard=0)
        fileset_path(tmp_path, "default", 0, START, 0, "data").unlink()
        with pytest.raises(FormatCorruption) as ei:
            DataFileSetReader(tmp_path, "default", 0, START, 0)
        assert ei.value.check == "missing-file"
        results = scrub_root(tmp_path)
        bad = [r for r in results if not r["ok"]]
        assert len(bad) == 1 and bad[0]["check"] == "missing-file"
        assert len(quar.list_quarantined(tmp_path)) == 1

    def test_corrupt_sealed_index_segment_does_not_crash_db_init(self, tmp_path):
        """A rotted main-root index segment must not crash-loop node
        start: NamespaceIndex skips it (data still serves via
        filesets/WAL)."""
        seg_dir = tmp_path / "index" / "default"
        seg_dir.mkdir(parents=True)
        (seg_dir / f"segment-{START}.db").write_bytes(b"\x00garbage\xff" * 8)
        db = _mkdb(tmp_path)
        db.bootstrap()  # neither init nor bootstrap may raise
        assert db.namespaces["default"].index.sealed == {}
        db.close()


class TestQuarantine:
    def test_move_reason_and_inventory(self, tmp_path):
        _write_fileset(tmp_path)
        err = ChecksumMismatch("digest mismatch for data file",
                               path="x", component="fileset",
                               check="digest:data")
        qdir = quar.quarantine_fileset(tmp_path, "ns", 0, START, 0, err)
        assert qdir is not None
        # invisible to the live tree, files preserved in quarantine
        assert list_filesets(tmp_path, "ns", 0) == []
        assert (qdir / f"fileset-{START}-0-checkpoint.db").exists()
        assert (qdir / f"fileset-{START}-0-data.db").exists()
        reason = json.loads((qdir / "reason.json").read_text())
        assert reason["check"] == "digest:data"
        assert reason["kind"] == "fileset" and reason["label"] == "data"
        assert reason["namespace"] == "ns" and reason["shard"] == 0
        assert reason["block_start"] == START and reason["volume"] == 0
        inv = quar.list_quarantined(tmp_path)
        assert len(inv) == 1 and inv[0]["dir"] == str(qdir)

    def test_requarantine_gets_unique_dir(self, tmp_path):
        _write_fileset(tmp_path)
        q1 = quar.quarantine_fileset(tmp_path, "ns", 0, START, 0, None)
        _write_fileset(tmp_path)  # healed (rewritten), rots again
        q2 = quar.quarantine_fileset(tmp_path, "ns", 0, START, 0, None)
        assert q1 != q2 and q2.name.endswith("-2")
        assert len(quar.list_quarantined(tmp_path)) == 2

    def test_quarantine_nothing_returns_none(self, tmp_path):
        assert quar.quarantine_fileset(tmp_path, "ns", 0, START, 0) is None
        assert quar.list_quarantined(tmp_path) == []


def _mkdb(tmp_path, reg=None, **dbkw):
    scope = reg.scope("t") if reg is not None else None
    return Database(
        DatabaseOptions(root=str(tmp_path), **dbkw),
        {"default": _ns_opts()}, instrument=scope,
    )


class TestReadDegradation:
    """Satellite regression: Shard.read_sources must degrade per-source
    on a corrupt fileset — buffers (and replicas) that still hold the
    data keep answering, and the volume is quarantined."""

    def test_read_serves_buffered_points_despite_corrupt_fileset(self, tmp_path):
        reg = instrument.new_registry()
        db = _mkdb(tmp_path, reg)
        sid = b"deg-series"
        shard = db.namespaces["default"].shards[shard_for_id(sid, 2)]
        t1 = START + 10 * SEC
        db.write_batch("default", [sid], np.array([t1]), np.array([1.0]))
        now = START + BLOCK + _ns_opts().buffer_past_nanos + SEC
        db.tick(now)  # flushes volume 0
        t2 = START + 20 * SEC
        db.write_batch("default", [sid], np.array([t2]), np.array([2.0]),
                       now_nanos=now)  # cold write, stays buffered
        _flip(fileset_path(tmp_path, "default", shard.shard_id, START, 0,
                           "data"))
        # The read must NOT raise: the corrupt fileset source degrades,
        # the cold buffer still answers.
        got = db.read("default", sid, START, START + BLOCK)
        assert got == [(t2, 2.0)]
        inv = quar.list_quarantined(tmp_path)
        assert len(inv) == 1 and inv[0]["shard"] == shard.shard_id
        assert reg.snapshot()["t.db.corruption_detected"] == 1
        # the block is no longer marked flushed: nothing intact remains
        assert START not in shard.flushed_blocks
        db.close()

    def test_falls_back_to_next_lower_intact_volume(self, tmp_path):
        sid = b"vol-series"
        shard_id = shard_for_id(sid, 2)
        pts_v0 = [(START + 5 * SEC, 1.5)]
        pts_v1 = [(START + 5 * SEC, 9.5)]
        root = tmp_path
        DataFileSetWriter(root, "default", shard_id, START, BLOCK,
                          volume=0).write_all(
            [(sid, encode_series(pts_v0, start=START))])
        DataFileSetWriter(root, "default", shard_id, START, BLOCK,
                          volume=1).write_all(
            [(sid, encode_series(pts_v1, start=START))])
        _flip(fileset_path(root, "default", shard_id, START, 1, "data"))
        db = _mkdb(tmp_path)
        got = db.read("default", sid, START, START + BLOCK)
        assert got == pts_v0  # volume 1 corrupt → volume 0 answers
        assert dict(list_filesets(root, "default", shard_id)) == {START: 0}
        inv = quar.list_quarantined(tmp_path)
        assert [e["volume"] for e in inv] == [1]
        # block still flushed: an intact volume remains
        assert START in db.namespaces["default"].shards[shard_id].flushed_blocks
        db.close()


class TestBootstrapResilience:
    """Acceptance matrix: corrupt checkpoint / digest / data segment /
    torn index — bootstrap never raises, the volume is quarantined, and
    WAL replay re-covers the lost block in the buffers."""

    CASES = [("checkpoint", _flip), ("digest", _flip), ("data", _flip),
             ("index", _truncate)]

    @pytest.mark.parametrize("ftype,mangle", CASES)
    def test_bootstrap_survives_and_wal_recovers(self, tmp_path, ftype, mangle):
        opts = DatabaseOptions(root=str(tmp_path))
        db1 = Database(opts, {"default": _ns_opts()})
        sid = b"boot-series"
        shard_id = shard_for_id(sid, 2)
        ts = np.array([START + (k + 1) * SEC for k in range(6)], np.int64)
        vals = np.arange(6, dtype=np.float64)
        db1.write_batch("default", [sid] * 6, ts, vals)
        now = START + BLOCK + _ns_opts().buffer_past_nanos + SEC
        db1.tick(now)
        db1.close()

        mangle(fileset_path(tmp_path, "default", shard_id, START, 0, ftype))

        reg = instrument.new_registry()
        db2 = _mkdb(tmp_path, reg)
        rep = db2.bootstrap()  # must not raise
        assert rep["commitlog_replayed"] == 6  # WAL re-covered the hole
        got = db2.read("default", sid, START, START + BLOCK)
        assert got == list(zip(ts.tolist(), vals.tolist()))
        inv = quar.list_quarantined(tmp_path)
        assert len(inv) == 1 and inv[0]["block_start"] == START
        assert reg.snapshot()["t.db.corruption_detected"] == 1
        db2.close()

    def test_bootstrap_survives_corrupt_snapshot_fileset(self, tmp_path):
        opts = DatabaseOptions(root=str(tmp_path))
        db1 = Database(opts, {"default": _ns_opts()})
        sid = b"snap-series"
        db1.write_batch("default", [sid], np.array([START + SEC]),
                        np.array([1.0]))
        out = db1.snapshot()
        db1.close()
        snap_root = snap.snapshot_data_root(tmp_path, out["seq"])
        data_files = list(snap_root.rglob("fileset-*-data.db"))
        assert data_files
        _flip(data_files[0])

        db2 = _mkdb(tmp_path)
        db2.bootstrap()  # must not raise
        inv = quar.list_quarantined(tmp_path)
        assert any(e["label"] == f"snapshot-{out['seq']}" for e in inv)
        db2.close()


class TestScrubber:
    def _flushed_db(self, tmp_path, reg=None, ids=(b"sc-0", b"sc-1", b"sc-2")):
        db = _mkdb(tmp_path, reg)
        ts = np.full(len(ids), START + SEC, np.int64)
        db.write_batch("default", list(ids), ts,
                       np.arange(len(ids), dtype=np.float64))
        db.tick(START + BLOCK + _ns_opts().buffer_past_nanos + SEC)
        return db

    def test_budgeted_cursor_resumes_and_wraps(self, tmp_path):
        db = self._flushed_db(tmp_path)  # both shards flushed → 2 volumes
        scr = Scrubber(db, budget_volumes=1)
        r1 = scr.run_once(repair=False)
        r2 = scr.run_once(repair=False)
        assert r1["checked"] == r2["checked"] == 1
        r3 = scr.run_once(repair=False)
        assert r3["wrapped"]  # cursor cycled back to the start
        db.close()

    def test_nonblocking_sweep_skips_when_busy(self, tmp_path):
        """The mediator's wait=False shape: a tick arriving while an
        admin whole-disk scrub holds the sweep lock skips instead of
        stalling the maintenance loop."""
        db = self._flushed_db(tmp_path)
        scr = Scrubber(db)
        assert scr._lock.acquire()  # an in-flight sweep
        try:
            assert scr.run_once(wait=False) == {"skipped": True}
        finally:
            scr._lock.release()
        assert scr.run_once(wait=False)["checked"] >= 1  # lock free again
        db.close()

    def test_finds_quarantines_and_counts(self, tmp_path):
        reg = instrument.new_registry()
        db = self._flushed_db(tmp_path, reg)
        victim_shard = next(
            sh.shard_id for sh in db.namespaces["default"].shards
            if list_filesets(str(tmp_path), "default", sh.shard_id)
        )
        _flip(fileset_path(str(tmp_path), "default", victim_shard, START, 0,
                           "data"))
        scr = Scrubber(db, instrument=reg.scope("t"))
        stats = scr.run_once(budget=0, repair=False)  # full sweep
        assert stats["corrupt"] == 1
        assert len(quar.list_quarantined(tmp_path)) == 1
        snap_ = reg.snapshot()
        assert snap_["t.scrub.volumes_checked"] == stats["checked"] >= 2
        assert snap_["t.scrub.corruptions_found"] == 1
        assert snap_["t.scrub.sweeps"] == 1
        # scrubbing again finds nothing new (volume is gone, not broken)
        assert scr.run_once(budget=0, repair=False)["corrupt"] == 0
        db.close()

    def test_peer_repair_restores_bit_identical_block(self, tmp_path):
        reg = instrument.new_registry()
        ids = [b"pr-%d" % i for i in range(6)]
        dbs = []
        for k in range(2):
            d = _mkdb(tmp_path / f"r{k}", reg if k == 0 else None)
            ts = np.array([START + (i + 1) * SEC for i in range(len(ids))],
                          np.int64)
            d.write_batch("default", ids, ts,
                          np.arange(len(ids), dtype=np.float64))
            d.tick(START + BLOCK + _ns_opts().buffer_past_nanos + SEC)
            dbs.append(d)
        db0, db1 = dbs
        victim_shard = next(
            sh.shard_id for sh in db1.namespaces["default"].shards
            if list_filesets(db1.opts.root, "default", sh.shard_id)
        )
        dpath = lambda db: fileset_path(  # noqa: E731
            db.opts.root, "default", victim_shard, START, 0, "data")
        want_sha = hashlib.sha256(dpath(db0).read_bytes()).hexdigest()
        assert hashlib.sha256(
            dpath(db1).read_bytes()).hexdigest() == want_sha  # replicas equal
        _flip(dpath(db1))

        scr = Scrubber(db1, peers=[db0], instrument=reg.scope("s1"))
        stats = scr.run_once(budget=0)
        assert stats["corrupt"] == 1
        assert stats["repair_attempts"] == 1 and stats["repaired"] == 1
        # bit-identical M3TSZ block bytes restored from the intact peer
        assert hashlib.sha256(
            dpath(db1).read_bytes()).hexdigest() == want_sha
        for i, sid in enumerate(ids):
            got = db1.read("default", sid, START, START + BLOCK)
            assert got == [(START + (i + 1) * SEC, float(i))]
        assert reg.snapshot()["s1.scrub.repairs_completed"] == 1
        # a second sweep: nothing corrupt, nothing to repair
        stats2 = scr.run_once(budget=0)
        assert stats2["corrupt"] == 0 and stats2["repair_attempts"] == 0
        for d in dbs:
            d.close()

    def test_unfillable_hole_attempts_are_capped(self, tmp_path):
        """A hole no replica can fill must stop generating repair RPCs
        after REPAIR_ATTEMPT_CAP passes."""
        db = self._flushed_db(tmp_path / "main")
        peer = _mkdb(tmp_path / "peer")  # never flushed anything
        victim_shard = next(
            sh.shard_id for sh in db.namespaces["default"].shards
            if list_filesets(db.opts.root, "default", sh.shard_id)
        )
        _flip(fileset_path(db.opts.root, "default", victim_shard, START, 0,
                           "data"))
        reg = instrument.new_registry()
        scr = Scrubber(db, peers=[peer], instrument=reg.scope("c"))
        for _ in range(Scrubber.REPAIR_ATTEMPT_CAP + 3):
            scr.run_once(budget=0)
        assert (reg.snapshot()["c.scrub.repair_attempts"]
                == Scrubber.REPAIR_ATTEMPT_CAP)
        # counters intern lazily: a never-incremented counter is absent
        assert reg.snapshot().get("c.scrub.repairs_completed", 0) == 0
        db.close()
        peer.close()

    def test_cleanup_reaps_out_of_retention_quarantine(self, tmp_path):
        """Quarantine evidence ages out with its block's retention so
        the inventory (and /health payload) stays bounded."""
        db = self._flushed_db(tmp_path)
        victim_shard = next(
            sh.shard_id for sh in db.namespaces["default"].shards
            if list_filesets(str(tmp_path), "default", sh.shard_id)
        )
        _flip(fileset_path(str(tmp_path), "default", victim_shard, START, 0,
                           "data"))
        Scrubber(db).run_once(budget=0, repair=False)
        assert len(quar.list_quarantined(tmp_path)) == 1
        still = START + _ns_opts().retention_nanos  # within retention
        assert db.cleanup(still).get("quarantine_reaped", 0) == 0
        assert len(quar.list_quarantined(tmp_path)) == 1
        past = START + _ns_opts().retention_nanos + 2 * BLOCK
        assert db.cleanup(past)["quarantine_reaped"] == 1
        assert quar.list_quarantined(tmp_path) == []
        db.close()

    def test_cleanup_reaps_aged_snapshot_quarantine(self, tmp_path):
        """Entries without a block retention anchor (quarantined
        snapshots) age out on their wall-clock quarantine time — the
        inventory never grows forever."""
        db = _mkdb(tmp_path)
        db.write_batch("default", [b"sq"], np.array([START + SEC]),
                       np.array([1.0]))
        db.snapshot()
        _flip(snap.meta_path(tmp_path, 0), offset=10)
        now = START + 2 * SEC
        db.cleanup(now)  # quarantines the corrupt-meta snapshot
        entries = [e for e in quar.list_quarantined(tmp_path)
                   if e.get("kind") == "snapshot"]
        assert len(entries) == 1
        # a fresh (wall-clock) entry survives further cleanup passes...
        assert db.cleanup(now).get("quarantine_reaped", 0) == 0
        assert any(e.get("kind") == "snapshot"
                   for e in quar.list_quarantined(tmp_path))
        # ...but an ancient one is reaped
        from pathlib import Path
        rf = Path(entries[0]["dir"]) / "reason.json"
        reason = json.loads(rf.read_text())
        reason["quarantined_at"] = 0.0
        rf.write_text(json.dumps(reason))
        stats = db.cleanup(now)
        assert stats["quarantine_reaped"] == 1
        assert not any(e.get("kind") == "snapshot"
                       for e in quar.list_quarantined(tmp_path))
        db.close()

    def test_scrub_without_peers_still_quarantines(self, tmp_path):
        db = self._flushed_db(tmp_path)
        victim_shard = next(
            sh.shard_id for sh in db.namespaces["default"].shards
            if list_filesets(str(tmp_path), "default", sh.shard_id)
        )
        _flip(fileset_path(str(tmp_path), "default", victim_shard, START, 0,
                           "digest"))
        stats = Scrubber(db).run_once(budget=0)  # repair=True, no peers
        assert stats["corrupt"] == 1 and stats["repair_attempts"] == 0
        db.close()

    def test_offline_scrub_root_cli_shape(self, tmp_path):
        _write_fileset(tmp_path, ns="default", shard=0)
        _write_fileset(tmp_path, ns="default", shard=1)
        _flip(fileset_path(tmp_path, "default", 1, START, 0, "data"))
        results = scrub_root(tmp_path)
        bad = [r for r in results if not r["ok"]]
        assert len(bad) == 1 and bad[0]["shard"] == 1
        assert "quarantined" in bad[0]
        assert len(quar.list_quarantined(tmp_path)) == 1
        # the intact volume verifies clean, the corrupt one is gone
        verify_volume(tmp_path, "default", 0, START, 0)
        assert list_fileset_volumes(tmp_path, "default", 1) == []

    def test_cli_scrub_exit_codes(self, tmp_path, capsys):
        from m3_tpu.tools.cli import main

        _write_fileset(tmp_path, ns="default", shard=0)
        assert main(["scrub", str(tmp_path)]) == 0
        _flip(fileset_path(tmp_path, "default", 0, START, 0, "data"))
        assert main(["scrub", str(tmp_path), "--inventory"]) == 1
        lines = [json.loads(ln) for ln in
                 capsys.readouterr().out.strip().splitlines() if ln]
        assert lines[-1]["corrupt"] == 1


class TestMediatorScrubTask:
    def test_scrub_rides_the_maintenance_loop(self, tmp_path):
        from m3_tpu.storage.mediator import Mediator

        db = _mkdb(tmp_path)
        sid = b"med-series"
        db.write_batch("default", [sid], np.array([START + SEC]),
                       np.array([1.0]))
        db.tick(START + BLOCK + _ns_opts().buffer_past_nanos + SEC)
        shard_id = shard_for_id(sid, 2)
        _flip(fileset_path(str(tmp_path), "default", shard_id, START, 0,
                           "data"))
        med = Mediator(db, clock=lambda: START + 2 * BLOCK,
                       scrubber=Scrubber(db, budget_volumes=8),
                       scrub_every=1)
        stats = med.run_once()
        assert stats["scrub"]["corrupt"] == 1
        assert len(quar.list_quarantined(tmp_path)) == 1
        db.close()


class TestSnapshotPruneCorrupt:
    """Satellite: corrupt snapshot metadata must be reaped by cleanup,
    not skipped-and-leaked forever."""

    def test_prune_removes_corrupt_meta_and_dir(self, tmp_path):
        db = _mkdb(tmp_path)
        db.write_batch("default", [b"s1"], np.array([START + SEC]),
                       np.array([1.0]))
        db.snapshot()  # seq 0
        db.write_batch("default", [b"s1"], np.array([START + 2 * SEC]),
                       np.array([2.0]))
        db.snapshot()  # seq 1 (latest)
        _flip(snap.meta_path(tmp_path, 1), offset=10)
        assert snap.latest_snapshot(tmp_path).seq == 0  # corrupt one skipped
        removed = snap.prune_snapshots(tmp_path, keep=1)
        assert removed >= 1
        assert not snap.meta_path(tmp_path, 1).exists()       # meta gone
        assert not snap.snapshot_data_root(tmp_path, 1).exists()  # dir gone
        assert snap.meta_path(tmp_path, 0).exists()           # live one kept
        # gone from the live tree but QUARANTINED, not destroyed — the
        # data filesets may be the only copy of what it covered
        entries = [e for e in quar.list_quarantined(tmp_path)
                   if e.get("kind") == "snapshot"]
        assert len(entries) == 1 and entries[0]["seq"] == 1
        from pathlib import Path
        assert (Path(entries[0]["dir"]) / "1").is_dir()  # data preserved
        db.close()

    def test_database_cleanup_reaps_corrupt_meta(self, tmp_path):
        db = _mkdb(tmp_path)
        db.write_batch("default", [b"s2"], np.array([START + SEC]),
                       np.array([1.0]))
        db.snapshot()
        _flip(snap.meta_path(tmp_path, 0), offset=10)
        stats = db.cleanup(START + 2 * SEC)
        assert stats["snapshots"] >= 1
        assert not snap.meta_path(tmp_path, 0).exists()
        db.close()


class TestCommitlogStreaming:
    """Satellite: the WAL reader streams chunk-by-chunk; the torn-tail
    truncation contract is unchanged and strict mode types the failure."""

    def _log(self, tmp_path, batches=3, per=4):
        w = CommitLogWriter(tmp_path, fsync=FsyncPolicy.EVERY_WRITE)
        want = []
        for b in range(batches):
            ids = [b"cl-%d-%d" % (b, i) for i in range(per)]
            ts = np.arange(per, dtype=np.int64) + b * 100
            vals = np.arange(per, dtype=np.float64) + b
            w.write_batch(ids, ts, vals,
                          annotations=[b"a%d" % i for i in range(per)],
                          namespace=b"nsx")
            want.extend(
                (ids[i], int(ts[i]), float(vals[i])) for i in range(per))
        w.close()
        return list_commitlogs(tmp_path)[0], want

    def test_multichunk_roundtrip(self, tmp_path):
        log, want = self._log(tmp_path)
        got = [(e.series_id, e.timestamp, e.value) for e in read_commitlog(log)]
        assert got == want
        e0 = next(iter(read_commitlog(log)))
        assert e0.namespace == b"nsx" and e0.annotation == b"a0"

    def test_torn_tail_truncates_and_strict_raises(self, tmp_path):
        log, want = self._log(tmp_path)
        raw = log.read_bytes()
        log.write_bytes(raw[:-5])  # torn mid final payload
        got = [(e.series_id, e.timestamp, e.value) for e in read_commitlog(log)]
        assert got == want[:-4]  # last batch dropped whole
        with pytest.raises(ChecksumMismatch) as ei:
            list(read_commitlog(log, strict=True))
        assert ei.value.check == "chunk-payload"

    def test_corrupt_header_truncates_and_strict_raises(self, tmp_path):
        log, want = self._log(tmp_path, batches=2)
        raw = bytearray(log.read_bytes())
        # find the second chunk's header: after hdr(12) + payload
        import struct as _s
        plen = _s.unpack_from("<I", raw, 0)[0]
        off = 12 + plen
        raw[off] ^= 0xFF
        log.write_bytes(bytes(raw))
        got = [e.series_id for e in read_commitlog(log)]
        assert got == [w[0] for w in want[:4]]  # first batch only
        with pytest.raises(ChecksumMismatch) as ei:
            list(read_commitlog(log, strict=True))
        assert ei.value.check == "chunk-header"

    def test_torn_header_strict(self, tmp_path):
        log, _ = self._log(tmp_path, batches=1)
        log.write_bytes(log.read_bytes() + b"\x01\x02")  # 2 stray bytes
        assert len(list(read_commitlog(log))) == 4  # lenient: ignored
        with pytest.raises(FormatCorruption):
            list(read_commitlog(log, strict=True))


class TestHealthAndAdminSurfaces:
    def test_health_exposes_quarantine_inventory(self, tmp_path):
        import urllib.request

        from m3_tpu.server.http_api import ApiContext, serve_background

        db = _mkdb(tmp_path)
        sid = b"h-series"
        db.write_batch("default", [sid], np.array([START + SEC]),
                       np.array([1.0]))
        db.tick(START + BLOCK + _ns_opts().buffer_past_nanos + SEC)
        srv = serve_background(ApiContext(db))
        port = srv.server_address[1]
        try:
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/health", timeout=10) as r:
                out = json.load(r)
            assert out == {"ok": True}  # no noise while clean
            shard_id = shard_for_id(sid, 2)
            _flip(fileset_path(str(tmp_path), "default", shard_id, START, 0,
                               "data"))
            Scrubber(db).run_once(budget=0, repair=False)
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/health", timeout=10) as r:
                out = json.load(r)
            assert out["ok"] and out["quarantine"]["entries"] == 1
            item = out["quarantine"]["items"][0]
            assert item["shard"] == shard_id and item["block_start"] == START
        finally:
            srv.shutdown()
            srv.server_close()
            db.close()

    def test_admin_scrub_endpoint(self, tmp_path):
        import urllib.request

        from m3_tpu.cluster.kv import KVStore
        from m3_tpu.server.admin_api import (
            AdminContext, serve_admin_background,
        )

        db = _mkdb(tmp_path / "data")
        sid = b"adm-series"
        db.write_batch("default", [sid], np.array([START + SEC]),
                       np.array([1.0]))
        db.tick(START + BLOCK + _ns_opts().buffer_past_nanos + SEC)
        _flip(fileset_path(db.opts.root, "default", shard_for_id(sid, 2),
                           START, 0, "data"))
        ctx = AdminContext(KVStore(str(tmp_path / "kv")), db,
                           scrubber=Scrubber(db))
        srv = serve_admin_background(ctx)
        port = srv.server_address[1]
        try:
            req = urllib.request.Request(
                f"http://127.0.0.1:{port}/api/v1/database/scrub",
                data=b"{}", headers={"Content-Type": "application/json"},
            )
            with urllib.request.urlopen(req, timeout=30) as r:
                out = json.load(r)
            assert out["scrub"]["corrupt"] == 1
            assert len(quar.list_quarantined(db.opts.root)) == 1
        finally:
            srv.shutdown()
            srv.server_close()
            db.close()
