"""Race-regression tier: threaded stress over the host paths.

The reference runs its unit/property tiers under Go's -race and keeps
dedicated race-regression tests (storage/shard_race_prop_test.go,
series_parallel_test.go) plus TLA+ specs for the flush/tick concurrency
design (specs/dbnode/flush/FlushVersion.tla).  CPython has no -race;
this tier is the executable analogue: concurrent writers against the
maintenance tick, cache readers against invalidation, KV watchers
against setters — each asserting the CONSERVATION invariants the specs
encode (no sample lost, no sample duplicated, no torn state), not just
"no exception"."""

import threading
import time

import numpy as np
import pytest

from m3_tpu.storage.block_cache import BlockCache
from m3_tpu.storage.database import Database, DatabaseOptions, NamespaceOptions

SEC = 10**9
BLOCK = 2 * 3600 * 10**9
START = (1_700_000_000 * 10**9) // BLOCK * BLOCK


def _run_threads(fns, timeout=300):
    errs = []

    def wrap(fn):
        def run():
            try:
                fn()
            except Exception as e:  # noqa: BLE001 — collected for assert
                errs.append(e)
        return run

    ts = [threading.Thread(target=wrap(f)) for f in fns]
    for t in ts:
        t.start()
    for t in ts:
        t.join(timeout)
        assert not t.is_alive(), "thread wedged (deadlock?)"
    assert errs == [], errs


class TestFlushTickVsWriters:
    """The FlushVersion.tla role: warm flush racing ingest must neither
    lose nor duplicate samples, and every sample is readable afterwards
    from exactly one place (buffer or fileset)."""

    def test_concurrent_writers_and_ticks_conserve_samples(self, tmp_path):
        db = Database(
            DatabaseOptions(root=str(tmp_path), commitlog_enabled=False),
            {"default": NamespaceOptions(num_shards=2, slot_capacity=1 << 10,
                                         sample_capacity=1 << 14)},
        )
        W = 3            # writer threads
        ROUNDS = 12      # batches per writer
        N = 16           # series per writer
        written = {}     # (sid -> [(ts, val)])  appended pre-write
        lock = threading.Lock()
        # Start 4 minutes before a block boundary: the ticker's clock
        # walks across it and then past the warm window, so the first
        # block SEALS AND FLUSHES while writers are mid-stream.  Steps
        # stay far under bufferPast (10m): a writer's timestamp can lag
        # the clock by at most one in-flight bump (the ticker is itself
        # serialized behind db._mu), so no sample ever falls out of the
        # warm window — every "missing" point is a real race loss, not
        # a bufferPast policy drop.
        clock = [START + BLOCK - 4 * 60 * SEC]

        def writer(w):
            def run():
                for r in range(ROUNDS):
                    now = clock[0]
                    ids = [b"race-%d-%d" % (w, j) for j in range(N)]
                    t = np.full(N, now + w, np.int64)
                    v = np.full(N, float(r + 1))
                    with lock:
                        for sid, tt, vv in zip(ids, t, v):
                            written.setdefault(sid, []).append((int(tt), vv))
                    db.write_batch("default", ids, t, v)
                    time.sleep(0.001)
            return run

        def ticker():
            for k in range(7):
                time.sleep(0.01)
                clock[0] += 2 * 60 * SEC
                db.tick(clock[0])

        _run_threads([writer(w) for w in range(W)] + [ticker])
        # Final tick far in the future: everything flushed or readable.
        db.tick(clock[0] + BLOCK)
        lost = dupes = 0
        for sid, pts in written.items():
            want = {}
            for tt, vv in pts:   # same (sid, ts) overwrites: last wins
                want[tt] = vv
            got = db.read("default", sid, START, clock[0] + 2 * BLOCK)
            got_ts = [t for t, _ in got]
            if len(got_ts) != len(set(got_ts)):
                dupes += 1
            if set(got_ts) != set(want):
                lost += 1
        assert lost == 0 and dupes == 0
        db.close()


class TestBlockCacheRaces:
    def test_readers_vs_invalidation(self, tmp_path):
        """Concurrent read_series + invalidate/clear: the single-flight
        and eviction paths must never deadlock, poison a read, or leak
        an inflight marker."""
        db = Database(
            DatabaseOptions(root=str(tmp_path), commitlog_enabled=False),
            {"default": NamespaceOptions(num_shards=1, slot_capacity=256,
                                         sample_capacity=1 << 12)},
        )
        ids = [b"bc-%d" % i for i in range(8)]
        t = np.full(8, START + SEC, np.int64)
        db.write_batch("default", ids, t, np.arange(8.0))
        db.tick(START + BLOCK + NamespaceOptions().buffer_past_nanos + SEC)
        cache: BlockCache = db.block_cache

        stop = threading.Event()
        reads = [0]

        def reader():
            while not stop.is_set():
                for sid in ids:
                    pts = db.read("default", sid, START, START + BLOCK)
                    assert len(pts) == 1
                    reads[0] += 1

        def invalidator():
            for _ in range(60):
                cache.invalidate_block("default", 0, START)
                cache.clear()
                time.sleep(0.002)
            stop.set()

        _run_threads([reader, reader, invalidator])
        assert reads[0] > 0
        assert not cache._inflight  # no leaked single-flight markers
        db.close()


class TestKVWatchRaces:
    def test_watchers_vs_setters_converge(self, tmp_path):
        from m3_tpu.cluster.kv import KVStore

        kv = KVStore(str(tmp_path))
        seen = []
        seen_lock = threading.Lock()

        def watcher_registrar():
            for _ in range(40):
                def cb(v, out=[]):
                    with seen_lock:
                        seen.append(v.version)
                kv.watch("k", cb)
                time.sleep(0.001)

        def setter():
            for i in range(80):
                kv.set("k", b"v%d" % i)

        _run_threads([watcher_registrar, setter, setter])
        final = kv.get("k").version
        assert final == 160
        # Late-registered watchers fired with then-current versions;
        # every observed version must be one that actually existed.
        assert all(1 <= v <= final for v in seen)

    def test_remote_kv_watch_no_lost_final_version(self, tmp_path):
        """The poll-loop + registration race (advisor round-4 finding):
        under concurrent set/watch the last version is always delivered
        to every watcher."""
        import threading as _th

        from m3_tpu.cluster.kv_remote import KVServer, RemoteKVStore

        srv = KVServer(root=str(tmp_path))
        _th.Thread(target=srv.serve_forever, daemon=True).start()
        try:
            kv = RemoteKVStore(("127.0.0.1", srv.port), watch_poll_s=0.02)
            got = {}

            def mk(i):
                def cb(v):
                    got[i] = v.version
                return cb

            def registrar(base):
                for i in range(10):
                    kv.watch("wk", mk(base + i))

            def setter():
                for i in range(30):
                    kv.set("wk", b"x%d" % i)

            _run_threads([lambda: registrar(0), lambda: registrar(100),
                          setter])
            final = kv.get("wk").version
            deadline = time.monotonic() + 5
            while time.monotonic() < deadline:
                if len(got) == 20 and all(v == final for v in got.values()):
                    break
                time.sleep(0.02)
            assert len(got) == 20
            assert all(v == final for v in got.values()), got
            kv.close()
        finally:
            srv.shutdown()
