"""Instrument package unit tier: registry interning, collector
isolation, strict exposition validity, Timer reservoir bounds + the
lifetime-bias staleness regression, Histogram merge/window semantics,
and cross-process trace context propagation.

Previously the instrument substrate was only covered transitively
(through server/dtest scenarios); round 10 makes it a first-class unit
surface because /health SLOs and the dtest artifacts now read straight
off Histogram state.
"""

import math

import pytest

from m3_tpu import instrument
from m3_tpu.instrument import (
    HISTOGRAM_BOUNDS, Histogram, Timer, exposition, new_registry,
    quantile_from_buckets,
)
from m3_tpu.instrument import tracing as tracing_bind
from m3_tpu.instrument.tracing import (
    NOOP_SPAN, TraceContext, Tracepoint, Tracer, join_traces,
)


class FakeClock:
    def __init__(self, t=1000.0):
        self.t = t

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt


class TestRegistryInterning:
    def test_same_name_tags_same_instrument(self):
        reg = new_registry()
        a = reg.scope("db").counter("writes")
        b = reg.scope("db").counter("writes")
        assert a is b
        a.inc(2)
        assert b.value == 2

    def test_tag_order_does_not_matter(self):
        reg = new_registry()
        a = reg.scope("s", {"x": "1", "y": "2"}).gauge("g")
        b = reg.scope("s", {"y": "2", "x": "1"}).gauge("g")
        assert a is b

    def test_distinct_tags_distinct_instruments(self):
        reg = new_registry()
        a = reg.scope("s", {"x": "1"}).counter("c")
        b = reg.scope("s", {"x": "2"}).counter("c")
        assert a is not b

    def test_subscope_and_tagged_compose(self):
        reg = new_registry()
        h1 = reg.scope("a").subscope("b").histogram("h")
        h2 = reg.scope("a.b").histogram("h")
        assert h1 is h2
        t1 = reg.scope("a", {"k": "v"}).tagged({"k2": "v2"}).timer("t")
        t2 = reg.scope("a", {"k2": "v2", "k": "v"}).timer("t")
        assert t1 is t2


class TestCollectorIsolation:
    def test_raising_collector_never_poisons_the_scrape(self):
        reg = new_registry()
        reg.scope("x").counter("c").inc()
        calls = []

        def bad():
            calls.append("bad")
            raise RuntimeError("collector exploded")

        def good():
            calls.append("good")
            reg.scope("x").gauge("g").update(7)

        reg.register_collector(bad)
        reg.register_collector(good)
        snap = reg.snapshot()
        assert snap["x.c"] == 1
        assert snap["x.g"] == 7.0  # collector after the raiser still ran
        assert calls == ["bad", "good"]
        # and the raiser is retried on the next scrape, not dropped
        reg.render_prometheus()
        assert calls == ["bad", "good", "bad", "good"]

    def test_unregister(self):
        reg = new_registry()
        fn = lambda: reg.scope("x").gauge("g").update(1)
        reg.register_collector(fn)
        reg.snapshot()
        reg.unregister_collector(fn)
        reg.scope("x").gauge("g").update(0)
        assert reg.snapshot()["x.g"] == 0.0


class TestTimer:
    def test_reservoir_bounded(self):
        t = Timer(reservoir=64)
        for i in range(10_000):
            t.record(i / 1000.0)
        assert len(t._reservoir) == 64  # bounded memory
        s = t.summary()
        assert s["count"] == 10_000
        assert s["max"] == pytest.approx(9.999)
        assert 0 <= s["p50"] <= s["p95"] <= s["p99"] <= s["max"]

    def test_lifetime_bias_is_the_documented_semantics(self):
        """Timer's reservoir is uniform over the LIFETIME: after a
        burst of slow samples followed by many fast ones, the summary
        still reflects the burst (max never decays) — why hot paths
        moved to Histogram."""
        t = Timer(reservoir=128)
        for _ in range(100):
            t.record(5.0)  # the burst
        for _ in range(100):
            t.record(0.001)  # back to fast
        s = t.summary()
        assert s["max"] == 5.0  # never decays
        assert s["p99"] == 5.0  # burst still dominates the tail


class TestHistogram:
    def test_bounds_are_log2_and_fixed(self):
        assert len(HISTOGRAM_BOUNDS) == 31
        for lo, hi in zip(HISTOGRAM_BOUNDS, HISTOGRAM_BOUNDS[1:]):
            assert hi == 2 * lo

    def test_merge_is_exact_bucket_sum(self):
        """The acceptance property: two nodes' histograms merge to the
        exact vector sum of their buckets (shared fixed bounds)."""
        import random

        rng = random.Random(7)
        a, b, both = Histogram(), Histogram(), Histogram()
        for _ in range(2000):
            v = rng.lognormvariate(-4, 2)
            a.record(v)
            both.record(v)
        for _ in range(3000):
            v = rng.lognormvariate(-2, 1)
            b.record(v)
            both.record(v)
        sa, sb, sboth = a.state(), b.state(), both.state()
        merged = [x + y for x, y in zip(sa["buckets"], sb["buckets"])]
        assert merged == sboth["buckets"]
        assert sa["count"] + sb["count"] == sboth["count"]
        assert sa["sum"] + sb["sum"] == pytest.approx(sboth["sum"])
        # merged quantiles == quantiles of the union stream's histogram
        for q in (0.5, 0.95, 0.99):
            assert quantile_from_buckets(merged, q) == pytest.approx(
                quantile_from_buckets(sboth["buckets"], q))

    def test_quantile_within_bucket_resolution(self):
        h = Histogram()
        for _ in range(1000):
            h.record(0.010)  # lands in the (2^-7, 2^-6] lane
        s = h.summary()
        # log-2 lanes: estimate within a factor of 2 of the true value
        assert 0.005 <= s["p50"] <= 0.020
        assert 0.005 <= s["p99"] <= 0.020

    def test_windowed_summary_decays_timer_does_not(self):
        """The staleness regression the ISSUE pins: after a burst ages
        past two windows, Histogram p99 reflects CURRENT traffic while
        Timer still reports the burst."""
        clock = FakeClock()
        h = Histogram(window_s=60.0, clock=clock)
        t = Timer()
        for _ in range(100):
            h.record(5.0)
            t.record(5.0)
        assert h.summary()["p99"] > 2.0  # burst visible now
        clock.advance(150.0)  # > 2 windows: the burst ages out entirely
        for _ in range(100):
            h.record(0.001)
            t.record(0.001)
        hs, ts = h.summary(), t.summary()
        assert hs["p99"] < 0.01, hs     # histogram: current traffic
        assert hs["max"] < 0.01, hs     # windowed max decayed too
        assert ts["p99"] == 5.0         # timer: stale burst forever
        assert ts["max"] == 5.0
        # cumulative lanes still carry everything (Prometheus counters)
        assert hs["count"] == 200

    def test_idle_gap_between_one_and_two_windows(self):
        clock = FakeClock()
        h = Histogram(window_s=60.0, clock=clock)
        h.record(1.0)
        clock.advance(90.0)  # 1-2 windows: previous window still counts
        assert h.summary()["window_count"] == 1
        clock.advance(60.0)
        assert h.summary()["window_count"] == 0


class TestExposition:
    def _render(self):
        reg = new_registry()
        s = reg.scope("m3tpu")
        s.counter("writes").inc(3)
        s.gauge("depth").update(2.5)
        s.timer("tick_seconds").record(0.5)
        s.tagged({"phase": "fetch"}).histogram("query_seconds").record(0.02)
        s.histogram("ingest_seconds").record(0.001)
        return reg.render_prometheus()

    def test_registry_output_parses_strict(self):
        samples = exposition.parse_text(self._render())
        names = {s.name for s in samples}
        assert "m3tpu_writes" in names
        assert "m3tpu_ingest_seconds_bucket" in names
        assert "m3tpu_ingest_seconds_count" in names

    def test_histogram_lanes_cumulative_and_inf_terminated(self):
        samples = exposition.parse_text(self._render())
        lanes = exposition.histogram_series(samples, "m3tpu_ingest_seconds")
        (lemap,) = lanes.values()
        les = sorted(lemap)
        assert math.isinf(les[-1])
        cums = [lemap[le] for le in les]
        assert cums == sorted(cums)

    def test_label_escaping_round_trips(self):
        reg = new_registry()
        reg.scope("s", {"q": 'a"b\\c\nd'}).counter("c").inc()
        samples = exposition.parse_text(reg.render_prometheus())
        assert samples[0].label("q") == 'a"b\\c\nd'

    def test_backslash_n_sequence_round_trips(self):
        """Review regression: a literal backslash followed by 'n'
        ('C:\\network') must survive escape→parse — sequential
        str.replace unescaping cut a newline into the middle of it."""
        reg = new_registry()
        reg.scope("s", {"p": "C:\\network", "q": "\\\\host\\n"}).counter(
            "c").inc()
        samples = exposition.parse_text(reg.render_prometheus())
        assert samples[0].label("p") == "C:\\network"
        assert samples[0].label("q") == "\\\\host\\n"

    @pytest.mark.parametrize("bad", [
        "1metric 2\n",                       # name starts with digit
        "metric  \n",                        # no value
        'metric{l="v} 1\n',                  # unterminated label value
        "metric 1\nmetric 1\n",              # duplicate series
        'h_bucket{le="0.5"} 5\nh_bucket{le="1.0"} 3\n'
        'h_bucket{le="+Inf"} 5\n',           # decreasing cumulative
        'h_bucket{le="0.5"} 5\n',            # no +Inf lane
        'h_bucket{le="+Inf"} 5\nh_count 4\n',  # +Inf != _count
        "metric 1 \n",                       # trailing whitespace
    ])
    def test_strict_parser_rejects(self, bad):
        with pytest.raises(exposition.ExpositionError):
            exposition.parse_text(bad)

    def test_merged_quantile_across_scrapes(self):
        regs = [new_registry() for _ in range(2)]
        for i, reg in enumerate(regs):
            h = reg.scope("node").histogram("lat_seconds")
            for _ in range(100):
                h.record(0.001 if i == 0 else 1.0)
        scrapes = [exposition.parse_text(r.render_prometheus())
                   for r in regs]
        merged = exposition.merge_histograms(scrapes, "node_lat_seconds")
        p50 = exposition.merged_quantile(merged, 0.50)
        p99 = exposition.merged_quantile(merged, 0.99)
        assert 0.0005 <= p50 <= 1.5
        assert 0.5 <= p99 <= 1.5  # the slow node's lane dominates p99


class TestFleetSummaryPartial:
    """Round-12 satellite: the fleet merge under partial scrape failure.
    The soak scrapes at phase boundaries INCLUDING mid-SIGKILL windows,
    so one-node-of-three-unreachable must yield a merged summary
    honestly flagged ``partial`` over the reachable majority — never an
    exception and never silently-wrong quantiles."""

    def _scrape(self, value_s, n=100):
        reg = new_registry()
        h = reg.scope("node").histogram("lat_seconds")
        for _ in range(n):
            h.record(value_s)
        return exposition.parse_text(reg.render_prometheus())

    def test_one_of_three_unreachable_flags_partial(self):
        scrapes = {0: self._scrape(0.001), 1: self._scrape(0.001), 2: None}
        out = exposition.fleet_summary(scrapes, "node_lat_seconds")
        assert out["partial"] is True
        assert out["unreachable"] == [2]
        assert out["reachable"] == [0, 1]
        assert out["count"] == 200  # the reachable majority, fully merged
        assert out["quantiles"]["p99"] is not None
        assert out["quantiles"]["p99"] < 0.1  # not polluted by a guess

    def test_all_unreachable_yields_empty_not_exception(self):
        out = exposition.fleet_summary({0: None, 1: None}, "node_lat")
        assert out["partial"] and out["count"] == 0
        assert out["quantiles"]["p50"] is None

    def test_phase_delta_subtracts_the_before_scrape(self):
        reg = new_registry()
        h = reg.scope("node").histogram("lat_seconds")
        for _ in range(50):
            h.record(0.001)
        before = exposition.parse_text(reg.render_prometheus())
        for _ in range(25):
            h.record(0.001)
        after = exposition.parse_text(reg.render_prometheus())
        out = exposition.fleet_summary({0: after}, "node_lat_seconds",
                                       before={0: before})
        assert out["count"] == 25  # just the window, not the lifetime

    def test_restart_between_scrapes_is_detected_not_negative(self):
        before = self._scrape(0.001, n=100)
        after = self._scrape(0.001, n=10)  # fresh process: counters reset
        out = exposition.fleet_summary({0: after}, "node_lat_seconds",
                                       before={0: before})
        assert out["resets"] == [0]
        assert out["count"] == 10  # the new process's absolute counts

    def test_node_missing_from_before_is_a_full_delta(self):
        # a node that JOINED mid-phase (the rolling-replace spare)
        out = exposition.fleet_summary(
            {0: self._scrape(0.001)}, "node_lat_seconds", before={})
        assert out["count"] == 100 and not out["resets"]

    def test_counter_value_sums_and_tolerates_none(self):
        reg = new_registry()
        reg.scope("s", {"k": "a"}).counter("c").inc(3)
        reg.scope("s", {"k": "b"}).counter("c").inc(4)
        samples = exposition.parse_text(reg.render_prometheus())
        assert exposition.counter_value(samples, "s_c") == 7
        assert exposition.counter_value(samples, "s_c", {"k": "a"}) == 3
        assert exposition.counter_value(None, "s_c") == 0.0
        assert exposition.counter_value(samples, "absent") == 0.0


class TestTraceContext:
    def test_wire_roundtrip(self):
        ctx = TraceContext(trace_id=2**63 + 5, span_id=42, sampled=True)
        assert TraceContext.from_wire(ctx.to_wire()) == ctx
        assert len(ctx.to_wire()) == TraceContext.WIRE_SIZE == 17
        unsampled = TraceContext(1, 2, sampled=False)
        assert not TraceContext.from_wire(unsampled.to_wire()).sampled

    def test_active_span_binds_context(self):
        tr = Tracer()
        assert tracing_bind.current() is None
        with tr.start_span("outer") as sp:
            ctx = tracing_bind.current()
            assert ctx.trace_id == sp.span.trace_id
            assert ctx.span_id == sp.span.span_id
            assert tracing_bind.current_wire() == ctx.to_wire()
        assert tracing_bind.current() is None

    def test_remote_context_parents_local_spans(self):
        upstream = Tracer()
        with upstream.start_span("api.write") as root:
            wire = tracing_bind.current_wire()
        downstream = Tracer()
        with tracing_bind.bind(TraceContext.from_wire(wire)):
            with downstream.start_span(Tracepoint.RPC_SERVER):
                with downstream.start_span(Tracepoint.DB_WRITE_BATCH):
                    pass
        spans = {s.name: s for s in downstream.finished()}
        assert spans["rpc.server"].trace_id == root.span.trace_id
        assert spans["rpc.server"].parent_id == root.span.span_id
        assert spans["db.writeBatch"].parent_id == spans["rpc.server"].span_id

    def test_unsampled_context_produces_no_spans_and_no_wire(self):
        tr = Tracer()
        with tracing_bind.bind(TraceContext(1, 2, sampled=False)):
            assert tracing_bind.current_wire() == b""
            span = tr.start_span("x")
            assert span is NOOP_SPAN
        assert tr.finished() == []

    def test_sample_rate_zero_records_nothing(self):
        tr = Tracer(sample_rate=0.0)
        with tr.start_span("root"):
            pass
        assert tr.finished() == []

    def test_unsampled_root_suppresses_descendants(self):
        """Review regression: a root that loses the sampling roll must
        bind its NEGATIVE decision — otherwise every in-process child
        re-rolls as a fresh root, littering the ring with unparented
        fragment traces and inflating the effective sample rate."""
        tr = Tracer(sample_rate=0.0)
        with tr.start_span("api.write"):
            # descendants on the same thread inherit "not sampled"
            ctx = tracing_bind.current()
            assert ctx is not None and not ctx.sampled
            assert tracing_bind.current_wire() == b""
            with tr.start_span("child"):
                with tr.start_span("grandchild"):
                    pass
        assert tr.finished() == []
        assert tracing_bind.current() is None  # binding restored

    def test_join_traces_orders_parent_first(self):
        tr = Tracer()
        with tr.start_span("a"):
            with tr.start_span("b"):
                with tr.start_span("c"):
                    pass
        rows = [s.to_dict() for s in tr.finished()]
        (trace,) = join_traces(rows).values()
        assert [s["name"] for s in trace] == ["a", "b", "c"]

    def test_inventory(self):
        tr = Tracer()
        with tr.start_span("a"):
            with tr.start_span("b"):
                pass
        with tr.start_span("other"):
            pass
        inv = tr.inventory()
        assert len(inv) == 2
        by_spans = sorted(inv, key=lambda r: r["spans"])
        assert by_spans[-1]["spans"] == 2
        assert set(by_spans[-1]["names"]) == {"a", "b"}
