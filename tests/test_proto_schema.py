"""Descriptor-driven proto codec: real protobuf schemas with nested
messages compress through the columnar engine and the schema rides a
FileDescriptorSet annotation (reference `src/dbnode/encoding/proto`
encoder.go descriptor parsing + schema annotations)."""

import pytest

from m3_tpu.encoding.proto_codec import (
    FieldKind,
    ProtoDecoder,
    ProtoEncoder,
)
from m3_tpu.encoding.proto_schema import (
    UnsupportedFieldError,
    columns_to_message,
    descriptor_from_annotation,
    message_class_for,
    message_to_columns,
    pack_schema_annotation,
    schema_from_descriptor,
    unpack_schema_annotation,
)

START = 1_600_000_000 * 10**9


def _build_pool():
    """A realistic message with a nested sub-message, built
    programmatically (no protoc run needed): the VehicleLocation shape
    the reference's proto tests use, plus nesting."""
    from google.protobuf import descriptor_pb2, descriptor_pool

    f = descriptor_pb2.FileDescriptorProto()
    f.name = "telemetry.proto"
    f.package = "m3test"
    f.syntax = "proto3"

    inner = f.message_type.add()
    inner.name = "Position"
    for i, (name, t) in enumerate(
        [("latitude", "TYPE_DOUBLE"), ("longitude", "TYPE_DOUBLE")], 1
    ):
        fd = inner.field.add()
        fd.name, fd.number = name, i
        fd.type = getattr(descriptor_pb2.FieldDescriptorProto, t)
        fd.label = descriptor_pb2.FieldDescriptorProto.LABEL_OPTIONAL

    outer = f.message_type.add()
    outer.name = "VehicleUpdate"
    specs = [
        ("fuel_percent", "TYPE_DOUBLE", None),
        ("odometer", "TYPE_INT64", None),
        ("status", "TYPE_STRING", None),
        ("moving", "TYPE_BOOL", None),
        ("position", "TYPE_MESSAGE", ".m3test.Position"),
    ]
    for i, (name, t, tn) in enumerate(specs, 1):
        fd = outer.field.add()
        fd.name, fd.number = name, i
        fd.type = getattr(descriptor_pb2.FieldDescriptorProto, t)
        fd.label = descriptor_pb2.FieldDescriptorProto.LABEL_OPTIONAL
        if tn:
            fd.type_name = tn

    pool = descriptor_pool.DescriptorPool()
    pool.Add(f)
    fds = descriptor_pb2.FileDescriptorSet()
    fds.file.add().CopyFrom(f)
    return pool, fds.SerializeToString()


class TestDescriptorSchema:
    def test_nested_flattening(self):
        pool, _ = _build_pool()
        desc = pool.FindMessageTypeByName("m3test.VehicleUpdate")
        schema = schema_from_descriptor(desc)
        assert schema.fields == (
            ("fuel_percent", FieldKind.FLOAT),
            ("odometer", FieldKind.INT),
            ("status", FieldKind.BYTES),
            ("moving", FieldKind.BOOL),
            ("position.latitude", FieldKind.FLOAT),
            ("position.longitude", FieldKind.FLOAT),
        )

    def test_repeated_map_oneof_ride_opaque_columns(self):
        """Round-4: repeated fields, maps and oneofs serialize to
        opaque wire-bytes columns (the reference's remaining-fields
        marshal role) and round-trip exactly, including which-oneof
        state."""
        from google.protobuf import descriptor_pb2, descriptor_pool

        f = descriptor_pb2.FileDescriptorProto()
        f.name = "rep.proto"
        f.package = "m3test2"
        f.syntax = "proto3"
        m = f.message_type.add()
        m.name = "Fancy"
        FD = descriptor_pb2.FieldDescriptorProto
        fd = m.field.add()
        fd.name, fd.number = "xs", 1
        fd.type, fd.label = FD.TYPE_INT64, FD.LABEL_REPEATED
        # map<string, int64> counts = 2;  (a nested MapEntry message)
        entry = m.nested_type.add()
        entry.name = "CountsEntry"
        entry.options.map_entry = True
        k = entry.field.add()
        k.name, k.number, k.type, k.label = "key", 1, FD.TYPE_STRING, FD.LABEL_OPTIONAL
        v = entry.field.add()
        v.name, v.number, v.type, v.label = "value", 2, FD.TYPE_INT64, FD.LABEL_OPTIONAL
        fd2 = m.field.add()
        fd2.name, fd2.number = "counts", 2
        fd2.type, fd2.label = FD.TYPE_MESSAGE, FD.LABEL_REPEATED
        fd2.type_name = ".m3test2.Fancy.CountsEntry"
        # oneof choice { int64 a = 3; string b = 4; }
        oo = m.oneof_decl.add()
        oo.name = "choice"
        for i, (nm, t) in enumerate((("a", FD.TYPE_INT64),
                                     ("b", FD.TYPE_STRING)), 3):
            fdo = m.field.add()
            fdo.name, fdo.number, fdo.type = nm, i, t
            fdo.label = FD.LABEL_OPTIONAL
            fdo.oneof_index = 0
        pool = descriptor_pool.DescriptorPool()
        pool.Add(f)
        desc = pool.FindMessageTypeByName("m3test2.Fancy")

        schema = schema_from_descriptor(desc)
        names = [n for n, _ in schema.fields]
        assert "xs" in names and "counts" in names
        assert "__oneof__.choice" in names
        kinds = dict(schema.fields)
        assert kinds["xs"] == FieldKind.BYTES
        assert kinds["counts"] == FieldKind.BYTES

        cls = message_class_for(desc)
        msg = cls()
        msg.xs.extend([5, -2, 7])
        msg.counts["api"] = 3
        msg.counts["db"] = 9
        msg.b = "branch-b"
        cols = message_to_columns(msg)
        out = columns_to_message(cls(), cols)
        assert list(out.xs) == [5, -2, 7]
        assert dict(out.counts) == {"api": 3, "db": 9}
        assert out.WhichOneof("choice") == "b" and out.b == "branch-b"
        # unset oneof round-trips as unset
        empty = columns_to_message(cls(), message_to_columns(cls()))
        assert empty.WhichOneof("choice") is None
        # deterministic: equal states serialize to equal column bytes
        msg2 = cls()
        msg2.counts["db"] = 9
        msg2.counts["api"] = 3
        assert message_to_columns(msg2)["counts"] == cols["counts"]

    def test_roundtrip_real_messages_through_codec(self):
        pool, fds_bytes = _build_pool()
        desc = pool.FindMessageTypeByName("m3test.VehicleUpdate")
        cls = message_class_for(desc)
        schema = schema_from_descriptor(desc)

        msgs = []
        for k in range(40):
            m = cls()
            m.fuel_percent = 75.0 - k * 0.25
            m.odometer = 100_000 + k * 7
            m.status = "cruising" if k % 5 else "stopped"
            m.moving = bool(k % 5)
            m.position.latitude = 47.6 + k * 1e-4
            m.position.longitude = -122.3 - k * 1e-4
            msgs.append(m)

        enc = ProtoEncoder(schema, START)
        for k, m in enumerate(msgs):
            enc.encode(START + (k + 1) * 10**9, message_to_columns(m))
        blob = enc.stream()

        dec = ProtoDecoder(schema, blob)
        out = list(dec)
        assert len(out) == 40
        for k, (ts, cols) in enumerate(out):
            assert ts == START + (k + 1) * 10**9
            back = columns_to_message(cls(), cols)
            assert back == msgs[k]

    def test_schema_annotation_roundtrip(self):
        pool, fds_bytes = _build_pool()
        ann = pack_schema_annotation(fds_bytes, "m3test.VehicleUpdate")
        fds2, name = unpack_schema_annotation(ann)
        assert name == "m3test.VehicleUpdate" and fds2 == fds_bytes
        assert unpack_schema_annotation(b"not a schema") is None
        # decode side: a fresh pool rebuilds the descriptor and class
        desc = descriptor_from_annotation(ann)
        schema = schema_from_descriptor(desc)
        assert schema.fields[0] == ("fuel_percent", FieldKind.FLOAT)
        cls = message_class_for(desc)
        m = cls()
        m.odometer = 5
        assert message_to_columns(m)["odometer"] == 5

    def test_schema_annotation_rides_m3tsz_device_encoder(self):
        """The schema annotation travels as the first-datapoint M3TSZ
        annotation on the batched device encoder and comes back through
        the scalar decoder on a node that has never seen the schema."""
        import numpy as np

        from m3_tpu.encoding.m3tsz import decode_series
        from m3_tpu.encoding.m3tsz_jax import encode_batch

        _, fds_bytes = _build_pool()
        ann = pack_schema_annotation(fds_bytes, "m3test.VehicleUpdate")
        T = 10
        ts = np.tile(START + np.arange(1, T + 1) * 10**9, (1, 1)).astype(np.int64)
        vals = np.round(np.arange(T, dtype=np.float64)[None, :] * 0.5, 1)
        streams, fb = encode_batch(ts, vals, np.full(1, START, np.int64),
                                   out_words=200, annotations=[ann])
        assert not fb.any()
        pts = decode_series(streams[0])
        desc = descriptor_from_annotation(pts[0].annotation)
        assert desc.full_name == "m3test.VehicleUpdate"


class TestProto3OptionalAndMessageMaps:
    def test_proto3_optional_keeps_native_column(self):
        """Synthetic single-field oneofs (proto3 `optional`) must not
        become opaque blobs — the scalar rides its native column."""
        from google.protobuf import descriptor_pb2, descriptor_pool

        f = descriptor_pb2.FileDescriptorProto()
        f.name = "opt.proto"
        f.package = "m3opt"
        f.syntax = "proto3"
        m = f.message_type.add()
        m.name = "M"
        FD = descriptor_pb2.FieldDescriptorProto
        fd = m.field.add()
        fd.name, fd.number, fd.type = "maybe", 1, FD.TYPE_INT64
        fd.label = FD.LABEL_OPTIONAL
        fd.proto3_optional = True
        oo = m.oneof_decl.add()
        oo.name = "_maybe"
        fd.oneof_index = 0
        pool = descriptor_pool.DescriptorPool()
        pool.Add(f)
        desc = pool.FindMessageTypeByName("m3opt.M")
        schema = schema_from_descriptor(desc)
        assert schema.fields == (("maybe", FieldKind.INT),
                                 ("maybe@set", FieldKind.BOOL))
        cls = message_class_for(desc)
        msg = cls()
        msg.maybe = 42
        out = columns_to_message(cls(), message_to_columns(msg))
        assert out.maybe == 42 and out.HasField("maybe")
        # explicit default is SET; untouched is UNSET - presence survives
        z = cls()
        z.maybe = 0
        rz = columns_to_message(cls(), message_to_columns(z))
        assert rz.HasField("maybe") and rz.maybe == 0
        ru = columns_to_message(cls(), message_to_columns(cls()))
        assert not ru.HasField("maybe")

    def test_message_valued_map_roundtrips(self):
        from google.protobuf import descriptor_pb2, descriptor_pool

        f = descriptor_pb2.FileDescriptorProto()
        f.name = "mm.proto"
        f.package = "m3mm"
        f.syntax = "proto3"
        sub = f.message_type.add()
        sub.name = "Sub"
        FD = descriptor_pb2.FieldDescriptorProto
        sf = sub.field.add()
        sf.name, sf.number, sf.type, sf.label = "x", 1, FD.TYPE_INT64, FD.LABEL_OPTIONAL
        m = f.message_type.add()
        m.name = "M"
        entry = m.nested_type.add()
        entry.name = "DEntry"
        entry.options.map_entry = True
        k = entry.field.add()
        k.name, k.number, k.type, k.label = "key", 1, FD.TYPE_STRING, FD.LABEL_OPTIONAL
        v = entry.field.add()
        v.name, v.number, v.type, v.label = "value", 2, FD.TYPE_MESSAGE, FD.LABEL_OPTIONAL
        v.type_name = ".m3mm.Sub"
        fd = m.field.add()
        fd.name, fd.number, fd.type, fd.label = "d", 1, FD.TYPE_MESSAGE, FD.LABEL_REPEATED
        fd.type_name = ".m3mm.M.DEntry"
        pool = descriptor_pool.DescriptorPool()
        pool.Add(f)
        desc = pool.FindMessageTypeByName("m3mm.M")
        cls = message_class_for(desc)
        msg = cls()
        msg.d["a"].x = 7
        msg.d["b"].x = -3
        out = columns_to_message(cls(), message_to_columns(msg))
        assert out.d["a"].x == 7 and out.d["b"].x == -3
