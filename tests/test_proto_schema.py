"""Descriptor-driven proto codec: real protobuf schemas with nested
messages compress through the columnar engine and the schema rides a
FileDescriptorSet annotation (reference `src/dbnode/encoding/proto`
encoder.go descriptor parsing + schema annotations)."""

import pytest

from m3_tpu.encoding.proto_codec import (
    FieldKind,
    ProtoDecoder,
    ProtoEncoder,
)
from m3_tpu.encoding.proto_schema import (
    UnsupportedFieldError,
    columns_to_message,
    descriptor_from_annotation,
    message_class_for,
    message_to_columns,
    pack_schema_annotation,
    schema_from_descriptor,
    unpack_schema_annotation,
)

START = 1_600_000_000 * 10**9


def _build_pool():
    """A realistic message with a nested sub-message, built
    programmatically (no protoc run needed): the VehicleLocation shape
    the reference's proto tests use, plus nesting."""
    from google.protobuf import descriptor_pb2, descriptor_pool

    f = descriptor_pb2.FileDescriptorProto()
    f.name = "telemetry.proto"
    f.package = "m3test"
    f.syntax = "proto3"

    inner = f.message_type.add()
    inner.name = "Position"
    for i, (name, t) in enumerate(
        [("latitude", "TYPE_DOUBLE"), ("longitude", "TYPE_DOUBLE")], 1
    ):
        fd = inner.field.add()
        fd.name, fd.number = name, i
        fd.type = getattr(descriptor_pb2.FieldDescriptorProto, t)
        fd.label = descriptor_pb2.FieldDescriptorProto.LABEL_OPTIONAL

    outer = f.message_type.add()
    outer.name = "VehicleUpdate"
    specs = [
        ("fuel_percent", "TYPE_DOUBLE", None),
        ("odometer", "TYPE_INT64", None),
        ("status", "TYPE_STRING", None),
        ("moving", "TYPE_BOOL", None),
        ("position", "TYPE_MESSAGE", ".m3test.Position"),
    ]
    for i, (name, t, tn) in enumerate(specs, 1):
        fd = outer.field.add()
        fd.name, fd.number = name, i
        fd.type = getattr(descriptor_pb2.FieldDescriptorProto, t)
        fd.label = descriptor_pb2.FieldDescriptorProto.LABEL_OPTIONAL
        if tn:
            fd.type_name = tn

    pool = descriptor_pool.DescriptorPool()
    pool.Add(f)
    fds = descriptor_pb2.FileDescriptorSet()
    fds.file.add().CopyFrom(f)
    return pool, fds.SerializeToString()


class TestDescriptorSchema:
    def test_nested_flattening(self):
        pool, _ = _build_pool()
        desc = pool.FindMessageTypeByName("m3test.VehicleUpdate")
        schema = schema_from_descriptor(desc)
        assert schema.fields == (
            ("fuel_percent", FieldKind.FLOAT),
            ("odometer", FieldKind.INT),
            ("status", FieldKind.BYTES),
            ("moving", FieldKind.BOOL),
            ("position.latitude", FieldKind.FLOAT),
            ("position.longitude", FieldKind.FLOAT),
        )

    def test_repeated_rejected(self):
        from google.protobuf import descriptor_pb2, descriptor_pool

        f = descriptor_pb2.FileDescriptorProto()
        f.name = "rep.proto"
        f.package = "m3test2"
        f.syntax = "proto3"
        m = f.message_type.add()
        m.name = "HasRepeated"
        fd = m.field.add()
        fd.name, fd.number = "xs", 1
        fd.type = descriptor_pb2.FieldDescriptorProto.TYPE_INT64
        fd.label = descriptor_pb2.FieldDescriptorProto.LABEL_REPEATED
        pool = descriptor_pool.DescriptorPool()
        pool.Add(f)
        with pytest.raises(UnsupportedFieldError):
            schema_from_descriptor(
                pool.FindMessageTypeByName("m3test2.HasRepeated"))

    def test_roundtrip_real_messages_through_codec(self):
        pool, fds_bytes = _build_pool()
        desc = pool.FindMessageTypeByName("m3test.VehicleUpdate")
        cls = message_class_for(desc)
        schema = schema_from_descriptor(desc)

        msgs = []
        for k in range(40):
            m = cls()
            m.fuel_percent = 75.0 - k * 0.25
            m.odometer = 100_000 + k * 7
            m.status = "cruising" if k % 5 else "stopped"
            m.moving = bool(k % 5)
            m.position.latitude = 47.6 + k * 1e-4
            m.position.longitude = -122.3 - k * 1e-4
            msgs.append(m)

        enc = ProtoEncoder(schema, START)
        for k, m in enumerate(msgs):
            enc.encode(START + (k + 1) * 10**9, message_to_columns(m))
        blob = enc.stream()

        dec = ProtoDecoder(schema, blob)
        out = list(dec)
        assert len(out) == 40
        for k, (ts, cols) in enumerate(out):
            assert ts == START + (k + 1) * 10**9
            back = columns_to_message(cls(), cols)
            assert back == msgs[k]

    def test_schema_annotation_roundtrip(self):
        pool, fds_bytes = _build_pool()
        ann = pack_schema_annotation(fds_bytes, "m3test.VehicleUpdate")
        fds2, name = unpack_schema_annotation(ann)
        assert name == "m3test.VehicleUpdate" and fds2 == fds_bytes
        assert unpack_schema_annotation(b"not a schema") is None
        # decode side: a fresh pool rebuilds the descriptor and class
        desc = descriptor_from_annotation(ann)
        schema = schema_from_descriptor(desc)
        assert schema.fields[0] == ("fuel_percent", FieldKind.FLOAT)
        cls = message_class_for(desc)
        m = cls()
        m.odometer = 5
        assert message_to_columns(m)["odometer"] == 5

    def test_schema_annotation_rides_m3tsz_device_encoder(self):
        """The schema annotation travels as the first-datapoint M3TSZ
        annotation on the batched device encoder and comes back through
        the scalar decoder on a node that has never seen the schema."""
        import numpy as np

        from m3_tpu.encoding.m3tsz import decode_series
        from m3_tpu.encoding.m3tsz_jax import encode_batch

        _, fds_bytes = _build_pool()
        ann = pack_schema_annotation(fds_bytes, "m3test.VehicleUpdate")
        T = 10
        ts = np.tile(START + np.arange(1, T + 1) * 10**9, (1, 1)).astype(np.int64)
        vals = np.round(np.arange(T, dtype=np.float64)[None, :] * 0.5, 1)
        streams, fb = encode_batch(ts, vals, np.full(1, START, np.int64),
                                   out_words=200, annotations=[ann])
        assert not fb.any()
        pts = decode_series(streams[0])
        desc = descriptor_from_annotation(pts[0].annotation)
        assert desc.full_name == "m3test.VehicleUpdate"
