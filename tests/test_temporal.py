"""Temporal stencil kernels vs a naive per-window numpy oracle
implementing Prometheus semantics (the reference's temporal functions,
src/query/functions/temporal/{rate,aggregation,linear_regression}.go)."""

import numpy as np
import jax.numpy as jnp
import pytest

from m3_tpu.query import temporal as tp

STEP = 15 * 10**9
RANGE = 5 * 60 * 10**9
T0 = 1_700_000_000 * 10**9


def _mk_series(S=6, P=200, seed=0, counter=False, irregular=True):
    rng = np.random.default_rng(seed)
    ts = np.full((S, P), np.iinfo(np.int64).max, np.int64)
    vals = np.full((S, P), np.nan)
    counts = np.zeros(S, np.int64)
    for s in range(S):
        n = rng.integers(P // 2, P)
        gaps = rng.integers(5, 15, n) if irregular else np.full(n, 10)
        t = T0 + np.cumsum(gaps * 10**9)
        if counter:
            v = np.cumsum(rng.integers(0, 100, n)).astype(float)
            # inject counter resets
            for r in rng.integers(5, n, 2):
                v[r:] = v[r:] - v[r] + rng.integers(0, 5)
        else:
            v = rng.normal(50, 10, n)
        ts[s, :n] = t
        vals[s, :n] = v
        counts[s] = n
    steps = np.arange(T0 + RANGE, T0 + RANGE + 40 * STEP, STEP, dtype=np.int64)
    return ts, vals, counts, steps


def _window(ts_row, vals_row, count, t, rng_nanos):
    sel = (ts_row[:count] > t - rng_nanos) & (ts_row[:count] <= t)
    return ts_row[:count][sel], vals_row[:count][sel]


def _oracle_rate(ts, vals, counts, steps, rng_nanos, func):
    S = ts.shape[0]
    out = np.full((S, len(steps)), np.nan)
    for s in range(S):
        for j, t in enumerate(steps):
            wt, wv = _window(ts[s], vals[s], counts[s], t, rng_nanos)
            if len(wt) < 2:
                continue
            if func in ("rate", "increase"):
                adj = wv.copy()
                bump = 0.0
                for i in range(1, len(adj)):
                    if wv[i] < wv[i - 1]:
                        bump += wv[i - 1]
                    adj[i] = wv[i] + bump
                wv = adj
            delta = wv[-1] - wv[0]
            sampled = (wt[-1] - wt[0])
            if sampled == 0:
                continue
            avg = sampled / (len(wt) - 1)
            dstart = wt[0] - (t - rng_nanos)
            dend = t - wt[-1]
            estart = dstart if dstart < avg * 1.1 else avg / 2
            eend = dend if dend < avg * 1.1 else avg / 2
            if func in ("rate", "increase") and delta > 0:
                zdur = sampled * (wv[0] / delta)
                estart = min(estart, zdur)
            val = delta * ((sampled + estart + eend) / sampled)
            if func == "rate":
                val = val / (rng_nanos / 1e9)
            out[s, j] = val
    return out


@pytest.mark.parametrize("func", ["rate", "increase", "delta"])
def test_rate_family(func):
    counter = func != "delta"
    ts, vals, counts, steps = _mk_series(counter=counter)
    got = np.asarray(
        tp.rate_family(jnp.asarray(ts), jnp.asarray(np.nan_to_num(vals)),
                       jnp.asarray(steps), RANGE, func)
    )
    want = _oracle_rate(ts, np.nan_to_num(vals), counts, steps, RANGE, func)
    np.testing.assert_allclose(got, want, rtol=1e-9, equal_nan=True)


@pytest.mark.parametrize(
    "func", ["sum_over_time", "count_over_time", "avg_over_time", "stddev_over_time"]
)
def test_sum_count_family(func):
    ts, vals, counts, steps = _mk_series()
    got = np.asarray(
        tp.sum_count_family(jnp.asarray(ts), jnp.asarray(np.nan_to_num(vals)),
                            jnp.asarray(steps), RANGE, func)
    )
    S = ts.shape[0]
    want = np.full_like(got, np.nan)
    for s in range(S):
        for j, t in enumerate(steps):
            _, wv = _window(ts[s], np.nan_to_num(vals[s]), counts[s], t, RANGE)
            if len(wv) == 0:
                continue
            want[s, j] = {
                "sum_over_time": wv.sum(),
                "count_over_time": float(len(wv)),
                "avg_over_time": wv.mean(),
                "stddev_over_time": wv.std(),
            }[func]
    np.testing.assert_allclose(got, want, rtol=1e-9, equal_nan=True)


@pytest.mark.parametrize("func,q", [("min_over_time", 0), ("max_over_time", 0),
                                    ("quantile_over_time", 0.9)])
def test_minmax_quantile_family(func, q):
    ts, vals, counts, steps = _mk_series()
    W = tp.window_pad_for(counts, ts, RANGE)
    got = np.asarray(
        tp.minmax_quantile_family(jnp.asarray(ts), jnp.asarray(np.nan_to_num(vals)),
                                  jnp.asarray(steps), RANGE, func, W, q)
    )
    want = np.full_like(got, np.nan)
    for s in range(ts.shape[0]):
        for j, t in enumerate(steps):
            _, wv = _window(ts[s], np.nan_to_num(vals[s]), counts[s], t, RANGE)
            if len(wv) == 0:
                continue
            if func == "min_over_time":
                want[s, j] = wv.min()
            elif func == "max_over_time":
                want[s, j] = wv.max()
            else:
                want[s, j] = np.quantile(wv, q, method="linear")
    np.testing.assert_allclose(got, want, rtol=1e-9, equal_nan=True)


def test_irate_idelta():
    ts, vals, counts, steps = _mk_series(counter=True)
    got = np.asarray(
        tp.rate_family(jnp.asarray(ts), jnp.asarray(np.nan_to_num(vals)),
                       jnp.asarray(steps), RANGE, "irate")
    )
    for s in range(ts.shape[0]):
        for j, t in enumerate(steps):
            wt, wv = _window(ts[s], np.nan_to_num(vals[s]), counts[s], t, RANGE)
            if len(wt) < 2:
                assert np.isnan(got[s, j])
                continue
            dv = wv[-1] - wv[0:][-2] if False else wv[-1] - wv[-2]
            if wv[-1] < wv[-2]:  # reset between the last two samples
                dv = wv[-1]
            dt = (wt[-1] - wt[-2]) / 1e9
            np.testing.assert_allclose(got[s, j], dv / dt, rtol=1e-9)


def test_deriv_and_predict_linear():
    ts, vals, counts, steps = _mk_series()
    got_d = np.asarray(
        tp.regression_family(jnp.asarray(ts), jnp.asarray(np.nan_to_num(vals)),
                             jnp.asarray(steps), RANGE, "deriv")
    )
    got_p = np.asarray(
        tp.regression_family(jnp.asarray(ts), jnp.asarray(np.nan_to_num(vals)),
                             jnp.asarray(steps), RANGE, "predict_linear", 600.0)
    )
    for s in range(ts.shape[0]):
        for j, t in enumerate(steps):
            wt, wv = _window(ts[s], np.nan_to_num(vals[s]), counts[s], t, RANGE)
            if len(wt) < 2:
                assert np.isnan(got_d[s, j])
                continue
            x = (wt - t) / 1e9  # centered at step time, like the kernel
            slope, intercept = np.polyfit(x, wv, 1)
            np.testing.assert_allclose(got_d[s, j], slope, rtol=1e-6)
            np.testing.assert_allclose(got_p[s, j], intercept + slope * 600.0, rtol=1e-6)


def test_last_over_time():
    ts, vals, counts, steps = _mk_series()
    got = np.asarray(
        tp.last_over_time(jnp.asarray(ts), jnp.asarray(np.nan_to_num(vals)),
                          jnp.asarray(steps), RANGE)
    )
    for s in range(ts.shape[0]):
        for j, t in enumerate(steps):
            _, wv = _window(ts[s], np.nan_to_num(vals[s]), counts[s], t, RANGE)
            if len(wv) == 0:
                assert np.isnan(got[s, j])
            else:
                assert got[s, j] == wv[-1]


def _oracle_transitions(ts, vals, counts, steps, rng_nanos, func):
    S = ts.shape[0]
    out = np.full((S, len(steps)), np.nan)
    for s in range(S):
        for j, t in enumerate(steps):
            _, wv = _window(ts[s], vals[s], counts[s], t, rng_nanos)
            if len(wv) == 0:
                continue
            if func == "resets":
                out[s, j] = float(np.sum(wv[1:] < wv[:-1]))
            else:
                out[s, j] = float(np.sum(wv[1:] != wv[:-1]))
    return out


def _oracle_holt_winters(ts, vals, counts, steps, rng_nanos, sf, tf):
    """Prometheus funcHoltWinters, verbatim sequential loop."""
    S = ts.shape[0]
    out = np.full((S, len(steps)), np.nan)
    for s in range(S):
        for j, t in enumerate(steps):
            _, wv = _window(ts[s], vals[s], counts[s], t, rng_nanos)
            if len(wv) < 2:
                continue
            s1 = wv[0]
            b = wv[1] - wv[0]
            for i in range(1, len(wv)):
                x = sf * wv[i]
                y = (1.0 - sf) * (s1 + b)
                s0, s1 = s1, x + y
                b = tf * (s1 - s0) + (1.0 - tf) * b
            out[s, j] = s1
    return out


class TestTransitionsFamily:
    @pytest.mark.parametrize("func", ["resets", "changes"])
    def test_vs_oracle(self, func):
        ts, vals, counts, steps = _mk_series(counter=True, seed=11)
        got = np.asarray(tp.transitions_family(
            jnp.asarray(ts), jnp.asarray(np.nan_to_num(vals)),
            jnp.asarray(steps), RANGE, func))
        want = _oracle_transitions(ts, np.nan_to_num(vals), counts, steps,
                                   RANGE, func)
        np.testing.assert_allclose(got, want, equal_nan=True)

    def test_single_sample_window_is_zero(self):
        ts = np.asarray([[T0 + 10**9]], np.int64)
        vals = np.asarray([[5.0]])
        steps = np.asarray([T0 + 2 * 10**9], np.int64)
        got = np.asarray(tp.transitions_family(
            jnp.asarray(ts), jnp.asarray(vals), jnp.asarray(steps),
            RANGE, "resets"))
        assert got[0, 0] == 0.0


class TestHoltWinters:
    def test_vs_prometheus_loop(self):
        ts, vals, counts, steps = _mk_series(seed=5)
        W = tp.window_pad_for(counts, ts, RANGE)
        got = np.asarray(tp.holt_winters(
            jnp.asarray(ts), jnp.asarray(np.nan_to_num(vals)),
            jnp.asarray(steps), RANGE, max(W, 2), 0.3, 0.6))
        want = _oracle_holt_winters(ts, np.nan_to_num(vals), counts, steps,
                                    RANGE, 0.3, 0.6)
        np.testing.assert_allclose(got, want, rtol=1e-10, equal_nan=True)
