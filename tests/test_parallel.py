"""Sharded aggregator step over the 8-device virtual CPU mesh."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from m3_tpu.aggregator import arena as _arena
from m3_tpu.parallel import make_mesh, sharded_init, sharded_ingest_consume
from m3_tpu.parallel.sharded_agg import ShardedBatch


def _mk_batch(topo, W, C, N, seed=0):
    D = topo.num_shards
    rng = np.random.default_rng(seed)
    sh = lambda a, dt: jax.device_put(jnp.asarray(a, dt), topo.sharded(None))
    return ShardedBatch(
        windows=sh(rng.integers(0, W, (D, N)), jnp.int32),
        slots=sh(rng.integers(0, C, (D, N)), jnp.int32),
        counter_values=sh(rng.integers(0, 1000, (D, N)), jnp.int64),
        gauge_values=sh(rng.normal(100.0, 10.0, (D, N)), jnp.float64),
        timer_values=sh(np.abs(rng.normal(0.1, 0.02, (D, N))), jnp.float64),
        times=sh(np.tile(np.arange(1, N + 1), (D, 1)), jnp.int64),
    )


@pytest.mark.parametrize("shards,replicas", [(8, 1), (4, 2)])
def test_sharded_step_matches_single_device(shards, replicas):
    topo = make_mesh(num_shards=shards, num_replicas=replicas)
    W, C, N = 2, 32, 64
    state = sharded_init(topo, W, C, 4 * N)
    batch = _mk_batch(topo, W, C, N)
    new_state, lanes = sharded_ingest_consume(
        topo, state, batch, jnp.int32(0), W, C, (0.5, 0.95, 0.99)
    )

    # Oracle: run each shard through the single-device arenas of the
    # SAME layout the sharded step resolved (the M3_ARENA_LAYOUT seam).
    windows = np.asarray(batch.windows)
    slots = np.asarray(batch.slots)
    cvals = np.asarray(batch.counter_values)
    times = np.asarray(batch.times)
    c_lanes = np.asarray(lanes["counter"][0])
    assert c_lanes.shape == (shards, C, 8)
    for d in range(shards):
        a, _g, _t = _arena.make_arenas(W, C, 4 * N, (0.5, 0.95, 0.99))
        a.ingest(
            jnp.asarray(windows[d]),
            jnp.asarray(slots[d]),
            jnp.asarray(cvals[d]),
            jnp.asarray(times[d]),
        )
        want, _ = a.consume(0)
        np.testing.assert_allclose(c_lanes[d], np.asarray(want), rtol=0, atol=0)

    # Packed degraded-state flags must be clean on a healthy run (the
    # engine path raises; the sharded path surfaces the same bits here).
    assert int(np.asarray(lanes["err"]).sum()) == 0

    # Global rollup = sum of per-shard sums for window 0.
    rollup = np.asarray(lanes["rollup"])
    gsum_want = 0.0
    gl = np.asarray(lanes["gauge"][0])
    for d in range(shards):
        gsum_want += np.nan_to_num(gl[d, :, 5]) + c_lanes[d, :, 5]
    np.testing.assert_allclose(rollup[:, 0], gsum_want, rtol=1e-12)

    # The drained window's ring row was reset; only window-1 samples
    # remain.  Counts live in a plain column on the f64 layout and in
    # the packed base word's count lane on the packed layout.
    if "count" in new_state.counters._fields:
        remaining = np.asarray(new_state.counters.count).sum()
    else:
        from m3_tpu.aggregator import packed as _packed

        cnt, _ = _packed._unpack_base(
            jnp.asarray(np.asarray(new_state.counters.base)),
            _packed.DEFAULT_WIDTHS)
        remaining = int(np.asarray(cnt).sum())
    assert remaining == (windows == 1).sum()


def test_graft_entry_single_chip():
    import __graft_entry__ as ge

    fn, args = ge.entry()
    out = jax.jit(fn)(*args)
    jax.block_until_ready(out)
    (counters, gauges, timers), (c_lanes, g_lanes, t_lanes, cnt) = out
    assert np.asarray(c_lanes).shape[1] == 8


@pytest.mark.slow  # round-12 tier-1 budget: ~17s duplicate of the driver's
# separate `__graft_entry__.dryrun_multichip` run (TESTING.md tier 6);
# test_graft_entry_single_chip keeps the entry-point contract in tier-1.
def test_graft_entry_dryrun_multichip():
    import __graft_entry__ as ge

    ge.dryrun_multichip(8)


def test_sharded_packed_err_surfaces_timer_overflow():
    """Review fix: a fixed-capacity sharded timer buffer that overflows
    loses MOMENTS (not just quantiles) on the packed layout — the step
    must flag it per shard instead of silently publishing wrong lanes."""
    topo = make_mesh(num_shards=1, num_replicas=1,
                     devices=jax.devices()[:1])
    W, C, N = 2, 16, 64
    state = sharded_init(topo, W, C, sample_capacity=8, layout="packed")
    batch = _mk_batch(topo, W, C, N, seed=3)
    _state, lanes = sharded_ingest_consume(
        topo, state, batch, jnp.int32(0), W, C, (0.5,), layout="packed")
    from m3_tpu.aggregator.packed import _ERR_TIMER_OVERFLOW

    err = np.asarray(lanes["err"])
    assert (err & _ERR_TIMER_OVERFLOW).any()


def test_sharded_layout_arg_validated():
    topo = make_mesh(num_shards=1, num_replicas=1,
                     devices=jax.devices()[:1])
    import pytest

    with pytest.raises(ValueError, match="unknown arena layout"):
        sharded_init(topo, 2, 8, 32, layout="packd")
