"""Round-14 acceptance dtest: the fleet scrapes itself into its own
storage, and a chaos wire-fault window trips a PromQL burn-rate rule.

3 real node processes (rf=3, shared remote KV, placement via the admin
API) under sustained Majority ingest with self-monitoring ON in fleet
mode (every node stores its own registry AND its peers' /metrics in
``_m3_selfmon`` through the real write path).  A wire-fault window
(``rpc.server`` drop faults armed live over HTTP) on node 1 must:

* trip the configured multi-window burn-rate rule ON the faulted
  node's ``/health`` ``slo`` section (the rule reads node 1's OWN
  self-stored fault/ingest series),
* be visible via a PromQL query over ``_m3_selfmon`` issued to a
  DIFFERENT node (node 0 fleet-scraped node 1's ``slo_burn`` gauge —
  the whole cluster's health is one query away from any node),
* CLEAR after disarm (the rate windows wash out),

with zero acked-sample loss throughout (the soak ledger's regenerate-
and-reread verify at Majority).
"""

import threading
import time
import urllib.request

import numpy as np
import pytest

from m3_tpu.dtest.soak import (
    NS, Ledger, SoakCluster, SoakConfig, WorkloadGen, _verify,
)

# objective 0.99 → budget 0.01: fires once >1% of rpc write FRAMES are
# dropped over BOTH windows (factor 1.0), clears ~long-window after
# disarm.  fault_drop_triggers is the x/fault mirror every node already
# exposes; db_write_batch_seconds_count counts completed write frames,
# so attempts ≈ completed + dropped — both sides frame-rate, same unit
# (db_writes would be SAMPLES: 1000x off per batch).
WIRE_RULE = {
    "name": "wire-errors",
    "objective": 0.99,
    "ratio": ("sum(rate(fault_drop_triggers[{window}])) / "
              "clamp_min(sum(rate(m3tpu_db_write_batch_seconds_count"
              "[{window}])) + sum(rate(fault_drop_triggers[{window}])), "
              "0.1)"),
    "windows": [{"long": "30s", "short": "10s", "factor": 1.0}],
}


def _health(cluster, k):
    import json

    with urllib.request.urlopen(
            f"http://127.0.0.1:{cluster.http_port(k)}/health",
            timeout=30) as r:
        return json.load(r)


def _rule_firing(cluster, k, rule):
    doc = (_health(cluster, k).get("slo") or {}).get("rules", {}).get(rule)
    return doc is not None and doc.get("firing") is True


@pytest.mark.slow
class TestSelfMonitoringFleetScenario:
    def test_wire_fault_trips_burn_rule_fleet_visible(self, tmp_path):
        cfg = SoakConfig(
            nodes=3, series=4000, batch=1000, num_shards=4,
            slot_capacity=1 << 16, churn=0.0, smoke=True,  # 1s ticks
            replace=False, selfmon_budget=4000,
            selfmon_extra_rules=[WIRE_RULE],
        )
        cluster = SoakCluster(cfg, tmp_path / "cluster")
        try:
            cluster.start()
            gen = WorkloadGen(cfg.series, cfg.churn, cfg.seed)
            ledger = Ledger(gen)
            stop = threading.Event()

            def ingest():
                sweep = 0
                while not stop.is_set():
                    for lo in range(0, cfg.series, cfg.batch):
                        if stop.is_set():
                            break
                        hi = min(lo + cfg.batch, cfg.series)
                        ids = gen.ids(sweep, lo, hi)
                        vals = gen.values(sweep, lo, hi)
                        ts = time.time_ns()
                        tsa = np.full(hi - lo, ts, np.int64)
                        try:
                            rejected = cluster.session.write_batch(
                                NS, ids, tsa, vals, now_nanos=ts)
                        except Exception:  # noqa: BLE001 — unacked
                            stop.wait(0.2)
                            continue
                        if not rejected:
                            ledger.ack_bulk(sweep, lo, hi, ts)
                    sweep += 1

            t = threading.Thread(target=ingest, daemon=True)
            t.start()

            # baseline: ingest + selfmon cycles, rule present, quiet
            deadline = time.monotonic() + 120
            while time.monotonic() < deadline:
                slo = _health(cluster, 1).get("slo")
                if slo and "wire-errors" in slo.get("rules", {}):
                    break
                time.sleep(1.0)
            else:
                pytest.fail("wire-errors rule never appeared on node 1")
            assert not _rule_firing(cluster, 1, "wire-errors")

            # -- fault window: drop 40% of node 1's rpc traffic -------
            cluster.arm_faults(1, "rpc.server=drop:p=0.4:seed=7")
            deadline = time.monotonic() + 120
            while time.monotonic() < deadline:
                if _rule_firing(cluster, 1, "wire-errors"):
                    break
                time.sleep(1.0)
            else:
                pytest.fail(
                    "burn rule never fired on the faulted node; health="
                    f"{_health(cluster, 1).get('slo')}")

            # fleet visibility: node 0 answers for node 1's burn from
            # its OWN storage (it fleet-scraped i1's slo_burn gauge)
            deadline = time.monotonic() + 60
            burn = None
            while time.monotonic() < deadline:
                rows = cluster.promql(
                    0, 'max_over_time(m3tpu_slo_burn'
                       '{rule="wire-errors",instance="i1"}[5m])',
                    namespace="_m3_selfmon")
                if rows:
                    burn = float(rows[0]["value"][1])
                    if burn >= 1.0:
                        break
                time.sleep(1.0)
            assert burn is not None and burn >= 1.0, (
                f"faulted node's burn not visible from node 0: {burn}")

            # -- disarm: the rule must CLEAR as the windows wash out --
            cluster.clear_faults(1)
            deadline = time.monotonic() + 150
            while time.monotonic() < deadline:
                if not _rule_firing(cluster, 1, "wire-errors"):
                    break
                time.sleep(2.0)
            else:
                pytest.fail("burn rule never cleared after disarm")

            # -- zero acked-sample loss throughout --------------------
            stop.set()
            t.join(60)
            assert ledger.acked_samples > 0
            for k in cluster.alive_nodes():
                cluster.nodes[k].wait_healthy(120)
            verdict = _verify(cluster, ledger, cfg)
            assert verdict["zero_acked_loss"], verdict
        finally:
            cluster.close()
