"""Query compute-precision policy (m3_tpu/query/precision.py).

The engine defaults to Prometheus's f64; `set_compute_dtype("f32")`
narrows the bulk stencil math for TPU (no native f64 ALU on v5e-class
chips).  These tests pin the accuracy envelope the policy documents:
f32 results within ~1e-4 relative of the f64 evaluation for the
north-star query shape, regression stencils exempt (always f64).
"""

import numpy as np
import pytest

from m3_tpu.query import precision
from m3_tpu.query.block import RawBlock, SeriesMeta
from m3_tpu.query.engine import Engine

T0 = 1_700_000_000 * 10**9
STEP = 15 * 10**9


class _ArrayStorage:
    def __init__(self, raw, name=b"m"):
        self.raw = raw
        self.name = name

    def fetch_raw(self, name, matchers, start_nanos, end_nanos):
        assert name == self.name
        return self.raw


def _bucket_block(G=40, B=4, P=261, seed=5, resets=False):
    """Realistic histogram series: per-bucket increments accumulate over
    time AND cumulate across the le axis (c_b = sum of buckets <= b), so
    quantile ranks sit strictly inside buckets — the shape real
    histogram counters have."""
    rng = np.random.default_rng(seed)
    ubs = [b"0.1", b"1", b"5", b"+Inf"]
    ts = np.tile(T0 + np.arange(P, dtype=np.int64) * STEP, (G * B, 1))
    incr = rng.poisson(3.0, (G, B, P)).astype(np.float64)
    if resets:
        # A counter reset zeroes every bucket of the group at once.
        r = rng.random((G, 1, P)) < 0.01
        incr = np.where(r, 0.0, incr)
    cum_t = np.cumsum(incr, axis=2)
    if resets:
        # Restart accumulation after each reset point.
        keep = np.maximum.accumulate(
            np.where(r, np.arange(P)[None, None, :], 0), axis=2)
        base = np.take_along_axis(cum_t, np.maximum(keep - 1, 0), axis=2)
        cum_t = np.where(keep > 0, cum_t - base, cum_t)
    vals = np.cumsum(cum_t, axis=1).reshape(G * B, P)  # le-cumulative
    counts = np.full(G * B, P, np.int64)
    series = [
        SeriesMeta(((b"__name__", b"m"), (b"g", b"g%03d" % g),
                    (b"le", ubs[b])))
        for g in range(G) for b in range(B)
    ]
    return RawBlock(np.ascontiguousarray(ts), vals, counts, series)


@pytest.fixture
def restore_policy():
    yield
    precision.set_compute_dtype("f64")


class TestPrecisionPolicy:
    def test_invalid_dtype_rejected(self):
        with pytest.raises(ValueError, match="f32"):
            precision.set_compute_dtype("f16")

    def test_f32_matches_f64_on_north_star_query(self, restore_policy):
        raw = _bucket_block()
        q = "histogram_quantile(0.9, rate(m[5m]))"
        start, end = T0 + 3600 * 10**9, T0 + 2 * 3600 * 10**9
        eng = Engine(_ArrayStorage(raw))
        precision.set_compute_dtype("f64")
        b64 = eng.execute_range(q, start, end, STEP)
        precision.set_compute_dtype("f32")
        b32 = eng.execute_range(q, start, end, STEP)
        assert b32.values.dtype == np.float64  # API surface stays f64
        v64, v32 = b64.values, b32.values
        assert v64.shape == v32.shape
        both = ~(np.isnan(v64) | np.isnan(v32))
        assert np.array_equal(np.isnan(v64), np.isnan(v32))
        denom = np.maximum(np.abs(v64[both]), 1e-6)
        assert np.max(np.abs(v64[both] - v32[both]) / denom) < 1e-4

    def test_f32_rate_only(self, restore_policy):
        raw = _bucket_block(G=10, B=4, resets=True)
        eng = Engine(_ArrayStorage(raw))
        start, end = T0 + 3600 * 10**9, T0 + 2 * 3600 * 10**9
        precision.set_compute_dtype("f64")
        b64 = eng.execute_range("rate(m[5m])", start, end, STEP)
        precision.set_compute_dtype("f32")
        b32 = eng.execute_range("rate(m[5m])", start, end, STEP)
        both = ~(np.isnan(b64.values) | np.isnan(b32.values))
        denom = np.maximum(np.abs(b64.values[both]), 1e-6)
        err = np.max(np.abs(b64.values[both] - b32.values[both]) / denom)
        assert err < 1e-4, err

    def test_f32_rate_long_span_large_counters(self, restore_policy):
        """The two cancellation traps: (a) a 30-day query span (times
        must not narrow against the epoch), (b) cumulative counters in
        the millions with small window deltas (values must difference
        in f64 before narrowing).  The rate kernel's i64-first duration
        math and internal `narrow` flag keep f32 error at the delta's
        own scale (~1e-7), independent of span or counter magnitude."""
        rng = np.random.default_rng(9)
        P = 30 * 24 * 12  # 5m samples for 30 days
        ts = np.tile(T0 + np.arange(P, dtype=np.int64) * 300 * 10**9,
                     (4, 1))
        vals = np.cumsum(rng.gamma(2.0, 5.0, (4, P)), axis=1)  # to ~1e6
        raw = RawBlock(np.ascontiguousarray(ts), vals,
                       np.full(4, P, np.int64),
                       [SeriesMeta(((b"__name__", b"c"), (b"i", b"%d" % i)))
                        for i in range(4)])
        eng = Engine(_ArrayStorage(raw, name=b"c"))
        q_start = T0 + 3600 * 10**9
        q_end = T0 + 30 * 24 * 3600 * 10**9 - 3600 * 10**9
        step = 3600 * 10**9
        precision.set_compute_dtype("f64")
        b64 = eng.execute_range("rate(c[15m])", q_start, q_end, step)
        precision.set_compute_dtype("f32")
        b32 = eng.execute_range("rate(c[15m])", q_start, q_end, step)
        both = ~(np.isnan(b64.values) | np.isnan(b32.values))
        err = np.max(np.abs(b64.values[both] - b32.values[both])
                     / np.maximum(np.abs(b64.values[both]), 1e-9))
        assert err < 1e-5, err

    def test_comparison_ops_exempt_from_f32(self, restore_policy):
        """f64-distinct operands that collide in f32 must still compare
        correctly under the f32 policy (comparisons never narrow)."""
        P = 8
        ts = np.tile(T0 + np.arange(P, dtype=np.int64) * STEP, (1, 1))
        raw_a = RawBlock(np.ascontiguousarray(ts),
                         np.full((1, P), 16777217.0),
                         np.full(1, P, np.int64),
                         [SeriesMeta(((b"__name__", b"a"),))])
        eng = Engine(_ArrayStorage(raw_a, name=b"a"))
        start, end = T0 + STEP, T0 + 6 * STEP
        precision.set_compute_dtype("f32")
        blk = eng.execute_range("a > 16777216.5", start, end, STEP)
        # 16777217.0 > 16777216.5 is true in f64; both round to
        # 16777216.0 in f32, which would drop the series.
        assert len(blk.series) == 1
        assert not np.isnan(blk.values).all()

    def test_regression_family_stays_f64(self, restore_policy):
        """deriv is exempt from the policy: its t² prefix sums overflow
        f32's integer range, so f32 and f64 policies must agree to f64
        accuracy (they run the same f64 kernel)."""
        raw = _bucket_block(G=4, B=4)
        eng = Engine(_ArrayStorage(raw))
        start, end = T0 + 3600 * 10**9, T0 + 2 * 3600 * 10**9
        precision.set_compute_dtype("f64")
        b64 = eng.execute_range("deriv(m[10m])", start, end, STEP)
        precision.set_compute_dtype("f32")
        b32 = eng.execute_range("deriv(m[10m])", start, end, STEP)
        both = ~(np.isnan(b64.values) | np.isnan(b32.values))
        assert np.allclose(b64.values[both], b32.values[both],
                           rtol=1e-12, atol=0)
