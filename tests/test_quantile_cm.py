"""Direct property tests for the host CM (CKMS) quantile stream.

``quantile_cm.Stream.add_batch`` is a per-sample Python loop over a
linked sample list — it had only transitive coverage through the
device-arena parity test before round 8.  These tests pin the CKMS
eps contract directly against numpy order statistics so the packed
arena rewrite (and any future reformulation of the stream) has an
oracle to stand on: for quantile q over n values, the returned value's
RANK must lie within [(q - eps)n - 1, (q + eps)n + 1].

Streams covered: uniform, duplicate-heavy (few distinct values — the
compress path collapses most samples), sorted ascending/descending
(adversarial for the insertion cursor), and batch-boundary shapes.
"""

import math

import numpy as np
import pytest

from m3_tpu.aggregator.quantile_cm import DEFAULT_EPS, Stream

QUANTILES = (0.5, 0.95, 0.99)


def _rank_bounds_ok(values: np.ndarray, q: float, got: float,
                    eps: float) -> bool:
    """CKMS guarantee as a rank check: got must sit between the
    order statistics at ranks floor((q-eps)n) and ceil((q+eps)n)."""
    n = len(values)
    s = np.sort(values)
    lo_rank = max(int(math.floor((q - eps) * n)) - 1, 0)
    hi_rank = min(int(math.ceil((q + eps) * n)) + 1, n - 1)
    return s[lo_rank] <= got <= s[hi_rank]


def _run(values: np.ndarray, batch: int = 997,
         eps: float = DEFAULT_EPS) -> Stream:
    st = Stream(QUANTILES, eps=eps)
    for lo in range(0, len(values), batch):
        st.add_batch([float(v) for v in values[lo:lo + batch]])
    st.flush()
    return st


class TestCKMSEpsBound:
    N = 10_000

    @pytest.mark.parametrize("seed", [0, 1, 2])
    def test_uniform_stream(self, seed):
        rng = np.random.default_rng(seed)
        values = rng.uniform(0.0, 1000.0, self.N)
        st = _run(values)
        for q in QUANTILES:
            got = st.quantile(q)
            assert _rank_bounds_ok(values, q, got, DEFAULT_EPS), \
                (q, got, np.percentile(values, q * 100))

    def test_duplicate_heavy_stream(self):
        # few distinct values: rank spans collapse, compress merges
        # aggressively; every answer must still be one of the values
        # within the eps rank window
        rng = np.random.default_rng(3)
        values = rng.choice([1.0, 2.0, 5.0, 100.0], self.N,
                            p=[0.6, 0.3, 0.05, 0.05])
        st = _run(values)
        for q in QUANTILES:
            got = st.quantile(q)
            assert _rank_bounds_ok(values, q, got, DEFAULT_EPS), (q, got)

    def test_sorted_ascending_adversarial(self):
        # sorted input keeps every insert at the cursor's tail — the
        # worst case for the insertion walk and for biased compression
        values = np.linspace(0.0, 1.0, self.N)
        st = _run(values)
        for q in QUANTILES:
            got = st.quantile(q)
            assert _rank_bounds_ok(values, q, got, DEFAULT_EPS), (q, got)

    def test_sorted_descending_adversarial(self):
        values = np.linspace(1.0, 0.0, self.N)
        st = _run(values)
        for q in QUANTILES:
            got = st.quantile(q)
            assert _rank_bounds_ok(values, q, got, DEFAULT_EPS), (q, got)

    def test_gamma_vs_numpy_percentile(self):
        # the shape the timer benches use; compare against numpy's
        # exact percentile with the eps rank window
        rng = np.random.default_rng(7)
        values = rng.gamma(2.0, 50.0, self.N)
        st = _run(values)
        for q in QUANTILES:
            got = st.quantile(q)
            assert _rank_bounds_ok(values, q, got, DEFAULT_EPS), \
                (q, got, np.percentile(values, q * 100))


class TestStreamMechanics:
    def test_min_max_exact(self):
        rng = np.random.default_rng(11)
        values = rng.normal(0.0, 10.0, 5000)
        st = _run(values)
        assert st.min() == values.min()
        assert st.max() == values.max()

    def test_incremental_flush_then_more_adds(self):
        # flush mid-stream, keep adding: the buffers must re-open
        rng = np.random.default_rng(13)
        a = rng.uniform(0, 1, 4000)
        b = rng.uniform(0, 1, 6000)
        st = Stream(QUANTILES)
        st.add_batch([float(v) for v in a])
        st.flush()
        st.add_batch([float(v) for v in b])
        st.flush()
        both = np.concatenate([a, b])
        for q in QUANTILES:
            assert _rank_bounds_ok(both, q, st.quantile(q), DEFAULT_EPS)

    def test_single_and_tiny_streams(self):
        st = Stream(QUANTILES)
        st.add(42.0)
        st.flush()
        for q in QUANTILES:
            assert st.quantile(q) == 42.0
        st2 = Stream(QUANTILES)
        st2.add_batch([3.0, 1.0, 2.0])
        st2.flush()
        assert st2.quantile(0.5) in (1.0, 2.0, 3.0)

    def test_batch_boundaries_equivalent_to_single_adds(self):
        rng = np.random.default_rng(17)
        values = rng.uniform(0, 100, 3000)
        st_batch = _run(values, batch=277)
        st_single = Stream(QUANTILES)
        for v in values:
            st_single.add(float(v))
        st_single.flush()
        # not bit-identical orders, but both within eps of the truth
        for q in QUANTILES:
            assert _rank_bounds_ok(values, q, st_batch.quantile(q),
                                   DEFAULT_EPS)
            assert _rank_bounds_ok(values, q, st_single.quantile(q),
                                   DEFAULT_EPS)
