"""Ops tools CLI, carbon line protocol, block cache, tracing.

Reference models: `src/cmd/tools/*` (read/verify/clone tools),
`src/metrics/carbon` + the coordinator carbon ingester,
`src/dbnode/persist/fs/seek_manager.go` + WiredList caching,
`src/x/opentracing` + tracepoint registries.
"""

import io
import json
import socket
import sys
import time

import numpy as np
import pytest

from m3_tpu.instrument.tracing import Tracepoint, Tracer
from m3_tpu.metrics.carbon import (
    document_to_path, parse_line, parse_lines, path_to_document,
    serve_carbon_background,
)
from m3_tpu.storage.block_cache import BlockCache
from m3_tpu.storage.database import Database, DatabaseOptions, NamespaceOptions
from m3_tpu.tools import cli

BLOCK = 2 * 3600 * 10**9
START = (1_700_000_000 * 10**9) // BLOCK * BLOCK
NS_OPTS = NamespaceOptions(num_shards=2, slot_capacity=1 << 10,
                           sample_capacity=1 << 12)


def _seeded_db(root):
    db = Database(DatabaseOptions(root=str(root)), namespaces={"default": NS_OPTS})
    ids = [b"cpu.a", b"cpu.b", b"mem.c"] * 4
    ts = START + np.arange(12, dtype=np.int64) * 10**9
    db.write_batch("default", ids, ts, np.arange(12.0))
    db.tick(START + BLOCK + NS_OPTS.buffer_past_nanos + 10**9)
    return db


def _run_cli(argv, capsys):
    rc = cli.main(argv)
    out = capsys.readouterr().out
    return rc, [json.loads(l) for l in out.splitlines() if l.strip()]


class TestTools:
    def test_read_data_files(self, tmp_path, capsys):
        db = _seeded_db(tmp_path)
        rc, rows = _run_cli(["read_data_files", str(tmp_path)], capsys)
        assert rc == 0
        ids = {r["id"] for r in rows}
        assert ids == {"cpu.a", "cpu.b", "mem.c"}
        for r in rows:
            assert len(r["points"]) == 4
        db.close()

    def test_read_commitlog(self, tmp_path, capsys):
        db = _seeded_db(tmp_path)
        db.close()
        rc, rows = _run_cli(["read_commitlog", str(tmp_path)], capsys)
        assert rc == 0
        assert len(rows) == 12
        assert rows[0]["namespace"] == "default"

    def test_verify_data_files_detects_corruption(self, tmp_path, capsys):
        db = _seeded_db(tmp_path)
        db.close()
        rc, rows = _run_cli(["verify_data_files", str(tmp_path)], capsys)
        assert rc == 0 and all(r["ok"] for r in rows)
        # corrupt one data file
        from m3_tpu.persist.fs import fileset_dir

        victim = next(iter(fileset_dir(tmp_path, "default", 0).glob("*-data.db")))
        raw = bytearray(victim.read_bytes())
        if not raw:
            pytest.skip("empty shard")
        raw[len(raw) // 2] ^= 0xFF
        victim.write_bytes(bytes(raw))
        rc, rows = _run_cli(["verify_data_files", str(tmp_path)], capsys)
        assert rc == 1
        assert any(not r["ok"] for r in rows)

    def test_clone_fileset(self, tmp_path, capsys):
        db = _seeded_db(tmp_path)
        db.close()
        from m3_tpu.persist.fs import list_filesets

        bs, vol = list_filesets(tmp_path, "default", 0)[0]
        dest = tmp_path / "clone"
        rc, rows = _run_cli([
            "clone_fileset", str(tmp_path), "default", "0", str(bs), str(dest),
            "--volume", str(vol),
        ], capsys)
        assert rc == 0 and rows[0]["cloned"] >= 1
        rc2, rows2 = _run_cli(["verify_data_files", str(dest)], capsys)
        assert rc2 == 0 and rows2


class TestCarbon:
    def test_parse_line(self):
        s = parse_line(b"foo.bar.baz 42.5 1700000000")
        assert s.path == b"foo.bar.baz"
        assert s.value == 42.5
        assert s.timestamp_nanos == 1_700_000_000 * 10**9

    def test_parse_rejects_malformed(self):
        for bad in (b"", b"# comment", b"noval 1", b"a..b 1 2",
                    b".lead 1 2", b"trail. 1 2", b"x nanb 2", b"x 1 notts",
                    b"x nan 1700000000",
                    # non-finite / out-of-int64-range timestamps must be
                    # skipped, not crash the connection handler
                    b"x 1 nan", b"x 1 inf", b"x 1 1e30", b"x 1 -5"):
            assert parse_line(bad) is None, bad

    def test_now_timestamp(self):
        s = parse_line(b"a.b 1 -1", now_nanos=123)
        assert s.timestamp_nanos == 123

    def test_path_document_roundtrip(self):
        d = path_to_document(b"servers.web01.cpu")
        assert d.tags()[b"__g1__"] == b"web01"
        assert document_to_path(d) == b"servers.web01.cpu"

    def test_tcp_ingest_end_to_end(self, tmp_path):
        db = Database(DatabaseOptions(root=str(tmp_path)),
                      namespaces={"default": NS_OPTS})
        srv = serve_carbon_background(
            lambda docs, ts, vals: db.write_tagged_batch("default", docs, ts, vals)
        )
        sock = socket.create_connection(("127.0.0.1", srv.port))
        t0 = START // 10**9
        lines = b"".join(
            b"servers.web01.cpu %d %d\nbogus line\n" % (i, t0 + i)
            for i in range(5)
        )
        sock.sendall(lines)
        sock.close()
        deadline = time.monotonic() + 60
        pts = []
        while time.monotonic() < deadline and len(pts) < 5:
            pts = db.read("default", b"servers.web01.cpu", START, START + BLOCK)
            time.sleep(0.05)
        assert [v for _, v in pts] == [0.0, 1.0, 2.0, 3.0, 4.0]
        # graphite tags are indexed
        from m3_tpu.index.search import Term

        docs = db.query_ids("default", Term(b"__g1__", b"web01"), START,
                            START + BLOCK)
        assert len(docs) == 1
        srv.shutdown()
        db.close()


class TestBlockCache:
    def test_hit_after_miss_and_lru_bound(self, tmp_path):
        db = _seeded_db(tmp_path)
        cache = db.block_cache
        r1 = db.read("default", b"cpu.a", START, START + BLOCK)
        stats1 = cache.stats
        r2 = db.read("default", b"cpu.a", START, START + BLOCK)
        assert r1 == r2 and len(r1) == 4
        assert cache.stats["series_blocks"] == stats1["series_blocks"]
        db.close()

    def test_invalidation_on_cold_flush(self, tmp_path):
        db = _seeded_db(tmp_path)
        before = db.read("default", b"cpu.a", START, START + BLOCK)
        # cold write into flushed block, then cold flush -> volume 1
        late_t = START + 77 * 10**9
        db.write_batch("default", [b"cpu.a"], np.asarray([late_t]),
                       np.asarray([321.0]))
        db.tick(START + BLOCK + NS_OPTS.buffer_past_nanos + 10**9)
        after = dict(db.read("default", b"cpu.a", START, START + BLOCK))
        assert after[late_t] == 321.0
        assert len(after) == len(before) + 1
        db.close()

    def test_byte_budget_bounded(self, tmp_path):
        """WiredList model: the decoded-block cache is bounded by BYTES,
        evicting least-recently-used series-blocks."""
        from m3_tpu.storage.block_cache import _entry_bytes

        c = BlockCache(max_readers=2, max_bytes=6000)
        with c._lock:
            pass  # lock exists and is not held by the public path below
        # simulate inserts through the accounting path
        for i in range(10):
            pts = [(k, float(k)) for k in range(20)]  # 120 + 320 bytes
            with c._lock:
                c._series[("k", i)] = pts
                c._series_bytes += _entry_bytes(pts)
                while c._series_bytes > c.max_bytes and len(c._series) > 1:
                    _, old = c._series.popitem(last=False)
                    c._series_bytes -= _entry_bytes(old)
        assert c._series_bytes <= c.max_bytes or len(c._series) == 1
        assert 0 < len(c._series) < 10
        assert c.stats["series_bytes"] == c._series_bytes

    def test_single_flight_coalesces(self, tmp_path):
        """Concurrent cold reads of one series-block pay one decode."""
        import threading

        calls = {"n": 0}
        gate = threading.Event()

        class _FakeReader:
            def read(self, sid):
                calls["n"] += 1
                gate.wait(2)
                return None

        c = BlockCache()
        c.reader = lambda *a, **k: _FakeReader()
        out = []

        def go():
            out.append(c.read_series("r", "ns", 0, 0, 0, b"x"))

        ts = [threading.Thread(target=go) for _ in range(6)]
        for t in ts:
            t.start()
        gate.set()
        for t in ts:
            t.join()
        assert calls["n"] == 1
        assert out == [None] * 6


class TestTracing:
    def test_span_nesting_and_ring(self):
        tr = Tracer(max_finished=8)
        with tr.start_span("outer") as outer:
            with tr.start_span("inner") as inner:
                inner.set_tag("k", 1)
        spans = tr.finished()
        byname = {s.name: s for s in spans}
        assert byname["inner"].parent_id == byname["outer"].span_id
        assert byname["inner"].trace_id == byname["outer"].trace_id
        assert byname["inner"].tags == {"k": 1}
        assert byname["outer"].duration_ns >= byname["inner"].duration_ns

    def test_error_capture(self):
        tr = Tracer()
        with pytest.raises(ValueError):
            with tr.start_span("boom"):
                raise ValueError("nope")
        assert "ValueError" in tr.finished("boom")[0].error

    def test_db_tracepoints(self, tmp_path):
        tr = Tracer()
        db = Database(DatabaseOptions(root=str(tmp_path)),
                      namespaces={"default": NS_OPTS}, tracer=tr)
        db.write_batch("default", [b"x"], np.asarray([START]), np.asarray([1.0]))
        db.read("default", b"x", START, START + BLOCK)
        names = {s.name for s in tr.finished()}
        assert Tracepoint.DB_WRITE_BATCH in names
        assert Tracepoint.DB_READ in names
        db.close()

    def test_ring_bounded(self):
        tr = Tracer(max_finished=4)
        for i in range(20):
            with tr.start_span(f"s{i}"):
                pass
        assert len(tr.finished()) == 4
