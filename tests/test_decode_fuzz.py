"""Round-6 two-phase decode: fuzz/property suite + sha256-pinned corpus.

Three layers of bit-identity evidence for the two-phase rewrite
(ISSUE 6), all against the golden-validated scalar codec (m3tsz.py):

* corpus — committed real-shape streams (tests/data/decode_corpus.json,
  regenerate with gen_decode_corpus.py) whose scalar-decoded output is
  sha256-pinned IN the file; both chains tails must reproduce the exact
  digest, covering NaN/±Inf, a mid-stream time-unit change and
  annotated streams.
* fuzz — random series families through the batched encoder, decoded by
  BOTH chains tails, exact (timestamp, value-bits) equality vs
  decode_series.
* properties — targeted edges: every dod bucket width, XOR
  contained/uncontained flips, int<->float mode churn.

Timestamp equality is on int64s; value equality is on the raw float64
BIT PATTERNS (``.view(uint64)``) — the decoder's contract is
bit-identity, and float compares would pass NaN-payload or -0.0 drift.
"""

import base64
import hashlib
import json
import os

import numpy as np
import pytest

jnp = pytest.importorskip("jax.numpy")

from tests.conftest import DATA_DIR  # noqa: E402
from m3_tpu.core.xtime import Unit  # noqa: E402
from m3_tpu.encoding.m3tsz import Datapoint, Encoder, decode_series  # noqa: E402
from m3_tpu.encoding.m3tsz_jax import decode_batch, encode_batch  # noqa: E402

START = 1_600_000_000 * 10**9
SEC = 10**9
CHAINS = ("fused", "gather")


def _digest(ts_list, bits_list):
    """Must match gen_decode_corpus.canonical_digest."""
    h = hashlib.sha256()
    for ts, bits in zip(ts_list, bits_list):
        h.update(np.int64(len(ts)).tobytes())
        h.update(np.asarray(ts, np.int64).tobytes())
        h.update(np.asarray(bits, np.uint64).tobytes())
    return h.hexdigest()


def _scalar_ts_bits(stream):
    pts = decode_series(stream)
    return (np.array([p.timestamp for p in pts], np.int64),
            np.array([p.value for p in pts], np.float64).view(np.uint64))


def _assert_batched_matches_scalar(streams, max_points, chains):
    ts, vals, counts, fb = decode_batch(streams, max_points=max_points,
                                        annotations_fallback=False,
                                        chains=chains)
    assert not fb.any(), f"unexpected fallback under chains={chains}"
    for i, s in enumerate(streams):
        want_ts, want_bits = _scalar_ts_bits(s)
        n = int(counts[i])
        assert n == len(want_ts), f"series {i}: count {n} != {len(want_ts)}"
        np.testing.assert_array_equal(ts[i, :n], want_ts,
                                      err_msg=f"series {i} timestamps")
        got_bits = vals[i, :n].copy().view(np.uint64)
        np.testing.assert_array_equal(got_bits, want_bits,
                                      err_msg=f"series {i} value bits")


class TestPinnedCorpus:
    @pytest.fixture(scope="class")
    def corpus(self):
        with open(DATA_DIR / "decode_corpus.json") as f:
            doc = json.load(f)
        return doc, [base64.b64decode(s) for s in doc["streams"]]

    def test_scalar_decoder_matches_pin(self, corpus):
        """The committed digest IS the scalar decoder's output — if this
        fails the corpus file drifted (or the scalar codec changed),
        and the batched assertions below would be pinning the wrong
        thing."""
        doc, streams = corpus
        ts_list, bits_list = zip(*(_scalar_ts_bits(s) for s in streams))
        assert _digest(ts_list, bits_list) == doc["sha256"]

    @pytest.mark.parametrize("chains", CHAINS)
    def test_batched_decode_matches_pin(self, corpus, chains):
        doc, streams = corpus
        ts, vals, counts, fb = decode_batch(
            streams, max_points=doc["max_points"],
            annotations_fallback=False, chains=chains)
        assert not fb.any()
        ts_list = [ts[i, :int(n)] for i, n in enumerate(counts)]
        bits_list = [vals[i, :int(n)].copy().view(np.uint64)
                     for i, n in enumerate(counts)]
        assert _digest(ts_list, bits_list) == doc["sha256"]


def _fuzz_batch(seed, S, T):
    """One (S, T) batch mixing the series families that hit different
    control paths: ints (diff chain), decimals (multiplier), floats
    (XOR chain), constants (repeat), spikes (uncontained XOR), NaN/Inf
    (special exponents), jittered cadence (all dod buckets)."""
    rng = np.random.default_rng(seed)
    cad_s = int(rng.integers(2, 30))
    ts = START + np.arange(1, T + 1) * (cad_s * SEC)
    ts = np.tile(ts, (S, 1)).astype(np.int64)
    # Jitter in WHOLE seconds so the time unit stays SECOND: sub-second
    # offsets would force the NANOS unit, whose deltas overflow the
    # 32-bit dod escape and legitimately flag encoder fallback.
    jit_rows = rng.random(S) < 0.5
    ts[jit_rows] += rng.integers(-(cad_s // 2), cad_s // 2,
                                 (int(jit_rows.sum()), T)) * SEC
    ts.sort(axis=1)
    vals = np.zeros((S, T))
    for i in range(S):
        fam = rng.integers(0, 6)
        if fam == 0:
            vals[i] = np.cumsum(rng.integers(-100, 100, T))
        elif fam == 1:
            vals[i] = np.round(rng.normal(0, 50, T),
                               int(rng.integers(0, 5)))
        elif fam == 2:
            vals[i] = rng.normal(0, 1, T)  # raw floats
        elif fam == 3:
            vals[i] = float(rng.integers(-5, 5))  # constant
        elif fam == 4:
            v = np.full(T, 7.25)
            v[rng.integers(0, T, max(1, T // 20))] = rng.choice(
                [1e8, -3e7, 0.0001])
            vals[i] = v
        else:
            v = np.round(rng.normal(10, 2, T), 2)
            v[rng.random(T) < 0.05] = np.nan
            v[rng.random(T) < 0.02] = np.inf * rng.choice([-1, 1])
            vals[i] = v
    starts = np.full(S, START, np.int64)
    return ts, vals, starts


class TestFuzzRoundtrip:
    @pytest.mark.parametrize("seed", [0, 1, 2, 3])
    def test_encode_decode_vs_scalar(self, seed):
        S, T = 12, 120
        ts, vals, starts = _fuzz_batch(seed, S, T)
        streams, fb = encode_batch(ts, vals, starts, out_words=256)
        assert not fb.any()
        for chains in CHAINS:
            _assert_batched_matches_scalar(
                [bytes(s) for s in streams], T + 1, chains)

    @pytest.mark.slow
    @pytest.mark.parametrize("seed", range(4, 16))
    def test_encode_decode_vs_scalar_deep(self, seed):
        S, T = 12, 120
        ts, vals, starts = _fuzz_batch(seed, S, T)
        streams, fb = encode_batch(ts, vals, starts, out_words=256)
        assert not fb.any()
        for chains in CHAINS:
            _assert_batched_matches_scalar(
                [bytes(s) for s in streams], T + 1, chains)


class TestDecodeProperties:
    def _encode_scalar(self, pts):
        enc = Encoder(START)
        for dp in pts:
            enc.encode(dp)
        return enc.stream()

    def test_every_dod_bucket_width(self):
        """Deltas hitting each timestamp opcode bucket (0/7/9/12-bit
        and the 32-bit default escape) in one stream."""
        t, pts = START, []
        for i, d in enumerate([10, 10, 10, 25, 10, 300, 10, 4000, 10,
                               2_000_000, 10, 10]):
            t += d * SEC
            pts.append(Datapoint(t, float(i)))
        streams = [self._encode_scalar(pts)]
        for chains in CHAINS:
            _assert_batched_matches_scalar(streams, len(pts) + 1, chains)

    def test_xor_contained_uncontained_flips(self):
        """Value sequence engineered to flip between contained and
        uncontained XOR windows and through zero-XOR repeats."""
        vs = [1.5, 1.5, 1.25, 1.2500000001, -1.25, 1.5e300, 1.5e-300,
              0.1, 0.1, 0.30000000000000004, 2.0**52, 1.0]
        pts = [Datapoint(START + (i + 1) * SEC, v)
               for i, v in enumerate(vs)]
        streams = [self._encode_scalar(pts)]
        for chains in CHAINS:
            _assert_batched_matches_scalar(streams, len(pts) + 1, chains)

    def test_int_float_mode_churn(self):
        """int -> float -> int transitions exercise the to-float /
        to-int-update control paths and the multiplier updates."""
        vs = [3.0, 4.0, 4.5, 4.75, 5.0, 6.0, 0.125, 7.0, 7.25, 8.0]
        pts = [Datapoint(START + (i + 1) * SEC, v)
               for i, v in enumerate(vs)]
        streams = [self._encode_scalar(pts)]
        for chains in CHAINS:
            _assert_batched_matches_scalar(streams, len(pts) + 1, chains)

    def test_single_point_and_two_point_streams(self):
        streams = [
            self._encode_scalar([Datapoint(START + SEC, 1.0)]),
            self._encode_scalar([Datapoint(START + SEC, np.nan),
                                 Datapoint(START + 2 * SEC, np.nan)]),
        ]
        for chains in CHAINS:
            _assert_batched_matches_scalar(streams, 4, chains)
