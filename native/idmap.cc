// Batched metric-ID -> slot resolver: the aggregator ingest hot path's
// host half.  The role of the reference's metricMap find-or-create
// (src/aggregator/aggregator/map.go:149) and the shard insert queue's
// series creation: every incoming sample resolves its string ID to a
// dense arena slot.  In Python this is a dict lookup per sample
// (~200-500 ns); here it is one hash probe over a packed batch
// (~40-80 ns), called once per ingest batch through ctypes
// (m3_tpu/native/idmap.py).
//
// Keys are (id bytes, 8-byte aggregation mask) — the same compound key
// the Python MetricMap uses so one metric ID can hold several
// aggregation-key slots.  Slots are dense int32 with a free list;
// capacity is fixed (the device arenas are fixed-size).

#include <cstdint>
#include <cstring>
#include <string>
#include <string_view>
#include <unordered_map>
#include <vector>

namespace {

// Heterogeneous (C++20 transparent) lookup: probes hash a borrowed
// (bytes, mask) view with zero allocation; only INSERTS copy the id
// into an owned key.
struct Key {
  std::string id;
  uint64_t mask;
  bool operator==(const Key&) const = default;
};

struct RefKey {
  std::string_view id;
  uint64_t mask;
};

struct KeyHash {
  using is_transparent = void;
  static size_t mix(std::string_view sv, uint64_t mask) {
    size_t h = std::hash<std::string_view>{}(sv);
    return h ^ (std::hash<uint64_t>{}(mask) + 0x9e3779b97f4a7c15ULL +
                (h << 6) + (h >> 2));
  }
  size_t operator()(const Key& k) const { return mix(k.id, k.mask); }
  size_t operator()(const RefKey& k) const { return mix(k.id, k.mask); }
};

struct KeyEq {
  using is_transparent = void;
  bool operator()(const Key& a, const Key& b) const {
    return a.mask == b.mask && a.id == b.id;
  }
  bool operator()(const RefKey& a, const Key& b) const {
    return a.mask == b.mask && a.id == b.id;
  }
  bool operator()(const Key& a, const RefKey& b) const {
    return a.mask == b.mask && a.id == b.id;
  }
};

struct IdMap {
  std::unordered_map<Key, int32_t, KeyHash, KeyEq> slots;
  std::vector<int32_t> free_list;
  int64_t capacity;
  int64_t next = 0;
};

}  // namespace

extern "C" {

void* idmap_new(int64_t capacity) {
  auto* m = new IdMap;
  m->capacity = capacity;
  m->slots.reserve(static_cast<size_t>(capacity < (1 << 20) ? capacity
                                                            : (1 << 20)));
  return m;
}

void idmap_del(void* h) { delete static_cast<IdMap*>(h); }

int64_t idmap_len(void* h) {
  return static_cast<int64_t>(static_cast<IdMap*>(h)->slots.size());
}

// Resolve a packed batch: ids laid out back-to-back in `buf`,
// `offsets[i]..offsets[i+1]` delimiting id i (n+1 entries).  Fills
// out_slots[n].  Newly-allocated entries are reported via
// out_new_idx (their batch positions); returns the count of new
// entries, or -1 when allocation would exceed capacity (no partial
// allocation is rolled back; callers treat -1 as fatal for the batch).
int64_t idmap_resolve_batch(void* h, const uint8_t* buf,
                            const uint64_t* offsets, int64_t n,
                            uint64_t mask, int32_t* out_slots,
                            int64_t* out_new_idx) {
  auto* m = static_cast<IdMap*>(h);
  int64_t n_new = 0;
  for (int64_t i = 0; i < n; ++i) {
    std::string_view sv(reinterpret_cast<const char*>(buf) + offsets[i],
                        offsets[i + 1] - offsets[i]);
    RefKey ref{sv, mask};
    auto it = m->slots.find(ref);
    if (it != m->slots.end()) {
      out_slots[i] = it->second;
      continue;
    }
    int32_t slot;
    if (!m->free_list.empty()) {
      slot = m->free_list.back();
      m->free_list.pop_back();
    } else if (m->next < m->capacity) {
      slot = static_cast<int32_t>(m->next++);
    } else {
      // Roll back this batch's inserts so the caller's state mirror
      // (which never sees this batch's new entries) stays consistent:
      // the erased slots return through the free list.
      for (int64_t k = 0; k < n_new; ++k) {
        int64_t j = out_new_idx[k];
        std::string_view jsv(
            reinterpret_cast<const char*>(buf) + offsets[j],
            offsets[j + 1] - offsets[j]);
        auto jit = m->slots.find(RefKey{jsv, mask});
        if (jit != m->slots.end()) {
          m->free_list.push_back(jit->second);
          m->slots.erase(jit);
        }
      }
      return -1;
    }
    m->slots.emplace(Key{std::string(sv), mask}, slot);
    out_slots[i] = slot;
    out_new_idx[n_new++] = i;
  }
  return n_new;
}

// Release one (id, mask) entry back to the free list.  Returns 1 when
// the key existed.
int32_t idmap_release(void* h, const uint8_t* id, uint64_t len,
                      uint64_t mask) {
  auto* m = static_cast<IdMap*>(h);
  RefKey ref{std::string_view(reinterpret_cast<const char*>(id), len), mask};
  auto it = m->slots.find(ref);
  if (it == m->slots.end()) return 0;
  m->free_list.push_back(it->second);
  m->slots.erase(it);
  return 1;
}

}  // extern "C"
