// Native host M3TSZ codec: the fast scalar encode/decode path.
//
// C++ port-of-capability of this framework's own scalar codec
// (m3_tpu/encoding/m3tsz.py), which is golden-validated against the
// reference stream format (src/dbnode/encoding/m3tsz/{encoder.go,
// timestamp_encoder.go,float_encoder_iterator.go,int_sig_bits_tracker.go,
// m3tsz.go} and src/dbnode/encoding/scheme.go).  The reference's hot
// scalar loop is Go; ours is this translation unit, loaded via ctypes
// (m3_tpu/native/__init__.py).  It covers fixed-time-unit streams without
// annotations — the overwhelmingly common shape — and reports -2 when it
// meets a stream feature it does not handle so callers fall back to the
// Python oracle.
//
// Bit-exactness contract: byte-identical output to the Python encoder for
// every supported input (tests/test_native.py fuzzes both directions).

#include <cmath>
#include <cstdint>
#include <cstring>
#include <thread>
#include <vector>

namespace {

constexpr int kMarkerOpcode = 0x100;
constexpr int kNumMarkerOpcodeBits = 9;
constexpr int kNumMarkerValueBits = 2;
constexpr int kEndOfStream = 0;

constexpr int kOpcodeZeroSig = 0x0;
constexpr int kOpcodeNonZeroSig = 0x1;
constexpr int kNumSigBits = 6;
constexpr int kOpcodeZeroValueXor = 0x0;
constexpr int kOpcodeContainedValueXor = 0x2;
constexpr int kOpcodeUncontainedValueXor = 0x3;
constexpr int kOpcodeUpdateSig = 0x1;
constexpr int kOpcodeUpdate = 0x0;
constexpr int kOpcodeNoUpdate = 0x1;
constexpr int kOpcodeUpdateMult = 0x1;
constexpr int kOpcodeNoUpdateMult = 0x0;
constexpr int kOpcodeNegative = 0x1;
constexpr int kOpcodeRepeat = 0x1;
constexpr int kOpcodeNoRepeat = 0x0;
constexpr int kOpcodeFloatMode = 0x1;
constexpr int kOpcodeIntMode = 0x0;

constexpr int kSigDiffThreshold = 3;
constexpr int kSigRepeatThreshold = 5;
constexpr int kMaxMult = 6;
constexpr int kNumMultBits = 3;

const double kMaxInt = 9223372036854775808.0;  // 2^63
const double kMinInt = -9223372036854775808.0;
const double kMaxOptInt = 1e13;
const double kMultipliers[] = {1., 1e1, 1e2, 1e3, 1e4, 1e5, 1e6};

int64_t unit_nanos(int unit) {
  switch (unit) {
    case 1: return 1000000000LL;        // SECOND
    case 2: return 1000000LL;           // MILLISECOND
    case 3: return 1000LL;              // MICROSECOND
    case 4: return 1LL;                 // NANOSECOND
    case 5: return 60LL * 1000000000LL;
    case 6: return 3600LL * 1000000000LL;
    case 7: return 86400LL * 1000000000LL;
    case 8: return 365LL * 86400LL * 1000000000LL;
    default: return 0;
  }
}

// Default dod bucket schemes (encoding/scheme.go:42-52): buckets
// 10+7bit, 110+9bit, 1110+12bit, default 1111 + 32 or 64 bits.
struct Scheme {
  int default_bits;  // 32 (s, ms) or 64 (us, ns)
};

bool scheme_for_unit(int unit, Scheme* out) {
  if (unit == 1 || unit == 2) { out->default_bits = 32; return true; }
  if (unit == 3 || unit == 4) { out->default_bits = 64; return true; }
  return false;
}

constexpr int kBucketBits[3] = {7, 9, 12};

struct OStream {
  std::vector<uint8_t> buf;
  int pos = 8;  // bits used in final byte (1..8)

  void write_bits(uint64_t v, int n) {
    if (n <= 0) return;
    if (n < 64) v &= (1ULL << n) - 1;
    while (n > 0) {
      if (pos == 8) { buf.push_back(0); pos = 0; }
      int take = 8 - pos;
      if (take > n) take = n;
      uint8_t chunk = (uint8_t)((v >> (n - take)) & ((1U << take) - 1));
      buf.back() |= (uint8_t)(chunk << (8 - pos - take));
      pos += take;
      n -= take;
    }
  }
  void write_bit(int v) { write_bits((uint64_t)(v & 1), 1); }
};

struct IStream {
  const uint8_t* data;
  int64_t nbits;
  int64_t bitpos = 0;
  bool eof = false;

  uint64_t peek(int n) {
    // caller checked bounds; an unaligned 64-bit read spans 9 bytes, so
    // accumulate in 128 bits
    int64_t start = bitpos, end = bitpos + n;
    int64_t fb = start >> 3, lb = (end + 7) >> 3;
    unsigned __int128 word = 0;
    for (int64_t i = fb; i < lb; i++) word = (word << 8) | data[i];
    int tail = (int)((lb << 3) - end);
    word >>= tail;
    uint64_t out = (uint64_t)word;
    if (n < 64) out &= (1ULL << n) - 1;
    return out;
  }
  uint64_t read(int n) {
    if (n == 0) return 0;
    if (bitpos + n > nbits) { eof = true; return 0; }
    uint64_t v = peek(n);
    bitpos += n;
    return v;
  }
  bool can(int n) const { return bitpos + n <= nbits; }
};

// Buffered bit reader for the batched path: maintains a 64-bit window
// of upcoming bits so a field read is usually two shifts, with ONE
// unaligned 8-byte refill per ~56 consumed bits (vs IStream's byte
// loop per field).  Requires >= 16 readable bytes past the stream end
// (refill loads 8 bytes at the current byte position, which can sit at
// the last stream byte; the ctypes binding pads the batch buffer).
struct BufferedIStream {
  static_assert(__BYTE_ORDER__ == __ORDER_LITTLE_ENDIAN__,
                "load+bswap word reads assume a little-endian host");
  const uint8_t* data;
  int64_t nbits;
  int64_t bitpos = 0;
  bool eof = false;
  uint64_t buf = 0;   // upcoming bits, left-aligned (MSB first)
  int avail = 0;      // valid bits in buf

  inline void refill() {
    // reload the full window at the current position: byte-aligned load
    // of 8 bytes starting at bitpos>>3, discard the sub-byte offset
    int64_t byte = bitpos >> 3;
    int off = (int)(bitpos & 7);
    uint64_t w;
    std::memcpy(&w, data + byte, 8);
    w = __builtin_bswap64(w);
    buf = w << off;
    avail = 64 - off;
  }

  uint64_t peek(int n) {  // n <= 56: refill guarantees >= 57 bits
    if (n > avail) refill();
    return buf >> (64 - n);
  }
  uint64_t read(int n) {
    if (n == 0) return 0;
    if (bitpos + n > nbits) { eof = true; return 0; }
    if (n > 56) {
      // A refill at byte offset 7 yields only 57 valid bits, so wide
      // reads (57..64, e.g. full XOR windows and 64-bit dods) split
      // into two halves of <= 32 bits each; also dodges the n==64
      // shift UB.
      int half = n / 2;
      uint64_t hi = read_small(half);
      uint64_t lo = read_small(n - half);
      return (hi << (n - half)) | lo;
    }
    return read_small(n);
  }
  inline uint64_t read_small(int n) {  // n in [1, 56]
    if (n > avail) refill();
    uint64_t v = buf >> (64 - n);
    buf <<= n;
    avail -= n;
    bitpos += n;
    return v;
  }
  bool can(int n) const { return bitpos + n <= nbits; }
};

// Run fn(lo, hi) over [0, B) split across up to nthreads OS threads.
template <typename Fn>
void parallel_for(long B, int nthreads, Fn fn) {
  if (nthreads <= 1 || B <= 1) { fn(0, B); return; }
  if (nthreads > B) nthreads = (int)B;
  std::vector<std::thread> pool;
  long chunk = (B + nthreads - 1) / nthreads;
  for (int t = 0; t < nthreads; t++) {
    long lo = t * chunk, hi = lo + chunk < B ? lo + chunk : B;
    if (lo >= hi) break;
    pool.emplace_back([=] { fn(lo, hi); });
  }
  for (auto& th : pool) th.join();
}

inline int num_sig(uint64_t v) { return v ? 64 - __builtin_clzll(v) : 0; }

inline void lead_trail(uint64_t v, int* lead, int* trail) {
  if (v == 0) { *lead = 64; *trail = 0; return; }
  *lead = __builtin_clzll(v);
  *trail = __builtin_ctzll(v);
}

inline uint64_t f2b(double v) { uint64_t b; std::memcpy(&b, &v, 8); return b; }
inline double b2f(uint64_t b) { double v; std::memcpy(&v, &b, 8); return v; }

// Go's uint64(int64(val)): cvttsd2si semantics (m3tsz.py
// _float_to_uint64_via_int64).
inline uint64_t f2u_via_i64(double val) {
  if (std::isnan(val) || val >= kMaxInt || val < kMinInt) return 1ULL << 63;
  return (uint64_t)(int64_t)val;
}

// float -> (scaled int, multiplier, is_float); reference m3tsz.go:78-118.
void convert_to_int_float(double v, int cur_max_mult, double* out_val,
                          int* out_mult, bool* out_is_float) {
  if (cur_max_mult == 0 && v < kMaxInt) {
    double r = std::fmod(v, 1.0);
    if (r == 0) { *out_val = v - r; *out_mult = 0; *out_is_float = false; return; }
  }
  double val = v * kMultipliers[cur_max_mult];
  double sign = 1.0;
  if (v < 0) { sign = -1.0; val = -val; }
  int mult = cur_max_mult;
  while (mult <= kMaxMult && val < kMaxOptInt) {
    double i;
    double r = std::modf(val, &i);
    if (r == 0) { *out_val = sign * i; *out_mult = mult; *out_is_float = false; return; }
    if (r < 0.1) {
      if (std::nextafter(val, 0.0) <= i) {
        *out_val = sign * i; *out_mult = mult; *out_is_float = false; return;
      }
    } else if (r > 0.9) {
      double nxt = i + 1;
      if (std::nextafter(val, nxt) >= nxt) {
        *out_val = sign * nxt; *out_mult = mult; *out_is_float = false; return;
      }
    }
    val *= 10.0;
    mult += 1;
  }
  *out_val = v; *out_mult = 0; *out_is_float = true;
}

struct FloatXOR {
  uint64_t prev_xor = 0, prev_bits = 0;

  void write_full(OStream& os, uint64_t bits) {
    prev_bits = bits; prev_xor = bits;
    os.write_bits(bits, 64);
  }
  void write_next(OStream& os, uint64_t bits) {
    uint64_t x = prev_bits ^ bits;
    if (x == 0) {
      os.write_bits(kOpcodeZeroValueXor, 1);
    } else {
      int pl, pt, cl, ct;
      lead_trail(prev_xor, &pl, &pt);
      lead_trail(x, &cl, &ct);
      if (cl >= pl && ct >= pt) {
        os.write_bits(kOpcodeContainedValueXor, 2);
        os.write_bits(x >> pt, 64 - pl - pt);
      } else {
        os.write_bits(kOpcodeUncontainedValueXor, 2);
        os.write_bits((uint64_t)cl, 6);
        int nm = 64 - cl - ct;
        os.write_bits((uint64_t)(nm - 1), 6);
        os.write_bits(x >> ct, nm);
      }
    }
    prev_xor = x; prev_bits = bits;
  }
  template <typename IS>
  void read_full(IS& is) {
    prev_bits = is.read(64); prev_xor = prev_bits;
  }
  template <typename IS>
  void read_next(IS& is) {
    uint64_t cb = is.read(1);
    if (cb == kOpcodeZeroValueXor) { prev_xor = 0; return; }
    cb = (cb << 1) | is.read(1);
    if (cb == kOpcodeContainedValueXor) {
      int pl, pt;
      lead_trail(prev_xor, &pl, &pt);
      int nm = 64 - pl - pt;
      uint64_t bits = is.read(nm);
      prev_xor = bits << pt;
      prev_bits ^= prev_xor;
      return;
    }
    uint64_t packed = is.read(12);
    int nl = (int)((packed >> 6) & 0x3F);
    int nm = (int)(packed & 0x3F) + 1;
    uint64_t bits = is.read(nm);
    int nt = 64 - nl - nm;
    prev_xor = bits << nt;
    prev_bits ^= prev_xor;
  }
};

struct SigTracker {
  int sig = 0, cur_highest_lower = 0, num_lower = 0;

  void write_diff(OStream& os, uint64_t bits, bool neg) {
    os.write_bit(neg ? kOpcodeNegative : 0);
    if (sig < 64 && sig > 0) bits &= (1ULL << sig) - 1;
    os.write_bits(bits, sig);
  }
  void write_sig(OStream& os, int s) {
    if (sig != s) {
      os.write_bit(kOpcodeUpdateSig);
      if (s == 0) {
        os.write_bit(kOpcodeZeroSig);
      } else {
        os.write_bit(kOpcodeNonZeroSig);
        os.write_bits((uint64_t)(s - 1), kNumSigBits);
      }
    } else {
      os.write_bit(0);
    }
    sig = s;
  }
  int track(int s) {
    int ns = sig;
    if (s > sig) {
      ns = s;
    } else if (sig - s >= kSigDiffThreshold) {
      if (num_lower == 0) cur_highest_lower = s;
      else if (s > cur_highest_lower) cur_highest_lower = s;
      if (++num_lower >= kSigRepeatThreshold) {
        ns = cur_highest_lower;
        num_lower = 0;
      }
    } else {
      num_lower = 0;
    }
    return ns;
  }
};

void write_dod_bucketed(OStream& os, int64_t dod, int default_bits) {
  if (dod == 0) { os.write_bits(0, 1); return; }
  int opcode = 0, opcode_bits = 1;
  for (int i = 0; i < 3; i++) {
    opcode = (1 << (i + 1)) | opcode;
    opcode_bits += 1;
    int nbits = kBucketBits[i];
    int64_t lo = -(1LL << (nbits - 1)), hi = (1LL << (nbits - 1)) - 1;
    if (dod >= lo && dod <= hi) {
      os.write_bits((uint64_t)opcode, opcode_bits);
      os.write_bits((uint64_t)dod & ((1ULL << nbits) - 1), nbits);
      return;
    }
  }
  os.write_bits((uint64_t)(opcode | 1), opcode_bits);
  if (default_bits < 64)
    os.write_bits((uint64_t)dod & ((1ULL << default_bits) - 1), default_bits);
  else
    os.write_bits((uint64_t)dod, 64);
}

inline int64_t sign_extend(uint64_t v, int n) {
  uint64_t sb = 1ULL << (n - 1);
  return (int64_t)((v ^ sb) - sb);
}

}  // namespace

extern "C" {

// Encode n datapoints; returns bytes written, -1 on small buffer, -2 on
// unsupported input (caller falls back to the Python codec).
long m3tsz_encode(const int64_t* ts, const double* vals, long n,
                  int64_t start, int unit, uint8_t* out, long out_cap) {
  Scheme scheme;
  if (!scheme_for_unit(unit, &scheme)) return -2;
  int64_t u_nanos = unit_nanos(unit);
  if (n <= 0) return 0;
  // initial_time_unit (timestamp_encoder.go:248-259): misaligned start
  // would need a time-unit marker mid-stream — Python path handles it.
  if (start % u_nanos != 0) return -2;

  OStream os;
  FloatXOR fx;
  SigTracker st;
  double int_val = 0.0;
  int max_mult = 0;
  bool is_float = false;
  int64_t prev_time = start, prev_delta = 0;

  for (long k = 0; k < n; k++) {
    // -- timestamp (timestamp_encoder.go:72-246) --
    if (k == 0) os.write_bits((uint64_t)prev_time, 64);
    int64_t delta = ts[k] - prev_time;
    prev_time = ts[k];
    int64_t dod_n = delta - prev_delta;
    int64_t dod = dod_n >= 0 ? dod_n / u_nanos : -((-dod_n) / u_nanos);
    // Sub-unit precision needs a time-unit switch (markers) — the
    // Python codec's path.  Truncating here would silently round the
    // timestamp (the round-4 flush-precision bug).
    if (dod * u_nanos != dod_n) return -2;
    if (scheme.default_bits == 32 && (dod < -(1LL << 31) || dod >= (1LL << 31)))
      return -2;  // overflow error in the reference
    write_dod_bucketed(os, dod, scheme.default_bits);
    prev_delta = delta;

    // -- value (encoder.go:112-250) --
    double v = vals[k];
    if (k == 0) {
      double val; int mult; bool isf;
      convert_to_int_float(v, 0, &val, &mult, &isf);
      if (isf) {
        os.write_bit(kOpcodeFloatMode);
        fx.write_full(os, f2b(v));
        is_float = true;
        max_mult = mult;
      } else {
        os.write_bit(kOpcodeIntMode);
        int_val = val;
        bool neg_diff = true;
        if (val < 0) { neg_diff = false; val = -val; }
        uint64_t vb = f2u_via_i64(val);
        int sig = num_sig(vb);
        // _write_int_sig_mult(sig, mult, false)
        st.write_sig(os, sig);
        if (mult > max_mult) {
          os.write_bit(kOpcodeUpdateMult);
          os.write_bits((uint64_t)mult, kNumMultBits);
          max_mult = mult;
        } else {
          os.write_bit(kOpcodeNoUpdateMult);
        }
        st.write_diff(os, vb, neg_diff);
      }
    } else {
      double val; int mult; bool isf;
      convert_to_int_float(v, max_mult, &val, &mult, &isf);
      double val_diff = 0.0;
      if (!isf) val_diff = int_val - val;
      if (isf || val_diff >= kMaxInt || val_diff <= kMinInt) {
        // _write_float_val
        uint64_t bits = f2b(val);
        if (!is_float) {
          os.write_bit(kOpcodeUpdate);
          os.write_bit(kOpcodeNoRepeat);
          os.write_bit(kOpcodeFloatMode);
          fx.write_full(os, bits);
          is_float = true;
          max_mult = mult;
        } else if (bits == fx.prev_bits) {
          os.write_bit(kOpcodeUpdate);
          os.write_bit(kOpcodeRepeat);
        } else {
          os.write_bit(kOpcodeNoUpdate);
          fx.write_next(os, bits);
        }
      } else {
        // _write_int_val
        if (val_diff == 0 && isf == is_float && mult == max_mult) {
          os.write_bit(kOpcodeUpdate);
          os.write_bit(kOpcodeRepeat);
        } else {
          bool neg = false;
          double vd = val_diff;
          if (vd < 0) { neg = true; vd = -vd; }
          uint64_t diff_bits = (uint64_t)vd;
          int sig = num_sig(diff_bits);
          int new_sig = st.track(sig);
          bool float_changed = isf != is_float;
          if (mult > max_mult || st.sig != new_sig || float_changed) {
            os.write_bit(kOpcodeUpdate);
            os.write_bit(kOpcodeNoRepeat);
            os.write_bit(kOpcodeIntMode);
            // _write_int_sig_mult(new_sig, mult, float_changed)
            st.write_sig(os, new_sig);
            if (mult > max_mult) {
              os.write_bit(kOpcodeUpdateMult);
              os.write_bits((uint64_t)mult, kNumMultBits);
              max_mult = mult;
            } else if (st.sig == new_sig && max_mult == mult && float_changed) {
              os.write_bit(kOpcodeUpdateMult);
              os.write_bits((uint64_t)max_mult, kNumMultBits);
            } else {
              os.write_bit(kOpcodeNoUpdateMult);
            }
            st.write_diff(os, diff_bits, neg);
            is_float = false;
          } else {
            os.write_bit(kOpcodeNoUpdate);
            st.write_diff(os, diff_bits, neg);
          }
          int_val = val;
        }
      }
    }
  }

  // Finalize: head bytes + tail (last byte's used bits + EOS marker).
  if (os.buf.empty()) return 0;
  OStream tail;
  tail.write_bits((uint64_t)(os.buf.back() >> (8 - os.pos)), os.pos);
  tail.write_bits(kMarkerOpcode, kNumMarkerOpcodeBits);
  tail.write_bits(kEndOfStream, kNumMarkerValueBits);
  long total = (long)(os.buf.size() - 1 + tail.buf.size());
  if (total > out_cap) return -1;
  std::memcpy(out, os.buf.data(), os.buf.size() - 1);
  std::memcpy(out + os.buf.size() - 1, tail.buf.data(), tail.buf.size());
  return total;
}

}  // extern "C"

// Decode a stream; returns count, -1 on small buffer, -2 unsupported
// (annotation/time-unit markers), -3 corrupt.  Trace pointers may be null.
template <typename IS>
static long decode_impl(const uint8_t* data, long nbytes, int default_unit,
                        int64_t* out_ts, double* out_vals, uint8_t* out_isf,
                        uint8_t* out_sig, uint8_t* out_mult,
                        double* out_intval, long cap) {
  if (nbytes == 0) return 0;
  IS is{data, (int64_t)nbytes * 8};
  Scheme scheme;

  int64_t prev_time = 0, prev_delta = 0;
  int unit = 0;
  FloatXOR fx;
  double int_val = 0.0;
  int mult = 0, sig = 0;
  bool is_float = false;
  long count = 0;

  for (;;) {
    bool first = (prev_time == 0);
    int64_t nt = 0;
    if (first) {
      nt = sign_extend(is.read(64), 64);
      if (is.eof) return -3;
      int64_t u_nanos = unit_nanos(default_unit);
      unit = (u_nanos != 0 && nt % u_nanos == 0) ? default_unit : 0;
    }
    // marker check (11 bits)
    if (is.can(kNumMarkerOpcodeBits + kNumMarkerValueBits)) {
      uint64_t peek = is.peek(kNumMarkerOpcodeBits + kNumMarkerValueBits);
      if ((peek >> kNumMarkerValueBits) == kMarkerOpcode) {
        int marker = (int)(peek & 0x3);
        if (marker == kEndOfStream) return count;
        return -2;  // annotation / time-unit change: python fallback
      }
    }
    if (!scheme_for_unit(unit, &scheme)) return -2;
    int64_t u_nanos = unit_nanos(unit);
    // dod
    int64_t dod;
    uint64_t cb = is.read(1);
    if (cb == 0) {
      dod = 0;
    } else {
      int opcode = 1;
      int matched = -1;
      for (int i = 0; i < 3; i++) {
        cb = (cb << 1) | is.read(1);
        opcode = (opcode << 1);
        uint64_t want = ((1ULL << (i + 2)) - 2);  // 10, 110, 1110 pattern
        if (cb == want) { matched = i; break; }
      }
      if (matched >= 0) {
        int nbits = kBucketBits[matched];
        dod = sign_extend(is.read(nbits), nbits) * u_nanos;
      } else {
        int nbits = scheme.default_bits;
        dod = sign_extend(is.read(nbits), nbits) * u_nanos;
      }
    }
    if (is.eof) return -3;
    prev_delta += dod;
    prev_time = first ? nt + prev_delta : prev_time + prev_delta;

    // value
    if (first) {
      if (is.read(1) == kOpcodeFloatMode) {
        fx.read_full(is);
        is_float = true;
      } else {
        // _read_int_sig_mult + diff
        if (is.read(1) == kOpcodeUpdateSig) {
          if (is.read(1) == kOpcodeZeroSig) sig = 0;
          else sig = (int)is.read(kNumSigBits) + 1;
        }
        if (is.read(1) == kOpcodeUpdateMult) {
          mult = (int)is.read(kNumMultBits);
          if (mult > kMaxMult) return -3;
        }
        goto read_diff;
      }
    } else {
      if (is.read(1) == kOpcodeUpdate) {
        if (is.read(1) == kOpcodeRepeat) goto emit;
        if (is.read(1) == kOpcodeFloatMode) {
          fx.read_full(is);
          is_float = true;
        } else {
          if (is.read(1) == kOpcodeUpdateSig) {
            if (is.read(1) == kOpcodeZeroSig) sig = 0;
            else sig = (int)is.read(kNumSigBits) + 1;
          }
          if (is.read(1) == kOpcodeUpdateMult) {
            mult = (int)is.read(kNumMultBits);
            if (mult > kMaxMult) return -3;
          }
          is_float = false;
          goto read_diff;
        }
      } else if (is_float) {
        fx.read_next(is);
      } else {
        goto read_diff;
      }
    }
    goto emit;

  read_diff:
    if (sig == 64) {
      double sgn = is.read(1) == kOpcodeNegative ? 1.0 : -1.0;
      int_val += sgn * (double)is.read(64);
    } else {
      uint64_t bits = is.read(sig + 1);
      double sgn = -1.0;
      if ((bits >> sig) == kOpcodeNegative) {
        sgn = 1.0;
        bits ^= 1ULL << sig;
      }
      int_val += sgn * (double)bits;
    }

  emit:
    if (is.eof) return -3;
    if (count >= cap) return -1;
    out_ts[count] = prev_time;
    out_vals[count] = is_float ? b2f(fx.prev_bits)
                               : (mult == 0 ? int_val : int_val / kMultipliers[mult]);
    if (out_isf) out_isf[count] = is_float ? 1 : 0;
    if (out_sig) out_sig[count] = (uint8_t)sig;
    if (out_mult) out_mult[count] = (uint8_t)mult;
    if (out_intval) out_intval[count] = int_val;
    count++;
  }
}

extern "C" long m3tsz_decode(const uint8_t* data, long nbytes, int default_unit,
                             int64_t* out_ts, double* out_vals, long cap) {
  return decode_impl<IStream>(data, nbytes, default_unit, out_ts, out_vals,
                              nullptr, nullptr, nullptr, nullptr, cap);
}

// Debug trace: per-element (is_float, sig, mult, int_val) for parity
// triage against the Python oracle.  Not part of the public surface.
extern "C" long m3tsz_decode_trace(const uint8_t* data, long nbytes,
                                   int default_unit, int64_t* out_ts,
                                   double* out_vals, uint8_t* out_isf,
                                   uint8_t* out_sig, uint8_t* out_mult,
                                   double* out_intval, long cap) {
  return decode_impl<IStream>(data, nbytes, default_unit, out_ts, out_vals,
                              out_isf, out_sig, out_mult, out_intval, cap);
}

// Batched decode: B streams concatenated in `data` at
// [offsets[i], offsets[i+1]) byte ranges.  The buffer MUST stay readable
// for >= 16 bytes past offsets[B] (BufferedIStream refills with 8-byte
// loads at arbitrary byte positions); the Python binding pads.  Series i's datapoints land in
// out_ts/out_vals[i*max_points ...]; counts[i] gets the datapoint count
// or the negative status (-1 cap, -2 unsupported, -3 corrupt).  Returns
// the number of series with negative status.  `nthreads` <= 1 runs
// inline; more splits series ranges across OS threads (the batch is
// embarrassingly parallel).
extern "C" long m3tsz_decode_batch(const uint8_t* data, const int64_t* offsets,
                                   long B, int default_unit, int64_t* out_ts,
                                   double* out_vals, long max_points,
                                   int64_t* counts, int nthreads) {
  parallel_for(B, nthreads, [=](long lo, long hi) {
    for (long i = lo; i < hi; i++) {
      counts[i] = decode_impl<BufferedIStream>(
          data + offsets[i], offsets[i + 1] - offsets[i], default_unit,
          out_ts + i * max_points, out_vals + i * max_points, nullptr,
          nullptr, nullptr, nullptr, max_points);
    }
  });
  long bad = 0;
  for (long i = 0; i < B; i++) bad += counts[i] < 0;
  return bad;
}

// Batched encode: series i is ts/vals[i*T .. i*T+ns[i]) started at
// starts[i]; its stream is written at out[i*stride] and lens[i] gets the
// byte length or negative status (-1 stride too small, -2 unsupported —
// callers fall back per series).  Returns the number of negative lens.
extern "C" long m3tsz_encode_batch(const int64_t* ts, const double* vals,
                                   const int64_t* ns, long B, long T,
                                   const int64_t* starts, int unit,
                                   uint8_t* out, long stride, int64_t* lens,
                                   int nthreads) {
  parallel_for(B, nthreads, [=](long lo, long hi) {
    for (long i = lo; i < hi; i++) {
      lens[i] = m3tsz_encode(ts + i * T, vals + i * T, ns[i], starts[i], unit,
                             out + i * stride, stride);
    }
  });
  long bad = 0;
  for (long i = 0; i < B; i++) bad += lens[i] < 0;
  return bad;
}
