// Single-core host proxy of the reference aggregator's ingest hot loop,
// used by bench.py to put a measured baseline under BASELINE configs
// #3 (1M-series counter/gauge rollup) and #4 (timer p50/95/99 quantiles)
// on this machine.  No Go toolchain ships in this image, so the Go
// engine cannot be benchmarked directly; this proxy re-creates the
// reference's per-sample work (src/aggregator/aggregation/counter.go:53,
// gauge.go:53, timer.go:55 + quantile/cm/stream.go:78 AddBatch) under
// conditions deliberately GENEROUS to the baseline:
//
//   * dense slot-indexed struct arrays stand in for the reference's
//     metricMap find-or-create + per-entry mutex (map.go:149,
//     entry.go:264) — a real Go aggregator pays hashing, pointer
//     chasing and lock traffic this proxy does not;
//   * timers append to flat per-ID sample vectors and flush with one
//     sort per ID — cheaper than the CM stream's cursor insert +
//     periodic compress;
//   * everything runs on one core with no scheduler or channel costs.
//
// The measured samples/s is therefore an UPPER BOUND on the single-core
// Go path; the device/baseline ratios bench.py reports are conservative.
//
// Exposed via ctypes (m3_tpu/native/aggproxy.py).

#include <cstdint>
#include <cstring>
#include <algorithm>
#include <cmath>
#include <vector>

extern "C" {

// ---------------------------------------------------------------------------
// Counter rollup: per-sample update of (sum, sum_sq, count, max, min).
// Returns a checksum so the work cannot be dead-code eliminated.
// ---------------------------------------------------------------------------

struct CounterCell {
  int64_t sum, sum_sq, count, max, min;
};

int64_t agg_counter_ingest(const uint32_t* ids, const int64_t* values,
                           int64_t n, int64_t capacity, void* cells_raw) {
  auto* cells = static_cast<CounterCell*>(cells_raw);
  for (int64_t i = 0; i < n; ++i) {
    CounterCell& c = cells[ids[i]];
    int64_t v = values[i];
    c.sum += v;
    c.sum_sq += v * v;
    c.count += 1;
    if (v > c.max) c.max = v;
    if (v < c.min) c.min = v;
  }
  int64_t acc = 0;
  for (int64_t s = 0; s < capacity; ++s) acc += cells[s].sum + cells[s].count;
  return acc;
}

void* agg_counter_new(int64_t capacity) {
  auto* cells = new CounterCell[capacity];
  for (int64_t i = 0; i < capacity; ++i) {
    cells[i] = {0, 0, 0, INT64_MIN, INT64_MAX};
  }
  return cells;
}

void agg_counter_free(void* cells) { delete[] static_cast<CounterCell*>(cells); }

// ---------------------------------------------------------------------------
// Gauge rollup: last/sum/sum_sq/count/max/min with timestamped last-wins.
// ---------------------------------------------------------------------------

struct GaugeCell {
  double last, sum, sum_sq, max, min;
  int64_t count, last_t;
};

double agg_gauge_ingest(const uint32_t* ids, const double* values,
                        const int64_t* times, int64_t n, int64_t capacity,
                        void* cells_raw) {
  auto* cells = static_cast<GaugeCell*>(cells_raw);
  for (int64_t i = 0; i < n; ++i) {
    GaugeCell& c = cells[ids[i]];
    double v = values[i];
    if (times[i] > c.last_t) {
      c.last_t = times[i];
      c.last = v;
    }
    c.sum += v;
    c.sum_sq += v * v;
    c.count += 1;
    if (v > c.max) c.max = v;
    if (v < c.min) c.min = v;
  }
  double acc = 0;
  for (int64_t s = 0; s < capacity; ++s) acc += cells[s].sum + cells[s].last;
  return acc;
}

void* agg_gauge_new(int64_t capacity) {
  auto* cells = new GaugeCell[capacity];
  for (int64_t i = 0; i < capacity; ++i) {
    cells[i] = {0.0, 0.0, 0.0, -HUGE_VAL, HUGE_VAL, 0, INT64_MIN};
  }
  return cells;
}

void agg_gauge_free(void* cells) { delete[] static_cast<GaugeCell*>(cells); }

// ---------------------------------------------------------------------------
// Timer quantiles: append samples per ID, flush = sort + rank reads at
// ceil(q*n) (the rank the CM stream approximates within eps:
// reference quantile/cm/stream.go:239-247).
// ---------------------------------------------------------------------------

struct TimerArena {
  std::vector<std::vector<double>> samples;
  std::vector<double> sum;
  std::vector<int64_t> count;
};

void* agg_timer_new(int64_t capacity) {
  auto* a = new TimerArena;
  a->samples.resize(capacity);
  a->sum.assign(capacity, 0.0);
  a->count.assign(capacity, 0);
  return a;
}

void agg_timer_free(void* arena) { delete static_cast<TimerArena*>(arena); }

void agg_timer_ingest(const uint32_t* ids, const double* values, int64_t n,
                      void* arena_raw) {
  auto* a = static_cast<TimerArena*>(arena_raw);
  for (int64_t i = 0; i < n; ++i) {
    uint32_t id = ids[i];
    a->samples[id].push_back(values[i]);
    a->sum[id] += values[i];
    a->count[id] += 1;
  }
}

// Flush all IDs: write (p_q0, p_q1, ..., mean) per ID into out
// (capacity x (nq + 1)), returns total samples flushed.
int64_t agg_timer_flush(void* arena_raw, const double* qs, int64_t nq,
                        double* out) {
  auto* a = static_cast<TimerArena*>(arena_raw);
  int64_t total = 0;
  int64_t capacity = static_cast<int64_t>(a->samples.size());
  for (int64_t id = 0; id < capacity; ++id) {
    auto& v = a->samples[id];
    double* row = out + id * (nq + 1);
    if (v.empty()) {
      for (int64_t q = 0; q <= nq; ++q) row[q] = 0.0;
      continue;
    }
    std::sort(v.begin(), v.end());
    int64_t sz = static_cast<int64_t>(v.size());
    for (int64_t q = 0; q < nq; ++q) {
      int64_t rank = static_cast<int64_t>(std::ceil(qs[q] * sz)) - 1;
      if (rank < 0) rank = 0;
      if (rank >= sz) rank = sz - 1;
      row[q] = v[rank];
    }
    row[nq] = a->sum[id] / static_cast<double>(sz);
    total += sz;
  }
  return total;
}

}  // extern "C"
