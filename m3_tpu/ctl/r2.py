"""R2: the rules-management service (HTTP CRUD over versioned rulesets).

Equivalent of the reference's r2/ctl service (`src/ctl` — an HTTP API
for editing mapping/rollup rules with versioning, backing the rules UI;
rules live in KV and the matcher watches them,
`src/metrics/rules/store`).  Endpoints:

    GET    /api/v1/rules                       list namespaces
    GET    /api/v1/rules/<namespace>           fetch ruleset (with version)
    PUT    /api/v1/rules/<namespace>           replace ruleset; body must
                                               carry the expected current
                                               version (optimistic CAS —
                                               conflicting editors get 409)
    DELETE /api/v1/rules/<namespace>           tombstone the namespace

Downstream consumers (the coordinator downsampler's matcher) watch the
same KV key and hot-reload on version change.
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from m3_tpu.cluster.kv import KVStore
from m3_tpu.metrics.rules import RuleSet
from m3_tpu.metrics.rules_json import ruleset_from_json, ruleset_to_json

KEY_PREFIX = "rules/"


class RulesStore:
    """Versioned ruleset storage over KV (reference rules/store/kv)."""

    def __init__(self, kv: KVStore):
        self.kv = kv

    def _key(self, namespace: str) -> str:
        return KEY_PREFIX + namespace

    def namespaces(self) -> list[str]:
        return sorted(
            k[len(KEY_PREFIX):] for k in self.kv.keys()
            if k.startswith(KEY_PREFIX) and self.get(k[len(KEY_PREFIX):])
        )

    def get(self, namespace: str) -> RuleSet | None:
        """None for absent AND tombstoned namespaces."""
        vv = self.kv.get(self._key(namespace))
        if vv is None:
            return None
        doc = json.loads(vv.data)
        if doc.get("tombstoned"):
            return None
        rs = ruleset_from_json(doc)
        rs.version = vv.version
        return rs

    def set(self, namespace: str, rs: RuleSet,
            expected_version: int | None) -> RuleSet:
        """CAS update: expected_version None means create-only.  Both
        paths use the KV store's atomic primitives — a racing create or
        interleaved write surfaces as VersionConflict, never a silent
        overwrite."""
        data = json.dumps(ruleset_to_json(rs)).encode()
        key = self._key(namespace)
        try:
            if expected_version is None:
                cur = self.kv.get(key)
                if cur is not None and json.loads(cur.data).get("tombstoned"):
                    # recreating a tombstoned namespace continues its
                    # version history
                    new_version = self.kv.check_and_set(key, cur.version, data)
                else:
                    new_version = self.kv.set_if_not_exists(key, data)
            else:
                new_version = self.kv.check_and_set(key, expected_version, data)
        except (KeyError, ValueError) as e:
            raise VersionConflict(str(e)) from None
        rs.version = new_version
        return rs

    def delete(self, namespace: str) -> bool:
        """Tombstone, not hard delete: watchers must observe the removal
        (KV only notifies on set), and the version history survives —
        the reference tombstones rules the same way."""
        key = self._key(namespace)
        if self.get(namespace) is None:
            return False
        self.kv.set(key, json.dumps(
            {"namespace": namespace, "tombstoned": True}
        ).encode())
        return True

    def watch(self, namespace: str, fn) -> None:
        self.kv.watch(self._key(namespace), fn)


class VersionConflict(RuntimeError):
    pass


class _R2Handler(BaseHTTPRequestHandler):
    store: RulesStore = None

    def log_message(self, fmt, *args):
        pass

    def _json(self, code: int, obj) -> None:
        data = json.dumps(obj).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(data)))
        self.end_headers()
        self.wfile.write(data)

    def _ns(self) -> str | None:
        parts = self.path.split("?")[0].strip("/").split("/")
        # api/v1/rules[/<ns>]
        if parts[:3] != ["api", "v1", "rules"]:
            return None
        return parts[3] if len(parts) > 3 else ""

    def do_GET(self):
        ns = self._ns()
        if ns is None:
            return self._json(404, {"error": "unknown path"})
        if ns == "":
            return self._json(200, {"namespaces": self.store.namespaces()})
        rs = self.store.get(ns)
        if rs is None:
            return self._json(404, {"error": f"no rules for {ns}"})
        return self._json(200, ruleset_to_json(rs))

    def do_PUT(self):
        ns = self._ns()
        if not ns:
            return self._json(404, {"error": "namespace required"})
        try:
            body = json.loads(
                self.rfile.read(int(self.headers.get("Content-Length", 0)))
            )
            rs = ruleset_from_json(body)
            rs.namespace = ns
            expected = body.get("expected_version")
            out = self.store.set(ns, rs, expected)
        except VersionConflict as e:
            return self._json(409, {"error": str(e)})
        except (ValueError, KeyError, AttributeError, TypeError) as e:
            # malformed documents (non-dict body, wrongly-typed fields)
            # must be a 400, not a dropped connection
            return self._json(400, {"error": f"bad ruleset: {e}"})
        return self._json(200, ruleset_to_json(out))

    def do_DELETE(self):
        ns = self._ns()
        if not ns:
            return self._json(404, {"error": "namespace required"})
        if not self.store.delete(ns):
            return self._json(404, {"error": f"no rules for {ns}"})
        return self._json(200, {"deleted": ns})


def serve_r2_background(store: RulesStore, host: str = "127.0.0.1",
                        port: int = 0) -> ThreadingHTTPServer:
    handler = type("BoundR2", (_R2Handler,), {"store": store})
    srv = ThreadingHTTPServer((host, port), handler)
    t = threading.Thread(target=srv.serve_forever, daemon=True)
    t.start()
    return srv
