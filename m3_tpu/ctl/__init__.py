"""Rules management service (reference `src/ctl` — r2)."""
