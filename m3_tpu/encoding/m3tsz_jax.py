"""Batched M3TSZ encode/decode as JAX array programs.

The reference codec is an inherently sequential per-series bit-stream
state machine (``src/dbnode/encoding/m3tsz/encoder.go``,
``iterator.go``).  The TPU-native formulation:

* **Encode** — ``lax.scan`` over timesteps carrying the codec state
  (timestamp delta, XOR window, sig-bit tracker), ``vmap``'d across the
  series axis.  Each step emits a fixed-width staging buffer (4 x uint64
  words + bit length); a cumulative-sum over lengths then assigns every
  datapoint its bit offset and a scatter-add packs the payload words into
  the output stream (disjoint bit ranges make add equivalent to or).
* **Decode** — ``lax.scan`` over datapoint slots operating on (S,)
  arrays, with a dynamic bit-cursor per series.  Bit reads never touch
  memory: each lane carries a 32-word (2048-bit) window of its stream
  in the scan carry, field reads are register-level selects/shifts
  against a 9-word buffer extracted once per step, and the window is
  refilled 16 words at a time by a block gather guarded by a scalar
  ``lax.cond`` (so the O(S*W) gather cost is paid only on the ~1/15th
  of steps where some lane runs low, not ~24x per step as a naive
  per-field gather formulation would).  100K series decode in parallel
  — the batched ReaderIterator configuration from BASELINE.json.
* All float64 arithmetic demanded by the format (int-optimization
  classification, ``m3tsz.go:78-118``) runs as exact integer emulation
  (``f64_emul.py``), so results are bit-identical on TPU, which has no
  float64 ALU.

Series that would exercise the reference's float64 *rounding* behavior on
values above 2^53, or that carry annotations, are flagged in the returned
``fallback`` mask; callers re-run those through the scalar host codec
(``m3tsz.py``).  This mirrors the host/device split the framework uses
throughout: the device owns the dense numeric 99.99%, the host owns the
long tail.
"""

from __future__ import annotations

import functools
import os

import numpy as np

import m3_tpu  # noqa: F401  (enables x64 at the framework root)
import jax
import jax.numpy as jnp
from jax import lax

from m3_tpu.core.xtime import Unit
from m3_tpu.encoding import f64_emul as fe
from m3_tpu.encoding.scheme import tail_bytes

U64 = jnp.uint64
I64 = jnp.int64
I32 = jnp.int32
MASK64 = (1 << 64) - 1

STAGE_WORDS = 4  # 256 bits of staging per datapoint (worst case ~227)

# Datapoints decoded/encoded per scan-loop iteration (lax.scan unroll):
# larger amortizes per-step overhead and keeps the carry fused between
# chained bodies, but MULTIPLIES compile time of the already-large step
# body (unroll=4 took the S=2000 decode compile from ~40s to 9+ minutes
# on XLA-CPU — measured round 4).  Round-5 measurement: on XLA-CPU
# unroll=2 DECODES 13x SLOWER than unroll=1 (161K vs 2.09M dp/s at
# S=10K — the duplicated step body spills the carry out of registers);
# do not raise this on CPU.  Default 1; the TPU tradeoff is separately
# measured by the watcher's decode_u* stages.
try:
    _SCAN_UNROLL = max(1, int(os.environ.get("M3_SCAN_UNROLL", "1")))
except ValueError:
    _SCAN_UNROLL = 1

# time-unit byte -> nanos (0 = invalid/None)
_UNIT_NANOS = np.zeros(16, dtype=np.int64)
for _u_ in Unit:
    _UNIT_NANOS[int(_u_)] = _u_.nanos()

_BITS_1E13 = np.frombuffer(np.float64(10.0**13).tobytes(), dtype=np.uint64)[0]
_BITS_2_63 = np.frombuffer(np.float64(2.0**63).tobytes(), dtype=np.uint64)[0]
_I64_MIN = -(2**63)
_PRECISION_LIMIT = 1 << 53  # beyond this the reference's f64 math rounds


def _c(x, dtype=U64):
    return jnp.asarray(x, dtype=dtype)


def _shl(v, s):
    """uint64 << s with s possibly >= 64 (yields 0)."""
    s = _c(s)
    return jnp.where(s >= _c(64), _c(0), v << jnp.minimum(s, _c(63)))


def _shr(v, s):
    s = _c(s)
    return jnp.where(s >= _c(64), _c(0), v >> jnp.minimum(s, _c(63)))


def _num_sig(v):
    """Number of significant bits of uint64 (0 for 0)."""
    return jnp.where(
        v == _c(0), _c(0, I32),
        (_c(64, I32) - lax.clz(v.astype(I64)).astype(I32)))


def _sign_extend(v, nbits):
    """Sign-extend the low ``nbits`` of uint64 v to int64 (nbits >= 1)."""
    shift = _c(64) - _c(nbits)
    return (_shl(v, shift)).astype(I64) >> jnp.minimum(shift, _c(63)).astype(I64)


# ---------------------------------------------------------------------------
# Value classification: exact convertToIntFloat (m3tsz.go:78-118)
# ---------------------------------------------------------------------------


def classify_value(v_bits, cur_mult):
    """Returns (val int64 scaled, mult int32, is_float bool, precision_flag bool).

    ``precision_flag`` marks values whose downstream encoding would hit
    float64 rounding in the reference (|val| > 2^53): callers must fall
    back to the scalar codec for those series.
    """
    v_bits = _c(v_bits)
    sign = (v_bits >> _c(63)) != _c(0)
    abs_b = v_bits & _c(fe.MASK63)
    _, exp, _ = fe.split(abs_b)
    special = exp == _c(0x7FF)  # NaN / Inf never take the int paths

    # Quick path: already integral and v < 2^63 (float compare).
    ipart0, frac_zero0 = fe.floor_parts(abs_b)
    v_lt_maxint = sign | (abs_b < _c(_BITS_2_63))
    quick_ok = (cur_mult == _c(0, I32)) & v_lt_maxint & frac_zero0 & ~special
    # Go's uint64(int64(v)) saturation for out-of-range magnitudes.
    sat = abs_b >= _c(_BITS_2_63)
    quick_mag = jnp.where(sat, _c(_I64_MIN, I64), ipart0.astype(I64))
    quick_val = jnp.where(sign & ~sat, -quick_mag, quick_mag)

    # Multiplier loop: val = v * 10^cur, then *10 per iteration, looking for
    # a value within 1 ulp of an integer (see scalar codec for the ulp
    # reduction of the Modf/Nextafter conditions).
    val_bits = fe.mul_pow10(abs_b, cur_mult)
    found = jnp.zeros_like(sign)
    res_i = jnp.zeros_like(abs_b)
    res_mult = jnp.zeros_like(cur_mult)
    for k in range(7):
        active = (~quick_ok) & (~found) & (_c(k, I32) >= cur_mult) & (
            val_bits < _c(_BITS_1E13)) & ~special
        ip, fz = fe.floor_parts(val_bits)
        bi = fe.uint_to_f64_bits(ip)
        bi1 = fe.uint_to_f64_bits(ip + _c(1))
        take_i = fz | (val_bits <= bi + _c(1))
        take_i1 = (~take_i) & (val_bits + _c(1) >= bi1)
        hit = active & (take_i | take_i1)
        chosen = jnp.where(take_i, ip, ip + _c(1))
        res_i = jnp.where(hit, chosen, res_i)
        res_mult = jnp.where(hit, _c(k, I32), res_mult)
        found = found | hit
        advance = active & ~hit
        val_bits = jnp.where(advance, fe.mul10(val_bits), val_bits)

    loop_val = jnp.where(sign, -(res_i.astype(I64)), res_i.astype(I64))

    is_float = ~quick_ok & ~found
    val = jnp.where(quick_ok, quick_val, jnp.where(found, loop_val, _c(0, I64)))
    mult = jnp.where(found & ~quick_ok, res_mult, _c(0, I32))
    # Signed compares (not jnp.abs) so INT64_MIN saturations are caught too.
    precision_flag = ~is_float & ((val > _c(_PRECISION_LIMIT, I64)) |
                                  (val < _c(-_PRECISION_LIMIT, I64)))
    return val, mult, is_float, precision_flag


# ---------------------------------------------------------------------------
# Bit builder: append fields into 4x uint64 staging words
# ---------------------------------------------------------------------------


def _bb_new():
    return (jnp.zeros((), U64), jnp.zeros((), U64), jnp.zeros((), U64),
            jnp.zeros((), U64), jnp.zeros((), I32))


def _bb_append(bb, value, nbits, enable=None):
    """Append the low ``nbits`` of value. nbits may be a traced int32; when
    ``enable`` is False (or nbits == 0) this is a no-op."""
    w0, w1, w2, w3, ln = bb
    nbits = _c(nbits, I32)
    if enable is not None:
        nbits = jnp.where(enable, nbits, _c(0, I32))
    value = _c(value) & jnp.where(nbits >= _c(64, I32), _c(MASK64),
                                  (_shl(_c(1), nbits.astype(U64)) - _c(1)))
    pos = ln.astype(U64)
    n = nbits.astype(U64)
    off = pos & _c(63)
    widx = (pos >> _c(6)).astype(I32)
    in_first = jnp.minimum(n, _c(64) - off)
    rest = n - in_first
    first_chunk = _shl(_shr(value, rest), _c(64) - off - in_first)
    second_chunk = _shl(value & (_shl(_c(1), rest) - _c(1)), _c(64) - rest)
    nonzero = nbits > _c(0, I32)
    first_chunk = jnp.where(nonzero, first_chunk, _c(0))
    second_chunk = jnp.where(nonzero & (rest > _c(0)), second_chunk, _c(0))
    ws = [w0, w1, w2, w3]
    out = []
    for j in range(STAGE_WORDS):
        wj = ws[j]
        wj = wj | jnp.where(widx == j, first_chunk, _c(0))
        wj = wj | jnp.where(widx == j - 1, second_chunk, _c(0))
        out.append(wj)
    return (out[0], out[1], out[2], out[3], ln + nbits)


# ---------------------------------------------------------------------------
# Encoder scan
# ---------------------------------------------------------------------------


# Non-default delta-of-delta buckets: (opcode, num_opcode_bits, num_value_bits).
_DOD_BUCKETS = ((0b10, 2, 7), (0b110, 3, 9), (0b1110, 4, 12))


def _append_dod(bb, dod, unit_is_32bit):
    """Append a bucketed delta-of-delta (already unit-normalized).

    Returns (bb, overflow) where overflow marks a dod that does not fit the
    32-bit default bucket of second/millisecond units (the reference raises
    OverflowError there: timestamp_encoder.go:213-221)."""
    is_zero = dod == _c(0, I64)
    bb = _bb_append(bb, _c(0), _c(1, I32), enable=is_zero)
    done = is_zero
    for opcode, nob, nvb in _DOD_BUCKETS:
        lo, hi = -(1 << (nvb - 1)), (1 << (nvb - 1)) - 1
        fits = (~done) & (dod >= _c(lo, I64)) & (dod <= _c(hi, I64))
        bb = _bb_append(bb, _c(opcode), _c(nob, I32), enable=fits)
        bb = _bb_append(bb, dod.astype(U64), _c(nvb, I32), enable=fits)
        done = done | fits
    # default bucket: 32-bit (s/ms) or 64-bit (us/ns) value
    take_def = ~done
    bb = _bb_append(bb, _c(0b1111), _c(4, I32), enable=take_def)
    nvb = jnp.where(unit_is_32bit, _c(32, I32), _c(64, I32))
    bb = _bb_append(bb, dod.astype(U64), nvb, enable=take_def)
    overflow = take_def & unit_is_32bit & (
        (dod < _c(-(2**31), I64)) | (dod > _c(2**31 - 1, I64)))
    return bb, overflow


def _append_xor(bb, state, cur_xor):
    """Gorilla XOR emit (float_encoder_iterator.go:82-103). Returns (bb, new prev_xor)."""
    prev_xor = state
    is_zero = cur_xor == _c(0)
    bb = _bb_append(bb, _c(0), _c(1, I32), enable=is_zero)

    pl = jnp.where(prev_xor == _c(0), _c(64, I32),
                   lax.clz(prev_xor.astype(I64)).astype(I32))
    # trailing zeros = index of lowest set bit
    pt = jnp.where(prev_xor == _c(0), _c(0, I32),
                   (_num_sig(prev_xor & (~prev_xor + _c(1))) - _c(1, I32)))
    cl = lax.clz(jnp.maximum(cur_xor, _c(1)).astype(I64)).astype(I32)
    ct = _num_sig(cur_xor & (~cur_xor + _c(1))) - _c(1, I32)

    contained = (~is_zero) & (cl >= pl) & (ct >= pt)
    bb = _bb_append(bb, _c(0b10), _c(2, I32), enable=contained)
    bb = _bb_append(bb, _shr(cur_xor, pt.astype(U64)),
                    _c(64, I32) - pl - pt, enable=contained)

    uncont = (~is_zero) & (~contained)
    meaningful = _c(64, I32) - cl - ct
    bb = _bb_append(bb, _c(0b11), _c(2, I32), enable=uncont)
    bb = _bb_append(bb, cl.astype(U64), _c(6, I32), enable=uncont)
    bb = _bb_append(bb, (meaningful - _c(1, I32)).astype(U64), _c(6, I32), enable=uncont)
    bb = _bb_append(bb, _shr(cur_xor, ct.astype(U64)), meaningful, enable=uncont)
    new_prev_xor = jnp.where(is_zero, _c(0), cur_xor)
    return bb, new_prev_xor


def _track_new_sig(num_sig_st, cur_hl, num_lower, sig):
    """IntSigBitsTracker.TrackNewSig (int_sig_bits_tracker.go:68-91)."""
    new_sig = num_sig_st
    grow = sig > num_sig_st
    new_sig = jnp.where(grow, sig, new_sig)
    shrink = (~grow) & ((num_sig_st - sig) >= _c(3, I32))
    chl = jnp.where(shrink & (num_lower == _c(0, I32)), sig,
                    jnp.where(shrink & (sig > cur_hl), sig, cur_hl))
    # The lower-sig streak counter resets only on the NEITHER branch
    # (within-threshold sig): a GROW step leaves it intact — Go keeps
    # t.NumLowerSig untouched when numSig > t.NumSig
    # (int_sig_bits_tracker.go:68-91).  Resetting on grow desynced the
    # device encoder's shrink timing from the scalar oracle on
    # grow-interleaved streams (caught by the round-5 bench's
    # device-vs-native byte-identity stage, 22/2000 series).
    nl = jnp.where(shrink, num_lower + _c(1, I32),
                   jnp.where(grow, num_lower, _c(0, I32)))
    fire = shrink & (nl >= _c(5, I32))
    new_sig = jnp.where(fire, chl, new_sig)
    nl = jnp.where(fire, _c(0, I32), nl)
    return new_sig, chl, nl


def _append_int_sig_mult(bb, num_sig_st, max_mult, sig, mult, float_changed):
    """writeIntSigMult (encoder.go:235-250). Returns (bb, new num_sig, new max_mult)."""
    # WriteIntSig
    sig_changed = num_sig_st != sig
    bb = _bb_append(bb, _c(1), _c(1, I32), enable=sig_changed)
    zero_sig = sig == _c(0, I32)
    bb = _bb_append(bb, _c(0), _c(1, I32), enable=sig_changed & zero_sig)
    bb = _bb_append(bb, _c(1), _c(1, I32), enable=sig_changed & ~zero_sig)
    bb = _bb_append(bb, (sig - _c(1, I32)).astype(U64), _c(6, I32),
                    enable=sig_changed & ~zero_sig)
    bb = _bb_append(bb, _c(0), _c(1, I32), enable=~sig_changed)
    new_num_sig = sig
    # mult update
    mult_up = mult > max_mult
    # after WriteIntSig num_sig == sig, so condition reduces to:
    float_only = (~mult_up) & (max_mult == mult) & float_changed
    bb = _bb_append(bb, _c(1), _c(1, I32), enable=mult_up | float_only)
    bb = _bb_append(bb, mult.astype(U64), _c(3, I32), enable=mult_up | float_only)
    bb = _bb_append(bb, _c(0), _c(1, I32), enable=~(mult_up | float_only))
    new_max_mult = jnp.where(mult_up, mult, max_mult)
    return bb, new_num_sig, new_max_mult


def _append_int_val_diff(bb, num_sig_st, diff_bits, neg):
    bb = _bb_append(bb, jnp.where(neg, _c(1), _c(0)), _c(1, I32))
    bb = _bb_append(bb, diff_bits, num_sig_st)
    return bb


def _encode_step(carry, xs, unit: int, default_unit_is_32bit: bool):
    """One datapoint for one series. carry is the full codec state."""
    (prev_time, prev_delta, tu_none, int_val, max_mult, is_float,
     prev_fbits, prev_xor, num_sig_st, cur_hl, num_lower, is_first,
     fallback) = carry
    t, v_bits, valid = xs

    bb = _bb_new()

    # ---- timestamp (timestamp_encoder.go:72-129) ----
    # first datapoint of the stream: 64-bit start already emitted by caller
    # via the start word (prev_time holds start). Time-unit change marker if
    # the initial unit was None (unaligned start).
    emit_tu = is_first & tu_none
    bb = _bb_append(bb, _c(0x100), _c(9, I32), enable=emit_tu)
    bb = _bb_append(bb, _c(2), _c(2, I32), enable=emit_tu)  # time-unit marker
    bb = _bb_append(bb, _c(unit), _c(8, I32), enable=emit_tu)

    time_delta = t - prev_time
    dod_ns = time_delta - prev_delta
    # after a TU write: full 64-bit nanosecond dod, delta resets to 0
    bb = _bb_append(bb, dod_ns.astype(U64), _c(64, I32), enable=emit_tu)
    unit_nanos = int(Unit(unit).nanos())
    dod_units = dod_ns // _c(unit_nanos, I64)  # deltas divisible (checked by caller)
    div_ok = (dod_ns % _c(unit_nanos, I64)) == _c(0, I64)
    bb_dod, dod_overflow = _append_dod(bb, dod_units,
                                       _c(default_unit_is_32bit, jnp.bool_))
    # Only one of the two paths appended bits (enable flags), so select:
    bb = tuple(jnp.where(emit_tu, a, b) for a, b in zip(bb, bb_dod))
    new_prev_delta = jnp.where(emit_tu, _c(0, I64), time_delta)
    new_prev_time = t
    new_tu_none = tu_none & ~emit_tu

    # ---- value ----
    val, mult, v_is_float, prec = classify_value(v_bits, max_mult)

    # ---------- first value (encoder.go:112-146) ----------
    bb_f = bb
    bb_f = _bb_append(bb_f, jnp.where(v_is_float, _c(1), _c(0)), _c(1, I32))
    # float mode
    bb_ff = _bb_append(bb_f, v_bits, _c(64, I32))
    # int mode
    neg_diff = val >= _c(0, I64)  # inverted: diff = 0 - val
    mag = jnp.abs(val).astype(U64)
    sig_f = _num_sig(mag)
    bb_fi, ns_fi, mm_fi = _append_int_sig_mult(
        bb_f, num_sig_st, max_mult, sig_f, mult, _c(False, jnp.bool_))
    bb_fi = _append_int_val_diff(bb_fi, ns_fi, mag, neg_diff)
    bb_first = tuple(jnp.where(v_is_float, a, b) for a, b in zip(bb_ff, bb_fi))
    st_first = dict(
        int_val=jnp.where(v_is_float, int_val, val),
        is_float=v_is_float,
        prev_fbits=jnp.where(v_is_float, v_bits, prev_fbits),
        prev_xor=jnp.where(v_is_float, v_bits, prev_xor),
        num_sig=jnp.where(v_is_float, num_sig_st, ns_fi),
        max_mult_i=jnp.where(v_is_float, mult, mm_fi),
        cur_hl=cur_hl, num_lower=num_lower,
    )

    # ---------- next value (encoder.go:148-231) ----------
    val_diff = int_val - val
    # float path trigger (diff overflow impossible: flagged by prec limit)
    go_float = v_is_float
    # writeFloatVal
    was_int = ~is_float
    bb_n = bb
    #   int->float: '0''0''1' + full float
    bb_nf1 = _bb_append(bb_n, _c(0b001), _c(3, I32))
    bb_nf1 = _bb_append(bb_nf1, v_bits, _c(64, I32))
    #   float repeat: '0''1'
    repeat_f = is_float & (v_bits == prev_fbits)
    bb_nf2 = _bb_append(bb_n, _c(0b01), _c(2, I32))
    #   float next: '1' + xor
    bb_nf3 = _bb_append(bb_n, _c(1), _c(1, I32))
    bb_nf3, nxor = _append_xor(bb_nf3, prev_xor, prev_fbits ^ v_bits)
    bb_float = tuple(
        jnp.where(was_int, a, jnp.where(repeat_f, b, c))
        for a, b, c in zip(bb_nf1, bb_nf2, bb_nf3))
    st_float = dict(
        int_val=int_val,
        is_float=_c(True, jnp.bool_),
        max_mult_i=jnp.where(was_int, mult, max_mult),
        prev_fbits=v_bits,
        prev_xor=jnp.where(was_int, v_bits, jnp.where(repeat_f, prev_xor, nxor)),
        num_sig=num_sig_st, cur_hl=cur_hl, num_lower=num_lower,
    )

    # writeIntVal
    repeat_i = (val_diff == _c(0, I64)) & (~is_float) & (mult == max_mult)
    bb_ir = _bb_append(bb_n, _c(0b01), _c(2, I32))
    neg = val_diff < _c(0, I64)
    diff_mag = jnp.abs(val_diff).astype(U64)
    sig_n = _num_sig(diff_mag)
    new_sig, t_chl, t_nl = _track_new_sig(num_sig_st, cur_hl, num_lower, sig_n)
    float_changed = is_float  # is_float state true means mode changes to int
    need_update = (mult > max_mult) | (num_sig_st != new_sig) | float_changed
    #   update: '1'? no: opcodeUpdate=0 -> bits '0''0''0'
    bb_iu = _bb_append(bb_n, _c(0b000), _c(3, I32))
    bb_iu, ns_iu, mm_iu = _append_int_sig_mult(
        bb_iu, num_sig_st, max_mult, new_sig, mult, float_changed)
    bb_iu = _append_int_val_diff(bb_iu, ns_iu, diff_mag, neg)
    #   no-update: '1' + diff
    bb_in = _bb_append(bb_n, _c(1), _c(1, I32))
    bb_in = _append_int_val_diff(bb_in, num_sig_st, diff_mag, neg)
    bb_int = tuple(
        jnp.where(repeat_i, a, jnp.where(need_update, b, c))
        for a, b, c in zip(bb_ir, bb_iu, bb_in))
    st_int = dict(
        int_val=jnp.where(repeat_i, int_val, val),
        is_float=jnp.where(repeat_i, is_float, _c(False, jnp.bool_)),
        max_mult_i=jnp.where(repeat_i, max_mult,
                             jnp.where(need_update, mm_iu, max_mult)),
        prev_fbits=prev_fbits, prev_xor=prev_xor,
        num_sig=jnp.where(repeat_i, num_sig_st,
                          jnp.where(need_update, ns_iu, num_sig_st)),
        cur_hl=jnp.where(repeat_i, cur_hl, t_chl),
        num_lower=jnp.where(repeat_i, num_lower, t_nl),
    )

    bb_next = tuple(
        jnp.where(go_float, a, b) for a, b in zip(bb_float, bb_int))
    st_next = {
        k: jnp.where(go_float, st_float[k], st_int[k])
        for k in st_float
    }

    bb_out = tuple(jnp.where(is_first, a, b) for a, b in zip(bb_first, bb_next))
    st = {
        k: jnp.where(is_first, st_first[k], st_next[k])
        for k in st_first
    }

    # inactive (padding) steps emit nothing and keep state
    w0, w1, w2, w3, ln = bb_out
    ln = jnp.where(valid, ln, _c(0, I32))
    zeros = _c(0)
    w0 = jnp.where(valid, w0, zeros)
    w1 = jnp.where(valid, w1, zeros)
    w2 = jnp.where(valid, w2, zeros)
    w3 = jnp.where(valid, w3, zeros)

    def keep(new, old):
        return jnp.where(valid, new, old)

    fallback = (fallback | (valid & prec) | (valid & ~div_ok & ~emit_tu)
                | (valid & dod_overflow & ~emit_tu))
    new_carry = (
        keep(new_prev_time, prev_time),
        keep(new_prev_delta, prev_delta),
        keep(new_tu_none, tu_none),
        keep(st["int_val"], int_val),
        keep(st["max_mult_i"], max_mult),
        keep(st["is_float"], is_float),
        keep(st["prev_fbits"], prev_fbits),
        keep(st["prev_xor"], prev_xor),
        keep(st["num_sig"], num_sig_st),
        keep(st["cur_hl"], cur_hl),
        keep(st["num_lower"], num_lower),
        is_first & ~valid,
        fallback,
    )
    return new_carry, (w0, w1, w2, w3, ln)


@functools.partial(jax.jit, static_argnames=("unit", "out_words"))
def encode_batch_device(timestamps, value_bits, start, valid, unit: int = 1,
                        out_words: int = 0, prefix_bits=None):
    """Encode (S, T) series on device.

    Args:
      timestamps: (S, T) int64 UnixNanos, padded entries arbitrary.
      value_bits: (S, T) uint64 float64 bit patterns.
      start: (S,) int64 encoder start times.
      valid: (S, T) bool mask of real datapoints (prefix True).
      unit: static time unit (wire byte value).
      out_words: static output width in 64-bit words per series
        (0 -> T * 16 bits / 64 + 4).
      prefix_bits: optional (S,) int32 — bits reserved after the start
        word for a host-composed prefix (the first datapoint's
        annotation marker+varint+bytes, spliced in by ``encode_batch``);
        all emitted fields shift right by this amount.

    Returns dict with packed words (S, W) uint64 (starting with the 64-bit
    start time), total_bits (S,), fallback (S,) bool.
    """
    S, T = timestamps.shape
    if out_words == 0:
        out_words = (T * 16) // 64 + 4
    u = Unit(unit)
    default_32 = u in (Unit.SECOND, Unit.MILLISECOND)

    tu_none = (start % jnp.asarray(u.nanos(), I64)) != 0

    carry0 = (
        start.astype(I64),                      # prev_time
        jnp.zeros(S, I64),                      # prev_delta
        tu_none,                                # initial unit None?
        jnp.zeros(S, I64),                      # int_val
        jnp.zeros(S, I32),                      # max_mult
        jnp.zeros(S, jnp.bool_),                # is_float
        jnp.zeros(S, U64),                      # prev_fbits
        jnp.zeros(S, U64),                      # prev_xor
        jnp.zeros(S, I32),                      # num_sig
        jnp.zeros(S, I32),                      # cur_highest_lower_sig
        jnp.zeros(S, I32),                      # num_lower_sig
        jnp.ones(S, jnp.bool_),                 # is_first
        jnp.zeros(S, jnp.bool_),                # fallback
    )

    step = functools.partial(_encode_step, unit=unit,
                             default_unit_is_32bit=default_32)
    vstep = jax.vmap(step)

    def scan_fn(carry, xs):
        return vstep(carry, xs)

    xs = (timestamps.T, value_bits.T, valid.T)  # scan over T
    carry, (w0, w1, w2, w3, lens) = lax.scan(scan_fn, carry0, xs,
                                             unroll=_SCAN_UNROLL)
    # outputs are (T, S); transpose to (S, T)
    w0, w1, w2, w3 = (w.T for w in (w0, w1, w2, w3))
    lens = lens.T.astype(jnp.int64)

    # bit offsets: 64 bits for the start word (+ any host prefix), then
    # cumulative lengths
    base = 64 if prefix_bits is None else (
        64 + prefix_bits.astype(jnp.int64)[:, None])
    offsets = jnp.cumsum(lens, axis=1) - lens + base
    total_bits = offsets[:, -1] + lens[:, -1]

    out = jnp.zeros((S, out_words), U64)
    # start word first
    out = out.at[:, 0].set(start.astype(U64))

    # Word placement: every step contributes (hi, lo) word fragments at
    # per-series word indices gw / gw+1.  Two formulations:
    #   scatter — 8 scatter-adds over (S, T); fine on XLA-CPU.
    #   gather  — per-series word indices are NON-DECREASING along T
    #             (offsets are cumulative), so for each output word the
    #             contributing step range is a searchsorted interval and
    #             its sum a cumsum difference — exact even with u64
    #             wraparound ((A+B)-A == B mod 2^64).  No scatter; built
    #             for TPU (~1us/element scatter, TPU_RESULTS_r05.json).
    # M3_ENCODE_PLACE overrides for parity tests.
    place = os.environ.get("M3_ENCODE_PLACE", "").strip() or (
        "gather" if jax.default_backend() == "tpu" else "scatter")
    if place == "gather":
        w_queries = jnp.arange(out_words, dtype=jnp.int64)
        zero_col = jnp.zeros((S, 1), U64)
        for j, wj in enumerate((w0, w1, w2, w3)):
            pos = offsets + j * 64
            sh = (pos & 63).astype(U64)
            in_range = (j * 64) < lens
            hi = jnp.where(in_range, _shr(wj, sh), _c(0))
            lo_shift = _c(64) - sh
            lo = jnp.where(in_range & (sh > _c(0)), _shl(wj, lo_shift),
                           _c(0))
            for delta, frag in ((0, hi), (1, lo)):
                keys = (pos >> 6) + delta  # (S, T) non-decreasing rows
                cum = jnp.concatenate(
                    [zero_col, jnp.cumsum(frag, axis=1)], axis=1)
                p_hi = jax.vmap(
                    lambda row: jnp.searchsorted(row, w_queries,
                                                 side="right"))(keys)
                # For contiguous integer queries, left(w) == right(w-1):
                # one sweep serves both interval bounds.  Keys are >= 1
                # (offsets start at base >= 64), so left(0) == 0.
                p_lo = jnp.concatenate(
                    [jnp.zeros((S, 1), p_hi.dtype), p_hi[:, :-1]], axis=1)
                out = out + (jnp.take_along_axis(cum, p_hi, axis=1)
                             - jnp.take_along_axis(cum, p_lo, axis=1))
    else:
        series_idx = jnp.broadcast_to(jnp.arange(S, dtype=I64)[:, None],
                                      (S, T))
        for j, wj in enumerate((w0, w1, w2, w3)):
            pos = offsets + j * 64
            gw = (pos >> 6).astype(I32)
            sh = (pos & 63).astype(U64)
            in_range = (j * 64) < lens  # word j carries bits iff len > 64j
            hi = jnp.where(in_range, _shr(wj, sh), _c(0))
            lo_shift = _c(64) - sh
            lo = jnp.where(in_range & (sh > _c(0)), _shl(wj, lo_shift),
                           _c(0))
            out = out.at[series_idx, jnp.clip(gw, 0, out_words - 1)].add(
                jnp.where(gw < out_words, hi, _c(0)))
            out = out.at[series_idx, jnp.clip(gw + 1, 0, out_words - 1)].add(
                jnp.where(gw + 1 < out_words, lo, _c(0)))

    fallback = carry[12] | (total_bits > (out_words * 64))
    return {"words": out, "total_bits": total_bits, "fallback": fallback}


def finalize_streams(words: np.ndarray, total_bits: np.ndarray,
                     counts=None) -> list[bytes]:
    """Host finalization: trim to byte length and append the EOS tail."""
    out = []
    words = np.asarray(words)
    total_bits = np.asarray(total_bits)
    for i in range(words.shape[0]):
        nbits = int(total_bits[i])
        raw = words[i].astype(">u8").tobytes()
        nbytes = (nbits + 7) // 8
        head = raw[:nbytes]
        pos = nbits - (nbytes - 1) * 8  # bits used in last byte, 1..8
        out.append(head[:-1] + tail_bytes(head[-1], pos))
    return out


def pack_streams(streams: list[bytes], pad_words: int = 0):
    """Pack finalized byte streams into the decoder's input layout:
    (S, pad_words) big-endian uint64 word arrays + per-stream bit lengths.

    ``pad_words`` of 0 sizes the array to the longest stream plus two
    slack words (the decoder pads further to whole refill blocks).
    """
    S = len(streams)
    if pad_words == 0:
        pad_words = max((len(s) for s in streams), default=0) // 8 + 2
    words = np.zeros((S, pad_words), np.uint64)
    nbits = np.zeros(S, np.int64)
    for i, s in enumerate(streams):
        nbits[i] = len(s) * 8
        padded = s + b"\x00" * (-len(s) % 8)
        w = np.frombuffer(padded, dtype=">u8").astype(np.uint64)
        words[i, : len(w)] = w
    return words, nbits


def _annotation_prefix(ann: bytes):
    """The first-datapoint annotation wire prefix (marker + varint +
    bytes) as (uint64 big-endian words, bit length) — composed with the
    scalar OStream so the bit layout is definitionally identical to the
    scalar encoder's (_write_annotation)."""
    from m3_tpu.encoding.bitstream import OStream
    from m3_tpu.encoding.m3tsz import _put_varint
    from m3_tpu.encoding.scheme import ANNOTATION_MARKER, write_special_marker

    os_ = OStream()
    write_special_marker(os_, ANNOTATION_MARKER)
    os_.write_bytes(_put_varint(len(ann) - 1))
    os_.write_bytes(ann)
    raw, _ = os_.raw_bytes()
    padded = raw + b"\x00" * (-len(raw) % 8)
    return np.frombuffer(padded, dtype=">u8").astype(np.uint64), os_.bit_length


def encode_batch(timestamps, values, start, counts=None, unit: Unit = Unit.SECOND,
                 out_words: int = 0, annotations=None):
    """Host-facing batched encode.

    Returns (streams: list[bytes], fallback: np.ndarray[bool]); fallback
    series contain b"" and must be encoded with the scalar codec.

    ``annotations`` (optional list[bytes|None], len S) attaches an
    annotation to each series' FIRST datapoint — the proto-schema /
    tag-payload shape (`timestamp_encoder.go:99-116` writes it before
    the first time-unit marker).  The device scan shifts its output by
    the prefix width and the host splices the marker+varint+bytes in;
    mid-stream annotation CHANGES stay on the scalar path.
    """
    timestamps = np.asarray(timestamps, dtype=np.int64)
    values = np.asarray(values, dtype=np.float64)
    S, T = timestamps.shape
    if counts is None:
        counts = np.full(S, T, dtype=np.int64)
    valid = np.arange(T, dtype=np.int64)[None, :] < np.asarray(counts)[:, None]
    vb = values.view(np.uint64)

    prefix_bits = None
    prefix_words: dict[int, np.ndarray] = {}
    if annotations is not None:
        pb = np.zeros(S, np.int32)
        for i, ann in enumerate(annotations):
            if ann:
                prefix_words[i], pb[i] = _annotation_prefix(ann)
        prefix_bits = jnp.asarray(pb) if prefix_words else None

    res = encode_batch_device(
        jnp.asarray(timestamps), jnp.asarray(vb), jnp.asarray(start, dtype=jnp.int64),
        jnp.asarray(valid), unit=int(unit), out_words=out_words,
        prefix_bits=prefix_bits)
    fallback = np.asarray(res["fallback"])
    words_out = np.asarray(res["words"])
    if prefix_words:
        # Splice each prefix in after the start word (bit 64 is a word
        # boundary, so this is a plain OR into untouched zero bits).
        words_out = words_out.copy()
        for i, pw in prefix_words.items():
            words_out[i, 1:1 + len(pw)] |= pw
    streams = finalize_streams(words_out, np.asarray(res["total_bits"]))
    counts_arr = np.asarray(counts)
    # An empty series encodes to b"" (the reference encoder's Stream() returns
    # no segment when nothing was written), not a bare start-word stream.
    streams = [b"" if (fallback[i] or counts_arr[i] == 0) else streams[i]
               for i in range(S)]
    return streams, fallback


# ---------------------------------------------------------------------------
# Batched decode
# ---------------------------------------------------------------------------


def _peek(words, cursor, n):
    """Read ``n`` (<=64, may be 0 or traced) bits at bit position cursor from a
    (W+1,) uint64 word array (extra zero pad word)."""
    w = (cursor >> _c(6, I32))
    off = (cursor & _c(63, I32)).astype(U64)
    W = words.shape[0] - 1
    w = jnp.clip(w, 0, W - 1)
    w0 = words[w]
    w1 = words[w + 1]
    window = _shl(w0, off) | jnp.where(off > _c(0), _shr(w1, _c(64) - off), _c(0))
    return _shr(window, _c(64) - _c(n, I32).astype(U64))


# -- Window-carry bit reader ------------------------------------------------
#
# Per-lane dynamic gathers from the (S, W) word array cost O(S*W) vector
# work on TPU (the backend lowers them to masked reductions over the W
# axis); the original decoder issued ~24 of them per scan step and was
# gather-bound (round-2: 0.96M datapoints/s on a v5e).  The decoder now
# carries a 32-word (2048-bit) window of each lane's stream in the scan
# carry.  All field reads are register-level selects/shifts against a
# 9-word buffer extracted from that window once per step; the only memory
# access is a 16-word block refill, executed under a *scalar* `lax.cond`
# only on steps where some lane's window runs low (~every 1024/avg-bits
# steps on typical corpora).  Worst case (adversarial drift) is one
# block gather per step -- still ~24x less gather work than before.

_WIN_WORDS = 32          # carried window: 2 blocks of 16 words (2048 bits)
_BLK_WORDS = 16          # refill granularity (1024 bits)
# Maximum bits one decode step can consume — the invariants in _buf9/_rd
# and the refill depend on this bound staying <= 256: first step worst
# case is 64 (start) + 11+8+64 (marker + unit byte + full dod) +
# 1 (mode) + 1+1+6 (sig) + 1+3 (mult) + 1+64 (diff) = 225 bits;
# steady-state steps top out lower (no 64-bit start).


def _buf9(window, rel):
    """Extract 9 consecutive words from the 32-word window starting at the
    4-word-aligned word index below bit offset ``rel`` (rel in [0, 1024)).

    Returns (B, base_bits) where B is a tuple of 9 (S,) words and
    base_bits is the window bit offset of B[0].  All selects are
    elementwise (no gathers): the aligned start has only 4 possibilities.
    9 words cover the worst case: a step starts at buffer offset < 256
    and consumes <= 225 bits, so reads end below 481 < 8*64, and the
    funnel in ``_rd`` may touch one word past the last data word.
    """
    wi0 = (rel >> _c(6, I32)) & ~_c(3, I32)      # 0, 4, 8, 12
    b = wi0 >> _c(2, I32)                         # 0..3
    cols = [window[:, j] for j in range(12 + 9)]
    B = []
    for j in range(9):
        w = jnp.where(b == _c(0, I32), cols[j],
            jnp.where(b == _c(1, I32), cols[4 + j],
            jnp.where(b == _c(2, I32), cols[8 + j], cols[12 + j])))
        B.append(w)
    return tuple(B), wi0 * _c(64, I32)


def _rd(B, o, n):
    """Read ``n`` (0..64, possibly traced) bits at buffer-relative bit
    offset ``o`` (0 <= o+n <= 512) from the 9-word buffer B.  Pure shifts
    and selects; no memory access."""
    wi = o >> _c(6, I32)                          # 0..7
    r = (o & _c(63, I32)).astype(U64)
    hi = B[0]
    lo = B[1]
    for j in range(1, 8):
        sel = wi == _c(j, I32)
        hi = jnp.where(sel, B[j], hi)
        lo = jnp.where(sel, B[j + 1], lo)
    chunk = _shl(hi, r) | jnp.where(r > _c(0), _shr(lo, _c(64) - r), _c(0))
    return _shr(chunk, _c(64) - _c(n, I32).astype(U64))


def _decode_step(carry, _, words3, nbits, default_unit: int):
    """One datapoint slot for every series at once ((S,) array ops).

    ``words3`` is the (S, NB+1, 16) blocked stream array (closure, not
    carry); ``nbits`` the per-series stream bit lengths.  All bit reads
    come from the carried window via ``_buf9``/``_rd``.
    """
    (cursor, done, err, prec, need_start, first_val, saw_ann, prev_time,
     prev_delta, unit_idx, prev_fbits, prev_xor, int_val, sig, mult,
     is_float, window, blk) = carry
    active = (~done) & (~err)

    unit_tbl = jnp.asarray(_UNIT_NANOS, I64)

    base_abs = blk * _c(_BLK_WORDS * 64, I32)
    B, base_bits = _buf9(window, cursor - base_abs)
    base_abs = base_abs + base_bits

    def _peek(_w, cur, n):  # same read interface as before, window-backed
        return _rd(B, cur - base_abs, n)

    words = None  # all reads go through the window

    # ---- first: 64-bit start timestamp ----
    rd_first = jnp.where(active & need_start, _c(64, I32), _c(0, I32))
    nt = _sign_extend(_peek(words, cursor, rd_first), _c(64, I32))
    cur = cursor + rd_first
    d_ns = jnp.asarray(int(Unit(default_unit).nanos()), I64)
    aligned = (lax.rem(nt, d_ns)) == _c(0, I64)
    unit0 = jnp.where(aligned, _c(default_unit, I32), _c(0, I32))
    unit_eff = jnp.where(need_start, unit0, unit_idx)
    base_time = jnp.where(need_start, nt, prev_time)
    first = first_val  # value-mode branch key (first value still pending)

    # ---- marker peek (11 bits) ----
    can_peek = (cur + _c(11, I32)) <= nbits
    peek11 = jnp.where(active & can_peek, _peek(words, cur, _c(11, I32)), _c(0))
    is_marker = (peek11 >> _c(2)) == _c(0x100)
    mval = (peek11 & _c(3)).astype(I32)
    eos = active & is_marker & (mval == _c(0, I32))
    ann = active & is_marker & (mval == _c(1, I32))
    is_tu = active & is_marker & (mval == _c(2, I32))
    done = done | eos
    proceed = active & ~eos & ~ann

    # ---- annotation skip (timestamp_encoder.go:99-116) ----
    # marker + zigzag-LEB128 varint of (len-1) + len bytes.  The step
    # consumes the marker and varint from the window (<= 43 bits) and
    # jumps the cursor over the payload; the refill below reloads the
    # window for any lane whose cursor left it.  The annotation slot
    # emits no datapoint — callers size max_points accordingly.
    acur = cur + _c(11, I32)
    ux = jnp.zeros_like(peek11)
    more = ann
    abits = jnp.zeros_like(cur)
    for k in range(4):
        rd = jnp.where(more, _c(8, I32), _c(0, I32))
        byte = _peek(words, acur + abits, rd)
        ux = ux | _shl(byte & _c(0x7F), _c(7 * k))
        abits = abits + rd
        more = more & ((byte & _c(0x80)) != _c(0))
    err = err | more  # varint > 4 bytes: host path
    ann_len = (ux >> _c(1)).astype(I32) + _c(1, I32)  # zigzag, stored len-1
    ann_end = acur + abits + ann_len * _c(8, I32)
    err = err | (ann & (ann_end > nbits))
    saw_ann = saw_ann | (ann & ~err)

    cur = cur + jnp.where(is_tu, _c(11, I32), _c(0, I32))
    rd_tu = jnp.where(is_tu, _c(8, I32), _c(0, I32))
    ub = _peek(words, cur, rd_tu).astype(I32)
    cur = cur + rd_tu
    ub_valid = (ub >= _c(1, I32)) & (ub <= _c(8, I32))
    tu_changed = is_tu & ub_valid & (ub != unit_eff)
    new_unit = jnp.where(is_tu, ub, unit_eff)
    unit_nanos = unit_tbl[jnp.clip(new_unit, 0, 15)]
    err = err | (proceed & (unit_nanos == _c(0, I64)) & ~tu_changed)

    # ---- delta of delta ----
    full64 = tu_changed
    rd_dod64 = jnp.where(proceed & full64, _c(64, I32), _c(0, I32))
    dod_full = _sign_extend(_peek(words, cur, rd_dod64), _c(64, I32))
    cur = cur + rd_dod64

    # bucketed path: peek 4 opcode bits, classify
    bucket_active = proceed & ~full64
    op4 = jnp.where(bucket_active, _peek(words, cur, _c(4, I32)), _c(0))
    b3 = (op4 >> _c(3)) & _c(1)
    b2 = (op4 >> _c(2)) & _c(1)
    b1 = (op4 >> _c(1)) & _c(1)
    b0 = op4 & _c(1)
    default_is32 = (new_unit == _c(1, I32)) | (new_unit == _c(2, I32))
    nop = jnp.where(b3 == _c(0), _c(1, I32),
          jnp.where(b2 == _c(0), _c(2, I32),
          jnp.where(b1 == _c(0), _c(3, I32), _c(4, I32))))
    nv = jnp.where(b3 == _c(0), _c(0, I32),
         jnp.where(b2 == _c(0), _c(7, I32),
         jnp.where(b1 == _c(0), _c(9, I32),
         jnp.where(b0 == _c(0), _c(12, I32),
                   jnp.where(default_is32, _c(32, I32), _c(64, I32))))))
    nop = jnp.where(bucket_active, nop, _c(0, I32))
    nv = jnp.where(bucket_active, nv, _c(0, I32))
    cur = cur + nop
    dod_bits = _peek(words, cur, nv)
    cur = cur + nv
    dod_units = jnp.where(nv > _c(0, I32),
                          _sign_extend(dod_bits, jnp.maximum(nv, _c(1, I32))),
                          _c(0, I64))
    dod_ns = jnp.where(full64, dod_full, dod_units * unit_nanos)

    pd = prev_delta + jnp.where(proceed, dod_ns, _c(0, I64))
    new_time = base_time + pd
    pd = jnp.where(full64, _c(0, I64), pd)

    # ---- value ----
    # Small-field chunk: every flag/sig/mult/sign read in the value
    # section starts within 16 bits of the section origin on whichever
    # path a lane takes (64-bit payload reads only precede reads that
    # are inactive on that lane), so ONE 64-bit window read serves all
    # thirteen of them as in-register shifts instead of full buffer
    # funnels.  Inactive lanes may compute off >= 64: the guarded
    # shifts return 0, matching a zero-width _peek.
    v0 = cur
    W = _peek(words, v0, _c(64, I32))

    def rdw(cur_abs, n):
        off = (cur_abs - v0).astype(U64)
        return _shr(_shl(W, off), _c(64) - _c(n, I32).astype(U64))

    # first value
    f_active = proceed & first
    rd = jnp.where(f_active, _c(1, I32), _c(0, I32))
    mode_bit = rdw(cur, rd)
    cur = cur + rd
    f_is_float = f_active & (mode_bit == _c(1))
    rd = jnp.where(f_is_float, _c(64, I32), _c(0, I32))
    f_fbits = _peek(words, cur, rd)
    cur = cur + rd

    # next-value branch bits
    n_active = proceed & ~first
    rd = jnp.where(n_active, _c(1, I32), _c(0, I32))
    nb1 = rdw(cur, rd)
    cur = cur + rd
    upd = n_active & (nb1 == _c(0))  # opcodeUpdate = 0
    rd = jnp.where(upd, _c(1, I32), _c(0, I32))
    nb2 = rdw(cur, rd)
    cur = cur + rd
    repeat = upd & (nb2 == _c(1))
    upd2 = upd & (nb2 == _c(0))
    rd = jnp.where(upd2, _c(1, I32), _c(0, I32))
    nb3 = rdw(cur, rd)
    cur = cur + rd
    to_float = upd2 & (nb3 == _c(1))
    rd = jnp.where(to_float, _c(64, I32), _c(0, I32))
    n_fbits = _peek(words, cur, rd)
    cur = cur + rd
    to_int_upd = upd2 & (nb3 == _c(0))

    # readIntSigMult for first-int or next-int-update
    sig_rd_active = (f_active & ~f_is_float) | to_int_upd
    rd = jnp.where(sig_rd_active, _c(1, I32), _c(0, I32))
    sb1 = rdw(cur, rd)
    cur = cur + rd
    sig_upd = sig_rd_active & (sb1 == _c(1))
    rd = jnp.where(sig_upd, _c(1, I32), _c(0, I32))
    sb2 = rdw(cur, rd)
    cur = cur + rd
    sig_nonzero = sig_upd & (sb2 == _c(1))
    rd = jnp.where(sig_nonzero, _c(6, I32), _c(0, I32))
    sigbits = rdw(cur, rd)
    cur = cur + rd
    new_sig = jnp.where(sig_upd & ~sig_nonzero, _c(0, I32),
               jnp.where(sig_nonzero, sigbits.astype(I32) + _c(1, I32), sig))
    rd = jnp.where(sig_rd_active, _c(1, I32), _c(0, I32))
    mb1 = rdw(cur, rd)
    cur = cur + rd
    mult_upd = sig_rd_active & (mb1 == _c(1))
    rd = jnp.where(mult_upd, _c(3, I32), _c(0, I32))
    multbits = rdw(cur, rd)
    cur = cur + rd
    new_mult = jnp.where(mult_upd, multbits.astype(I32), mult)
    err = err | (mult_upd & (new_mult > _c(6, I32)))

    # int val diff read (first-int, next-int-update, next-int-noupdate)
    int_noupd = n_active & (nb1 == _c(1)) & ~is_float
    diff_active = sig_rd_active | int_noupd
    eff_sig = jnp.where(int_noupd, sig, new_sig)
    rd = jnp.where(diff_active, _c(1, I32), _c(0, I32))
    sign_bit = rdw(cur, rd)
    cur = cur + rd
    rd = jnp.where(diff_active, eff_sig, _c(0, I32))
    diff_bits = _peek(words, cur, rd)
    cur = cur + rd
    # sign convention: opcodeNegative(1) -> +, opcodePositive(0) -> -
    signed_diff = jnp.where(sign_bit == _c(1), diff_bits.astype(I64),
                            -(diff_bits.astype(I64)))
    prec = prec | (diff_active & (diff_bits > _c(_PRECISION_LIMIT)))

    # XOR float next (n_active & ~upd & is_float)
    xor_active = n_active & (nb1 == _c(1)) & is_float
    rd = jnp.where(xor_active, _c(1, I32), _c(0, I32))
    xb1 = rdw(cur, rd)
    cur = cur + rd
    xor_zero = xor_active & (xb1 == _c(0))
    xor_nz = xor_active & (xb1 == _c(1))
    rd = jnp.where(xor_nz, _c(1, I32), _c(0, I32))
    xb2 = rdw(cur, rd)
    cur = cur + rd
    contained = xor_nz & (xb2 == _c(0))
    uncont = xor_nz & (xb2 == _c(1))
    pl = jnp.where(prev_xor == _c(0), _c(64, I32),
                   lax.clz(prev_xor.astype(I64)).astype(I32))
    pt = jnp.where(prev_xor == _c(0), _c(0, I32),
                   (_num_sig(prev_xor & (~prev_xor + _c(1))) - _c(1, I32)))
    meaningful_c = _c(64, I32) - pl - pt
    rd = jnp.where(contained, meaningful_c, _c(0, I32))
    cbits = _peek(words, cur, rd)
    cur = cur + rd
    rd = jnp.where(uncont, _c(12, I32), _c(0, I32))
    packed = rdw(cur, rd)
    cur = cur + rd
    u_lead = ((packed >> _c(6)) & _c(0x3F)).astype(I32)
    u_meaningful = (packed & _c(0x3F)).astype(I32) + _c(1, I32)
    rd = jnp.where(uncont, u_meaningful, _c(0, I32))
    ubits = _peek(words, cur, rd)
    cur = cur + rd
    u_trail = _c(64, I32) - u_lead - u_meaningful
    new_xor = jnp.where(xor_zero, _c(0),
              jnp.where(contained, _shl(cbits, pt.astype(U64)),
              jnp.where(uncont, _shl(ubits, jnp.clip(u_trail, 0, 63).astype(U64)),
                        prev_xor)))

    # ---- state update ----
    got_float_full = f_is_float | to_float
    n_prev_fbits = jnp.where(got_float_full, jnp.where(f_is_float, f_fbits, n_fbits),
                    jnp.where(xor_active, prev_fbits ^ new_xor, prev_fbits))
    n_prev_xor = jnp.where(got_float_full, jnp.where(f_is_float, f_fbits, n_fbits),
                  jnp.where(xor_active, new_xor, prev_xor))
    n_int_val = jnp.where(diff_active, int_val + signed_diff, int_val)
    prec = prec | (diff_active & (jnp.abs(n_int_val) > _c(_PRECISION_LIMIT, I64)))
    n_is_float = jnp.where(got_float_full, _c(True, jnp.bool_),
                  jnp.where(to_int_upd | (f_active & ~f_is_float),
                            _c(False, jnp.bool_), is_float))
    n_sig = jnp.where(sig_rd_active, new_sig, sig)
    n_mult = jnp.where(sig_rd_active, new_mult, mult)

    err = err | (proceed & (cur > nbits))
    emit = proceed & ~err

    out_ts = jnp.where(emit, new_time, _c(0, I64))
    out_isf = n_is_float
    out_payload = jnp.where(out_isf, n_prev_fbits, n_int_val.astype(U64))
    out_meta = (jnp.where(emit, _c(1, I32), _c(0, I32)) << 4 |
                jnp.where(out_isf, _c(1, I32), _c(0, I32)) << 3 |
                jnp.clip(n_mult, 0, 7)).astype(jnp.uint8)

    # ---- cursor update ----
    # Normal datapoint steps advance to `cur`; annotation steps jump the
    # cursor past the payload (consuming this scan slot without a
    # datapoint); the start word still counts as consumed for them.
    ann_ok = ann & ~err
    new_cursor = jnp.where(ann_ok, ann_end,
                           jnp.where(proceed, cur, cursor))

    # ---- window refill ----
    # Lanes whose cursor crossed into the window's second 16-word block
    # shift down and pull the next block; annotation jumps may leave the
    # window entirely and reload both halves.  All gathers sit behind a
    # scalar predicate: on typical corpora only ~1 step in 15-100 pays.
    new_rel = new_cursor - blk * _c(_BLK_WORDS * 64, I32)
    advanced = proceed | ann_ok
    need_shift = advanced & (new_rel >= _c(_BLK_WORDS * 64, I32)) & (
        new_rel < _c(2 * _BLK_WORDS * 64, I32))
    need_jump = advanced & (new_rel >= _c(2 * _BLK_WORDS * 64, I32))

    def _refill(ops):
        win, bk = ops
        NB = words3.shape[1] - 1
        # Shift path: window [bk, bk+1] -> [bk+1, bk+2].
        bnext = jnp.clip(bk + _c(2, I32), 0, NB)
        nxt = jnp.take_along_axis(
            words3, bnext[:, None, None].astype(jnp.int32), axis=1)[:, 0]
        shifted = jnp.concatenate([win[:, _BLK_WORDS:], nxt], axis=1)
        win = jnp.where(need_shift[:, None], shifted, win)
        bk = jnp.where(need_shift, bk + _c(1, I32), bk)

        # Jump path (annotation skip may leave the window entirely):
        # reload [tb, tb+1] from scratch.  Split behind its OWN scalar
        # cond: at large S the outer cond fires nearly every step
        # (P[any lane shifts] -> 1), but jumps exist only on
        # annotation-carrying streams — the common corpus should not
        # pay the two reload gathers and extra (S, WIN) select per
        # step (profiling round 5: the refill layer dominates the
        # decode scan on XLA-CPU at S=10K).
        def _jump(ops2):
            w2, b2 = ops2
            tb = new_cursor // _c(_BLK_WORDS * 64, I32)
            lo = jnp.take_along_axis(
                words3, jnp.clip(tb, 0, NB)[:, None, None].astype(jnp.int32),
                axis=1)[:, 0]
            hi = jnp.take_along_axis(
                words3,
                jnp.clip(tb + 1, 0, NB)[:, None, None].astype(jnp.int32),
                axis=1)[:, 0]
            reload = jnp.concatenate([lo, hi], axis=1)
            w2 = jnp.where(need_jump[:, None], reload, w2)
            b2 = jnp.where(need_jump, tb, b2)
            return w2, b2

        return lax.cond(jnp.any(need_jump), _jump, lambda o: o, (win, bk))

    window, blk = lax.cond(jnp.any(need_shift | need_jump), _refill,
                           lambda ops: ops, (window, blk))

    consumed = proceed | ann_ok
    new_carry = (
        new_cursor,
        done, err, prec,
        need_start & ~consumed,
        first_val & ~proceed,
        saw_ann,
        jnp.where(proceed, new_time,
                  jnp.where(ann_ok & need_start, nt, prev_time)),
        jnp.where(proceed, pd, prev_delta),
        jnp.where(proceed, new_unit,
                  jnp.where(ann_ok & need_start, unit0, unit_idx)),
        jnp.where(proceed, n_prev_fbits, prev_fbits),
        jnp.where(proceed, n_prev_xor, prev_xor),
        jnp.where(proceed, n_int_val, int_val),
        jnp.where(proceed, n_sig, sig),
        jnp.where(proceed, n_mult, mult),
        jnp.where(proceed, n_is_float, is_float),
        window, blk,
    )
    return new_carry, (out_ts, out_payload, out_meta)


@functools.partial(jax.jit, static_argnames=("max_points", "default_unit"))
def decode_batch_device(words, nbits, max_points: int, default_unit: int = 1):
    """Decode (S, W+1) padded word arrays in parallel.

    Returns (ts (S, max_points) int64, payload (S, max_points) uint64,
    meta (S, max_points) uint8, err (S,), prec (S,), ann (S,)).
    meta: bit4 = valid, bit3 = is_float, bits0-2 = multiplier.
    ``ann`` marks series whose stream carried annotation markers: their
    datapoints are decoded (each annotation consumes one scan slot) but
    the annotation bytes are skipped — callers needing them re-read via
    the scalar iterator.
    """
    S, Wp = words.shape
    # Pad the stream out to whole refill blocks plus one zero block so the
    # window gather never reads out of bounds, and reshape for block pulls.
    NB = -(-Wp // _BLK_WORDS)
    wpad = jnp.pad(words, ((0, 0), (0, (NB + 1) * _BLK_WORDS - Wp)))
    words3 = wpad.reshape(S, NB + 1, _BLK_WORDS)
    nbits32 = nbits.astype(I32)

    carry0 = (
        jnp.zeros(S, I32), jnp.zeros(S, jnp.bool_), jnp.zeros(S, jnp.bool_),
        jnp.zeros(S, jnp.bool_), jnp.ones(S, jnp.bool_),
        jnp.ones(S, jnp.bool_), jnp.zeros(S, jnp.bool_),
        jnp.zeros(S, I64), jnp.zeros(S, I64), jnp.zeros(S, I32),
        jnp.zeros(S, U64), jnp.zeros(S, U64), jnp.zeros(S, I64),
        jnp.zeros(S, I32), jnp.zeros(S, I32), jnp.zeros(S, jnp.bool_),
        wpad[:, :_WIN_WORDS], jnp.zeros(S, I32),
    )
    step = functools.partial(_decode_step, words3=words3, nbits=nbits32,
                             default_unit=default_unit)

    # Decode k datapoints per loop iteration (VERDICT round-3 weak #2:
    # the per-step formulation was flat with scale).  Unrolling chains k
    # step bodies inside one iteration, so the carry — the (S, 32) word
    # window plus ~17 per-lane scalars — stays in registers/fused
    # between them instead of round-tripping memory every datapoint,
    # and the loop's fixed dispatch overhead is paid T/k times.
    carry, (ts, payload, meta) = lax.scan(step, carry0, None,
                                          length=max_points,
                                          unroll=_SCAN_UNROLL)
    # A stream whose EOS marker sits exactly after max_points datapoints never
    # sets done inside the scan; peek once more for it.
    cursor, done = carry[0], carry[1]
    can = (cursor + 11) <= nbits32
    peek11 = jax.vmap(lambda w, c: _peek(w, c, _c(11, I32)))(wpad, cursor)
    eos_tail = can & ((peek11 >> _c(2)) == _c(0x100)) & ((peek11 & _c(3)) == _c(0))
    done = done | eos_tail
    err = carry[2] | (~done)  # not done after max_points -> error
    prec = carry[3]
    ann = carry[6]  # series whose stream carried annotation markers
    return ts.T, payload.T, meta.T, err, prec, ann


def decode_batch(streams: list[bytes], max_points: int,
                 default_unit: Unit = Unit.SECOND,
                 annotations_fallback: bool = True):
    """Host-facing batched decode.

    Returns (timestamps (S, P) int64, values (S, P) float64,
    counts (S,), fallback (S,) bool).  Fallback series (>2^53
    magnitudes, errors) must use the scalar ReaderIterator.

    Annotated streams decode on device (timestamps/values come back
    correct; each annotation consumes one max_points slot) but their
    annotation BYTES are skipped, so by default they still flag
    fallback for callers that need the bytes (tag payloads, proto
    schemas); pass annotations_fallback=False when only the numeric
    series matters.
    """
    words, nbits = pack_streams(streams)
    ts, payload, meta, err, prec, ann = decode_batch_device(
        jnp.asarray(words), jnp.asarray(nbits), max_points=max_points,
        default_unit=int(default_unit))
    ts = np.asarray(ts)
    payload = np.asarray(payload)
    meta = np.asarray(meta)
    valid = (meta & 16) != 0
    isf = (meta & 8) != 0
    mult = (meta & 7).astype(np.int64)
    fvals = payload.view(np.float64)
    ivals = payload.astype(np.int64).astype(np.float64) / np.power(10.0, mult)
    values = np.where(isf, fvals, ivals)
    counts = valid.sum(axis=1)
    ann_np = np.asarray(ann)
    if ann_np.any():
        # Annotation slots leave holes in annotated rows; compact each
        # row's valid datapoints to a prefix (the contract counts rely on).
        ts = ts.copy()
        values = values.copy()
        for i in np.nonzero(ann_np)[0]:
            m = valid[i]
            k = int(m.sum())
            ts[i, :k] = ts[i, m]
            values[i, :k] = values[i, m]
            ts[i, k:] = 0
            values[i, k:] = 0.0
    fallback = np.asarray(err) | np.asarray(prec)
    if annotations_fallback:
        fallback = fallback | ann_np
    return ts, values, counts, fallback
