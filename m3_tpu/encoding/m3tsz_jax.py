"""Batched M3TSZ encode/decode as JAX array programs.

The reference codec is an inherently sequential per-series bit-stream
state machine (``src/dbnode/encoding/m3tsz/encoder.go``,
``iterator.go``).  The TPU-native formulation:

* **Encode** — two phases, the mirror of decode (round 9).  Phase 1 is
  a ``lax.scan`` over timesteps carrying ONLY the narrow codec control
  state (timestamp delta, XOR hysteresis, sig-bit tracker), emitting
  per-datapoint lane tables: four (value, width) fields per point,
  composed with static shift-ors — no bit assembly rides the scan.
  Phase 2 computes every datapoint's absolute output bit offset with
  ONE exclusive prefix sum over the widths and assembles output words
  scatter-free (cumsum-interval gathers, or the Pallas placement
  kernel on TPU — ``M3_ENCODE_PLACE``; disjoint bit ranges make add
  equivalent to or).
* **Decode** — ``lax.scan`` over datapoint slots operating on (S,)
  arrays, with a dynamic bit-cursor per series.  Bit reads never touch
  memory: each lane carries a 32-word (2048-bit) window of its stream
  in the scan carry, field reads are register-level selects/shifts
  against a 9-word buffer extracted once per step, and the window is
  refilled 16 words at a time by a block gather guarded by a scalar
  ``lax.cond`` (so the O(S*W) gather cost is paid only on the ~1/15th
  of steps where some lane runs low, not ~24x per step as a naive
  per-field gather formulation would).  100K series decode in parallel
  — the batched ReaderIterator configuration from BASELINE.json.
* All float64 arithmetic demanded by the format (int-optimization
  classification, ``m3tsz.go:78-118``) runs as exact integer emulation
  (``f64_emul.py``), so results are bit-identical on TPU, which has no
  float64 ALU.

Series that would exercise the reference's float64 *rounding* behavior on
values above 2^53, or that carry annotations, are flagged in the returned
``fallback`` mask; callers re-run those through the scalar host codec
(``m3tsz.py``).  This mirrors the host/device split the framework uses
throughout: the device owns the dense numeric 99.99%, the host owns the
long tail.
"""

from __future__ import annotations

import functools
import os
import threading

import numpy as np

import m3_tpu  # noqa: F401  (enables x64 at the framework root)
import jax
import jax.numpy as jnp
from jax import lax

from m3_tpu.core.xtime import Unit
from m3_tpu.encoding import f64_emul as fe
from m3_tpu.encoding.scheme import tail_bytes
from m3_tpu.x import devguard, membudget

U64 = jnp.uint64
I64 = jnp.int64
I32 = jnp.int32
MASK64 = (1 << 64) - 1

# Datapoints encoded per scan-loop iteration (lax.scan unroll): larger
# amortizes per-step overhead and keeps the carry fused between chained
# bodies, but MULTIPLIES compile time of the step body (unroll=4 took
# the S=2000 decode compile from ~40s to 9+ minutes on XLA-CPU —
# measured round 4; the round-5 "unroll=2 decodes 13x slower" spill
# was the old WIDE-carry formulations' — both are gone since the
# two-phase splits).  Round-9 measurement on the narrow-carry encode
# scan: unroll=2 is compile-slower and within noise at steady state on
# XLA-CPU, so the default stays 1; the TPU tradeoff is separately
# measured by the watcher's decode_u* stages.
try:
    _SCAN_UNROLL = max(1, int(os.environ.get("M3_SCAN_UNROLL", "1")))
except ValueError:
    _SCAN_UNROLL = 1
# The DECODE scan's unroll is tuned separately: its carry is a handful
# of narrow (S,) lanes (no word window since the round-6 two-phase
# split), so chaining two step bodies wins ~11% on XLA-CPU where the
# encode scan's wide carry still spills.
try:
    _DECODE_UNROLL = max(1, int(os.environ.get("M3_DECODE_UNROLL", "2")))
except ValueError:
    _DECODE_UNROLL = 2

# time-unit byte -> nanos (0 = invalid/None)
_UNIT_NANOS = np.zeros(16, dtype=np.int64)
for _u_ in Unit:
    _UNIT_NANOS[int(_u_)] = _u_.nanos()

_BITS_1E13 = np.frombuffer(np.float64(10.0**13).tobytes(), dtype=np.uint64)[0]
_BITS_2_63 = np.frombuffer(np.float64(2.0**63).tobytes(), dtype=np.uint64)[0]
_I64_MIN = -(2**63)
_PRECISION_LIMIT = 1 << 53  # beyond this the reference's f64 math rounds


def _c(x, dtype=U64):
    return jnp.asarray(x, dtype=dtype)


def _shl(v, s):
    """uint64 << s with s possibly >= 64 (yields 0)."""
    s = _c(s)
    return jnp.where(s >= _c(64), _c(0), v << jnp.minimum(s, _c(63)))


def _shr(v, s):
    s = _c(s)
    return jnp.where(s >= _c(64), _c(0), v >> jnp.minimum(s, _c(63)))


def _num_sig(v):
    """Number of significant bits of uint64 (0 for 0)."""
    return jnp.where(
        v == _c(0), _c(0, I32),
        (_c(64, I32) - lax.clz(v.astype(I64)).astype(I32)))


def _sign_extend(v, nbits):
    """Sign-extend the low ``nbits`` of uint64 v to int64 (nbits >= 1)."""
    shift = _c(64) - _c(nbits)
    return (_shl(v, shift)).astype(I64) >> jnp.minimum(shift, _c(63)).astype(I64)


# ---------------------------------------------------------------------------
# Value classification: exact convertToIntFloat (m3tsz.go:78-118)
# ---------------------------------------------------------------------------


def _mul10_me(mant, exp2):
    """Exact IEEE float64 multiply by 10 in the (mantissa, exp2)
    representation: value = mant * 2^exp2, mant < 2^53 (mant in
    [2^52, 2^53) for normals, unnormalized with exp2 == -1074 for
    subnormals).  Equivalent to ``fe.mul10(bits)`` without the
    pack/unpack round-trip through the bit representation — the
    classify loop below runs this 7 times per datapoint, and the
    full ``_pack`` (msb search, subnormal clamps, carry fixes) was
    ~3x the ops of this direct form (round-9 encode profiling)."""
    p = mant * _c(10)  # < 2^57: never overflows
    L = fe.msb_index(jnp.maximum(p, _c(1)))
    sh = jnp.maximum(L, _c(52)) - _c(52)
    # sh > 0 only when p >= 2^53, i.e. the result is normal and RNE
    # rounds at its 53-bit ulp; p < 2^53 stays exact at the carried
    # exp2 granularity (subnormals keep their fixed 2^-1074 ulp, and
    # exp2 + sh can never sink below -1074 since sh >= 0).
    q = fe._round_shift_right_even(p, sh)
    carried = q >= _c(1 << 53)
    q = jnp.where(carried, q >> _c(1), q)
    exp2p = exp2 + sh.astype(I64) + carried.astype(I64)
    return q, jnp.where(mant == _c(0), exp2, exp2p)


def classify_value(v_bits, cur_mult):
    """Returns (val int64 scaled, mult int32, is_float bool, precision_flag bool).

    ``precision_flag`` marks values whose downstream encoding would hit
    float64 rounding in the reference (|val| > 2^53): callers must fall
    back to the scalar codec for those series.
    """
    v_bits = _c(v_bits)
    sign = (v_bits >> _c(63)) != _c(0)
    abs_b = v_bits & _c(fe.MASK63)
    _, exp, _ = fe.split(abs_b)
    special = exp == _c(0x7FF)  # NaN / Inf never take the int paths

    # Quick path: already integral and v < 2^63 (float compare).
    ipart0, frac_zero0 = fe.floor_parts(abs_b)
    v_lt_maxint = sign | (abs_b < _c(_BITS_2_63))
    quick_ok = (cur_mult == _c(0, I32)) & v_lt_maxint & frac_zero0 & ~special
    # Go's uint64(int64(v)) saturation for out-of-range magnitudes.
    sat = abs_b >= _c(_BITS_2_63)
    quick_mag = jnp.where(sat, _c(_I64_MIN, I64), ipart0.astype(I64))
    quick_val = jnp.where(sign & ~sat, -quick_mag, quick_mag)

    # Multiplier loop: val = v * 10^cur, then *10 per iteration, looking
    # for a value within 1 ulp of an integer.  The loop runs in the
    # (mantissa, exp2) domain: with s = -exp2 and frac = mant & (2^s-1),
    # the reference's Modf/Nextafter conditions (see the scalar codec's
    # ulp reduction, and the bits-domain forms this replaced:
    # ``val_bits <= bits(ip)+1`` / ``val_bits+1 >= bits(ip+1)``) reduce
    # EXACTLY to ``frac <= 1`` / ``frac >= 2^s - 1``: positive float
    # bit patterns are value-ordered and increment across binades, so
    # "within one ulp of an integer" is a pure property of the fraction
    # field.  This cuts the two uint_to_f64_bits packs + floor_parts +
    # full mul10 per iteration (~110 ops) to ~50, and every byte is
    # still pinned by the oracle/corpus/fuzz suites.
    val_bits0 = fe.mul_pow10(abs_b, cur_mult)
    mant, exp2 = fe._mantissa_and_exp2(val_bits0)
    found = jnp.zeros_like(sign)
    res_i = jnp.zeros_like(abs_b)
    res_mult = jnp.zeros_like(cur_mult)
    for k in range(7):
        # current value's bit pattern (monotone compare key): normals
        # re-pack from (mant, exp2); subnormals (unnormalized mant,
        # exp2 == -1074) ARE their bit pattern.
        vb_cur = jnp.where(
            mant < _c(fe.IMPLICIT), mant,
            ((exp2 + _c(1075, I64)).astype(U64) << _c(52))
            | (mant & _c(fe.MASK52)))
        active = (~quick_ok) & (~found) & (_c(k, I32) >= cur_mult) & (
            vb_cur < _c(_BITS_1E13)) & ~special
        s = jnp.clip(-exp2, 0, 63).astype(U64)
        big_s = -exp2 > _c(63, I64)  # val << 1: ip == 0, frac == mant
        frac = mant & ((_c(1) << s) - _c(1))
        frac = jnp.where(big_s, mant, frac)
        ip = jnp.where(big_s, _c(0), mant >> s)
        # active lanes have val < 1e13 < 2^53 => exp2 <= 0, so the
        # s == -exp2 clamp only ever bites inactive lanes (discarded).
        take_i = frac <= _c(1)
        take_i1 = (~take_i) & (frac >= ((_c(1) << s) - _c(1)))
        hit = active & (take_i | take_i1)
        chosen = jnp.where(take_i, ip, ip + _c(1))
        res_i = jnp.where(hit, chosen, res_i)
        res_mult = jnp.where(hit, _c(k, I32), res_mult)
        found = found | hit
        advance = active & ~hit
        m10, e10 = _mul10_me(mant, exp2)
        mant = jnp.where(advance, m10, mant)
        exp2 = jnp.where(advance, e10, exp2)

    loop_val = jnp.where(sign, -(res_i.astype(I64)), res_i.astype(I64))

    is_float = ~quick_ok & ~found
    val = jnp.where(quick_ok, quick_val, jnp.where(found, loop_val, _c(0, I64)))
    mult = jnp.where(found & ~quick_ok, res_mult, _c(0, I32))
    # Signed compares (not jnp.abs) so INT64_MIN saturations are caught too.
    precision_flag = ~is_float & ((val > _c(_PRECISION_LIMIT, I64)) |
                                  (val < _c(-_PRECISION_LIMIT, I64)))
    return val, mult, is_float, precision_flag


# ---------------------------------------------------------------------------
# Encoder phase 1: branchless per-datapoint lane emission
# ---------------------------------------------------------------------------
#
# The round-9 mirror of the two-phase decode: the sequential scan no
# longer ASSEMBLES bits (the old formulation threaded a 4-word staging
# buffer through ~25 dynamic-offset `_bb_append` funnels per step —
# ~7.8K element-ops/datapoint, and the reason encode compiled in ~11s
# and ran at ~0.5M dp/s while decode did 7M).  Phase 1 only RESOLVES
# the format: each datapoint's emission is a concatenation of a
# bounded set of variable-width fields, and every path's fields fold
# into at most FOUR value lanes, each <= 64 bits, composed with plain
# shift-ors (static in-lane offsets — no funnel):
#
#   t0  timestamp control+payload: the dod opcode fused with its
#       payload when it fits a word (<= 36 bits), or the 19-bit
#       TU-marker prefix / 4-bit default-bucket opcode otherwise
#   t1  the 64-bit dod payload (TU path / default bucket), else empty
#   v0  value control: mode/update/sig/mult/sign or XOR opcode+lead/
#       meaningful fields (<= 16 bits)
#   v1  value payload: full float, XOR window, or int diff (<= 64)
#
# Widths ride four i32 lanes beside the values; the scan stacks both
# as (T, 4, S) tables whose (4T, S) stream-order reshape is free.
# Phase 2 turns the widths into absolute bit offsets with ONE
# exclusive prefix sum and assembles output words from the
# (value, offset, width) lanes — see `_encode_batch_device`.  The lane
# table is format-agnostic on purpose: a DeXOR-class codec (ROADMAP
# item 5) emits through the same (value, width) contract with its own
# field resolution.


def _cat(acc, add_val, add_n, enable=None):
    """Append the low ``add_n`` (< 64, possibly traced) bits of
    ``add_val`` to the (value, nbits) accumulator — MSB-first: earlier
    fields land in higher bits, matching OStream order."""
    val, n = acc
    add_n = _c(add_n, I32)
    if enable is not None:
        add_n = jnp.where(enable, add_n, _c(0, I32))
    sh = add_n.astype(U64)
    val = (val << sh) | (_c(add_val) & ((_c(1) << sh) - _c(1)))
    return val, n + add_n


# Non-default delta-of-delta buckets: (opcode, num_opcode_bits, num_value_bits).
_DOD_BUCKETS = ((0b10, 2, 7), (0b110, 3, 9), (0b1110, 4, 12))


def _dod_lanes(dod, default_unit_is_32bit: bool):
    """Bucketed delta-of-delta (timestamp_encoder.go:131-221) as lane
    fields: (t0, n_t0, need64, overflow).  Opcode and payload compose
    into the single <= 36-bit t0 field except the 64-bit default
    bucket, whose payload rides the t1 lane (``need64``); ``overflow``
    marks a dod outside the 32-bit default bucket of second/
    millisecond units (the reference raises OverflowError there)."""
    d = dod.astype(U64)
    is_zero = dod == _c(0, I64)
    fits = []
    for _, _, nvb in _DOD_BUCKETS:
        lo, hi = -(1 << (nvb - 1)), (1 << (nvb - 1)) - 1
        fits.append((dod >= _c(lo, I64)) & (dod <= _c(hi, I64)))
    t1_ = (~is_zero) & fits[0]
    t2_ = (~is_zero) & ~fits[0] & fits[1]
    t3_ = (~is_zero) & ~fits[1] & fits[2]
    take_def = (~is_zero) & ~fits[2]
    if default_unit_is_32bit:
        t0_def = (_c(0b1111) << _c(32)) | (d & _c(0xFFFFFFFF))
        n_def = _c(36, I32)
        need64 = jnp.zeros_like(is_zero)
        overflow = take_def & ((dod < _c(-(2**31), I64))
                               | (dod > _c(2**31 - 1, I64)))
    else:
        t0_def = _c(0b1111)
        n_def = _c(4, I32)
        need64 = take_def
        overflow = jnp.zeros_like(is_zero)
    t0 = jnp.where(
        is_zero, _c(0),
        jnp.where(t1_, (_c(0b10) << _c(7)) | (d & _c(0x7F)),
        jnp.where(t2_, (_c(0b110) << _c(9)) | (d & _c(0x1FF)),
        jnp.where(t3_, (_c(0b1110) << _c(12)) | (d & _c(0xFFF)), t0_def))))
    n_t0 = jnp.where(
        is_zero, _c(1, I32),
        jnp.where(t1_, _c(9, I32),
        jnp.where(t2_, _c(12, I32),
        jnp.where(t3_, _c(16, I32), n_def))))
    return t0, n_t0, need64, overflow


def _int_sig_mult_ctrl(acc, num_sig_st, max_mult, sig, mult, float_changed):
    """writeIntSigMult (encoder.go:235-250) as control-field
    composition onto ``acc``: the sig-change cascade
    (sb1 [sb2 sig6]) then the multiplier update (mb1 [mult3]).
    Returns (acc, new num_sig, new max_mult)."""
    sig_changed = num_sig_st != sig
    zero_sig = sig == _c(0, I32)
    acc = _cat(acc, jnp.where(sig_changed, _c(1), _c(0)), 1)
    acc = _cat(acc, jnp.where(zero_sig, _c(0), _c(1)), 1, enable=sig_changed)
    acc = _cat(acc, (sig - _c(1, I32)).astype(U64), 6,
               enable=sig_changed & ~zero_sig)
    mult_up = mult > max_mult
    # after WriteIntSig num_sig == sig, so condition reduces to:
    float_only = (~mult_up) & (max_mult == mult) & float_changed
    wr = mult_up | float_only
    acc = _cat(acc, jnp.where(wr, _c(1), _c(0)), 1)
    acc = _cat(acc, mult.astype(U64), 3, enable=wr)
    return acc, sig, jnp.where(mult_up, mult, max_mult)


def _track_new_sig(num_sig_st, cur_hl, num_lower, sig):
    """IntSigBitsTracker.TrackNewSig (int_sig_bits_tracker.go:68-91)."""
    new_sig = num_sig_st
    grow = sig > num_sig_st
    new_sig = jnp.where(grow, sig, new_sig)
    shrink = (~grow) & ((num_sig_st - sig) >= _c(3, I32))
    chl = jnp.where(shrink & (num_lower == _c(0, I32)), sig,
                    jnp.where(shrink & (sig > cur_hl), sig, cur_hl))
    # The lower-sig streak counter resets only on the NEITHER branch
    # (within-threshold sig): a GROW step leaves it intact — Go keeps
    # t.NumLowerSig untouched when numSig > t.NumSig
    # (int_sig_bits_tracker.go:68-91).  Resetting on grow desynced the
    # device encoder's shrink timing from the scalar oracle on
    # grow-interleaved streams (caught by the round-5 bench's
    # device-vs-native byte-identity stage, 22/2000 series).
    nl = jnp.where(shrink, num_lower + _c(1, I32),
                   jnp.where(grow, num_lower, _c(0, I32)))
    fire = shrink & (nl >= _c(5, I32))
    new_sig = jnp.where(fire, chl, new_sig)
    nl = jnp.where(fire, _c(0, I32), nl)
    return new_sig, chl, nl


def _encode_step(carry, xs, unit: int, default_unit_is_32bit: bool):
    """One datapoint for one series: resolve the format (field values
    and widths) WITHOUT assembling bits.  The carry is only the narrow
    codec control state; the step emits the four value lanes
    (t0, t1, v0, v1) plus their packed widths — see the lane-table
    comment above — and phase 2 (`_encode_batch_device`) places them
    into the output stream with one prefix sum.  The body is one
    branch-free straight line, mirroring the decode step's contract."""
    (prev_time, prev_delta, tu_none, int_val, max_mult, is_float,
     prev_fbits, prev_xor, num_sig_st, cur_hl, num_lower, is_first,
     fallback) = carry
    t, v_bits, valid = xs

    # ---- timestamp (timestamp_encoder.go:72-129) ----
    # first datapoint of the stream: 64-bit start already emitted by the
    # caller via the start word (prev_time holds start).  Time-unit
    # change marker if the initial unit was None (unaligned start):
    # 0x100 marker(9) + TU opcode(2) + unit byte(8) — one 19-bit static
    # constant — then the full 64-bit nanosecond dod on the t1 lane.
    emit_tu = is_first & tu_none
    time_delta = t - prev_time
    dod_ns = time_delta - prev_delta
    unit_nanos = int(Unit(unit).nanos())
    dod_units = dod_ns // _c(unit_nanos, I64)  # deltas divisible (checked below)
    div_ok = (dod_ns % _c(unit_nanos, I64)) == _c(0, I64)
    t0_b, n_t0_b, need64, dod_overflow = _dod_lanes(dod_units,
                                                    default_unit_is_32bit)
    tu_const = (0x100 << 10) | (0b10 << 8) | (unit & 0xFF)
    t0 = jnp.where(emit_tu, _c(tu_const), t0_b)
    n_t0 = jnp.where(emit_tu, _c(19, I32), n_t0_b)
    t1_64 = emit_tu | (need64 & ~emit_tu)
    t1 = jnp.where(emit_tu, dod_ns.astype(U64), dod_units.astype(U64))
    n_t1 = jnp.where(t1_64, _c(64, I32), _c(0, I32))
    new_prev_delta = jnp.where(emit_tu, _c(0, I64), time_delta)
    new_prev_time = t
    new_tu_none = tu_none & ~emit_tu

    # ---- value ----
    val, mult, v_is_float, prec = classify_value(v_bits, max_mult)
    acc0 = (_c(0), _c(0, I32))

    # ---------- first value (encoder.go:112-146) ----------
    # float mode: '1' + the raw 64 bits; int mode: '0' + sig/mult
    # cascade + sign on v0, the magnitude (sig_f bits) on v1.  The
    # cascade itself is emitted by the SHARED _int_sig_mult_ctrl call
    # below (first-value and to-int-update paths run the identical
    # writeIntSigMult; only the opcode prefix, the candidate sig and
    # the float_changed flag differ, so the inputs select per path
    # instead of running the ~60-op cascade twice).
    neg_diff = val >= _c(0, I64)  # inverted: diff = 0 - val
    mag = jnp.abs(val).astype(U64)
    sig_f = _num_sig(mag)

    # ---------- next value (encoder.go:148-231) ----------
    val_diff = int_val - val
    # float path trigger (diff overflow impossible: flagged by prec limit)
    go_float = v_is_float
    was_int = ~is_float

    # writeFloatVal: int->float '001'+float64; repeat '01'; else '1' +
    # Gorilla XOR (float_encoder_iterator.go:82-103) — zero '0',
    # contained '10'+window, uncontained '11'+lead6+meaningful6+window
    # (the leading '1' value bit fuses into each opcode below).
    repeat_f = is_float & (v_bits == prev_fbits)
    cur_xor = prev_fbits ^ v_bits
    xor_zero = cur_xor == _c(0)
    pl = jnp.where(prev_xor == _c(0), _c(64, I32),
                   lax.clz(prev_xor.astype(I64)).astype(I32))
    # trailing zeros = index of lowest set bit
    pt = jnp.where(prev_xor == _c(0), _c(0, I32),
                   (_num_sig(prev_xor & (~prev_xor + _c(1))) - _c(1, I32)))
    cl = lax.clz(jnp.maximum(cur_xor, _c(1)).astype(I64)).astype(I32)
    ct = _num_sig(cur_xor & (~cur_xor + _c(1))) - _c(1, I32)
    contained = (~xor_zero) & (cl >= pl) & (ct >= pt)
    meaningful = _c(64, I32) - cl - ct
    v0_unc = ((_c(0b111) << _c(12)) | (cl.astype(U64) << _c(6))
              | (meaningful - _c(1, I32)).astype(U64))
    v0_f = jnp.where(was_int, _c(0b001),
           jnp.where(repeat_f, _c(0b01),
           jnp.where(xor_zero, _c(0b10),
           jnp.where(contained, _c(0b110), v0_unc))))
    n_v0_f = jnp.where(was_int, _c(3, I32),
             jnp.where(repeat_f | xor_zero, _c(2, I32),
             jnp.where(contained, _c(3, I32), _c(15, I32))))
    v1_f = jnp.where(was_int, v_bits,
           jnp.where(contained, _shr(cur_xor, pt.astype(U64)),
                     _shr(cur_xor, ct.astype(U64))))
    n_v1_f = jnp.where(was_int, _c(64, I32),
             jnp.where(repeat_f | xor_zero, _c(0, I32),
             jnp.where(contained, _c(64, I32) - pl - pt, meaningful)))
    nxor = jnp.where(xor_zero, _c(0), cur_xor)
    st_float = dict(
        int_val=int_val,
        is_float=_c(True, jnp.bool_),
        max_mult_i=jnp.where(was_int, mult, max_mult),
        prev_fbits=v_bits,
        prev_xor=jnp.where(was_int, v_bits, jnp.where(repeat_f, prev_xor, nxor)),
        num_sig=num_sig_st, cur_hl=cur_hl, num_lower=num_lower,
    )

    # writeIntVal: repeat '01'; update '000'+cascade+sign+diff;
    # no-update '1'+sign+diff
    repeat_i = (val_diff == _c(0, I64)) & (~is_float) & (mult == max_mult)
    neg = val_diff < _c(0, I64)
    diff_mag = jnp.abs(val_diff).astype(U64)
    sig_n = _num_sig(diff_mag)
    new_sig, t_chl, t_nl = _track_new_sig(num_sig_st, cur_hl, num_lower, sig_n)
    float_changed = is_float  # is_float state true means mode changes to int
    need_update = (mult > max_mult) | (num_sig_st != new_sig) | float_changed

    # THE shared writeIntSigMult cascade: both opcode prefixes are
    # zero-valued ('0' first-value mode bit / '000' update escape), so
    # only the prefix WIDTH and the cascade inputs select per path.
    acc_sh = _cat(acc0, _c(0), jnp.where(is_first, _c(1, I32), _c(3, I32)))
    acc_sh, ns_sh, mm_sh = _int_sig_mult_ctrl(
        acc_sh, num_sig_st, max_mult,
        jnp.where(is_first, sig_f, new_sig), mult,
        (~is_first) & float_changed)
    acc_sh = _cat(acc_sh, jnp.where(jnp.where(is_first, neg_diff, neg),
                                    _c(1), _c(0)), 1)

    v0_first = jnp.where(v_is_float, _c(1), acc_sh[0])
    n_v0_first = jnp.where(v_is_float, _c(1, I32), acc_sh[1])
    v1_first = jnp.where(v_is_float, v_bits, mag)
    n_v1_first = jnp.where(v_is_float, _c(64, I32), ns_sh)
    st_first = dict(
        int_val=jnp.where(v_is_float, int_val, val),
        is_float=v_is_float,
        prev_fbits=jnp.where(v_is_float, v_bits, prev_fbits),
        prev_xor=jnp.where(v_is_float, v_bits, prev_xor),
        num_sig=jnp.where(v_is_float, num_sig_st, ns_sh),
        max_mult_i=jnp.where(v_is_float, mult, mm_sh),
        cur_hl=cur_hl, num_lower=num_lower,
    )

    ns_iu, mm_iu = ns_sh, mm_sh
    v0_i = jnp.where(repeat_i, _c(0b01),
           jnp.where(need_update, acc_sh[0],
                     _c(0b10) | jnp.where(neg, _c(1), _c(0))))
    n_v0_i = jnp.where(repeat_i | ~need_update, _c(2, I32), acc_sh[1])
    v1_i = diff_mag
    n_v1_i = jnp.where(repeat_i, _c(0, I32),
             jnp.where(need_update, ns_iu, num_sig_st))
    st_int = dict(
        int_val=jnp.where(repeat_i, int_val, val),
        is_float=jnp.where(repeat_i, is_float, _c(False, jnp.bool_)),
        max_mult_i=jnp.where(repeat_i, max_mult,
                             jnp.where(need_update, mm_iu, max_mult)),
        prev_fbits=prev_fbits, prev_xor=prev_xor,
        num_sig=jnp.where(repeat_i, num_sig_st,
                          jnp.where(need_update, ns_iu, num_sig_st)),
        cur_hl=jnp.where(repeat_i, cur_hl, t_chl),
        num_lower=jnp.where(repeat_i, num_lower, t_nl),
    )

    v0_next = jnp.where(go_float, v0_f, v0_i)
    n_v0_next = jnp.where(go_float, n_v0_f, n_v0_i)
    v1_next = jnp.where(go_float, v1_f, v1_i)
    n_v1_next = jnp.where(go_float, n_v1_f, n_v1_i)
    st_next = {
        k: jnp.where(go_float, st_float[k], st_int[k])
        for k in st_float
    }

    v0 = jnp.where(is_first, v0_first, v0_next)
    n_v0 = jnp.where(is_first, n_v0_first, n_v0_next)
    v1 = jnp.where(is_first, v1_first, v1_next)
    n_v1 = jnp.where(is_first, n_v1_first, n_v1_next)
    st = {
        k: jnp.where(is_first, st_first[k], st_next[k])
        for k in st_first
    }

    # inactive (padding) steps emit nothing (all widths 0) and keep state
    zero_w = _c(0, I32)
    n_t0 = jnp.where(valid, n_t0, zero_w)
    n_t1 = jnp.where(valid, n_t1, zero_w)
    n_v0 = jnp.where(valid, n_v0, zero_w)
    n_v1 = jnp.where(valid, n_v1, zero_w)

    def keep(new, old):
        return jnp.where(valid, new, old)

    fallback = (fallback | (valid & prec) | (valid & ~div_ok & ~emit_tu)
                | (valid & dod_overflow & ~emit_tu))
    new_carry = (
        keep(new_prev_time, prev_time),
        keep(new_prev_delta, prev_delta),
        keep(new_tu_none, tu_none),
        keep(st["int_val"], int_val),
        keep(st["max_mult_i"], max_mult),
        keep(st["is_float"], is_float),
        keep(st["prev_fbits"], prev_fbits),
        keep(st["prev_xor"], prev_xor),
        keep(st["num_sig"], num_sig_st),
        keep(st["cur_hl"], cur_hl),
        keep(st["num_lower"], num_lower),
        is_first & ~valid,
        fallback,
    )
    return new_carry, (t0, t1, v0, v1, n_t0, n_t1, n_v0, n_v1)


_PLACE_IMPLS = ("scatter", "gather", "pallas")


def resolved_place() -> str:
    """Which phase-2 word-placement formulation the encoder uses on
    this process' backend; ``M3_ENCODE_PLACE`` overrides (parity tests
    pin all of them).  Resolved on the HOST, outside the trace, and
    passed as a static argument — an env read under the tracer is
    frozen into the first compile and the seam silently stops
    responding (retrace-risk; exactly how the in-process override was
    broken until round 7).  auto = ``pallas`` only on a real TPU
    backend (the clean-fallback contract tier-1 pins, like
    M3_DECODE_EXTRACT), ``gather`` everywhere else."""
    place = os.environ.get("M3_ENCODE_PLACE", "").strip()
    if place:
        if place not in _PLACE_IMPLS:
            raise ValueError(
                f"M3_ENCODE_PLACE={place!r}: expected one of {_PLACE_IMPLS}")
        return place
    return "pallas" if jax.default_backend() == "tpu" else "gather"


def fallback_place(place: str) -> str:
    """The devguard stepping-down rule for the encode placement seam,
    owned ONCE (encode_batch_device + parallel/sharded_encode): a
    classified device failure re-runs through the cheap-compile jnp
    scatter tail, or gather when scatter IS the primary — every tail
    is byte-identical, so the choice is purely about compile cost."""
    return "scatter" if place != "scatter" else "gather"


def _lane_frags(valq, pos, n):
    """One (value, bit offset, width) lane class -> its two word
    fragments.  ``valq`` holds the field right-aligned (low ``n``
    bits); the MSB-aligned 64-bit image splits across stream words
    ``pos >> 6`` and ``pos >> 6 + 1``.  Returns (hi, lo, gw)."""
    vm = jnp.where(n > _c(0, I32),
                   valq << ((_c(64, I32) - n) & _c(63, I32)).astype(U64),
                   _c(0))
    sh = (pos & _c(63, I32)).astype(U64)
    hi = vm >> sh
    lo = jnp.where(sh > _c(0), vm << ((_c(64) - sh) & _c(63)), _c(0))
    return hi, lo, pos >> _c(6, I32)


def encode_batch_device(timestamps, value_bits, start, valid, unit: int = 1,
                        out_words: int = 0, prefix_bits=None,
                        place: str = "auto"):
    """Encode (S, T) series on device (host wrapper: resolves the
    placement seam outside the trace, then dispatches to the jitted
    implementation with ``place`` as a static argument).

    Args:
      timestamps: (S, T) int64 UnixNanos, padded entries arbitrary.
      value_bits: (S, T) uint64 float64 bit patterns.
      start: (S,) int64 encoder start times.
      valid: (S, T) bool mask of real datapoints (prefix True).
      unit: static time unit (wire byte value).
      out_words: static output width in 64-bit words per series
        (0 -> T * 16 bits / 64 + 4).
      prefix_bits: optional (S,) int32 — bits reserved after the start
        word for a host-composed prefix (the first datapoint's
        annotation marker+varint+bytes, spliced in by ``encode_batch``);
        all emitted fields shift right by this amount.
      place: phase-2 placement impl (see ``resolved_place``); "auto"
        resolves per backend/env here on the host.

    Returns dict with packed words (S, W) uint64 (starting with the 64-bit
    start time), total_bits (S,), fallback (S,) bool.
    """
    if place == "auto":
        place = resolved_place()
    if place not in _PLACE_IMPLS:
        raise ValueError(f"place={place!r}: expected one of "
                         f"{_PLACE_IMPLS + ('auto',)}")
    S, T = timestamps.shape
    ow = out_words if out_words else (T * 16) // 64 + 4

    def _run(p: str):
        # the jitted program with the placement as a STATIC argument —
        # the guard's fallback is just a different static value, so
        # nothing retraces and the happy path stays transfer-free
        # (hops --check)
        return _encode_batch_device(
            timestamps, value_bits, start, valid, unit=unit,
            out_words=out_words, prefix_bits=prefix_bits, place=p)

    # device-guard seam: a classified device failure re-runs the SAME
    # batch through the cheap-compile jnp scatter tail (or gather when
    # scatter IS the primary) — all placements are byte-identical
    # (PINNED_ENCODE_DIGEST + the fuzz suite pin every tail).  Budget
    # admission for the transient lane tables happens ONCE, outside
    # the guard, at the WORSE of the primary/fallback tails' footprints
    # (the formulas are per-tail since round 13, XLA-verified by the
    # costs artifact): an admission reject is not a device fault the
    # fallback can relieve — it raises typed here without touching the
    # stage breaker.
    lane_bytes = max(
        membudget.encode_lane_bytes(S, T, ow, place=place),
        membudget.encode_lane_bytes(S, T, ow, place=fallback_place(place)))
    with membudget.transient("encode.lanes", lane_bytes):
        return devguard.run_guarded("encode", lambda: _run(place),
                                    lambda: _run(fallback_place(place)))


def _encode_carry0(S: int, start, unit: int):
    """Phase-1 initial carry (shared with the profile harness — the
    decode side's ``_decode_carry0`` precedent: one owner for the
    carry layout, so a layout change can't silently desync a proxy)."""
    tu_none = (start % jnp.asarray(int(Unit(unit).nanos()), I64)) != 0
    return (
        start.astype(I64),                      # prev_time
        jnp.zeros(S, I64),                      # prev_delta
        tu_none,                                # initial unit None?
        jnp.zeros(S, I64),                      # int_val
        jnp.zeros(S, I32),                      # max_mult
        jnp.zeros(S, jnp.bool_),                # is_float
        jnp.zeros(S, U64),                      # prev_fbits
        jnp.zeros(S, U64),                      # prev_xor
        jnp.zeros(S, I32),                      # num_sig
        jnp.zeros(S, I32),                      # cur_highest_lower_sig
        jnp.zeros(S, I32),                      # num_lower_sig
        jnp.ones(S, jnp.bool_),                 # is_first
        jnp.zeros(S, jnp.bool_),                # fallback
    )


@functools.partial(jax.jit, static_argnames=("unit", "out_words", "place"))
def _encode_batch_device(timestamps, value_bits, start, valid, unit: int = 1,
                         out_words: int = 0, prefix_bits=None,
                         place: str = "gather"):
    S, T = timestamps.shape
    if out_words == 0:
        out_words = (T * 16) // 64 + 4
    u = Unit(unit)
    default_32 = u in (Unit.SECOND, Unit.MILLISECOND)

    carry0 = _encode_carry0(S, start, unit)

    step = functools.partial(_encode_step, unit=unit,
                             default_unit_is_32bit=default_32)
    vstep = jax.vmap(step)

    def scan_fn(carry, xs):
        c2, (t0, t1, v0, v1, n0, n1, n2, n3) = vstep(carry, xs)
        # Stack the four lanes in STREAM ORDER: the scan then yields
        # (T, 4, S) tables whose (4T, S) reshape is free, and in that
        # interleaved order the fragment word keys are GLOBALLY
        # non-decreasing per series — the property the scatter-free
        # placement below rides.
        return c2, (jnp.stack([t0, t1, v0, v1]),
                    jnp.stack([n0, n1, n2, n3]))

    xs = (timestamps.T, value_bits.T, valid.T)  # scan over T
    carry, (lv, lw) = lax.scan(scan_fn, carry0, xs, unroll=_SCAN_UNROLL)
    # Lane tables stay SCAN-MAJOR — (T, 4, S), no transpose.  All
    # offset arithmetic is pinned i32 (sum/cumsum would silently
    # promote to i64 — double the traffic of the placement stages).
    lens = lw.sum(axis=1, dtype=I32)  # (T, S) per-datapoint bit counts

    # Absolute bit offsets: ONE exclusive prefix sum over per-datapoint
    # bit counts (the only cross-datapoint dependence left after the
    # scan), based at the 64-bit start word (+ any host prefix); each
    # lane's offset adds its in-datapoint exclusive width sum.
    base = _c(64, I32) if prefix_bits is None else (
        _c(64, I32) + prefix_bits.astype(I32)[None, :])
    off_dp = jnp.cumsum(lens, axis=0, dtype=I32) - lens + base
    total_bits = (off_dp[-1] + lens[-1]).astype(jnp.int64)
    pos = off_dp[:, None, :] + (jnp.cumsum(lw, axis=1, dtype=I32) - lw)

    F = 4 * T
    val4 = lv.reshape(F, S)
    pos4 = pos.reshape(F, S)
    n4 = lw.reshape(F, S)
    hi, lo, gw = _lane_frags(val4, pos4, n4)  # (F, S), gw non-decreasing

    out = jnp.zeros((S, out_words), U64)
    # start word first
    out = out.at[:, 0].set(start.astype(U64))

    # Word placement: every lane contributes (hi, lo) word fragments at
    # per-series word indices gw / gw+1 (disjoint bit ranges make add
    # equivalent to or).  Three formulations behind the static seam:
    #   scatter — two scatter-adds over the (F, S) fragments; the
    #             XLA-CPU scatter floor (~43ns/elt, BENCH_r07) makes it
    #             the SLOW tail at corpus scale but the cheapest
    #             compile.
    #   gather  — scatter-free: the stream-order fragment keys are
    #             NON-DECREASING along F, so each output word's
    #             contribution is a rank interval of the fragment
    #             cumsum — exact even under u64 wraparound ((A+B)-A ==
    #             B mod 2^64).  One branchless binary search serves
    #             both classes: the lo-class keys are gw+1, so its
    #             rank table is the hi-class's shifted one query down.
    #             The same segmented idiom as parallel/segmented.py.
    #   pallas  — the hand-scheduled TPU kernel: the masked-sum
    #             scatter inversion of the decode gather kernel
    #             (parallel/pallas_encode.py); interpret mode off-TPU.
    # ``place`` is STATIC, resolved by the encode_batch_device wrapper
    # (resolved_place: backend default, M3_ENCODE_PLACE override).
    if place == "pallas":
        from m3_tpu.parallel import pallas_encode

        frags = jnp.concatenate([hi.T, lo.T], axis=1)   # (S, 2F)
        keys = jnp.concatenate([gw.T, gw.T + _c(1, I32)], axis=1)
        out = out + pallas_encode.place_words(frags, keys, out_words)
    elif place == "gather":
        # Series-major for the gather stages: axis-1 gathers walk
        # contiguous rows; the axis-0 formulation's column-strided
        # accesses measured ~3x slower on XLA-CPU.
        zero_col = jnp.zeros((S, 1), U64)

        def _lane_cumsum_t(frag):
            # Inclusive lane cumsum, HIERARCHICALLY: 3 adds within
            # each datapoint's 4 lanes + one 4x-shorter dp-level
            # cumsum (XLA-CPU lowers a long cumsum to log-depth
            # full-array passes, so the (F, S) form paid ~4x this
            # traffic; exact either way — u64 adds commute).
            r = frag.reshape(T, 4, S)
            within = jnp.cumsum(r, axis=1)
            dp_sums = within[:, 3]
            dp_pre = jnp.cumsum(dp_sums, axis=0) - dp_sums
            return (dp_pre[:, None, :] + within).reshape(F, S).T

        cum_hi = jnp.concatenate([zero_col, _lane_cumsum_t(hi)], axis=1)
        cum_lo = jnp.concatenate([zero_col, _lane_cumsum_t(lo)], axis=1)
        keys = gw.T  # (S, F), non-decreasing rows
        # rank[s, w] = #lanes with key <= w, all output words at once:
        # one branchless binary search (cand-1 stays in range via the
        # min; the cand <= F guard rejects the clamped probes).
        wq = jnp.arange(out_words, dtype=I32)[None, :]  # (1, W)
        rank = jnp.zeros((S, out_words), I32)
        # 2^k > F so the greedy bit descent can reach rank == F exactly
        # (every lane before the word): (F-1).bit_length() tops out at
        # 2^k - 1 = F - 1 and silently drops the LAST lane's fragment
        # from the final stream word.
        for b in reversed(range(max(F, 1).bit_length())):
            cand = rank + _c(1 << b, I32)
            kv = jnp.take_along_axis(
                keys, jnp.minimum(cand, _c(F, I32)) - _c(1, I32), axis=1)
            rank = jnp.where((cand <= _c(F, I32)) & (kv <= wq), cand, rank)
        # Contiguous integer queries: rank(w-1) is rank shifted one
        # column (keys are >= 1 — offsets start at base >= 64 — so
        # rank(0) == 0 and the shifted-in zero column is exact).  The
        # lo-class keys are gw+1, so its rank table is the hi-class's
        # shifted once more: no second search.
        zc = jnp.zeros((S, 1), I32)
        rank_m1 = jnp.concatenate([zc, rank[:, :-1]], axis=1)
        rank_m2 = jnp.concatenate([zc, rank_m1[:, :-1]], axis=1)
        out = out + (jnp.take_along_axis(cum_hi, rank, axis=1)
                     - jnp.take_along_axis(cum_hi, rank_m1, axis=1)
                     + jnp.take_along_axis(cum_lo, rank_m1, axis=1)
                     - jnp.take_along_axis(cum_lo, rank_m2, axis=1))
    else:
        series_idx = jnp.broadcast_to(jnp.arange(S, dtype=I32)[None, :],
                                      (F, S))
        out = out.at[series_idx, jnp.clip(gw, 0, out_words - 1)].add(
            jnp.where(gw < out_words, hi, _c(0)))
        out = out.at[series_idx, jnp.clip(gw + 1, 0, out_words - 1)].add(
            jnp.where(gw + 1 < out_words, lo, _c(0)))

    fallback = carry[12] | (total_bits > (out_words * 64))
    return {"words": out, "total_bits": total_bits, "fallback": fallback}


def finalize_streams(words: np.ndarray, total_bits: np.ndarray,
                     counts=None) -> list[bytes]:
    """Host finalization: trim to byte length and append the EOS tail."""
    out = []
    words = np.asarray(words)
    total_bits = np.asarray(total_bits)
    for i in range(words.shape[0]):
        nbits = int(total_bits[i])
        raw = words[i].astype(">u8").tobytes()
        nbytes = (nbits + 7) // 8
        head = raw[:nbytes]
        pos = nbits - (nbytes - 1) * 8  # bits used in last byte, 1..8
        out.append(head[:-1] + tail_bytes(head[-1], pos))
    return out


def pack_streams(streams: list[bytes], pad_words: int = 0):
    """Pack finalized byte streams into the decoder's input layout:
    (S, pad_words) big-endian uint64 word arrays + per-stream bit lengths.

    ``pad_words`` of 0 sizes the array to the longest stream plus two
    slack words (the decoder pads further — ``_PAD_WORDS`` zero words —
    so its register-file gathers and phase-2 funnels never read OOB).
    """
    S = len(streams)
    if pad_words == 0:
        pad_words = max((len(s) for s in streams), default=0) // 8 + 2
    words = np.zeros((S, pad_words), np.uint64)
    nbits = np.zeros(S, np.int64)
    for i, s in enumerate(streams):
        nbits[i] = len(s) * 8
        padded = s + b"\x00" * (-len(s) % 8)
        w = np.frombuffer(padded, dtype=">u8").astype(np.uint64)
        words[i, : len(w)] = w
    return words, nbits


def _annotation_prefix(ann: bytes):
    """The first-datapoint annotation wire prefix (marker + varint +
    bytes) as (uint64 big-endian words, bit length) — composed with the
    scalar OStream so the bit layout is definitionally identical to the
    scalar encoder's (_write_annotation)."""
    from m3_tpu.encoding.bitstream import OStream
    from m3_tpu.encoding.m3tsz import _put_varint
    from m3_tpu.encoding.scheme import ANNOTATION_MARKER, write_special_marker

    os_ = OStream()
    write_special_marker(os_, ANNOTATION_MARKER)
    os_.write_bytes(_put_varint(len(ann) - 1))
    os_.write_bytes(ann)
    raw, _ = os_.raw_bytes()
    padded = raw + b"\x00" * (-len(raw) % 8)
    return np.frombuffer(padded, dtype=">u8").astype(np.uint64), os_.bit_length


def encode_batch(timestamps, values, start, counts=None, unit: Unit = Unit.SECOND,
                 out_words: int = 0, annotations=None, place: str = "auto"):
    """Host-facing batched encode.

    Returns (streams: list[bytes], fallback: np.ndarray[bool]); fallback
    series contain b"" and must be encoded with the scalar codec.

    ``annotations`` (optional list[bytes|None], len S) attaches an
    annotation to each series' FIRST datapoint — the proto-schema /
    tag-payload shape (`timestamp_encoder.go:99-116` writes it before
    the first time-unit marker).  The device scan shifts its output by
    the prefix width and the host splices the marker+varint+bytes in;
    mid-stream annotation CHANGES stay on the scalar path.
    """
    timestamps = np.asarray(timestamps, dtype=np.int64)
    values = np.asarray(values, dtype=np.float64)
    S, T = timestamps.shape
    if counts is None:
        counts = np.full(S, T, dtype=np.int64)
    valid = np.arange(T, dtype=np.int64)[None, :] < np.asarray(counts)[:, None]
    vb = values.view(np.uint64)

    prefix_bits = None
    prefix_words: dict[int, np.ndarray] = {}
    if annotations is not None:
        pb = np.zeros(S, np.int32)
        for i, ann in enumerate(annotations):
            if ann:
                prefix_words[i], pb[i] = _annotation_prefix(ann)
        prefix_bits = jnp.asarray(pb) if prefix_words else None

    res = encode_batch_device(
        jnp.asarray(timestamps), jnp.asarray(vb), jnp.asarray(start, dtype=jnp.int64),
        jnp.asarray(valid), unit=int(unit), out_words=out_words,
        prefix_bits=prefix_bits, place=place)
    fallback = np.asarray(res["fallback"])
    words_out = np.asarray(res["words"])
    if prefix_words:
        # Splice each prefix in after the start word (bit 64 is a word
        # boundary, so this is a plain OR into untouched zero bits).
        words_out = words_out.copy()
        for i, pw in prefix_words.items():
            words_out[i, 1:1 + len(pw)] |= pw
    streams = finalize_streams(words_out, np.asarray(res["total_bits"]))
    counts_arr = np.asarray(counts)
    # An empty series encodes to b"" (the reference encoder's Stream() returns
    # no segment when nothing was written), not a bare start-word stream.
    streams = [b"" if (fallback[i] or counts_arr[i] == 0) else streams[i]
               for i in range(S)]
    return streams, fallback


# ---------------------------------------------------------------------------
# Batched decode
# ---------------------------------------------------------------------------


def _peek(words, cursor, n):
    """Read ``n`` (<=64, may be 0 or traced) bits at bit position cursor from a
    (W+1,) uint64 word array (extra zero pad word)."""
    w = (cursor >> _c(6, I32))
    off = (cursor & _c(63, I32)).astype(U64)
    W = words.shape[0] - 1
    w = jnp.clip(w, 0, W - 1)
    w0 = words[w]
    w1 = words[w + 1]
    window = _shl(w0, off) | jnp.where(off > _c(0), _shr(w1, _c(64) - off), _c(0))
    return _shr(window, _c(64) - _c(n, I32).astype(U64))


# -- Register-file bit reader -----------------------------------------------
#
# Phase 1 reads at most 229 bits per step — 64 (start) + 11+8+64
# (marker + unit byte + full dod) + 16 (value control prefix) + 64
# (payload peek) — and every read starts within 102 bits of the
# post-start cursor ``c0``.  One 4-word gather at the word index below
# c0 therefore covers the whole step: bits [b0, b0+256) with
# c0 - b0 <= 63, so reads end at most at c0+166 <= b0+229 < b0+256.
# Earlier rounds carried a 32-word window in the scan carry instead
# (per-lane gathers lowered to O(S*W) masked reductions on TPU,
# round-2), but with phase 2 owning ALL wide payload extraction the
# per-step demand collapsed to these 4 words, and round-6 CPU profiling
# showed the window machinery (16-word refills + 9-word select funnels)
# costing ~5x the single tiny gather it avoided.  The padded stream
# array keeps >= 4 zero words past the longest stream, so the gather
# never clips in range.

_PAD_WORDS = 16          # zero padding after the longest stream (words)


def _regfile4(words, w0i):
    """Gather the 4 consecutive u64 stream words starting at per-lane
    word index ``w0i`` from the padded (S, W) array."""
    idx = w0i[:, None] + jnp.arange(4, dtype=I32)[None, :]
    R = jnp.take_along_axis(words, idx, axis=1, mode="promise_in_bounds")
    return R[:, 0], R[:, 1], R[:, 2], R[:, 3]


# -- Value-control lookup table ---------------------------------------------
#
# The value section's control prefix — mode / update-opcode / sig / mult
# / XOR-class flags — is a pure function of (first-value pending,
# int-or-float mode, next 16 stream bits): every branch's control bits
# fit inside a 16-bit window, and only the *payload* beyond it is wider.
# Round-6 profiling: the original 13-read flag cascade was ~250 fused
# element-ops per lane per scan step, while an XLA-CPU gather costs a
# few ns per lane — so the whole cascade collapses into ONE gather into
# this precomputed 2^18-entry table plus ~30 unpack ops.  Table rows are
# u32-packed:
#
#   bits  0-4   ctrl: control bits consumed before the payload/diff
#               field (the field itself starts at ``value_cursor+ctrl``)
#   bits  5-11  sig7: new significand width 0..64, 127 = keep carried
#   bits 12-14  mult3: new decimal multiplier (valid when bit 15 clear)
#   bit   15    mult_keep: no multiplier field, keep carried
#   bit   16    sign: the int-diff sign bit's value
#   bit   17    got_float_full: 64-bit raw float payload follows
#   bit   18    xor_nz: nonzero XOR (contained or uncontained)
#   bit   19    contained: XOR payload width = 64 - pl - pt (carried)
#   bit   20    uncont: explicit lead6/meaningful6 then payload
#   bit   21    diff_active: signed int-diff payload of eff-sig bits
#   bit   22    nfloat_set: mode becomes float after this point
#   bit   23    nfloat_keep: mode unchanged (neither set nor clear)
#   bit   24    mult_err: multiplier field decoded > max (stream error)
#   bit   25    xor_zero: zero-XOR repeat (no payload)
#
# For the uncontained path the lead/meaningful fields also sit inside
# the 16-bit window (bits 3..14) and are re-extracted with two shifts —
# cheaper than widening the table rows to u64.

_VC_KEEP_SIG = 127


def _build_value_ctrl_table() -> np.ndarray:
    """Precompute the (2^18,) u32 value-control table (numpy, import
    time).  Index = first << 17 | is_float << 16 | next-16-bits
    (MSB-first).  Mirrors the reference decoder's branch structure
    (m3tsz.py readIntSigMult / XOR paths) exactly; the jit path's
    correctness against the scalar decoder is pinned by the round-trip
    and sha256 corpus tests."""
    idx = np.arange(1 << 18, dtype=np.int64)
    X = idx & 0xFFFF
    isf = ((idx >> 16) & 1) == 1
    first = ((idx >> 17) & 1) == 1

    def bit(k):  # k-th stream bit of the window, 0 = first read
        return (X >> (15 - k)) & 1

    def bit_at(pos):  # data-dependent bit position (numpy array)
        return (X >> (15 - pos)) & 1

    def cascade(k0: int):
        """The sig/mult update cascade starting at control offset k0:
        sb1 [sb2 sig6] mb1 [mult3] sign."""
        sb1 = bit(k0)
        sb2 = bit(k0 + 1)
        sig6 = np.zeros_like(X)
        for j in range(6):
            sig6 = (sig6 << 1) | bit(k0 + 2 + j)
        sig = np.where(sb1 == 0, _VC_KEEP_SIG,
                       np.where(sb2 == 0, 0, sig6 + 1))
        k_m = np.where(sb1 == 0, k0 + 1,
                       np.where(sb2 == 0, k0 + 2, k0 + 8))
        mb1 = bit_at(k_m)
        m3 = (bit_at(k_m + 1) << 2) | (bit_at(k_m + 2) << 1) | bit_at(k_m + 3)
        mult = np.where(mb1 == 1, m3, 0)
        mult_keep = mb1 == 0
        mult_err = (mb1 == 1) & (m3 > 6)  # MAX_MULT (m3tsz.py)
        k_s = k_m + np.where(mb1 == 1, 4, 1)
        sign = bit_at(k_s)
        ctrl = k_s + 1
        return ctrl, sig, mult, mult_keep, mult_err, sign

    c1 = cascade(1)   # first-value int: after the mode bit
    c3 = cascade(3)   # next-value to-int-update: after nb1 nb2 nb3

    p_a2 = first & (bit(0) == 1)                                # full float
    p_a1 = first & (bit(0) == 0)                                # first int
    nfirst = ~first
    p_rep = nfirst & (bit(0) == 0) & (bit(1) == 1)              # repeat
    p_tofl = nfirst & (bit(0) == 0) & (bit(1) == 0) & (bit(2) == 1)
    p_toint = nfirst & (bit(0) == 0) & (bit(1) == 0) & (bit(2) == 0)
    p_xz = nfirst & (bit(0) == 1) & isf & (bit(1) == 0)         # zero XOR
    p_cont = nfirst & (bit(0) == 1) & isf & (bit(1) == 1) & (bit(2) == 0)
    p_unc = nfirst & (bit(0) == 1) & isf & (bit(1) == 1) & (bit(2) == 1)
    p_ino = nfirst & (bit(0) == 1) & ~isf                       # int no-upd

    def sel(pairs, default):
        out = np.full_like(X, default)
        for mask, val in pairs:
            out = np.where(mask, val, out)
        return out

    ctrl = sel([(p_a2, 1), (p_a1, c1[0]), (p_rep, 2), (p_tofl, 3),
                (p_toint, c3[0]), (p_xz, 2), (p_cont, 3), (p_unc, 15),
                (p_ino, 2)], 0)
    sig7 = sel([(p_a1, c1[1]), (p_toint, c3[1])], _VC_KEEP_SIG)
    mult3 = sel([(p_a1, c1[2]), (p_toint, c3[2])], 0)
    mult_keep = ~((p_a1 & ~c1[3]) | (p_toint & ~c3[3]))
    mult_err = (p_a1 & c1[4]) | (p_toint & c3[4])
    sign = sel([(p_a1, c1[5]), (p_toint, c3[5]), (p_ino, bit(1))], 0)

    flags = ((p_a2 | p_tofl).astype(np.int64) << 17
             | (p_cont | p_unc).astype(np.int64) << 18
             | p_cont.astype(np.int64) << 19
             | p_unc.astype(np.int64) << 20
             | (p_a1 | p_toint | p_ino).astype(np.int64) << 21
             | (p_a2 | p_tofl).astype(np.int64) << 22
             | (p_rep | p_xz | p_cont | p_unc | p_ino).astype(np.int64) << 23
             | mult_err.astype(np.int64) << 24
             | p_xz.astype(np.int64) << 25)
    packed = (ctrl | (sig7 << 5) | (mult3 << 12)
              | mult_keep.astype(np.int64) << 15 | (sign << 16) | flags)
    return packed.astype(np.uint32)


_VALUE_CTRL_TBL = _build_value_ctrl_table()


@functools.lru_cache(maxsize=1)
def value_ctrl_table():
    """The 2^18-entry value-control table as a DEVICE array, uploaded
    once per process and threaded through the decode entry points as an
    ARGUMENT.  Referencing the numpy module global under the tracer
    instead would constant-fold ~1MB of table into the HLO of every
    decode compilation — per shape, per chains tail, per backend
    (constant-bloat; the finding that motivated the rule).  Uncommitted
    (plain jnp.asarray, no device pin) so the sharded paths can
    replicate it across the mesh without a resharding error."""
    global _CTRL_TBL_RESERVED
    # lru_cache does not serialize concurrent first calls — the lock
    # keeps two first decoders from double-reserving the ledger entry
    with _CTRL_TBL_LOCK:
        if not _CTRL_TBL_RESERVED:
            # one permanent ~1MiB ledger entry for the resident control
            # table (x/membudget admission; never released — the table
            # lives for the process)
            membudget.reserve("decode.ctrl_table", _VALUE_CTRL_TBL.nbytes)
            _CTRL_TBL_RESERVED = True
    return jnp.asarray(_VALUE_CTRL_TBL, dtype=jnp.uint32)


_CTRL_TBL_RESERVED = False
_CTRL_TBL_LOCK = threading.Lock()


def _decode_step(carry, _, words, nbits, unit0, ctrl_tbl,
                 emit_chains: bool = False):
    """Phase 1 of the two-phase decode: ONE datapoint slot for every
    series at once ((S,) array ops), resolving ONLY the data-dependent
    minimum — control bits, field widths and the bit cursor — and
    emitting a per-datapoint lane table for the parallel phase-2 field
    gather (``_phase2``).  No timestamps, no value reconstruction, no
    wide XOR/int state rides the scan: the carry is the cursor plus a
    handful of narrow i32 lanes (sig width, time unit, and the previous
    XOR's leading/trailing-zero counts, which decide the 'contained'
    field width).

    ``words`` is the padded (S, W) stream array (closure, not carry);
    ``nbits`` the per-series stream bit lengths.  All bit reads come
    from a 4-word register file gathered once per step (``_regfile4``).
    The body is deliberately ONE branch-free straight line — no
    ``lax.cond`` anywhere (round-6 profiling: every cond is a thunk
    boundary on XLA-CPU, and the buffer round-trips at those boundaries
    cost more than the work the cond skipped).
    """
    (cursor, done, err, need_start, first_val, saw_ann, unit_idx,
     sig, mult, is_float, pl, pt) = carry[:12]
    chain_carry = carry[12:]
    active = (~done) & (~err)

    # ---- first: 64-bit start timestamp (only its ALIGNMENT matters —
    # it decides the initial time unit; phase 2 re-reads the value
    # directly from word 0).  need_start implies cursor == 0 (the
    # encoder splices annotation prefixes AFTER the start word and
    # every other step consumes it).  ``unit0`` — the per-series
    # initial unit derived from that alignment — is loop-invariant, so
    # the caller computes it ONCE and closes over it (the i64 rem it
    # needs is division, ~20x an add per lane; round-6 profiling caught
    # it riding every step). ----
    rd_first = jnp.where(active & need_start, _c(64, I32), _c(0, I32))
    cur = cursor + rd_first
    unit_eff = jnp.where(need_start, unit0, unit_idx)
    first = first_val  # value-mode branch key (first value still pending)

    # ---- register file: ONE 4-word gather at the word index below
    # `cur` covers every read this step makes (see _regfile4).  The
    # 64-bit funnel W0 at `cur` serves the marker peek (11), the
    # annotation varint bytes (<= 43 bits in), the time-unit byte
    # (<= 19 + 8) and the dod opcode (<= 19 + 4) as in-register shifts
    # — they all start within 64 bits of `cur` on whichever path a
    # lane takes; the value-section reads (<= 102 bits in) use the
    # full 3-word funnel ``rd3``. ----
    c0 = cur
    w0i = c0 >> _c(6, I32)
    r0, r1, r2, r3 = _regfile4(words, w0i)
    rf_base = w0i << _c(6, I32)

    # All shifts below are UNGUARDED (no _shl/_shr >=64 clamps): every
    # data-dependent amount is < 64 by construction, and the one
    # 64-minus case (a funnel's low word at offset 0) masks the shift
    # to (64-r)&63 and discards the r==0 lane with the select — its
    # clamped value is never read, so the result stays deterministic.
    def _funnel(hi, lo, r):
        return (hi << r) | jnp.where(
            r > _c(0), lo >> ((_c(64) - r) & _c(63)), _c(0))

    off0 = (c0 - rf_base).astype(U64)
    W0 = _funnel(r0, r1, off0)

    def rd0(cur_abs, n: int):
        # n is a STATIC width (1..64); cur_abs - c0 <= 43 < 64.
        off = (cur_abs - c0).astype(U64)
        chunk = W0 << off
        return chunk >> _c(64 - n) if n < 64 else chunk

    def rd3(cur_abs, n: int):
        """Up to 64 STATIC-width bits anywhere in [c0, rf_base+192):
        3-way funnel over the register file."""
        o = cur_abs - rf_base
        k = o >> _c(6, I32)                       # 0..2
        r = (o & _c(63, I32)).astype(U64)
        hi = jnp.where(k == _c(0, I32), r0,
                       jnp.where(k == _c(1, I32), r1, r2))
        lo = jnp.where(k == _c(0, I32), r1,
                       jnp.where(k == _c(1, I32), r2, r3))
        chunk = _funnel(hi, lo, r)
        return chunk >> _c(64 - n) if n < 64 else chunk

    # ---- marker peek (11 bits) ----
    can_peek = (cur + _c(11, I32)) <= nbits
    peek11 = jnp.where(active & can_peek, rd0(cur, 11), _c(0))
    is_marker = (peek11 >> _c(2)) == _c(0x100)
    mval = (peek11 & _c(3)).astype(I32)
    eos = active & is_marker & (mval == _c(0, I32))
    ann = active & is_marker & (mval == _c(1, I32))
    is_tu = active & is_marker & (mval == _c(2, I32))
    done = done | eos
    proceed = active & ~eos & ~ann

    # ---- annotation skip (timestamp_encoder.go:99-116) ----
    # marker + zigzag-LEB128 varint of (len-1) + len bytes.  The step
    # consumes the marker and varint from W0 (<= 43 bits) and jumps the
    # cursor over the payload.  The annotation slot emits no datapoint
    # — callers size max_points accordingly.  All four varint bytes sit
    # at FIXED offsets inside W0, so they are four shifts plus a
    # continuation-chain mask — no data-dependent read offsets.
    acur = cur + _c(11, I32)

    vb = [rd0(acur + _c(8 * k, I32), 8) for k in range(4)]
    t1 = (vb[0] & _c(0x80)) != _c(0)
    t2 = t1 & ((vb[1] & _c(0x80)) != _c(0))
    t3 = t2 & ((vb[2] & _c(0x80)) != _c(0))
    ux = ((vb[0] & _c(0x7F))
          | jnp.where(t1, _shl(vb[1] & _c(0x7F), _c(7)), _c(0))
          | jnp.where(t2, _shl(vb[2] & _c(0x7F), _c(14)), _c(0))
          | jnp.where(t3, _shl(vb[3] & _c(0x7F), _c(21)), _c(0)))
    abits = (_c(8, I32)
             + jnp.where(t1, _c(8, I32), _c(0, I32))
             + jnp.where(t2, _c(8, I32), _c(0, I32))
             + jnp.where(t3, _c(8, I32), _c(0, I32)))
    ann_len = (ux >> _c(1)).astype(I32) + _c(1, I32)
    err = err | (ann & t3 & ((vb[3] & _c(0x80)) != _c(0)))  # varint > 4B
    ann_end = acur + abits + ann_len * _c(8, I32)
    err = err | (ann & (ann_end > nbits))
    saw_ann = saw_ann | (ann & ~err)

    cur = cur + jnp.where(is_tu, _c(11, I32), _c(0, I32))
    ub = jnp.where(is_tu, rd0(cur, 8), _c(0)).astype(I32)
    cur = cur + jnp.where(is_tu, _c(8, I32), _c(0, I32))
    ub_valid = (ub >= _c(1, I32)) & (ub <= _c(8, I32))
    tu_changed = is_tu & ub_valid & (ub != unit_eff)
    new_unit = jnp.where(is_tu, ub, unit_eff)
    # _UNIT_NANOS is nonzero exactly on 1..8: a range check, not a gather
    unit_ok = (new_unit >= _c(1, I32)) & (new_unit <= _c(8, I32))
    err = err | (proceed & ~unit_ok & ~tu_changed)

    # ---- delta of delta: widths only (payload bits are phase 2's) ----
    full64 = tu_changed
    rd_dod64 = jnp.where(proceed & full64, _c(64, I32), _c(0, I32))
    cur = cur + rd_dod64
    dod64_off = cur - rd_dod64

    # bucketed path: peek 4 opcode bits, classify
    bucket_active = proceed & ~full64
    op4 = jnp.where(bucket_active, rd0(cur, 4), _c(0))
    b3 = (op4 >> _c(3)) & _c(1)
    b2 = (op4 >> _c(2)) & _c(1)
    b1 = (op4 >> _c(1)) & _c(1)
    b0 = op4 & _c(1)
    default_is32 = (new_unit == _c(1, I32)) | (new_unit == _c(2, I32))
    nop = jnp.where(b3 == _c(0), _c(1, I32),
          jnp.where(b2 == _c(0), _c(2, I32),
          jnp.where(b1 == _c(0), _c(3, I32), _c(4, I32))))
    nv = jnp.where(b3 == _c(0), _c(0, I32),
         jnp.where(b2 == _c(0), _c(7, I32),
         jnp.where(b1 == _c(0), _c(9, I32),
         jnp.where(b0 == _c(0), _c(12, I32),
                   jnp.where(default_is32, _c(32, I32), _c(64, I32))))))
    nop = jnp.where(bucket_active, nop, _c(0, I32))
    nv = jnp.where(bucket_active, nv, _c(0, I32))
    cur = cur + nop
    ts_off = jnp.where(full64, dod64_off, cur)
    ts_w = jnp.where(full64, _c(64, I32), nv)
    cur = cur + nv

    # ---- value section: ONE 16-bit funnel read + ONE table gather ----
    # Every value path's control bits fit in the next 16 stream bits
    # (see _build_value_ctrl_table): the 13-read flag cascade of the
    # previous formulation collapses into a single precomputed-table
    # gather plus unpack shifts.  Only the *payload* beyond the control
    # prefix is wider, and the only payload LOOKED AT here is the
    # full-float / contained-XOR word, whose bit pattern decides the
    # next leading/trailing counts.
    v0 = cur
    X = rd3(v0, 16).astype(I32)
    tidx = (X | jnp.where(is_float, _c(1 << 16, I32), _c(0, I32))
              | jnp.where(first, _c(1 << 17, I32), _c(0, I32)))
    tv = ctrl_tbl[tidx].astype(I32)

    ctrl = tv & _c(0x1F, I32)
    sig7 = (tv >> _c(5, I32)) & _c(0x7F, I32)
    mult3 = (tv >> _c(12, I32)) & _c(0x7, I32)
    mult_keep = (tv & _c(1 << 15, I32)) != _c(0, I32)
    sign_v = (tv & _c(1 << 16, I32)) != _c(0, I32)
    got_float_full = proceed & ((tv & _c(1 << 17, I32)) != _c(0, I32))
    xor_nz = proceed & ((tv & _c(1 << 18, I32)) != _c(0, I32))
    contained = proceed & ((tv & _c(1 << 19, I32)) != _c(0, I32))
    uncont = proceed & ((tv & _c(1 << 20, I32)) != _c(0, I32))
    diff_active = proceed & ((tv & _c(1 << 21, I32)) != _c(0, I32))
    nfloat_set = (tv & _c(1 << 22, I32)) != _c(0, I32)
    nfloat_keep = (tv & _c(1 << 23, I32)) != _c(0, I32)
    xor_zero = proceed & ((tv & _c(1 << 25, I32)) != _c(0, I32))
    err = err | (proceed & ((tv & _c(1 << 24, I32)) != _c(0, I32)))

    eff_sig = jnp.where(sig7 == _c(_VC_KEEP_SIG, I32), sig, sig7)
    meaningful_c = _c(64, I32) - pl - pt
    u_lead = (X >> _c(7, I32)) & _c(0x3F, I32)
    u_meaningful = ((X >> _c(1, I32)) & _c(0x3F, I32)) + _c(1, I32)
    u_trail = _c(64, I32) - u_lead - u_meaningful
    # lead + meaningful > 64 never leaves a valid encoder; route such
    # streams to the scalar path instead of desyncing pl/pt.
    err = err | (uncont & (u_trail < _c(0, I32)))

    val_w = jnp.where(got_float_full, _c(64, I32),
            jnp.where(contained, meaningful_c,
            jnp.where(uncont, u_meaningful,
            jnp.where(diff_active, eff_sig, _c(0, I32)))))
    val_off = v0 + ctrl
    cur = v0 + jnp.where(proceed, ctrl + val_w, _c(0, I32))

    # ---- leading/trailing update for the next step ----
    # Full-float and contained-XOR writes set the float-chain word to a
    # value whose clz/ctz depend on PAYLOAD bits, so those two (and
    # only those two) paths read it.  Uncontained writes are canonical
    # (top and bottom meaningful bits set — phase 2 verifies), so their
    # counts come straight from the explicit lead/meaningful fields.
    # Exactly one payload can be live per lane and all of them start at
    # ``val_off``, so ONE funnel read serves every path: the full-float
    # word is the raw 64 bits, the contained window is its top
    # ``meaningful_c`` bits.
    need_payload = got_float_full | contained
    c_w = jnp.where(contained, meaningful_c, _c(0, I32))
    raw = rd3(val_off, 64)
    cb = _shr(raw, _c(64) - jnp.clip(c_w, 0, 64).astype(U64))
    nx = jnp.where(got_float_full, raw, _shl(cb, pt.astype(U64)))
    nx_zero = nx == _c(0)
    pl_c = jnp.where(nx_zero, _c(64, I32),
                     lax.clz(nx.astype(I64)).astype(I32))
    pt_c = jnp.where(nx_zero, _c(0, I32),
                     _num_sig(nx & (~nx + _c(1))) - _c(1, I32))
    n_pl = jnp.where(need_payload, pl_c,
            jnp.where(uncont, u_lead,
            jnp.where(xor_zero, _c(64, I32), pl)))
    n_pt = jnp.where(need_payload, pt_c,
            jnp.where(uncont, u_trail,
            jnp.where(xor_zero, _c(0, I32), pt)))

    # ---- narrow state update (self-gating: every update predicate is
    # already ANDed with ``proceed``) ----
    n_is_float = jnp.where(proceed,
                           nfloat_set | (nfloat_keep & is_float), is_float)
    n_sig = jnp.where(proceed & (sig7 != _c(_VC_KEEP_SIG, I32)), sig7, sig)
    n_mult = jnp.where(proceed & ~mult_keep, mult3, mult)

    err = err | (proceed & (cur > nbits))
    emit = proceed & ~err

    # ---- cursor update ----
    # Normal datapoint steps advance to `cur`; annotation steps jump the
    # cursor past the payload (consuming this scan slot without a
    # datapoint); the start word still counts as consumed for them.
    ann_ok = ann & ~err
    new_cursor = jnp.where(ann_ok, ann_end,
                           jnp.where(proceed, cur, cursor))

    consumed = proceed | ann_ok
    new_carry = (
        new_cursor,
        done, err,
        need_start & ~consumed,
        first_val & ~proceed,
        saw_ann,
        jnp.where(proceed, new_unit,
                  jnp.where(ann_ok & need_start, unit0, unit_idx)),
        n_sig, n_mult, n_is_float, n_pl, n_pt,
    )

    if not emit_chains:
        # ---- GATHER tail: lane-table emission (see _phase2) ----
        shift = jnp.where(contained, pt,
                jnp.where(uncont, jnp.clip(u_trail, 0, 63), _c(0, I32)))
        U32c = lambda b, n: jnp.where(b, jnp.uint32(1 << n), jnp.uint32(0))
        out_p1 = (jnp.where(emit, ts_w, _c(0, I32)).astype(jnp.uint32)
                  | U32c(emit & full64, 7)
                  | (jnp.clip(new_unit, 0, 15).astype(jnp.uint32)
                     << jnp.uint32(8))
                  | U32c(emit, 12))
        out_p2 = (jnp.where(emit, val_w, _c(0, I32)).astype(jnp.uint32)
                  | (jnp.clip(shift, 0, 63).astype(jnp.uint32)
                     << jnp.uint32(7))
                  | (jnp.clip(n_mult, 0, 7).astype(jnp.uint32)
                     << jnp.uint32(13))
                  | U32c(n_is_float, 16)
                  | U32c(emit & xor_nz, 17)
                  | U32c(emit & got_float_full, 18)
                  | U32c(emit & diff_active, 19)
                  | U32c(sign_v, 20)
                  | U32c(emit & uncont, 21))
        return new_carry, (ts_off, out_p1, val_off, out_p2)

    # ---- FUSED tail: the three value chains ride THIS scan, consuming
    # the payload words already in registers (``raw`` was read for the
    # pl/pt update; the dod word is one more register-file funnel).
    # Bit-identical to the gather tail by the parity tests; see
    # decode_batch_device for when each tail is selected. ----
    (time, csum, csum_rst, fb, iv, prec, err2) = chain_carry
    unit_tbl = jnp.asarray(_UNIT_NANOS, I64)

    # timestamp chain: running delta = csum - csum@(last unit reset)
    draw = rd3(ts_off, 64)
    dmag = _shr(draw, _c(64) - jnp.clip(ts_w, 0, 64).astype(U64))
    dod = _sign_extend(dmag, ts_w)
    un = unit_tbl[jnp.clip(new_unit, 0, 15)]
    d_k = jnp.where(emit, jnp.where(full64, dod, dod * un), _c(0, I64))
    csum2 = csum + d_k
    time2 = time + jnp.where(emit, csum2 - csum_rst, _c(0, I64))
    csum_rst2 = jnp.where(emit & full64, csum2, csum_rst)

    # float-bits chain (running XOR with full-write resets); nx already
    # equals the XOR word for the full-float and contained paths
    pay_unc = raw >> (_c(64) - jnp.clip(u_meaningful, 1, 64).astype(U64))
    xv_unc = pay_unc << jnp.clip(u_trail, 0, 63).astype(U64)
    xv = jnp.where(xor_nz & emit,
                   jnp.where(uncont, xv_unc, nx), _c(0))
    fb2 = jnp.where(emit & got_float_full, raw, fb ^ xv)

    # int chain; sign: opcodeNegative(1) -> +, opcodePositive(0) -> -
    dv = _shr(raw, _c(64) - jnp.clip(eff_sig, 0, 64).astype(U64))
    sd = jnp.where(emit & diff_active,
                   jnp.where(sign_v, dv.astype(I64), -(dv.astype(I64))),
                   _c(0, I64))
    iv2 = iv + sd
    prec2 = prec | (emit & diff_active
                    & ((dv > _c(_PRECISION_LIMIT))
                       | (jnp.abs(iv2) > _c(_PRECISION_LIMIT, I64))))

    # Canonical-XOR guard (the gather tail's phase-2 epilogue check)
    top_ok = (pay_unc >> jnp.clip(u_meaningful - _c(1, I32), 0, 63)
              .astype(U64)) == _c(1)
    bot_ok = (pay_unc & _c(1)) == _c(1)
    err2_2 = err2 | (emit & uncont & ~(top_ok & bot_ok))

    ts_o = jnp.where(emit, time2, _c(0, I64))
    pay_o = jnp.where(n_is_float, fb2, iv2.astype(U64))
    meta_o = (jnp.where(emit, _c(16, I32), _c(0, I32))
              | jnp.where(n_is_float, _c(8, I32), _c(0, I32))
              | jnp.clip(n_mult, 0, 7)).astype(jnp.uint8)
    return (new_carry + (time2, csum2, csum_rst2, fb2, iv2, prec2, err2_2),
            (ts_o, pay_o, meta_o))


def _decode_carry0(S: int, base_time=None):
    """Phase-1 initial carry (shared with tools/decode_profile.py).
    ``base_time`` (the start words as int64) arms the fused-chains tail:
    when given, the seven chain lanes ride the carry too."""
    base = (
        jnp.zeros(S, I32), jnp.zeros(S, jnp.bool_), jnp.zeros(S, jnp.bool_),
        jnp.ones(S, jnp.bool_), jnp.ones(S, jnp.bool_),
        jnp.zeros(S, jnp.bool_), jnp.zeros(S, I32),
        jnp.zeros(S, I32), jnp.zeros(S, I32), jnp.zeros(S, jnp.bool_),
        jnp.full(S, 64, I32), jnp.zeros(S, I32),  # pl/pt of prev_xor == 0
    )
    if base_time is None:
        return base
    z64 = jnp.zeros(S, I64)
    return base + (base_time.astype(I64), z64, z64, jnp.zeros(S, U64), z64,
                   jnp.zeros(S, jnp.bool_), jnp.zeros(S, jnp.bool_))


def _phase2(wpad, ts_off, p1, val_off, p2, extract_impl: str = "jnp"):
    """Phase 2: fully parallel, branchless field extraction + chain
    reconstruction over the phase-1 lane table.

    All lane tables arrive SCAN-MAJOR — (P, S), straight off the
    ``lax.scan`` stack with no transpose.  The sequential scan resolved
    every bit boundary; everything left is data-parallel over (P, S):
    gather the timestamp-DoD and value payloads out of the int32-packed
    stream words (shift/mask funnels — a Pallas kernel on TPU,
    ``take_along_axis`` elsewhere; see parallel/pallas_decode.py), then
    rebuild the three value chains in ONE cheap ``lax.scan`` over the
    point axis with (S,) lanes (~8 fused element-ops per step — round-6
    profiling: the previous O(log P) associative-scan formulation paid
    five full (S, P) array passes PER LEVEL on XLA-CPU and dominated
    phase 2):

      timestamps — running delta + running sum, the delta segmented at
        time-unit changes (where the reference resets it);
      float bits — running XOR, reset at full-float writes;
      int values — running sum of the signed significand diffs.

    Returns (ts, payload, meta, prec, err2) — outputs (S, P) — where
    err2 flags streams whose uncontained XOR fields are non-canonical
    (top/bottom meaningful bit clear — impossible from a valid encoder;
    phase 1's width bookkeeping assumes canonical, so such streams must
    take the scalar path instead of silently diverging from it).
    """
    from m3_tpu.parallel import pallas_decode

    P, S = ts_off.shape
    U32 = jnp.uint32
    base_time = wpad[:, 0].astype(I64)

    # ---- the gather: both fields of every datapoint in one call ----
    # Scan-major throughout: the lane tables arrive (P, S) and the
    # stream array is transposed ONCE so the gather and every later
    # pass run in the (point, series) layout.  The Pallas path gathers
    # from the int32-packed view (big-endian u32 halves of the u64
    # stream words — u32 word k holds stream bits [32k, 32k+32)
    # MSB-first, the fixed-lane layout Mosaic needs); the jnp path
    # reads the u64 words directly (one fewer gather, no repack).
    ts_w = (p1 & jnp.uint32(0x7F)).astype(I32)
    val_w = (p2 & jnp.uint32(0x7F)).astype(I32)
    offs = jnp.concatenate([ts_off, val_off], axis=0)
    widths = jnp.concatenate([ts_w, val_w], axis=0)
    # ``extract_impl`` arrives as a STATIC from the decode wrapper
    # (resolved on the host — an env/backend read at trace time is
    # frozen into the first compile; retrace-risk).
    impl = extract_impl
    wpad_t = wpad.T
    if impl == "pallas":
        w32_t = jnp.stack([(wpad_t >> _c(32)).astype(U32),
                           (wpad_t & _c(0xFFFFFFFF)).astype(U32)],
                          axis=1).reshape(-1, S)
        fields = pallas_decode.extract_fields_t(w32_t, offs, widths,
                                                impl=impl)
    else:
        fields = pallas_decode.extract_fields64_t(wpad_t, offs, widths)
    dod_bits = fields[:P]
    payload = fields[P:]

    # ---- the chain scan: three running chains over the point axis
    # with (S,) lanes, lane tables unpacked IN the step body (the
    # tables are the scan's xs — unpacking inside costs a few u32 ops
    # per step on data already in registers, while precomputing the
    # unpacked lanes outside materializes three more (P, S) arrays of
    # memory-bound traffic; round-6 measured both, as well as the
    # O(log P) associative-scan formulation that paid five full-array
    # passes per level).  Everything derivable from the chain OUTPUTS
    # (emit/float masking, meta, the precision and canonical-XOR
    # reductions) runs vectorized in the epilogue instead.  Time-unit
    # changes reset the carried delta AFTER applying their full 64-bit
    # dod: the running delta is csum - csum@(last reset strictly before
    # this point), tracked incrementally. ----
    unit_tbl = jnp.asarray(_UNIT_NANOS, I64)

    def bit(p, n):
        return (p & jnp.uint32(1 << n)) != jnp.uint32(0)

    def _chain_step(carry, x):
        time, csum, csum_rst, fb, iv = carry
        p1_i, p2_i, dod_i, pay_i = x
        tsw = (p1_i & jnp.uint32(0x7F)).astype(I32)
        full_i = bit(p1_i, 7)
        unit_i = ((p1_i >> jnp.uint32(8)) & jnp.uint32(0xF)).astype(I32)
        emit_i = bit(p1_i, 12)
        sh = ((p2_i >> jnp.uint32(7)) & jnp.uint32(0x3F)).astype(I32)
        xnz_i = bit(p2_i, 17)
        ff_i = bit(p2_i, 18)
        diff_i = bit(p2_i, 19)
        sign_i = bit(p2_i, 20)

        dod = jnp.where(tsw > _c(0, I32),
                        _sign_extend(dod_i, jnp.maximum(tsw, _c(1, I32))),
                        _c(0, I64))
        d_k = jnp.where(full_i, dod,
                        dod * unit_tbl[jnp.clip(unit_i, 0, 15)])
        csum2 = csum + d_k
        time2 = time + jnp.where(emit_i, csum2 - csum_rst, _c(0, I64))
        csum_rst2 = jnp.where(full_i, csum2, csum_rst)

        xv_k = jnp.where(ff_i, pay_i,
                         jnp.where(xnz_i, _shl(pay_i, sh.astype(U64)),
                                   _c(0)))
        fb2 = jnp.where(ff_i, xv_k, fb ^ xv_k)  # XOR chain, full resets

        # int diff; sign: opcodeNegative(1) -> +, opcodePositive(0) -> -
        sd_k = jnp.where(diff_i,
                         jnp.where(sign_i, pay_i.astype(I64),
                                   -(pay_i.astype(I64))), _c(0, I64))
        iv2 = iv + sd_k
        return (time2, csum2, csum_rst2, fb2, iv2), (time2, fb2, iv2)

    z64 = jnp.zeros(S, I64)
    _, (time_o, fb_o, iv_o) = lax.scan(
        _chain_step, (base_time, z64, z64, jnp.zeros(S, U64), z64),
        (p1, p2, dod_bits, payload))

    # ---- vectorized epilogue over (P, S) ----
    emit = bit(p1, 12)
    isf = bit(p2, 16)
    diff = bit(p2, 19)
    unc = bit(p2, 21)
    vw = (p2 & jnp.uint32(0x7F)).astype(I32)

    # Canonical-XOR guard: a valid encoder always sets the top and
    # bottom bits of an uncontained meaningful window (the explicit
    # lead/trail fields ARE its clz/ctz); anything else desyncs the
    # carried pl/pt, so route such streams to the scalar path.
    top_ok = _shr(payload, jnp.maximum(vw - _c(1, I32), _c(0, I32))
                  .astype(U64)) == _c(1)
    bot_ok = (payload & _c(1)) == _c(1)
    err2 = jnp.any(unc & ~(top_ok & bot_ok), axis=0)
    prec = jnp.any(diff & ((payload > _c(_PRECISION_LIMIT))
                           | (jnp.abs(iv_o) > _c(_PRECISION_LIMIT, I64))),
                   axis=0)
    ts = jnp.where(emit, time_o, _c(0, I64))
    out_payload = jnp.where(isf, fb_o, iv_o.astype(U64))
    meta = (jnp.where(emit, _c(16, I32), _c(0, I32))
            | jnp.where(isf, _c(8, I32), _c(0, I32))
            | ((p2 >> jnp.uint32(13)) & jnp.uint32(0x7)).astype(I32)
            ).astype(jnp.uint8)

    return ts, out_payload, meta, prec, err2  # scan-major (P, S)


_CHAIN_IMPLS = ("fused", "gather")


def resolved_chains() -> str:
    """Which tail ``chains='auto'`` resolves to on this process'
    backend.  ``M3_DECODE_CHAINS`` overrides (parity tests pin both)."""
    impl = os.environ.get("M3_DECODE_CHAINS", "").strip()
    if impl:
        if impl not in _CHAIN_IMPLS:
            raise ValueError(
                f"M3_DECODE_CHAINS={impl!r}: expected one of {_CHAIN_IMPLS}")
        return impl
    return "gather" if jax.default_backend() == "tpu" else "fused"


def fallback_chains(chains: str) -> str:
    """The devguard stepping-down rule for the decode chains seam,
    owned ONCE (decode_batch_device + parallel/sharded_decode): step
    down to the OTHER tail (the fused tail also pins extract="jnp",
    so a failing Pallas extraction kernel steps down with it)."""
    return "fused" if chains != "fused" else "gather"


def _resolved_extract(chains: str) -> str:
    """The phase-2 field-extraction impl for a chains tail, resolved on
    the host: only the gather tail runs the extraction pass, so the
    fused tail pins "jnp" (keeps M3_DECODE_EXTRACT flips from
    needlessly splitting the fused jit cache)."""
    if chains != "gather":
        return "jnp"
    from m3_tpu.parallel import pallas_decode

    return pallas_decode.resolved_impl()


def decode_batch_device(words, nbits, max_points: int, default_unit: int = 1,
                        chains: str = "auto", scan_major: bool = False):
    """Decode (S, W+1) padded word arrays in parallel, in two phases:
    a sequential bit-boundary scan (``_decode_step``) that resolves
    control bits into a per-datapoint lane table, then branchless field
    extraction + chain reconstruction.  Where the second phase runs is
    the ``chains`` seam (same shape as M3_ENCODE_PLACE / the arena's
    ingest impls — one contract, backend-measured formulations,
    parity-pinned):

    ``gather``  phase 2 is a separate parallel pass (``_phase2``): lane
                tables -> payload gather (Pallas kernel on TPU, see
                parallel/pallas_decode.py) -> vectorized chain scan.
                The TPU shape: the boundary scan stays minimal and the
                heavy field traffic runs as wide fixed-lane gathers.
    ``fused``   the three value chains ride the boundary scan itself
                (``_decode_step(emit_chains=True)``), consuming payload
                words already in the step's register file.  The XLA-CPU
                shape: round-6 measured the separate chain scan paying
                more in (P, S) lane-table materialization + scan
                mechanics than the ~10 fused element-ops it saves.
    ``auto``    (default) fused on CPU, gather on TPU; override with
                M3_DECODE_CHAINS.  Both tails are bit-identical — pinned
                by the corpus sha256 + fuzz parity tests.

    Returns (ts (S, max_points) int64, payload (S, max_points) uint64,
    meta (S, max_points) uint8, err (S,), prec (S,), ann (S,)).
    meta: bit4 = valid, bit3 = is_float, bits0-2 = multiplier.
    ``ann`` marks series whose stream carried annotation markers: their
    datapoints are decoded (each annotation consumes one scan slot) but
    the annotation bytes are skipped — callers needing them re-read via
    the scalar iterator.

    ``scan_major=True`` returns ts/payload/meta as (max_points, S) —
    the layout the scan produces — skipping the three (P, S)->(S, P)
    transposes.  As the TERMINAL ops of this jit they materialize full
    passes XLA cannot fuse into anything (round-6 CPU profiling: 30% of
    total decode wall-time); host callers flip axes with free numpy
    views instead, and in-jit callers compose the decode so XLA folds
    the layout change into their own downstream ops.

    This is the HOST wrapper: the chains/extract seams resolve here
    (env + backend reads are host state — under the tracer they would
    freeze into the first compile and the env override would silently
    stop responding), and the value-control table is fetched as a
    device ARGUMENT (constant-bloat: referenced as a module global it
    would be re-baked into every compiled HLO).  In-jit callers use
    ``_decode_batch_device`` (via ``__wrapped__``) and thread the
    table/statics themselves — see parallel/sharded_decode.py.
    """
    if chains == "auto":
        chains = resolved_chains()
    if chains not in _CHAIN_IMPLS:
        raise ValueError(f"chains={chains!r}: expected one of "
                         f"{_CHAIN_IMPLS + ('auto',)}")
    S, Wp = words.shape

    def _run(ch: str):
        return _decode_batch_device(
            words, nbits, value_ctrl_table(), max_points=max_points,
            default_unit=default_unit, chains=ch,
            scan_major=scan_major, extract=_resolved_extract(ch))

    # device-guard seam: the fallback rides the OTHER chains tail as a
    # static argument (the fused tail also pins extract="jnp", so a
    # failing Pallas extraction kernel steps down with it) — both tails
    # are bit-identical, corpus sha256 + fuzz pinned.  Lane-table
    # admission is ONCE, outside the guard, at the worse of the
    # primary/fallback tails (encode_batch_device's rationale: an
    # admission reject is not a fault the fallback can relieve — typed
    # raise, no breaker).
    fb = fallback_chains(chains)
    lane_bytes = max(
        membudget.decode_lane_bytes(S, Wp, max_points, chains=chains,
                                    extract=_resolved_extract(chains)),
        membudget.decode_lane_bytes(S, Wp, max_points, chains=fb,
                                    extract=_resolved_extract(fb)))
    with membudget.transient("decode.lanes", lane_bytes):
        return devguard.run_guarded("decode", lambda: _run(chains),
                                    lambda: _run(fallback_chains(chains)))


@functools.partial(jax.jit,
                   static_argnames=("max_points", "default_unit", "chains",
                                    "scan_major", "extract"))
def _decode_batch_device(words, nbits, ctrl_tbl, max_points: int,
                         default_unit: int = 1, chains: str = "fused",
                         scan_major: bool = False, extract: str = "jnp"):
    S, Wp = words.shape
    # Pad the stream with zero words so the phase-1 register-file gather
    # (4 words at the cursor) and phase 2's 3-word funnels never read
    # out of bounds.
    wpad = jnp.pad(words, ((0, 0), (0, _PAD_WORDS)))
    nbits32 = nbits.astype(I32)

    # The per-series initial time unit depends only on the start
    # word's alignment — computed once here, not per scan step (i64
    # rem is division).
    d_ns = jnp.asarray(int(Unit(default_unit).nanos()), I64)
    aligned = (lax.rem(wpad[:, 0].astype(I64), d_ns)) == _c(0, I64)
    unit0 = jnp.where(aligned, _c(default_unit, I32), _c(0, I32))

    fused = chains == "fused"
    base_time = wpad[:, 0].astype(I64)
    carry0 = _decode_carry0(S, base_time if fused else None)
    step = functools.partial(_decode_step, words=wpad, nbits=nbits32,
                             unit0=unit0, ctrl_tbl=ctrl_tbl,
                             emit_chains=fused)

    # Decode k datapoints per loop iteration.  Unrolling chains k step
    # bodies inside one iteration, so the narrow carry stays fused
    # between them instead of round-tripping memory every datapoint,
    # and the loop's fixed dispatch overhead is paid T/k times.
    # (Round-5's unroll=1 pin predates the two-phase split: with the
    # 32-word window gone from the carry, unroll=2 measured ~11% faster
    # on XLA-CPU, round 6.)
    carry, lanes = lax.scan(step, carry0, None, length=max_points,
                            unroll=_DECODE_UNROLL)

    # A stream whose EOS marker sits exactly after max_points datapoints never
    # sets done inside the scan; peek once more for it.
    cursor, done = carry[0], carry[1]
    can = (cursor + 11) <= nbits32
    peek11 = jax.vmap(lambda w, c: _peek(w, c, _c(11, I32)))(wpad, cursor)
    eos_tail = can & ((peek11 >> _c(2)) == _c(0x100)) & ((peek11 & _c(3)) == _c(0))
    done = done | eos_tail
    err = carry[2] | (~done)  # not done after max_points -> error
    ann = carry[5]  # series whose stream carried annotation markers

    if fused:
        ts, payload, meta = lanes  # scan-major (P, S)
        prec, err2 = carry[17], carry[18]
    else:
        ts_off, p1, val_off, p2 = lanes  # scan-major (P, S) — no transpose
        ts, payload, meta, prec, err2 = _phase2(wpad, ts_off, p1, val_off,
                                                p2, extract_impl=extract)
    if not scan_major:
        ts, payload, meta = ts.T, payload.T, meta.T
    return ts, payload, meta, err | err2, prec, ann


def payload_value_bits(payload: np.ndarray, meta: np.ndarray) -> np.ndarray:
    """Host-side float64 BIT reconstruction from raw decode outputs.

    Float payloads (meta bit 3) ARE the bits; int payloads divide by
    10^mult (meta bits 0-2) in numpy's IEEE f64 — bit-identical to the
    reference's own f64 division, so the result upholds the codec's
    lossless-bits contract.  Elementwise/layout-blind: works on (S, P)
    or scan-major (P, S) arrays.  THE one home of the meta-layout
    knowledge on the host side — decode_batch and bench validation both
    call it.
    """
    isf = (meta & 8) != 0
    mult = (meta & 7).astype(np.int64)
    ivals = (payload.astype(np.int64).astype(np.float64)
             / np.power(10.0, mult))
    return np.where(isf, payload, ivals.view(np.uint64))


def decode_batch(streams: list[bytes], max_points: int,
                 default_unit: Unit = Unit.SECOND,
                 annotations_fallback: bool = True,
                 chains: str = "auto"):
    """Host-facing batched decode.

    Returns (timestamps (S, P) int64, values (S, P) float64,
    counts (S,), fallback (S,) bool).  Fallback series (>2^53
    magnitudes, errors) must use the scalar ReaderIterator.

    Annotated streams decode on device (timestamps/values come back
    correct; each annotation consumes one max_points slot) but their
    annotation BYTES are skipped, so by default they still flag
    fallback for callers that need the bytes (tag payloads, proto
    schemas); pass annotations_fallback=False when only the numeric
    series matters.
    """
    words, nbits = pack_streams(streams)
    ts, payload, meta, err, prec, ann = decode_batch_device(
        jnp.asarray(words), jnp.asarray(nbits), max_points=max_points,
        default_unit=int(default_unit), chains=chains, scan_major=True)
    # Scan-major on device (the terminal transposes were 30% of decode
    # wall-time on CPU); the value reconstruction (payload_value_bits)
    # is elementwise (layout-blind), so it runs on the contiguous
    # (P, S) arrays and the (S, P) flip happens ONCE on the two
    # results, where numpy's tiled copy is cheaper than three XLA
    # passes.  .T.copy() (not ascontiguousarray) so the result is
    # ALWAYS a writable host copy — for S == 1 the transposed view is
    # already C-contiguous and ascontiguousarray would return the
    # read-only device buffer itself, breaking the in-place compaction
    # below.
    payload_pm = np.asarray(payload)            # (P, S), contiguous
    meta_pm = np.asarray(meta)
    valid_pm = (meta_pm & 16) != 0
    ts = np.asarray(ts).T.copy()
    values = payload_value_bits(payload_pm, meta_pm).view(np.float64).T.copy()
    valid = valid_pm.T
    counts = valid_pm.sum(axis=0)
    ann_np = np.asarray(ann)
    if ann_np.any():
        # Annotation slots leave holes in annotated rows; compact each
        # row's valid datapoints to a prefix (the contract counts rely
        # on).  ts/values are fresh writable host copies (the .T.copy()
        # above), so in-place edits are safe.
        for i in np.nonzero(ann_np)[0]:
            m = valid[i]
            k = int(m.sum())
            ts[i, :k] = ts[i, m]
            values[i, :k] = values[i, m]
            ts[i, k:] = 0
            values[i, k:] = 0.0
    fallback = np.asarray(err) | np.asarray(prec)
    if annotations_fallback:
        fallback = fallback | ann_np
    return ts, values, counts, fallback
