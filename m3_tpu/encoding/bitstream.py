"""Bit-level output/input streams, MSB-first within each byte.

Capability equivalent of the reference's ``src/dbnode/encoding/ostream.go``
(WriteBits writes the low ``n`` bits of a value most-significant-first,
``ostream.go:180-220``) and ``istream.go`` (ReadBits/PeekBits,
``istream.go:71-126``).  This host-side implementation backs the scalar
oracle codec; the batched TPU codec packs bits with vectorized scans
instead (see ``m3tsz_jax.py``).
"""

from __future__ import annotations

_MASK64 = (1 << 64) - 1


class OStream:
    """Append-only bit stream. Bits fill each byte from the MSB down."""

    __slots__ = ("_buf", "_pos")

    def __init__(self) -> None:
        self._buf = bytearray()
        # Number of bits used in the final byte (1..8); 8 means full/aligned.
        self._pos = 8

    def __len__(self) -> int:  # bytes, rounding the partial byte up
        return len(self._buf)

    @property
    def bit_length(self) -> int:
        if not self._buf:
            return 0
        return (len(self._buf) - 1) * 8 + self._pos

    @property
    def last_byte_pos(self) -> int:
        """Bits used in last byte (1..8); matches reference ``os.pos``."""
        return self._pos

    def write_bit(self, v: int) -> None:
        self.write_bits(v & 1, 1)

    def write_bits(self, v: int, num_bits: int) -> None:
        if num_bits <= 0:
            return
        v &= (1 << num_bits) - 1 if num_bits < 64 else _MASK64
        if num_bits > 64:  # mirror reference clamp (ostream.go:185-187)
            num_bits = 64
        buf, pos = self._buf, self._pos
        while num_bits > 0:
            if pos == 8:
                buf.append(0)
                pos = 0
            take = min(8 - pos, num_bits)
            chunk = (v >> (num_bits - take)) & ((1 << take) - 1)
            buf[-1] |= chunk << (8 - pos - take)
            pos += take
            num_bits -= take
        self._pos = pos

    def write_byte(self, v: int) -> None:
        self.write_bits(v & 0xFF, 8)

    def write_bytes(self, bs: bytes) -> None:
        if self._pos == 8:
            self._buf.extend(bs)
        else:
            for b in bs:
                self.write_byte(b)

    def raw_bytes(self) -> tuple[bytes, int]:
        """(raw buffer including partial last byte, bits used in last byte)."""
        return bytes(self._buf), self._pos

    def bytes_aligned(self) -> bytes:
        """Zero-padded byte string of everything written."""
        return bytes(self._buf)


class IStream:
    """Bit reader over a byte string, MSB-first."""

    __slots__ = ("_data", "_bitpos", "_nbits")

    def __init__(self, data: bytes) -> None:
        self._data = data
        self._bitpos = 0
        self._nbits = len(data) * 8

    @property
    def bit_pos(self) -> int:
        return self._bitpos

    def remaining_bits(self) -> int:
        return self._nbits - self._bitpos

    def read_bits(self, num_bits: int) -> int:
        v = self.peek_bits(num_bits)
        self._bitpos += num_bits
        return v

    def peek_bits(self, num_bits: int) -> int:
        if num_bits == 0:
            return 0
        start = self._bitpos
        end = start + num_bits
        if end > self._nbits:
            raise EOFError("end of stream")
        first_byte = start >> 3
        last_byte = (end + 7) >> 3
        word = int.from_bytes(self._data[first_byte:last_byte], "big")
        tail = (last_byte << 3) - end
        return (word >> tail) & ((1 << num_bits) - 1)

    def try_peek_bits(self, num_bits: int) -> int | None:
        if self._bitpos + num_bits > self._nbits:
            return None
        return self.peek_bits(num_bits)

    def read_bit(self) -> int:
        return self.read_bits(1)

    def read_byte(self) -> int:
        return self.read_bits(8)

    def read_bytes(self, n: int) -> bytes:
        return bytes(self.read_byte() for _ in range(n))
