"""Bit-exact M3TSZ codec (scalar host oracle).

This is a from-scratch implementation of the M3TSZ stream format — the
Gorilla-style TSZ codec with M3's int optimization — producing output
byte-identical to the reference implementation
(``src/dbnode/encoding/m3tsz/{encoder.go,timestamp_encoder.go,
float_encoder_iterator.go,int_sig_bits_tracker.go,m3tsz.go}`` and
``src/dbnode/encoding/scheme.go``).  It serves as the correctness oracle
for the batched TPU codec (``m3tsz_jax.py``) and the C++ host codec.

Stream layout (int-optimized mode, the default):

* 64-bit first timestamp (UnixNano of the encoder's start time), then per
  datapoint: [annotation marker?][time-unit marker?][delta-of-delta]
  [value bits].
* Delta-of-delta uses per-unit bucket schemes (see ``scheme.py``); a
  time-unit change writes a marker + unit byte + 64-bit nanosecond dod and
  resets the previous delta to zero.
* Values: first value writes a mode bit (0=int, 1=float); ints are stored
  as significant-bit-tracked diffs of ``value * 10^mult``; floats as XOR
  with leading/trailing-zero windows.
* The finalized stream ends with the 11-bit end-of-stream marker.
"""

from __future__ import annotations

import math
import struct
from dataclasses import dataclass, field

from m3_tpu.core.xtime import (
    Unit,
    initial_time_unit,
    to_normalized_duration,
    unit_from_byte,
)
from m3_tpu.encoding import scheme as _scheme
from m3_tpu.encoding.bitstream import IStream, OStream
from m3_tpu.encoding.scheme import (
    ANNOTATION_MARKER,
    END_OF_STREAM_MARKER,
    MARKER_OPCODE,
    NUM_MARKER_OPCODE_BITS,
    NUM_MARKER_VALUE_BITS,
    TIME_UNIT_MARKER,
    scheme_for_unit,
    sign_extend,
    tail_bytes,
    write_special_marker,
)

# --- constants mirroring m3tsz.go:28-62 ---

DEFAULT_INT_OPTIMIZATION_ENABLED = True

OPCODE_ZERO_SIG = 0x0
OPCODE_NON_ZERO_SIG = 0x1
NUM_SIG_BITS = 6

OPCODE_ZERO_VALUE_XOR = 0x0
OPCODE_CONTAINED_VALUE_XOR = 0x2
OPCODE_UNCONTAINED_VALUE_XOR = 0x3
OPCODE_NO_UPDATE_SIG = 0x0
OPCODE_UPDATE_SIG = 0x1
OPCODE_UPDATE = 0x0
OPCODE_NO_UPDATE = 0x1
OPCODE_UPDATE_MULT = 0x1
OPCODE_NO_UPDATE_MULT = 0x0
OPCODE_POSITIVE = 0x0
OPCODE_NEGATIVE = 0x1
OPCODE_REPEAT = 0x1
OPCODE_NO_REPEAT = 0x0
OPCODE_FLOAT_MODE = 0x1
OPCODE_INT_MODE = 0x0

SIG_DIFF_THRESHOLD = 3
SIG_REPEAT_THRESHOLD = 5

MAX_MULT = 6
NUM_MULT_BITS = 3

_MAX_INT = float(2**63)  # float64(math.MaxInt64) rounds up to 2^63
_MIN_INT = float(-(2**63))
_MAX_OPT_INT = 10.0**13
_MULTIPLIERS = [10.0**i for i in range(MAX_MULT + 1)]

_MASK64 = (1 << 64) - 1


def float_to_bits(v: float) -> int:
    return struct.unpack("<Q", struct.pack("<d", v))[0]


def bits_to_float(b: int) -> float:
    return struct.unpack("<d", struct.pack("<Q", b & _MASK64))[0]


def num_sig(v: int) -> int:
    """Number of significant bits in a uint64 (encoding.go:29-31)."""
    return v.bit_length()


def leading_and_trailing_zeros(v: int) -> tuple[int, int]:
    if v == 0:
        return 64, 0
    lead = 64 - v.bit_length()
    trail = (v & -v).bit_length() - 1
    return lead, trail


def convert_to_int_float(v: float, cur_max_mult: int) -> tuple[float, int, bool]:
    """Attempt float -> (scaled int, multiplier); mirrors m3tsz.go:78-118.

    Returns (value, multiplier, is_float).
    """
    if cur_max_mult == 0 and v < _MAX_INT:
        # Quick check for vals that are already ints.  Go's math.Mod
        # yields NaN for ±Inf (and NaN) inputs, which fails the r == 0
        # test and falls through to the float path; Python's math.fmod
        # RAISES on an infinite numerator, so guard explicitly to keep
        # the reference behavior (m3tsz.go:81-86).
        r = math.fmod(v, 1.0) if math.isfinite(v) else math.nan
        if r == 0:
            return v - r, 0, False

    if cur_max_mult > MAX_MULT:
        raise ValueError("supplied multiplier is invalid")

    val = v * _MULTIPLIERS[cur_max_mult]
    sign = 1.0
    if v < 0:
        sign = -1.0
        val = -val

    mult = cur_max_mult
    while mult <= MAX_MULT and val < _MAX_OPT_INT:
        r, i = math.modf(val)
        if r == 0:
            return sign * i, mult, False
        elif r < 0.1:
            # Round down and check.
            if math.nextafter(val, 0.0) <= i:
                return sign * i, mult, False
        elif r > 0.9:
            # Round up and check.
            nxt = i + 1
            if math.nextafter(val, nxt) >= nxt:
                return sign * nxt, mult, False
        val = val * 10.0
        mult += 1

    return v, 0, True


def convert_from_int_float(val: float, mult: int) -> float:
    if mult == 0:
        return val
    return val / _MULTIPLIERS[mult]


@dataclass
class Datapoint:
    timestamp: int  # UnixNano
    value: float
    # None = "derive from encoder state" (auto units): tuples and plain
    # constructions get exactness-preserving unit selection; decode
    # paths set the stream's explicit unit.
    unit: Unit | None = None
    annotation: bytes = b""


def _float_to_uint64_via_int64(val: float) -> int:
    """Go's ``uint64(int64(val))``: amd64 cvttsd2si semantics — NaN and
    out-of-int64-range floats convert to INT64_MIN, then reinterpret as uint64."""
    if math.isnan(val) or val >= _MAX_INT or val < _MIN_INT:
        return 1 << 63
    return int(val) & _MASK64


def _put_varint(x: int) -> bytes:
    """Go binary.PutVarint: zigzag + LEB128."""
    ux = (x << 1) ^ (x >> 63) if x < 0 else x << 1
    out = bytearray()
    while ux >= 0x80:
        out.append((ux & 0x7F) | 0x80)
        ux >>= 7
    out.append(ux)
    return bytes(out)


def _read_varint(istream: IStream) -> int:
    shift = 0
    ux = 0
    while True:
        b = istream.read_byte()
        ux |= (b & 0x7F) << shift
        if b < 0x80:
            break
        shift += 7
    # zigzag decode
    return (ux >> 1) ^ -(ux & 1)


@dataclass
class TimestampEncoder:
    """Delta-of-delta timestamp encoder state (timestamp_encoder.go:35-259)."""

    prev_time: int
    time_unit: Unit
    prev_time_delta: int = 0
    prev_annotation: bytes | None = None  # None == "empty" sentinel
    has_written_first: bool = False
    time_unit_encoded_manually: bool = False

    @classmethod
    def new(cls, start: int, unit: Unit = Unit.SECOND) -> "TimestampEncoder":
        return cls(prev_time=start, time_unit=initial_time_unit(start, unit))

    def write_time(self, os: OStream, curr: int, annotation: bytes, unit: Unit) -> None:
        if not self.has_written_first:
            self.write_first_time(os, curr, annotation, unit)
            self.has_written_first = True
            return
        self.write_next_time(os, curr, annotation, unit)

    def write_first_time(self, os: OStream, curr: int, annotation: bytes, unit: Unit) -> None:
        # First time is always written in nanoseconds (64 bits of start time).
        os.write_bits(self.prev_time & _MASK64, 64)
        self.write_next_time(os, curr, annotation, unit)

    def write_next_time(self, os: OStream, curr: int, annotation: bytes, unit: Unit) -> None:
        self._write_annotation(os, annotation)
        tu_changed = self._maybe_write_time_unit_change(os, unit)

        time_delta = curr - self.prev_time
        self.prev_time = curr
        if tu_changed or self.time_unit_encoded_manually:
            # Normalize to nanoseconds and write a full 64-bit dod.
            dod = time_delta - self.prev_time_delta
            os.write_bits(dod & _MASK64, 64)
            self.prev_time_delta = 0
            self.time_unit_encoded_manually = False
            return

        self._write_dod(os, self.prev_time_delta, time_delta, unit)
        self.prev_time_delta = time_delta

    def write_time_unit(self, os: OStream, unit: Unit) -> None:
        os.write_byte(int(unit))
        self.time_unit = unit
        self.time_unit_encoded_manually = True

    def auto_unit_for(self, curr: int) -> Unit:
        """State-aware unit choice: the current unit while it represents
        the next delta-of-delta exactly, else the coarsest unit that
        does (reference timestamp_encoder.go:205-246 switches units via
        markers; precision is never rounded away)."""
        dod = (curr - self.prev_time) - self.prev_time_delta
        u = self.time_unit
        if u.is_valid() and u.nanos() > 0 and dod % u.nanos() == 0:
            return u
        for cand in (Unit.SECOND, Unit.MILLISECOND, Unit.MICROSECOND):
            if dod % cand.nanos() == 0:
                return cand
        return Unit.NANOSECOND

    def _maybe_write_time_unit_change(self, os: OStream, unit: Unit) -> bool:
        if not unit.is_valid() or unit == self.time_unit:
            return False
        write_special_marker(os, TIME_UNIT_MARKER)
        self.write_time_unit(os, unit)
        return True

    def _write_annotation(self, os: OStream, annotation: bytes) -> None:
        if not annotation:
            return
        if self.prev_annotation is not None and annotation == self.prev_annotation:
            return
        write_special_marker(os, ANNOTATION_MARKER)
        os.write_bytes(_put_varint(len(annotation) - 1))
        os.write_bytes(annotation)
        self.prev_annotation = annotation

    def _write_dod(self, os: OStream, prev_delta: int, curr_delta: int, unit: Unit) -> None:
        u = unit.nanos()
        if u == 0:
            raise ValueError("invalid time unit for dod encoding")
        dod = to_normalized_duration(curr_delta - prev_delta, u)
        if unit in (Unit.MILLISECOND, Unit.SECOND):
            if not (-(2**31) <= dod < 2**31):
                raise OverflowError(f"deltaOfDelta value {dod} {unit} overflows 32 bits")
        tes = scheme_for_unit(unit)
        if tes is None:
            raise ValueError("time encoding scheme doesn't exist for unit")
        if dod == 0:
            zb = tes.zero_bucket
            os.write_bits(zb.opcode, zb.num_opcode_bits)
            return
        for b in tes.buckets:
            if b.min <= dod <= b.max:
                os.write_bits(b.opcode, b.num_opcode_bits)
                os.write_bits(dod & ((1 << b.num_value_bits) - 1), b.num_value_bits)
                return
        db = tes.default_bucket
        os.write_bits(db.opcode, db.num_opcode_bits)
        os.write_bits(dod & ((1 << db.num_value_bits) - 1), db.num_value_bits)


@dataclass
class FloatXOR:
    """XOR float encode/decode state (float_encoder_iterator.go:34-165)."""

    prev_xor: int = 0
    prev_float_bits: int = 0

    def write_full(self, os: OStream, bits: int) -> None:
        self.prev_float_bits = bits
        self.prev_xor = bits
        os.write_bits(bits, 64)

    def write_next(self, os: OStream, bits: int) -> None:
        xor = self.prev_float_bits ^ bits
        self._write_xor(os, xor)
        self.prev_xor = xor
        self.prev_float_bits = bits

    def _write_xor(self, os: OStream, cur_xor: int) -> None:
        if cur_xor == 0:
            os.write_bits(OPCODE_ZERO_VALUE_XOR, 1)
            return
        prev_lead, prev_trail = leading_and_trailing_zeros(self.prev_xor)
        cur_lead, cur_trail = leading_and_trailing_zeros(cur_xor)
        if cur_lead >= prev_lead and cur_trail >= prev_trail:
            os.write_bits(OPCODE_CONTAINED_VALUE_XOR, 2)
            os.write_bits(cur_xor >> prev_trail, 64 - prev_lead - prev_trail)
            return
        os.write_bits(OPCODE_UNCONTAINED_VALUE_XOR, 2)
        os.write_bits(cur_lead, 6)
        num_meaningful = 64 - cur_lead - cur_trail
        os.write_bits(num_meaningful - 1, 6)
        os.write_bits(cur_xor >> cur_trail, num_meaningful)

    def read_full(self, ist: IStream) -> None:
        bits = ist.read_bits(64)
        self.prev_float_bits = bits
        self.prev_xor = bits

    def read_next(self, ist: IStream) -> None:
        cb = ist.read_bits(1)
        if cb == OPCODE_ZERO_VALUE_XOR:
            self.prev_xor = 0
            return
        cb = (cb << 1) | ist.read_bits(1)
        if cb == OPCODE_CONTAINED_VALUE_XOR:
            prev_lead, prev_trail = leading_and_trailing_zeros(self.prev_xor)
            num_meaningful = 64 - prev_lead - prev_trail
            bits = ist.read_bits(num_meaningful)
            self.prev_xor = (bits << prev_trail) & _MASK64
            self.prev_float_bits ^= self.prev_xor
            return
        packed = ist.read_bits(12)
        num_lead = (packed >> 6) & 0x3F
        num_meaningful = (packed & 0x3F) + 1
        bits = ist.read_bits(num_meaningful)
        num_trail = 64 - num_lead - num_meaningful
        self.prev_xor = (bits << num_trail) & _MASK64
        self.prev_float_bits ^= self.prev_xor


@dataclass
class IntSigBitsTracker:
    """Significant-bit tracker for int diffs (int_sig_bits_tracker.go:27-91)."""

    num_sig: int = 0
    cur_highest_lower_sig: int = 0
    num_lower_sig: int = 0

    def write_int_val_diff(self, os: OStream, val_bits: int, neg: bool) -> None:
        os.write_bit(OPCODE_NEGATIVE if neg else OPCODE_POSITIVE)
        os.write_bits(val_bits & ((1 << self.num_sig) - 1 if self.num_sig < 64 else _MASK64),
                      self.num_sig)

    def write_int_sig(self, os: OStream, sig: int) -> None:
        if self.num_sig != sig:
            os.write_bit(OPCODE_UPDATE_SIG)
            if sig == 0:
                os.write_bit(OPCODE_ZERO_SIG)
            else:
                os.write_bit(OPCODE_NON_ZERO_SIG)
                os.write_bits(sig - 1, NUM_SIG_BITS)
        else:
            os.write_bit(OPCODE_NO_UPDATE_SIG)
        self.num_sig = sig

    def track_new_sig(self, sig: int) -> int:
        new_sig = self.num_sig
        if sig > self.num_sig:
            new_sig = sig
        elif self.num_sig - sig >= SIG_DIFF_THRESHOLD:
            if self.num_lower_sig == 0:
                self.cur_highest_lower_sig = sig
            elif sig > self.cur_highest_lower_sig:
                self.cur_highest_lower_sig = sig
            self.num_lower_sig += 1
            if self.num_lower_sig >= SIG_REPEAT_THRESHOLD:
                new_sig = self.cur_highest_lower_sig
                self.num_lower_sig = 0
        else:
            self.num_lower_sig = 0
        return new_sig


class Encoder:
    """M3TSZ stream encoder (encoder.go:42-250).

    Datapoints with ``unit=None`` derive their time unit from the
    encoder state: keep the current stream unit while it divides the
    delta-of-delta exactly, otherwise switch (with a marker) to the
    coarsest unit that does.  This is the faithful mapping of the
    reference's per-write unit metadata onto an API whose timestamps
    are raw int64 nanos — a sub-unit timestamp can NEVER be silently
    rounded (the round-4 flush-precision bug), and aligned streams stay
    byte-identical to the fixed-unit form."""

    def __init__(self, start: int, int_optimized: bool = True,
                 unit: Unit = Unit.SECOND):
        self.os = OStream()
        self.ts = TimestampEncoder.new(start, unit)
        self.float_enc = FloatXOR()
        self.sig_tracker = IntSigBitsTracker()
        self.int_val = 0.0
        self.num_encoded = 0
        self.max_mult = 0
        self.int_optimized = int_optimized
        self.is_float = False

    def encode(self, dp: Datapoint) -> None:
        unit = dp.unit
        if unit is None:  # derive exactness-preserving unit from state
            unit = self.ts.auto_unit_for(dp.timestamp)
        self.ts.write_time(self.os, dp.timestamp, dp.annotation, unit)
        if self.num_encoded == 0:
            self._write_first_value(dp.value)
        else:
            self._write_next_value(dp.value)
        self.num_encoded += 1

    def _write_first_value(self, v: float) -> None:
        if not self.int_optimized:
            self.float_enc.write_full(self.os, float_to_bits(v))
            return
        val, mult, is_float = convert_to_int_float(v, 0)
        if is_float:
            self.os.write_bit(OPCODE_FLOAT_MODE)
            self.float_enc.write_full(self.os, float_to_bits(v))
            self.is_float = True
            self.max_mult = mult
            return
        self.os.write_bit(OPCODE_INT_MODE)
        self.int_val = val
        neg_diff = True
        if val < 0:
            neg_diff = False
            val = -val
        val_bits = _float_to_uint64_via_int64(val)
        sig = num_sig(val_bits)
        self._write_int_sig_mult(sig, mult, False)
        self.sig_tracker.write_int_val_diff(self.os, val_bits, neg_diff)

    def _write_next_value(self, v: float) -> None:
        if not self.int_optimized:
            self.float_enc.write_next(self.os, float_to_bits(v))
            return
        val, mult, is_float = convert_to_int_float(v, self.max_mult)
        val_diff = 0.0
        if not is_float:
            val_diff = self.int_val - val
        if is_float or val_diff >= _MAX_INT or val_diff <= _MIN_INT:
            self._write_float_val(float_to_bits(val), mult)
            return
        self._write_int_val(val, mult, is_float, val_diff)

    def _write_float_val(self, bits: int, mult: int) -> None:
        if not self.is_float:
            self.os.write_bit(OPCODE_UPDATE)
            self.os.write_bit(OPCODE_NO_REPEAT)
            self.os.write_bit(OPCODE_FLOAT_MODE)
            self.float_enc.write_full(self.os, bits)
            self.is_float = True
            self.max_mult = mult
            return
        if bits == self.float_enc.prev_float_bits:
            self.os.write_bit(OPCODE_UPDATE)
            self.os.write_bit(OPCODE_REPEAT)
            return
        self.os.write_bit(OPCODE_NO_UPDATE)
        self.float_enc.write_next(self.os, bits)

    def _write_int_val(self, val: float, mult: int, is_float: bool, val_diff: float) -> None:
        if val_diff == 0 and is_float == self.is_float and mult == self.max_mult:
            self.os.write_bit(OPCODE_UPDATE)
            self.os.write_bit(OPCODE_REPEAT)
            return
        neg = False
        if val_diff < 0:
            neg = True
            val_diff = -val_diff
        val_diff_bits = int(val_diff)
        sig = num_sig(val_diff_bits)
        new_sig = self.sig_tracker.track_new_sig(sig)
        is_float_changed = is_float != self.is_float
        if mult > self.max_mult or self.sig_tracker.num_sig != new_sig or is_float_changed:
            self.os.write_bit(OPCODE_UPDATE)
            self.os.write_bit(OPCODE_NO_REPEAT)
            self.os.write_bit(OPCODE_INT_MODE)
            self._write_int_sig_mult(new_sig, mult, is_float_changed)
            self.sig_tracker.write_int_val_diff(self.os, val_diff_bits, neg)
            self.is_float = False
        else:
            self.os.write_bit(OPCODE_NO_UPDATE)
            self.sig_tracker.write_int_val_diff(self.os, val_diff_bits, neg)
        self.int_val = val

    def _write_int_sig_mult(self, sig: int, mult: int, float_changed: bool) -> None:
        self.sig_tracker.write_int_sig(self.os, sig)
        if mult > self.max_mult:
            self.os.write_bit(OPCODE_UPDATE_MULT)
            self.os.write_bits(mult, NUM_MULT_BITS)
            self.max_mult = mult
        elif self.sig_tracker.num_sig == sig and self.max_mult == mult and float_changed:
            self.os.write_bit(OPCODE_UPDATE_MULT)
            self.os.write_bits(self.max_mult, NUM_MULT_BITS)
        else:
            self.os.write_bit(OPCODE_NO_UPDATE_MULT)

    def stream(self) -> bytes:
        """Finalized stream: head bytes + tail (last byte bits + EOS marker)."""
        raw, pos = self.os.raw_bytes()
        if not raw:
            return b""
        return raw[:-1] + tail_bytes(raw[-1], pos)

    def last_encoded(self) -> Datapoint:
        if self.num_encoded == 0:
            raise ValueError("encoder has no encoded datapoints")
        value = (
            bits_to_float(self.float_enc.prev_float_bits) if self.is_float else self.int_val
        )
        return Datapoint(self.ts.prev_time, value, self.ts.time_unit)


class ReaderIterator:
    """M3TSZ stream decoder (iterator.go:47-278, timestamp_iterator.go:41-361)."""

    def __init__(self, data: bytes, int_optimized: bool = True,
                 default_unit: Unit = Unit.SECOND, skip_markers: bool = False):
        self.ist = IStream(data)
        self.int_optimized = int_optimized
        self.skip_markers = skip_markers
        self.default_unit = default_unit
        # timestamp state
        self.prev_time = 0
        self.prev_time_delta = 0
        self.time_unit = Unit.NONE
        self.time_unit_changed = False
        self.done = False
        self.cur_annotation: bytes = b""
        # value state
        self.float_iter = FloatXOR()
        self.int_val = 0.0
        self.mult = 0
        self.sig = 0
        self.is_float = False
        self.curr: Datapoint | None = None

    # -- timestamp path --

    def _read_timestamp(self) -> bool:
        """Returns True if this was the first timestamp; sets self.done on EOS."""
        self.cur_annotation = b""
        first = False
        if self.prev_time != 0:
            dod = self._read_marker_or_dod()
            if not self.done:
                self.prev_time_delta += dod
                self.prev_time += self.prev_time_delta
        else:
            first = True
            self._read_first_timestamp()
        if self.time_unit_changed:
            self.prev_time_delta = 0
            self.time_unit_changed = False
        return first

    def _read_first_timestamp(self) -> None:
        nt = sign_extend(self.ist.read_bits(64), 64)
        if self.time_unit == Unit.NONE:
            self.time_unit = initial_time_unit(nt, self.default_unit)
        dod = self._read_marker_or_dod()
        if self.done:
            return
        self.prev_time_delta += dod
        self.prev_time = nt + self.prev_time_delta

    def _read_marker_or_dod(self) -> int:
        if not self.skip_markers:
            dod, success = self._try_read_marker()
            if success or self.done:
                return dod
        return self._read_dod()

    def _try_read_marker(self) -> tuple[int, bool]:
        num_bits = NUM_MARKER_OPCODE_BITS + NUM_MARKER_VALUE_BITS
        peek = self.ist.try_peek_bits(num_bits)
        if peek is None:
            return 0, False
        opcode = peek >> NUM_MARKER_VALUE_BITS
        if opcode != MARKER_OPCODE:
            return 0, False
        marker = peek & ((1 << NUM_MARKER_VALUE_BITS) - 1)
        if marker == END_OF_STREAM_MARKER:
            self.ist.read_bits(num_bits)
            self.done = True
            return 0, True
        elif marker == ANNOTATION_MARKER:
            self.ist.read_bits(num_bits)
            ant_len = _read_varint(self.ist) + 1
            if ant_len <= 0:
                raise ValueError("expected annotation length to be >= 0")
            self.cur_annotation = self.ist.read_bytes(ant_len)
            return self._read_marker_or_dod(), True
        elif marker == TIME_UNIT_MARKER:
            self.ist.read_bits(num_bits)
            self._read_time_unit()
            return self._read_marker_or_dod(), True
        return 0, False

    def _read_time_unit(self) -> None:
        tu = unit_from_byte(self.ist.read_bits(8))
        if tu.is_valid() and tu != self.time_unit:
            self.time_unit_changed = True
        self.time_unit = tu

    def _read_dod(self) -> int:
        if self.time_unit_changed:
            # Full 64-bit nanosecond dod after a time unit change.
            return sign_extend(self.ist.read_bits(64), 64)
        tes = scheme_for_unit(self.time_unit)
        if tes is None:
            raise ValueError("time encoding scheme doesn't exist for unit")
        cb = self.ist.read_bits(1)
        if cb == tes.zero_bucket.opcode:
            return 0
        for bucket in tes.buckets:
            cb = (cb << 1) | self.ist.read_bits(1)
            if cb == bucket.opcode:
                dod = sign_extend(self.ist.read_bits(bucket.num_value_bits),
                                  bucket.num_value_bits)
                return dod * self.time_unit.nanos()
        dod = sign_extend(self.ist.read_bits(tes.default_bucket.num_value_bits),
                          tes.default_bucket.num_value_bits)
        return dod * self.time_unit.nanos()

    # -- value path --

    def _read_first_value(self) -> None:
        if not self.int_optimized:
            self.float_iter.read_full(self.ist)
            return
        if self.ist.read_bits(1) == OPCODE_FLOAT_MODE:
            self.float_iter.read_full(self.ist)
            self.is_float = True
            return
        self._read_int_sig_mult()
        self._read_int_val_diff()

    def _read_next_value(self) -> None:
        if not self.int_optimized:
            self.float_iter.read_next(self.ist)
            return
        if self.ist.read_bits(1) == OPCODE_UPDATE:
            if self.ist.read_bits(1) == OPCODE_REPEAT:
                return
            if self.ist.read_bits(1) == OPCODE_FLOAT_MODE:
                self.float_iter.read_full(self.ist)
                self.is_float = True
                return
            self._read_int_sig_mult()
            self._read_int_val_diff()
            self.is_float = False
            return
        if self.is_float:
            self.float_iter.read_next(self.ist)
            return
        self._read_int_val_diff()

    def _read_int_sig_mult(self) -> None:
        if self.ist.read_bits(1) == OPCODE_UPDATE_SIG:
            if self.ist.read_bits(1) == OPCODE_ZERO_SIG:
                self.sig = 0
            else:
                self.sig = self.ist.read_bits(NUM_SIG_BITS) + 1
        if self.ist.read_bits(1) == OPCODE_UPDATE_MULT:
            self.mult = self.ist.read_bits(NUM_MULT_BITS)
            if self.mult > MAX_MULT:
                raise ValueError("supplied multiplier is invalid")

    def _read_int_val_diff(self) -> None:
        if self.sig == 64:
            sign = 1.0 if self.ist.read_bits(1) == OPCODE_NEGATIVE else -1.0
            self.int_val += sign * float(self.ist.read_bits(self.sig))
            return
        bits = self.ist.read_bits(self.sig + 1)
        sign = -1.0
        if (bits >> self.sig) == OPCODE_NEGATIVE:
            sign = 1.0
            bits ^= 1 << self.sig
        self.int_val += sign * float(bits)

    # -- iteration --

    def __iter__(self):
        return self

    def __next__(self) -> Datapoint:
        if self.done:
            raise StopIteration
        first = self._read_timestamp()
        if self.done:
            raise StopIteration
        if first:
            self._read_first_value()
        else:
            self._read_next_value()
        if not self.int_optimized or self.is_float:
            value = bits_to_float(self.float_iter.prev_float_bits)
        else:
            value = convert_from_int_float(self.int_val, self.mult)
        self.curr = Datapoint(self.prev_time, value, self.time_unit, self.cur_annotation)
        return self.curr


def encode_series(datapoints, start: int | None = None,
                  int_optimized: bool = True, unit: Unit = Unit.SECOND) -> bytes:
    """Encode a sequence of (timestamp, value) or Datapoint into one stream.

    Bare (timestamp, value) tuples become unit=None datapoints, whose
    units derive per datapoint from the encoder state: a sub-unit delta
    switches the stream to a finer unit with a marker instead of being
    SILENTLY ROUNDED (the bug the round-4 race tier caught: flushed
    blocks lost nanosecond offsets), while aligned streams stay
    byte-identical to the fixed-unit form.  Datapoints with an explicit
    unit keep it — the reference's semantics, where precision is
    per-write metadata — and mixing the two forms is safe."""
    dps = [dp if isinstance(dp, Datapoint) else Datapoint(dp[0], dp[1])
           for dp in datapoints]
    if not dps:
        return b""
    if start is None:
        start = dps[0].timestamp
    enc = Encoder(start, int_optimized=int_optimized, unit=unit)
    for dp in dps:
        enc.encode(dp)  # unit=None datapoints (tuples) auto-derive
    return enc.stream()


def decode_series(data: bytes, int_optimized: bool = True,
                  default_unit: Unit = Unit.SECOND) -> list[Datapoint]:
    if not data:
        return []
    return list(ReaderIterator(data, int_optimized=int_optimized, default_unit=default_unit))
