"""Protobuf descriptor bridge for the proto value codec.

Equivalent of the reference's descriptor-driven proto encoding
(`src/dbnode/encoding/proto/encoder.go` parses real protobuf schemas;
the schema travels as a FileDescriptorSet annotation and nested
messages compress field-by-field).  The columnar codec in
``proto_codec.py`` stays the compression engine; this module maps real
protobuf message descriptors onto its (name, kind) schema:

* scalar fields map directly (ints/enums/bool -> INT, float/double ->
  FLOAT, string/bytes -> BYTES so they ride the byte-field LRU);
* NESTED message fields flatten to dotted column names
  (``outer.inner.value``), arbitrarily deep — the columnar model's
  answer to the reference's recursive custom marshal;
* the schema annotation is a serialized FileDescriptorSet plus the
  fully-qualified message name (``pack_schema_annotation``), the same
  payload shape the reference stores, so it can ride the codec
  annotation path (commitlog annotations / M3TSZ first-datapoint
  annotations);
* REPEATED fields, MAPS, and ``oneof`` groups ride OPAQUE BYTES
  columns: the field (or the oneof's set branch) serializes to its own
  proto wire bytes (deterministic map ordering) and compresses through
  the byte-field LRU — the role of the reference's "remaining fields"
  custom marshal (`encoder.go` marshals non-custom fields as a delta'd
  proto blob) rather than per-element columns, which would break the
  dense-column device contract.
"""

from __future__ import annotations

import struct

from m3_tpu.encoding.proto_codec import FieldKind, Schema

class UnsupportedFieldError(ValueError):
    pass


def _real_oneofs(desc):
    """Declared oneof groups only: proto3 `optional` fields synthesize a
    single-member oneof named `_<field>` — those are plain presence
    tracking and must keep their native scalar columns, not an opaque
    blob (the python descriptor API exposes no is_synthetic flag; the
    protoc naming contract is the detection)."""
    return [o for o in desc.oneofs
            if not (len(o.fields) == 1
                    and o.name == "_" + o.fields[0].name)]


def _optional_fields(desc) -> set:
    """Field names with proto3 `optional` presence (synthetic oneofs):
    they ride their native column PLUS a `<name>@set` bool column so
    unset-vs-explicit-default survives the round trip."""
    return {o.fields[0].name for o in desc.oneofs
            if len(o.fields) == 1 and o.name == "_" + o.fields[0].name}


def _kind_for(field) -> FieldKind:
    from google.protobuf import descriptor as _d

    FD = _d.FieldDescriptor
    t = field.type
    if t in (FD.TYPE_INT32, FD.TYPE_INT64, FD.TYPE_UINT32, FD.TYPE_UINT64,
             FD.TYPE_SINT32, FD.TYPE_SINT64, FD.TYPE_FIXED32,
             FD.TYPE_FIXED64, FD.TYPE_SFIXED32, FD.TYPE_SFIXED64,
             FD.TYPE_ENUM):
        return FieldKind.INT
    if t in (FD.TYPE_FLOAT, FD.TYPE_DOUBLE):
        return FieldKind.FLOAT
    if t in (FD.TYPE_STRING, FD.TYPE_BYTES):
        return FieldKind.BYTES
    if t == FD.TYPE_BOOL:
        return FieldKind.BOOL
    raise UnsupportedFieldError(
        f"field {field.full_name!r} type {t} unsupported"
    )


def schema_from_descriptor(desc, prefix: str = "",
                           _depth: int = 0) -> Schema:
    """Flatten a protobuf message Descriptor into the columnar schema.

    Nested message fields recurse with dotted names; field order is the
    declaration order at every level (deterministic wire order)."""
    from google.protobuf import descriptor as _d

    if _depth > 16:
        raise UnsupportedFieldError("message nesting too deep")
    fields: list[tuple[str, FieldKind]] = []
    real = _real_oneofs(desc)
    oneofs = {f.name for o in real for f in o.fields}
    for o in real:
        # one opaque column per oneof group: only the SET branch
        # serializes, so which-branch state survives the round trip
        fields.append((prefix + "__oneof__." + o.name, FieldKind.BYTES))
    optionals = _optional_fields(desc)
    for field in desc.fields:
        if field.name in oneofs:
            continue
        name = prefix + field.name
        if field.is_repeated:
            fields.append((name, FieldKind.BYTES))  # opaque wire bytes
        elif field.type == _d.FieldDescriptor.TYPE_MESSAGE:
            sub = schema_from_descriptor(field.message_type, name + ".",
                                         _depth + 1)
            fields.extend(sub.fields)
        else:
            fields.append((name, _kind_for(field)))
            if field.name in optionals:
                fields.append((name + "@set", FieldKind.BOOL))
    return Schema(tuple(fields))


def _field_wire_bytes(m, field) -> bytes:
    """Serialize ONE field's state to proto wire bytes (tag included)
    by copying it into an empty sibling message — deterministic map
    ordering so equal states produce equal bytes."""
    tmp = type(m)()
    src = getattr(m, field.name)
    dst = getattr(tmp, field.name)
    if field.message_type is not None and field.message_type.GetOptions(
    ).map_entry:
        # map field; message-valued maps forbid update()/assignment
        vf = field.message_type.fields_by_name["value"]
        if vf.type == vf.TYPE_MESSAGE:
            for k in src:
                dst[k].CopyFrom(src[k])
        else:
            dst.update(src)
    elif field.is_repeated:
        if field.type == field.TYPE_MESSAGE:
            dst.MergeFrom(src)
        else:
            dst.extend(src)
    elif field.type == field.TYPE_MESSAGE:
        dst.CopyFrom(src)
    else:
        setattr(tmp, field.name, src)
    return tmp.SerializePartialToString(deterministic=True)


def message_to_columns(msg) -> dict:
    """Flatten one parsed protobuf message to {dotted name: value}
    (schema order supplies defaults for unset scalar fields)."""
    from google.protobuf import descriptor as _d

    out: dict = {}

    def walk(m, prefix: str):
        real = _real_oneofs(m.DESCRIPTOR)
        oneofs = {f.name for o in real for f in o.fields}
        for o in real:
            set_field = m.WhichOneof(o.name)
            out[prefix + "__oneof__." + o.name] = (
                b"" if set_field is None
                else _field_wire_bytes(m, m.DESCRIPTOR.fields_by_name[set_field]))
        optionals = _optional_fields(m.DESCRIPTOR)
        for field in m.DESCRIPTOR.fields:
            if field.name in oneofs:
                continue
            name = prefix + field.name
            if field.is_repeated:
                out[name] = _field_wire_bytes(m, field)
            elif field.type == _d.FieldDescriptor.TYPE_MESSAGE:
                walk(getattr(m, field.name), name + ".")
            else:
                v = getattr(m, field.name)
                if field.type == _d.FieldDescriptor.TYPE_STRING:
                    v = v.encode()
                elif field.type in (_d.FieldDescriptor.TYPE_FLOAT,
                                    _d.FieldDescriptor.TYPE_DOUBLE):
                    v = float(v)
                out[name] = v
                if field.name in optionals:
                    out[name + "@set"] = m.HasField(field.name)

    walk(msg, "")
    return out


def columns_to_message(msg, columns: dict):
    """Fill a protobuf message instance from flattened columns; returns
    the message (strings decode back from bytes)."""
    from google.protobuf import descriptor as _d

    def walk(m, prefix: str):
        real = _real_oneofs(m.DESCRIPTOR)
        oneofs = {f.name for o in real for f in o.fields}
        for o in real:
            blob = columns.get(prefix + "__oneof__." + o.name)
            if blob:
                m.MergeFromString(blob)
        optionals = _optional_fields(m.DESCRIPTOR)
        for field in m.DESCRIPTOR.fields:
            if field.name in oneofs:
                continue
            name = prefix + field.name
            if field.is_repeated:
                blob = columns.get(name)
                if blob:
                    m.MergeFromString(blob)
                continue
            if field.type == _d.FieldDescriptor.TYPE_MESSAGE:
                walk(getattr(m, field.name), name + ".")
                continue
            if field.name in optionals and not columns.get(name + "@set"):
                continue  # unset `optional` stays unset
            v = columns.get(name)
            if v is None:
                continue
            if field.type == _d.FieldDescriptor.TYPE_STRING:
                v = v.decode() if isinstance(v, bytes) else v
            elif field.type == _d.FieldDescriptor.TYPE_BOOL:
                v = bool(v)
            elif field.type in (_d.FieldDescriptor.TYPE_FLOAT,
                                _d.FieldDescriptor.TYPE_DOUBLE):
                v = float(v)
            else:
                v = int(v)
            setattr(m, field.name, v)

    walk(msg, "")
    return msg


# ---------------------------------------------------------------------------
# Schema annotation: serialized FileDescriptorSet + message name — the
# wire form the reference stores so decoders can rebuild the schema.
# ---------------------------------------------------------------------------

_SCHEMA_MAGIC = b"m3ps"


def pack_schema_annotation(file_descriptor_set_bytes: bytes,
                           message_name: str) -> bytes:
    name = message_name.encode()
    return (_SCHEMA_MAGIC + struct.pack("<H", len(name)) + name
            + file_descriptor_set_bytes)


def unpack_schema_annotation(raw: bytes):
    """(FileDescriptorSet bytes, message name) or None when `raw` is
    not a schema annotation."""
    if not raw.startswith(_SCHEMA_MAGIC):
        return None
    (n,) = struct.unpack_from("<H", raw, 4)
    name = raw[6 : 6 + n].decode()
    return raw[6 + n :], name


def descriptor_from_annotation(raw: bytes):
    """Rebuild the message Descriptor from a schema annotation through a
    fresh descriptor pool (the decode-side path: a node that has never
    seen this schema learns it from the stream)."""
    from google.protobuf import descriptor_pb2, descriptor_pool

    unpacked = unpack_schema_annotation(raw)
    if unpacked is None:
        raise ValueError("not a schema annotation")
    fds_bytes, message_name = unpacked
    fds = descriptor_pb2.FileDescriptorSet()
    fds.MergeFromString(fds_bytes)
    pool = descriptor_pool.DescriptorPool()
    for f in fds.file:
        pool.Add(f)
    return pool.FindMessageTypeByName(message_name)


def message_class_for(desc):
    """A concrete message class for a Descriptor (decode-side
    materialization)."""
    from google.protobuf import message_factory

    return message_factory.GetMessageClass(desc)
