"""Proto value codec: per-field compression of message-valued series.

Equivalent of the reference's protobuf encoder
(`src/dbnode/encoding/proto` — custom marshal + per-field compression:
float fields XOR'd like the m3tsz float path
(`float_encoder_iterator.go`), int fields delta-compressed
(`int_encoder_iterator.go`), bytes fields through a small LRU dict
(`byteFieldDictLRUSize=4`, `encoding/options.go:33`); per-message
changed-field tracking so an unchanged field costs one bit).

Redesign notes (not a port): the reference parses real protobuf
descriptors; here a schema is an explicit ordered tuple of
(name, kind) with kind ∈ {INT, FLOAT, BYTES, BOOL} — the columnar
essence of the format without a protobuf runtime (message
marshal/unmarshal is the caller's business; this layer compresses the
*columns*).  The float path reuses the exact m3tsz `FloatXOR` control
bits; timestamps use a self-contained delta-of-delta (zigzag varbits)
with a continuation bit per message, since proto streams have no
cross-implementation bit-exactness contract to honor.

Stream layout:
  [first_ts: 64 bits]
  per message: [cont=1] [dod: zigzag varbits]
               [changed-bitset: one bit per schema field]
               per changed field its kind-specific payload:
                 FLOAT  m3tsz FloatXOR (full 64 bits first, XOR after)
                 INT    zigzag(delta) varbits
                 BYTES  2-bit LRU dict index, or literal marker +
                        varbits length + bytes
                 BOOL   1 bit
  [cont=0]  end of stream
"""

from __future__ import annotations

import copy
import enum
from dataclasses import dataclass

from m3_tpu.encoding.bitstream import IStream, OStream
from m3_tpu.encoding.m3tsz import FloatXOR, bits_to_float, float_to_bits

_DICT_SIZE = 4  # reference byteFieldDictLRUSize, encoding/options.go:33
_MASK64 = (1 << 64) - 1


class FieldKind(enum.IntEnum):
    INT = 0
    FLOAT = 1
    BYTES = 2
    BOOL = 3


@dataclass(frozen=True)
class Schema:
    """Ordered field schema; order is the wire order."""

    fields: tuple[tuple[str, FieldKind], ...]

    def __post_init__(self):
        if not self.fields:
            raise ValueError("empty schema")
        names = [n for n, _ in self.fields]
        if len(set(names)) != len(names):
            raise ValueError("duplicate field names in schema")


def _zigzag(v: int) -> int:
    # Arithmetic (not shift/mask) form, arbitrary precision on purpose:
    # varbits carry any magnitude, and the usual `(v << 1) ^ (v >> 63)`
    # silently corrupts deltas below -2**63 (e.g. 2**62 -> -(2**62)-1
    # between consecutive samples) because Python's arithmetic shift of
    # such values is no longer -1.
    return v << 1 if v >= 0 else ((-v) << 1) - 1


def _unzigzag(u: int) -> int:
    return (u >> 1) ^ -(u & 1)


def _write_varbits(os: OStream, u: int) -> None:
    """7-bit groups with a continuation bit, bit-packed."""
    while True:
        group = u & 0x7F
        u >>= 7
        os.write_bit(1 if u else 0)
        os.write_bits(group, 7)
        if not u:
            return


def _read_varbits(ist: IStream) -> int:
    out = 0
    shift = 0
    while True:
        more = ist.read_bit()
        out |= ist.read_bits(7) << shift
        shift += 7
        if not more:
            return out


class _FloatField:
    """m3tsz FloatXOR per float field."""

    __slots__ = ("xor", "first")

    def __init__(self):
        self.xor = FloatXOR()
        self.first = True

    def encode(self, os: OStream, value: float) -> None:
        bits = float_to_bits(value)
        if self.first:
            self.xor.write_full(os, bits)
            self.first = False
        else:
            self.xor.write_next(os, bits)

    def decode(self, ist: IStream) -> float:
        if self.first:
            self.xor.read_full(ist)
            self.first = False
        else:
            self.xor.read_next(ist)
        return bits_to_float(self.xor.prev_float_bits)


class _IntField:
    """zigzag(delta) varbits per int field (int_encoder_iterator.go's
    delta role, varbit form)."""

    __slots__ = ("prev",)

    def __init__(self):
        self.prev = 0

    def encode(self, os: OStream, value: int) -> None:
        _write_varbits(os, _zigzag(value - self.prev))
        self.prev = value

    def decode(self, ist: IStream) -> int:
        self.prev += _unzigzag(_read_varbits(ist))
        return self.prev


class _BytesField:
    """4-entry LRU dict per bytes field."""

    __slots__ = ("lru",)

    def __init__(self):
        self.lru: list[bytes] = []

    def _touch(self, value: bytes) -> None:
        if value in self.lru:
            self.lru.remove(value)
        self.lru.append(value)
        if len(self.lru) > _DICT_SIZE:
            self.lru.pop(0)

    def encode(self, os: OStream, value: bytes) -> None:
        if value in self.lru:
            os.write_bit(0)  # dict hit
            os.write_bits(self.lru.index(value), 2)
        else:
            os.write_bit(1)  # literal
            _write_varbits(os, len(value))
            os.write_bytes(value)
        self._touch(value)

    def decode(self, ist: IStream) -> bytes:
        if ist.read_bit() == 0:
            value = self.lru[ist.read_bits(2)]
        else:
            n = _read_varbits(ist)
            value = ist.read_bytes(n)
        self._touch(value)
        return value


def _state_for(kind: FieldKind):
    return {
        FieldKind.FLOAT: _FloatField,
        FieldKind.INT: _IntField,
        FieldKind.BYTES: _BytesField,
        FieldKind.BOOL: lambda: None,
    }[kind]()


_DEFAULTS = {
    FieldKind.INT: 0,
    FieldKind.FLOAT: 0.0,
    FieldKind.BYTES: b"",
    FieldKind.BOOL: False,
}


class ProtoEncoder:
    """Encode (timestamp, {field: value}) messages."""

    def __init__(self, schema: Schema, start_nanos: int):
        self.schema = schema
        self._os = OStream()
        self._os.write_bits(start_nanos & _MASK64, 64)
        self._prev_time = start_nanos
        self._prev_delta = 0
        self._states = [_state_for(kind) for _, kind in schema.fields]
        self._current = {
            name: _DEFAULTS[kind] for name, kind in schema.fields
        }
        self.num_encoded = 0

    def encode(self, timestamp_nanos: int, values: dict) -> None:
        unknown = set(values) - set(self._current)
        if unknown:
            raise ValueError(f"fields not in schema: {sorted(unknown)}")
        self._os.write_bit(1)  # continuation
        delta = timestamp_nanos - self._prev_time
        _write_varbits(self._os, _zigzag(delta - self._prev_delta))
        self._prev_time, self._prev_delta = timestamp_nanos, delta
        changed_idx = []
        for i, (name, kind) in enumerate(self.schema.fields):
            is_changed = name in values and values[name] != self._current[name]
            self._os.write_bit(1 if is_changed else 0)
            if is_changed:
                changed_idx.append(i)
        for i in changed_idx:
            name, kind = self.schema.fields[i]
            value = values[name]
            if kind == FieldKind.BOOL:
                self._os.write_bit(1 if value else 0)
            else:
                self._states[i].encode(self._os, value)
            self._current[name] = value
        self.num_encoded += 1

    def stream(self) -> bytes:
        """Finalized stream (the encoder stays usable — m3tsz encoders
        are likewise snapshot-able mid-stream for reads)."""
        final = copy.deepcopy(self._os)
        final.write_bit(0)  # end of stream
        raw, _pos = final.raw_bytes()
        return raw


class ProtoDecoder:
    def __init__(self, schema: Schema, data: bytes):
        self.schema = schema
        self._ist = IStream(data)
        self._first = True
        self._prev_time = 0
        self._prev_delta = 0
        self._states = [_state_for(kind) for _, kind in schema.fields]
        self._current = {
            name: _DEFAULTS[kind] for name, kind in schema.fields
        }

    def __iter__(self):
        while True:
            if self._first:
                self._prev_time = self._ist.read_bits(64)
                if self._prev_time >= 1 << 63:
                    self._prev_time -= 1 << 64
                self._first = False
            if self._ist.read_bit() == 0:
                return
            dod = _unzigzag(_read_varbits(self._ist))
            self._prev_delta += dod
            self._prev_time += self._prev_delta
            changed = [self._ist.read_bit() for _ in self.schema.fields]
            for i, (name, kind) in enumerate(self.schema.fields):
                if not changed[i]:
                    continue
                if kind == FieldKind.BOOL:
                    self._current[name] = bool(self._ist.read_bit())
                else:
                    self._current[name] = self._states[i].decode(self._ist)
            yield self._prev_time, dict(self._current)


def encode_proto_series(schema: Schema, messages, start_nanos: int) -> bytes:
    enc = ProtoEncoder(schema, start_nanos)
    for ts, values in messages:
        enc.encode(ts, values)
    return enc.stream()


def decode_proto_series(schema: Schema, data: bytes) -> list:
    return list(ProtoDecoder(schema, data))
