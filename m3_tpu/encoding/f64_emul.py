"""Exact IEEE-754 float64 semantics as pure integer (uint64) array ops.

TPUs have no float64 ALU, but M3TSZ bit-exactness requires the precise
rounding behavior of the reference's float arithmetic in
``convertToIntFloat`` (``src/dbnode/encoding/m3tsz/m3tsz.go:78-118``):
a single-rounded multiply by 10^k, a chain of single-rounded multiplies
by 10, Modf integer/fraction splits, and Nextafter ulp steps.  This module
implements those operations directly on the float64 *bit patterns* as
jax uint64 ops — deterministic and bit-exact on any backend (CPU test
mesh or TPU, where XLA lowers 64-bit integer ops to 32-bit pairs).

All functions operate elementwise on arrays of uint64 bit patterns.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

MASK52 = (1 << 52) - 1
MASK63 = (1 << 63) - 1
IMPLICIT = 1 << 52
U64 = jnp.uint64
I64 = jnp.int64

POW10_U64 = tuple(10**k for k in range(7))


def _u(x) -> jax.Array:
    return jnp.asarray(x, dtype=U64)


def split(bits):
    """(sign, biased_exponent, fraction) fields."""
    bits = _u(bits)
    sign = bits >> _u(63)
    exp = (bits >> _u(52)) & _u(0x7FF)
    frac = bits & _u(MASK52)
    return sign, exp, frac


def is_nan(bits):
    _, exp, frac = split(bits)
    return (exp == _u(0x7FF)) & (frac != _u(0))


def abs_bits(bits):
    return _u(bits) & _u(MASK63)


def neg_bits(bits):
    return _u(bits) ^ _u(1 << 63)


def msb_index(v):
    """Index of the most significant set bit of a uint64 (v must be > 0)."""
    v = _u(v)
    # lax.clz on uint64
    return _u(63) - jnp.asarray(jax.lax.clz(v.astype(I64)).astype(U64))


def _mantissa_and_exp2(bits):
    """value = mantissa * 2^exp2 exactly, for positive finite bits.

    Normals: mantissa has the implicit bit set (53 bits); subnormals use the
    raw fraction.  Zero yields mantissa 0.
    """
    _, exp, frac = split(bits)
    is_sub = exp == _u(0)
    mant = jnp.where(is_sub, frac, frac | _u(IMPLICIT))
    exp2 = jnp.where(is_sub, jnp.int64(-1074), exp.astype(I64) - jnp.int64(1075))
    return mant, exp2


def _round_shift_right_even(m, k):
    """Round-to-nearest-even right shift of uint64 m by k (0 <= k <= 63)."""
    m = _u(m)
    k = _u(k)
    q = m >> k
    rem = m & ((_u(1) << k) - _u(1))
    half = jnp.where(k > _u(0), _u(1) << (k - _u(1)), _u(0))
    round_up = (rem > half) | ((rem == half) & ((q & _u(1)) == _u(1)))
    return jnp.where(k > _u(0), q + round_up.astype(U64), m)


def _pack(mant, exp2):
    """Pack (mantissa m, exp2) with value = m * 2^exp2 (m < 2^64, m > 0)
    into positive float64 bits with round-to-nearest-even."""
    mant = _u(mant)
    L = msb_index(jnp.maximum(mant, _u(1))).astype(I64)
    # Normalized target: 53-bit mantissa, biased exponent.
    shift = L - jnp.int64(52)
    eb = exp2 + shift + jnp.int64(1075)
    # Subnormal: clamp biased exponent at 0 and shift further right.
    extra = jnp.where(eb < jnp.int64(1), jnp.int64(1) - eb, jnp.int64(0))
    # Avoid shifting everything out (total > 63 -> result 0).
    total_r = jnp.clip(shift + extra, None, jnp.int64(63))
    eb = jnp.where(eb < jnp.int64(1), jnp.int64(0), eb)

    left = jnp.clip(-total_r, jnp.int64(0), jnp.int64(63)).astype(U64)
    right = jnp.clip(total_r, jnp.int64(0), jnp.int64(63)).astype(U64)
    m = jnp.where(total_r >= jnp.int64(0),
                  _round_shift_right_even(mant, right),
                  mant << left)
    # Rounding may carry to 2^53 (normal) -> shift one more.
    carried = m >= _u(1 << 53)
    m = jnp.where(carried, m >> _u(1), m)
    eb = jnp.where(carried, eb + jnp.int64(1), eb)
    # Subnormal carry to 2^52 encodes exp=1 automatically (m == IMPLICIT).
    is_norm = m >= _u(IMPLICIT)
    bits = jnp.where(
        is_norm & (eb >= jnp.int64(1)),
        (eb.astype(U64) << _u(52)) | (m & _u(MASK52)),
        m,  # subnormal (eb forced 0) or the carry-to-normal m == 2^52 case
    )
    return jnp.where(mant == _u(0), _u(0), bits)


def mul10(bits):
    """Exact IEEE float64 multiply by 10.0 of positive finite bits."""
    mant, exp2 = _mantissa_and_exp2(bits)
    return _pack(mant * _u(10), exp2)


def mul_pow10(bits, k):
    """Exact IEEE float64 multiply of positive finite ``bits`` by 10^k, k in [0, 6].

    The 53-bit x 20-bit product can reach 73 bits, so compute it in two
    uint64 halves before rounding.
    """
    mant, exp2 = _mantissa_and_exp2(bits)
    p10 = jnp.asarray(jnp.array(POW10_U64, dtype=U64))[k]
    lo32 = mant & _u(0xFFFFFFFF)
    hi32 = mant >> _u(32)
    p_lo = lo32 * p10
    p_hi = hi32 * p10  # < 2^41; full product = (p_hi << 32) + p_lo
    lo = (p_lo + ((p_hi & _u(0xFFFFFFFF)) << _u(32)))
    carry = jnp.where(lo < p_lo, _u(1), _u(0))
    hi = (p_hi >> _u(32)) + carry  # < 2^9
    # Reduce the 128-bit (hi, lo) product to <= 64 bits with sticky rounding:
    # shift right by s so msb < 64, tracking dropped bits for round-to-even.
    nz_hi = hi != _u(0)
    s = jnp.where(nz_hi, msb_index(jnp.maximum(hi, _u(1))) + _u(1), _u(0))
    # merged = (hi:lo) >> s, plus sticky bit if any dropped bit set
    dropped = jnp.where(s > _u(0), lo & ((_u(1) << s) - _u(1)), _u(0))
    lshift = jnp.where(s > _u(0), _u(64) - s, _u(0))  # avoid shift-by-64
    merged = jnp.where(nz_hi, (lo >> s) | (hi << lshift), lo)
    # Fold sticky dropped bits into the low bit region by ORing a sticky flag:
    # we must preserve "rem vs half" comparisons; since s <= 9 and the final
    # rounding shift in _pack is >= s bits more, it suffices to OR sticky into
    # the lowest bit of merged.
    sticky = (dropped != _u(0)).astype(U64)
    merged = merged | sticky
    return _pack(merged, exp2 + s.astype(I64))


def floor_parts(bits):
    """For positive finite bits: (floor as uint64, frac_is_zero bool).

    Only valid when floor(value) < 2^63.
    """
    _, exp, _ = split(bits)
    mant, _ = _mantissa_and_exp2(bits)
    e = exp.astype(I64) - jnp.int64(1023)  # unbiased exponent
    lt_one = e < jnp.int64(0)
    big = e >= jnp.int64(52)
    shift_r = jnp.clip(jnp.int64(52) - e, jnp.int64(0), jnp.int64(63)).astype(U64)
    shift_l = jnp.clip(e - jnp.int64(52), jnp.int64(0), jnp.int64(63)).astype(U64)
    ipart = jnp.where(lt_one, _u(0), jnp.where(big, mant << shift_l, mant >> shift_r))
    frac_bits = jnp.where(lt_one | big, _u(0), mant & ((_u(1) << shift_r) - _u(1)))
    frac_zero = jnp.where(lt_one, bits == _u(0), frac_bits == _u(0))
    return ipart, frac_zero


def _div_u128_by_small(hi, lo, d):
    """floor((hi·2^64 + lo) / d) and remainder, for d < 2^20 and quotient
    < 2^64: base-2^32 long division, fully vectorized."""
    hi, lo, d = _u(hi), _u(lo), _u(d)
    m32 = _u(0xFFFFFFFF)
    q = _u(0)
    r = _u(0)
    for digit in (hi >> _u(32), hi & m32, lo >> _u(32), lo & m32):
        cur = (r << _u(32)) | digit  # r < d < 2^20 ⇒ cur < 2^52
        qd = cur // d
        r = cur - qd * d
        q = (q << _u(32)) | qd
    return q, r


def int_div_pow10(i, k):
    """Bits of `float64(i) / 10^k` for int64 i and 0 <= k <= 6, matching
    the reference's two-step IEEE computation bit-for-bit — including its
    double rounding for |i| > 2^53.

    The decoder's int-optimization inverse (reference `m3tsz.go:120-131`
    convertFromIntFloat) computes `float64(v) / multiplier`: an RNE
    int→float64 conversion followed by an IEEE division.  TPU's emulated
    f64 divide is not correctly rounded, so both steps run in integer
    arithmetic: the existing exact conversion (`uint_to_f64_bits`), then
    a long division of the 53-bit mantissa by 10^k with guard-bit +
    remainder-as-sticky rounding.
    """
    i = jnp.asarray(i, I64)
    k = jnp.asarray(k, I64)
    sign = (i < 0).astype(U64) << _u(63)
    a = jnp.abs(i).astype(U64)
    d = jnp.asarray(np.array([10**p for p in range(7)], np.uint64))[jnp.clip(k, 0, 6)]

    # Step 1: float64(|i|) with round-to-nearest-even.
    fbits = uint_to_f64_bits(a)
    mant, exp2 = _mantissa_and_exp2(jnp.maximum(fbits, _u(1 << 52)))
    # (|i| >= 1 ⇒ normal; the max() only guards the a == 0 lane.)

    # Step 2: mant·2^exp2 / d.  With mant in [2^52, 2^53) and
    # t = ld + 2, q = floor(mant·2^t/d) lands in (2^53, 2^55).
    ld = msb_index(d).astype(I64)
    t = ld + jnp.int64(2)
    tu = t.astype(U64)  # t in [2, 21]: the 128-bit shift never wraps
    hi = mant >> (_u(64) - tu)
    lo = mant << tu
    q, r = _div_u128_by_small(hi, lo, d)

    # Normalize to exactly 54 bits (53 mantissa + 1 guard).
    over = q >= _u(1 << 54)
    sticky_extra = over & ((q & _u(1)) == _u(1))
    q = jnp.where(over, q >> _u(1), q)
    t = jnp.where(over, t - 1, t)

    guard = (q & _u(1)) == _u(1)
    m = q >> _u(1)  # 53 bits, in [2^52, 2^53)
    sticky = (r != _u(0)) | sticky_extra
    round_up = guard & (sticky | ((m & _u(1)) == _u(1)))
    m = m + round_up.astype(U64)
    carried = m >= _u(1 << 53)
    m = jnp.where(carried, m >> _u(1), m)
    # value = m·2^(exp2 - t + 1); biased exponent encodes m·2^(eb - 1075).
    E = exp2 - t + jnp.int64(1) + carried.astype(I64)
    bits = sign | ((E + jnp.int64(1075)).astype(U64) << _u(52)) | (m & _u(MASK52))
    return jnp.where(a == _u(0), sign, bits)


def uint_to_f64_bits(i):
    """Positive uint64 to float64 bits: exact below 2^53, round-to-
    nearest-even above (the IEEE int→double conversion)."""
    i = _u(i)
    L = msb_index(jnp.maximum(i, _u(1)))
    small = L <= _u(52)
    mant_small = i << jnp.where(small, _u(52) - L, _u(0))
    m = _round_shift_right_even(i, jnp.where(small, _u(0), L - _u(52)))
    carried = m >= _u(1 << 53)
    m = jnp.where(carried, m >> _u(1), m)
    L_big = L + carried.astype(U64)
    mant = jnp.where(small, mant_small, m)
    eb = _u(1023) + jnp.where(small, L, L_big)
    bits = (eb << _u(52)) | (mant & _u(MASK52))
    return jnp.where(i == _u(0), _u(0), bits)
