"""Marker and delta-of-delta time encoding schemes.

Wire-compatible with the reference defaults in
``src/dbnode/encoding/scheme.go:28-62``:

* marker opcode ``0x100`` in 9 bits followed by a 2-bit marker value
  (end-of-stream=0, annotation=1, time-unit=2);
* per-unit delta-of-delta bucket schemes: zero bucket = 1 bit ``0``;
  buckets ``10``+7-bit, ``110``+9-bit, ``1110``+12-bit; default bucket
  ``1111`` + 32 bits (second/millisecond) or 64 bits (micro/nanosecond).

Values in buckets are two's-complement truncated to the value width and
sign-extended on read.
"""

from __future__ import annotations

from dataclasses import dataclass

from m3_tpu.core.xtime import Unit

MARKER_OPCODE = 0x100
NUM_MARKER_OPCODE_BITS = 9
NUM_MARKER_VALUE_BITS = 2

END_OF_STREAM_MARKER = 0
ANNOTATION_MARKER = 1
TIME_UNIT_MARKER = 2


@dataclass(frozen=True)
class TimeBucket:
    opcode: int
    num_opcode_bits: int
    num_value_bits: int

    @property
    def min(self) -> int:
        return -(1 << (self.num_value_bits - 1))

    @property
    def max(self) -> int:
        return (1 << (self.num_value_bits - 1)) - 1


@dataclass(frozen=True)
class TimeEncodingScheme:
    zero_bucket: TimeBucket
    buckets: tuple[TimeBucket, ...]
    default_bucket: TimeBucket


def _make_scheme(bucket_value_bits: list[int], default_value_bits: int) -> TimeEncodingScheme:
    buckets = []
    opcode = 0
    num_opcode_bits = 1
    for i, nbits in enumerate(bucket_value_bits):
        opcode = (1 << (i + 1)) | opcode
        buckets.append(TimeBucket(opcode, num_opcode_bits + 1, nbits))
        num_opcode_bits += 1
    default = TimeBucket(opcode | 0x1, num_opcode_bits, default_value_bits)
    return TimeEncodingScheme(TimeBucket(0x0, 1, 0), tuple(buckets), default)


_DEFAULT_BUCKET_BITS = [7, 9, 12]

DEFAULT_TIME_ENCODING_SCHEMES: dict[Unit, TimeEncodingScheme] = {
    Unit.SECOND: _make_scheme(_DEFAULT_BUCKET_BITS, 32),
    Unit.MILLISECOND: _make_scheme(_DEFAULT_BUCKET_BITS, 32),
    Unit.MICROSECOND: _make_scheme(_DEFAULT_BUCKET_BITS, 64),
    Unit.NANOSECOND: _make_scheme(_DEFAULT_BUCKET_BITS, 64),
}


def scheme_for_unit(unit: Unit) -> TimeEncodingScheme | None:
    return DEFAULT_TIME_ENCODING_SCHEMES.get(unit)


def sign_extend(v: int, num_bits: int) -> int:
    sign_bit = 1 << (num_bits - 1)
    return (v ^ sign_bit) - sign_bit


def write_special_marker(os, marker: int) -> None:
    os.write_bits(MARKER_OPCODE, NUM_MARKER_OPCODE_BITS)
    os.write_bits(marker, NUM_MARKER_VALUE_BITS)


def tail_bytes(last_byte: int, pos: int) -> bytes:
    """The end-of-stream tail: the used bits of the last byte followed by the
    end-of-stream marker, zero padded to a byte boundary.

    Mirrors the precomputed tails in ``scheme.go:198-212``.
    """
    from m3_tpu.encoding.bitstream import OStream

    tmp = OStream()
    tmp.write_bits(last_byte >> (8 - pos), pos)
    write_special_marker(tmp, END_OF_STREAM_MARKER)
    return tmp.bytes_aligned()
