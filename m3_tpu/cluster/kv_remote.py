"""External coordination binding: the KV control plane as a service.

Equivalent of the reference's etcd layer (`src/cluster/kv` over
`src/cluster/client/etcd/client.go`): placements, namespaces, topics,
rules and elections live in a store that SURVIVES the nodes — every
node process dials it instead of owning a file-backed copy.  etcd
itself collapses to the framework's own framed-TCP service around the
existing ``KVStore`` (versioned values, CAS, watches):

* ``KVServer`` — hosts one authoritative ``KVStore`` (file-backed for
  durability) behind the msg/protocol framing.
* ``RemoteKVStore`` — implements the exact ``KVStore`` method surface
  (get/set/set_if_not_exists/check_and_set/delete/keys/watch) over a
  connection, so ``PlacementService``, ``NamespaceRegistry``,
  ``TopicService``, ``RuntimeOptionsManager`` and ``LeaderElection``
  work unchanged against the remote plane — CAS conflicts raise the
  same ValueError/KeyError the local store raises.
* watches poll on a short interval over a dedicated connection (the
  reference's etcd watch channels; polling keeps the protocol
  request/response only).

Cross-process leader election follows for free: ``LeaderElection``'s
TTL-lease CAS runs against the shared remote store, so aggregator
leader/follower pairs in different processes elect exactly one emitter.
"""

from __future__ import annotations

import socket
import socketserver
import struct
import threading
from typing import Callable, Tuple

from m3_tpu.cluster.kv import KVStore, VersionedValue
from m3_tpu.msg.protocol import (
    ProtocolError, connect as wire_connect, recv_frame, send_frame,
)
from m3_tpu.x import fault
from m3_tpu.x.retry import Retrier, RetryOptions

KV_REQ = 24
KV_OK = 25
KV_ERR = 26

M_GET = 1
M_SET = 2
M_SET_NX = 3
M_CAS = 4
M_DELETE = 5
M_KEYS = 6


def _pack(b: bytes) -> bytes:
    return struct.pack("<I", len(b)) + b


def _unpack(raw: bytes, pos: int):
    (n,) = struct.unpack_from("<I", raw, pos)
    return raw[pos + 4 : pos + 4 + n], pos + 4 + n


class _KVHandler(socketserver.BaseRequestHandler):
    def handle(self):
        srv: KVServer = self.server
        sock = self.request
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        while True:
            try:
                frame = recv_frame(sock)
            except (ProtocolError, OSError):
                return
            if frame is None or frame[0] != KV_REQ:
                return
            payload = frame[1]
            try:
                if not payload:
                    raise ProtocolError("empty kv request")
                resp = self._dispatch(srv.store, payload[0], payload[1:])
                send_frame(sock, KV_OK, resp)
            except Exception as e:  # typed error frame, conn survives
                try:
                    send_frame(
                        sock, KV_ERR,
                        f"{type(e).__name__}\x00{e}".encode()[:4096])
                except OSError:
                    return

    def _dispatch(self, store: KVStore, method: int, raw: bytes) -> bytes:
        if method == M_GET:
            key, _ = _unpack(raw, 0)
            v = store.get(key.decode())
            if v is None:
                return b"\x00"
            return b"\x01" + struct.pack("<q", v.version) + v.data
        if method == M_SET:
            key, pos = _unpack(raw, 0)
            data, _ = _unpack(raw, pos)
            return struct.pack("<q", store.set(key.decode(), data))
        if method == M_SET_NX:
            key, pos = _unpack(raw, 0)
            data, _ = _unpack(raw, pos)
            return struct.pack("<q", store.set_if_not_exists(key.decode(), data))
        if method == M_CAS:
            key, pos = _unpack(raw, 0)
            (expect,) = struct.unpack_from("<q", raw, pos)
            data, _ = _unpack(raw, pos + 8)
            return struct.pack(
                "<q", store.check_and_set(key.decode(), expect, data))
        if method == M_DELETE:
            key, _ = _unpack(raw, 0)
            return b"\x01" if store.delete(key.decode()) else b"\x00"
        if method == M_KEYS:
            keys = store.keys()
            return struct.pack("<I", len(keys)) + b"".join(
                _pack(k.encode()) for k in keys)
        raise ProtocolError(f"unknown kv method {method}")


class KVServer(socketserver.ThreadingTCPServer):
    allow_reuse_address = True
    daemon_threads = True

    def __init__(self, store: KVStore | None = None, root: str | None = None,
                 host: str = "127.0.0.1", port: int = 0):
        self.store = store if store is not None else KVStore(root)
        super().__init__((host, port), _KVHandler)

    @property
    def port(self) -> int:
        return self.server_address[1]


def serve_kv_background(root: str | None = None, host: str = "127.0.0.1",
                        port: int = 0, store: KVStore | None = None) -> KVServer:
    srv = KVServer(store=store, root=root, host=host, port=port)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    return srv


class RemoteKVStore:
    """KVStore-shaped client over one connection (+ one watch poller).

    Errors raised by the authoritative store come back typed: CAS
    conflicts re-raise as ValueError, set_if_not_exists duplicates as
    KeyError — identical to the local store so callers (elections,
    placement CAS loops) are transport-agnostic."""

    _RERAISE = {"ValueError": ValueError, "KeyError": KeyError}

    def __init__(self, address: Tuple[str, int], timeout_s: float = 30.0,
                 watch_poll_s: float = 2.0,
                 retry_options: RetryOptions | None = None):
        # watch_poll_s: control-plane objects change rarely; every
        # watched key costs one round-trip per tick, so the default
        # favors low idle load (tests pass a small value).
        self.address = tuple(address)
        self.timeout_s = timeout_s
        # Every control-plane call retries transport failures (x/retry
        # adoption): a flapping KV server heals inside one call instead
        # of surfacing ConnectionError to every placement/election
        # caller.  Application errors (CAS ValueError etc.) never retry.
        self.retrier = Retrier(
            retry_options or RetryOptions(
                initial_backoff_s=0.05, max_backoff_s=2.0, max_attempts=4),
            name="kv_remote",
            # Interruptible backoff: close() wakes every sleeper.
            sleep=lambda s: self._closed.wait(s),
        )
        self._sock: socket.socket | None = None
        self._mu = threading.Lock()       # connection
        self._wmu = threading.Lock()      # watcher registry
        self._watch_poll_s = watch_poll_s
        self._watchers: dict[str, list[Callable]] = {}
        self._watch_seen: dict[str, int] = {}
        # Watchers owed a re-delivery: registration raced the poll loop,
        # the reconciling re-read failed, and the watcher was fired with
        # a value older than _watch_seen — the poll loop re-delivers the
        # current value to these on its next tick even when the version
        # has not advanced past seen.
        self._watch_pending: dict[str, set] = {}
        self._watch_thread: threading.Thread | None = None
        self._closed = threading.Event()

    def _call(self, method: int, body: bytes) -> bytes:
        # abort: a deliberately closed client must not wait out the
        # backoff schedule against a server that is gone on purpose.
        return self.retrier.run(
            lambda: self._call_once(method, body),
            abort=self._closed.is_set)

    def _drop_sock(self) -> None:
        # Caller holds self._mu (rpc.py's RemoteDatabase._drop shape).
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
            self._sock = None  # m3lint: disable=lock-discipline

    def _call_once(self, method: int, body: bytes) -> bytes:
        if self._closed.is_set():
            raise ConnectionError(f"kv {self.address}: store closed")
        with self._mu:
            try:
                # Socket-boundary faultpoint: drop (request lost on the
                # wire) and error both surface as the transport failure
                # the retrier exists for; delay models a slow peer.
                if fault.fire("kv_remote.call") == "drop":
                    raise fault.FaultInjected(
                        "kv_remote.call: request dropped")
                if self._sock is None:
                    self._sock = wire_connect(
                        self.address, timeout=self.timeout_s)
                send_frame(self._sock, KV_REQ, bytes([method]) + body)
                frame = recv_frame(self._sock)
            except (OSError, ProtocolError) as e:
                self._drop_sock()
                raise ConnectionError(f"kv {self.address}: {e}") from e
        if frame is None:
            raise ConnectionError(f"kv {self.address}: closed")
        ftype, payload = frame
        if ftype == KV_ERR:
            tname, _, msg = payload.decode(errors="replace").partition("\x00")
            raise self._RERAISE.get(tname, RuntimeError)(msg)
        if ftype != KV_OK:
            # Protocol confusion: the reply stream is desynced — drop
            # the connection rather than treating an arbitrary frame as
            # a success payload (m3lint wire-exhaustive).
            with self._mu:
                self._drop_sock()
            raise ConnectionError(f"kv {self.address}: bad frame {ftype}")
        return payload

    # -- KVStore surface --

    @staticmethod
    def _parse_get(raw: bytes) -> VersionedValue | None:
        if raw[0] == 0:
            return None
        (version,) = struct.unpack_from("<q", raw, 1)
        return VersionedValue(version, raw[9:])

    def get(self, key: str) -> VersionedValue | None:
        return self._parse_get(self._call(M_GET, _pack(key.encode())))

    def _get_once(self, key: str) -> VersionedValue | None:
        """Single-attempt get for the watch poll loop: the loop has its
        OWN backoff-between-rounds schedule, so running the full
        in-call retry ladder per key would multiply a dead server's
        stall time by max_attempts for every watched key."""
        return self._parse_get(self._call_once(M_GET, _pack(key.encode())))

    def set(self, key: str, data: bytes) -> int:
        raw = self._call(M_SET, _pack(key.encode()) + _pack(data))
        return struct.unpack("<q", raw)[0]

    def set_if_not_exists(self, key: str, data: bytes) -> int:
        raw = self._call(M_SET_NX, _pack(key.encode()) + _pack(data))
        return struct.unpack("<q", raw)[0]

    def check_and_set(self, key: str, expect_version: int, data: bytes) -> int:
        raw = self._call(
            M_CAS,
            _pack(key.encode()) + struct.pack("<q", expect_version) + _pack(data),
        )
        return struct.unpack("<q", raw)[0]

    def delete(self, key: str) -> bool:
        return self._call(M_DELETE, _pack(key.encode())) == b"\x01"

    def keys(self) -> list:
        raw = self._call(M_KEYS, b"")
        (n,) = struct.unpack_from("<I", raw, 0)
        pos = 4
        out = []
        for _ in range(n):
            k, pos = _unpack(raw, pos)
            out.append(k.decode())
        return out

    def watch(self, key: str, fn: Callable[[VersionedValue], None]) -> None:
        """Fire on every observed version change (etcd watch channel
        role, implemented as a version poll).

        ``_watch_seen`` updates and the initial-fire decision happen
        under ``_wmu`` so registration and the poll loop agree on the
        last-seen version: without it a poll tick racing a registration
        could double-fire or swallow one version change.  Callbacks fire
        outside the lock (they may re-enter the store)."""
        cur = self.get(key)
        with self._wmu:
            self._watchers.setdefault(key, []).append(fn)
            start = self._watch_thread is None
            if start:
                self._watch_thread = threading.Thread(
                    target=self._watch_loop, daemon=True)
            fire = self._decide_locked(key, fn, cur)
        if fire is None:
            # Pre-lock read lost a race with the poll loop (cur older
            # than what it delivered).  Re-read OUTSIDE the lock —
            # network I/O under _wmu would stall every watcher — then
            # reconcile; if the re-read fails too, deliver what we have
            # rather than nothing.
            try:
                cur = self.get(key) or cur
            except (ConnectionError, RuntimeError):
                pass
            with self._wmu:
                fire = self._decide_locked(key, fn, cur)
                if fire is None:
                    # Still stale after the re-read (or the re-read
                    # failed): mark this watcher pending so the next
                    # poll tick delivers the current value instead of
                    # waiting for the key to change again.  Do NOT fire
                    # the stale value here — an unlocked stale fire can
                    # race the poll tick's re-delivery and land AFTER
                    # it, regressing the watcher's view until the next
                    # version change.
                    self._watch_pending.setdefault(key, set()).add(fn)
                    fire = []
        for f in fire:
            self._fire(f, cur)
        if start:
            self._watch_thread.start()

    def _decide_locked(self, key, fn, cur):
        """Under _wmu: advance _watch_seen for ``cur`` and return the
        callbacks to fire.  When ``cur`` moved past the loop's last
        delivery — including the key-creation case where watchers
        registered while the key was absent — EVERY watcher fires, or
        the poll loop (which compares against the now-advanced seen)
        would swallow that change for the others.  Returns None when
        ``cur`` is older than seen: the caller re-reads outside the
        lock (versions are monotonic per key)."""
        if cur is None:
            return []
        seen = self._watch_seen.get(key)
        if seen is None or cur.version > seen:
            self._watch_seen[key] = cur.version
            # Every watcher (including any parked pending) receives
            # this delivery — clear the owed re-deliveries or the next
            # poll tick would double-fire them with the same version.
            self._watch_pending.pop(key, None)
            return list(self._watchers[key])
        if cur.version == seen:
            pend = self._watch_pending.get(key)
            if pend is not None:
                pend.discard(fn)
                if not pend:
                    del self._watch_pending[key]
            return [fn]  # initial fire for the new watcher only
        return None

    @staticmethod
    def _fire(fn, cur) -> None:
        """Deliver one watch callback; a raising callback must never
        kill the shared poll thread or starve its sibling watchers."""
        try:
            fn(cur)
        except Exception:  # noqa: BLE001 — isolate watcher faults
            import logging

            logging.getLogger("m3_tpu.cluster.kv_remote").exception(
                "kv watch callback raised")

    def _watch_loop(self) -> None:
        # Reconnect loop with backoff: a dead KV server must not be
        # hammered at the poll cadence forever — consecutive failed
        # rounds stretch the wait along the retrier's schedule (capped
        # at its max backoff), and one healthy round snaps it back.
        failed_rounds = 0
        while True:
            wait_s = self._watch_poll_s
            if failed_rounds:
                wait_s = max(wait_s, self.retrier.backoff_for(failed_rounds))
            if self._closed.wait(wait_s):
                return
            round_failed = False
            with self._wmu:
                keys = list(self._watchers)
            for key in keys:
                try:
                    cur = self._get_once(key)
                except (ConnectionError, RuntimeError):
                    round_failed = True
                    continue
                if cur is None:
                    continue
                with self._wmu:
                    changed = cur.version != self._watch_seen.get(key)
                    if changed:
                        self._watch_seen[key] = cur.version
                        fns = list(self._watchers.get(key, ()))
                        # A full delivery covers any owed re-delivery.
                        self._watch_pending.pop(key, None)
                    else:
                        pend = self._watch_pending.pop(key, None)
                        live = self._watchers.get(key, ())
                        fns = [f for f in pend if f in live] if pend else []
                for fn in fns:
                    self._fire(fn, cur)
            failed_rounds = failed_rounds + 1 if round_failed else 0

    def close(self) -> None:
        self._closed.set()
        with self._mu:
            self._drop_sock()
