"""Dynamic namespaces: KV-watched namespace metadata.

Equivalent of the reference's dynamic namespace registry
(`src/dbnode/namespace/dynamic.go` — namespaces live in KV; dbnode
watches and adds/readies them without restart; the coordinator's
database-create admin API writes them).  A NamespaceRegistry owns the
KV document, attach() wires a live Database so new namespaces
materialize as they are registered.
"""

from __future__ import annotations

import json
from dataclasses import asdict, dataclass

from m3_tpu.cluster.kv import KVStore

KEY = "namespaces"


@dataclass(frozen=True)
class NamespaceMeta:
    """The KV form (namespace/options.go essentials)."""

    name: str
    retention_nanos: int = 48 * 3600 * 10**9
    block_size_nanos: int = 2 * 3600 * 10**9
    buffer_past_nanos: int = 10 * 60 * 10**9
    buffer_future_nanos: int = 2 * 60 * 10**9
    cold_writes_enabled: bool = True
    num_shards: int = 4


def _encode(metas: dict[str, NamespaceMeta]) -> bytes:
    return json.dumps({n: asdict(m) for n, m in sorted(metas.items())}).encode()


def _decode(raw: bytes) -> dict[str, NamespaceMeta]:
    return {n: NamespaceMeta(**d) for n, d in json.loads(raw).items()}


class NamespaceRegistry:
    def __init__(self, kv: KVStore):
        self.kv = kv
        self._dbs: list = []

    # -- CRUD (the coordinator admin API's storage) ------------------------

    def all(self) -> dict[str, NamespaceMeta]:
        vv = self.kv.get(KEY)
        return _decode(vv.data) if vv else {}

    def _cas_update(self, mutate) -> bool:
        """CAS-loop read-modify-write: concurrent admin requests must
        not lose each other's namespaces (PlacementService.set pattern)."""
        for _ in range(16):
            vv = self.kv.get(KEY)
            metas = _decode(vv.data) if vv else {}
            out = mutate(metas)
            if out is None:
                return False  # mutate declined (no-op)
            try:
                self.kv.check_and_set(KEY, vv.version if vv else 0,
                                      _encode(out))
                return True
            except ValueError:
                continue  # raced another writer; retry on fresh state
        raise RuntimeError("namespace registry CAS contention")

    def add(self, meta: NamespaceMeta) -> None:
        def mutate(metas):
            if meta.name in metas:
                raise ValueError(f"namespace {meta.name} exists")
            metas[meta.name] = meta
            return metas
        self._cas_update(mutate)

    def remove(self, name: str) -> bool:
        def mutate(metas):
            if name not in metas:
                return None
            del metas[name]
            return metas
        return self._cas_update(mutate)

    # -- dynamic attach (dbnode namespace watch) ---------------------------

    def attach(self, db) -> None:
        """Materialize current + future namespaces on a live Database
        (dynamic.go's watch loop).  Removal does NOT drop data — the
        reference also keeps data until cleanup policies apply."""
        self._dbs.append(db)
        self.kv.watch(KEY, lambda vv: self._sync(vv))
        vv = self.kv.get(KEY)
        if vv is not None:
            self._sync(vv)

    def _sync(self, vv) -> None:
        from m3_tpu.storage.database import NamespaceOptions

        try:
            metas = _decode(vv.data)
        except (ValueError, TypeError):
            return
        for db in self._dbs:
            for name, m in metas.items():
                if name not in db.namespaces:
                    db.ensure_namespace(name, NamespaceOptions(
                        block_size_nanos=m.block_size_nanos,
                        retention_nanos=m.retention_nanos,
                        buffer_past_nanos=m.buffer_past_nanos,
                        buffer_future_nanos=m.buffer_future_nanos,
                        cold_writes_enabled=m.cold_writes_enabled,
                        num_shards=m.num_shards,
                    ))
