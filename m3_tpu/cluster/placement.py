"""Placements: shard ↔ instance assignment with staged shard states.

Reference parity: `src/cluster/placement` — instances carrying shards in
Initializing/Available/Leaving states, the sharded add/remove/replace
algorithm (`algo/sharded.go:39,93-148`), isolation-group-aware balancing,
and versioned storage in KV (`placement/storage`).  The TPU mapping: a
placement names which host (and which mesh slice) owns each logical
shard; shard movement = staged handoff (Initializing streams from the
Leaving source, then both flip) exactly as dbnode does topology changes.
"""

from __future__ import annotations

import enum
import json
from dataclasses import dataclass, field, replace

from m3_tpu.cluster.kv import KVStore


class ShardState(enum.Enum):
    INITIALIZING = "I"
    AVAILABLE = "A"
    LEAVING = "L"


@dataclass(frozen=True)
class ShardAssignment:
    shard: int
    state: ShardState
    source_id: str | None = None  # Initializing: who streams the data


@dataclass
class Instance:
    id: str
    isolation_group: str = ""
    weight: int = 1
    shards: dict = field(default_factory=dict)  # shard id -> ShardAssignment
    shard_set_id: int = 0  # mirrored placements: same set id => same shards
    # Data-plane RPC address ("host:port") other nodes dial to stream
    # this instance's blocks (the reference placement instance's
    # endpoint field); empty when unknown (in-process tests resolve by
    # id instead).
    endpoint: str = ""

    def owned(self) -> list[int]:
        return sorted(self.shards)

    def available(self) -> list[int]:
        return sorted(
            s for s, a in self.shards.items() if a.state == ShardState.AVAILABLE
        )


@dataclass
class Placement:
    instances: dict  # id -> Instance
    num_shards: int
    replica_factor: int
    version: int = 0
    is_mirrored: bool = False

    # -- queries -----------------------------------------------------------

    def instances_for_shard(self, shard: int) -> list[Instance]:
        return [
            inst for inst in self.instances.values() if shard in inst.shards
        ]

    def validate(self) -> None:
        """Every shard has exactly RF non-Leaving owners; Initializing
        shards name a Leaving source (reference placement.Validate)."""
        for s in range(self.num_shards):
            owners = [
                i for i in self.instances.values()
                if s in i.shards and i.shards[s].state != ShardState.LEAVING
            ]
            if len(owners) != self.replica_factor:
                raise ValueError(
                    f"shard {s} has {len(owners)} owners, want {self.replica_factor}"
                )

    # -- serialization -----------------------------------------------------

    def to_json(self) -> bytes:
        return json.dumps({
            "num_shards": self.num_shards,
            "replica_factor": self.replica_factor,
            "version": self.version,
            "is_mirrored": self.is_mirrored,
            "instances": {
                iid: {
                    "isolation_group": inst.isolation_group,
                    "weight": inst.weight,
                    "shard_set_id": inst.shard_set_id,
                    "endpoint": inst.endpoint,
                    "shards": {
                        str(s): [a.state.value, a.source_id]
                        for s, a in inst.shards.items()
                    },
                }
                for iid, inst in self.instances.items()
            },
        }).encode()

    @classmethod
    def from_json(cls, raw: bytes) -> "Placement":
        d = json.loads(raw)
        insts = {}
        for iid, idata in d["instances"].items():
            shards = {
                int(s): ShardAssignment(int(s), ShardState(v[0]), v[1])
                for s, v in idata["shards"].items()
            }
            insts[iid] = Instance(iid, idata["isolation_group"],
                                  idata["weight"], shards,
                                  idata.get("shard_set_id", 0),
                                  idata.get("endpoint", ""))
        return cls(insts, d["num_shards"], d["replica_factor"], d["version"],
                   d.get("is_mirrored", False))


def _copy_instances(p: Placement) -> dict:
    """Deep-enough copy for the staged mutation algorithms: fresh
    Instance objects with fresh shard dicts, every identity field
    (isolation group, weight, shard set, endpoint) preserved."""
    return {
        iid: Instance(i.id, i.isolation_group, i.weight, dict(i.shards),
                      i.shard_set_id, i.endpoint)
        for iid, i in p.instances.items()
    }


def _least_loaded(instances: list[Instance], shard: int,
                  taken_groups: set[str]) -> Instance:
    """Pick the least-loaded candidate, preferring new isolation groups
    (the reference's isolation-group constraint, algo/sharded.go)."""
    def key(inst: Instance):
        return (
            inst.isolation_group in taken_groups,
            len(inst.shards) / max(inst.weight, 1),
            inst.id,
        )
    candidates = [i for i in instances if shard not in i.shards]
    if not candidates:
        raise ValueError(f"no candidate instance for shard {shard}")
    return min(candidates, key=key)


def initial_placement(instances: list[Instance], num_shards: int,
                      rf: int) -> Placement:
    """reference algo/sharded.go InitialPlacement: spread each shard's RF
    replicas across isolation groups onto the least-loaded instances."""
    insts = {i.id: Instance(i.id, i.isolation_group, i.weight, {},
                            i.shard_set_id, i.endpoint) for i in instances}
    for s in range(num_shards):
        groups: set[str] = set()
        for _ in range(rf):
            inst = _least_loaded(list(insts.values()), s, groups)
            inst.shards[s] = ShardAssignment(s, ShardState.AVAILABLE)
            groups.add(inst.isolation_group)
    p = Placement(insts, num_shards, rf, version=1)
    p.validate()
    return p


def add_instance(p: Placement, new: Instance) -> Placement:
    """reference algo/sharded.go AddInstance: steal shards from the most
    loaded instances; stolen shards go Initializing on the new instance
    with the donor as source (donor keeps serving until cutover)."""
    insts = _copy_instances(p)
    newcomer = Instance(new.id, new.isolation_group, new.weight, {},
                        new.shard_set_id, new.endpoint)
    insts[new.id] = newcomer
    target = p.num_shards * p.replica_factor // len(insts)
    while len(newcomer.shards) < target:
        donor = max(
            (i for i in insts.values() if i.id != new.id),
            key=lambda i: len([a for a in i.shards.values()
                               if a.state == ShardState.AVAILABLE]),
        )
        movable = [s for s, a in donor.shards.items()
                   if a.state == ShardState.AVAILABLE and s not in newcomer.shards]
        if not movable:
            break
        s = movable[0]
        donor.shards[s] = ShardAssignment(s, ShardState.LEAVING)
        newcomer.shards[s] = ShardAssignment(s, ShardState.INITIALIZING, donor.id)
    return Placement(insts, p.num_shards, p.replica_factor, p.version + 1,
                     p.is_mirrored)


def remove_instance(p: Placement, instance_id: str) -> Placement:
    """reference algo/sharded.go RemoveInstance: the leaver's shards go
    Initializing on the least-loaded survivors."""
    insts = _copy_instances(p)
    leaver = insts[instance_id]
    for s in list(leaver.shards):
        a = leaver.shards[s]
        leaver.shards[s] = ShardAssignment(s, ShardState.LEAVING, None)
        groups = {i.isolation_group for i in insts.values()
                  if s in i.shards and i.id != instance_id}
        dest = _least_loaded(
            [i for i in insts.values() if i.id != instance_id], s, groups
        )
        dest.shards[s] = ShardAssignment(s, ShardState.INITIALIZING, instance_id)
    return Placement(insts, p.num_shards, p.replica_factor, p.version + 1,
                     p.is_mirrored)


def replace_instance(p: Placement, leaving_id: str, new: Instance) -> Placement:
    """reference algo/sharded.go ReplaceInstances: the replacement takes
    exactly the leaver's shards."""
    insts = _copy_instances(p)
    leaver = insts[leaving_id]
    newcomer = Instance(new.id, new.isolation_group, new.weight, {},
                        new.shard_set_id, new.endpoint)
    insts[new.id] = newcomer
    for s, a in list(leaver.shards.items()):
        leaver.shards[s] = ShardAssignment(s, ShardState.LEAVING)
        newcomer.shards[s] = ShardAssignment(s, ShardState.INITIALIZING, leaving_id)
    return Placement(insts, p.num_shards, p.replica_factor, p.version + 1,
                     p.is_mirrored)


def mark_available(p: Placement, instance_id: str, shard: int) -> Placement:
    """Cutover: Initializing→Available on the target, and the matching
    Leaving shard disappears from its source (reference
    MarkShardsAvailable)."""
    insts = _copy_instances(p)
    inst = insts[instance_id]
    a = inst.shards.get(shard)
    if a is None or a.state != ShardState.INITIALIZING:
        raise ValueError(f"shard {shard} not initializing on {instance_id}")
    inst.shards[shard] = ShardAssignment(shard, ShardState.AVAILABLE)
    if a.source_id and a.source_id in insts:
        src = insts[a.source_id]
        if shard in src.shards and src.shards[shard].state == ShardState.LEAVING:
            del src.shards[shard]
    return Placement(insts, p.num_shards, p.replica_factor, p.version + 1,
                     p.is_mirrored)


def forget_instance(p: Placement, instance_id: str) -> Placement:
    """Drop an instance's entry outright (no staged handoff) — the
    operator's final delete of a drained/dead instance whose shards are
    all gone (or a dead leaver whose shards already re-initialized
    elsewhere via remove_instance).  Refuses while the instance still
    carries non-Leaving shards: those owners must be moved first."""
    inst = p.instances.get(instance_id)
    if inst is None:
        raise KeyError(f"no instance {instance_id} in placement")
    live = [s for s, a in inst.shards.items()
            if a.state != ShardState.LEAVING]
    if live:
        raise ValueError(
            f"instance {instance_id} still owns shards {sorted(live)}; "
            "remove_instance/replace_instance first"
        )
    insts = _copy_instances(p)
    del insts[instance_id]
    return Placement(insts, p.num_shards, p.replica_factor, p.version + 1,
                     p.is_mirrored)


class PlacementService:
    """Versioned placement storage over KV (reference
    placement/service + placement/storage).

    Every mutation of the placement key MUST go through this class (the
    m3lint ``placement-cas`` rule gates it): ``update()`` is the
    get→mutate→CAS loop with bounded retry on version conflicts, so two
    concurrent admin mutations (or an admin mutation racing a node's
    ``mark_available`` cutover) serialize instead of one 500ing."""

    #: bounded CAS retries: placement churn is operator-paced, so a
    #: handful of re-reads always wins unless something is spinning.
    CAS_ATTEMPTS = 5

    def __init__(self, kv: KVStore, key: str = "placement"):
        self.kv = kv
        self.key = key

    def get(self) -> Placement | None:
        v = self.kv.get(self.key)
        return Placement.from_json(v.data) if v else None

    def set(self, p: Placement) -> None:
        cur = self.kv.get(self.key)
        self.kv.check_and_set(self.key, cur.version if cur else 0, p.to_json())

    def update(self, mutate, max_attempts: int | None = None) -> Placement:
        """Apply ``mutate(placement | None) -> Placement`` atomically:
        re-read + re-mutate + CAS, retrying (bounded) when another
        writer moved the version between our get and our set.  Only the
        CAS conflict retries — errors raised by ``mutate`` itself
        (validation, unknown instance...) surface immediately."""
        attempts = self.CAS_ATTEMPTS if max_attempts is None else max_attempts
        last: Exception | None = None
        for _ in range(max(1, attempts)):
            cur = self.kv.get(self.key)
            p2 = mutate(Placement.from_json(cur.data) if cur else None)
            try:
                self.kv.check_and_set(
                    self.key, cur.version if cur else 0, p2.to_json())
                return p2
            except ValueError as e:
                if "version conflict" not in str(e):
                    raise
                last = e  # another writer won: re-read and re-apply
        raise last
