"""Mirrored placements: replica groups that share identical shard sets.

Reference parity: `src/cluster/placement/algo/mirrored.go` — the
aggregator's HA placement.  Instances carry a ``shard_set_id``; every
instance in a shard set holds EXACTLY the same shards (they mirror each
other), so leader/follower pairs see identical traffic and a follower
can take over flushing without any shard movement
(`aggregator/aggregator/election_mgr.go` elects within the pair).

The algorithm treats each shard set as one logical node of weight =
group weight and runs the sharded balancing over groups:

* ``mirrored_initial_placement`` — groups of exactly RF instances
  (distinct isolation groups within a set preferred by construction:
  the caller builds the sets), each shard assigned to one group.
* ``mirrored_add_group`` / ``mirrored_remove_group`` — whole groups
  join/leave; shards move group-to-group with per-member source pairing
  (member k of the new set streams from member k of the donor set).
* ``mirrored_replace_instance`` — a new instance takes over a dead
  member's slot in its shard set, streaming from the SURVIVING mirror
  (not the leaver — that is the point of mirroring).

All functions return new Placement objects with version+1 and
``is_mirrored=True``; ``validate_mirrored`` checks the mirror invariant
on top of the base RF validation.
"""

from __future__ import annotations

from collections import defaultdict

from m3_tpu.cluster.placement import (
    Instance,
    Placement,
    ShardAssignment,
    ShardState,
)


def _groups(p_or_insts) -> dict[int, list[Instance]]:
    insts = (p_or_insts.instances.values()
             if isinstance(p_or_insts, Placement) else p_or_insts)
    out: dict[int, list[Instance]] = defaultdict(list)
    for i in insts:
        out[i.shard_set_id].append(i)
    for members in out.values():
        members.sort(key=lambda i: i.id)
    return dict(out)


def validate_mirrored(p: Placement) -> None:
    """Base validation + the mirror invariant: every shard set has
    exactly RF members with identical shard assignments (states may
    differ only in source pairing during migration)."""
    p.validate()
    for ssid, members in _groups(p).items():
        if len(members) != p.replica_factor:
            raise ValueError(
                f"shard set {ssid} has {len(members)} members, "
                f"want RF={p.replica_factor}"
            )
        shard_sets = {frozenset(m.shards) for m in members}
        if len(shard_sets) != 1:
            raise ValueError(f"shard set {ssid} members own differing shards")
        for s in members[0].shards:
            states = {m.shards[s].state for m in members}
            if len(states) != 1:
                raise ValueError(
                    f"shard set {ssid} shard {s} states differ: {states}"
                )


def _group_load(members: list[Instance]) -> float:
    w = sum(max(m.weight, 1) for m in members) / len(members)
    return len(members[0].shards) / w


def mirrored_initial_placement(instances: list[Instance], num_shards: int,
                               rf: int) -> Placement:
    """Each shard lands on exactly one shard set (whose RF members all
    carry it), balanced by group load (algo/mirrored.go InitialPlacement
    via the grouped sharded algorithm)."""
    groups = _groups([
        Instance(i.id, i.isolation_group, i.weight, {}, i.shard_set_id,
                 i.endpoint)
        for i in instances
    ])
    if not groups:
        raise ValueError("no instances")
    for ssid, members in groups.items():
        if len(members) != rf:
            raise ValueError(
                f"shard set {ssid} has {len(members)} instances, want RF={rf}"
            )
    for s in range(num_shards):
        members = min(groups.values(), key=lambda g: (_group_load(g), g[0].id))
        for m in members:
            m.shards[s] = ShardAssignment(s, ShardState.AVAILABLE)
    insts = {m.id: m for members in groups.values() for m in members}
    p = Placement(insts, num_shards, rf, version=1, is_mirrored=True)
    validate_mirrored(p)
    return p


def _copy(p: Placement) -> dict[str, Instance]:
    return {
        iid: Instance(i.id, i.isolation_group, i.weight, dict(i.shards),
                      i.shard_set_id, i.endpoint)
        for iid, i in p.instances.items()
    }


def mirrored_add_group(p: Placement, new_members: list[Instance]) -> Placement:
    """A whole new shard set joins; it steals shards group-to-group from
    the most loaded sets.  Member k of the new set initializes from
    member k of the donor set (deterministic mirror pairing)."""
    if len(new_members) != p.replica_factor:
        raise ValueError(
            f"need RF={p.replica_factor} instances, got {len(new_members)}"
        )
    ssids = {i.shard_set_id for i in new_members}
    if len(ssids) != 1:
        raise ValueError("new members must share one shard_set_id")
    ssid = ssids.pop()
    insts = _copy(p)
    if ssid in {i.shard_set_id for i in insts.values()}:
        raise ValueError(f"shard set {ssid} already present")
    newcomers = [
        Instance(i.id, i.isolation_group, i.weight, {}, ssid, i.endpoint)
        for i in sorted(new_members, key=lambda i: i.id)
    ]
    for m in newcomers:
        insts[m.id] = m
    groups = _groups(insts.values())
    target = p.num_shards // len(groups)
    while len(newcomers[0].shards) < target:
        donors = max(
            (g for sid, g in groups.items() if sid != ssid),
            key=lambda g: len([a for a in g[0].shards.values()
                               if a.state == ShardState.AVAILABLE]),
        )
        movable = [s for s, a in donors[0].shards.items()
                   if a.state == ShardState.AVAILABLE
                   and s not in newcomers[0].shards]
        if not movable:
            break
        s = movable[0]
        for donor, taker in zip(donors, newcomers):
            donor.shards[s] = ShardAssignment(s, ShardState.LEAVING)
            taker.shards[s] = ShardAssignment(
                s, ShardState.INITIALIZING, donor.id
            )
    return Placement(insts, p.num_shards, p.replica_factor, p.version + 1,
                     is_mirrored=True)


def mirrored_remove_group(p: Placement, shard_set_id: int) -> Placement:
    """A whole shard set leaves; its shards move group-to-group onto the
    least loaded surviving sets with mirror pairing."""
    insts = _copy(p)
    groups = _groups(insts.values())
    if shard_set_id not in groups:
        raise ValueError(f"no shard set {shard_set_id}")
    leavers = groups.pop(shard_set_id)
    if not groups:
        raise ValueError("cannot remove the last shard set")
    for s in sorted(leavers[0].shards):
        dest = min(
            (g for g in groups.values() if s not in g[0].shards),
            key=lambda g: (_group_load(g), g[0].id),
            default=None,
        )
        if dest is None:
            raise ValueError(f"no destination shard set for shard {s}")
        for leaver, taker in zip(leavers, dest):
            leaver.shards[s] = ShardAssignment(s, ShardState.LEAVING)
            taker.shards[s] = ShardAssignment(
                s, ShardState.INITIALIZING, leaver.id
            )
    return Placement(insts, p.num_shards, p.replica_factor, p.version + 1,
                     is_mirrored=True)


def mirrored_replace_instance(p: Placement, leaving_id: str,
                              new: Instance) -> Placement:
    """A new instance takes a dead/retiring member's place within its
    shard set, streaming every shard from the surviving mirror peer
    (mirrored.go ReplaceInstances: replacements stay within the set)."""
    insts = _copy(p)
    leaver = insts[leaving_id]
    ssid = leaver.shard_set_id
    peers = [i for i in insts.values()
             if i.shard_set_id == ssid and i.id != leaving_id]
    newcomer = Instance(new.id, new.isolation_group, new.weight, {}, ssid,
                        new.endpoint)
    insts[new.id] = newcomer
    for s, a in list(leaver.shards.items()):
        leaver.shards[s] = ShardAssignment(s, ShardState.LEAVING)
        src = next(
            (pi.id for pi in peers
             if pi.shards.get(s, None) is not None
             and pi.shards[s].state == ShardState.AVAILABLE),
            leaving_id,
        )
        newcomer.shards[s] = ShardAssignment(s, ShardState.INITIALIZING, src)
    return Placement(insts, p.num_shards, p.replica_factor, p.version + 1,
                     is_mirrored=True)


def mirrored_mark_available(p: Placement, instance_id: str,
                            shard: int) -> Placement:
    """Cutover for mirrored moves: flips the Initializing shard on the
    target and clears the matching Leaving shard.  For group moves the
    Leaving holder IS the pairing source; for replacements the source is
    the surviving mirror (AVAILABLE there), so the Leaving shard is
    found on the retiring same-shard-set member instead."""
    insts = _copy(p)
    inst = insts[instance_id]
    a = inst.shards.get(shard)
    if a is None or a.state != ShardState.INITIALIZING:
        raise ValueError(f"shard {shard} not initializing on {instance_id}")
    inst.shards[shard] = ShardAssignment(shard, ShardState.AVAILABLE)
    cleared = False
    if a.source_id and a.source_id in insts:
        src = insts[a.source_id]
        if (shard in src.shards
                and src.shards[shard].state == ShardState.LEAVING):
            del src.shards[shard]
            cleared = True
    if not cleared:
        for i in insts.values():
            if (i.shard_set_id == inst.shard_set_id
                    and i.id != instance_id
                    and i.shards.get(shard) is not None
                    and i.shards[shard].state == ShardState.LEAVING):
                del i.shards[shard]
                break
    # A fully drained leaver (replacement/removal complete) exits the
    # placement — the reference drops instances with no shards left.
    for iid in [i.id for i in insts.values()
                if not i.shards and iid_all_leaving(p, i.id)]:
        del insts[iid]
    return Placement(insts, p.num_shards, p.replica_factor, p.version + 1,
                     is_mirrored=True)


def iid_all_leaving(p: Placement, iid: str) -> bool:
    """True when the instance's shards in the PRIOR placement were all
    Leaving — i.e. it was on its way out, not a zero-shard newcomer."""
    prior = p.instances.get(iid)
    return bool(prior and prior.shards) and all(
        a.state == ShardState.LEAVING for a in prior.shards.values()
    )
