"""TopologyWatcher: a node's live view of the placement it serves in.

Reference parity: `src/dbnode/topology` — the dbnode side of the
cluster story.  `topology/dynamic.go` watches the placement key in the
cluster KV and turns every new version into an immutable topology map;
`storage/database.go` + `shard.go` consume those maps to assign/close
shards.  Here the watcher is deliberately *thin*: it owns the KV watch,
version filtering, and an immutable per-version snapshot of THIS
instance's shard assignment — all the side effects (ownership install,
block streaming, cutover CAS, shard drops) live in
``m3_tpu.storage.migration.ShardMigrator``, which reads snapshots from
this class on the mediator tick.

Thread model: KV watches fire inline from arbitrary threads (the local
store's set path, or the remote store's poller thread).  The callback
only swaps one attribute under a lock and notifies listeners; listeners
must be cheap and non-blocking (the migrator's listener just records
"something changed" — the heavy work happens on its own tick).
"""

from __future__ import annotations

import threading
from dataclasses import dataclass
from typing import Callable, Dict, Optional

from m3_tpu.cluster.placement import (
    Placement, ShardAssignment, ShardState,
)
from m3_tpu.instrument import logger

_LOG = logger("cluster.topology")


@dataclass(frozen=True)
class TopologyView:
    """One immutable observation of the placement, pre-digested for the
    instance the watcher serves.

    ``my_shards`` is this instance's shard map (empty when the
    placement exists but does not list the instance — a removed or
    not-yet-added node owns nothing).  ``placement`` is None only
    before any placement has been created, in which case the node keeps
    the own-everything default (single-node bring-up order: nodes boot
    first, the operator inits the placement after)."""

    placement: Optional[Placement]
    version: int
    instance_id: str

    @property
    def in_placement(self) -> bool:
        return (self.placement is not None
                and self.instance_id in self.placement.instances)

    @property
    def my_shards(self) -> Dict[int, ShardAssignment]:
        if not self.in_placement:
            return {}
        return dict(self.placement.instances[self.instance_id].shards)

    def shards_in_state(self, state: ShardState) -> list[int]:
        return sorted(s for s, a in self.my_shards.items()
                      if a.state == state)

    def owned_shards(self) -> Optional[frozenset]:
        """The shard set this node serves (writes AND reads):
        INITIALIZING (new data lands while history streams), AVAILABLE,
        and LEAVING (keep serving both paths until the newcomer cuts
        over).  None = no placement yet = own everything."""
        if self.placement is None:
            return None
        return frozenset(self.my_shards)

    def donor_for(self, shard: int) -> Optional[str]:
        """Source instance id for one of my INITIALIZING shards."""
        a = self.my_shards.get(shard)
        return a.source_id if a is not None else None

    def available_replicas(self, shard: int) -> list:
        """Other instances serving the shard AVAILABLE right now — the
        streaming fallback when an INITIALIZING shard's named donor is
        unreachable (replace-a-dead-node: the donor never answers)."""
        if self.placement is None:
            return []
        return [
            inst for inst in self.placement.instances_for_shard(shard)
            if inst.id != self.instance_id
            and inst.shards[shard].state == ShardState.AVAILABLE
        ]


class TopologyWatcher:
    """Watches the placement KV key on behalf of one instance id.

    ``on_change(view)`` listeners fire on every newly observed version
    (monotonic: stale versions are dropped, exactly like the session's
    dynamic watch).  ``view()`` returns the latest snapshot at any
    time.  ``close()`` detaches from the KV watch."""

    def __init__(self, kv, instance_id: str, key: str = "placement"):
        self.kv = kv
        self.key = key
        self.instance_id = instance_id
        self._mu = threading.Lock()
        self._listeners: list[Callable[[TopologyView], None]] = []
        self._view = TopologyView(None, 0, instance_id)
        self._closed = False

        def _watch_cb(vv) -> None:
            self._observe(vv)

        self._watch_cb = _watch_cb
        kv.watch(key, _watch_cb)

    def _observe(self, vv) -> None:
        try:
            p = Placement.from_json(vv.data)
        except Exception:  # noqa: BLE001 — a malformed placement must
            # not kill the watch (the control plane may be mid-repair);
            # the previous good view keeps serving.
            _LOG.exception("ignoring malformed placement at version %d",
                           vv.version)
            return
        with self._mu:
            if self._closed or vv.version <= self._view.version:
                return
            view = TopologyView(p, vv.version, self.instance_id)
            self._view = view
            listeners = list(self._listeners)
        _LOG.info(
            "placement v%d: instance %s shards I=%d A=%d L=%d",
            vv.version, self.instance_id,
            len(view.shards_in_state(ShardState.INITIALIZING)),
            len(view.shards_in_state(ShardState.AVAILABLE)),
            len(view.shards_in_state(ShardState.LEAVING)),
        )
        for fn in listeners:
            try:
                fn(view)
            except Exception:  # noqa: BLE001 — one listener must not
                # starve the rest (watch callbacks share the KV
                # notification path)
                _LOG.exception("topology listener raised")

    def view(self) -> TopologyView:
        with self._mu:
            return self._view

    def on_change(self, fn: Callable[[TopologyView], None]) -> None:
        with self._mu:
            self._listeners.append(fn)
            view = self._view
        if view.placement is not None:
            fn(view)  # replay the current state to the new listener

    def close(self) -> None:
        with self._mu:
            self._closed = True
            self._listeners.clear()
        if hasattr(self.kv, "unwatch"):
            self.kv.unwatch(self.key, self._watch_cb)
