"""Versioned KV store with watches: the cluster control plane.

Reference parity: `src/cluster/kv` (`kv.Store`, `types.go:123`: Get/Set/
SetIfNotExists/CheckAndSet with monotonically versioned values, watchable
keys) and its in-memory fake (`kv/mem`) that backs every integration test.
The production reference binds this to etcd; the TPU framework's control
plane is host-side and deliberately etcd-compatible in shape — an etcd
binding would implement this same interface.  File persistence gives
single-host durability (placements/rules/flush-times survive restarts).
"""

from __future__ import annotations

import json
import threading
from dataclasses import dataclass
from pathlib import Path
from typing import Callable, Dict, List


@dataclass(frozen=True)
class VersionedValue:
    version: int
    data: bytes


class KVStore:
    """In-memory versioned KV with watches; optionally file-backed."""

    def __init__(self, root: str | None = None):
        self._lock = threading.RLock()
        self._data: Dict[str, VersionedValue] = {}
        self._watchers: Dict[str, List[Callable[[VersionedValue], None]]] = {}
        self._path = Path(root) / "kv.json" if root else None
        if self._path and self._path.exists():
            raw = json.loads(self._path.read_text())
            self._data = {
                k: VersionedValue(v["version"], bytes.fromhex(v["data"]))
                for k, v in raw.items()
            }

    def _persist(self) -> None:
        if self._path is None:
            return
        tmp = self._path.with_suffix(".tmp")
        tmp.write_text(json.dumps({
            k: {"version": v.version, "data": v.data.hex()}
            for k, v in self._data.items()
        }))
        tmp.replace(self._path)

    def get(self, key: str) -> VersionedValue | None:
        with self._lock:
            return self._data.get(key)

    def set(self, key: str, data: bytes) -> int:
        """Unconditional set; returns the new version."""
        with self._lock:
            cur = self._data.get(key)
            v = (cur.version if cur else 0) + 1
            self._data[key] = VersionedValue(v, data)
            self._persist()
            self._notify(key)
            return v

    def set_if_not_exists(self, key: str, data: bytes) -> int:
        with self._lock:
            if key in self._data:
                raise KeyError(f"{key} already exists")
            return self.set(key, data)

    def check_and_set(self, key: str, expect_version: int, data: bytes) -> int:
        """CAS (reference kv.Store.CheckAndSet): version 0 = must not
        exist."""
        with self._lock:
            cur = self._data.get(key)
            cur_v = cur.version if cur else 0
            if cur_v != expect_version:
                raise ValueError(
                    f"version conflict on {key}: have {cur_v}, want {expect_version}"
                )
            return self.set(key, data)

    def delete(self, key: str) -> bool:
        """Returns whether the key existed."""
        with self._lock:
            existed = self._data.pop(key, None) is not None
            self._persist()
            return existed

    def keys(self) -> list[str]:
        with self._lock:
            return sorted(self._data)

    def watch(self, key: str, fn: Callable[[VersionedValue], None]) -> None:
        """Register a watcher; fired inline on every set (the reference
        delivers via watch channels)."""
        with self._lock:
            self._watchers.setdefault(key, []).append(fn)
            cur = self._data.get(key)
        if cur is not None:
            fn(cur)

    def unwatch(self, key: str, fn: Callable[[VersionedValue], None]) -> None:
        """Remove a watcher registered with watch() (no-op when absent)
        so short-lived watchers don't accumulate forever."""
        with self._lock:
            fns = self._watchers.get(key)
            if fns and fn in fns:
                fns.remove(fn)

    def _notify(self, key: str) -> None:
        cur = self._data[key]
        # Snapshot: a callback may unwatch() mid-delivery (the list is
        # shrinkable now), and mutating the live list would skip the
        # next watcher's notification.
        for fn in list(self._watchers.get(key, ())):
            fn(cur)


class LeaderElection:
    """Leader election over the KV store's CAS (reference
    `src/cluster/services/leader/client.go:32-70`, which campaigns via
    etcd concurrency.Election; same protocol shape: the leader key holds
    the leader's ID at a version, resign deletes it).

    With ``ttl_nanos`` set, the leadership is a *lease* (etcd's session
    TTL): the record carries an expiry, ``campaign(now)`` renews it for
    the incumbent, and any candidate may take over an expired lease via
    CAS — so a crashed leader is superseded after one TTL, the failover
    behavior `election_mgr.go` gets from etcd sessions.  Without a TTL
    the legacy hold-until-resign behavior is preserved.
    """

    def __init__(
        self,
        kv: KVStore,
        electionid: str,
        instance_id: str,
        ttl_nanos: int | None = None,
    ):
        self.kv = kv
        self.key = f"_election/{electionid}"
        self.instance_id = instance_id
        self.ttl_nanos = ttl_nanos

    def _record(self, now_nanos: int | None):
        cur = self.kv.get(self.key)
        if cur is None:
            return None, 0
        try:
            rec = json.loads(cur.data)
            holder, expires = rec["id"], rec.get("expires")
        except (ValueError, KeyError, TypeError):
            holder, expires = cur.data.decode(), None  # legacy raw-ID record
        if (
            expires is not None
            and now_nanos is not None
            and expires <= now_nanos
        ):
            return None, cur.version  # lease expired: claimable via CAS
        return holder, cur.version

    def _payload(self, now_nanos: int | None) -> bytes:
        if self.ttl_nanos is None:
            return self.instance_id.encode()
        return json.dumps(
            {"id": self.instance_id, "expires": now_nanos + self.ttl_nanos}
        ).encode()

    def _require_now(self, now_nanos: int | None) -> None:
        # A TTL election silently degrading to a never-expiring lease on a
        # legacy no-arg call would defeat failover — fail loudly instead.
        if self.ttl_nanos is not None and now_nanos is None:
            raise ValueError("ttl_nanos is set: pass now_nanos")

    def campaign(self, now_nanos: int | None = None) -> bool:
        """Try to become (or renew being) leader."""
        self._require_now(now_nanos)
        holder, version = self._record(now_nanos)
        payload = self._payload(now_nanos)
        if holder == self.instance_id and self.ttl_nanos is None:
            return True
        if holder is not None and holder != self.instance_id:
            return False
        try:
            if version == 0:
                self.kv.set_if_not_exists(self.key, payload)
            else:
                self.kv.check_and_set(self.key, version, payload)
            return True
        except (KeyError, ValueError):
            # Lost the CAS race; we're leader only if the winner was us.
            holder, _ = self._record(now_nanos)
            return holder == self.instance_id

    def leader(self, now_nanos: int | None = None) -> str | None:
        self._require_now(now_nanos)
        holder, _ = self._record(now_nanos)
        return holder

    def is_leader(self, now_nanos: int | None = None) -> bool:
        return self.leader(now_nanos) == self.instance_id

    def resign(self) -> None:
        holder, _ = self._record(None)
        if holder == self.instance_id:
            self.kv.delete(self.key)
