"""Storage adapter: PromQL selectors → index query → raw series blocks.

Equivalent of `src/query/storage/m3` (FetchCompressed
`m3/storage.go:215-225`: label matchers → index FetchTagged → decoded
series) without the network hop — the engine and the database share a
process, as in the reference's embedded coordinator mode.
"""

from __future__ import annotations

import contextlib

import numpy as np

from m3_tpu.index.search import (
    All, Conjunction, Negation, Query, Regexp, Term,
)
from m3_tpu.query.block import RawBlock, SeriesMeta
from m3_tpu.query.promql import LabelMatcher
from m3_tpu.storage.database import Database, ShardNotOwnedError
from m3_tpu.x import deadline as xdeadline
from m3_tpu.x import fault

# reusable no-op scope for the unbound-deadline fast path
_NULL_PHASE = contextlib.nullcontext()


def matchers_to_query(name: bytes | None,
                      matchers: tuple[LabelMatcher, ...]) -> Query:
    """Label matchers → boolean index query (reference storage/m3
    FetchOptionsToM3Options + idx query conversion)."""
    parts: list[Query] = []
    if name is not None:
        parts.append(Term(b"__name__", name))
    for m in matchers:
        if m.op == "=":
            parts.append(Term(m.name, m.value))
        elif m.op == "!=":
            parts.append(Negation(Term(m.name, m.value)))
        elif m.op == "=~":
            parts.append(Regexp(m.name, m.value))
        elif m.op == "!~":
            parts.append(Negation(Regexp(m.name, m.value)))
        else:
            raise ValueError(f"bad matcher op {m.op}")
    if not parts:
        return All()
    if len(parts) == 1 and not isinstance(parts[0], Negation):
        return parts[0]
    return Conjunction(*parts)


class DatabaseStorage:
    """Engine Storage implementation over one Database namespace."""

    def __init__(self, db: Database, namespace: str = "default"):
        self.db = db
        self.namespace = namespace

    def fetch_raw(self, name, matchers, start_nanos, end_nanos) -> RawBlock:
        # The read path's deterministic injection point: delay = slow
        # storage/peer (the overload dtest arms this on one replica),
        # error = failed fetch.  Fired here so BOTH local engine reads
        # and federation-served remote fetches cross one boundary.
        fault.fire("query.fetch")
        dl = xdeadline.current()
        with (dl.phase("fetch") if dl is not None
              else _NULL_PHASE):
            return self._fetch_raw(name, matchers, start_nanos, end_nanos)

    def _fetch_raw(self, name, matchers, start_nanos, end_nanos) -> RawBlock:
        q = matchers_to_query(name, matchers)
        docs = self.db.query_ids(self.namespace, q, start_nanos, end_nanos)
        docs.sort(key=lambda d: d.id)
        pts = []
        metas = []
        for i, d in enumerate(docs):
            if i % 64 == 0:  # per-series read loop: cancellable
                xdeadline.check_current("fetch series")
            try:
                pts.append(
                    self.db.read(self.namespace, d.id, start_nanos, end_nanos))
            except ShardNotOwnedError:
                # "Reads answer only owned shards": the index still
                # knows series whose shard the placement moved away —
                # a local query answers from what this node owns, and
                # the cluster-level union comes from the session's
                # replica fan-out, not from this handle.
                continue
            metas.append(SeriesMeta(tuple(sorted(d.tags().items()))))
        return RawBlock.from_lists(pts, metas)


class SessionStorage:
    """Engine Storage over a ReplicatedSession: the coordinator-style
    deployment where the query engine reaches storage through the
    replica-merging client (`query/storage/m3/storage.go:215-225`
    FetchCompressed → session.FetchTagged)."""

    def __init__(self, session, namespace: str = "default"):
        self.session = session
        self.namespace = namespace

    def fetch_raw(self, name, matchers, start_nanos, end_nanos) -> RawBlock:
        fault.fire("query.fetch")
        q = matchers_to_query(name, matchers)
        docs = self.session.query_ids(self.namespace, q, start_nanos, end_nanos)
        pts = []
        for i, d in enumerate(docs):
            if i % 64 == 0:  # per-series replica fan-out: cancellable
                xdeadline.check_current("fetch series")
            pts.append(
                self.session.fetch(self.namespace, d.id, start_nanos,
                                   end_nanos))
        metas = [SeriesMeta(tuple(sorted(d.tags().items()))) for d in docs]
        return RawBlock.from_lists(pts, metas)
