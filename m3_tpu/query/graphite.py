"""Graphite query engine: path expressions, function pipeline, render.

Equivalent of the reference's Graphite engine (`src/query/graphite` —
lexer/parser under `graphite/lexer`+`native`, ~100 render functions,
and the storage adapter translating dotted paths to tags
`graphite/storage`).  This is the working core of that surface: a
recursive-descent parser for nested function expressions, glob path
resolution against the inverted index via the carbon `__g{i}__` tag
convention (metrics/carbon.py), and the most-used render functions
evaluated over (series × step) arrays.

Series model: values aligned to a fixed step grid over [from, until);
each bucket takes the LAST datapoint falling in it (Graphite's
consolidation default), missing buckets are NaN.
"""

from __future__ import annotations

import math
import re
from dataclasses import dataclass, field, replace

import numpy as np

from m3_tpu.index.search import (
    All, Conjunction, FieldExists, Negation, Regexp, Term,
)

NAN = float("nan")


# ---------------------------------------------------------------------------
# Series model
# ---------------------------------------------------------------------------


@dataclass
class GraphiteSeries:
    name: str           # display name (mutated by alias*)
    path: str           # the real metric path
    values: np.ndarray  # (T,) float64, NaN = missing
    step_nanos: int
    start_nanos: int

    def with_values(self, values, name: str | None = None) -> "GraphiteSeries":
        return replace(self, values=np.asarray(values, np.float64),
                       name=name if name is not None else self.name)


# ---------------------------------------------------------------------------
# Expression parser (reference graphite/lexer + native/parser)
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class PathExpr:
    path: str


@dataclass(frozen=True)
class Call:
    name: str
    args: tuple
    kwargs: tuple = ()


_NUM_RE = re.compile(r"-?\d+(?:\.\d+)?(?:[eE][+-]?\d+)?")
_IDENT_RE = re.compile(r"[A-Za-z_][A-Za-z0-9_]*")
_PATH_CHARS = set(
    "ABCDEFGHIJKLMNOPQRSTUVWXYZabcdefghijklmnopqrstuvwxyz0123456789"
    "_.-*?[]:$%+#"
)


def _scan_path(s: str, i: int) -> int:
    """End index of a path starting at i; ',' belongs to the path only
    inside {...} alternations (it separates args at depth 0)."""
    depth = 0
    j = i
    while j < len(s):
        c = s[j]
        if c == "{":
            depth += 1
        elif c == "}":
            if depth == 0:
                break
            depth -= 1
        elif c == ",":
            if depth == 0:
                break
        elif c not in _PATH_CHARS:
            break
        j += 1
    return j


class ParseError(ValueError):
    pass


class _Parser:
    def __init__(self, s: str):
        self.s = s
        self.i = 0

    def _ws(self):
        while self.i < len(self.s) and self.s[self.i].isspace():
            self.i += 1

    def _peek(self) -> str:
        return self.s[self.i] if self.i < len(self.s) else ""

    def parse(self):
        self._ws()
        out = self._expr()
        self._ws()
        if self.i != len(self.s):
            raise ParseError(f"trailing input at {self.i}: {self.s[self.i:]!r}")
        return out

    def _expr(self):
        self._ws()
        c = self._peek()
        if c and c in "'\"":  # NB: `"" in str` is always True
            return self._string()
        if c.isdigit() or (c == "-" and self.i + 1 < len(self.s)
                           and self.s[self.i + 1].isdigit()):
            m = _NUM_RE.match(self.s, self.i)
            # "404.count" / "1min.load" are legal paths: only a token
            # that ends where the path-scan ends is a number literal
            if m.end() == _scan_path(self.s, self.i):
                self.i = m.end()
                text = m.group()
                return float(text) if ("." in text or "e" in text.lower()) else int(text)
        # identifier: function call or path
        m = _IDENT_RE.match(self.s, self.i)
        if m:
            j = m.end()
            k = j
            while k < len(self.s) and self.s[k].isspace():
                k += 1
            if k < len(self.s) and self.s[k] == "(":
                name = m.group()
                self.i = k + 1
                args, kwargs = self._args()
                return Call(name, tuple(args), tuple(kwargs))
        j = _scan_path(self.s, self.i)
        if j == self.i:
            raise ParseError(f"unexpected input at {self.i}: {self.s[self.i:]!r}")
        text = self.s[self.i : j]
        self.i = j
        if text in ("true", "false"):
            return text == "true"
        return PathExpr(text)

    def _args(self):
        args: list = []
        kwargs: list = []
        self._ws()
        if self._peek() == ")":
            self.i += 1
            return args, kwargs
        while True:
            self._ws()
            # keyword argument?
            m = _IDENT_RE.match(self.s, self.i)
            if m:
                k = m.end()
                while k < len(self.s) and self.s[k].isspace():
                    k += 1
                if k < len(self.s) and self.s[k] == "=" and (
                    k + 1 >= len(self.s) or self.s[k + 1] != "="
                ):
                    self.i = k + 1
                    kwargs.append((m.group(), self._expr()))
                    self._ws()
                    if self._peek() == ",":
                        self.i += 1
                        continue
                    if self._peek() == ")":
                        self.i += 1
                        return args, kwargs
                    raise ParseError(f"bad arg list at {self.i}")
            args.append(self._expr())
            self._ws()
            if self._peek() == ",":
                self.i += 1
                continue
            if self._peek() == ")":
                self.i += 1
                return args, kwargs
            raise ParseError(f"bad arg list at {self.i}")

    def _string(self):
        q = self.s[self.i]
        self.i += 1
        j = self.s.find(q, self.i)
        if j < 0:
            raise ParseError("unterminated string")
        out = self.s[self.i : j]
        self.i = j + 1
        return out


def parse_target(s: str):
    return _Parser(s).parse()


# ---------------------------------------------------------------------------
# Path → index query (glob translation; reference graphite/storage)
# ---------------------------------------------------------------------------


def _component_to_query(i: int, comp: str):
    tag = b"__g%d__" % i
    if comp == "*":
        return FieldExists(tag)
    if not re.search(r"[*?{\[]", comp):
        return Term(tag, comp.encode())
    return Regexp(tag, glob_component_regex(comp).encode())


def glob_component_regex(comp: str) -> str:
    """Graphite glob → regexp: `*` any, `?` one, `{a,b}` alternation,
    `[0-9]` char class (reference graphite/graphite.go GlobToRegexPattern)."""
    out = []
    i = 0
    while i < len(comp):
        c = comp[i]
        if c == "*":
            out.append("[^.]*")
        elif c == "?":
            out.append("[^.]")
        elif c == "{":
            j = comp.find("}", i)
            if j < 0:
                raise ParseError(f"unbalanced {{ in {comp!r}")
            alts = comp[i + 1 : j].split(",")
            out.append("(?:" + "|".join(re.escape(a) for a in alts) + ")")
            i = j
        elif c == "[":
            j = comp.find("]", i)
            if j < 0:
                raise ParseError(f"unbalanced [ in {comp!r}")
            out.append(comp[i : j + 1])
            i = j
        else:
            out.append(re.escape(c))
        i += 1
    return "".join(out)


def path_to_index_query(path: str):
    comps = path.split(".")
    qs = [_component_to_query(i, c) for i, c in enumerate(comps)]
    # exactly-N-components: component N must not exist
    qs.append(Negation(FieldExists(b"__g%d__" % len(comps))))
    return Conjunction(*qs)


# ---------------------------------------------------------------------------
# Storage bridge
# ---------------------------------------------------------------------------


MAX_RENDER_POINTS = 100_000  # per-series grid cap: one request must not OOM


class GraphiteStorage:
    """Fetch graphite-shaped series from a Database namespace."""

    def __init__(self, db, namespace: str = "default",
                 max_points: int = MAX_RENDER_POINTS):
        self.db = db
        self.namespace = namespace
        self.max_points = max_points

    def fetch(self, path: str, start: int, end: int,
              step: int) -> list[GraphiteSeries]:
        from m3_tpu.metrics.carbon import document_to_path

        if step <= 0:
            raise ParseError("step must be positive")
        T = max(0, (end - start) // step)
        if T > self.max_points:
            # an unauthenticated /render must not drive the node to OOM
            # (query limits never see numpy grid allocations)
            raise ParseError(
                f"render grid too large: {T} points > {self.max_points}; "
                "increase step or narrow the range"
            )
        docs = self.db.query_ids(self.namespace, path_to_index_query(path),
                                 start, end)
        out = []
        for d in sorted(docs, key=lambda d: d.id):
            p = document_to_path(d)
            if p is None:
                continue
            pts = self.db.read(self.namespace, d.id, start, end)
            vals = np.full(T, NAN)
            for t, v in pts:  # last point per bucket wins (consolidation)
                b = (t - start) // step
                if 0 <= b < T:
                    vals[b] = v
            out.append(GraphiteSeries(p.decode(), p.decode(), vals, step, start))
        return out

    def find(self, pattern: str) -> list[tuple[str, bool, bool]]:
        """(name, is_leaf, expandable) children matching the pattern's
        last component.  A node can be BOTH (metric `a.b` and branch of
        `a.b.c`) — Graphite reports leaf=1 + expandable=1 then."""
        comps = pattern.split(".")
        n = len(comps)
        qs = [_component_to_query(i, c) for i, c in enumerate(comps)]
        docs = self.db.query_ids(self.namespace, Conjunction(*qs),
                                 -(2**62), 2**62)
        seen: dict[str, list] = {}
        for d in docs:
            tags = d.tags()
            comp = tags.get(b"__g%d__" % (n - 1))
            if comp is None:
                continue
            leaf = (b"__g%d__" % n) not in tags
            flags = seen.setdefault(comp.decode(), [False, False])
            flags[0] |= leaf
            flags[1] |= not leaf
        return sorted((k, v[0], v[1]) for k, v in seen.items())


# ---------------------------------------------------------------------------
# Render functions (reference src/query/graphite/native)
# ---------------------------------------------------------------------------

_FUNCS: dict = {}


def _func(*names):
    def deco(fn):
        for n in names:
            _FUNCS[n] = fn
        return fn
    return deco


def _combine(series: list[GraphiteSeries], op, name: str):
    if not series:
        return []
    vals = np.stack([s.values for s in series])
    with np.errstate(all="ignore"):
        out = op(vals)
    paths = ",".join(s.name for s in series[:3])
    return [series[0].with_values(out, f"{name}({paths})")]


def _nan_agg(fn):
    """Run a nan-aggregate with all-NaN-slice warnings silenced (the
    result is correctly NaN; the warning is just noise)."""
    import warnings

    def run(v, *a, **kw):
        with warnings.catch_warnings():
            warnings.simplefilter("ignore")
            return fn(v, *a, **kw)
    return run


@_func("sumSeries", "sum")
def _sum(ctx, series):
    return _combine(series, lambda v: np.nansum(v, 0), "sumSeries")


@_func("averageSeries", "avg")
def _avg(ctx, series):
    return _combine(series, _nan_agg(lambda v: np.nanmean(v, 0)), "averageSeries")


@_func("maxSeries")
def _max(ctx, series):
    return _combine(series, _nan_agg(lambda v: np.nanmax(v, 0)), "maxSeries")


@_func("minSeries")
def _min(ctx, series):
    return _combine(series, _nan_agg(lambda v: np.nanmin(v, 0)), "minSeries")


@_func("diffSeries")
def _diff(ctx, series):
    def d(v):
        out = v[0].copy()
        out -= np.nansum(v[1:], 0)
        return out
    return _combine(series, d, "diffSeries")


@_func("multiplySeries")
def _mul(ctx, series):
    return _combine(series, lambda v: np.nanprod(v, 0), "multiplySeries")


@_func("scale")
def _scale(ctx, series, factor):
    return [s.with_values(s.values * factor, f"scale({s.name},{factor:g})")
            for s in series]


@_func("offset")
def _offset(ctx, series, amount):
    return [s.with_values(s.values + amount, f"offset({s.name},{amount:g})")
            for s in series]


@_func("absolute")
def _absolute(ctx, series):
    return [s.with_values(np.abs(s.values), f"absolute({s.name})")
            for s in series]


@_func("invert")
def _invert(ctx, series):
    with np.errstate(all="ignore"):
        return [s.with_values(1.0 / s.values, f"invert({s.name})")
                for s in series]


@_func("derivative")
def _derivative(ctx, series):
    out = []
    for s in series:
        d = np.diff(s.values, prepend=NAN)
        out.append(s.with_values(d, f"derivative({s.name})"))
    return out


@_func("nonNegativeDerivative")
def _nnderivative(ctx, series):
    out = []
    for s in series:
        d = np.diff(s.values, prepend=NAN)
        d = np.where(d < 0, NAN, d)
        out.append(s.with_values(d, f"nonNegativeDerivative({s.name})"))
    return out


@_func("perSecond")
def _per_second(ctx, series):
    out = []
    for s in series:
        d = np.diff(s.values, prepend=NAN) / (s.step_nanos / 1e9)
        d = np.where(d < 0, NAN, d)
        out.append(s.with_values(d, f"perSecond({s.name})"))
    return out


@_func("integral")
def _integral(ctx, series):
    out = []
    for s in series:
        v = np.nan_to_num(s.values)
        out.append(s.with_values(np.cumsum(v), f"integral({s.name})"))
    return out


@_func("keepLastValue")
def _keep_last(ctx, series, limit=-1):
    out = []
    for s in series:
        v = s.values.copy()
        run = 0
        last = NAN
        for i in range(len(v)):
            if math.isnan(v[i]):
                run += 1
                if not math.isnan(last) and (limit < 0 or run <= limit):
                    v[i] = last
            else:
                last = v[i]
                run = 0
        out.append(s.with_values(v, f"keepLastValue({s.name})"))
    return out


def _moving(series, window: int, fn, name):
    out = []
    for s in series:
        v = s.values
        res = np.full_like(v, NAN)
        for i in range(len(v)):
            lo = max(0, i - window + 1)
            w = v[lo : i + 1]
            w = w[~np.isnan(w)]
            if len(w):
                res[i] = fn(w)
        out.append(s.with_values(res, f"{name}({s.name},{window})"))
    return out


@_func("movingAverage")
def _moving_avg(ctx, series, window):
    return _moving(series, int(window), np.mean, "movingAverage")


@_func("movingSum")
def _moving_sum(ctx, series, window):
    return _moving(series, int(window), np.sum, "movingSum")


@_func("movingMax")
def _moving_max(ctx, series, window):
    return _moving(series, int(window), np.max, "movingMax")


@_func("movingMin")
def _moving_min(ctx, series, window):
    return _moving(series, int(window), np.min, "movingMin")


@_func("alias")
def _alias(ctx, series, name):
    return [s.with_values(s.values, str(name)) for s in series]


@_func("aliasByNode")
def _alias_by_node(ctx, series, *nodes):
    out = []
    for s in series:
        comps = s.path.split(".")
        try:
            parts = [comps[int(n)] for n in nodes]
        except IndexError:
            parts = [s.path]
        out.append(s.with_values(s.values, ".".join(parts)))
    return out


@_func("timeShift")
def _time_shift(ctx, series, shift):
    """Placeholder: the evaluator intercepts timeShift and evaluates
    the INNER expression against a shifted window (so nested functions
    like scale/sumSeries apply to the shifted data, Graphite semantics).
    Reaching this body means a caller bypassed the evaluator."""
    raise ParseError("timeShift must be evaluated by GraphiteEngine")


@_func("summarize")
def _summarize(ctx, series, interval, func="sum"):
    nanos = _duration_nanos(str(interval))
    out = []
    agg = _nan_agg({"sum": np.nansum, "avg": np.nanmean, "max": np.nanmax,
                    "min": np.nanmin,
                    "last": lambda w: w[~np.isnan(w)][-1] if
                    (~np.isnan(w)).any() else NAN}[func])
    for s in series:
        k = max(1, nanos // s.step_nanos)
        T = len(s.values)
        nb = (T + k - 1) // k
        res = np.full(nb, NAN)
        for b in range(nb):
            w = s.values[b * k : (b + 1) * k]
            if (~np.isnan(w)).any():
                res[b] = agg(w)
        out.append(GraphiteSeries(
            f'summarize({s.name},"{interval}","{func}")', s.path, res,
            s.step_nanos * k, s.start_nanos,
        ))
    return out


# selection / filtering ------------------------------------------------------


def _series_stat(s: GraphiteSeries, what: str) -> float | None:
    """None when the series has no datapoints — empty series never win
    a lowest/below selection (and always lose highest/above)."""
    v = s.values[~np.isnan(s.values)]
    if not len(v):
        return None
    if what == "max":
        return float(v.max())
    if what == "avg":
        return float(v.mean())
    if what == "current":
        return float(v[-1])
    if what == "min":
        return float(v.min())
    raise ValueError(what)


def _select(series, what: str, n: int, largest: bool):
    scored = [(s, _series_stat(s, what)) for s in series]
    scored = [(s, v) for s, v in scored if v is not None]
    scored.sort(key=lambda sv: -sv[1] if largest else sv[1])
    return [s for s, _ in scored[:n]]


@_func("highestMax")
def _highest_max(ctx, series, n=1):
    return _select(series, "max", int(n), True)


@_func("highestAverage")
def _highest_avg(ctx, series, n=1):
    return _select(series, "avg", int(n), True)


@_func("highestCurrent")
def _highest_cur(ctx, series, n=1):
    return _select(series, "current", int(n), True)


@_func("lowestAverage")
def _lowest_avg(ctx, series, n=1):
    return _select(series, "avg", int(n), False)


@_func("limit")
def _limit(ctx, series, n):
    return series[: int(n)]


@_func("sortByName")
def _sort_by_name(ctx, series):
    return sorted(series, key=lambda s: s.name)


@_func("sortByMaxima")
def _sort_by_maxima(ctx, series):
    # empty (all-NaN) series sort last instead of crashing on None
    return sorted(
        series,
        key=lambda s: -(v if (v := _series_stat(s, "max")) is not None
                        else -math.inf),
    )


def _filter_stat(series, what: str, pred):
    out = []
    for s in series:
        v = _series_stat(s, what)
        if v is not None and pred(v):
            out.append(s)
    return out


@_func("averageAbove")
def _avg_above(ctx, series, n):
    return _filter_stat(series, "avg", lambda v: v > n)


@_func("averageBelow")
def _avg_below(ctx, series, n):
    return _filter_stat(series, "avg", lambda v: v < n)


@_func("maximumAbove")
def _max_above(ctx, series, n):
    return _filter_stat(series, "max", lambda v: v > n)


@_func("currentAbove")
def _cur_above(ctx, series, n):
    return _filter_stat(series, "current", lambda v: v > n)


@_func("groupByNode")
def _group_by_node(ctx, series, node, func="sum"):
    groups: dict[str, list] = {}
    for s in series:
        comps = s.path.split(".")
        key = comps[int(node)] if int(node) < len(comps) else s.path
        groups.setdefault(key, []).append(s)
    agg = _FUNCS[{"sum": "sumSeries", "avg": "averageSeries",
                  "max": "maxSeries", "min": "minSeries"}[func]]
    out = []
    for key in sorted(groups):
        combined = agg(ctx, groups[key])
        if combined:
            out.append(combined[0].with_values(combined[0].values, key))
    return out


# ---------------------------------------------------------------------------
# Evaluator + render entry points
# ---------------------------------------------------------------------------


_DUR_RE = re.compile(r"^-?(\d+)(s|min|h|d|w|y|mon)$")
_DUR_NANOS = {"s": 10**9, "min": 60 * 10**9, "h": 3600 * 10**9,
              "d": 86400 * 10**9, "w": 7 * 86400 * 10**9,
              "mon": 30 * 86400 * 10**9, "y": 365 * 86400 * 10**9}


def _duration_nanos(s: str) -> int:
    s = s.strip()
    m = _DUR_RE.match(s)
    if not m:
        raise ParseError(f"bad duration {s!r}")
    nanos = int(m.group(1)) * _DUR_NANOS[m.group(2)]
    # the sign matters: timeShift(x, "-1h") shifts forward, "1h" back
    return -nanos if s.startswith("-") else nanos


def parse_graphite_time(s: str, now_nanos: int) -> int:
    """Epoch seconds, 'now', or relative '-1h' (reference
    graphite/ts parsing, minimal form)."""
    s = s.strip()
    if s == "now" or s == "":
        return now_nanos
    if s.startswith("-"):
        return now_nanos - _duration_nanos(s[1:])
    return int(float(s) * 1e9)


@dataclass
class _Ctx:
    storage: GraphiteStorage
    start: int
    end: int
    step: int


class GraphiteEngine:
    """Parse + evaluate render targets (reference native/engine.go)."""

    def __init__(self, storage: GraphiteStorage):
        self.storage = storage

    def render(self, target: str, start_nanos: int, end_nanos: int,
               step_nanos: int) -> list[GraphiteSeries]:
        ast = parse_target(target)
        ctx = _Ctx(self.storage, start_nanos, end_nanos, step_nanos)
        out = self._eval(ast, ctx)
        if not isinstance(out, list):
            raise ParseError(f"target does not evaluate to series: {target!r}")
        return out

    def _eval(self, node, ctx: _Ctx):
        if isinstance(node, PathExpr):
            return ctx.storage.fetch(node.path, ctx.start, ctx.end, ctx.step)
        if isinstance(node, Call):
            if node.name == "timeShift":
                if len(node.args) != 2:
                    raise ParseError("timeShift(expr, shift) takes 2 args")
                shift = node.args[1]
                nanos = _duration_nanos(str(shift))
                shifted = _Ctx(ctx.storage, ctx.start - nanos,
                               ctx.end - nanos, ctx.step)
                inner = self._eval(node.args[0], shifted)
                return [
                    replace(s, start_nanos=ctx.start,
                            name=f'timeShift({s.name},"{shift}")')
                    for s in inner
                ]
            fn = _FUNCS.get(node.name)
            if fn is None:
                raise ParseError(f"unsupported function {node.name!r}")
            args = [self._eval(a, ctx) for a in node.args]
            kwargs = {k: self._eval(v, ctx) for k, v in node.kwargs}
            # series-list args may come from nested calls/paths; scalars
            # pass through
            return fn(ctx, *args, **kwargs)
        return node  # number / string / bool


def supported_functions() -> list[str]:
    return sorted(_FUNCS)
