"""Graphite query engine: path expressions, function pipeline, render.

Equivalent of the reference's Graphite engine (`src/query/graphite` —
lexer/parser under `graphite/lexer`+`native`, ~100 render functions,
and the storage adapter translating dotted paths to tags
`graphite/storage`).  This is the working core of that surface: a
recursive-descent parser for nested function expressions, glob path
resolution against the inverted index via the carbon `__g{i}__` tag
convention (metrics/carbon.py), and the most-used render functions
evaluated over (series × step) arrays.

Series model: values aligned to a fixed step grid over [from, until);
each bucket takes the LAST datapoint falling in it (Graphite's
consolidation default), missing buckets are NaN.
"""

from __future__ import annotations

import math
import re
from dataclasses import dataclass, field, replace

import numpy as np

from m3_tpu.index.search import (
    All, Conjunction, FieldExists, Negation, Regexp, Term,
)
from m3_tpu.storage.database import ShardNotOwnedError
from m3_tpu.x import deadline as xdeadline

NAN = float("nan")


# ---------------------------------------------------------------------------
# Series model
# ---------------------------------------------------------------------------


@dataclass
class GraphiteSeries:
    name: str           # display name (mutated by alias*)
    path: str           # the real metric path
    values: np.ndarray  # (T,) float64, NaN = missing
    step_nanos: int
    start_nanos: int

    def with_values(self, values, name: str | None = None) -> "GraphiteSeries":
        return replace(self, values=np.asarray(values, np.float64),
                       name=name if name is not None else self.name)


# ---------------------------------------------------------------------------
# Expression parser (reference graphite/lexer + native/parser)
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class PathExpr:
    path: str


@dataclass(frozen=True)
class Call:
    name: str
    args: tuple
    kwargs: tuple = ()


_NUM_RE = re.compile(r"-?\d+(?:\.\d+)?(?:[eE][+-]?\d+)?")
_IDENT_RE = re.compile(r"[A-Za-z_][A-Za-z0-9_]*")
_PATH_CHARS = set(
    "ABCDEFGHIJKLMNOPQRSTUVWXYZabcdefghijklmnopqrstuvwxyz0123456789"
    "_.-*?[]:$%+#"
)


def _scan_path(s: str, i: int) -> int:
    """End index of a path starting at i; ',' belongs to the path only
    inside {...} alternations (it separates args at depth 0)."""
    depth = 0
    j = i
    while j < len(s):
        c = s[j]
        if c == "{":
            depth += 1
        elif c == "}":
            if depth == 0:
                break
            depth -= 1
        elif c == ",":
            if depth == 0:
                break
        elif c not in _PATH_CHARS:
            break
        j += 1
    return j


class ParseError(ValueError):
    pass


class _Parser:
    def __init__(self, s: str):
        self.s = s
        self.i = 0

    def _ws(self):
        while self.i < len(self.s) and self.s[self.i].isspace():
            self.i += 1

    def _peek(self) -> str:
        return self.s[self.i] if self.i < len(self.s) else ""

    def parse(self):
        self._ws()
        out = self._expr()
        self._ws()
        if self.i != len(self.s):
            raise ParseError(f"trailing input at {self.i}: {self.s[self.i:]!r}")
        return out

    def _expr(self):
        self._ws()
        c = self._peek()
        if c and c in "'\"":  # NB: `"" in str` is always True
            return self._string()
        if c.isdigit() or (c == "-" and self.i + 1 < len(self.s)
                           and self.s[self.i + 1].isdigit()):
            m = _NUM_RE.match(self.s, self.i)
            # "404.count" / "1min.load" are legal paths: only a token
            # that ends where the path-scan ends is a number literal
            if m.end() == _scan_path(self.s, self.i):
                self.i = m.end()
                text = m.group()
                return float(text) if ("." in text or "e" in text.lower()) else int(text)
        # identifier: function call or path
        m = _IDENT_RE.match(self.s, self.i)
        if m:
            j = m.end()
            k = j
            while k < len(self.s) and self.s[k].isspace():
                k += 1
            if k < len(self.s) and self.s[k] == "(":
                name = m.group()
                self.i = k + 1
                args, kwargs = self._args()
                return Call(name, tuple(args), tuple(kwargs))
        j = _scan_path(self.s, self.i)
        if j == self.i:
            raise ParseError(f"unexpected input at {self.i}: {self.s[self.i:]!r}")
        text = self.s[self.i : j]
        self.i = j
        if text in ("true", "false"):
            return text == "true"
        return PathExpr(text)

    def _args(self):
        args: list = []
        kwargs: list = []
        self._ws()
        if self._peek() == ")":
            self.i += 1
            return args, kwargs
        while True:
            self._ws()
            # keyword argument?
            m = _IDENT_RE.match(self.s, self.i)
            if m:
                k = m.end()
                while k < len(self.s) and self.s[k].isspace():
                    k += 1
                if k < len(self.s) and self.s[k] == "=" and (
                    k + 1 >= len(self.s) or self.s[k + 1] != "="
                ):
                    self.i = k + 1
                    kwargs.append((m.group(), self._expr()))
                    self._ws()
                    if self._peek() == ",":
                        self.i += 1
                        continue
                    if self._peek() == ")":
                        self.i += 1
                        return args, kwargs
                    raise ParseError(f"bad arg list at {self.i}")
            args.append(self._expr())
            self._ws()
            if self._peek() == ",":
                self.i += 1
                continue
            if self._peek() == ")":
                self.i += 1
                return args, kwargs
            raise ParseError(f"bad arg list at {self.i}")

    def _string(self):
        q = self.s[self.i]
        self.i += 1
        j = self.s.find(q, self.i)
        if j < 0:
            raise ParseError("unterminated string")
        out = self.s[self.i : j]
        self.i = j + 1
        return out


def parse_target(s: str):
    return _Parser(s).parse()


# ---------------------------------------------------------------------------
# Path → index query (glob translation; reference graphite/storage)
# ---------------------------------------------------------------------------


def _component_to_query(i: int, comp: str):
    tag = b"__g%d__" % i
    if comp == "*":
        return FieldExists(tag)
    if not re.search(r"[*?{\[]", comp):
        return Term(tag, comp.encode())
    return Regexp(tag, glob_component_regex(comp).encode())


def glob_component_regex(comp: str) -> str:
    """Graphite glob → regexp: `*` any, `?` one, `{a,b}` alternation,
    `[0-9]` char class (reference graphite/graphite.go GlobToRegexPattern)."""
    out = []
    i = 0
    while i < len(comp):
        c = comp[i]
        if c == "*":
            out.append("[^.]*")
        elif c == "?":
            out.append("[^.]")
        elif c == "{":
            j = comp.find("}", i)
            if j < 0:
                raise ParseError(f"unbalanced {{ in {comp!r}")
            alts = comp[i + 1 : j].split(",")
            out.append("(?:" + "|".join(re.escape(a) for a in alts) + ")")
            i = j
        elif c == "[":
            j = comp.find("]", i)
            if j < 0:
                raise ParseError(f"unbalanced [ in {comp!r}")
            out.append(comp[i : j + 1])
            i = j
        else:
            out.append(re.escape(c))
        i += 1
    return "".join(out)


def path_to_index_query(path: str):
    comps = path.split(".")
    qs = [_component_to_query(i, c) for i, c in enumerate(comps)]
    # exactly-N-components: component N must not exist
    qs.append(Negation(FieldExists(b"__g%d__" % len(comps))))
    return Conjunction(*qs)


# ---------------------------------------------------------------------------
# Storage bridge
# ---------------------------------------------------------------------------


MAX_RENDER_POINTS = 100_000  # per-series grid cap: one request must not OOM


class GraphiteStorage:
    """Fetch graphite-shaped series from a Database namespace."""

    def __init__(self, db, namespace: str = "default",
                 max_points: int = MAX_RENDER_POINTS):
        self.db = db
        self.namespace = namespace
        self.max_points = max_points

    def fetch(self, path: str, start: int, end: int,
              step: int) -> list[GraphiteSeries]:
        from m3_tpu.metrics.carbon import document_to_path

        if step <= 0:
            raise ParseError("step must be positive")
        T = max(0, (end - start) // step)
        if T > self.max_points:
            # an unauthenticated /render must not drive the node to OOM
            # (query limits never see numpy grid allocations)
            raise ParseError(
                f"render grid too large: {T} points > {self.max_points}; "
                "increase step or narrow the range"
            )
        docs = self.db.query_ids(self.namespace, path_to_index_query(path),
                                 start, end)
        out = []
        for i, d in enumerate(sorted(docs, key=lambda d: d.id)):
            if i % 64 == 0:  # per-series read loop: cancellable
                xdeadline.check_current("render fetch")
            p = document_to_path(d)
            if p is None:
                continue
            try:
                pts = self.db.read(self.namespace, d.id, start, end)
            except ShardNotOwnedError:
                continue  # unowned shard: replicas answer it
            vals = np.full(T, NAN)
            for t, v in pts:  # last point per bucket wins (consolidation)
                b = (t - start) // step
                if 0 <= b < T:
                    vals[b] = v
            out.append(GraphiteSeries(p.decode(), p.decode(), vals, step, start))
        return out

    def find(self, pattern: str) -> list[tuple[str, bool, bool]]:
        """(name, is_leaf, expandable) children matching the pattern's
        last component.  A node can be BOTH (metric `a.b` and branch of
        `a.b.c`) — Graphite reports leaf=1 + expandable=1 then."""
        comps = pattern.split(".")
        n = len(comps)
        qs = [_component_to_query(i, c) for i, c in enumerate(comps)]
        docs = self.db.query_ids(self.namespace, Conjunction(*qs),
                                 -(2**62), 2**62)
        seen: dict[str, list] = {}
        for d in docs:
            tags = d.tags()
            comp = tags.get(b"__g%d__" % (n - 1))
            if comp is None:
                continue
            leaf = (b"__g%d__" % n) not in tags
            flags = seen.setdefault(comp.decode(), [False, False])
            flags[0] |= leaf
            flags[1] |= not leaf
        return sorted((k, v[0], v[1]) for k, v in seen.items())


# ---------------------------------------------------------------------------
# Render functions (reference src/query/graphite/native)
# ---------------------------------------------------------------------------

_FUNCS: dict = {}


def _func(*names):
    def deco(fn):
        for n in names:
            _FUNCS[n] = fn
        return fn
    return deco


def _combine(series: list[GraphiteSeries], op, name: str):
    if not series:
        return []
    vals = np.stack([s.values for s in series])
    with np.errstate(all="ignore"):
        out = op(vals)
    paths = ",".join(s.name for s in series[:3])
    return [series[0].with_values(out, f"{name}({paths})")]


def _nan_agg(fn):
    """Run a nan-aggregate with all-NaN-slice warnings silenced (the
    result is correctly NaN; the warning is just noise)."""
    import warnings

    def run(v, *a, **kw):
        with warnings.catch_warnings():
            warnings.simplefilter("ignore")
            return fn(v, *a, **kw)
    return run


@_func("sumSeries", "sum")
def _sum(ctx, series):
    return _combine(series, lambda v: np.nansum(v, 0), "sumSeries")


@_func("averageSeries", "avg")
def _avg(ctx, series):
    return _combine(series, _nan_agg(lambda v: np.nanmean(v, 0)), "averageSeries")


@_func("maxSeries")
def _max(ctx, series):
    return _combine(series, _nan_agg(lambda v: np.nanmax(v, 0)), "maxSeries")


@_func("minSeries")
def _min(ctx, series):
    return _combine(series, _nan_agg(lambda v: np.nanmin(v, 0)), "minSeries")


@_func("diffSeries")
def _diff(ctx, series):
    def d(v):
        out = v[0].copy()
        out -= np.nansum(v[1:], 0)
        return out
    return _combine(series, d, "diffSeries")


@_func("multiplySeries")
def _mul(ctx, series):
    return _combine(series, lambda v: np.nanprod(v, 0), "multiplySeries")


@_func("scale")
def _scale(ctx, series, factor):
    return [s.with_values(s.values * factor, f"scale({s.name},{factor:g})")
            for s in series]


@_func("offset")
def _offset(ctx, series, amount):
    return [s.with_values(s.values + amount, f"offset({s.name},{amount:g})")
            for s in series]


@_func("absolute")
def _absolute(ctx, series):
    return [s.with_values(np.abs(s.values), f"absolute({s.name})")
            for s in series]


@_func("invert")
def _invert(ctx, series):
    with np.errstate(all="ignore"):
        return [s.with_values(1.0 / s.values, f"invert({s.name})")
                for s in series]


@_func("derivative")
def _derivative(ctx, series):
    out = []
    for s in series:
        d = np.diff(s.values, prepend=NAN)
        out.append(s.with_values(d, f"derivative({s.name})"))
    return out


@_func("nonNegativeDerivative")
def _nnderivative(ctx, series):
    out = []
    for s in series:
        d = np.diff(s.values, prepend=NAN)
        d = np.where(d < 0, NAN, d)
        out.append(s.with_values(d, f"nonNegativeDerivative({s.name})"))
    return out


@_func("perSecond")
def _per_second(ctx, series):
    out = []
    for s in series:
        d = np.diff(s.values, prepend=NAN) / (s.step_nanos / 1e9)
        d = np.where(d < 0, NAN, d)
        out.append(s.with_values(d, f"perSecond({s.name})"))
    return out


@_func("integral")
def _integral(ctx, series):
    out = []
    for s in series:
        v = np.nan_to_num(s.values)
        out.append(s.with_values(np.cumsum(v), f"integral({s.name})"))
    return out


@_func("keepLastValue")
def _keep_last(ctx, series, limit=-1):
    out = []
    for s in series:
        v = s.values.copy()
        run = 0
        last = NAN
        for i in range(len(v)):
            if math.isnan(v[i]):
                run += 1
                if not math.isnan(last) and (limit < 0 or run <= limit):
                    v[i] = last
            else:
                last = v[i]
                run = 0
        out.append(s.with_values(v, f"keepLastValue({s.name})"))
    return out


def _moving(series, window: int, fn, name, min_fraction: float = 0.0):
    """Trailing-window aggregate over non-null points.  ``min_fraction``
    (stdev's windowTolerance) nulls windows whose valid fraction falls
    below it; 0 keeps any non-empty window."""
    out = []
    for s in series:
        v = s.values
        res = np.full_like(v, NAN)
        for i in range(len(v)):
            lo = max(0, i - window + 1)
            w = v[lo : i + 1]
            w = w[~np.isnan(w)]
            if len(w) and (not min_fraction
                           or len(w) / window >= min_fraction):
                res[i] = fn(w)
        out.append(s.with_values(res, f"{name}({s.name},{window})"))
    return out


@_func("movingAverage")
def _moving_avg(ctx, series, window):
    return _moving(series, int(window), np.mean, "movingAverage")


@_func("movingSum")
def _moving_sum(ctx, series, window):
    return _moving(series, int(window), np.sum, "movingSum")


@_func("movingMax")
def _moving_max(ctx, series, window):
    return _moving(series, int(window), np.max, "movingMax")


@_func("movingMin")
def _moving_min(ctx, series, window):
    return _moving(series, int(window), np.min, "movingMin")


@_func("alias")
def _alias(ctx, series, name):
    return [s.with_values(s.values, str(name)) for s in series]


@_func("aliasByNode")
def _alias_by_node(ctx, series, *nodes):
    out = []
    for s in series:
        comps = s.path.split(".")
        try:
            parts = [comps[int(n)] for n in nodes]
        except IndexError:
            parts = [s.path]
        out.append(s.with_values(s.values, ".".join(parts)))
    return out


@_func("timeShift")
def _time_shift(ctx, series, shift):
    """Placeholder: the evaluator intercepts timeShift and evaluates
    the INNER expression against a shifted window (so nested functions
    like scale/sumSeries apply to the shifted data, Graphite semantics).
    Reaching this body means a caller bypassed the evaluator."""
    raise ParseError("timeShift must be evaluated by GraphiteEngine")


@_func("summarize")
def _summarize(ctx, series, interval, func="sum"):
    nanos = _duration_nanos(str(interval))
    out = []
    agg = _nan_agg({"sum": np.nansum, "avg": np.nanmean, "max": np.nanmax,
                    "min": np.nanmin,
                    "last": lambda w: w[~np.isnan(w)][-1] if
                    (~np.isnan(w)).any() else NAN}[func])
    for s in series:
        k = max(1, nanos // s.step_nanos)
        T = len(s.values)
        nb = (T + k - 1) // k
        res = np.full(nb, NAN)
        for b in range(nb):
            w = s.values[b * k : (b + 1) * k]
            if (~np.isnan(w)).any():
                res[b] = agg(w)
        out.append(GraphiteSeries(
            f'summarize({s.name},"{interval}","{func}")', s.path, res,
            s.step_nanos * k, s.start_nanos,
        ))
    return out


# selection / filtering ------------------------------------------------------


# Canonical aggregation-name aliases for the per-series stat used by
# selection/sorting/filter builtins; one map so every function accepts
# the same spellings (and unknown names fail loudly everywhere).
_STAT_ALIASES = {
    "average": "avg", "avg": "avg", "max": "max", "min": "min",
    "current": "current", "last": "current", "sum": "sum",
    "total": "sum", "median": "median", "stddev": "stddev",
}


def _stat_name(func) -> str:
    what = _STAT_ALIASES.get(str(func))
    if what is None:
        raise ParseError(f"unknown aggregation func {func!r}")
    return what


def _series_stat(s: GraphiteSeries, what: str) -> float | None:
    """None when the series has no datapoints — empty series never win
    a lowest/below selection (and always lose highest/above)."""
    v = s.values[~np.isnan(s.values)]
    if not len(v):
        return None
    if what == "max":
        return float(v.max())
    if what == "avg":
        return float(v.mean())
    if what == "current":
        return float(v[-1])
    if what == "min":
        return float(v.min())
    if what == "sum":
        return float(v.sum())
    if what == "median":
        return float(np.median(v))
    if what == "stddev":
        return float(v.std())
    raise ValueError(what)


def _select(series, what: str, n: int, largest: bool):
    scored = [(s, _series_stat(s, what)) for s in series]
    scored = [(s, v) for s, v in scored if v is not None]
    scored.sort(key=lambda sv: -sv[1] if largest else sv[1])
    return [s for s, _ in scored[:n]]


@_func("highestMax")
def _highest_max(ctx, series, n=1):
    return _select(series, "max", int(n), True)


@_func("highestAverage")
def _highest_avg(ctx, series, n=1):
    return _select(series, "avg", int(n), True)


@_func("highestCurrent")
def _highest_cur(ctx, series, n=1):
    return _select(series, "current", int(n), True)


@_func("lowestAverage")
def _lowest_avg(ctx, series, n=1):
    return _select(series, "avg", int(n), False)


@_func("limit")
def _limit(ctx, series, n):
    return series[: int(n)]


@_func("sortByName")
def _sort_by_name(ctx, series):
    return sorted(series, key=lambda s: s.name)


@_func("sortByMaxima")
def _sort_by_maxima(ctx, series):
    # empty (all-NaN) series sort last instead of crashing on None
    return sorted(
        series,
        key=lambda s: -(v if (v := _series_stat(s, "max")) is not None
                        else -math.inf),
    )


def _filter_stat(series, what: str, pred):
    out = []
    for s in series:
        v = _series_stat(s, what)
        if v is not None and pred(v):
            out.append(s)
    return out


@_func("averageAbove")
def _avg_above(ctx, series, n):
    return _filter_stat(series, "avg", lambda v: v > n)


@_func("averageBelow")
def _avg_below(ctx, series, n):
    return _filter_stat(series, "avg", lambda v: v < n)


@_func("maximumAbove")
def _max_above(ctx, series, n):
    return _filter_stat(series, "max", lambda v: v > n)


@_func("currentAbove")
def _cur_above(ctx, series, n):
    return _filter_stat(series, "current", lambda v: v > n)


@_func("groupByNode")
def _group_by_node(ctx, series, node, func="sum"):
    groups: dict[str, list] = {}
    for s in series:
        comps = s.path.split(".")
        key = comps[int(node)] if int(node) < len(comps) else s.path
        groups.setdefault(key, []).append(s)
    agg = _FUNCS[{"sum": "sumSeries", "avg": "averageSeries",
                  "max": "maxSeries", "min": "minSeries"}[func]]
    out = []
    for key in sorted(groups):
        combined = agg(ctx, groups[key])
        if combined:
            out.append(combined[0].with_values(combined[0].values, key))
    return out


# Breadth tier: the most-used remainder of the reference's ~107 builtins
# (`src/query/graphite/native/builtin_functions.go`), implemented over
# the same GraphiteSeries model.  Purely presentational builtins
# (dashed, legendValue, cactiStyle, secondYAxis) and the holt-winters /
# random-walk families are intentionally out of scope.
# ---------------------------------------------------------------------------


def _percentile(values: np.ndarray, n: float, interpolate: bool = False):
    """Graphite's _getPercentile: rank = (n/100)*(count+1) over sorted
    non-null values, optionally linearly interpolated."""
    pts = np.sort(values[~np.isnan(values)])
    if not len(pts):
        return None
    frac_rank = (n / 100.0) * (len(pts) + 1)
    rank = int(frac_rank)
    rank_frac = frac_rank - rank
    if not interpolate:
        rank += int(math.ceil(rank_frac))
    if rank == 0:
        out = float(pts[0])
    elif rank - 1 >= len(pts):
        out = float(pts[-1])
    else:
        out = float(pts[rank - 1])
    if interpolate and 0 < rank < len(pts):
        out += rank_frac * (float(pts[rank]) - float(pts[rank - 1]))
    return out


_AGG_OPS = {
    "sum": lambda v: np.nansum(v, 0),
    "total": lambda v: np.nansum(v, 0),
    "avg": _nan_agg(lambda v: np.nanmean(v, 0)),
    "average": _nan_agg(lambda v: np.nanmean(v, 0)),
    "max": _nan_agg(lambda v: np.nanmax(v, 0)),
    "min": _nan_agg(lambda v: np.nanmin(v, 0)),
    "median": _nan_agg(lambda v: np.nanmedian(v, 0)),
    "range": _nan_agg(lambda v: np.nanmax(v, 0) - np.nanmin(v, 0)),
    "rangeOf": _nan_agg(lambda v: np.nanmax(v, 0) - np.nanmin(v, 0)),
    "stddev": _nan_agg(lambda v: np.nanstd(v, 0)),
    "count": lambda v: np.sum(~np.isnan(v), 0).astype(np.float64),
    "last": _nan_agg(lambda v: _last_non_nan(v)),
    "multiply": lambda v: np.nanprod(v, 0),
    "diff": lambda v: v[0] - np.nansum(v[1:], 0),
}


def _last_non_nan(v: np.ndarray) -> np.ndarray:
    out = np.full(v.shape[1], NAN)
    for row in v:
        out = np.where(np.isnan(row), out, row)
    return out


@_func("aggregate")
def _aggregate(ctx, series, func):
    op = _AGG_OPS.get(str(func).removesuffix("Series"))
    if op is None:
        raise ParseError(f"aggregate: unknown func {func!r}")
    return _combine(series, op, f"aggregate:{func}")


@_func("group")
def _group(ctx, *series_lists):
    out = []
    for sl in series_lists:
        out.extend(sl)
    return out


@_func("aliasByMetric")
def _alias_by_metric(ctx, series):
    return [s.with_values(s.values, s.path.split(".")[-1]) for s in series]


@_func("aliasSub")
def _alias_sub(ctx, series, search, rep):
    rx = re.compile(str(search))
    return [s.with_values(s.values, rx.sub(str(rep), s.name)) for s in series]


@_func("aliasByTags")
def _alias_by_tags(ctx, series, *tags):
    """Graphite-on-tags naming: M3 maps path component i to tag __gi__
    (reference graphite storage adapter); 'name' is the full path."""
    out = []
    for s in series:
        comps = s.path.split(".")
        parts = []
        for t in tags:
            t = str(t)
            if t == "name":
                parts.append(s.path)
            elif t.startswith("__g") and t.endswith("__"):
                i = int(t[3:-2])
                parts.append(comps[i] if i < len(comps) else "")
            elif t.isdigit():
                i = int(t)
                parts.append(comps[i] if i < len(comps) else "")
            else:
                parts.append("")
        out.append(s.with_values(s.values, ".".join(p for p in parts if p)))
    return out


@_func("asPercent")
def _as_percent(ctx, series, total=None):
    if not series:
        return []
    with np.errstate(all="ignore"):
        if total is None:
            denom = np.nansum(np.stack([s.values for s in series]), 0)
            return [s.with_values(100.0 * s.values / denom,
                                  f"asPercent({s.name})") for s in series]
        if isinstance(total, (int, float)):
            return [s.with_values(100.0 * s.values / float(total),
                                  f"asPercent({s.name},{total:g})")
                    for s in series]
        if len(total) == 1:
            d = total[0].values
            return [s.with_values(100.0 * s.values / d,
                                  f"asPercent({s.name},{total[0].name})")
                    for s in series]
        if len(total) == len(series):
            return [s.with_values(100.0 * s.values / t.values,
                                  f"asPercent({s.name},{t.name})")
                    for s, t in zip(series, total)]
    raise ParseError("asPercent: total must be scalar, 1 series, or match")


@_func("changed")
def _changed(ctx, series):
    out = []
    for s in series:
        v = s.values
        prev = np.concatenate([[NAN], v[:-1]])
        ch = ((~np.isnan(v)) & (~np.isnan(prev)) & (v != prev)).astype(np.float64)
        out.append(s.with_values(ch, f"changed({s.name})"))
    return out


@_func("consolidateBy", "cumulative")
def _consolidate_by(ctx, series, func="sum"):
    # graphite-web's consolidationFunc only changes how the RENDERER
    # reduces points when maxDataPoints forces downsampling; this
    # engine always returns full-resolution data (no maxDataPoints
    # reduction exists), so pass-through is exact — there is no code
    # path where the chosen func could alter returned values.
    return [s.with_values(s.values, f'consolidateBy({s.name},"{func}")')
            for s in series]


def _grid(ctx):
    n = max(1, (ctx.end - ctx.start) // ctx.step)
    return n


@_func("constantLine")
def _constant_line(ctx, value):
    n = _grid(ctx)
    return [GraphiteSeries(f"{float(value):g}", f"{float(value):g}",
                           np.full(n, float(value)), ctx.step, ctx.start)]


@_func("threshold")
def _threshold(ctx, value, label=None):
    (line,) = _constant_line(ctx, value)
    return [line.with_values(line.values,
                             str(label) if label is not None else line.name)]


@_func("identity")
def _identity(ctx, name="identity"):
    n = _grid(ctx)
    secs = (ctx.start + np.arange(n) * ctx.step) / 1e9
    return [GraphiteSeries(str(name), str(name), secs.astype(np.float64),
                           ctx.step, ctx.start)]


@_func("timeFunction", "time")
def _time_function(ctx, name="time", step=None):
    return _identity(ctx, name)


@_func("countSeries")
def _count_series(ctx, *series_lists):
    series = [s for sl in series_lists for s in sl]
    if not series:
        return []
    n = len(series[0].values)
    return [series[0].with_values(np.full(n, float(len(series))),
                                  "countSeries()")]


@_func("currentBelow")
def _cur_below(ctx, series, n):
    return _filter_stat(series, "current", lambda v: v < n)


@_func("maximumBelow")
def _max_below(ctx, series, n):
    return _filter_stat(series, "max", lambda v: v < n)


@_func("minimumAbove")
def _min_above(ctx, series, n):
    return _filter_stat(series, "min", lambda v: v > n)


@_func("minimumBelow")
def _min_below(ctx, series, n):
    return _filter_stat(series, "min", lambda v: v < n)


@_func("lowestCurrent")
def _lowest_cur(ctx, series, n=1):
    return _select(series, "current", int(n), False)


@_func("highest")
def _highest(ctx, series, n=1, func="average"):
    return _select(series, _stat_name(func), int(n), True)


@_func("lowest")
def _lowest(ctx, series, n=1, func="average"):
    return _select(series, _stat_name(func), int(n), False)


@_func("delay")
def _delay(ctx, series, steps):
    k = int(steps)
    out = []
    for s in series:
        v = np.full_like(s.values, NAN)
        if k >= 0:
            if k < len(v):
                v[k:] = s.values[: len(v) - k]
        else:
            if -k < len(v):
                v[:k] = s.values[-k:]
        out.append(s.with_values(v, f"delay({s.name},{k})"))
    return out


@_func("divideSeries")
def _divide_series(ctx, dividends, divisor):
    if len(divisor) != 1:
        raise ParseError("divideSeries: divisor must be exactly one series")
    d = divisor[0].values
    with np.errstate(all="ignore"):
        return [
            s.with_values(np.where(d == 0, NAN, s.values / d),
                          f"divideSeries({s.name},{divisor[0].name})")
            for s in dividends
        ]


@_func("divideSeriesLists")
def _divide_series_lists(ctx, dividends, divisors):
    if len(dividends) != len(divisors):
        raise ParseError("divideSeriesLists: length mismatch")
    with np.errstate(all="ignore"):
        return [
            s.with_values(np.where(t.values == 0, NAN, s.values / t.values),
                          f"divideSeries({s.name},{t.name})")
            for s, t in zip(dividends, divisors)
        ]


@_func("exclude")
def _exclude(ctx, series, pattern):
    rx = re.compile(str(pattern))
    return [s for s in series if not rx.search(s.name)]


@_func("grep")
def _grep(ctx, series, pattern):
    rx = re.compile(str(pattern))
    return [s for s in series if rx.search(s.name)]


@_func("fallbackSeries")
def _fallback_series(ctx, series, fallback):
    return series if series else fallback


@_func("filterSeries")
def _filter_series(ctx, series, func, op, threshold):
    what = _stat_name(func)
    ops = {
        "=": lambda v: v == threshold, "!=": lambda v: v != threshold,
        ">": lambda v: v > threshold, ">=": lambda v: v >= threshold,
        "<": lambda v: v < threshold, "<=": lambda v: v <= threshold,
    }
    pred = ops.get(str(op))
    if pred is None:
        raise ParseError(f"filterSeries: unknown op {op!r}")
    return _filter_stat(series, what, pred)


_MINUTE_NANOS = 60 * 10**9
_HOUR_NANOS = 3600 * 10**9
_DAY_NANOS = 86400 * 10**9


@_func("hitcount")
def _hitcount(ctx, series, interval, align_to_interval=False):
    """Per-bucket hit totals (value x step-seconds summed per interval),
    graphite-web functions.py hitcount semantics:

    * default — buckets are anchored at the series END
      (``newStart = end - bucket_count*interval``), so any partial
      bucket is the FIRST one;
    * ``alignToInterval=True`` — the start truncates to the interval's
      leading calendar unit (day/hour/minute) and buckets run forward
      from there.  (graphite-web re-fetches from the truncated start;
      without a re-fetch the pre-start remainder of that first bucket
      is simply empty here.)"""
    nanos = max(_duration_nanos(str(interval)),
                1)
    out = []
    for s in series:
        T = len(s.values)
        # A bucket can't be finer than the data's step: an interval
        # below the step would time-stretch the output.
        eff = max(nanos, s.step_nanos)
        end = s.start_nanos + T * s.step_nanos
        if align_to_interval:
            unit = (_DAY_NANOS if eff >= _DAY_NANOS
                    else _HOUR_NANOS if eff >= _HOUR_NANOS
                    else _MINUTE_NANOS if eff >= _MINUTE_NANOS
                    else 10**9)
            base = (s.start_nanos // unit) * unit
        else:
            nb0 = max(0, -(-(end - s.start_nanos) // eff))
            base = end - nb0 * eff
        t = s.start_nanos + np.arange(T, dtype=np.int64) * s.step_nanos
        bidx = (t - base) // eff
        nb = int(bidx[-1]) + 1 if T else 0
        res = np.full(nb, NAN)
        secs = s.step_nanos / 1e9
        # bidx is non-decreasing: bucket b is the slice between edges.
        edges = np.searchsorted(bidx, np.arange(nb + 1))
        for b in range(nb):
            w = s.values[edges[b]:edges[b + 1]]
            if w.size and (~np.isnan(w)).any():
                res[b] = np.nansum(w) * secs
        suffix = ",true" if align_to_interval else ""
        out.append(GraphiteSeries(
            f'hitcount({s.name},"{interval}"{suffix})', s.path, res,
            eff, base,
        ))
    return out


@_func("integralByInterval")
def _integral_by_interval(ctx, series, interval):
    nanos = _duration_nanos(str(interval))
    out = []
    for s in series:
        k = max(1, nanos // s.step_nanos)
        v = np.nan_to_num(s.values)
        res = np.empty_like(v)
        for b in range(0, len(v), k):
            res[b: b + k] = np.cumsum(v[b: b + k])
        out.append(s.with_values(res, f"integralByInterval({s.name})"))
    return out


@_func("interpolate")
def _interpolate(ctx, series, limit=-1):
    """Fill interior NaN gaps linearly; a gap is filled only when its
    ENTIRE run length is <= limit (graphite-web leaves longer gaps
    untouched rather than partially filling them)."""
    out = []
    for s in series:
        v = s.values.copy()
        idx = np.arange(len(v))
        good = ~np.isnan(v)
        if good.sum() >= 2:
            filled = np.interp(idx, idx[good], v[good])
            first, last = idx[good][0], idx[good][-1]
            i = 0
            while i < len(v):
                if np.isnan(v[i]):
                    j = i
                    while j < len(v) and np.isnan(v[j]):
                        j += 1
                    interior = first < i and j - 1 < last
                    if interior and (limit < 0 or (j - i) <= limit):
                        v[i:j] = filled[i:j]
                    i = j
                else:
                    i += 1
        out.append(s.with_values(v, f"interpolate({s.name})"))
    return out


@_func("isNonNull")
def _is_non_null(ctx, series):
    return [s.with_values((~np.isnan(s.values)).astype(np.float64),
                          f"isNonNull({s.name})") for s in series]


@_func("logarithm", "log")
def _logarithm(ctx, series, base=10):
    with np.errstate(all="ignore"):
        return [
            s.with_values(
                np.where(s.values > 0,
                         np.log(s.values) / math.log(float(base)), NAN),
                f"log({s.name},{float(base):g})")
            for s in series
        ]


@_func("mostDeviant")
def _most_deviant(ctx, series, n):
    def sigma(s):
        v = s.values[~np.isnan(s.values)]
        return float(v.std()) if len(v) else -math.inf
    return sorted(series, key=sigma, reverse=True)[: int(n)]


@_func("movingMedian")
def _moving_median(ctx, series, window):
    return _moving(series, int(window), np.median, "movingMedian")


@_func("movingWindow")
def _moving_window(ctx, series, window, func="average"):
    fn = {"average": np.mean, "avg": np.mean, "sum": np.sum,
          "max": np.max, "min": np.min, "median": np.median,
          "stddev": np.std}.get(str(func))
    if fn is None:
        raise ParseError(f"movingWindow: unknown func {func!r}")
    return _moving(series, int(window), fn, f"movingWindow:{func}")


@_func("exponentialMovingAverage")
def _ema(ctx, series, window):
    """graphite-web semantics: the EMA seeds with the simple average of
    the first ``window`` points (emitted at that index; earlier points
    are null), then decays with alpha = 2/(window+1)."""
    n = int(window)
    alpha = 2.0 / (n + 1)
    out = []
    for s in series:
        v = s.values
        res = np.full_like(v, NAN)
        if len(v) >= n:
            seed_w = v[:n]
            seed = float(np.nanmean(seed_w)) if (~np.isnan(seed_w)).any() else NAN
            ema = seed
            res[n - 1] = ema
            for i in range(n, len(v)):
                x = v[i]
                if not math.isnan(x) and not math.isnan(ema):
                    ema = alpha * x + (1 - alpha) * ema
                elif not math.isnan(x):
                    ema = x
                res[i] = ema
        out.append(s.with_values(res, f"exponentialMovingAverage({s.name},{n})"))
    return out


@_func("stdev")
def _stdev_moving(ctx, series, points, window_tolerance=0.1):
    """Trailing-window population stddev over non-null points; a window
    whose valid fraction falls below ``windowTolerance`` yields null
    (graphite-web functions.py stdev: validPoints/points >=
    windowTolerance gates the calculation)."""
    return _moving(series, int(points), np.std, "stdev",
                   min_fraction=float(window_tolerance))


@_func("stddevSeries")
def _stddev_series(ctx, series):
    return _combine(series, _nan_agg(lambda v: np.nanstd(v, 0)), "stddevSeries")


@_func("rangeOfSeries")
def _range_of_series(ctx, series):
    return _combine(
        series,
        _nan_agg(lambda v: np.nanmax(v, 0) - np.nanmin(v, 0)),
        "rangeOfSeries",
    )


@_func("nPercentile")
def _n_percentile(ctx, series, n):
    out = []
    for s in series:
        p = _percentile(s.values, float(n))
        if p is None:
            continue
        out.append(s.with_values(np.full_like(s.values, p),
                                 f"nPercentile({s.name},{float(n):g})"))
    return out


@_func("percentileOfSeries")
def _percentile_of_series(ctx, series, n, interpolate=False):
    if not series:
        return []
    vals = np.stack([s.values for s in series])
    T = vals.shape[1]
    res = np.full(T, NAN)
    for t in range(T):
        p = _percentile(vals[:, t], float(n), bool(interpolate))
        if p is not None:
            res[t] = p
    return [series[0].with_values(res, f"percentileOfSeries({series[0].name},{float(n):g})")]


@_func("pow")
def _pow(ctx, series, factor):
    with np.errstate(all="ignore"):
        return [s.with_values(np.power(s.values, float(factor)),
                              f"pow({s.name},{float(factor):g})")
                for s in series]


@_func("powSeries")
def _pow_series(ctx, *series_lists):
    series = [s for sl in series_lists for s in sl]
    if not series:
        return []
    with np.errstate(all="ignore"):
        acc = series[0].values.copy()
        for s in series[1:]:
            acc = np.power(acc, s.values)
    return [series[0].with_values(acc, "powSeries()")]


@_func("offsetToZero")
def _offset_to_zero(ctx, series):
    out = []
    for s in series:
        v = s.values[~np.isnan(s.values)]
        base = float(v.min()) if len(v) else 0.0
        out.append(s.with_values(s.values - base, f"offsetToZero({s.name})"))
    return out


def _remove_by(series, pred, name):
    out = []
    for s in series:
        v = s.values.copy()
        v[pred(s, v)] = NAN
        out.append(s.with_values(v, f"{name}({s.name})"))
    return out


@_func("removeAboveValue")
def _remove_above_value(ctx, series, n):
    return _remove_by(series, lambda s, v: v > n, "removeAboveValue")


@_func("removeBelowValue")
def _remove_below_value(ctx, series, n):
    return _remove_by(series, lambda s, v: v < n, "removeBelowValue")


@_func("removeAbovePercentile")
def _remove_above_pct(ctx, series, n):
    def pred(s, v):
        p = _percentile(v, float(n))
        return v > p if p is not None else np.zeros(len(v), bool)
    return _remove_by(series, pred, "removeAbovePercentile")


@_func("removeBelowPercentile")
def _remove_below_pct(ctx, series, n):
    def pred(s, v):
        p = _percentile(v, float(n))
        return v < p if p is not None else np.zeros(len(v), bool)
    return _remove_by(series, pred, "removeBelowPercentile")


@_func("removeEmptySeries")
def _remove_empty(ctx, series, xFilesFactor=0):
    out = []
    for s in series:
        frac = float((~np.isnan(s.values)).mean()) if len(s.values) else 0.0
        if frac > 0 and frac >= float(xFilesFactor):
            out.append(s)
    return out


@_func("round")
def _round(ctx, series, precision=0):
    p = int(precision)
    return [s.with_values(np.round(s.values, p),
                          f"round({s.name},{p})") for s in series]


@_func("scaleToSeconds")
def _scale_to_seconds(ctx, series, seconds):
    return [
        s.with_values(s.values * (float(seconds) / (s.step_nanos / 1e9)),
                      f"scaleToSeconds({s.name},{float(seconds):g})")
        for s in series
    ]


@_func("smartSummarize")
def _smart_summarize(ctx, series, interval, func="sum"):
    # summarize with buckets aligned to the interval epoch boundary:
    # the leading partial bucket is trimmed so every bucket starts on a
    # multiple of the interval.
    nanos = _duration_nanos(str(interval))
    out = []
    for s in series:
        off = s.start_nanos % nanos
        lead = 0 if off == 0 else int((nanos - off) // s.step_nanos)
        trimmed = replace(s, values=s.values[lead:],
                          start_nanos=s.start_nanos + lead * s.step_nanos)
        summ = _summarize(ctx, [trimmed], interval, func)
        if summ:
            out.append(replace(
                summ[0],
                name=f'smartSummarize({s.name},"{interval}","{func}")'))
    return out


@_func("sortBy")
def _sort_by(ctx, series, func="average", reverse=False):
    what = _stat_name(func)
    scored = [(s, _series_stat(s, what)) for s in series]
    scored = [(s, v if v is not None else -math.inf) for s, v in scored]
    scored.sort(key=lambda sv: sv[1], reverse=bool(reverse))
    return [s for s, _ in scored]


@_func("sortByMinima")
def _sort_by_minima(ctx, series):
    return sorted(
        series,
        key=lambda s: (v if (v := _series_stat(s, "min")) is not None
                       else math.inf),
    )


@_func("sortByTotal")
def _sort_by_total(ctx, series):
    def total(s):
        v = s.values[~np.isnan(s.values)]
        return float(v.sum()) if len(v) else -math.inf
    return sorted(series, key=total, reverse=True)


@_func("squareRoot")
def _square_root(ctx, series):
    with np.errstate(all="ignore"):
        return [s.with_values(np.where(s.values >= 0, np.sqrt(s.values), NAN),
                              f"squareRoot({s.name})") for s in series]


@_func("substr")
def _substr(ctx, series, start=0, stop=0):
    out = []
    for s in series:
        comps = s.name.split(".")
        sl = comps[int(start): int(stop)] if int(stop) != 0 else comps[int(start):]
        out.append(s.with_values(s.values, ".".join(sl)))
    return out


def _sustained(series, duration, pred, name):
    """Keep values only inside runs satisfying ``pred`` for at least
    ``duration`` (shared body of sustainedAbove/Below)."""
    nanos = _duration_nanos(str(duration))
    out = []
    for s in series:
        k = max(1, int(nanos // s.step_nanos))
        v = s.values
        ok = pred(v)
        res = np.full_like(v, NAN)
        run = 0
        for i in range(len(v)):
            run = run + 1 if ok[i] else 0
            if run >= k:
                res[i - run + 1: i + 1] = v[i - run + 1: i + 1]
        out.append(s.with_values(res, f"{name}({s.name})"))
    return out


@_func("sustainedAbove")
def _sustained_above(ctx, series, value, duration):
    return _sustained(series, duration, lambda v: v >= value,
                      "sustainedAbove")


@_func("sustainedBelow")
def _sustained_below(ctx, series, value, duration):
    return _sustained(series, duration, lambda v: v <= value,
                      "sustainedBelow")


@_func("transformNull")
def _transform_null(ctx, series, default=0):
    return [
        s.with_values(np.where(np.isnan(s.values), float(default), s.values),
                      f"transformNull({s.name},{float(default):g})")
        for s in series
    ]


@_func("groupByNodes")
def _group_by_nodes(ctx, series, func, *nodes):
    groups: dict[str, list] = {}
    for s in series:
        comps = s.path.split(".")
        key = ".".join(
            comps[int(n)] if int(n) < len(comps) else "" for n in nodes
        )
        groups.setdefault(key, []).append(s)
    op = _AGG_OPS.get(str(func).removesuffix("Series"))
    if op is None:
        raise ParseError(f"groupByNodes: unknown func {func!r}")
    out = []
    for key in sorted(groups):
        combined = _combine(groups[key], op, key)
        if combined:
            out.append(combined[0].with_values(combined[0].values, key))
    return out


def _with_wildcards(series, positions):
    groups: dict[str, list] = {}
    for s in series:
        comps = s.path.split(".")
        key = ".".join(
            c for i, c in enumerate(comps) if i not in positions
        )
        groups.setdefault(key, []).append(s)
    return groups


@_func("aggregateWithWildcards")
def _aggregate_with_wildcards(ctx, series, func, *positions):
    op = _AGG_OPS.get(str(func).removesuffix("Series"))
    if op is None:
        raise ParseError(f"aggregateWithWildcards: unknown func {func!r}")
    pos = {int(p) for p in positions}
    out = []
    for key in sorted(groups := _with_wildcards(series, pos)):
        combined = _combine(groups[key], op, key)
        if combined:
            out.append(combined[0].with_values(combined[0].values, key))
    return out


@_func("sumSeriesWithWildcards")
def _sum_with_wildcards(ctx, series, *positions):
    return _aggregate_with_wildcards(ctx, series, "sum", *positions)


@_func("averageSeriesWithWildcards")
def _avg_with_wildcards(ctx, series, *positions):
    return _aggregate_with_wildcards(ctx, series, "average", *positions)


@_func("multiplySeriesWithWildcards")
def _mul_with_wildcards(ctx, series, *positions):
    return _aggregate_with_wildcards(ctx, series, "multiply", *positions)


@_func("weightedAverage")
def _weighted_average(ctx, avg_series, weight_series, *nodes):
    def key_of(s):
        comps = s.path.split(".")
        return ".".join(
            comps[int(n)] if int(n) < len(comps) else "" for n in nodes
        )
    weights = {key_of(s): s for s in weight_series}
    num = None
    den = None
    for s in avg_series:
        w = weights.get(key_of(s))
        if w is None:
            continue
        prod = np.where(np.isnan(s.values) | np.isnan(w.values), 0.0,
                        s.values * w.values)
        wv = np.where(np.isnan(s.values) | np.isnan(w.values), 0.0, w.values)
        num = prod if num is None else num + prod
        den = wv if den is None else den + wv
    if num is None:
        return []
    with np.errstate(all="ignore"):
        res = np.where(den == 0, NAN, num / den)
    return [avg_series[0].with_values(res, "weightedAverage()")]


@_func("aggregateLine")
def _aggregate_line(ctx, series, func="average"):
    what = _stat_name(func)
    out = []
    for s in series:
        stat = _series_stat(s, what)
        if stat is None:
            continue
        out.append(s.with_values(np.full_like(s.values, stat),
                                 f"aggregateLine({s.name},{stat:g})"))
    return out


# ---------------------------------------------------------------------------
# Evaluator + render entry points
# ---------------------------------------------------------------------------


_DUR_RE = re.compile(r"^-?(\d+)(s|min|h|d|w|y|mon)$")
_DUR_NANOS = {"s": 10**9, "min": 60 * 10**9, "h": 3600 * 10**9,
              "d": 86400 * 10**9, "w": 7 * 86400 * 10**9,
              "mon": 30 * 86400 * 10**9, "y": 365 * 86400 * 10**9}


def _duration_nanos(s: str) -> int:
    s = s.strip()
    m = _DUR_RE.match(s)
    if not m:
        raise ParseError(f"bad duration {s!r}")
    nanos = int(m.group(1)) * _DUR_NANOS[m.group(2)]
    # the sign matters: timeShift(x, "-1h") shifts forward, "1h" back
    return -nanos if s.startswith("-") else nanos


def parse_graphite_time(s: str, now_nanos: int) -> int:
    """Epoch seconds, 'now', or relative '-1h' (reference
    graphite/ts parsing, minimal form)."""
    s = s.strip()
    if s == "now" or s == "":
        return now_nanos
    if s.startswith("-"):
        return now_nanos - _duration_nanos(s[1:])
    return int(float(s) * 1e9)


@dataclass
class _Ctx:
    storage: GraphiteStorage
    start: int
    end: int
    step: int
    engine: "GraphiteEngine | None" = None  # re-entrant evaluation


class GraphiteEngine:
    """Parse + evaluate render targets (reference native/engine.go)."""

    def __init__(self, storage: GraphiteStorage):
        self.storage = storage

    def render(self, target: str, start_nanos: int, end_nanos: int,
               step_nanos: int) -> list[GraphiteSeries]:
        ast = parse_target(target)
        ctx = _Ctx(self.storage, start_nanos, end_nanos, step_nanos, self)
        out = self._eval(ast, ctx)
        if not isinstance(out, list):
            raise ParseError(f"target does not evaluate to series: {target!r}")
        return out

    def _eval(self, node, ctx: _Ctx):
        # cancellation point between render-pipeline nodes (the
        # graphite entry rides the same deadline as PromQL queries)
        xdeadline.check_current("render eval")
        if isinstance(node, PathExpr):
            return ctx.storage.fetch(node.path, ctx.start, ctx.end, ctx.step)
        if isinstance(node, Call):
            if node.name == "timeShift":
                if len(node.args) != 2:
                    raise ParseError("timeShift(expr, shift) takes 2 args")
                shift = node.args[1]
                nanos = _duration_nanos(str(shift))
                shifted = replace(ctx, start=ctx.start - nanos,
                                  end=ctx.end - nanos)
                inner = self._eval(node.args[0], shifted)
                return [
                    replace(s, start_nanos=ctx.start,
                            name=f'timeShift({s.name},"{shift}")')
                    for s in inner
                ]
            fn = _FUNCS.get(node.name)
            if fn is None:
                raise ParseError(f"unsupported function {node.name!r}")
            args = [self._eval(a, ctx) for a in node.args]
            kwargs = {k: self._eval(v, ctx) for k, v in node.kwargs}
            # series-list args may come from nested calls/paths; scalars
            # pass through
            return fn(ctx, *args, **kwargs)
        return node  # number / string / bool


def supported_functions() -> list[str]:
    return sorted(_FUNCS)


# ---------------------------------------------------------------------------
# Round-4 breadth: functions moved out of the out-of-scope set.
# ---------------------------------------------------------------------------


@_func("randomWalkFunction", "randomWalk")
def _random_walk(ctx, name, step=None):
    """Synthetic random-walk series over the render window (graphite-web
    functions.py randomWalkFunction).  Seeded from the name so repeated
    renders of one target are stable — a test-friendly divergence from
    graphite's unseeded random.random().  ``step`` defaults to the
    RENDER step so the series stays grid-compatible with fetched ones
    (this engine does not LCM-normalize mixed grids), and the point
    count honors the same OOM cap as every fetch path."""
    import zlib

    step_nanos = (ctx.step if step is None
                  else max(1, int(step)) * 10**9)
    n = max(1, int((ctx.end - ctx.start) // step_nanos))
    cap = (ctx.storage.max_points if ctx.storage is not None
           else 100_000)
    if n > cap:
        raise ParseError(f"render grid too large: {n} > {cap} points")
    rng = np.random.default_rng(zlib.crc32(str(name).encode()))
    vals = np.cumsum(rng.random(n) - 0.5)
    return [GraphiteSeries(str(name), str(name), vals, step_nanos, ctx.start)]


@_func("timeSlice")
def _time_slice(ctx, series, start_str, end_str="now"):
    """Null out values outside [startSliceAt, endSliceAt] (graphite-web
    timeSlice); the window parses with graphite's relative time syntax
    against the render end (render() always sets it — no wall-clock
    fallback, which would make epoch-0 test windows nondeterministic)."""
    now = ctx.end
    lo = parse_graphite_time(str(start_str), now)
    hi = parse_graphite_time(str(end_str), now)
    out = []
    for s in series:
        t = s.start_nanos + np.arange(len(s.values), dtype=np.int64) * s.step_nanos
        v = np.where((t >= lo) & (t <= hi), s.values, NAN)
        out.append(s.with_values(
            v, f'timeSlice({s.name},"{start_str}","{end_str}")'))
    return out


def _fmt_legend(v: float) -> str:
    return "None" if np.isnan(v) else f"{v:g}"


# Per-series legend statistics (shared by cactiStyle/legendValue):
# _nan_agg silences the all-NaN-slice warning; the NaN result is right.
_LEGEND_FNS = {
    "avg": _nan_agg(np.nanmean),
    "average": _nan_agg(np.nanmean),
    "min": _nan_agg(np.nanmin),
    "max": _nan_agg(np.nanmax),
    "last": lambda v: (v[~np.isnan(v)][-1] if (~np.isnan(v)).any()
                       else np.nan),
    "total": _nan_agg(np.nansum),
}


@_func("cactiStyle")
def _cacti_style(ctx, series, system=None, units=None):
    """Append Current/Max/Min to each alias (graphite-web cactiStyle;
    the si-system scaling of the reference renderer is presentational
    and out of scope — raw values render instead)."""
    suffix_units = f" {units}" if units else ""
    out = []
    for s in series:
        cur = _LEGEND_FNS["last"](s.values)
        name = (f"{s.name} Current:{_fmt_legend(cur)}{suffix_units} "
                f"Max:{_fmt_legend(_LEGEND_FNS['max'](s.values))}"
                f"{suffix_units} "
                f"Min:{_fmt_legend(_LEGEND_FNS['min'](s.values))}"
                f"{suffix_units}")
        out.append(s.with_values(s.values, name))
    return out


@_func("legendValue")
def _legend_value(ctx, series, *value_types):
    """Append requested statistics to each alias (graphite-web
    legendValue).  A trailing "si"/"binary" system argument is accepted
    (graphite-web uses it to pick unit formatting; values render
    unscaled here)."""
    value_types = list(value_types)
    if value_types and str(value_types[-1]) in ("si", "binary"):
        value_types.pop()  # formatting-system hint, not a value type
    out = []
    for s in series:
        name = s.name
        for vt in value_types:
            fn_ = _LEGEND_FNS.get(str(vt))
            if fn_ is None:
                name += " (?)"  # graphite-web degrades, never errors
                continue
            name += f" ({vt}: {_fmt_legend(fn_(s.values))})"
        out.append(s.with_values(s.values, name))
    return out


@_func("dashed")
def _dashed(ctx, series, dash_length=5):
    # A render-style hint: data passes through under the dashed() alias
    # (the drawing itself belongs to a renderer this API does not have).
    return [s.with_values(s.values, f"dashed({s.name},{dash_length})")
            for s in series]


@_func("useSeriesAbove")
def _use_series_above(ctx, series, value, search, replace):
    """For every series whose max exceeds ``value``, fetch the series
    whose path substitutes search->replace (graphite-web useSeriesAbove
    applies ``re.sub`` — regex patterns work; a series whose
    substitution leaves the path unchanged is skipped rather than
    re-fetched as itself)."""
    rx = re.compile(str(search))
    out = []
    for s in series:
        if (~np.isnan(s.values)).any() and np.nanmax(s.values) > value:
            newpath = rx.sub(str(replace), s.path)
            if newpath == s.path:
                continue
            for hit in ctx.storage.fetch(newpath, ctx.start, ctx.end,
                                         ctx.step):
                out.append(hit)
    return out


@_func("applyByNode")
def _apply_by_node(ctx, series, node_num, template, new_name=None):
    """Re-evaluate a template per distinct node prefix (graphite-web
    applyByNode): for each unique first-(node+1)-components prefix of
    the input paths, render ``template`` with '%' replaced by the
    prefix; ``newName`` (also %-substituted) renames the results."""
    if ctx.engine is None:
        raise ParseError("applyByNode needs an engine-bound context")
    n = int(node_num)
    prefixes = []
    for s in series:
        parts = s.path.split(".")
        if len(parts) <= n:
            continue
        pre = ".".join(parts[: n + 1])
        if pre not in prefixes:
            prefixes.append(pre)
    out = []
    for pre in prefixes:
        target = str(template).replace("%", pre)
        for r in ctx.engine._eval(parse_target(target), ctx):
            if new_name is not None:
                r = r.with_values(r.values,
                                  str(new_name).replace("%", pre))
            out.append(r)
    return out


# ---------------------------------------------------------------------------
# Holt-Winters forecasting (graphite-web functions.py holtWintersAnalysis:
# triple exponential smoothing, one-day season, alpha=gamma=0.1,
# beta=0.0035).  The sequential reference loop is mirrored exactly —
# including its None-breaks-the-math restart behavior (NaN here).
# ---------------------------------------------------------------------------

_HW_ALPHA = 0.1
_HW_GAMMA = 0.1
_HW_BETA = 0.0035


def _holt_winters_analysis(values: np.ndarray, step_nanos: int):
    """Returns (predictions, deviations) float64 arrays (NaN = None)."""
    season = max(1, int((24 * 3600 * 10**9) // max(1, step_nanos)))
    n = len(values)
    intercepts: list = []
    slopes: list = []
    seasonals: list = []
    predictions = np.full(n, NAN)
    deviations = np.full(n, NAN)

    def last_seasonal(i):
        # bounds-checked both ways: at season=1 (steps >= 1 day) the
        # lookahead last_seasonal(i+1) would otherwise index a slot not
        # yet appended (a latent IndexError in the graphite-web loop)
        j = i - season
        return seasonals[j] if 0 <= j < len(seasonals) else 0.0

    def last_deviation(i):
        j = i - season
        return deviations[j] if j >= 0 and not math.isnan(deviations[j]) else 0.0

    next_pred = NAN
    for i in range(n):
        actual = values[i]
        if math.isnan(actual):
            # missing input values break all the math: restart
            intercepts.append(None)
            slopes.append(0.0)
            seasonals.append(0.0)
            predictions[i] = next_pred
            deviations[i] = 0.0
            next_pred = NAN
            continue
        if i == 0:
            last_intercept = actual
            last_slope = 0.0
            prediction = actual  # seed: first prediction = first actual
        else:
            last_intercept = intercepts[-1]
            last_slope = slopes[-1]
            if last_intercept is None:
                last_intercept = actual
            prediction = next_pred
        ls = last_seasonal(i)
        next_ls = last_seasonal(i + 1)
        lsd = last_deviation(i)
        intercept = (_HW_ALPHA * (actual - ls)
                     + (1 - _HW_ALPHA) * (last_intercept + last_slope))
        slope = (_HW_BETA * (intercept - last_intercept)
                 + (1 - _HW_BETA) * last_slope)
        seasonal = (_HW_GAMMA * (actual - intercept)
                    + (1 - _HW_GAMMA) * ls)
        next_pred = intercept + slope + next_ls
        pred_for_dev = 0.0 if math.isnan(prediction) else prediction
        deviation = (_HW_GAMMA * abs(actual - pred_for_dev)
                     + (1 - _HW_GAMMA) * lsd)
        intercepts.append(intercept)
        slopes.append(slope)
        seasonals.append(seasonal)
        predictions[i] = prediction
        deviations[i] = deviation
    return predictions, deviations


def _hw_bootstrapped(ctx, series, bootstrap_interval):
    """(bootstrapped GraphiteSeries, trim point count) per input: the
    series re-fetched with `bootstrapInterval` of leading history
    (graphite-web previewSeconds) so the seasonal state is warm when
    the render window starts.  Computed series that cannot re-fetch
    (path no longer a plain metric) analyze the window alone."""
    nanos = _duration_nanos(str(bootstrap_interval))
    out = []
    for s in series:
        try:
            fetched = ctx.storage.fetch(
                s.path, ctx.start - nanos, ctx.end, ctx.step
            ) if ctx.storage is not None else []
        except ParseError:
            # e.g. the extended grid exceeds the render cap: analyze
            # the window alone rather than failing the whole render
            fetched = []
        if len(fetched) == 1:
            boot = fetched[0]
            # round UP: a bootstrap interval that is not a step
            # multiple must not leave the forecast shifted off the
            # render grid (the first on-grid point is the one at or
            # after ctx.start)
            trim = int(-(-(ctx.start - boot.start_nanos)
                         // boot.step_nanos))
            out.append((boot, max(0, trim), s))
        else:
            out.append((s, 0, s))
    return out


def _hw_forecast_parts(ctx, series, bootstrap_interval):
    for boot, trim, orig in _hw_bootstrapped(ctx, series, bootstrap_interval):
        pred, dev = _holt_winters_analysis(boot.values, boot.step_nanos)
        start = boot.start_nanos + trim * boot.step_nanos
        yield orig, boot, trim, start, pred, dev


@_func("holtWintersForecast")
def _holt_winters_forecast(ctx, series, bootstrap_interval="7d"):
    out = []
    for orig, boot, trim, start, pred, dev in _hw_forecast_parts(
            ctx, series, bootstrap_interval):
        out.append(GraphiteSeries(
            f"holtWintersForecast({orig.name})", orig.path,
            pred[trim:], boot.step_nanos, start))
    return out


@_func("holtWintersConfidenceBands")
def _holt_winters_confidence_bands(ctx, series, delta=3,
                                   bootstrap_interval="7d"):
    out = []
    for orig, boot, trim, start, pred, dev in _hw_forecast_parts(
            ctx, series, bootstrap_interval):
        upper = pred[trim:] + delta * dev[trim:]
        lower = pred[trim:] - delta * dev[trim:]
        out.append(GraphiteSeries(
            f"holtWintersConfidenceUpper({orig.name})", orig.path,
            upper, boot.step_nanos, start))
        out.append(GraphiteSeries(
            f"holtWintersConfidenceLower({orig.name})", orig.path,
            lower, boot.step_nanos, start))
    return out


@_func("holtWintersAberration")
def _holt_winters_aberration(ctx, series, delta=3, bootstrap_interval="7d"):
    out = []
    for orig, boot, trim, start, pred, dev in _hw_forecast_parts(
            ctx, series, bootstrap_interval):
        upper = pred[trim:] + delta * dev[trim:]
        lower = pred[trim:] - delta * dev[trim:]
        actual = boot.values[trim:]
        ab = np.where(
            np.isnan(actual), 0.0,
            np.where(actual > upper, actual - upper,
                     np.where(actual < lower, actual - lower, 0.0)))
        ab = np.where(np.isnan(upper) | np.isnan(lower), 0.0, ab)
        out.append(GraphiteSeries(
            f"holtWintersAberration({orig.name})", orig.path,
            ab, boot.step_nanos, start))
    return out
