"""Remote query federation: the cross-coordinator fetch protocol.

Equivalent of the reference's gRPC query federation (`src/query/remote`
— rpcpb client/server letting one coordinator query another region's
storage, plugged into fanout as a remote store).  gRPC collapses to the
framework's framed TCP protocol (msg/protocol.py): a QUERY_FETCH frame
carries (name, matchers, start, end); the QUERY_RESULT frame carries
the matched series (tags + raw points).  `RemoteStorage` implements the
same `fetch_raw` seam as DatabaseStorage, so it drops straight into
`FanoutSource` — cross-region federation is just another fanout source
with a coarser typical resolution.
"""

from __future__ import annotations

import socket
import socketserver
import struct
import threading

import numpy as np

from m3_tpu.msg import protocol as wire
from m3_tpu.query.block import RawBlock, SeriesMeta

QUERY_FETCH = 8
QUERY_RESULT = 9


# -- payload codecs ---------------------------------------------------------


def encode_fetch(name: bytes | None, matchers, start: int, end: int) -> bytes:
    parts = [struct.pack("<qq", start, end)]
    parts.append(struct.pack("<H", len(name) if name is not None else 0xFFFF))
    if name is not None:
        parts.append(name)
    parts.append(struct.pack("<H", len(matchers)))
    for m in matchers:
        op = m.op.encode()
        parts.append(struct.pack("<BHH", len(op), len(m.name), len(m.value)))
        parts.append(op)
        parts.append(m.name)
        parts.append(m.value)
    return b"".join(parts)


def decode_fetch(raw: bytes):
    from m3_tpu.query.promql import LabelMatcher

    start, end = struct.unpack_from("<qq", raw, 0)
    pos = 16
    (nlen,) = struct.unpack_from("<H", raw, pos)
    pos += 2
    name = None
    if nlen != 0xFFFF:
        name = raw[pos : pos + nlen]
        pos += nlen
    (nm,) = struct.unpack_from("<H", raw, pos)
    pos += 2
    matchers = []
    for _ in range(nm):
        ol, nl, vl = struct.unpack_from("<BHH", raw, pos)
        pos += 5
        op = raw[pos : pos + ol].decode()
        pos += ol
        mname = raw[pos : pos + nl]
        pos += nl
        value = raw[pos : pos + vl]
        pos += vl
        matchers.append(LabelMatcher(mname, op, value))
    return name, tuple(matchers), start, end


def encode_result(block: RawBlock) -> bytes:
    parts = [struct.pack("<I", len(block.series))]
    for i, meta in enumerate(block.series):
        tags = list(meta.tags)
        parts.append(struct.pack("<H", len(tags)))
        for k, v in tags:
            parts.append(struct.pack("<HH", len(k), len(v)))
            parts.append(k)
            parts.append(v)
        n = int(block.counts[i])
        parts.append(struct.pack("<I", n))
        parts.append(block.ts[i, :n].astype("<i8").tobytes())
        parts.append(block.values[i, :n].astype("<f8").tobytes())
    return b"".join(parts)


def decode_result(raw: bytes) -> RawBlock:
    (ns,) = struct.unpack_from("<I", raw, 0)
    pos = 4
    pts, metas = [], []
    for _ in range(ns):
        (ntags,) = struct.unpack_from("<H", raw, pos)
        pos += 2
        tags = []
        for _ in range(ntags):
            lk, lv = struct.unpack_from("<HH", raw, pos)
            pos += 4
            k = raw[pos : pos + lk]
            pos += lk
            v = raw[pos : pos + lv]
            pos += lv
            tags.append((k, v))
        (n,) = struct.unpack_from("<I", raw, pos)
        pos += 4
        ts = np.frombuffer(raw, "<i8", n, pos)
        pos += 8 * n
        vals = np.frombuffer(raw, "<f8", n, pos)
        pos += 8 * n
        metas.append(SeriesMeta(tuple(tags)))
        pts.append(list(zip(ts.tolist(), vals.tolist())))
    return RawBlock.from_lists(pts, metas)


# -- server -----------------------------------------------------------------


class _QueryHandler(socketserver.BaseRequestHandler):
    def handle(self):
        srv = self.server
        sock = self.request
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        while True:
            try:
                frame = wire.recv_frame(sock)
            except (wire.ProtocolError, OSError):
                return
            if frame is None or frame[0] != QUERY_FETCH:
                return
            try:
                name, matchers, start, end = decode_fetch(frame[1])
                block = srv.storage.fetch_raw(name, matchers, start, end)
                wire.send_frame(sock, QUERY_RESULT, encode_result(block))
            except Exception as e:  # noqa: BLE001 — report, don't die
                try:
                    wire.send_frame(sock, wire.ERROR, str(e).encode())
                except OSError:
                    return


class QueryServer(socketserver.ThreadingTCPServer):
    """Serves fetch_raw over TCP (reference query/remote/server.go)."""

    allow_reuse_address = True
    daemon_threads = True

    def __init__(self, storage, host: str = "127.0.0.1", port: int = 0):
        self.storage = storage
        super().__init__((host, port), _QueryHandler)

    @property
    def port(self) -> int:
        return self.server_address[1]


def serve_query_background(storage, host: str = "127.0.0.1",
                           port: int = 0) -> QueryServer:
    srv = QueryServer(storage, host, port)
    t = threading.Thread(target=srv.serve_forever, daemon=True)
    t.start()
    return srv


# -- client -----------------------------------------------------------------


class RemoteStorage:
    """fetch_raw over the wire: a drop-in fanout source
    (reference query/remote/client.go wrapped as a remote store)."""

    def __init__(self, address, timeout_s: float = 30.0):
        self.address = address
        self.timeout_s = timeout_s
        self._sock: socket.socket | None = None
        self._lock = threading.Lock()

    def _connect(self) -> socket.socket:
        if self._sock is None:
            self._sock = wire.connect(self.address, timeout=self.timeout_s)
        return self._sock

    def fetch_raw(self, name, matchers, start_nanos, end_nanos) -> RawBlock:
        payload = encode_fetch(name, matchers, start_nanos, end_nanos)
        with self._lock:
            try:
                sock = self._connect()
                wire.send_frame(sock, QUERY_FETCH, payload)
                frame = wire.recv_frame(sock)
            except (OSError, wire.ProtocolError):
                # one reconnect attempt (server restarts are routine)
                self.close()
                sock = self._connect()
                wire.send_frame(sock, QUERY_FETCH, payload)
                frame = wire.recv_frame(sock)
        if frame is None:
            raise ConnectionError("remote query peer closed connection")
        ftype, body = frame
        if ftype == wire.ERROR:
            raise RuntimeError(f"remote query failed: {body.decode()}")
        if ftype != QUERY_RESULT:
            raise wire.ProtocolError(f"unexpected frame type {ftype}")
        return decode_result(body)

    def close(self) -> None:
        if self._sock is not None:
            try:
                self._sock.close()
            finally:
                self._sock = None
