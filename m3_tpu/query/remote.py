"""Remote query federation: the cross-coordinator fetch protocol.

Equivalent of the reference's gRPC query federation (`src/query/remote`
— rpcpb client/server letting one coordinator query another region's
storage, plugged into fanout as a remote store).  gRPC collapses to the
framework's framed TCP protocol (msg/protocol.py): a QUERY_FETCH frame
carries (name, matchers, start, end, remaining-deadline-ms); the
QUERY_RESULT frame carries the matched series (tags + raw points).
`RemoteStorage` implements the same `fetch_raw` seam as
DatabaseStorage, so it drops straight into `FanoutSource` —
cross-region federation is just another fanout source with a coarser
typical resolution.

Overload contract (the read-path mirror of PR 1's wire retries):

* the query's **deadline** rides the frame as a relative ms budget, so
  the server stops work for a client that already gave up, and every
  per-call socket timeout derives from ``remaining()`` instead of a
  fixed constant;
* server-side errors cross the wire **typed** (`TypeName: message`,
  like the rpc layer) — a remote ``QueryLimitExceeded`` surfaces as a
  client-side ``QueryLimitExceeded`` (HTTP 429), a remote deadline trip
  as ``DeadlineExceeded`` (504), never a generic ``RuntimeError`` 500;
* a small **per-peer connection pool** replaces the old single
  socket + lock, so concurrent fanout fetches never serialize behind —
  or wedge on — one slow peer's round-trip;
* every fetch flows through the peer's shared **circuit breaker**
  (x/breaker): a dead region fails fast instead of eating the whole
  deadline on every query.

The ``query.fetch`` faultpoint fires server-side in the storage adapter
(`query/storage_adapter.py`) so delay/error injection covers local and
federated reads through one point.
"""

from __future__ import annotations

import socket
import socketserver
import struct
import threading

import numpy as np

from m3_tpu.instrument import tracing
from m3_tpu.instrument.tracing import NOOP_TRACER, TraceContext, Tracepoint
from m3_tpu.msg import protocol as wire
from m3_tpu.query.block import RawBlock, SeriesMeta
from m3_tpu.x import deadline as xdeadline
from m3_tpu.x.breaker import CircuitBreaker
from m3_tpu.x.deadline import Deadline, DeadlineExceeded

QUERY_FETCH = 8
QUERY_RESULT = 9


# -- payload codecs ---------------------------------------------------------


def encode_fetch(name: bytes | None, matchers, start: int, end: int,
                 deadline_ms: int = -1, trace_ctx: bytes = b"") -> bytes:
    parts = [struct.pack("<qq", start, end)]
    parts.append(struct.pack("<H", len(name) if name is not None else 0xFFFF))
    if name is not None:
        parts.append(name)
    parts.append(struct.pack("<H", len(matchers)))
    for m in matchers:
        op = m.op.encode()
        parts.append(struct.pack("<BHH", len(op), len(m.name), len(m.value)))
        parts.append(op)
        parts.append(m.name)
        parts.append(m.value)
    # trailer: the query's REMAINING budget (relative ms; -1 = none) so
    # the server stops work once the client's deadline is spent, then —
    # for sampled queries only — the caller's packed TraceContext (the
    # same grow-at-the-tail pattern the deadline trailer used: old
    # decoders read their prefix and ignore the rest)
    parts.append(struct.pack("<q", deadline_ms))
    parts.append(trace_ctx)
    return b"".join(parts)


def decode_fetch(raw: bytes):
    from m3_tpu.query.promql import LabelMatcher

    start, end = struct.unpack_from("<qq", raw, 0)
    pos = 16
    (nlen,) = struct.unpack_from("<H", raw, pos)
    pos += 2
    name = None
    if nlen != 0xFFFF:
        name = raw[pos : pos + nlen]
        pos += nlen
    (nm,) = struct.unpack_from("<H", raw, pos)
    pos += 2
    matchers = []
    for _ in range(nm):
        ol, nl, vl = struct.unpack_from("<BHH", raw, pos)
        pos += 5
        op = raw[pos : pos + ol].decode()
        pos += ol
        mname = raw[pos : pos + nl]
        pos += nl
        value = raw[pos : pos + vl]
        pos += vl
        matchers.append(LabelMatcher(mname, op, value))
    deadline_ms = -1
    if pos + 8 <= len(raw):  # pre-deadline encoders have no trailer
        (deadline_ms,) = struct.unpack_from("<q", raw, pos)
        pos += 8
    tctx = None
    if pos + TraceContext.WIRE_SIZE <= len(raw):  # sampled caller
        tctx = TraceContext.from_wire(raw, pos)
    return name, tuple(matchers), start, end, deadline_ms, tctx


def encode_result(block: RawBlock) -> bytes:
    parts = [struct.pack("<I", len(block.series))]
    for i, meta in enumerate(block.series):
        tags = list(meta.tags)
        parts.append(struct.pack("<H", len(tags)))
        for k, v in tags:
            parts.append(struct.pack("<HH", len(k), len(v)))
            parts.append(k)
            parts.append(v)
        n = int(block.counts[i])
        parts.append(struct.pack("<I", n))
        parts.append(block.ts[i, :n].astype("<i8").tobytes())
        parts.append(block.values[i, :n].astype("<f8").tobytes())
    return b"".join(parts)


def decode_result(raw: bytes) -> RawBlock:
    (ns,) = struct.unpack_from("<I", raw, 0)
    pos = 4
    pts, metas = [], []
    for _ in range(ns):
        (ntags,) = struct.unpack_from("<H", raw, pos)
        pos += 2
        tags = []
        for _ in range(ntags):
            lk, lv = struct.unpack_from("<HH", raw, pos)
            pos += 4
            k = raw[pos : pos + lk]
            pos += lk
            v = raw[pos : pos + lv]
            pos += lv
            tags.append((k, v))
        (n,) = struct.unpack_from("<I", raw, pos)
        pos += 4
        ts = np.frombuffer(raw, "<i8", n, pos)
        pos += 8 * n
        vals = np.frombuffer(raw, "<f8", n, pos)
        pos += 8 * n
        metas.append(SeriesMeta(tuple(tags)))
        pts.append(list(zip(ts.tolist(), vals.tolist())))
    return RawBlock.from_lists(pts, metas)


# -- typed error mapping ----------------------------------------------------


def _decode_query_error(msg: str) -> Exception:
    """wire.ERROR payload (``TypeName: message``) → the exception to
    re-raise client-side.  Overload errors map through the shared
    ``x/deadline.decode_wire_error`` (one mapping for both wire
    protocols): a remote limit trip stays a ``QueryLimitExceeded``
    (HTTP 429) and a remote deadline trip a ``DeadlineExceeded``
    (504) — not a generic 500."""
    typed = xdeadline.decode_wire_error(msg)
    if typed is not None:
        return typed
    return RuntimeError(f"remote query failed: {msg}")


# -- server -----------------------------------------------------------------


class _QueryHandler(socketserver.BaseRequestHandler):
    def handle(self):
        srv = self.server
        sock = self.request
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        while True:
            try:
                frame = wire.recv_frame(sock)
            except (wire.ProtocolError, OSError):
                return
            if frame is None or frame[0] != QUERY_FETCH:
                return
            try:
                name, matchers, start, end, dl_ms, tctx = decode_fetch(
                    frame[1])
                # The client's remaining budget becomes THIS side's
                # deadline: storage stops work (typed) once the caller
                # has given up, instead of computing an answer nobody
                # will read.  A sampled caller's TraceContext binds the
                # same way, so the fetch span joins its trace.
                dl = Deadline(dl_ms / 1000.0) if dl_ms >= 0 else None
                with xdeadline.bind(dl), tracing.bind(tctx):
                    xdeadline.check_current("remote fetch")
                    span = (srv.tracer.start_span(
                        Tracepoint.REMOTE_FETCH, {"matchers": len(matchers)})
                        if tctx is not None else tracing.NOOP_SPAN)
                    with span:
                        block = srv.storage.fetch_raw(name, matchers,
                                                      start, end)
                wire.send_frame(sock, QUERY_RESULT, encode_result(block))
            except Exception as e:  # noqa: BLE001 — report, don't die
                try:
                    wire.send_frame(sock, wire.ERROR,
                                    f"{type(e).__name__}: {e}".encode()[:4096])
                except OSError:
                    return


class QueryServer(socketserver.ThreadingTCPServer):
    """Serves fetch_raw over TCP (reference query/remote/server.go)."""

    allow_reuse_address = True
    daemon_threads = True

    def __init__(self, storage, host: str = "127.0.0.1", port: int = 0,
                 tracer=None):
        self.storage = storage
        self.tracer = tracer if tracer is not None else NOOP_TRACER
        super().__init__((host, port), _QueryHandler)

    @property
    def port(self) -> int:
        return self.server_address[1]


def serve_query_background(storage, host: str = "127.0.0.1",
                           port: int = 0, tracer=None) -> QueryServer:
    srv = QueryServer(storage, host, port, tracer=tracer)
    t = threading.Thread(target=srv.serve_forever, daemon=True)
    t.start()
    return srv


# -- client -----------------------------------------------------------------


class _ConnPool:
    """Small per-peer socket pool: concurrent queries each check out
    their own connection instead of serializing behind one shared
    socket (the old single-socket + lock shape let one slow peer wedge
    EVERY concurrent fanout fetch).  ``max_idle`` bounds what a burst
    leaves warm; checkouts beyond it dial fresh and close on return."""

    def __init__(self, address, max_idle: int = 4):
        self.address = address
        self.max_idle = int(max_idle)
        self._mu = threading.Lock()
        self._idle: list[socket.socket] = []
        self._closed = False

    def get(self, cap_s: float, fresh: bool = False) -> socket.socket:
        # per-checkout timeout from the bound deadline's remaining
        # budget (capped): a pooled socket must never outlive its query.
        # ``fresh`` skips the idle list and dials — retry-after-failure
        # must not pop ANOTHER socket staled by the same peer restart.
        timeout_s = xdeadline.socket_timeout(cap_s)
        if not fresh:
            with self._mu:
                if self._idle:
                    sock = self._idle.pop()
                    sock.settimeout(timeout_s)
                    return sock
        return wire.connect(self.address, timeout=timeout_s)

    def put(self, sock: socket.socket) -> None:
        with self._mu:
            if not self._closed and len(self._idle) < self.max_idle:
                self._idle.append(sock)
                return
        try:
            sock.close()
        except OSError:
            pass

    def close(self) -> None:
        with self._mu:
            self._closed = True
            idle, self._idle = self._idle, []
        for sock in idle:
            try:
                sock.close()
            except OSError:
                pass


class RemoteStorage:
    """fetch_raw over the wire: a drop-in fanout source
    (reference query/remote/client.go wrapped as a remote store).

    Deadline-aware: per-call socket timeouts derive from the bound
    deadline's ``remaining()`` (capped by ``timeout_s``), the remaining
    budget rides the QUERY_FETCH frame, and a transport timeout with
    the budget spent surfaces as typed ``DeadlineExceeded``.  All calls
    flow through ``breaker`` (one per peer) so a dead region fails fast
    for every sharer at once."""

    def __init__(self, address, timeout_s: float = 30.0, pool_size: int = 4,
                 breaker: CircuitBreaker | None = None):
        self.address = tuple(address)
        self.timeout_s = timeout_s
        self.breaker = breaker
        self._pool = _ConnPool(self.address, max_idle=pool_size)

    @property
    def peer(self) -> str:
        return f"{self.address[0]}:{self.address[1]}"

    def _round_trip(self, payload: bytes, fresh: bool = False):
        """One send/recv on a pooled connection; the connection returns
        to the pool only after a clean exchange.  EOF mid-exchange (the
        peer restarted; send into the half-closed socket still
        "succeeds") raises ``ConnectionError`` — an ``OSError``, so the
        caller's one-reconnect retry fires — and the dead socket is
        closed, never re-pooled."""
        xdeadline.check_current("remote fetch")
        sock = self._pool.get(self.timeout_s, fresh=fresh)
        try:
            wire.send_frame(sock, QUERY_FETCH, payload)
            frame = wire.recv_frame(sock)
            if frame is None:
                raise ConnectionError("remote query peer closed connection")
        except BaseException:
            try:
                sock.close()
            except OSError:
                pass
            raise
        self._pool.put(sock)
        return frame

    def fetch_raw(self, name, matchers, start_nanos, end_nanos) -> RawBlock:
        # A budget already spent UPSTREAM (engine eval, another fanout
        # source) raises here, before the breaker: it is the query's
        # failure, not this peer's — a burst of slow queries must not
        # trip a healthy peer's breaker open.
        xdeadline.check_current("remote fetch")
        if self.breaker is not None:
            return self.breaker.call(
                lambda: self._fetch_raw(name, matchers, start_nanos,
                                        end_nanos))
        return self._fetch_raw(name, matchers, start_nanos, end_nanos)

    def _fetch_raw(self, name, matchers, start_nanos, end_nanos) -> RawBlock:
        dl = xdeadline.current()

        def payload() -> bytes:
            # encoded per attempt: the trailer must carry the budget
            # REMAINING at send time, not at first-attempt time; a
            # sampled query's bound TraceContext rides the tail
            return encode_fetch(name, matchers, start_nanos, end_nanos,
                                deadline_ms=xdeadline.remaining_ms(),
                                trace_ctx=tracing.current_wire())

        try:
            try:
                frame = self._round_trip(payload())
            except (OSError, wire.ProtocolError):
                # one reconnect attempt (server restarts are routine);
                # ``fresh`` dials a new socket — the restart that staled
                # this one staled every idle pooled socket too
                if dl is not None:
                    dl.check("remote fetch retry")
                frame = self._round_trip(payload(), fresh=True)
        except (socket.timeout, TimeoutError) as e:
            if dl is not None and dl.expired:
                raise dl.exceeded(
                    f"remote fetch {self.peer}: deadline exceeded") from e
            raise
        ftype, body = frame
        if ftype == wire.ERROR:
            raise _decode_query_error(body.decode())
        if ftype != QUERY_RESULT:
            raise wire.ProtocolError(f"unexpected frame type {ftype}")
        return decode_result(body)

    def close(self) -> None:
        self._pool.close()
