"""Multi-window multi-burn-rate SLO rules over self-monitored series.

The Google SRE-workbook alerting shape (chapter 5): an SLO with
objective ``o`` has an error budget ``1 - o``; a rule fires when the
measured bad-event ratio burns that budget faster than a threshold
``factor`` over BOTH a long window (sustained, low false-positive) and
a short window (still happening, fast reset).  Classic pairs:
``(1h, 5m, 14.4x)`` pages, ``(6h, 30m, 6x)`` tickets.

Rules here are declarative and PromQL-native: ``ratio`` is a PromQL
expression template computing the bad-event FRACTION over a window,
with the literal token ``{window}`` substituted per evaluation (plain
``str.replace`` — label matchers' braces are untouched, unlike
``str.format``).  The evaluator runs every rule's window queries
through the ordinary :class:`~m3_tpu.query.engine.Engine` instant path
over the ``_m3_selfmon`` namespace under ONE ``x/deadline`` budget —
a slow/expensive rule set degrades to a typed partial verdict, never a
stalled mediator tick.  Verdicts are cached for ``/health``'s ``slo``
section and mirrored as ``slo_burn{rule=...}`` gauges, which the next
selfmon scrape writes BACK into storage — burn history is itself one
PromQL query away (``max_over_time(m3tpu_slo_burn[1h])``).
"""

from __future__ import annotations

import dataclasses
import threading
import time
from typing import Iterable, List, Tuple

import numpy as np

from m3_tpu.core.config import ConfigError, parse_duration
from m3_tpu.x import deadline as xdeadline
from m3_tpu.x.deadline import Deadline, DeadlineExceeded

__all__ = ["BurnWindow", "SLORule", "SLOEvaluator", "rule_from_dict",
           "default_rules", "latency_ratio"]

_WINDOW_TOKEN = "{window}"


@dataclasses.dataclass(frozen=True)
class BurnWindow:
    """One (long, short, factor) pair: the rule fires on this pair when
    the ratio over BOTH windows is at least ``factor x error budget``."""

    long: str            # e.g. "1h"
    short: str           # e.g. "5m"
    factor: float        # burn-rate threshold (x budget)

    def __post_init__(self):
        for f in ("long", "short"):
            try:
                parse_duration(getattr(self, f))
            except ConfigError as e:
                raise ValueError(f"burn window {f}: {e}") from None
        if parse_duration(self.short) > parse_duration(self.long):
            raise ValueError(
                f"burn window short {self.short!r} exceeds long {self.long!r}")
        if self.factor <= 0:
            raise ValueError("burn window factor must be > 0")


# The SRE-workbook default ladder: page on fast burn, ticket on slow.
DEFAULT_WINDOWS: Tuple[BurnWindow, ...] = (
    BurnWindow("1h", "5m", 14.4),
    BurnWindow("6h", "30m", 6.0),
)


@dataclasses.dataclass(frozen=True)
class SLORule:
    """One burn-rate rule: ``ratio`` computes the bad-event fraction
    over a ``{window}``; the objective fixes the budget it burns."""

    name: str
    objective: float                       # e.g. 0.999
    ratio: str                             # PromQL template with {window}
    windows: Tuple[BurnWindow, ...] = DEFAULT_WINDOWS

    def __post_init__(self):
        if not self.name:
            raise ValueError("SLO rule needs a name")
        if not (0.0 < self.objective < 1.0):
            raise ValueError(
                f"rule {self.name}: objective must be in (0, 1), "
                f"got {self.objective}")
        if _WINDOW_TOKEN not in self.ratio:
            raise ValueError(
                f"rule {self.name}: ratio template must contain "
                f"'{_WINDOW_TOKEN}'")
        if not self.windows:
            raise ValueError(f"rule {self.name}: at least one burn window")

    @property
    def budget(self) -> float:
        return 1.0 - self.objective

    def query(self, window: str) -> str:
        return self.ratio.replace(_WINDOW_TOKEN, window)


def rule_from_dict(d: dict) -> SLORule:
    """Config-dict → rule (the ``selfmon.rules`` entries).  Eager and
    total like the chaos-timeline parser: a typo'd key or malformed
    window fails at config-validate time, never mid-tick."""
    unknown = set(d) - {"name", "objective", "ratio", "windows"}
    if unknown:
        raise ValueError(f"SLO rule: unknown keys {sorted(unknown)}")
    windows: Tuple[BurnWindow, ...] = DEFAULT_WINDOWS
    if "windows" in d:
        ws = []
        for i, w in enumerate(d["windows"]):
            bad = set(w) - {"long", "short", "factor"}
            if bad:
                raise ValueError(
                    f"SLO rule window #{i}: unknown keys {sorted(bad)}")
            missing = {"long", "short", "factor"} - set(w)
            if missing:
                # ValueError, not KeyError: config validation aggregates
                # ValueErrors into ONE ConfigError naming every bad field
                raise ValueError(
                    f"SLO rule window #{i}: missing keys {sorted(missing)}")
            ws.append(BurnWindow(str(w["long"]), str(w["short"]),
                                 float(w["factor"])))
        windows = tuple(ws)
    try:
        return SLORule(name=str(d.get("name", "")),
                       objective=float(d.get("objective", 0.0)),
                       ratio=str(d.get("ratio", "")), windows=windows)
    except ValueError as e:
        raise ValueError(f"SLO rule {d.get('name', '?')!r}: {e}") from None


def latency_ratio(base: str, le: str) -> str:
    """Bad-event fraction for a latency SLO over a fixed log-2 bucket
    histogram: the share of events SLOWER than ``le`` seconds.  The
    denominator is clamped so an idle window reads 0.0, not 0/0."""
    return (f"(sum(rate({base}_count[{_WINDOW_TOKEN}])) - "
            f"sum(rate({base}_bucket{{le=\"{le}\"}}[{_WINDOW_TOKEN}]))) / "
            f"clamp_min(sum(rate({base}_count[{_WINDOW_TOKEN}])), 0.001)")


def default_rules(prefix: str = "m3tpu") -> List[SLORule]:
    """The built-in rule set over series every node self-stores:
    ingest and query latency burn against fixed bucket bounds (0.25s
    and 1.0s are exact HISTOGRAM_BOUNDS lanes, so the ratio is
    bucket-exact, not interpolated)."""
    p = prefix
    return [
        SLORule("ingest-latency", 0.999,
                latency_ratio(f"{p}_db_write_batch_seconds", "0.25")),
        SLORule("query-latency", 0.99,
                latency_ratio(f"{p}_query_seconds", "1.0")),
    ]


class SLOEvaluator:
    """Evaluate a rule set against a PromQL engine on a tick cadence.

    One :class:`~m3_tpu.x.deadline.Deadline` bounds the WHOLE pass
    (``deadline_s``): rules evaluated after the budget is spent are
    reported ``"error": "deadline ..."`` instead of stalling the
    mediator.  A single rule whose query raises (bad series name, empty
    namespace) degrades to a per-rule error — one rotten rule must not
    silence the rest.  A rule that stops evaluating exports
    ``slo_burn = NaN`` — explicit "unknown", never its stale last-good
    value masquerading as current (NaN samples are absent to the
    temporal kernels, so ``max_over_time`` over stored burn history
    skips the outage instead of freezing it).  ``evaluate()`` is
    serialized by ``_eval_lock`` (the mediator tick and an
    admin-triggered pass must not interleave) while ``status()`` takes
    only the cheap state lock — the ``/health`` read path never waits
    behind an in-flight evaluation.
    """

    def __init__(self, engine, rules: Iterable[SLORule],
                 deadline_s: float = 2.0, scope=None):
        self.engine = engine
        self._rules: Tuple[SLORule, ...] = tuple(rules)
        self.deadline_s = float(deadline_s)
        # _eval_lock serializes evaluation passes (engine queries, up
        # to deadline_s); _lock guards ONLY the cached verdicts, so
        # /health reads never block behind a slow pass.
        self._eval_lock = threading.Lock()
        self._lock = threading.Lock()
        self._last: dict = {"rules": {}, "evaluated_unix": None,
                            "deadline_s": self.deadline_s}
        # slo_burn{rule=...} gauges, interned ONCE here: the tag set is
        # bounded by the configured rule set (config-literal, not
        # request-derived), and priming them to 0 now means the very
        # first selfmon scrape already stores one burn series per rule
        # — the series count is constant from cycle one (the
        # amplification-guard constancy test pins exactly that).
        self._gauges = {}
        if scope is not None:
            for r in self._rules:
                g = scope.tagged({"rule": r.name}).gauge("slo_burn")  # m3lint: disable=metric-hygiene — interned once per configured rule at construction; rule names are config-bounded, never request-derived
                g.update(0.0)
                self._gauges[r.name] = g

    # -- evaluation --------------------------------------------------------

    def _ratio(self, rule: SLORule, window: str, now_nanos: int) -> float:
        """One window's bad-event fraction: instant-evaluate the
        rule's query; an empty result (no data yet) is 0.0 burn, NaN
        rows are ignored, multiple series collapse by max (an
        aggregated ratio query yields one row; a per-instance one
        answers for the worst instance)."""
        block = self.engine.execute_instant(rule.query(window), now_nanos)
        vals = np.asarray(block.values)
        if vals.size == 0:
            return 0.0
        col = vals[:, -1]
        finite = col[~np.isnan(col)]
        if finite.size == 0:
            return 0.0
        return float(finite.max())

    def evaluate(self, now_nanos: int | None = None) -> dict:
        if now_nanos is None:
            now_nanos = time.time_ns()
        with self._eval_lock:
            dl = Deadline(self.deadline_s)
            rules_out: dict = {}
            spent = False
            with xdeadline.bind(dl):
                for rule in self._rules:
                    doc: dict = {"objective": rule.objective,
                                 "budget": round(rule.budget, 9)}
                    if spent:
                        doc["error"] = "deadline: evaluation budget spent"
                        doc["burn"], doc["firing"] = None, None
                        rules_out[rule.name] = doc
                        g = self._gauges.get(rule.name)
                        if g is not None:
                            g.update(float("nan"))  # unevaluated ≠ last-good
                        continue
                    try:
                        windows = []
                        burn = 0.0
                        firing = False
                        for w in rule.windows:
                            lr = self._ratio(rule, w.long, now_nanos)
                            sr = self._ratio(rule, w.short, now_nanos)
                            thr = w.factor * rule.budget
                            w_firing = lr >= thr and sr >= thr
                            firing = firing or w_firing
                            burn = max(burn, lr / rule.budget)
                            windows.append({
                                "long": w.long, "short": w.short,
                                "factor": w.factor,
                                "long_ratio": round(lr, 9),
                                "short_ratio": round(sr, 9),
                                "firing": w_firing,
                            })
                        doc.update(burn=round(burn, 6), firing=firing,
                                   windows=windows)
                    except DeadlineExceeded as e:
                        doc["error"] = f"deadline: {e}"
                        doc["burn"], doc["firing"] = None, None
                        spent = True
                    except Exception as e:  # noqa: BLE001 — one rotten
                        # rule degrades alone; the tick and the other
                        # rules keep going
                        doc["error"] = f"{type(e).__name__}: {e}"
                        doc["burn"], doc["firing"] = None, None
                    rules_out[rule.name] = doc
                    g = self._gauges.get(rule.name)
                    if g is not None:
                        # errored rules export NaN (unknown), never the
                        # stale last-good burn — see class docstring
                        g.update(doc["burn"] if doc.get("burn") is not None
                                 else float("nan"))
            last = {
                "rules": rules_out,
                "evaluated_unix": round(time.time(), 3),
                "deadline_s": self.deadline_s,
                "elapsed_s": round(dl.elapsed(), 4),
                "firing": sorted(n for n, d in rules_out.items()
                                 if d.get("firing")),
            }
            with self._lock:
                self._last = last
            return last

    def rules(self) -> dict:
        """Static rule metadata keyed by name — consumers (the
        x/controller's bindings, operators reading ``/health``) bind to
        rules by NAME through this accessor instead of re-parsing the
        selfmon config.  Pure configuration: no queries, no verdicts."""
        return {
            r.name: {
                "objective": r.objective,
                "budget": round(r.budget, 9),
                "windows": [
                    {"long": w.long, "short": w.short, "factor": w.factor}
                    for w in r.windows
                ],
            }
            for r in self._rules
        }

    def status(self) -> dict:
        """The cached last evaluation (the /health ``slo`` document) —
        no queries run on the health path.  ``rule_set`` carries the
        static rule metadata, so the configured objectives/windows are
        readable even before (or without) a completed evaluation."""
        with self._lock:
            out = dict(self._last)
        out["rule_set"] = self.rules()
        return out

    @property
    def firing(self) -> List[str]:
        with self._lock:
            return list(self._last.get("firing", ()))
