"""Query compute-precision policy.

Prometheus evaluates in float64 and so does this engine by default.  On
TPU that default is expensive: v5e-class chips have no native f64 ALU,
so XLA software-emulates every f64 elementwise op at ~10-20x the f32
cost — measured here as the PromQL north star (BASELINE config #5)
running 8x SLOWER on a TPU v5 lite than on the host CPU (47.9s vs 5.7s
per eval; TPU_RESULTS_r05.json).

The policy narrows the BULK stencil math (temporal kernels, the
histogram-quantile kernel) to f32 when selected, keeping:
- window *bounds* exact (i64 searchsorted, unaffected);
- times recentered at the first step before narrowing, so f32 holds
  window-relative nanos (<=hours, ~0.4ms resolution) instead of epoch
  nanos;
- regression stencils (deriv/predict_linear) in f64 always — their
  t^2 prefix sums exceed f32's 2^24 integer range;
- the f64 API surface: blocks upcast on exit, so callers never see the
  narrow dtype.

Accuracy envelope (validated by tests/test_query_precision.py and the
bench promql stage's scalar oracle): ~1e-6 relative per op; through the
rate+histogram_quantile chain the interpolation step AMPLIFIES by the
rank-to-bucket-width ratio — observed ~2e-4, bench-bounded at 5e-3.
Comparison operators are exempt (always f64): narrowing before ==/>/<
flips booleans for f64-distinct operands, which no relative envelope
covers.  Counter values above 2^24 lose integer exactness in f32 —
reset detection on such counters can misfire; deployments with
billion-count counters should stay on f64.

Selection: ``set_compute_dtype("f32"|"f64")`` or env
``M3_QUERY_DTYPE`` at import.  The dtype rides the ARRAYS (engine casts
at the fetch boundary; kernels follow ``vals.dtype``), so jitted
kernels re-specialize per dtype automatically — no stale-trace hazard.
"""

from __future__ import annotations

import os

import numpy as np

_VALID = {"f32": np.float32, "f64": np.float64}
_env = os.environ.get("M3_QUERY_DTYPE", "").strip().lower() or "f64"
if _env not in _VALID:
    raise ValueError(
        f"M3_QUERY_DTYPE={_env!r}: must be 'f32' or 'f64' (a typo "
        "silently running f64 would invalidate a perf comparison)")
_dtype = _VALID[_env]


def set_compute_dtype(name: str) -> None:
    global _dtype
    if name not in _VALID:
        raise ValueError(f"query compute dtype must be f32|f64, got {name!r}")
    _dtype = _VALID[name]


def compute_dtype() -> np.dtype:
    return np.dtype(_dtype)
