"""Temporal (sliding-window) functions as batched stencil kernels.

Equivalent of `src/query/functions/temporal`: rate/irate/delta/idelta/
increase (`rate.go:34-49` with the extrapolated-rate math of
`standardRateFunc`), *_over_time aggregations (`aggregation.go`), and
deriv/predict_linear (`linear_regression.go`).  The reference walks each
series' datapoints per step with per-series goroutine batches
(`base.go:172-230`); here every (series, step) window is computed at once:

* window boundaries via two vmapped `searchsorted`s over the sorted
  per-series timestamps → (S, T) lo/hi index matrices;
* sum/count/avg/stddev + the rate family read **prefix sums** and
  boundary gathers — O(S·(P+T)) with no window materialization;
* min/max/quantile gather a bounded (S, T, W) window tensor (W = max
  points per window, a static pad) — the stencil form.

Counter-reset correction and extrapolation follow the Prometheus
algorithm the reference implements (rate.go standardRateFunc: adjust by
cumulative resets, extrapolate to window edges capped at half the average
sample spacing).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

NAN = jnp.nan  # weak-typed: jnp.where keeps the value operand dtype


def _window_bounds(ts, step_times, range_nanos):
    """(S, T) lo/hi: half-open [lo, hi) indices of samples in
    (step - range, step] per series."""
    starts = step_times - range_nanos  # (T,)
    lo = jax.vmap(lambda row: jnp.searchsorted(row, starts, side="right"))(ts)
    hi = jax.vmap(lambda row: jnp.searchsorted(row, step_times, side="right"))(ts)
    return lo.astype(jnp.int32), hi.astype(jnp.int32)


def _prefix(vals):
    """Exclusive prefix sum with leading zero: (S, P+1)."""
    return jnp.concatenate(
        [jnp.zeros((vals.shape[0], 1), vals.dtype), jnp.cumsum(vals, axis=1)], axis=1
    )


def _gather_rows(a, idx):
    """a (S, P), idx (S, T) -> a[s, idx[s, t]]."""
    return jnp.take_along_axis(a, idx, axis=1)


@functools.partial(jax.jit, static_argnames=("func",))
def sum_count_family(ts, vals, step_times, range_nanos, func: str):
    """sum/count/avg/stddev/stdvar_over_time via prefix sums."""
    lo, hi = _window_bounds(ts, step_times, range_nanos)
    n = (hi - lo).astype(vals.dtype)
    c1 = _prefix(vals)
    c2 = _prefix(vals * vals)
    s1 = _gather_rows(c1, hi) - _gather_rows(c1, lo)
    s2 = _gather_rows(c2, hi) - _gather_rows(c2, lo)
    empty = n == 0
    if func == "sum_over_time":
        out = s1
    elif func == "count_over_time":
        out = n
    elif func == "avg_over_time":
        out = s1 / jnp.where(empty, 1.0, n)
    else:  # stddev/stdvar: population (Prometheus semantics)
        mean = s1 / jnp.where(empty, 1.0, n)
        var = jnp.maximum(s2 / jnp.where(empty, 1.0, n) - mean * mean, 0.0)
        out = jnp.sqrt(var) if func == "stddev_over_time" else var
    return jnp.where(empty, NAN, out)


def _gather_window(vals, lo, hi, W: int):
    """(S, T, W) stencil gather of each window's samples plus the valid
    mask — the shared idiom of every W-bounded kernel."""
    S, P = vals.shape
    T = lo.shape[1]
    idx = lo[:, :, None] + jnp.arange(W, dtype=jnp.int32)[None, None, :]
    valid = idx < hi[:, :, None]
    idx = jnp.clip(idx, 0, P - 1)
    g = jnp.take_along_axis(
        vals[:, None, :], idx.reshape(S, -1)[:, None, :], axis=2
    ).reshape(S, T, W)
    return g, valid


@functools.partial(jax.jit, static_argnames=("func", "window_pad"))
def minmax_quantile_family(ts, vals, step_times, range_nanos, func: str,
                           window_pad: int, q: float = 0.0):
    """min/max/quantile_over_time via the (S, T, W) gathered stencil."""
    lo, hi = _window_bounds(ts, step_times, range_nanos)
    g, valid = _gather_window(vals, lo, hi, window_pad)
    W = window_pad
    n = (hi - lo).astype(jnp.int32)
    empty = n == 0
    if func == "min_over_time":
        out = jnp.min(jnp.where(valid, g, jnp.inf), axis=2)
    elif func == "max_over_time":
        out = jnp.max(jnp.where(valid, g, -jnp.inf), axis=2)
    else:  # quantile_over_time (Prometheus: linear interpolation)
        gs = jnp.sort(jnp.where(valid, g, jnp.inf), axis=2)
        rank = q * (n.astype(vals.dtype) - 1.0)
        lo_r = jnp.clip(
            jnp.minimum(jnp.floor(rank).astype(jnp.int32), n - 1), 0, W - 1
        )
        hi_r = jnp.clip(jnp.minimum(lo_r + 1, n - 1), 0, W - 1)
        frac = rank - lo_r.astype(vals.dtype)
        v_lo = jnp.take_along_axis(gs, lo_r[:, :, None], axis=2)[:, :, 0]
        v_hi = jnp.take_along_axis(gs, hi_r[:, :, None], axis=2)[:, :, 0]
        out = v_lo + (v_hi - v_lo) * frac
    return jnp.where(empty, NAN, out)


@functools.partial(jax.jit, static_argnames=("func", "narrow"))
def rate_family(ts, vals, step_times, range_nanos, func: str,
                narrow: bool = False):
    """rate/increase/delta with Prometheus extrapolation
    (reference rate.go:99-102 standardRateFunc); counter funcs apply
    cumulative-reset correction.

    ``narrow`` is the f32 policy's entry point (query/precision.py).
    Unlike the other stencils, rate CANNOT take f32 values: cumulative
    counters are large and window deltas small, so narrowing before the
    difference cancels catastrophically (a 1e6-count counter with a
    30-count window delta loses ~2e-3 of the delta).  Instead ``vals``
    stays f64 through the reset correction and the v_last - v_first
    difference, and only the DIFFERENCES — delta, durations — narrow
    for the extrapolation arithmetic, where error is relative to the
    delta itself (~1e-7)."""
    dt_ = jnp.float32 if narrow else vals.dtype
    lo, hi = _window_bounds(ts, step_times, range_nanos)
    n = hi - lo
    has2 = n >= 2
    P = vals.shape[1]
    last_i = jnp.clip(hi - 1, 0, P - 1)
    first_i = jnp.clip(lo, 0, P - 1)

    is_counter = func in ("rate", "increase", "irate")
    if is_counter:
        prev = jnp.concatenate([vals[:, :1], vals[:, :-1]], axis=1)
        # Prometheus counter correction: on reset (v < prev) add the full
        # previous value (the counter restarted from zero).
        resets = jnp.where(vals < prev, prev, 0.0)
        resets = jnp.where(jnp.isnan(resets), 0.0, resets)
        cum_resets = jnp.cumsum(resets, axis=1)
        adj = vals + cum_resets
    else:
        adj = vals

    # All DURATION math happens in i64 nanos first and narrows only the
    # differences: sampled / dur_start / dur_end are bounded by the
    # range window, so they fit any float dtype regardless of where the
    # query sits on the epoch axis or how long its span is (epoch nanos
    # themselves fit neither f32 nor even f64 exactly).  Gathered pad
    # entries (i64 max) wrap to garbage — every lane that can read one
    # is masked below (has2 / sampled>0 / dt>0).
    v_first = _gather_rows(adj, first_i)
    v_last = _gather_rows(adj, last_i)
    ti_first = _gather_rows(ts, first_i)  # i64 (S, T)
    ti_last = _gather_rows(ts, last_i)

    if func in ("irate", "idelta"):
        prev_i = jnp.clip(hi - 2, 0, P - 1)
        v_prev = _gather_rows(adj, prev_i)
        dv = (v_last - v_prev).astype(dt_)  # difference, then narrow
        dt = (ti_last - _gather_rows(ts, prev_i)).astype(dt_) / 1e9
        out = jnp.where(dt > 0, dv / dt if func == "irate" else dv, NAN)
        return jnp.where(has2, out, NAN)

    range_f = jnp.asarray(range_nanos, dt_)
    window_start = step_times - range_nanos  # i64 (T,)

    delta_v = (v_last - v_first).astype(dt_)  # difference, then narrow
    sampled = (ti_last - ti_first).astype(dt_)  # nanos, <= range
    avg_dur = sampled / jnp.maximum(n.astype(dt_) - 1.0, 1.0)
    dur_start = (ti_first - window_start[None, :]).astype(dt_)
    dur_end = (step_times[None, :] - ti_last).astype(dt_)

    # Prometheus extrapolation: extend to the window edge unless the gap
    # exceeds 1.1× the average sample spacing, then cap at avg/2.
    extrap_start = jnp.where(dur_start < avg_dur * 1.1, dur_start, avg_dur / 2.0)
    extrap_end = jnp.where(dur_end < avg_dur * 1.1, dur_end, avg_dur / 2.0)
    if is_counter:
        # A counter cannot extrapolate below zero: cap the start-side
        # extension at the time it would take to reach zero.  Prometheus
        # uses the RAW first sample here (pre reset-adjustment).
        v_first_raw = _gather_rows(vals, first_i)
        # Ratio of two f64 quantities (large raw value / small delta):
        # divide in f64, then narrow the bounded result.
        delta64 = v_last - v_first
        ratio = (v_first_raw
                 / jnp.where(delta64 == 0, 1.0, delta64)).astype(dt_)
        zero_dur = jnp.where(
            (delta_v > 0) & (v_first_raw.astype(dt_) >= 0),
            sampled * ratio,
            jnp.inf,
        )
        extrap_start = jnp.minimum(extrap_start, zero_dur)
    factor = (sampled + extrap_start + extrap_end) / jnp.where(sampled == 0, 1.0, sampled)
    extrapolated = delta_v * factor

    if func == "rate":
        out = extrapolated / (range_f / 1e9)
    else:  # increase, delta
        out = extrapolated
    return jnp.where(has2 & (sampled > 0), out, NAN)


@functools.partial(jax.jit, static_argnames=("func",))
def regression_family(ts, vals, step_times, range_nanos, func: str,
                      predict_offset_s: float = 0.0):
    """deriv / predict_linear: least-squares slope over each window
    (reference linear_regression.go), via prefix sums of (t, v, t·v, t²)
    with per-window re-centering at the window end for stability.

    Always f64 regardless of the precision policy: the t² prefix sums
    span ~3e9 for an hour window, past f32's 2^24 integer range."""
    vals = vals.astype(jnp.float64)
    lo, hi = _window_bounds(ts, step_times, range_nanos)
    n = (hi - lo).astype(jnp.float64)
    # Center on the first step BEFORE the prefix sums: epoch-scale t²
    # (~1e19) would otherwise swamp float64 and cancel catastrophically.
    g_ref = step_times[0]
    tsec = (ts - g_ref).astype(jnp.float64) / 1e9
    ref = ((step_times - g_ref).astype(jnp.float64) / 1e9)[None, :]  # (1, T)

    c_v = _prefix(vals)
    c_t = _prefix(tsec)
    c_tv = _prefix(tsec * vals)
    c_tt = _prefix(tsec * tsec)
    S_v = _gather_rows(c_v, hi) - _gather_rows(c_v, lo)
    S_t = _gather_rows(c_t, hi) - _gather_rows(c_t, lo)
    S_tv = _gather_rows(c_tv, hi) - _gather_rows(c_tv, lo)
    S_tt = _gather_rows(c_tt, hi) - _gather_rows(c_tt, lo)
    # Re-center times at the step time: t' = t - ref.
    S_t_c = S_t - n * ref
    S_tt_c = S_tt - 2 * ref * S_t + n * ref * ref
    S_tv_c = S_tv - ref * S_v
    denom = n * S_tt_c - S_t_c * S_t_c
    slope = jnp.where(denom != 0, (n * S_tv_c - S_t_c * S_v) / denom, NAN)
    intercept = (S_v - slope * S_t_c) / jnp.where(n == 0, 1.0, n)  # value at ref
    ok = n >= 2
    if func == "deriv":
        return jnp.where(ok, slope, NAN)
    return jnp.where(ok, intercept + slope * predict_offset_s, NAN)


@functools.partial(jax.jit, static_argnames=("func",))
def transitions_family(ts, vals, step_times, range_nanos, func: str):
    """resets / changes (reference functions.go funcResets/funcChanges):
    count the transitions between CONSECUTIVE samples inside each
    window — resets counts v[i] < v[i-1] (counter restarts), changes
    counts v[i] != v[i-1].  Prefix-summed over the adjacent-pair
    indicator, so the windowed count is two gathers: pairs (i-1, i)
    with both ends inside [lo, hi) are those with i in [lo+1, hi)."""
    lo, hi = _window_bounds(ts, step_times, range_nanos)
    prev = jnp.concatenate([vals[:, :1], vals[:, :-1]], axis=1)
    if func == "resets":
        ind = (vals < prev).astype(vals.dtype)
    else:  # changes
        ind = (vals != prev).astype(vals.dtype)
    c = _prefix(ind)
    P = vals.shape[1]
    count = (_gather_rows(c, hi) -
             _gather_rows(c, jnp.clip(lo + 1, 0, P)))
    n = hi - lo
    # >=1 sample emits (0 transitions for a single sample); empty -> NaN
    return jnp.where(n >= 1, jnp.maximum(count, 0.0), NAN)


@functools.partial(jax.jit, static_argnames=("window_pad",))
def holt_winters(ts, vals, step_times, range_nanos, window_pad: int,
                 sf: float, tf: float):
    """holt_winters / double_exponential_smoothing (reference
    functions/temporal + Prometheus funcHoltWinters): per window,
    level/trend smoothing over the gathered (S, T, W) stencil with a
    masked fori over W — s1 seeds from x0, trend from x1-x0, and each
    in-window sample advances (s1, b) exactly like the sequential
    reference loop."""
    lo, hi = _window_bounds(ts, step_times, range_nanos)
    W = window_pad
    g, valid = _gather_window(vals, lo, hi, W)
    g = jnp.where(valid, g, 0.0)
    n = hi - lo

    x0 = g[:, :, 0]
    x1 = g[:, :, 1] if W > 1 else x0
    s1_0 = x0
    b_0 = x1 - x0

    def body(i, carry):
        s1, b = carry
        x = jax.lax.dynamic_index_in_dim(g, i, axis=2, keepdims=False)
        active = i < n
        xs = sf * x
        y = (1.0 - sf) * (s1 + b)
        s0_new, s1_new = s1, xs + y
        b_new = tf * (s1_new - s0_new) + (1.0 - tf) * b
        return (jnp.where(active, s1_new, s1), jnp.where(active, b_new, b))

    s1, _b = jax.lax.fori_loop(1, W, body, (s1_0, b_0))
    return jnp.where(n >= 2, s1, NAN)


@jax.jit
def last_over_time(ts, vals, step_times, range_nanos):
    lo, hi = _window_bounds(ts, step_times, range_nanos)
    P = vals.shape[1]
    out = _gather_rows(vals, jnp.clip(hi - 1, 0, P - 1))
    return jnp.where(hi > lo, out, NAN)


def window_pad_for(counts: np.ndarray, ts: np.ndarray, range_nanos: int) -> int:
    """Static W bound for the stencil kernels: the exact maximum number
    of samples any range-length window can contain, computed host-side
    per series via a sliding searchsorted.  No silent cap — the (S, T, W)
    gather tensor is as wide as the densest window requires; callers
    chunk the series axis if that exceeds memory."""
    best = 1
    for s in range(len(counts)):
        n = int(counts[s])
        if n == 0:
            continue
        row = ts[s, :n]
        lo = np.searchsorted(row, row - range_nanos, side="right")
        best = max(best, int((np.arange(1, n + 1) - lo).max()))
    return best
