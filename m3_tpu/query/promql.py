"""PromQL parser (recursive descent) producing this engine's AST.

The reference wraps the upstream Prometheus parser and maps its AST into
M3 parse nodes (`src/query/parser/promql/parse.go`); this is a
from-scratch parser for the supported subset:

* literals, vector selectors `m{a="b",c!~"d"}`, range `[5m]`, `offset`;
* function calls (temporal family, math family, histogram_quantile,
  clamp/round, scalar/vector, label_replace/label_join, absent);
* aggregations with `by`/`without` grouping + parameterized topk/
  bottomk/quantile/count_values;
* binary operators with precedence (^ > */% > +- > comparisons > and/
  unless > or), `bool` modifier, and `on`/`ignoring` vector matching.
"""

from __future__ import annotations

import re
import dataclasses
from dataclasses import dataclass, field


# ---------------------------------------------------------------------------
# AST
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class Expr:
    pass


@dataclass(frozen=True)
class NumberLiteral(Expr):
    value: float


@dataclass(frozen=True)
class StringLiteral(Expr):
    value: str


@dataclass(frozen=True)
class LabelMatcher:
    name: bytes
    op: str  # "=", "!=", "=~", "!~"
    value: bytes


@dataclass(frozen=True)
class VectorSelector(Expr):
    name: bytes | None
    matchers: tuple[LabelMatcher, ...] = ()
    range_nanos: int = 0  # 0 = instant
    offset_nanos: int = 0
    # @ modifier: pin evaluation to a fixed time.  at_nanos holds the
    # literal timestamp; at_edge "start"/"end" resolves to the query
    # range boundary at evaluation (Prometheus start()/end()).
    at_nanos: int | None = None
    at_edge: str = ""


@dataclass(frozen=True)
class Subquery(Expr):
    """``<expr>[range:step]`` (Prometheus subqueries): evaluate the
    inner INSTANT expression on a step grid, then window it like a
    range vector.  step 0 = the engine's default resolution."""

    expr: Expr
    range_nanos: int
    step_nanos: int = 0
    offset_nanos: int = 0
    at_nanos: int | None = None
    at_edge: str = ""


@dataclass(frozen=True)
class Call(Expr):
    func: str
    args: tuple[Expr, ...]


@dataclass(frozen=True)
class Aggregation(Expr):
    op: str
    expr: Expr
    by: tuple[bytes, ...] | None = None
    without: tuple[bytes, ...] | None = None
    param: Expr | None = None


@dataclass(frozen=True)
class BinaryOp(Expr):
    op: str
    lhs: Expr
    rhs: Expr
    bool_mode: bool = False
    on: tuple[bytes, ...] | None = None
    ignoring: tuple[bytes, ...] | None = None


@dataclass(frozen=True)
class Unary(Expr):
    op: str
    expr: Expr


# ---------------------------------------------------------------------------
# Lexer
# ---------------------------------------------------------------------------

_TOKEN_RE = re.compile(
    r"""
    (?P<space>\s+)
  | (?P<duration>\d+(?:\.\d+)?(?:ms|s|m|h|d|w|y))
  | (?P<number>
        0x[0-9a-fA-F]+
      | (?:\d+\.?\d*|\.\d+)(?:[eE][+-]?\d+)?
      | [iI][nN][fF] | [nN][aA][nN])
  | (?P<string>"(?:\\.|[^"\\])*"|'(?:\\.|[^'\\])*')
  | (?P<op>=~|!~|==|!=|>=|<=|<|>|=|\+|-|\*|/|%|\^|\(|\)|\{|\}|\[|\]|,|:|@)
  | (?P<ident>[a-zA-Z_:][a-zA-Z0-9_:.]*)
    """,
    re.VERBOSE,
)

_DUR = {"ms": 10**6, "s": 10**9, "m": 60 * 10**9, "h": 3600 * 10**9,
        "d": 86400 * 10**9, "w": 7 * 86400 * 10**9, "y": 365 * 86400 * 10**9}

AGG_OPS = {"sum", "avg", "min", "max", "count", "stddev", "stdvar",
           "topk", "bottomk", "quantile", "count_values", "group"}

_CMP = {"==", "!=", ">", "<", ">=", "<="}


@dataclass
class _Tok:
    kind: str
    text: str


def _lex(s: str) -> list[_Tok]:
    out, pos = [], 0
    while pos < len(s):
        m = _TOKEN_RE.match(s, pos)
        if not m:
            raise ValueError(f"promql: bad token at {s[pos:pos+20]!r}")
        pos = m.end()
        kind = m.lastgroup
        if kind == "space":
            continue
        out.append(_Tok(kind, m.group()))
    out.append(_Tok("eof", ""))
    return out


def _unquote(text: str) -> str:
    """Strip quotes and resolve escape sequences (Prometheus string
    literals use Go escaping; \\" \\\\ \\n \\t etc.)."""
    body = text[1:-1]
    return body.encode("latin-1", "backslashreplace").decode("unicode_escape")


def parse_duration(text: str) -> int:
    m = re.fullmatch(r"(\d+(?:\.\d+)?)(ms|s|m|h|d|w|y)", text)
    if not m:
        raise ValueError(f"bad duration {text!r}")
    return int(float(m.group(1)) * _DUR[m.group(2)])


# ---------------------------------------------------------------------------
# Parser
# ---------------------------------------------------------------------------


class _Parser:
    def __init__(self, toks: list[_Tok]):
        self.toks = toks
        self.i = 0

    def peek(self) -> _Tok:
        return self.toks[self.i]

    def next(self) -> _Tok:
        t = self.toks[self.i]
        self.i += 1
        return t

    def expect(self, text: str) -> _Tok:
        t = self.next()
        if t.text != text:
            raise ValueError(f"promql: expected {text!r}, got {t.text!r}")
        return t

    def accept(self, text: str) -> bool:
        if self.peek().text == text:
            self.i += 1
            return True
        return False

    # precedence climbing: or < and/unless < cmp < +- < */% < ^ < unary
    def parse_expr(self) -> Expr:
        return self._parse_or()

    def _bin_rhs(self, op: str):
        bool_mode = False
        on = ignoring = None
        if self.peek().text == "bool":
            self.next()
            bool_mode = True
        if self.peek().text in ("on", "ignoring"):
            which = self.next().text
            labels = self._parse_label_list()
            if which == "on":
                on = labels
            else:
                ignoring = labels
            if self.peek().text in ("group_left", "group_right"):
                self.next()
                if self.peek().text == "(":
                    self._parse_label_list()
        return bool_mode, on, ignoring

    def _parse_or(self) -> Expr:
        lhs = self._parse_and()
        while self.peek().text == "or":
            self.next()
            bm, on, ig = self._bin_rhs("or")
            lhs = BinaryOp("or", lhs, self._parse_and(), bm, on, ig)
        return lhs

    def _parse_and(self) -> Expr:
        lhs = self._parse_cmp()
        while self.peek().text in ("and", "unless"):
            op = self.next().text
            bm, on, ig = self._bin_rhs(op)
            lhs = BinaryOp(op, lhs, self._parse_cmp(), bm, on, ig)
        return lhs

    def _parse_cmp(self) -> Expr:
        lhs = self._parse_add()
        while self.peek().text in _CMP:
            op = self.next().text
            bm, on, ig = self._bin_rhs(op)
            lhs = BinaryOp(op, lhs, self._parse_add(), bm, on, ig)
        return lhs

    def _parse_add(self) -> Expr:
        lhs = self._parse_mul()
        while self.peek().text in ("+", "-"):
            op = self.next().text
            bm, on, ig = self._bin_rhs(op)
            lhs = BinaryOp(op, lhs, self._parse_mul(), bm, on, ig)
        return lhs

    def _parse_mul(self) -> Expr:
        lhs = self._parse_unary()
        while self.peek().text in ("*", "/", "%"):
            op = self.next().text
            bm, on, ig = self._bin_rhs(op)
            lhs = BinaryOp(op, lhs, self._parse_unary(), bm, on, ig)
        return lhs

    def _parse_unary(self) -> Expr:
        # Unary binds LOOSER than ^ (Prometheus: -2^2 == -(2^2)).
        if self.peek().text in ("-", "+"):
            op = self.next().text
            return Unary(op, self._parse_unary())
        return self._parse_pow()

    def _parse_pow(self) -> Expr:
        lhs = self._parse_postfix()
        if self.peek().text == "^":  # right-assoc; rhs may be unary (2^-3)
            self.next()
            bm, on, ig = self._bin_rhs("^")
            return BinaryOp("^", lhs, self._parse_unary(), bm, on, ig)
        return lhs

    def _parse_postfix(self) -> Expr:
        e = self._parse_primary()
        while True:
            if self.peek().text == "[":
                self.next()
                dur = self.next()
                rng = parse_duration(dur.text)
                if self.accept(":"):
                    # subquery: [range:step] or [range:] (default step)
                    sub_step = 0
                    if self.peek().text != "]":
                        sub_step = parse_duration(self.next().text)
                    self.expect("]")
                    e = Subquery(e, rng, sub_step)
                    continue
                self.expect("]")
                if not isinstance(e, VectorSelector):
                    raise ValueError(
                        "range selector on non-selector (use [range:step] "
                        "for a subquery)")
                e = dataclasses.replace(e, range_nanos=rng)
            elif self.peek().text == "offset":
                self.next()
                off = parse_duration(self.next().text)
                if not isinstance(e, (Subquery, VectorSelector)):
                    raise ValueError("offset on non-selector")
                e = dataclasses.replace(e, offset_nanos=off)
            elif self.peek().text == "@":
                self.next()
                at_nanos: int | None = None
                edge = ""
                t = self.next()
                if t.text in ("start", "end"):
                    self.expect("(")
                    self.expect(")")
                    edge = t.text
                else:
                    # unix seconds, possibly fractional or signed.
                    # Parsed at millisecond precision like Prometheus:
                    # float seconds * 1e9 at epoch magnitude is ~200ns
                    # off, enough to exclude a sample stored exactly at
                    # the pinned time from its (t-range, t] window.
                    txt = t.text
                    if txt == "-":
                        txt += self.next().text
                    at_nanos = int(round(float(txt) * 1000)) * 10**6
                if not isinstance(e, (Subquery, VectorSelector)):
                    raise ValueError("@ modifier on non-selector")
                e = dataclasses.replace(e, at_nanos=at_nanos, at_edge=edge)
            else:
                return e

    def _parse_label_list(self) -> tuple[bytes, ...]:
        self.expect("(")
        out = []
        while self.peek().text != ")":
            out.append(self.next().text.encode())
            if not self.accept(","):
                break
        self.expect(")")
        return tuple(out)

    def _parse_matchers(self) -> tuple[LabelMatcher, ...]:
        self.expect("{")
        out = []
        while self.peek().text != "}":
            name = self.next().text.encode()
            op = self.next().text
            if op not in ("=", "!=", "=~", "!~"):
                raise ValueError(f"bad matcher op {op!r}")
            val = self.next()
            if val.kind != "string":
                raise ValueError("matcher value must be a string")
            out.append(LabelMatcher(name, op, _unquote(val.text).encode()))
            if not self.accept(","):
                break
        self.expect("}")
        return tuple(out)

    def _parse_primary(self) -> Expr:
        t = self.peek()
        if t.text == "(":
            self.next()
            e = self.parse_expr()
            self.expect(")")
            return e
        if t.kind == "number":
            self.next()
            txt = t.text.lower()
            if txt.startswith("0x"):
                return NumberLiteral(float(int(txt, 16)))
            if txt == "inf":
                return NumberLiteral(float("inf"))
            if txt == "nan":
                return NumberLiteral(float("nan"))
            return NumberLiteral(float(t.text))
        if t.kind == "duration":
            self.next()
            return NumberLiteral(parse_duration(t.text) / 1e9)
        if t.kind == "string":
            self.next()
            return StringLiteral(_unquote(t.text))
        if t.text == "{":
            return VectorSelector(None, self._parse_matchers())
        if t.kind == "ident":
            self.next()
            name = t.text
            if name in AGG_OPS and self.peek().text in ("(", "by", "without"):
                return self._parse_aggregation(name)
            if self.peek().text == "(":
                self.next()
                args = []
                while self.peek().text != ")":
                    args.append(self.parse_expr())
                    if not self.accept(","):
                        break
                self.expect(")")
                return Call(name, tuple(args))
            matchers = ()
            if self.peek().text == "{":
                matchers = self._parse_matchers()
            return VectorSelector(name.encode(), matchers)
        raise ValueError(f"promql: unexpected token {t.text!r}")

    def _parse_aggregation(self, op: str) -> Expr:
        by = without = None
        if self.peek().text == "by":
            self.next()
            by = self._parse_label_list()
        elif self.peek().text == "without":
            self.next()
            without = self._parse_label_list()
        self.expect("(")
        first = self.parse_expr()
        param = None
        expr = first
        if self.accept(","):
            param = first
            expr = self.parse_expr()
        self.expect(")")
        if self.peek().text == "by":
            self.next()
            by = self._parse_label_list()
        elif self.peek().text == "without":
            self.next()
            without = self._parse_label_list()
        return Aggregation(op, expr, by, without, param)


def parse(query: str) -> Expr:
    p = _Parser(_lex(query))
    e = p.parse_expr()
    if p.peek().kind != "eof":
        raise ValueError(f"promql: trailing input at {p.peek().text!r}")
    return e
