"""Instant-vector functions over step-aligned blocks.

Equivalents of `src/query/functions/{aggregation,linear,binary,tag}`:

* label-grouped aggregations (sum/avg/min/max/count/stddev/quantile/
  topk/bottomk by/without) — `aggregation/function.go`;
* `histogram_quantile` — `linear/histogram_quantile.go:38-54`, computed
  per (group, step) over the le-bucket axis as one segmented device op;
* scalar math (abs/ceil/floor/exp/ln/log2/log10/sqrt/round/clamp_*) —
  `linear/math.go`, `linear/clamp.go`;
* binary arithmetic/comparison with vector matching (on/ignoring) —
  `binary/binary.go`.

All operate on the (S, T) matrix; grouping is a host-computed partition of
series rows (tag work stays on host) followed by one device segmented
reduction over the group axis.
"""

from __future__ import annotations

import math
from collections import defaultdict

import jax.numpy as jnp
import numpy as np

from m3_tpu.query.block import Block, SeriesMeta

NAN = float("nan")


# ---------------------------------------------------------------------------
# Label grouping (host): series rows -> group ids
# ---------------------------------------------------------------------------


def group_series(series: list[SeriesMeta], by: set[bytes] | None,
                 without: set[bytes] | None) -> tuple[np.ndarray, list[SeriesMeta]]:
    """Group assignment per series row + the output group metas.

    by=None, without=None → one global group (Prometheus `sum(x)`).
    """
    groups: dict[tuple, int] = {}
    metas: list[SeriesMeta] = []
    gids = np.zeros(len(series), np.int32)
    for i, m in enumerate(series):
        if by is not None:
            key_meta = m.keep(by)
        elif without is not None:
            key_meta = m.drop(without | {b"__name__"})
        else:
            key_meta = SeriesMeta(())
        k = key_meta.tags
        g = groups.get(k)
        if g is None:
            g = groups[k] = len(metas)
            metas.append(key_meta)
        gids[i] = g
    return gids, metas


def _segment_reduce(values: np.ndarray, gids: np.ndarray, num_groups: int,
                    func: str, q: float = 0.0) -> np.ndarray:
    """(S, T) + group ids -> (G, T) via device segment ops.

    Two formulations: XLA segment_* (scatter-based — fast on CPU) and a
    sort/scan/gather form for TPU, where scatter measured ~1us/element
    (TPU_RESULTS_r05.json window #3) — a 100K-series `sum by (...)`
    would otherwise scatter S*T elements.  Chosen at trace time by
    backend; both are pinned equal in tests/test_query_engine.py.
    """
    import jax
    import jax.numpy as jnp

    if func == "quantile":
        from m3_tpu.query.device_fns import group_quantile

        return group_quantile(values, gids, num_groups, q)

    v = jnp.asarray(values)
    g = jnp.asarray(gids)
    nan = jnp.isnan(v)
    zero = jnp.where(nan, 0.0, v)
    ones = (~nan).astype(jnp.float64)

    if jax.default_backend() == "tpu" and v.shape[0] > 0:
        from m3_tpu.parallel import segmented as so

        order = jnp.argsort(g)
        gs = g[order]
        is_start = jnp.concatenate(
            [jnp.ones(1, bool), gs[1:] != gs[:-1]])
        adds, mins, maxs = [], [], []
        if func in ("sum", "avg", "stddev", "stdvar"):
            adds.append(zero[order])
        if func in ("stddev", "stdvar"):
            adds.append((zero * zero)[order])
        if func == "min":
            mins.append(jnp.where(nan, jnp.inf, v)[order])
        if func == "max":
            maxs.append(jnp.where(nan, -jnp.inf, v)[order])
        adds.append(ones[order])  # count rides every form
        r_adds, r_mins, r_maxs = so.head_flag_scan(
            is_start, adds=tuple(adds), mins=tuple(mins), maxs=tuple(maxs))
        pos, found = so.last_occurrence(
            gs, jnp.arange(num_groups, dtype=gs.dtype))
        fm = found[:, None]

        def at_ends(seg):
            return jnp.where(fm, seg[pos], jnp.zeros((), seg.dtype))

        cnt = at_ends(r_adds[-1])
        empty = cnt == 0
        if func == "sum":
            out = at_ends(r_adds[0])
        elif func == "count":
            out = cnt
        elif func == "avg":
            out = at_ends(r_adds[0]) / jnp.where(empty, 1.0, cnt)
        elif func in ("stddev", "stdvar"):
            s1, s2 = at_ends(r_adds[0]), at_ends(r_adds[1])
            mean = s1 / jnp.where(empty, 1.0, cnt)
            var = jnp.maximum(
                s2 / jnp.where(empty, 1.0, cnt) - mean * mean, 0.0)
            out = jnp.sqrt(var) if func == "stddev" else var
        elif func == "min":
            out = jnp.where(fm, r_mins[0][pos], jnp.inf)
            out = jnp.where(jnp.isposinf(out), NAN, out)
        elif func == "max":
            out = jnp.where(fm, r_maxs[0][pos], -jnp.inf)
            out = jnp.where(jnp.isneginf(out), NAN, out)
        else:
            raise ValueError(f"unknown aggregation {func}")
        return jnp.where(empty, NAN, out)

    def seg_sum(x):
        return jax.ops.segment_sum(x, g, num_segments=num_groups)

    cnt = seg_sum(ones)
    empty = cnt == 0
    if func == "sum":
        out = seg_sum(zero)
    elif func == "count":
        out = cnt
    elif func == "avg":
        out = seg_sum(zero) / jnp.where(empty, 1.0, cnt)
    elif func in ("stddev", "stdvar"):
        s1 = seg_sum(zero)
        s2 = seg_sum(zero * zero)
        mean = s1 / jnp.where(empty, 1.0, cnt)
        var = jnp.maximum(s2 / jnp.where(empty, 1.0, cnt) - mean * mean, 0.0)
        out = jnp.sqrt(var) if func == "stddev" else var
    elif func == "min":
        out = jax.ops.segment_min(jnp.where(nan, jnp.inf, v), g,
                                  num_segments=num_groups)
        out = jnp.where(jnp.isposinf(out), NAN, out)
    elif func == "max":
        out = jax.ops.segment_max(jnp.where(nan, -jnp.inf, v), g,
                                  num_segments=num_groups)
        out = jnp.where(jnp.isneginf(out), NAN, out)
    else:
        raise ValueError(f"unknown aggregation {func}")
    return jnp.where(empty, NAN, out)  # device-resident (Block contract)


def aggregate(block: Block, func: str, by: set[bytes] | None = None,
              without: set[bytes] | None = None, param: float = 0.0) -> Block:
    gids, metas = group_series(block.series, by, without)
    vals = _segment_reduce(block.values, gids, len(metas), func, param)
    return Block(block.step_times, vals, metas)


def topk_bottomk(block: Block, k: int, func: str,
                 by: set[bytes] | None = None,
                 without: set[bytes] | None = None) -> Block:
    """topk/bottomk keep original series, masking all but the k extreme
    per (group, step)."""
    from m3_tpu.query.device_fns import topk_mask

    gids, metas = group_series(block.series, by, without)
    import jax.numpy as jnp

    v = jnp.asarray(block.values)
    keep = topk_mask(v, gids, len(metas), int(k), func == "topk")
    out = jnp.where(jnp.asarray(keep), v, NAN)
    return block.with_values(out)


# ---------------------------------------------------------------------------
# histogram_quantile
# ---------------------------------------------------------------------------


def histogram_quantile(block: Block, q: float) -> Block:
    """Per-step quantile from cumulative `le` buckets (reference
    linear/histogram_quantile.go: group series by tags-minus-le, sort
    buckets by upper bound, linear interpolation within the bucket)."""
    groups: dict[tuple, list[tuple[float, int]]] = defaultdict(list)
    for i, m in enumerate(block.series):
        tags = m.as_dict()
        le = tags.get(b"le")
        if le is None:
            continue
        try:
            ub = float(le)
        except ValueError:
            continue
        key = m.drop({b"le", b"__name__"}).tags
        groups[key].append((ub, i))

    from m3_tpu.query.device_fns import histogram_quantile_groups

    T = block.num_steps
    metas: list[SeriesMeta] = []
    group_rows: list[list[int]] = []
    group_ubs: list[np.ndarray] = []
    nan_metas: list[SeriesMeta] = []
    for key, buckets in groups.items():
        buckets.sort()
        ubs = np.array([b[0] for b in buckets])
        if not np.isinf(ubs[-1]):
            # no +Inf bucket → undefined (Prometheus returns NaN)
            nan_metas.append(SeriesMeta(key))
            continue
        metas.append(SeriesMeta(key))
        group_rows.append([b[1] for b in buckets])
        group_ubs.append(ubs)
    vals = None
    if group_rows:
        # Stays device-resident — iterating rows here would sync each
        # of the G rows separately (Block contract: one boundary sync).
        vals = histogram_quantile_groups(block.values, group_rows,
                                         group_ubs, q)
    metas += nan_metas
    if vals is None and not nan_metas:
        return Block(block.step_times, np.zeros((0, T)), [])
    if nan_metas:
        import jax.numpy as jnp

        nan_blk = jnp.full((len(nan_metas), T), NAN, jnp.float64)
        vals = nan_blk if vals is None else jnp.concatenate([vals, nan_blk])
    return Block(block.step_times, vals, metas)


# ---------------------------------------------------------------------------
# Scalar math + binary ops
# ---------------------------------------------------------------------------

_UNARY = {
    "abs": np.abs,
    "ceil": np.ceil,
    "floor": np.floor,
    "exp": np.exp,
    "ln": np.log,
    "log2": np.log2,
    "log10": np.log10,
    "sqrt": np.sqrt,
    "sgn": np.sign,
    # trigonometric family (Prometheus 2.31+)
    "sin": np.sin, "cos": np.cos, "tan": np.tan,
    "asin": np.arcsin, "acos": np.arccos, "atan": np.arctan,
    "sinh": np.sinh, "cosh": np.cosh, "tanh": np.tanh,
    "asinh": np.arcsinh, "acosh": np.arccosh, "atanh": np.arctanh,
    "deg": np.degrees, "rad": np.radians,
}

# Date parts of a unix-seconds vector (Prometheus functions.go
# funcDaysInMonth..funcYear; UTC, like Prometheus).  Each function
# receives the precomputed (dt, Y, M, D) datetime64 casts ONCE.
_DATE_FNS = {
    "minute": lambda dt, Y, M, D: (dt.astype("datetime64[m]")
                                   - dt.astype("datetime64[h]")
                                   ).astype("int64"),
    "hour": lambda dt, Y, M, D: (dt.astype("datetime64[h]") - D
                                 ).astype("int64"),
    "day_of_week": lambda dt, Y, M, D: (D.astype("int64") + 4) % 7,
    "day_of_month": lambda dt, Y, M, D: (D - M).astype("int64") + 1,
    "day_of_year": lambda dt, Y, M, D: (D - Y).astype("int64") + 1,
    "days_in_month": lambda dt, Y, M, D: (
        (M + 1).astype("datetime64[D]") - M.astype("datetime64[D]")
    ).astype("int64"),
    "month": lambda dt, Y, M, D: (M - Y).astype("int64") + 1,
    "year": lambda dt, Y, M, D: Y.astype("int64") + 1970,
}


def date_fn(block: Block, func: str) -> Block:
    v = block.values
    finite = np.isfinite(v)
    secs = np.where(finite, v, 0.0).astype("int64")
    dt = secs.astype("datetime64[s]")
    Y = dt.astype("datetime64[Y]")
    M = dt.astype("datetime64[M]")
    D = dt.astype("datetime64[D]")
    with np.errstate(all="ignore"):
        out = _DATE_FNS[func](dt, Y, M, D).astype(np.float64)
    # non-finite inputs (NaN gaps AND +/-Inf poison) stay NaN — an
    # Inf-valued sample must not masquerade as the epoch's date parts
    out = np.where(finite, out, np.nan)
    return block.with_values(out, [m.drop_name() for m in block.series])


# Device-resident forms (Block contract), derived key-for-key from the
# numpy table so engine dispatch (`f in _UNARY`) can never drift from
# execution: every numpy ufunc here has a same-named jnp equivalent.
_J_UNARY = {name: getattr(jnp, f.__name__) for name, f in _UNARY.items()}


def unary_math(block: Block, func: str) -> Block:
    out = _J_UNARY[func](jnp.asarray(block.values, jnp.float64))
    return block.with_values(out, [m.drop_name() for m in block.series])


def round_fn(block: Block, to_nearest: float = 1.0) -> Block:
    # Prometheus round(): half UP (floor(v+0.5)); device-resident.
    v = jnp.asarray(block.values, jnp.float64)
    out = jnp.floor(v / to_nearest + 0.5) * to_nearest
    return block.with_values(out, [m.drop_name() for m in block.series])


def clamp(block: Block, lo: float = -math.inf, hi: float = math.inf) -> Block:
    return block.with_values(
        jnp.clip(jnp.asarray(block.values, jnp.float64), lo, hi),
        [m.drop_name() for m in block.series]
    )


_BINOPS = {
    "+": np.add,
    "-": np.subtract,
    "*": np.multiply,
    "/": np.divide,
    "%": np.mod,
    "^": np.power,
    "==": np.equal,
    "!=": np.not_equal,
    ">": np.greater,
    "<": np.less,
    ">=": np.greater_equal,
    "<=": np.less_equal,
}

from m3_tpu.query.device_fns import COMPARISONS as _COMPARISONS


# Derived key-for-key from _BINOPS (same drift guard as _J_UNARY).
_J_BINOPS = {op: getattr(jnp, f.__name__) for op, f in _BINOPS.items()}


def scalar_binary(block: Block, op: str, scalar: float,
                  scalar_left: bool = False, bool_mode: bool = False) -> Block:
    f = _J_BINOPS[op]
    v = jnp.asarray(block.values, jnp.float64)  # comparisons stay f64
    out = (f(scalar, v) if scalar_left else f(v, scalar)).astype(jnp.float64)
    if op in _COMPARISONS:
        if bool_mode:
            out = jnp.where(jnp.isnan(v), NAN, out)  # NaN stays missing
        else:
            out = jnp.where(out != 0, v, NAN)  # filter semantics
    series = block.series if op in _COMPARISONS and not bool_mode else [
        m.drop_name() for m in block.series
    ]
    return block.with_values(out, series)


def _match_key(meta: SeriesMeta, on: set[bytes] | None,
               ignoring: set[bytes] | None) -> tuple:
    if on is not None:
        return meta.keep(on).tags
    drop = {b"__name__"} | (ignoring or set())
    return meta.drop(drop).tags


def vector_binary(lhs: Block, rhs: Block, op: str,
                  on: set[bytes] | None = None,
                  ignoring: set[bytes] | None = None,
                  bool_mode: bool = False) -> Block:
    """One-to-one vector matching (reference binary/binary.go)."""
    rindex = { _match_key(m, on, ignoring): i for i, m in enumerate(rhs.series) }
    rows_l, rows_r, metas = [], [], []
    for i, m in enumerate(lhs.series):
        k = _match_key(m, on, ignoring)
        j = rindex.get(k)
        if j is None:
            continue
        rows_l.append(i)
        rows_r.append(j)
        metas.append(m.drop_name() if not (op in _COMPARISONS and not bool_mode) else m)
    if not rows_l:
        return Block(lhs.step_times, np.zeros((0, lhs.num_steps)), [])
    from m3_tpu.query.device_fns import vector_binary_matched

    out = vector_binary_matched(
        lhs.values, rhs.values, rows_l, rows_r, op, bool_mode
    )
    return Block(lhs.step_times, out, metas)
