"""Device kernels for the query engine's hot per-step functions.

The reference computes these per-series/per-step on the CPU with
goroutine fan-out (`src/query/functions/linear/histogram_quantile.go:38-54`,
`aggregation/function.go`, `binary/binary.go`); here each one is a
single jitted array program over the whole (series × step) block — the
TPU-shaped replacement for per-step loops.

Ragged group structure (different bucket/row counts per group) is
handled the TPU way: the host builds padded gather-index matrices once
(cheap tag work it owns anyway), and the device kernel runs on dense
(G, R_max, T) tensors with masks.  jit caches per shape, so repeated
queries over the same block geometry pay tracing once.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

NAN = float("nan")


# ---------------------------------------------------------------------------
# Padded group gather plans (host)
# ---------------------------------------------------------------------------


def group_plan(gids: np.ndarray, num_groups: int):
    """(row_idx (G, R_max), mask (G, R_max)) gathering each group's rows."""
    order = np.argsort(gids, kind="stable")
    sorted_g = gids[order]
    starts = np.searchsorted(sorted_g, np.arange(num_groups))
    ends = np.searchsorted(sorted_g, np.arange(num_groups), side="right")
    counts = ends - starts
    r_max = max(1, int(counts.max(initial=0)))
    idx = np.zeros((num_groups, r_max), np.int32)
    mask = np.zeros((num_groups, r_max), bool)
    for g in range(num_groups):
        c = counts[g]
        idx[g, :c] = order[starts[g] : ends[g]]
        mask[g, :c] = True
    return idx, mask


# ---------------------------------------------------------------------------
# Grouped quantile  (quantile(0.9, x) by (...))
# ---------------------------------------------------------------------------


@functools.partial(jax.jit, static_argnames=())
def _group_quantile_kernel(values, idx, mask, q):
    """(S, T), (G, R), (G, R) -> (G, T) linear-interpolated quantile over
    present (non-NaN) rows — matches numpy nanquantile 'linear'."""
    rows = values[idx]  # (G, R, T)
    present = mask[:, :, None] & ~jnp.isnan(rows)
    big = jnp.where(present, rows, jnp.inf)
    s = jnp.sort(big, axis=1)  # present values first, inf after
    n = present.sum(axis=1)  # (G, T)
    # rank into the sorted axis: h = q*(n-1); linear interp between floor/ceil
    h = q * (n - 1).astype(values.dtype)
    lo = jnp.clip(jnp.floor(h).astype(jnp.int32), 0, s.shape[1] - 1)
    hi = jnp.clip(jnp.ceil(h).astype(jnp.int32), 0, s.shape[1] - 1)
    v_lo = jnp.take_along_axis(s, lo[:, None, :], axis=1)[:, 0, :]
    v_hi = jnp.take_along_axis(s, hi[:, None, :], axis=1)[:, 0, :]
    frac = h - jnp.floor(h)
    out = v_lo + (v_hi - v_lo) * frac
    return jnp.where(n > 0, out, jnp.nan)


def group_quantile(values: np.ndarray, gids: np.ndarray, num_groups: int,
                   q: float) -> np.ndarray:
    from m3_tpu.query import precision

    dt = precision.compute_dtype()
    idx, mask = group_plan(gids, num_groups)
    return _group_quantile_kernel(
        jnp.asarray(values, dt), jnp.asarray(idx), jnp.asarray(mask),
        jnp.asarray(q, dt),
    ).astype(jnp.float64)  # device-resident (Block contract)


# ---------------------------------------------------------------------------
# topk / bottomk
# ---------------------------------------------------------------------------


@functools.partial(jax.jit, static_argnames=("k", "top"))
def _topk_mask_kernel(values, idx, mask, inv_g, inv_r, k: int, top: bool):
    """Keep-mask (S, T): True where the row is among the k extreme in its
    group at that step."""
    rows = values[idx]  # (G, R, T)
    # Present = in-group and not NaN; ±Inf are real sample values and
    # compete for rank slots (Prometheus topk keeps Inf).
    present = mask[:, :, None] & ~jnp.isnan(rows)
    key = jnp.where(present, rows, -jnp.inf if top else jnp.inf)
    s = jnp.sort(key, axis=1)
    R = s.shape[1]
    # kth extreme per (group, step); groups with < k present rows keep all
    kth = s[:, max(R - k, 0), :] if top else s[:, min(k - 1, R - 1), :]
    keep_g = (key >= kth[:, None, :]) if top else (key <= kth[:, None, :])
    keep_g = keep_g & present
    # (G, R, T) back to (S, T) by GATHER through the inverse mapping —
    # not scatter (~1us/element on TPU; TPU_RESULTS_r05.json window #3)
    return keep_g[inv_g, inv_r, :]


def topk_mask(values: np.ndarray, gids: np.ndarray, num_groups: int,
              k: int, top: bool) -> np.ndarray:
    idx, mask = group_plan(gids, num_groups)
    # Inverse of group_plan — derived from its OWN output so the two
    # can never drift: series idx[g, r] sits at rank r of group g.
    S = len(gids)
    rows, cols = np.nonzero(mask)
    inv_r = np.empty(S, np.int32)
    inv_r[idx[rows, cols]] = cols.astype(np.int32)
    return _topk_mask_kernel(jnp.asarray(values), jnp.asarray(idx),
                             jnp.asarray(mask),
                             jnp.asarray(np.asarray(gids, np.int32)),
                             jnp.asarray(inv_r), k=int(k), top=bool(top))


# ---------------------------------------------------------------------------
# histogram_quantile
# ---------------------------------------------------------------------------


@jax.jit
def _histogram_quantile_kernel(values, idx, nbuckets, ubs, q):
    """values (S, T); idx (G, B) row index per bucket rank (le-ascending,
    +Inf last when present); nbuckets (G,); ubs (G, B) upper bounds
    (inf-padded).  Returns (G, T).

    Mirrors the reference math (`linear/histogram_quantile.go`):
    cumulative counts clamped monotone, rank = q * total, linear
    interpolation inside the first bucket reaching the rank, +Inf bucket
    answered by the highest finite bound."""
    G, B = idx.shape
    rows = values[idx]  # (G, B, T)
    bpos = jnp.arange(B)[None, :]
    valid = bpos < nbuckets[:, None]  # (G, B)
    counts = jnp.where(valid[:, :, None], jnp.nan_to_num(rows), 0.0)
    counts = jax.lax.cummax(counts, axis=1)
    # total comes from the RAW +Inf-bucket sample: a NaN there must
    # propagate to a NaN result (a nan_to_num'd total would silently
    # substitute the previous bucket's cumulative count).
    last = jnp.clip(nbuckets - 1, 0, B - 1)
    total = jnp.take_along_axis(rows, last[:, None, None], axis=1)[:, 0, :]
    rank = q * total
    ge = (counts >= rank[:, None, :]) & valid[:, :, None]
    first = jnp.argmax(ge, axis=1)  # (G, T)
    take = lambda a, i: jnp.take_along_axis(a, i[:, None, :], axis=1)[:, 0, :]
    b_hi = jnp.take_along_axis(ubs, first, axis=1)
    prev = jnp.maximum(first - 1, 0)
    b_lo = jnp.where(first > 0, jnp.take_along_axis(ubs, prev, axis=1), 0.0)
    c_hi = take(counts, first)
    c_lo = jnp.where(first > 0, take(counts, prev), 0.0)
    frac = jnp.where(c_hi > c_lo, (rank - c_lo) / (c_hi - c_lo), 0.0)
    val = b_lo + (b_hi - b_lo) * frac
    # +Inf bucket → highest finite bound; a group with ONLY the +Inf
    # bucket has no finite bound and answers 0.0 (host-code parity).
    hf_idx = jnp.clip(nbuckets - 2, 0, B - 1)
    highest_finite = jnp.where(
        (nbuckets >= 2)[:, None],
        jnp.take_along_axis(ubs, hf_idx[:, None], axis=1),
        0.0,
    )
    val = jnp.where(jnp.isinf(b_hi), highest_finite, val)
    bad = (total == 0) | jnp.isnan(total)
    return jnp.where(bad, jnp.nan, val)


def histogram_quantile_groups(values: np.ndarray, group_rows: list,
                              group_ubs: list, q: float) -> np.ndarray:
    """group_rows[g] = row indices le-ascending (+Inf last); group_ubs[g]
    the matching upper bounds.  Returns (G, T)."""
    G = len(group_rows)
    B = max(len(r) for r in group_rows)
    idx = np.zeros((G, B), np.int32)
    ubs = np.full((G, B), np.inf)
    nb = np.zeros(G, np.int32)
    for g, (rows, u) in enumerate(zip(group_rows, group_ubs)):
        idx[g, : len(rows)] = rows
        ubs[g, : len(u)] = u
        nb[g] = len(rows)
    from m3_tpu.query import precision

    dt = precision.compute_dtype()
    return _histogram_quantile_kernel(
        jnp.asarray(values, dt), jnp.asarray(idx), jnp.asarray(nb),
        jnp.asarray(ubs, dt), jnp.asarray(q, dt),
    ).astype(jnp.float64)  # device-resident (Block contract)


# ---------------------------------------------------------------------------
# Binary ops with vector matching
# ---------------------------------------------------------------------------

COMPARISONS = {"==", "!=", ">", "<", ">=", "<="}


@functools.partial(jax.jit, static_argnames=("op", "bool_mode"))
def _vector_binary_kernel(lv, rv, op: str, bool_mode: bool):
    ops = {
        "+": jnp.add, "-": jnp.subtract, "*": jnp.multiply,
        "/": jnp.divide, "%": jnp.mod, "^": jnp.power,
        "==": jnp.equal, "!=": jnp.not_equal, ">": jnp.greater,
        "<": jnp.less, ">=": jnp.greater_equal, "<=": jnp.less_equal,
    }
    out = ops[op](lv, rv).astype(lv.dtype)
    if op in COMPARISONS and not bool_mode:
        out = jnp.where(out != 0, lv, jnp.nan)
    miss = jnp.isnan(lv) | jnp.isnan(rv)
    return jnp.where(miss, jnp.nan, out)


def vector_binary_matched(l_values: np.ndarray, r_values: np.ndarray,
                          rows_l, rows_r, op: str,
                          bool_mode: bool) -> np.ndarray:
    """Gather matched rows on device and apply the op in one kernel.

    Comparisons are EXEMPT from the f32 policy: narrowing before ==/>/<
    discretely flips results for f64-distinct operands (16777217.0 vs
    16777216.0 collide in f32) — a boolean error no relative-error
    envelope covers.  Only the arithmetic ops narrow."""
    from m3_tpu.query import precision

    dt = np.float64 if op in COMPARISONS else precision.compute_dtype()
    lv = jnp.asarray(l_values, dt)[jnp.asarray(np.asarray(rows_l, np.int32))]
    rv = jnp.asarray(r_values, dt)[jnp.asarray(np.asarray(rows_r, np.int32))]
    return _vector_binary_kernel(
        lv, rv, op=op, bool_mode=bool_mode).astype(jnp.float64)
