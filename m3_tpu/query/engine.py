"""Query engine: parse → evaluate → step-aligned block.

Equivalent of `src/query/executor` (`engine.ExecuteExpr` `engine.go:111`:
parse → logical plan → DAG of transforms pulling blocks).  The evaluator
walks the AST depth-first; leaves fetch raw series through a Storage
interface (the fanout/m3db adapter seam, `query/storage/fanout`), and
every interior node is a whole-block array op (`temporal.py`,
`functions.py`) instead of a per-step iterator chain.
"""

from __future__ import annotations

import math
import threading
from dataclasses import dataclass
from typing import Protocol

import jax.numpy as jnp
import numpy as np

from m3_tpu.query import functions as fn
from m3_tpu.query import temporal as tp
from m3_tpu.x import deadline as xdeadline
from m3_tpu.query.block import Block, RawBlock, SeriesMeta
from m3_tpu.query.promql import (
    Subquery,
    Aggregation, BinaryOp, Call, Expr, LabelMatcher, NumberLiteral,
    StringLiteral, Unary, VectorSelector, parse,
)

LOOKBACK_NANOS = 5 * 60 * 10**9  # Prometheus default lookback delta

_TEMPORAL_SUM = {"sum_over_time", "count_over_time", "avg_over_time",
                 "stddev_over_time", "stdvar_over_time"}
_TEMPORAL_MINMAXQ = {"min_over_time", "max_over_time", "quantile_over_time"}
_TEMPORAL_RATE = {"rate", "increase", "delta", "irate", "idelta"}
_TEMPORAL_REG = {"deriv", "predict_linear"}
_TEMPORAL_TRANS = {"resets", "changes"}
_TEMPORAL_ALL = (_TEMPORAL_SUM | _TEMPORAL_MINMAXQ | _TEMPORAL_RATE
                 | _TEMPORAL_REG | _TEMPORAL_TRANS
                 | {"last_over_time", "present_over_time",
                    "absent_over_time", "holt_winters"})


class Storage(Protocol):
    def fetch_raw(self, name: bytes | None, matchers: tuple[LabelMatcher, ...],
                  start_nanos: int, end_nanos: int) -> RawBlock: ...


@dataclass
class _Scalar:
    """A PromQL scalar: a float, or a per-step (T,) array (scalar(),
    time()).  Binary ops broadcast arrays across the series axis."""

    value: float | np.ndarray


class Engine:
    """reference `executor/engine.go:47 NewEngine`."""

    def __init__(self, storage: Storage, lookback_nanos: int = LOOKBACK_NANOS,
                 tracer=None):
        from m3_tpu.instrument.tracing import NOOP_TRACER

        self.storage = storage
        self.lookback = lookback_nanos
        self.tracer = tracer if tracer is not None else NOOP_TRACER
        # Per-query (start, end) for @ start()/end() resolution: they
        # ALWAYS refer to the top-level query parameters (Prometheus),
        # never an inner subquery grid.  Thread-local because one
        # engine serves concurrent HTTP requests.
        self._query_bounds = threading.local()

    # -- public API --------------------------------------------------------

    def execute_range(self, query: str, start_nanos: int, end_nanos: int,
                      step_nanos: int, deadline=None) -> Block:
        """PromQL range query (reference api/v1 native read →
        ExecuteExpr).  ``deadline`` (an ``x/deadline.Deadline``) bounds
        the whole evaluation: checked between eval nodes and inside
        per-step loops, threaded to storage fetches through the context
        binding (callers that already bound one can omit it)."""
        from m3_tpu.instrument.tracing import Tracepoint

        with self.tracer.start_span(Tracepoint.ENGINE_EXECUTE,
                                    {"query": query}):
            with xdeadline.bind(deadline if deadline is not None
                                else xdeadline.current()):
                return self._execute_range(query, start_nanos, end_nanos,
                                           step_nanos)

    def _execute_range(self, query: str, start_nanos: int, end_nanos: int,
                       step_nanos: int) -> Block:
        ast = parse(query)
        steps = np.arange(start_nanos, end_nanos + 1, step_nanos, dtype=np.int64)
        self._query_bounds.range = (start_nanos, end_nanos)
        try:
            out = self._eval(ast, steps)
        finally:
            del self._query_bounds.range
        if isinstance(out, _Scalar):
            vals = np.broadcast_to(
                np.asarray(out.value, np.float64), (1, len(steps))
            ).copy()
            return Block(steps, vals, [SeriesMeta(())])
        # The ONE device->host sync: blocks stay device-resident between
        # pipeline stages (see Block docstring) and leave the engine as
        # host float64.
        return out.materialized()

    def execute_instant(self, query: str, time_nanos: int,
                        deadline=None) -> Block:
        return self.execute_range(query, time_nanos, time_nanos, 10**9,
                                  deadline=deadline)

    # -- evaluation --------------------------------------------------------

    def _eval(self, e: Expr, steps: np.ndarray):
        # Cooperative cancellation point between eval nodes: a deep AST
        # over a spent budget stops HERE, not after the next expensive
        # kernel (the per-step loops below check too).
        xdeadline.check_current("query eval")
        if isinstance(e, NumberLiteral):
            return _Scalar(e.value)
        if isinstance(e, StringLiteral):
            return e.value
        if isinstance(e, Unary):
            v = self._eval(e.expr, steps)
            if e.op == "+":
                return v
            if isinstance(v, _Scalar):
                return _Scalar(-v.value)
            return v.with_values(-v.values)
        if isinstance(e, VectorSelector):
            if e.range_nanos:
                raise ValueError("range selector outside temporal function")
            return self._eval_instant_selector(e, steps)
        if isinstance(e, Call):
            return self._eval_call(e, steps)
        if isinstance(e, Aggregation):
            return self._eval_aggregation(e, steps)
        if isinstance(e, BinaryOp):
            return self._eval_binary(e, steps)
        raise ValueError(f"cannot evaluate {e}")

    def _resolve_at(self, node, steps: np.ndarray) -> int | None:
        """The @ modifier's fixed evaluation time, or None.  start()/
        end() resolve to the TOP-LEVEL query range parameters — even
        inside a subquery, whose inner grid is wider and step-aligned —
        and to the true end timestamp even when the range is not a
        step multiple (Prometheus @ semantics)."""
        if node.at_edge in ("start", "end"):
            bounds = getattr(self._query_bounds, "range",
                             (int(steps[0]), int(steps[-1])))
            return bounds[0] if node.at_edge == "start" else bounds[1]
        return node.at_nanos

    def _fetch(self, sel: VectorSelector, steps: np.ndarray, range_nanos: int):
        at = self._resolve_at(sel, steps)
        if at is not None:
            # pinned evaluation computes ONE column; callers broadcast
            # the constant result across the output steps
            eval_steps = np.asarray([at - sel.offset_nanos], np.int64)
        else:
            eval_steps = steps - sel.offset_nanos
        start = int(eval_steps[0]) - range_nanos
        # +1: storage reads are end-EXCLUSIVE, but a sample exactly at
        # the final evaluation step belongs to it (Prometheus windows
        # are (t-range, t] — found by the comparator harness, which
        # caught the last step evaluating with the previous sample).
        end = int(eval_steps[-1]) + 1
        raw = self.storage.fetch_raw(sel.name, sel.matchers, start, end)
        return raw, eval_steps

    def _eval_subquery(self, sub: Subquery, steps: np.ndarray):
        """Evaluate ``expr[range:step]``: run the inner INSTANT
        expression on the subquery's absolute-aligned step grid, then
        hand the samples to the temporal kernels exactly like fetched
        raw datapoints (Prometheus subquery semantics: inner steps are
        aligned to multiples of the subquery step; NaN results are
        stale and yield no sample)."""
        step = sub.step_nanos
        if step == 0:
            # Prometheus uses the global evaluation interval as the
            # default resolution; the closest engine-native analogue is
            # the outer query's step, falling back to 60s for
            # single-step (instant) evaluations.  (Resolved BEFORE any
            # @ pinning collapses the grid to a constant.)
            step = (int(steps[1] - steps[0]) if len(steps) > 1
                    else 60 * 10**9)
        at = self._resolve_at(sub, steps)
        if at is not None:
            steps = np.asarray([at], np.int64)  # single pinned column
        end = int(steps[-1]) - sub.offset_nanos
        start = int(steps[0]) - sub.range_nanos - sub.offset_nanos
        first = -(-start // step) * step  # absolute alignment (ceil)
        inner = np.arange(first, end + 1, step, dtype=np.int64)
        if len(inner) == 0:
            inner = np.asarray([end], np.int64)
        b = self._eval(sub.expr, inner)
        if isinstance(b, _Scalar):
            # scalar-valued inner exprs (time(), literals) broadcast to
            # one anonymous series over the inner grid
            vals = np.broadcast_to(
                np.asarray(b.value, np.float64), (len(inner),))
            b = Block(inner, vals[None, :].copy(), [SeriesMeta(())])
        bvals = np.asarray(b.values)  # one sync, not one per row
        pts = []
        for i, row in enumerate(bvals):
            if i % 256 == 0:  # per-row loop over the inner grid
                xdeadline.check_current("subquery rows")
            pts.append([(int(t), float(v)) for t, v in zip(inner, row)
                        if not math.isnan(v)])
        raw = RawBlock.from_lists(pts, b.series)
        return raw, steps - sub.offset_nanos

    def _eval_instant_selector(self, sel: VectorSelector, steps: np.ndarray) -> Block:
        raw, eval_steps = self._fetch(sel, steps, self.lookback)
        vals = tp.last_over_time(jnp.asarray(raw.ts),
                                 jnp.asarray(raw.values),
                                 jnp.asarray(eval_steps), self.lookback)
        if vals.shape[1] != len(steps):  # @-pinned single column
            vals = jnp.broadcast_to(vals, (vals.shape[0], len(steps)))
        return Block(steps, vals, raw.series)

    def _eval_call(self, call: Call, steps: np.ndarray):
        f = call.func
        if f in _TEMPORAL_ALL:
            q = 0.0
            sel_arg = call.args[-1]
            extra = 0.0
            if f == "quantile_over_time":
                q = self._scalar_arg(call.args[0], steps)
                sel_arg = call.args[1]
            elif f == "predict_linear":
                sel_arg = call.args[0]
                extra = self._scalar_arg(call.args[1], steps)
            elif f == "holt_winters":
                sel_arg = call.args[0]
            if isinstance(sel_arg, Subquery):
                raw, eval_steps = self._eval_subquery(sel_arg, steps)
            elif (not isinstance(sel_arg, VectorSelector)
                    or sel_arg.range_nanos == 0):
                raise ValueError(
                    f"{f} requires a range selector or subquery")
            else:
                raw, eval_steps = self._fetch(sel_arg, steps,
                                              sel_arg.range_nanos)
            if len(raw.series) == 0 and f != "absent_over_time":
                # No matched series: an empty instant vector
                # (Prometheus semantics).  Must short-circuit BEFORE
                # the jitted stencils — a 0-row window gather cannot
                # even shape its reshape.
                return Block(steps, np.empty((0, len(steps)),
                                             np.float64), [])
            from m3_tpu.query import precision

            narrow = precision.compute_dtype() == np.float32
            ts_j = jnp.asarray(raw.ts)
            # The policy dtype rides the value array: jitted stencils
            # follow vals.dtype, so f32 selection re-specializes every
            # kernel without any static plumbing (query/precision.py).
            # The rate family is the exception — it must difference
            # cumulative counters in f64 and narrows internally via its
            # static `narrow` flag — as is regression (f64-pinned).
            narrow_vals = f not in _TEMPORAL_RATE and f not in _TEMPORAL_REG
            vals_j = jnp.asarray(
                np.nan_to_num(raw.values),
                precision.compute_dtype() if narrow_vals else np.float64)
            st_j = jnp.asarray(eval_steps)
            rng = sel_arg.range_nanos
            if f in _TEMPORAL_SUM:
                out = tp.sum_count_family(ts_j, vals_j, st_j, rng, f)
            elif f in _TEMPORAL_MINMAXQ:
                W = tp.window_pad_for(raw.counts, raw.ts, rng)
                out = tp.minmax_quantile_family(ts_j, vals_j, st_j, rng, f, W, q)
            elif f in _TEMPORAL_RATE:
                out = tp.rate_family(ts_j, vals_j, st_j, rng, f,
                                     narrow=narrow)
            elif f in _TEMPORAL_REG:
                out = tp.regression_family(ts_j, vals_j, st_j, rng, f, extra)
            elif f in _TEMPORAL_TRANS:
                out = tp.transitions_family(ts_j, vals_j, st_j, rng, f)
            elif f == "holt_winters":
                sfv = float(self._scalar_arg(call.args[1], steps))
                tfv = float(self._scalar_arg(call.args[2], steps))
                # Prometheus funcHoltWinters: sf in (0, 1), tf in (0, 1]
                if not (0.0 < sfv < 1.0) or not (0.0 < tfv <= 1.0):
                    raise ValueError(
                        "holt_winters smoothing factor must be in (0, 1) "
                        "and trend factor in (0, 1]")
                W = tp.window_pad_for(raw.counts, raw.ts, rng)
                out = tp.holt_winters(ts_j, vals_j, st_j, rng, max(W, 2),
                                      sfv, tfv)
            elif f == "last_over_time":
                out = tp.last_over_time(ts_j, vals_j, st_j, rng)
            elif f == "absent_over_time":
                # 1 for every step where NO matched series has samples
                # in the window; when nothing matched at all, a single
                # empty-labelled series of 1s (Prometheus semantics).
                if len(raw.series) == 0:
                    return Block(steps, np.ones((1, len(steps))),
                                 [SeriesMeta(())])
                cnt = np.asarray(tp.sum_count_family(
                    ts_j, vals_j, st_j, rng, "count_over_time"))
                any_present = (~np.isnan(cnt) & (cnt > 0)).any(axis=0)
                vals_out = np.where(any_present, np.nan, 1.0)[None, :]
                if vals_out.shape[1] != len(steps):  # @-pinned
                    vals_out = np.broadcast_to(
                        vals_out, (1, len(steps))).copy()
                return Block(steps, vals_out, [SeriesMeta(())])
            else:  # present_over_time
                out = tp.sum_count_family(ts_j, vals_j, st_j, rng, "count_over_time")
                out = jnp.where(jnp.isnan(out), out, jnp.minimum(out, 1.0))
            metas = [m.drop_name() for m in raw.series]
            # Blocks stay f64 whatever the compute policy — downstream
            # code sees one dtype.  The cast happens ON DEVICE; the
            # block leaves the engine device-resident so a following
            # stage (histogram_quantile, aggregation) consumes it
            # without a host round-trip.
            out = out.astype(jnp.float64)
            if out.ndim == 2 and out.shape[1] != len(steps):
                # @-pinned: one computed column broadcast across steps
                out = jnp.broadcast_to(out, (out.shape[0], len(steps)))
            return Block(steps, out, metas)

        if f == "histogram_quantile":
            q = self._scalar_arg(call.args[0], steps)
            block = self._eval(call.args[1], steps)
            return fn.histogram_quantile(block, q)
        if f in fn._UNARY:
            return fn.unary_math(self._eval(call.args[0], steps), f)
        if f == "pi":
            return _Scalar(math.pi)
        if f in fn._DATE_FNS:
            # date parts of the argument's unix-seconds values;
            # argument defaults to vector(time()) like Prometheus
            if call.args:
                b = self._eval(call.args[0], steps)
            else:
                b = Block(steps, (steps.astype(np.float64) / 1e9)[None, :],
                          [SeriesMeta(())])
            if isinstance(b, _Scalar):
                b = Block(steps, np.broadcast_to(
                    np.asarray(b.value, np.float64),
                    (1, len(steps))).copy(), [SeriesMeta(())])
            return fn.date_fn(b, f)
        if f == "round":
            nearest = (self._scalar_arg(call.args[1], steps)
                       if len(call.args) > 1 else 1.0)
            return fn.round_fn(self._eval(call.args[0], steps), nearest)
        if f == "clamp":
            return fn.clamp(self._eval(call.args[0], steps),
                            self._scalar_arg(call.args[1], steps),
                            self._scalar_arg(call.args[2], steps))
        if f == "clamp_min":
            return fn.clamp(self._eval(call.args[0], steps),
                            lo=self._scalar_arg(call.args[1], steps))
        if f == "clamp_max":
            return fn.clamp(self._eval(call.args[0], steps),
                            hi=self._scalar_arg(call.args[1], steps))
        if f == "scalar":
            b = self._eval(call.args[0], steps)
            if isinstance(b, _Scalar):
                return b
            if b.num_series == 1:
                return _Scalar(b.values[0].copy())
            return _Scalar(np.full(len(steps), np.nan))
        if f == "vector":
            v = self._eval(call.args[0], steps)
            if not isinstance(v, _Scalar):
                raise ValueError("vector() expects a scalar argument")
            # Per-step scalars stay per-step (Prometheus vector(time())
            # is the canonical example), device or host.
            val = np.asarray(v.value, np.float64)
            row = (np.broadcast_to(val, (len(steps),)) if val.ndim
                   else np.full(len(steps), float(val)))
            return Block(steps, row[None, :].copy(), [SeriesMeta(())])
        if f == "absent":
            b = self._eval(call.args[0], steps)
            present = (~np.isnan(b.values)).any(axis=0) if b.num_series else (
                np.zeros(len(steps), bool))
            vals = np.where(present, np.nan, 1.0)[None, :]
            return Block(steps, vals, [SeriesMeta(())])
        if f == "label_replace":
            return self._label_replace(call, steps)
        if f == "label_join":
            return self._label_join(call, steps)
        if f == "timestamp":
            b = self._eval(call.args[0], steps)
            tvals = np.broadcast_to(steps.astype(np.float64) / 1e9, b.values.shape)
            return b.with_values(np.where(np.isnan(b.values), np.nan, tvals),
                                 [m.drop_name() for m in b.series])
        if f == "time":
            return _Scalar(steps.astype(np.float64) / 1e9)
        if f in ("sort", "sort_desc"):
            # Prometheus sorts instant vectors by value; for a range
            # evaluation the order is taken at the final step (stable
            # for ties, NaNs last), matching how dashboards consume it.
            b = self._eval(call.args[0], steps)
            if isinstance(b, _Scalar):
                raise ValueError(f"{f} expects an instant vector")
            if b.num_series <= 1:
                return b
            key = b.values[:, -1]
            key = np.where(np.isnan(key), np.inf if f == "sort" else -np.inf,
                           key)
            order = np.argsort(key if f == "sort" else -key, kind="stable")
            return Block(steps, b.values[order],
                         [b.series[i] for i in order])
        raise ValueError(f"unsupported function {f!r}")

    def _label_replace(self, call: Call, steps: np.ndarray) -> Block:
        import re as _re

        b = self._eval(call.args[0], steps)
        dst = self._string_arg(call.args[1]).encode()
        repl = self._string_arg(call.args[2])
        src = self._string_arg(call.args[3]).encode()
        regex = _re.compile(self._string_arg(call.args[4]))
        metas = []
        for m in b.series:
            tags = m.as_dict()
            val = tags.get(src, b"").decode()
            mm = regex.fullmatch(val)
            if mm:
                new = mm.expand(repl.replace("$", "\\")).encode()
                if new:
                    tags[dst] = new
                else:
                    tags.pop(dst, None)
            metas.append(SeriesMeta.from_dict(tags))
        return Block(b.step_times, b.values, metas)

    def _label_join(self, call: Call, steps: np.ndarray) -> Block:
        b = self._eval(call.args[0], steps)
        dst = self._string_arg(call.args[1]).encode()
        sep = self._string_arg(call.args[2]).encode()
        srcs = [self._string_arg(a).encode() for a in call.args[3:]]
        metas = []
        for m in b.series:
            tags = m.as_dict()
            joined = sep.join(tags.get(s, b"") for s in srcs)
            if joined:
                tags[dst] = joined
            else:
                tags.pop(dst, None)
            metas.append(SeriesMeta.from_dict(tags))
        return Block(b.step_times, b.values, metas)

    def _eval_aggregation(self, agg: Aggregation, steps: np.ndarray) -> Block:
        block = self._eval(agg.expr, steps)
        by = set(agg.by) if agg.by is not None else None
        without = set(agg.without) if agg.without is not None else None
        if agg.op in ("topk", "bottomk"):
            k = int(self._scalar_arg(agg.param, steps))
            return fn.topk_bottomk(block, k, agg.op, by, without)
        if agg.op == "quantile":
            q = self._scalar_arg(agg.param, steps)
            return fn.aggregate(block, "quantile", by, without, q)
        if agg.op == "group":
            out = fn.aggregate(block, "count", by, without)
            return out.with_values(np.where(np.isnan(out.values), np.nan, 1.0))
        return fn.aggregate(block, agg.op, by, without)

    def _eval_binary(self, b: BinaryOp, steps: np.ndarray):
        lhs = self._eval(b.lhs, steps)
        rhs = self._eval(b.rhs, steps)
        sl, sr = isinstance(lhs, _Scalar), isinstance(rhs, _Scalar)
        if b.op in ("and", "or", "unless"):
            return self._set_op(b, lhs, rhs)
        if sl and sr:
            with np.errstate(all="ignore"):
                v = fn._BINOPS[b.op](lhs.value, rhs.value)
            if b.op in fn._COMPARISONS:
                v = np.asarray(v, np.float64) if isinstance(v, np.ndarray) \
                    else (1.0 if v else 0.0)
            return _Scalar(v if isinstance(v, np.ndarray) else float(v))
        if sr:
            return fn.scalar_binary(lhs, b.op, rhs.value, False, b.bool_mode)
        if sl:
            return fn.scalar_binary(rhs, b.op, lhs.value, True, b.bool_mode)
        return fn.vector_binary(
            lhs, rhs, b.op,
            set(b.on) if b.on is not None else None,
            set(b.ignoring) if b.ignoring is not None else None,
            b.bool_mode,
        )

    def _set_op(self, b: BinaryOp, lhs: Block, rhs: Block) -> Block:
        on = set(b.on) if b.on is not None else None
        ig = set(b.ignoring) if b.ignoring is not None else None
        # Host row-matching path: materialize both sides once up front
        # (device arrays reject list indexing, and the per-row loop
        # below would otherwise sync repeatedly).
        lvals = np.asarray(lhs.values)
        rvals = np.asarray(rhs.values)
        rkeys = {fn._match_key(m, on, ig): i for i, m in enumerate(rhs.series)}
        if b.op == "or":
            extra_rows = [i for i, m in enumerate(rhs.series)
                          if fn._match_key(m, on, ig) not in
                          {fn._match_key(x, on, ig) for x in lhs.series}]
            vals = np.concatenate([lvals, rvals[extra_rows]]) if extra_rows \
                else lvals
            metas = lhs.series + [rhs.series[i] for i in extra_rows]
            return Block(lhs.step_times, vals, metas)
        out = np.full_like(lvals, np.nan)
        for i, m in enumerate(lhs.series):
            if i % 256 == 0:  # per-series host loop: cancellable
                xdeadline.check_current("set-op rows")
            j = rkeys.get(fn._match_key(m, on, ig))
            if b.op == "and":
                if j is not None:
                    out[i] = np.where(~np.isnan(rvals[j]), lvals[i], np.nan)
            else:  # unless
                if j is None:
                    out[i] = lvals[i]
                else:
                    out[i] = np.where(np.isnan(rvals[j]), lvals[i], np.nan)
        return lhs.with_values(out)

    # -- helpers -----------------------------------------------------------

    def _scalar_arg(self, e: Expr, steps: np.ndarray) -> float:
        """A static float parameter (topk k, quantile q, clamp bounds…).
        Per-step scalars collapse to their first finite value."""
        v = self._eval(e, steps)
        if isinstance(v, _Scalar):
            # scalar() rows may be numpy OR device arrays now that
            # blocks stay device-resident: normalize through numpy
            # before collapsing (a device (T,) array must not escape
            # into int(k)/float() call sites).
            if getattr(v.value, "ndim", 0):
                arr = np.asarray(v.value)
                finite = arr[np.isfinite(arr)]
                return float(finite[0]) if len(finite) else float("nan")
            return v.value
        raise ValueError("expected scalar argument")

    def _string_arg(self, e: Expr) -> str:
        if isinstance(e, StringLiteral):
            return e.value
        raise ValueError("expected string argument")
