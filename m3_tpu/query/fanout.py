"""Query fanout: multi-namespace, resolution-aware fetch + merge.

Reference parity: `src/query/storage/fanout/storage.go:50,110,540` (fan
queries across local namespaces and remote stores, merge results) and
the namespace resolution logic of `src/query/storage/m3/storage.go:215-225`
(pick, per query window, which retention/resolution namespaces must be
consulted; consolidate multi-resolution data).

Selection rule (resolveClusterNamespacesForQuery distilled):

* Sources are (storage, resolution, retention) triples — e.g. the raw
  10s/2d namespace plus downsampled 1m/30d and 1h/1y namespaces the
  coordinator's rollup rules populate.
* The finest-resolution source whose retention covers the whole query
  window serves it alone (fast path — no merge cost).
* Otherwise the window is partitioned into disjoint time bands, one per
  source: the finest source serves the most recent band (everything its
  retention covers), each coarser source serves only the strictly older
  band beyond the next-finer source's retention.  Bands never overlap,
  so coarse aggregate samples can never interleave with raw samples
  over the same interval — the consolidation-by-coverage the reference
  does when mixing resolutions.

Overload contract: multi-source fetches run **concurrently**, each
bounded by the query's shared deadline (x/deadline; workers re-bind the
context since threads do not inherit it), so total fetch wall-clock is
the slowest source, never the sum.  Partial-result policy mirrors the
reference's fanout warnings: a **required** source that fails or misses
the deadline fails the query (typed :class:`PartialResultError`, or the
underlying ``DeadlineExceeded``); a non-required source (a remote
region, a coarse historical namespace) degrades to a ``warnings`` entry
on the bound deadline, surfaced through the HTTP response.
"""

from __future__ import annotations

import dataclasses
import threading
import time
from typing import Callable, Dict, List, Sequence

from m3_tpu.query.block import RawBlock, SeriesMeta
from m3_tpu.storage.series_merge import merge_point_sources
from m3_tpu.x import deadline as xdeadline
from m3_tpu.x.deadline import DeadlineExceeded


class PartialResultError(RuntimeError):
    """A REQUIRED fanout source failed or missed the deadline — the
    merged result would silently be missing data the caller considers
    load-bearing.  Carries the per-source failures."""

    def __init__(self, failures: Dict[str, Exception]):
        detail = "; ".join(f"{k}: {v}" for k, v in sorted(failures.items()))
        super().__init__(f"partial result: {detail}")
        self.failures = failures


def _failure_error(failures: Dict[str, Exception]) -> Exception:
    """The exception for a set of load-bearing source failures.  A LONE
    overload-typed error stays itself (``DeadlineExceeded`` → 504,
    ``QueryLimitExceeded`` → 429); everything else — transport errors,
    open breakers, multi-source mixes — wraps in
    :class:`PartialResultError` so the API maps it as a server-side
    condition (502/504/429), never a 400."""
    if len(failures) == 1:
        from m3_tpu.storage.limits import QueryLimitExceeded

        only = next(iter(failures.values()))
        if isinstance(only, (DeadlineExceeded, QueryLimitExceeded)):
            return only
    return PartialResultError(failures)


@dataclasses.dataclass(frozen=True)
class FanoutSource:
    """One queryable namespace (or remote store) + its storage policy.
    ``required=False`` sources (remote regions, historical coarse
    namespaces) degrade to a warning instead of failing the query."""

    storage: object  # fetch_raw(name, matchers, start, end) -> RawBlock
    resolution_nanos: int
    retention_nanos: int
    required: bool = True
    name: str = ""

    def label(self, i: int) -> str:
        return self.name or f"source[{i}]"


def _accumulate_block(blk: RawBlock, per_series: Dict[tuple, List[List[tuple]]]) -> None:
    """Unpack one RawBlock's series into the shared (tags -> point-list
    sources) accumulator both fanout shapes merge from."""
    for i, meta in enumerate(blk.series):
        c = int(blk.counts[i])
        pts = list(zip(blk.ts[i, :c].tolist(), blk.values[i, :c].tolist()))
        per_series.setdefault(meta.tags, []).append(pts)


def _merged_block(per_series: Dict[tuple, List[List[tuple]]]) -> RawBlock:
    keys = sorted(per_series)
    pts_out = [merge_point_sources(per_series[k]) for k in keys]
    return RawBlock.from_lists(pts_out, [SeriesMeta(k) for k in keys])


def _fetch_concurrent(jobs: List[tuple]) -> List:
    """Run ``(label, fn)`` jobs concurrently under the caller's bound
    deadline.  Returns a parallel list of results/exceptions.  Join
    waits are deadline-bounded: a worker still running once the budget
    is spent is recorded as ``DeadlineExceeded`` (its wire call carries
    its own deadline-derived socket timeout, so the thread itself
    unwinds cooperatively rather than leaking forever)."""
    dl = xdeadline.current()
    if len(jobs) == 1:
        label, fn = jobs[0]
        try:
            return [fn()]
        except Exception as e:  # noqa: BLE001 — classified by caller
            return [e]
    out: List = [None] * len(jobs)
    # Slot protocol: once the main thread gives up on a straggler and
    # claims its slot as DeadlineExceeded, the still-running worker must
    # never overwrite it (the caller is already classifying `out`); a
    # worker that lands BEFORE the claim keeps its real result.
    done = [False] * len(jobs)
    claimed = [False] * len(jobs)
    mu = threading.Lock()

    def run(i: int, fn: Callable[[], RawBlock]) -> None:
        # threads do NOT inherit contextvars: re-bind the shared
        # deadline so every source's wire hops stay budget-bounded
        try:
            with xdeadline.bind(dl):
                r: object = fn()
        except Exception as e:  # noqa: BLE001 — classified by caller
            r = e
        with mu:
            if not claimed[i]:
                out[i] = r
                done[i] = True

    threads = [
        threading.Thread(target=run, args=(i, fn), daemon=True,
                         name=f"fanout-{label}")
        for i, (label, fn) in enumerate(jobs)
    ]
    for t in threads:
        t.start()
    for i, t in enumerate(threads):
        if dl is None:
            t.join()
            continue
        t.join(max(dl.remaining(), 0.0))
        if t.is_alive():
            # cooperative: the worker's own socket timeout/check will
            # unwind it; the QUERY must answer now (dl.exceeded so N
            # stragglers still count as ONE blown deadline)
            with mu:
                if not done[i]:
                    claimed[i] = True
                    out[i] = dl.exceeded(
                        f"fanout source {jobs[i][0]}: deadline exceeded")
    return out


class FanoutStorage:
    """Engine-facing Storage over multiple namespaces/remotes."""

    def __init__(
        self,
        sources: Sequence[FanoutSource],
        now_fn: Callable[[], int] = time.time_ns,
    ):
        if not sources:
            raise ValueError("fanout needs at least one source")
        # finest resolution first
        self.sources = sorted(sources, key=lambda s: s.resolution_nanos)
        # Retention is measured from wall-clock now, NOT the query end:
        # a short window queried far in the past would otherwise look
        # "covered" by the raw namespace that retains nothing that old.
        self.now_fn = now_fn

    def _select(
        self, start_nanos: int, end_nanos: int, now_nanos: int
    ) -> List[FanoutSource]:
        """Sources needed for the query window: the finest source serves
        alone when its retention covers the whole window; otherwise all
        overlapping sources, band-partitioned in fetch_raw (each range
        gets the finest data available for it; sources whose band comes
        out empty are skipped there, so no spurious coarse fetches)."""
        finest = self.sources[0]
        if now_nanos - finest.retention_nanos <= start_nanos:
            return [finest]
        return [
            s
            for s in self.sources
            if now_nanos - s.retention_nanos < end_nanos
        ]

    def fetch_raw(
        self,
        name,
        matchers,
        start_nanos: int,
        end_nanos: int,
        now_nanos: int | None = None,
    ) -> RawBlock:
        now = self.now_fn() if now_nanos is None else now_nanos
        chosen = self._select(start_nanos, end_nanos, now)
        if len(chosen) == 1:
            # Same failure policy as the fanned path: required sources
            # fail typed (never a client-error mapping), best-effort
            # sources degrade to a warning + empty result.
            src = chosen[0]
            try:
                return src.storage.fetch_raw(
                    name, matchers, start_nanos, end_nanos
                )
            except Exception as e:  # noqa: BLE001 — classified below
                if src.required:
                    raise _failure_error({src.label(0): e})
                dl = xdeadline.current()
                if dl is not None:
                    dl.add_warning(
                        f"fanout source {src.label(0)} skipped: {e}")
                return _merged_block({})
        # Band partition: finest source serves its whole covered range;
        # each coarser source only the strictly older remainder.  Bands
        # are disjoint, so no cross-resolution interleaving can occur.
        jobs: List[tuple] = []
        bands: List[FanoutSource] = []
        hi = end_nanos
        for i, src in enumerate(chosen):  # finest → coarsest
            lo = max(start_nanos, now - src.retention_nanos)
            if lo < hi:
                jobs.append((
                    src.label(i),
                    (lambda s=src, a=lo, b=hi:
                     s.storage.fetch_raw(name, matchers, a, b)),
                ))
                bands.append(src)
            hi = min(hi, lo)
            if hi <= start_nanos:
                break
        per_series: Dict[tuple, List[List[tuple]]] = {}
        failures: Dict[str, Exception] = {}
        dl = xdeadline.current()
        for (label, _), src, result in zip(jobs, bands,
                                           _fetch_concurrent(jobs)):
            if isinstance(result, Exception):
                if src.required:
                    failures[label] = result
                elif dl is not None:
                    dl.add_warning(f"fanout source {label} skipped: {result}")
                continue
            _accumulate_block(result, per_series)
        if failures:
            raise _failure_error(failures)
        return _merged_block(per_series)


class FederatedStorage:
    """Cross-region union: query EVERY store and merge same-ID series.

    The band-partitioned FanoutStorage above divides a window between
    resolutions of the SAME data; federation is the other axis — each
    store (the local fanout + remote coordinators, `query/remote`) holds
    DIFFERENT series, with possible overlap deduplicated point-wise
    (reference `fanout/storage.go` merging local clusters with remote
    stores).  Stores are queried CONCURRENTLY under the bound deadline.
    A store that fails is skipped (best-effort federation, like the
    reference's partial-result handling, with a ``warnings`` entry on
    the bound deadline) unless every store fails — except stores listed
    in ``required`` (by index), whose failure is load-bearing and
    raises :class:`PartialResultError`."""

    def __init__(self, stores: Sequence[object],
                 required: Sequence[int] = ()):
        if not stores:
            raise ValueError("federation needs at least one store")
        self.stores = list(stores)
        self.required = frozenset(required)

    @staticmethod
    def _store_label(i: int, st: object) -> str:
        peer = getattr(st, "peer", None)
        return f"store[{i}]({peer})" if peer else f"store[{i}]"

    def fetch_raw(self, name, matchers, start_nanos, end_nanos) -> RawBlock:
        jobs = [
            (self._store_label(i, st),
             (lambda s=st: s.fetch_raw(name, matchers, start_nanos,
                                       end_nanos)))
            for i, st in enumerate(self.stores)
        ]
        results = _fetch_concurrent(jobs)
        per_series: Dict[tuple, List[List[tuple]]] = {}
        all_failures: Dict[str, Exception] = {}
        required_failures: Dict[str, Exception] = {}
        dl = xdeadline.current()
        for i, ((label, _), result) in enumerate(zip(jobs, results)):
            if isinstance(result, Exception):
                all_failures[label] = result
                if i in self.required:
                    required_failures[label] = result
                elif dl is not None:
                    dl.add_warning(
                        f"federated store {label} skipped: {result}")
                continue
            _accumulate_block(result, per_series)
        if required_failures:
            raise _failure_error(required_failures)
        if all_failures and len(all_failures) == len(self.stores):
            # EVERY store failed: nothing merged, surface typed too
            raise _failure_error(all_failures)
        return _merged_block(per_series)
