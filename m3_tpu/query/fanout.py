"""Query fanout: multi-namespace, resolution-aware fetch + merge.

Reference parity: `src/query/storage/fanout/storage.go:50,110,540` (fan
queries across local namespaces and remote stores, merge results) and
the namespace resolution logic of `src/query/storage/m3/storage.go:215-225`
(pick, per query window, which retention/resolution namespaces must be
consulted; consolidate multi-resolution data).

Selection rule (resolveClusterNamespacesForQuery distilled):

* Sources are (storage, resolution, retention) triples — e.g. the raw
  10s/2d namespace plus downsampled 1m/30d and 1h/1y namespaces the
  coordinator's rollup rules populate.
* The finest-resolution source whose retention covers the whole query
  window serves it alone (fast path — no merge cost).
* Otherwise the window is partitioned into disjoint time bands, one per
  source: the finest source serves the most recent band (everything its
  retention covers), each coarser source serves only the strictly older
  band beyond the next-finer source's retention.  Bands never overlap,
  so coarse aggregate samples can never interleave with raw samples
  over the same interval — the consolidation-by-coverage the reference
  does when mixing resolutions.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Callable, Dict, List, Sequence

from m3_tpu.query.block import RawBlock, SeriesMeta
from m3_tpu.storage.series_merge import merge_point_sources


@dataclasses.dataclass(frozen=True)
class FanoutSource:
    """One queryable namespace (or remote store) + its storage policy."""

    storage: object  # fetch_raw(name, matchers, start, end) -> RawBlock
    resolution_nanos: int
    retention_nanos: int


def _accumulate_block(blk: RawBlock, per_series: Dict[tuple, List[List[tuple]]]) -> None:
    """Unpack one RawBlock's series into the shared (tags -> point-list
    sources) accumulator both fanout shapes merge from."""
    for i, meta in enumerate(blk.series):
        c = int(blk.counts[i])
        pts = list(zip(blk.ts[i, :c].tolist(), blk.values[i, :c].tolist()))
        per_series.setdefault(meta.tags, []).append(pts)


def _merged_block(per_series: Dict[tuple, List[List[tuple]]]) -> RawBlock:
    keys = sorted(per_series)
    pts_out = [merge_point_sources(per_series[k]) for k in keys]
    return RawBlock.from_lists(pts_out, [SeriesMeta(k) for k in keys])


class FanoutStorage:
    """Engine-facing Storage over multiple namespaces/remotes."""

    def __init__(
        self,
        sources: Sequence[FanoutSource],
        now_fn: Callable[[], int] = time.time_ns,
    ):
        if not sources:
            raise ValueError("fanout needs at least one source")
        # finest resolution first
        self.sources = sorted(sources, key=lambda s: s.resolution_nanos)
        # Retention is measured from wall-clock now, NOT the query end:
        # a short window queried far in the past would otherwise look
        # "covered" by the raw namespace that retains nothing that old.
        self.now_fn = now_fn

    def _select(
        self, start_nanos: int, end_nanos: int, now_nanos: int
    ) -> List[FanoutSource]:
        """Sources needed for the query window: the finest source serves
        alone when its retention covers the whole window; otherwise all
        overlapping sources, band-partitioned in fetch_raw (each range
        gets the finest data available for it; sources whose band comes
        out empty are skipped there, so no spurious coarse fetches)."""
        finest = self.sources[0]
        if now_nanos - finest.retention_nanos <= start_nanos:
            return [finest]
        return [
            s
            for s in self.sources
            if now_nanos - s.retention_nanos < end_nanos
        ]

    def fetch_raw(
        self,
        name,
        matchers,
        start_nanos: int,
        end_nanos: int,
        now_nanos: int | None = None,
    ) -> RawBlock:
        now = self.now_fn() if now_nanos is None else now_nanos
        chosen = self._select(start_nanos, end_nanos, now)
        if len(chosen) == 1:
            return chosen[0].storage.fetch_raw(
                name, matchers, start_nanos, end_nanos
            )
        # Band partition: finest source serves its whole covered range;
        # each coarser source only the strictly older remainder.  Bands
        # are disjoint, so no cross-resolution interleaving can occur.
        per_series: Dict[tuple, List[List[tuple]]] = {}
        hi = end_nanos
        for src in chosen:  # finest → coarsest
            lo = max(start_nanos, now - src.retention_nanos)
            if lo < hi:
                _accumulate_block(
                    src.storage.fetch_raw(name, matchers, lo, hi), per_series
                )
            hi = min(hi, lo)
            if hi <= start_nanos:
                break
        return _merged_block(per_series)


class FederatedStorage:
    """Cross-region union: query EVERY store and merge same-ID series.

    The band-partitioned FanoutStorage above divides a window between
    resolutions of the SAME data; federation is the other axis — each
    store (the local fanout + remote coordinators, `query/remote`) holds
    DIFFERENT series, with possible overlap deduplicated point-wise
    (reference `fanout/storage.go` merging local clusters with remote
    stores).  A store that fails is skipped (best-effort federation,
    like the reference's partial-result handling) unless every store
    fails."""

    def __init__(self, stores: Sequence[object]):
        if not stores:
            raise ValueError("federation needs at least one store")
        self.stores = list(stores)

    def fetch_raw(self, name, matchers, start_nanos, end_nanos) -> RawBlock:
        per_series: Dict[tuple, List[List[tuple]]] = {}
        errors: List[Exception] = []
        for st in self.stores:
            try:
                blk = st.fetch_raw(name, matchers, start_nanos, end_nanos)
            except Exception as e:  # noqa: BLE001 — best-effort fan-out
                errors.append(e)
                continue
            _accumulate_block(blk, per_series)
        if errors and len(errors) == len(self.stores):
            raise errors[0]
        return _merged_block(per_series)
