"""Columnar block model: the unit of data between storage and functions.

Equivalent of `src/query/block` (`column.go`, series/step iterators in
`types.go`): a block is a (series × step) matrix of float64 samples on a
regular step grid, plus per-series metadata (tags).  Where the reference
exposes pull-based iterators consumed one step/series at a time, the TPU
form IS the matrix — every function is an array op over it, NaN marks
missing samples (Prometheus staleness semantics).

`RawBlock` carries irregular raw datapoints (padded (S, P) with counts)
for temporal functions that need the actual samples within each window
(rate & friends, *_over_time) — mirroring how the reference's temporal
nodes re-read raw series rather than pre-aligned steps
(`src/query/functions/temporal/base.go:102-230`).
"""

from __future__ import annotations

import dataclasses

import numpy as np


@dataclasses.dataclass(frozen=True)
class SeriesMeta:
    """Tags for one series (reference block.SeriesMeta)."""

    tags: tuple[tuple[bytes, bytes], ...]

    @classmethod
    def from_dict(cls, d: dict[bytes, bytes]) -> "SeriesMeta":
        return cls(tuple(sorted(d.items())))

    def as_dict(self) -> dict[bytes, bytes]:
        return dict(self.tags)

    def drop(self, names: set[bytes]) -> "SeriesMeta":
        return SeriesMeta(tuple((n, v) for n, v in self.tags if n not in names))

    def keep(self, names: set[bytes]) -> "SeriesMeta":
        return SeriesMeta(tuple((n, v) for n, v in self.tags if n in names))

    def drop_name(self) -> "SeriesMeta":
        return self.drop({b"__name__"})


@dataclasses.dataclass
class Block:
    """Step-aligned block: values[s, t] at step_times[t] (NaN = no sample).

    ``values`` may be a numpy array OR a device (JAX) array: the engine
    keeps blocks device-resident between pipeline stages — a
    rate→histogram_quantile chain at 100K series moves ~200MB per hop,
    which must not round-trip through the host — and materializes ONCE
    at the query boundary (`Engine._execute_range`).  Host-side
    consumers inside the engine simply use numpy ops (a device array
    converts implicitly); anything outside the engine only ever sees
    numpy."""

    step_times: np.ndarray  # (T,) int64 UnixNanos
    values: np.ndarray  # (S, T) float64 (numpy or device array)
    series: list[SeriesMeta]

    @property
    def num_series(self) -> int:
        return self.values.shape[0]

    @property
    def num_steps(self) -> int:
        return self.values.shape[1]

    def with_values(self, values, series: list[SeriesMeta] | None = None) -> "Block":
        return Block(self.step_times, values,
                     series if series is not None else self.series)

    def materialized(self) -> "Block":
        """Force values to host float64 (the query-boundary sync)."""
        return Block(self.step_times, np.asarray(self.values, np.float64),
                     self.series)


@dataclasses.dataclass
class RawBlock:
    """Irregular raw datapoints per series, time-sorted and right-padded."""

    ts: np.ndarray  # (S, P) int64; padded tail = i64 max
    values: np.ndarray  # (S, P) float64
    counts: np.ndarray  # (S,) int64 real points per series
    series: list[SeriesMeta]

    @classmethod
    def from_lists(cls, pts: list[list[tuple[int, float]]],
                   series: list[SeriesMeta]) -> "RawBlock":
        S = len(pts)
        P = max((len(p) for p in pts), default=0)
        P = max(P, 1)
        ts = np.full((S, P), np.iinfo(np.int64).max, np.int64)
        vals = np.full((S, P), np.nan)
        counts = np.zeros(S, np.int64)
        for i, p in enumerate(pts):
            counts[i] = len(p)
            if p:
                ts[i, : len(p)] = [t for t, _ in p]
                vals[i, : len(p)] = [v for _, v in p]
        return cls(ts, vals, counts, series)
