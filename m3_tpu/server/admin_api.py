"""Admin API: namespace / placement / topic / runtime-option CRUD.

Equivalent of the reference's coordinator admin handlers
(`src/query/api/v1/handler/{namespace,placement...}` +
`cluster/placementhandler` + topic handlers): cluster metadata CRUD
over the KV control plane.  Routes:

    GET/POST          /api/v1/services/m3db/namespace
    DELETE            /api/v1/services/m3db/namespace/<name>
    GET/DELETE        /api/v1/services/m3db/placement
    POST              /api/v1/services/m3db/placement/init
    POST              /api/v1/services/m3db/placement          (add instance)
    POST              /api/v1/services/m3db/placement/replace  (body
                      {"leaving_id": ..., "instance": {...}}: the
                      newcomer takes the leaver's shards INITIALIZING,
                      streaming from it — the rolling node-replace verb)
    DELETE            /api/v1/services/m3db/placement/<instance_id>
                      (staged remove_instance while the instance still
                      owns shards; outright forget once it is drained —
                      also the dead-leaver cleanup)
    POST              /api/v1/topology/migrate                 (run one
                      shard-migration pass in-process now, instead of
                      waiting for the mediator tick)
    GET               /api/v1/topology/status                  (the same
                      migration-progress document /health embeds)
    GET/POST          /api/v1/topic
    GET/PUT           /api/v1/runtime                          (options)
    POST              /api/v1/database/scrub                   (on-demand
                      corruption sweep + peer repair; body optionally
                      {"budget": N volumes (0 = whole disk, the default),
                       "repair": bool})
    GET/POST          /api/v1/debug/faults                     (runtime
                      faultpoint re-arm: GET = armed specs + counters;
                      POST {"disarm": true|[points], "arm":
                      "point=mode[:k=v]*;...", "reset_counters": bool}
                      — the M3_FAULTPOINTS grammar, applied LIVE so a
                      chaos scheduler flips fault windows without
                      restarting the node; counters survive re-arm)

Every placement mutation goes through ``PlacementService.update`` — a
get→mutate→CAS loop with bounded retry on version conflict, so two
concurrent admin calls (or an admin call racing a node's cutover CAS)
both land instead of one 500ing.

Query-path overload controls live on the MAIN HTTP API
(server/http_api.py), not here: the read endpoints accept a
``timeout=`` param (end-to-end deadline, default
``query.default_timeout``) and map the typed overload errors to
**429** (resource limit), **503 + Retry-After** (admission shed) and
**504** (deadline exceeded); admission/breaker/slow-query state is
observable on every node's ``/health`` (``query`` section) and
``/metrics`` — see TESTING.md "Query deadlines, admission & breakers".
"""

from __future__ import annotations

import dataclasses
import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from m3_tpu.cluster.kv import KVStore
from m3_tpu.cluster.namespace_registry import NamespaceMeta, NamespaceRegistry
from m3_tpu.cluster.placement import (
    Instance, PlacementService, add_instance, forget_instance,
    initial_placement, remove_instance, replace_instance,
)
from m3_tpu.core.runtime_options import RuntimeOptionsManager
from m3_tpu.msg.bus import ConsumerService, ConsumptionType, Topic, TopicService


# Retention -> recommended block size ladder (reference
# handler/database/create.go recommendedBlockSizesByRetentionAsc).
_BLOCK_LADDER_HOURS = (
    (12, 0.5), (24, 1), (7 * 24, 2), (30 * 24, 12), (365 * 24, 24),
)


def _recommended_block_size(retention_nanos: int) -> int:
    hours = retention_nanos / 3600e9
    for upto, block in _BLOCK_LADDER_HOURS:
        if hours <= upto:
            return int(block * 3600 * 10**9)
    return 24 * 3600 * 10**9


def _parse_dur_nanos(s) -> int:
    from m3_tpu.core.config import parse_duration

    return parse_duration(str(s))


class AdminContext:
    def __init__(self, kv: KVStore, db=None, aggregator=None, scrubber=None,
                 migrator=None, tracer=None, selfmon=None, controller=None):
        self.kv = kv
        self.namespaces = NamespaceRegistry(kv)
        self.placements = PlacementService(kv)
        self.topics = TopicService(kv)
        self.runtime = RuntimeOptionsManager(kv)
        self.aggregator = aggregator
        self.scrubber = scrubber
        self.migrator = migrator  # storage.migration.ShardMigrator | None
        self.selfmon = selfmon  # instrument.selfmon.SelfMonitor | None
        self.controller = controller  # x.controller.Controller | None
        # span-ring debug surface: defaults to the database's tracer so
        # the admin port serves the same ring as the main API's
        # /api/v1/debug/traces (dtest trace collection hits either)
        self.tracer = (tracer if tracer is not None
                       else getattr(db, "tracer", None))
        if db is not None:
            self.namespaces.attach(db)


def _parse_instance(body: dict) -> Instance:
    return Instance(body["id"], body.get("isolation_group", ""),
                    body.get("weight", 1),
                    shard_set_id=body.get("shard_set_id", 0),
                    endpoint=body.get("endpoint", ""))


class _AdminHandler(BaseHTTPRequestHandler):
    ctx: AdminContext = None

    def log_message(self, fmt, *args):
        pass

    def _json(self, code: int, obj) -> None:
        data = json.dumps(obj).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(data)))
        self.end_headers()
        self.wfile.write(data)

    def _body(self) -> dict:
        n = int(self.headers.get("Content-Length", 0))
        return json.loads(self.rfile.read(n)) if n else {}

    def do_GET(self):
        try:
            path = self.path.split("?")[0].rstrip("/")
            if path == "/health":
                # Admin-port liveness with the SAME ``slo`` section the
                # main port serves (the traces/faults parity pattern):
                # an operator cut off from the serving port — admission
                # shedding, a wedged handler pool — still reads the
                # burn-rate verdicts from the admin side.
                out = {"ok": True}
                sm = self.ctx.selfmon
                if sm is not None:
                    try:
                        slo = sm.health_slo()
                        if slo is not None:
                            out["slo"] = slo
                    except Exception:  # noqa: BLE001 — health never 500s
                        pass
                # ... and the same ``controller`` section: the
                # self-healing state must be readable even when the
                # controller itself shed the serving port's slots.
                if self.ctx.controller is not None:
                    try:
                        out["controller"] = self.ctx.controller.status()
                    except Exception:  # noqa: BLE001 — health never 500s
                        pass
                return self._json(200, out)
            if path == "/api/v1/debug/traces":
                # the same ring + filters the main API serves, through
                # the ONE shared response builder (tracing.
                # traces_response): trace collection must work through
                # whichever port a harness has (dtest joins spans from
                # every process) and the two handlers must not drift
                from urllib.parse import parse_qs, urlparse

                from m3_tpu.instrument.tracing import traces_response

                tr = self.ctx.tracer
                if tr is None:
                    return self._json(404, {"error": "no tracer configured"})
                q = parse_qs(urlparse(self.path).query)
                return self._json(200, traces_response(
                    tr, trace_id=q.get("trace_id", [None])[0],
                    name=q.get("name", [None])[0]))
            if path == "/api/v1/debug/faults":
                # same shared builder as the main API (the
                # traces_response pattern): the chaos scheduler arms
                # through whichever port it holds
                from m3_tpu.x import fault

                return self._json(200, fault.registry_response())
            if path == "/api/v1/services/m3db/namespace":
                return self._json(200, {
                    "registry": {
                        n: dataclasses.asdict(m)
                        for n, m in self.ctx.namespaces.all().items()
                    }
                })
            if path == "/api/v1/services/m3db/placement":
                p = self.ctx.placements.get()
                if p is None:
                    return self._json(404, {"error": "no placement"})
                return self._json(200, json.loads(p.to_json()))
            if path == "/api/v1/topic":
                names = [k.split("/", 1)[1] for k in self.ctx.kv.keys()
                         if k.startswith("_topic/")]
                return self._json(200, {"topics": names})
            if path.startswith("/api/v1/topic/"):
                t = self.ctx.topics.get(path.rsplit("/", 1)[1])
                if t is None:
                    return self._json(404, {"error": "no such topic"})
                return self._json(200, json.loads(t.to_json()))
            if path == "/api/v1/runtime":
                return self._json(200, self.ctx.runtime.snapshot())
            if path == "/api/v1/topology/status":
                if self.ctx.migrator is None:
                    return self._json(
                        404, {"error": "no shard migrator in this process "
                              "(db.instance_id not configured)"})
                return self._json(200, {"topology": self.ctx.migrator.status()})
            if path == "/api/v1/aggregator/status":
                # Engine operational counters incl. forwarded-tail
                # conflicts (the reference aggregator httpd's /status
                # role) — a silent-drop edge must be auditable from
                # outside the process.
                if self.ctx.aggregator is None:
                    return self._json(
                        404, {"error": "no aggregator in this process"})
                return self._json(
                    200, {"counters": self.ctx.aggregator.counters()})
            return self._json(404, {"error": f"unknown path {path}"})
        except Exception as e:  # noqa: BLE001 — API boundary
            return self._json(400, {"error": str(e)})

    def do_POST(self):
        try:
            path = self.path.split("?")[0].rstrip("/")
            body = self._body()
            if path == "/api/v1/services/m3db/namespace":
                meta = NamespaceMeta(**body)
                self.ctx.namespaces.add(meta)
                return self._json(200, dataclasses.asdict(meta))
            if path == "/api/v1/services/m3db/placement/init":
                instances = [_parse_instance(i) for i in body["instances"]]

                def init_mutate(cur):
                    if cur is not None:
                        raise ValueError(
                            "placement already exists; DELETE it first")
                    if body.get("mirrored", False):
                        # Aggregator-style HA placement (algo/mirrored.go):
                        # shard sets of RF instances sharing identical
                        # shards.
                        from m3_tpu.cluster.placement_mirrored import (
                            mirrored_initial_placement,
                        )

                        return mirrored_initial_placement(
                            instances, body.get("num_shards", 64),
                            body.get("rf", 3),
                        )
                    return initial_placement(
                        instances, body.get("num_shards", 64),
                        body.get("rf", 3),
                    )

                p = self.ctx.placements.update(init_mutate)
                return self._json(200, json.loads(p.to_json()))
            if path == "/api/v1/services/m3db/placement":
                if self.ctx.placements.get() is None:
                    # 404, not 400: the resource is missing (run init),
                    # the request body may be perfectly fine
                    return self._json(404, {"error": "no placement; init first"})

                def add_mutate(p):
                    if p is None:
                        raise KeyError("no placement; init first")
                    if p.is_mirrored:
                        # Mirrored placements grow by whole shard sets
                        # of RF instances (algo/mirrored.go
                        # AddInstances); a solo add would break the
                        # mirror invariant.
                        insts = body.get("instances")
                        if not insts:
                            raise ValueError(
                                "mirrored placement: POST {'instances': "
                                "[RF members sharing a new shard_set_id]}")
                        from m3_tpu.cluster.placement_mirrored import (
                            mirrored_add_group,
                        )

                        group = [_parse_instance(dict(i, shard_set_id=i[
                            "shard_set_id"])) for i in insts]
                        return mirrored_add_group(p, group)
                    return add_instance(p, _parse_instance(body))

                p2 = self.ctx.placements.update(add_mutate)
                return self._json(200, json.loads(p2.to_json()))
            if path == "/api/v1/services/m3db/placement/replace":
                # Rolling node replace (algo ReplaceInstances): the
                # newcomer takes exactly the leaver's shards
                # INITIALIZING with a streaming source; node-side
                # migrators do the rest.  Mirrored placements use the
                # mirror-preserving variant (the newcomer streams from
                # the SURVIVING mirror, algo/mirrored.go).
                if self.ctx.placements.get() is None:
                    return self._json(404, {"error": "no placement; init first"})
                new = _parse_instance(body["instance"])
                leaving = body["leaving_id"]

                def replace_mutate(p):
                    if p is None:
                        raise KeyError("no placement; init first")
                    if p.is_mirrored:
                        from m3_tpu.cluster.placement_mirrored import (
                            mirrored_replace_instance,
                        )

                        return mirrored_replace_instance(p, leaving, new)
                    return replace_instance(p, leaving, new)

                p2 = self.ctx.placements.update(replace_mutate)
                return self._json(200, json.loads(p2.to_json()))
            if path == "/api/v1/topology/migrate":
                if self.ctx.migrator is None:
                    return self._json(
                        404, {"error": "no shard migrator in this process "
                              "(db.instance_id not configured)"})
                return self._json(200, {"migrate": self.ctx.migrator.tick()})
            if path == "/api/v1/database/create":
                # One-call bring-up (reference handler/database/create.go):
                # namespace with a retention-recommended block size, plus a
                # single-node placement when none exists ("local" type).
                name = body.get("namespaceName")
                if not name:
                    return self._json(400, {"error": "namespaceName required"})
                retention = _parse_dur_nanos(body.get("retentionTime", "48h"))
                block = _recommended_block_size(retention)
                meta = NamespaceMeta(
                    name=name, retention_nanos=retention,
                    block_size_nanos=block,
                    num_shards=int(body.get("numShards", 4)),
                )
                self.ctx.namespaces.add(meta)
                placement_out = None
                if (body.get("type", "local") == "local"
                        and self.ctx.placements.get() is None):
                    host = body.get("hostID", "m3db_local")

                    def local_mutate(cur):
                        if cur is not None:
                            return cur  # raced another create: keep it
                        return initial_placement(
                            [Instance(host)], num_shards=meta.num_shards,
                            rf=1)

                    p = self.ctx.placements.update(local_mutate)
                    placement_out = json.loads(p.to_json())
                return self._json(200, {
                    "namespace": dataclasses.asdict(meta),
                    "placement": placement_out,
                })
            if path == "/api/v1/debug/faults":
                # Runtime re-arm: validate-then-mutate through the ONE
                # shared grammar/applier in x/fault (disarm first, then
                # arm; counters preserved) — the soak's chaos scheduler
                # opens/closes wire-fault windows on live nodes here.
                from m3_tpu.x import fault

                return self._json(200, fault.apply_request(body))
            if path == "/api/v1/database/scrub":
                # On-demand integrity sweep (reference ops run
                # verify_data_files out-of-band; here the scrubber is
                # in-process so the sweep also quarantines and repairs
                # from peers).  Default budget 0 = the whole disk.
                if self.ctx.scrubber is None:
                    return self._json(
                        404, {"error": "no scrubber in this process"})
                stats = self.ctx.scrubber.run_once(
                    budget=int(body.get("budget", 0)),
                    repair=bool(body.get("repair", True)),
                )
                return self._json(200, {"scrub": stats})
            if path == "/api/v1/topic":
                t = Topic(
                    body["name"], body.get("num_shards", 64),
                    tuple(
                        ConsumerService(
                            c["name"],
                            ConsumptionType(c.get("consumption", "shared")),
                        )
                        for c in body.get("consumer_services", [])
                    ),
                )
                self.ctx.topics.set(t)
                return self._json(200, json.loads(t.to_json()))
            return self._json(404, {"error": f"unknown path {path}"})
        except Exception as e:  # noqa: BLE001 — every failure must come
            # back as an HTTP error, never a dropped connection (config
            # parse errors, registry conflicts, placement validation...)
            code = 400 if isinstance(
                e, (KeyError, TypeError, ValueError)) else 500
            return self._json(code, {"error": f"{type(e).__name__}: {e}"})

    def do_PUT(self):
        try:
            path = self.path.split("?")[0].rstrip("/")
            if path == "/api/v1/runtime":
                body = self._body()
                # validate the WHOLE body before applying anything — a
                # partial apply followed by a 400 would leave the
                # operator believing nothing changed
                for name, value in body.items():
                    self.ctx.runtime.validate(name, value)
                for name, value in body.items():
                    self.ctx.runtime.set(name, value)
                return self._json(200, self.ctx.runtime.snapshot())
            return self._json(404, {"error": f"unknown path {path}"})
        except KeyError as e:
            return self._json(400, {"error": str(e)})

    def do_DELETE(self):
        try:
            path = self.path.split("?")[0].rstrip("/")
            if path.startswith("/api/v1/services/m3db/namespace/"):
                name = path.rsplit("/", 1)[1]
                if not self.ctx.namespaces.remove(name):
                    return self._json(404, {"error": f"no namespace {name}"})
                return self._json(200, {"deleted": name})
            if path == "/api/v1/services/m3db/placement":
                self.ctx.kv.delete(self.ctx.placements.key)
                return self._json(200, {"deleted": "placement"})
            if path.startswith("/api/v1/services/m3db/placement/"):
                # Instance removal: staged (remove_instance — shards go
                # INITIALIZING on survivors, streaming from the leaver)
                # while the instance still owns live shards; outright
                # forget once it is drained/empty — which also covers a
                # dead leaver whose shards were already re-homed.
                iid = path.rsplit("/", 1)[1]

                def rm_mutate(p):
                    if p is None:
                        raise KeyError("no placement")
                    if iid not in p.instances:
                        raise KeyError(f"no instance {iid}")
                    try:
                        # drained/dead-leaver entry: drop it outright
                        # (forget_instance owns the live-shard guard)
                        return forget_instance(p, iid)
                    except ValueError:
                        if p.is_mirrored:
                            # removing one loaded member would break the
                            # shard-set mirror invariant; the mirror
                            # verbs operate on whole groups
                            raise ValueError(
                                "mirrored placement: replace the member "
                                "(POST .../placement/replace) or remove "
                                "its whole shard set")
                        return remove_instance(p, iid)

                p2 = self.ctx.placements.update(rm_mutate)
                return self._json(200, json.loads(p2.to_json()))
            return self._json(404, {"error": f"unknown path {path}"})
        except KeyError as e:
            return self._json(404, {"error": str(e)})
        except Exception as e:  # noqa: BLE001
            return self._json(400, {"error": str(e)})


def serve_admin_background(ctx: AdminContext, host: str = "127.0.0.1",
                           port: int = 0) -> ThreadingHTTPServer:
    handler = type("BoundAdmin", (_AdminHandler,), {"ctx": ctx})
    srv = ThreadingHTTPServer((host, port), handler)
    t = threading.Thread(target=srv.serve_forever, daemon=True)
    t.start()
    return srv
