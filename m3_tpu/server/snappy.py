"""Snappy block-format codec (pure Python).

Prometheus remote read/write bodies are snappy-compressed protobuf
(the reference handles them via golang/snappy in
`src/query/api/v1/handler/prometheus/remote`).  No snappy module ships
in this environment, so this implements the block format directly:
decompression handles the full tag set (literals + both copy forms);
compression emits a valid all-literal stream (legal snappy — every
decoder accepts it; we trade ratio for simplicity on the encode side,
exactly enough to serve read responses).

Format: [uncompressed length varint] then tagged elements:
  tag & 3 == 0  literal, length from tag (or trailing bytes for >60)
  tag & 3 == 1  copy: 4-11 byte length, 11-bit offset
  tag & 3 == 2  copy: 1-64 length, 16-bit LE offset
  tag & 3 == 3  copy: 1-64 length, 32-bit LE offset
"""

from __future__ import annotations


class SnappyError(ValueError):
    pass


def _read_uvarint(data: bytes, pos: int) -> tuple[int, int]:
    out = 0
    shift = 0
    while True:
        if pos >= len(data):
            raise SnappyError("truncated varint")
        b = data[pos]
        pos += 1
        out |= (b & 0x7F) << shift
        if not b & 0x80:
            return out, pos
        shift += 7
        if shift > 63:
            raise SnappyError("varint too long")


def _write_uvarint(n: int) -> bytes:
    out = bytearray()
    while True:
        b = n & 0x7F
        n >>= 7
        out.append(b | (0x80 if n else 0))
        if not n:
            return bytes(out)


def decompress(data: bytes) -> bytes:
    want, pos = _read_uvarint(data, 0)
    out = bytearray()
    while pos < len(data):
        tag = data[pos]
        pos += 1
        kind = tag & 3
        if kind == 0:  # literal
            ln = tag >> 2
            if ln >= 60:
                nbytes = ln - 59
                if pos + nbytes > len(data):
                    raise SnappyError("truncated literal length")
                ln = int.from_bytes(data[pos : pos + nbytes], "little")
                pos += nbytes
            ln += 1
            if pos + ln > len(data):
                raise SnappyError("truncated literal")
            out += data[pos : pos + ln]
            pos += ln
            continue
        if kind == 1:
            ln = ((tag >> 2) & 0x7) + 4
            off = ((tag >> 5) << 8) | data[pos]
            pos += 1
        elif kind == 2:
            ln = (tag >> 2) + 1
            off = int.from_bytes(data[pos : pos + 2], "little")
            pos += 2
        else:
            ln = (tag >> 2) + 1
            off = int.from_bytes(data[pos : pos + 4], "little")
            pos += 4
        if off == 0 or off > len(out):
            raise SnappyError(f"bad copy offset {off}")
        start = len(out) - off
        if off >= ln:
            # non-overlapping (the common case): one slice extend
            out += out[start : start + ln]
        else:
            # overlapping forward copy (RLE): byte-by-byte semantics
            for i in range(ln):
                out.append(out[start + i])
    if len(out) != want:
        raise SnappyError(f"length mismatch: got {len(out)}, want {want}")
    return bytes(out)


def compress(data: bytes) -> bytes:
    """All-literal snappy: valid for every decoder, no back-references."""
    out = bytearray(_write_uvarint(len(data)))
    pos = 0
    while pos < len(data):
        chunk = data[pos : pos + 65536]
        n = len(chunk) - 1
        if n < 60:
            out.append(n << 2)
        elif n < (1 << 8):
            out.append(60 << 2)
            out += n.to_bytes(1, "little")
        else:  # chunks cap at 65536, so 2 length bytes always suffice
            out.append(61 << 2)
            out += n.to_bytes(2, "little")
        out += chunk
        pos += len(chunk)
    return bytes(out)
