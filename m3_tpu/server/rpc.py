"""dbnode socket RPC: the cross-process data plane.

Equivalent of the reference's TChannel+Thrift ``Node`` service
(`src/dbnode/network/server/tchannelthrift/node/service.go` — Write
:1664, WriteTagged :1711, FetchTagged :736, FetchBlocksMetadataRawV2
:1529) plus the client side's per-host connections
(`src/dbnode/client/host_queue.go`, `connection_pool.go`).  Thrift
collapses to the framework's framed binary protocol (msg/protocol.py:
length prefix + type byte + adler32, struct-packed payloads) — same
contract, no IDL toolchain.

Two halves:

* ``DbNodeRpcServer`` — ThreadingTCPServer exporting a ``Database``'s
  data plane: write/write_tagged/read/query_ids, plus the block-level
  replication surface (list_block_filesets / block_metadata /
  read_block / write_block) that repair and peers bootstrap run
  against, and a tick method for harness-driven maintenance (the role
  of m3em agent operations in the reference's dtests).
* ``RemoteDatabase`` — a connection-holding client exposing the SAME
  method surface as a local ``Database`` handle, so
  ``client/session.py`` (quorum fan-out) and ``storage/repair.py``
  (anti-entropy, peers bootstrap) work unchanged against remote
  replicas.  Calls raise ``ConnectionError`` on transport failure; the
  session counts those as per-replica errors exactly like the
  reference's per-host op failures.  The client reconnects lazily on
  the next call, so a bounced node heals without new plumbing.

Query ASTs (index/search.py) and documents travel as a compact
recursive binary form (`_enc_query`).
"""

from __future__ import annotations

import re
import socket
import socketserver
import struct
import threading
from typing import Dict, List, Tuple

import numpy as np

from m3_tpu.index import search
from m3_tpu.index.doc import Document, Field
from m3_tpu.instrument import tracing
from m3_tpu.instrument.tracing import NOOP_TRACER, TraceContext, Tracepoint
from m3_tpu.msg.protocol import (
    ProtocolError, connect as wire_connect, recv_frame, send_frame,
)
from m3_tpu.x import deadline as xdeadline
from m3_tpu.x import fault
from m3_tpu.x.breaker import CircuitBreaker
from m3_tpu.x.deadline import Deadline, DeadlineExceeded

# frame types (disjoint from the bus's so a misdirected client fails fast)
RPC_REQ = 16     # legacy request: [method u8][body]
RPC_OK = 17
RPC_ERR = 18
RPC_REQ_DL = 19  # deadline-carrying request: [method u8][budget ms i64][body]
RPC_REQ_TR = 20  # + trace context: [method u8][budget ms i64]
                 # [TraceContext 17B][body] — sent only for SAMPLED
                 # requests, so unsampled traffic stays RPC_REQ_DL-sized


class RemoteError(RuntimeError):
    """Application-level failure reported by the remote node (RPC_ERR
    frame): the transport is healthy but the call failed there — e.g. a
    segment checksum error on a corrupt replica.  Kept a RuntimeError
    subclass so pre-existing broad handlers still match; sweeps like
    repair catch it per replica and demote the handle instead of
    aborting (reference: per-host fetch failures in
    src/dbnode/storage/repair.go:115-246 fail only that host)."""


# ShardNotOwnedError crosses the wire TYPED (not as a generic
# RemoteError): the session must tell "your placement is stale, refresh
# and re-route" apart from "the data operation failed".  The server side
# encodes it like any error (type name prefix); the client re-raises the
# real class, parsing namespace/shard back out of the stable message.
_SHARD_NOT_OWNED_RE = re.compile(
    r"shard (\d+) not owned by this node \(namespace '([^']*)'\)"
)


def _decode_remote_error(msg: str):
    """RPC_ERR payload → the exception to raise client-side.  Besides
    routing misses, the overload family crosses typed too (via the
    shared ``x/deadline.decode_wire_error`` mapping): a remote
    ``QueryLimitExceeded`` must surface as 429 and a remote deadline
    trip as 504 at the API boundary, never a generic ``RemoteError``
    500."""
    if msg.startswith("ShardNotOwnedError:"):
        from m3_tpu.storage.database import ShardNotOwnedError

        m = _SHARD_NOT_OWNED_RE.search(msg)
        if m:
            return ShardNotOwnedError(m.group(2), int(m.group(1)))
        return ShardNotOwnedError(None, None)
    typed = xdeadline.decode_wire_error(msg)
    if typed is not None:
        return typed
    return RemoteError(msg)

# methods
M_WRITE_BATCH = 1
M_WRITE_TAGGED = 2
M_READ = 3
M_QUERY_IDS = 4
M_LIST_BLOCKS = 5
M_BLOCK_META = 6
M_READ_BLOCK = 7
M_WRITE_BLOCK = 8
M_TICK = 9
M_HEALTH = 10
M_READ_BATCH = 11  # batched M_READ: one call, N ids, N point lists


# ---------------------------------------------------------------------------
# payload codecs
# ---------------------------------------------------------------------------


def _pack_bytes(b: bytes) -> bytes:
    return struct.pack("<I", len(b)) + b


def _unpack_bytes(raw: bytes, pos: int) -> Tuple[bytes, int]:
    (n,) = struct.unpack_from("<I", raw, pos)
    return raw[pos + 4: pos + 4 + n], pos + 4 + n


def _enc_query(q: search.Query) -> bytes:
    if isinstance(q, search.All):
        return b"\x00"
    if isinstance(q, search.Term):
        return b"\x01" + _pack_bytes(q.field) + _pack_bytes(q.value)
    if isinstance(q, search.Regexp):
        return b"\x02" + _pack_bytes(q.field) + _pack_bytes(q.pattern)
    if isinstance(q, search.FieldExists):
        return b"\x03" + _pack_bytes(q.field)
    if isinstance(q, search.Conjunction):
        return (b"\x04" + struct.pack("<H", len(q.queries))
                + b"".join(_enc_query(s) for s in q.queries))
    if isinstance(q, search.Disjunction):
        return (b"\x05" + struct.pack("<H", len(q.queries))
                + b"".join(_enc_query(s) for s in q.queries))
    if isinstance(q, search.Negation):
        return b"\x06" + _enc_query(q.query)
    raise TypeError(f"unencodable query node: {q!r}")


def _dec_query(raw: bytes, pos: int = 0) -> Tuple[search.Query, int]:
    kind = raw[pos]
    pos += 1
    if kind == 0:
        return search.All(), pos
    if kind == 1:
        f, pos = _unpack_bytes(raw, pos)
        v, pos = _unpack_bytes(raw, pos)
        return search.Term(f, v), pos
    if kind == 2:
        f, pos = _unpack_bytes(raw, pos)
        p, pos = _unpack_bytes(raw, pos)
        return search.Regexp(f, p), pos
    if kind == 3:
        f, pos = _unpack_bytes(raw, pos)
        return search.FieldExists(f), pos
    if kind in (4, 5):
        (n,) = struct.unpack_from("<H", raw, pos)
        pos += 2
        subs = []
        for _ in range(n):
            s, pos = _dec_query(raw, pos)
            subs.append(s)
        cls = search.Conjunction if kind == 4 else search.Disjunction
        return cls(*subs), pos
    if kind == 6:
        s, pos = _dec_query(raw, pos)
        return search.Negation(s), pos
    raise ProtocolError(f"bad query node kind {kind}")


def _enc_doc(d: Document) -> bytes:
    parts = [_pack_bytes(d.id), struct.pack("<H", len(d.fields))]
    for f in d.fields:
        parts.append(_pack_bytes(f.name))
        parts.append(_pack_bytes(f.value))
    return b"".join(parts)


def _dec_doc(raw: bytes, pos: int) -> Tuple[Document, int]:
    sid, pos = _unpack_bytes(raw, pos)
    (n,) = struct.unpack_from("<H", raw, pos)
    pos += 2
    fields = []
    for _ in range(n):
        name, pos = _unpack_bytes(raw, pos)
        value, pos = _unpack_bytes(raw, pos)
        fields.append(Field(name, value))
    return Document(sid, tuple(fields)), pos


def _enc_points(pts: List[Tuple[int, float]]) -> bytes:
    ts = np.fromiter((p[0] for p in pts), np.int64, len(pts))
    vs = np.fromiter((p[1] for p in pts), np.float64, len(pts))
    return struct.pack("<I", len(pts)) + ts.tobytes() + vs.tobytes()


def _dec_points(raw: bytes, pos: int) -> Tuple[List[Tuple[int, float]], int]:
    (n,) = struct.unpack_from("<I", raw, pos)
    pos += 4
    ts = np.frombuffer(raw, np.int64, n, pos)
    pos += 8 * n
    vs = np.frombuffer(raw, np.float64, n, pos)
    pos += 8 * n
    return list(zip(ts.tolist(), vs.tolist())), pos


def _enc_series_list(series) -> bytes:
    parts = [struct.pack("<I", len(series))]
    for sid, seg in series:
        parts.append(_pack_bytes(sid))
        parts.append(_pack_bytes(seg))
    return b"".join(parts)


def _dec_series_list(raw: bytes, pos: int):
    (n,) = struct.unpack_from("<I", raw, pos)
    pos += 4
    out = []
    for _ in range(n):
        sid, pos = _unpack_bytes(raw, pos)
        seg, pos = _unpack_bytes(raw, pos)
        out.append((sid, seg))
    return out, pos


def _enc_str(s: str) -> bytes:
    return _pack_bytes(s.encode())


def _dec_str(raw: bytes, pos: int) -> Tuple[str, int]:
    b, pos = _unpack_bytes(raw, pos)
    return b.decode(), pos


# ---------------------------------------------------------------------------
# server
# ---------------------------------------------------------------------------


class _RpcHandler(socketserver.BaseRequestHandler):
    def handle(self):
        srv: DbNodeRpcServer = self.server
        sock = self.request
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        while True:
            try:
                frame = recv_frame(sock)
            except (ProtocolError, OSError):
                return
            if frame is None or frame[0] not in (RPC_REQ, RPC_REQ_DL,
                                                 RPC_REQ_TR):
                return
            payload = frame[1]
            try:
                # Socket-boundary faultpoint: drop closes the conn (a
                # crashed-mid-request peer), error returns a typed
                # RPC_ERR via the handler below, delay stalls dispatch.
                act, payload = fault.mangle("rpc.server", payload)
                if act == "drop":
                    return
                tctx = None
                if frame[0] in (RPC_REQ_DL, RPC_REQ_TR):
                    # [method u8][remaining-deadline ms i64][body]: bind
                    # the client's surviving budget so the server stops
                    # work (typed DeadlineExceeded → RPC_ERR) once the
                    # caller has given up; -1 = no deadline.  RPC_REQ_TR
                    # additionally carries the caller's TraceContext
                    # between the budget and the body.
                    hdr = 9
                    if frame[0] == RPC_REQ_TR:
                        hdr += TraceContext.WIRE_SIZE
                    if len(payload) < hdr:
                        raise ProtocolError("short rpc request")
                    (dl_ms,) = struct.unpack_from("<q", payload, 1)
                    dl = Deadline(dl_ms / 1000.0) if dl_ms >= 0 else None
                    if frame[0] == RPC_REQ_TR:
                        tctx = TraceContext.from_wire(payload, 9)
                    body = payload[hdr:]
                else:
                    # legacy [method u8][body] frame from a pre-deadline
                    # client (rolling upgrade): no budget, full service
                    if not payload:
                        raise ProtocolError("empty rpc request")
                    dl = None
                    body = payload[1:]
                with xdeadline.bind(dl), tracing.bind(tctx):
                    xdeadline.check_current("rpc dispatch")
                    # The server-side hop span: opened only for SAMPLED
                    # requests (a bound context), joining the caller's
                    # trace; everything _dispatch opens (db.writeBatch
                    # etc.) parents on it.  Untraced traffic pays one
                    # None-check, never a root span per request.
                    span = (srv.tracer.start_span(
                        Tracepoint.RPC_SERVER, {"method": int(payload[0])})
                        if tctx is not None else tracing.NOOP_SPAN)
                    with span:
                        resp = self._dispatch(srv.db, payload[0], body)
                send_frame(sock, RPC_OK, resp)
            except Exception as e:  # application error -> typed error frame
                try:
                    send_frame(sock, RPC_ERR,
                               f"{type(e).__name__}: {e}".encode()[:4096])
                except OSError:
                    return

    def _dispatch(self, db, method: int, raw: bytes) -> bytes:
        if method == M_HEALTH:
            return b"ok"
        if method in (M_WRITE_BATCH, M_WRITE_TAGGED):
            # Disk-pressure admission (assembly wires the gate from
            # x.diskbudget.check_ingest): at CRITICAL the batch is
            # refused BEFORE decode with the typed DiskCapacityError —
            # the RPC_ERR frame below makes it a per-replica failure
            # the session's consistency level absorbs, so nothing is
            # acked here and nothing is lost.  Reads, repair streams
            # and ticks are never gated.
            gate = getattr(self.server, "ingest_gate", None)
            if gate is not None:
                gate()
        if method == M_WRITE_BATCH:
            ns, pos = _dec_str(raw, 0)
            (now,) = struct.unpack_from("<q", raw, pos)
            pos += 8
            (n,) = struct.unpack_from("<I", raw, pos)
            pos += 4
            ids = []
            for _ in range(n):
                sid, pos = _unpack_bytes(raw, pos)
                ids.append(sid)
            ts = np.frombuffer(raw, np.int64, n, pos)
            pos += 8 * n
            vs = np.frombuffer(raw, np.float64, n, pos)
            res = db.write_batch(ns, ids, ts.copy(), vs.copy(),
                                 None if now == -1 else now)
            # (ncold, new-series rejections): the wire carries the typed
            # back-pressure signal so remote writers see churn limits.
            return struct.pack("<II", int(res), getattr(res, "rejected", 0))
        if method == M_WRITE_TAGGED:
            ns, pos = _dec_str(raw, 0)
            (now,) = struct.unpack_from("<q", raw, pos)
            pos += 8
            (n,) = struct.unpack_from("<I", raw, pos)
            pos += 4
            docs = []
            for _ in range(n):
                d, pos = _dec_doc(raw, pos)
                docs.append(d)
            ts = np.frombuffer(raw, np.int64, n, pos)
            pos += 8 * n
            vs = np.frombuffer(raw, np.float64, n, pos)
            res = db.write_tagged_batch(ns, docs, ts.copy(), vs.copy(),
                                        None if now == -1 else now)
            return struct.pack("<II", int(res), getattr(res, "rejected", 0))
        if method == M_READ:
            ns, pos = _dec_str(raw, 0)
            sid, pos = _unpack_bytes(raw, pos)
            start, end = struct.unpack_from("<qq", raw, pos)
            return _enc_points(db.read(ns, sid, start, end))
        if method == M_READ_BATCH:
            # Batched read: the ledger-verify / bulk-fetch wire shape.
            # One storage read_batch amortizes the per-window sort
            # across every id; the response is each id's point list in
            # request order.
            ns, pos = _dec_str(raw, 0)
            start, end = struct.unpack_from("<qq", raw, pos)
            pos += 16
            (n,) = struct.unpack_from("<I", raw, pos)
            pos += 4
            sids = []
            for _ in range(n):
                sid, pos = _unpack_bytes(raw, pos)
                sids.append(sid)
            out = db.read_batch(ns, sids, start, end)
            return (struct.pack("<I", len(out))
                    + b"".join(_enc_points(p) for p in out))
        if method == M_QUERY_IDS:
            ns, pos = _dec_str(raw, 0)
            start, end = struct.unpack_from("<qq", raw, pos)
            pos += 16
            q, pos = _dec_query(raw, pos)
            docs = db.query_ids(ns, q, start, end)
            return (struct.pack("<I", len(docs))
                    + b"".join(_enc_doc(d) for d in docs))
        if method == M_LIST_BLOCKS:
            ns, pos = _dec_str(raw, 0)
            (shard,) = struct.unpack_from("<i", raw, pos)
            pairs = db.list_block_filesets(ns, shard)
            return (struct.pack("<I", len(pairs))
                    + b"".join(struct.pack("<qi", bs, vol)
                               for bs, vol in pairs))
        if method == M_BLOCK_META:
            ns, pos = _dec_str(raw, 0)
            shard, bs = struct.unpack_from("<iq", raw, pos)
            meta = db.block_metadata(ns, shard, bs)
            if meta is None:
                return b"\x00"
            parts = [b"\x01", struct.pack("<I", len(meta))]
            for sid, ck in sorted(meta.items()):
                parts.append(_pack_bytes(sid))
                parts.append(struct.pack("<I", ck))
            return b"".join(parts)
        if method == M_READ_BLOCK:
            ns, pos = _dec_str(raw, 0)
            shard, bs = struct.unpack_from("<iq", raw, pos)
            return _enc_series_list(db.read_block(ns, shard, bs))
        if method == M_WRITE_BLOCK:
            ns, pos = _dec_str(raw, 0)
            shard, bs = struct.unpack_from("<iq", raw, pos)
            pos += 12
            series, pos = _dec_series_list(raw, pos)
            db.write_block(ns, shard, bs, series)
            return b""
        if method == M_TICK:
            (now,) = struct.unpack_from("<q", raw, 0)
            db.tick(now)
            return b""
        raise ProtocolError(f"unknown rpc method {method}")


class DbNodeRpcServer(socketserver.ThreadingTCPServer):
    allow_reuse_address = True
    daemon_threads = True

    def __init__(self, db, host: str = "127.0.0.1", port: int = 0,
                 tracer=None):
        self.db = db
        # default to the database's tracer so rpc.server spans land in
        # the same ring the debug endpoint serves
        self.tracer = (tracer if tracer is not None
                       else getattr(db, "tracer", None) or NOOP_TRACER)
        # Optional nullary admission gate for the write methods (raises
        # typed to refuse a batch un-acked); assembly binds it to the
        # disk ledger's check_ingest when disk.enabled.
        self.ingest_gate = None
        super().__init__((host, port), _RpcHandler)

    @property
    def port(self) -> int:
        return self.server_address[1]


def serve_rpc_background(db, host: str = "127.0.0.1",
                         port: int = 0, tracer=None) -> DbNodeRpcServer:
    srv = DbNodeRpcServer(db, host, port, tracer=tracer)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    return srv


# ---------------------------------------------------------------------------
# client
# ---------------------------------------------------------------------------


class RemoteDatabase:
    """Database-shaped handle over one RPC connection.

    Lazily (re)connects per call; any transport failure closes the
    socket and raises ConnectionError so quorum layers can count the
    replica as failed and the next call can retry a bounced node.

    Deadline-aware: with a query deadline bound (x/deadline), per-call
    socket timeouts derive from ``remaining()`` (capped at
    ``timeout_s``), the surviving budget rides the RPC_REQ_DL frame so the
    server stops work too, and a transport timeout with the budget
    spent surfaces typed as ``DeadlineExceeded``.  An optional shared
    ``breaker`` (x/breaker, one per peer) makes calls to a dead node
    fail fast for every holder at once."""

    def __init__(self, address: Tuple[str, int], timeout_s: float = 180.0,
                 breaker: CircuitBreaker | None = None):
        # The generous default absorbs one-time jit compiles behind
        # flush/tick paths on a freshly started node (CPU backend pays
        # tens of seconds for the encoder scan); connect failures to a
        # dead node still surface immediately (ECONNREFUSED).
        self.address = tuple(address)
        self.timeout_s = timeout_s
        self.breaker = breaker
        self._sock: socket.socket | None = None
        self._mu = threading.Lock()

    # -- transport --

    def _connect(self) -> socket.socket:
        # dial timeout from the bound deadline's remaining budget
        # (capped by the legacy constant, never extended past it)
        return wire_connect(self.address,
                            timeout=xdeadline.socket_timeout(self.timeout_s))

    def _call(self, method: int, body: bytes) -> bytes:
        # A budget spent before this call is the QUERY's failure, not
        # this peer's: raise outside the breaker so overload upstream
        # cannot trip a healthy node's breaker open.
        xdeadline.check_current("rpc call")
        if self.breaker is not None:
            return self.breaker.call(lambda: self._call_inner(method, body))
        return self._call_inner(method, body)

    def _call_inner(self, method: int, body: bytes) -> bytes:
        dl = xdeadline.current()
        # Sampled callers (a bound trace context — e.g. the session's
        # replica fan-out span) upgrade the frame to RPC_REQ_TR so the
        # server's dispatch joins their trace; everyone else stays on
        # the deadline-only frame.  One contextvar read per call.
        tctx_wire = tracing.current_wire()
        ftype = RPC_REQ_TR if tctx_wire else RPC_REQ_DL
        header = (bytes([method]) + struct.pack("<q", xdeadline.remaining_ms())
                  + tctx_wire)
        with self._mu:
            try:
                # Socket-boundary faultpoint: drop/error surface as the
                # ConnectionError quorum layers count per replica (and
                # the session's retrier absorbs); delay = slow peer.
                if fault.fire("rpc.call") == "drop":
                    raise fault.FaultInjected("rpc.call: request dropped")
                if self._sock is None:
                    self._sock = self._connect()
                # per-call timeout from the remaining budget: a wire
                # hop must never outlive its query (raises typed when
                # the budget is already spent)
                self._sock.settimeout(
                    xdeadline.socket_timeout(self.timeout_s))
                send_frame(self._sock, ftype, header + body)
                frame = recv_frame(self._sock)
            except DeadlineExceeded:
                raise  # budget spent BEFORE I/O: the socket is intact
            except (OSError, ProtocolError) as e:
                self._drop()
                if dl is not None and dl.expired:
                    raise dl.exceeded(
                        f"rpc {self.address}: deadline exceeded") from e
                raise ConnectionError(f"rpc {self.address}: {e}") from e
            if frame is None:
                self._drop()
                raise ConnectionError(f"rpc {self.address}: connection closed")
        ftype, payload = frame
        if ftype == RPC_ERR:
            raise _decode_remote_error(payload.decode(errors="replace"))
        if ftype != RPC_OK:
            # _drop mutates the connection — retake the lock (the frame
            # was already read; another caller may be mid-_call).
            with self._mu:
                self._drop()
            raise ConnectionError(f"rpc {self.address}: bad frame {ftype}")
        return payload

    def _drop(self) -> None:
        # All callers hold self._mu (the _call error paths run inside
        # the with-block; close() and the bad-frame path retake it).
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
            self._sock = None  # m3lint: disable=lock-discipline

    def close(self) -> None:
        with self._mu:
            self._drop()

    # -- data plane --

    def health(self) -> bool:
        return self._call(M_HEALTH, b"") == b"ok"

    def write_batch(self, namespace, ids, ts, vals, now_nanos=None) -> None:
        ts = np.asarray(ts, np.int64)
        vals = np.asarray(vals, np.float64)
        body = (_enc_str(namespace)
                + struct.pack("<q", -1 if now_nanos is None else now_nanos)
                + struct.pack("<I", len(ids))
                + b"".join(_pack_bytes(i) for i in ids)
                + ts.tobytes() + vals.tobytes())
        return self._dec_write_result(self._call(M_WRITE_BATCH, body))

    def write_tagged_batch(self, namespace, docs, ts, vals,
                           now_nanos=None) -> None:
        ts = np.asarray(ts, np.int64)
        vals = np.asarray(vals, np.float64)
        body = (_enc_str(namespace)
                + struct.pack("<q", -1 if now_nanos is None else now_nanos)
                + struct.pack("<I", len(docs))
                + b"".join(_enc_doc(d) for d in docs)
                + ts.tobytes() + vals.tobytes())
        return self._dec_write_result(self._call(M_WRITE_TAGGED, body))

    @staticmethod
    def _dec_write_result(payload: bytes):
        from m3_tpu.storage.database import WriteResult

        if len(payload) < 8:
            return WriteResult(0, 0)
        ncold, rejected = struct.unpack_from("<II", payload, 0)
        return WriteResult(ncold, rejected)

    def read(self, namespace, sid, start, end):
        body = (_enc_str(namespace) + _pack_bytes(sid)
                + struct.pack("<qq", start, end))
        pts, _ = _dec_points(self._call(M_READ, body), 0)
        return pts

    def read_batch(self, namespace, sids, start, end):
        """Batched read: N ids in one round trip, point lists back in
        request order (the soak ledger verify reads millions of acked
        samples — per-id round trips would dominate the recovery
        check)."""
        body = (_enc_str(namespace) + struct.pack("<qq", start, end)
                + struct.pack("<I", len(sids))
                + b"".join(_pack_bytes(s) for s in sids))
        raw = self._call(M_READ_BATCH, body)
        (n,) = struct.unpack_from("<I", raw, 0)
        pos = 4
        out = []
        for _ in range(n):
            pts, pos = _dec_points(raw, pos)
            out.append(pts)
        return out

    def query_ids(self, namespace, q, start, end):
        body = (_enc_str(namespace) + struct.pack("<qq", start, end)
                + _enc_query(q))
        raw = self._call(M_QUERY_IDS, body)
        (n,) = struct.unpack_from("<I", raw, 0)
        pos = 4
        docs = []
        for _ in range(n):
            d, pos = _dec_doc(raw, pos)
            docs.append(d)
        return docs

    # -- block-level replication surface --

    def list_block_filesets(self, namespace, shard):
        raw = self._call(M_LIST_BLOCKS,
                         _enc_str(namespace) + struct.pack("<i", shard))
        (n,) = struct.unpack_from("<I", raw, 0)
        pos = 4
        out = []
        for _ in range(n):
            bs, vol = struct.unpack_from("<qi", raw, pos)
            pos += 12
            out.append((bs, vol))
        return out

    def block_metadata(self, namespace, shard, block_start):
        raw = self._call(M_BLOCK_META, _enc_str(namespace)
                         + struct.pack("<iq", shard, block_start))
        if raw[0] == 0:
            return None
        (n,) = struct.unpack_from("<I", raw, 1)
        pos = 5
        meta: Dict[bytes, int] = {}
        for _ in range(n):
            sid, pos = _unpack_bytes(raw, pos)
            (ck,) = struct.unpack_from("<I", raw, pos)
            pos += 4
            meta[sid] = ck
        return meta

    def read_block(self, namespace, shard, block_start):
        raw = self._call(M_READ_BLOCK, _enc_str(namespace)
                         + struct.pack("<iq", shard, block_start))
        series, _ = _dec_series_list(raw, 0)
        return series

    def write_block(self, namespace, shard, block_start, series) -> None:
        body = (_enc_str(namespace) + struct.pack("<iq", shard, block_start)
                + _enc_series_list(list(series)))
        self._call(M_WRITE_BLOCK, body)

    # -- harness-driven maintenance (m3em agent role) --

    def tick(self, now_nanos: int) -> None:
        self._call(M_TICK, struct.pack("<q", now_nanos))
