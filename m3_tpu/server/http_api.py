"""HTTP API: the coordinator's front door (JSON write + PromQL read).

Reference parity: `src/query/api/v1` — Prometheus-compatible query
endpoints (`handler/prometheus/native/read.go:111` → engine), the JSON
write endpoint (`api/v1/json/write`), and label/series metadata
endpoints.  Response shapes follow the Prometheus HTTP API so Grafana
pointed at `/api/v1/query_range` works unchanged — the same
compatibility target the reference serves.

Read-path overload contract (`/api/v1/query`, `/api/v1/query_range`,
`/render`, `/api/v1/prom/remote/read`):

* ``timeout=`` query param (seconds or a duration like ``30s``/``2m``)
  sets the query's END-TO-END deadline, defaulting to the
  ``query.default_timeout`` config; the deadline is threaded through
  the engine, fanout and every wire hop (x/deadline), and partial
  results from non-required fanout sources surface in the Prometheus
  ``warnings`` response field.
* Status mapping: **429** a per-query resource limit tripped
  (``QueryLimitExceeded``, local or remote) — client should back off;
  **503 + Retry-After** admission control shed the query
  (``QueryShedError``: concurrency slots and wait queue full) — retry
  after the hinted delay; **504** the deadline was exceeded
  (``DeadlineExceeded``, including cooperative cancellation) — retry
  with a longer ``timeout=`` or narrower query.  Multiple REQUIRED
  fanout sources failing together (``PartialResultError``) map by the
  dominant cause: 504 if any missed the deadline, 429 if any tripped a
  limit, else **502**.
* Queries spending more than ``query.slow_query_fraction`` of their
  deadline land in the slow-query log (`/health` ``query.slow`` +
  ``slow_query_total`` on /metrics) with per-phase timings.
* ``namespace=`` on ``/api/v1/query``/``query_range`` evaluates over
  another configured namespace's LOCAL storage — how the
  ``_m3_selfmon`` self-monitoring history is queried from outside
  (unknown names 400).
"""

from __future__ import annotations

import collections
import json
import math
import re
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from pathlib import Path
from urllib.parse import parse_qs, urlparse

import numpy as np

from m3_tpu.index.doc import Document
from m3_tpu.index.search import All, FieldExists, Term
from m3_tpu.instrument.tracing import NOOP_TRACER, Tracepoint, traces_response
from m3_tpu.query.engine import Engine
from m3_tpu.query.fanout import FederatedStorage, PartialResultError
from m3_tpu.query.storage_adapter import DatabaseStorage
from m3_tpu.storage.database import Database, ShardNotOwnedError
from m3_tpu.storage.limits import QueryLimitExceeded
from m3_tpu.x import deadline as xdeadline
from m3_tpu.x.admission import AdmissionController, QueryShedError
from m3_tpu.x.deadline import Deadline, DeadlineExceeded

_DUR_RE = re.compile(r"^(\d+(?:\.\d+)?)([smhdwy]|ms)$")


def _parse_time(v: str) -> int:
    """RFC3339-less Prometheus time params: unix seconds (float) → nanos."""
    return int(float(v) * 1e9)


def _parse_step(v: str) -> int:
    m = _DUR_RE.match(v)
    if m:
        mult = {"ms": 1e6, "s": 1e9, "m": 60e9, "h": 3600e9, "d": 86400e9,
                "w": 7 * 86400e9, "y": 365 * 86400e9}[m.group(2)]
        return int(float(m.group(1)) * mult)
    return int(float(v) * 1e9)


class _Handler(BaseHTTPRequestHandler):
    server_version = "m3tpu/0.1"
    ctx = None  # set by make_server

    def log_message(self, fmt, *args):  # quiet
        pass

    # -- helpers -----------------------------------------------------------

    def _json(self, code: int, obj, headers: dict | None = None) -> None:
        data = json.dumps(obj).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(data)))
        for k, v in (headers or {}).items():
            self.send_header(k, v)
        self.end_headers()
        self.wfile.write(data)

    def _error(self, code: int, msg: str,
               headers: dict | None = None) -> None:
        self._json(code, {"status": "error", "error": msg}, headers)

    def _overload_status(self, e: Exception) -> None:
        """The typed read-path overload errors → HTTP status (see
        module docstring: 429 limit / 503 shed / 504 deadline).  A
        multi-source ``PartialResultError`` maps by its dominant cause
        — these are server-side failures, never a 400."""
        if isinstance(e, PartialResultError):
            causes = e.failures.values()
            if any(isinstance(c, DeadlineExceeded) for c in causes):
                return self._error(504, str(e))
            if any(isinstance(c, QueryLimitExceeded) for c in causes):
                return self._error(429, str(e))
            return self._error(502, str(e))
        if isinstance(e, QueryLimitExceeded):
            return self._error(429, str(e))
        if isinstance(e, QueryShedError):
            return self._error(
                503, str(e),
                headers={"Retry-After":
                         str(max(1, math.ceil(e.retry_after_s)))})
        if isinstance(e, DeadlineExceeded):
            return self._error(504, str(e))
        raise e

    def _deadline(self, q) -> Deadline:
        """Every read request gets an end-to-end deadline: the
        ``timeout=`` param (seconds or ``30s``-style duration), default
        from config (``query.default_timeout``)."""
        v = q.get("timeout", [None])[0]
        timeout_s = (self.ctx.query_timeout_s if v is None
                     else _parse_step(v) / 1e9)
        return Deadline(timeout_s)

    def _body(self):
        n = int(self.headers.get("Content-Length", 0))
        return self.rfile.read(n)

    # -- routes ------------------------------------------------------------

    def do_GET(self):
        u = urlparse(self.path)
        q = parse_qs(u.query)
        try:
            if u.path == "/health":
                return self._health()
            if u.path == "/metrics":
                return self._metrics()
            if u.path in ("/debug/traces", "/api/v1/debug/traces"):
                return self._traces(q)
            if u.path == "/api/v1/debug/faults":
                return self._faults()
            if u.path == "/debug/dump":
                return self._debug_dump(q)
            if u.path in ("/api/v1/query_range", "/api/v1/query"):
                return self._query(u.path.endswith("query_range"), q)
            if u.path == "/api/v1/labels":
                return self._labels(q)
            if u.path.startswith("/api/v1/label/") and u.path.endswith("/values"):
                name = u.path[len("/api/v1/label/") : -len("/values")]
                return self._label_values(name, q)
            if u.path == "/api/v1/series":
                return self._series(q)
            if u.path == "/render":
                return self._render(q)
            if u.path == "/metrics/find":
                return self._find(q)
            return self._error(404, f"unknown path {u.path}")
        except (QueryLimitExceeded, QueryShedError, DeadlineExceeded,
                PartialResultError) as e:
            return self._overload_status(e)
        except Exception as e:  # noqa: BLE001 — API boundary
            return self._error(400, str(e))

    def do_POST(self):
        u = urlparse(self.path)
        try:
            if u.path == "/api/v1/json/write":
                return self._write_json()
            if u.path in ("/api/v1/influxdb/write", "/write"):
                return self._influx_write(parse_qs(u.query))
            if u.path == "/api/v1/prom/remote/write":
                return self._prom_remote_write()
            if u.path == "/api/v1/prom/remote/read":
                return self._prom_remote_read(parse_qs(u.query))
            if u.path in ("/api/v1/query_range", "/api/v1/query"):
                q = parse_qs(self._body().decode())
                return self._query(u.path.endswith("query_range"), q)
            if u.path == "/api/v1/debug/faults":
                return self._faults(json.loads(self._body() or b"{}"))
            return self._error(404, f"unknown path {u.path}")
        except (QueryLimitExceeded, QueryShedError, DeadlineExceeded,
                PartialResultError) as e:
            return self._overload_status(e)
        except Exception as e:  # noqa: BLE001
            return self._error(400, str(e))

    # -- handlers ----------------------------------------------------------

    def _health(self):
        """Liveness plus the corruption-quarantine inventory: a node
        serving around quarantined volumes is healthy (that is the
        design) but an operator must be able to SEE the holes without
        shelling into the data dir."""
        out = {"ok": True}
        try:
            inv = self.ctx.db.quarantine_inventory()
        except Exception:  # noqa: BLE001 — health must never 500
            inv = None
        if inv:
            # Byte accounting per entry: under disk pressure the reaper
            # (and the operator) needs to know what releasing an entry
            # buys, not just that it exists.
            qbytes = 0
            for e in inv:
                try:
                    d = e.get("dir")
                    if d:
                        qbytes += sum(f.stat().st_size
                                      for f in Path(d).rglob("*")
                                      if f.is_file())
                except OSError:
                    pass
            out["quarantine"] = {
                "entries": len(inv),
                "bytes": qbytes,
                # brief per-entry detail; the full reason files live in
                # <root>/quarantine/
                "items": [
                    {k: e.get(k) for k in ("label", "namespace", "shard",
                                           "block_start", "volume", "check",
                                           "error_type")}
                    for e in inv[:50]
                ],
            }
        # Topology/migration visibility: which shards this node serves
        # per the watched placement, per-shard streaming progress of
        # INITIALIZING ones, and pending grace-period drops — the
        # operator's window into a rolling node add/replace/remove.
        if self.ctx.migrator is not None:
            try:
                out["topology"] = self.ctx.migrator.status()
            except Exception:  # noqa: BLE001 — health must never 500
                pass
        # Hot-path latency (windowed histogram summaries, NOT lifetime
        # reservoirs): merged p50/p99 per surface — ingest batches,
        # query phases, flush/snapshot, drains.  Omitted while no
        # histogram has recorded anything.
        try:
            if self.ctx.registry is not None:
                lat = {name: {k: round(v, 6) if isinstance(v, float) else v
                              for k, v in s.items()}
                       for name, s in
                       self.ctx.registry.histogram_summaries().items()
                       if s["count"]}
                if lat:
                    out["latency"] = lat
        except Exception:  # noqa: BLE001 — health must never 500
            pass
        # Read-path overload visibility: admission gauges, the slow-
        # query log tail, and per-peer breaker states — the operator's
        # window into WHY queries are shedding/504ing.  Omitted while
        # there is nothing to see (no gating configured, no slow
        # queries, no peers): a clean node's health stays noise-free.
        try:
            q = self.ctx.query_status()
            from m3_tpu.x.breaker import all_breakers

            # peer breakers only: stage:* breakers (x/devguard) report
            # through the `device` section below, not the query view
            breakers = {name: br.state
                        for name, br in all_breakers().items()
                        if br.kind == "peer"}
            if breakers:
                q["breakers"] = breakers
            if (breakers or q["max_concurrent"] > 0
                    or q["slow_query_total"] or q["shed_total"]):
                out["query"] = q
        except Exception:  # noqa: BLE001 — health must never 500
            pass
        # Device-boundary visibility: per-stage guard counters +
        # breaker states (x/devguard), the HBM budget ledger
        # (x/membudget), and the arena checkpoint driver — the
        # operator's window into a degraded device path that is still
        # serving.  Health reports DEGRADATION, not activity: a stage
        # appears once it has errors/fallbacks or a non-closed breaker
        # (full happy-path counters live on /metrics), so a clean
        # node's health stays noise-free.
        try:
            from m3_tpu.x import devguard, membudget

            dev = devguard.status()
            mb = membudget.snapshot()
            section = {}
            degraded = {
                st: doc for st, doc in dev["stages"].items()
                if doc.get("errors") or doc.get("fallback_calls")
                or doc.get("breaker", "closed") != "closed"
            }
            if degraded:
                section["stages"] = degraded
            # used_bytes alone is NOT a signal — every node's buffers
            # reserve bytes; the ledger is health-worthy only once a
            # budget is configured (or something was rejected before
            # one was)
            if mb["budget_bytes"] or mb["rejected_total"]:
                section["membudget"] = mb
            if self.ctx.checkpointer is not None:
                section["checkpoint"] = self.ctx.checkpointer.status()
            if section:
                out["device"] = section
        except Exception:  # noqa: BLE001 — health must never 500
            pass
        # Disk-capacity visibility (x/diskbudget + persist/capacity):
        # the ledger's watermark verdict, per-family byte accounting
        # and shed/typed-error counters.  The membudget discipline —
        # health reports DEGRADATION, not activity: the section appears
        # only once the node is at/past LOW, has shed ingest, or has
        # classified a capacity error; a clean node stays noise-free.
        try:
            from m3_tpu.persist import capacity as xcap
            from m3_tpu.x import diskbudget

            dsnap = diskbudget.snapshot()
            caps = xcap.counters()
            if dsnap["enabled"] and (dsnap["level_value"] > 0
                                     or dsnap["shed_total"] or caps):
                disk = dict(dsnap)
                disk["free_ratio"] = round(disk["free_ratio"], 4)
                if caps:
                    disk["capacity_errors"] = caps
                out["disk"] = disk
            elif caps:
                # Typed errors with the ledger disarmed (statvfs-only
                # deployments without watermarks) still surface.
                out["disk"] = {"enabled": False, "capacity_errors": caps}
        except Exception:  # noqa: BLE001 — health must never 500
            pass
        # SLO burn-rate verdicts over the self-monitored history
        # (query/slo.py: cached last evaluation, no queries run here)
        # plus a compact selfmon scrape summary.  Present only when
        # rules are configured — a node that only stores, never
        # judges, keeps a noise-free health document.
        try:
            if self.ctx.selfmon is not None:
                slo = self.ctx.selfmon.health_slo()
                if slo is not None:
                    out["slo"] = slo
        except Exception:  # noqa: BLE001 — health must never 500
            pass
        # Self-healing controller: configuration + per-binding state +
        # actuator positions + the recent action tail (x/controller;
        # cheap cached state, no queries run here).
        try:
            if self.ctx.controller is not None:
                out["controller"] = self.ctx.controller.status()
        except Exception:  # noqa: BLE001 — health must never 500
            pass
        return self._json(200, out)

    def _debug_dump(self, q):
        """One-stop debug zip: thread stacks, a short CPU profile, a
        heap view, host info + metrics snapshot (reference
        x/debug/debug.go's pprof bundle served over HTTP)."""
        from m3_tpu.instrument.debug import debug_bundle

        seconds = min(float(q.get("seconds", ["0.5"])[0]), 10.0)
        data = debug_bundle(self.ctx.registry, cpu_seconds=seconds)
        self.send_response(200)
        self.send_header("Content-Type", "application/zip")
        self.send_header("Content-Length", str(len(data)))
        self.end_headers()
        self.wfile.write(data)

    def _metrics(self):
        """Prometheus text exposition of the process registry (reference
        x/instrument tally prometheus reporter + x/debug introspection)."""
        reg = self.ctx.registry
        if reg is None:
            return self._error(404, "no instrument registry configured")
        data = reg.render_prometheus().encode()
        self.send_response(200)
        self.send_header("Content-Type", "text/plain; version=0.0.4")
        self.send_header("Content-Length", str(len(data)))
        self.end_headers()
        self.wfile.write(data)

    def _render(self, q):
        """Graphite render endpoint (reference
        `query/api/v1/handler/graphite/render.go`): JSON list of
        {target, datapoints: [[value|null, unix_seconds], ...]}."""
        import math as _math
        import time as _time

        from m3_tpu.query.graphite import parse_graphite_time

        now = _time.time_ns()
        start = parse_graphite_time(q.get("from", ["-1h"])[0], now)
        end = parse_graphite_time(q.get("until", ["now"])[0], now)
        step = _parse_step(q.get("step", ["10s"])[0])
        dl = self._deadline(q)
        out = []
        targets = q.get("target", [])
        try:
            with self.ctx.admission.admit(deadline=dl), xdeadline.bind(dl):
                for target in targets:
                    for s in self.ctx.graphite.render(target, start, end,
                                                      step):
                        step_s = s.step_nanos / 1e9
                        out.append({
                            "target": s.name,
                            "datapoints": [
                                [None if _math.isnan(v) else v,
                                 int(s.start_nanos / 1e9 + i * step_s)]
                                for i, v in enumerate(s.values.tolist())
                            ],
                        })
        except Exception as e:  # noqa: BLE001 — observed, then re-raised
            self.ctx.observe_query("graphite", ";".join(targets), dl, error=e)
            raise
        self.ctx.observe_query("graphite", ";".join(targets), dl)
        return self._json(200, out)

    def _find(self, q):
        """Graphite find endpoint (reference handler/graphite/find.go)."""
        pattern = q["query"][0]
        prefix = pattern.rsplit(".", 1)[0] + "." if "." in pattern else ""
        out = [
            {"text": name, "id": prefix + name, "leaf": 1 if leaf else 0,
             "expandable": 1 if expandable else 0}
            for name, leaf, expandable in self.ctx.graphite.storage.find(pattern)
        ]
        return self._json(200, out)

    def _traces(self, q=None):
        """Span-ring debug surface (reference x/debug's introspection
        bundles; jaeger exporter seam collapses to JSON-over-HTTP).

        ``/api/v1/debug/traces``            — ring inventory (one row
                                              per trace) + raw spans
        ``?trace_id=<id>``                  — that trace's spans,
                                              parent-before-child
        ``?name=<tracepoint>``              — spans of one tracepoint
        """
        tr = self.ctx.tracer
        if tr is None:
            return self._error(404, "no tracer configured")
        q = q or {}
        return self._json(200, traces_response(
            tr, trace_id=q.get("trace_id", [None])[0],
            name=q.get("name", [None])[0]))

    def _faults(self, body: dict | None = None):
        """Faultpoint debug surface, mirrored on the admin port like
        /api/v1/debug/traces: GET = armed specs + counters, POST =
        runtime re-arm in the M3_FAULTPOINTS grammar (x/fault owns the
        shared parse/apply builders — two ports, one behavior).  This
        is what lets the soak's chaos scheduler open and close wire-
        fault windows on LIVE nodes instead of restarting them."""
        from m3_tpu.x import fault

        if body is None:
            return self._json(200, fault.registry_response())
        return self._json(200, fault.apply_request(body))

    @staticmethod
    def _series_id(tags: dict) -> bytes:
        name = tags.get(b"__name__", b"")
        return name + b"{" + b",".join(
            k + b"=" + v for k, v in sorted(tags.items()) if k != b"__name__"
        ) + b"}"

    def _ingest_tagged(self, docs, ts, vals) -> tuple[int, int]:
        """Shared downsample-then-write tail of every write handler.
        Returns (written, rejected): rejected = samples whose series
        creation hit the new-series rate limit — the typed
        back-pressure signal, surfaced so HTTP writers can back off.

        Opens the ``api.write`` root span (the coordinator-ingest end
        of a cross-process trace: downstream session/rpc hops join it
        through the bound context) and records the batch into the
        windowed ingest-latency histogram."""
        ctx = self.ctx
        t0 = time.perf_counter()
        with (ctx.tracer or NOOP_TRACER).start_span(
                Tracepoint.API_WRITE, {"n": len(docs)}):
            keep = np.ones(len(docs), bool)
            if ctx.downsampler is not None:
                keep = ctx.downsampler.write_batch(
                    docs, np.asarray(ts, np.int64), np.asarray(vals)
                )
            idx = np.nonzero(keep)[0]
            rejected = not_owned = 0
            if len(idx):
                res = ctx.db.write_tagged_batch(
                    ctx.namespace,
                    [docs[i] for i in idx],
                    np.asarray(ts, np.int64)[idx],
                    np.asarray(vals)[idx],
                )
                rejected = getattr(res, "rejected", 0)
                # samples whose shard this node does not own
                # (placement-scoped node fed directly): dropped, not
                # written — the correct ingest path for a scoped
                # cluster is the session
                not_owned = getattr(res, "not_owned", 0)
        if ctx.hist_ingest is not None:
            ctx.hist_ingest.record(time.perf_counter() - t0)
        return int(len(idx)) - rejected - not_owned, rejected

    def _prom_remote_write(self):
        """Prometheus remote write: snappy+protobuf WriteRequest
        (reference handler/prometheus/remote/write.go)."""
        from m3_tpu.server.prom_remote import parse_write_request

        series = parse_write_request(self._body())
        docs, ts, vals = [], [], []
        for s in series:
            sid = self._series_id(s.labels)
            doc = Document.from_tags(sid, s.labels)
            for t_nanos, v in s.samples:
                docs.append(doc)
                ts.append(t_nanos)
                vals.append(v)
        rejected = 0
        if docs:
            _, rejected = self._ingest_tagged(docs, ts, vals)
        # Prometheus remote-write clients back off on 429 — the typed
        # signal for new-series rate limiting; 2xx otherwise.  The 429
        # is deliberate despite the accepted subset having been
        # persisted: spec-compliant clients retry the WHOLE batch, and
        # retrying is what eventually admits the REJECTED series (a 2xx
        # would silently drop them).  Costs of that choice: accepted
        # samples are re-written into the WAL (harmless — raw-namespace
        # dedupe is last-write-wins — but WAL volume inflates under
        # sustained churn), and if a downsampler is attached the retry
        # RE-AGGREGATES accepted samples into any still-open window
        # (sum/count lanes double-count until the window closes).
        # Deployments pairing the limiter with downsampling should set
        # the limit headroom so steady-state traffic never 429s.
        self.send_response(429 if rejected else 204)
        if rejected:
            self.send_header("X-Rejected", str(rejected))
        self.send_header("Content-Length", "0")
        self.end_headers()
        return None

    def _prom_remote_read(self, uq):
        """Prometheus remote read: snappy+protobuf ReadRequest →
        ReadResponse (reference handler/prometheus/remote/read.go).
        ``timeout=`` rides the URL query string (the body is
        protobuf)."""
        from m3_tpu.query.promql import LabelMatcher
        from m3_tpu.query.storage_adapter import matchers_to_query
        from m3_tpu.server.prom_remote import (
            PromTimeSeries, build_read_response, parse_read_request,
        )

        ctx = self.ctx
        _OPS = {0: "=", 1: "!=", 2: "=~", 3: "!~"}
        results = []
        dl = self._deadline(uq)
        with ctx.admission.admit(deadline=dl), xdeadline.bind(dl):
            for q in parse_read_request(self._body()):
                matchers = tuple(
                    LabelMatcher(m.name, _OPS[m.type], m.value)
                    for m in q.matchers
                )
                idx_q = matchers_to_query(None, matchers)
                # prompb end timestamps are INCLUSIVE; db reads are
                # end-exclusive (same boundary rule as Engine._fetch)
                end = q.end_nanos + 1
                docs = ctx.db.query_ids(ctx.namespace, idx_q,
                                        q.start_nanos, end)
                series_out = []
                for i, d in enumerate(sorted(docs, key=lambda d: d.id)):
                    if i % 64 == 0:  # per-series read loop: cancellable
                        dl.check("remote read")
                    try:
                        pts = ctx.db.read(ctx.namespace, d.id,
                                          q.start_nanos, end)
                    except ShardNotOwnedError:
                        continue  # unowned shard: replicas answer it
                    series_out.append(PromTimeSeries(d.tags(), list(pts)))
                results.append(series_out)
        body = build_read_response(results)
        self.send_response(200)
        self.send_header("Content-Type", "application/x-protobuf")
        self.send_header("Content-Encoding", "snappy")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)
        return None

    def _write_json(self):
        """reference api/v1/json/write: one sample or a list of
        {tags: {..}, timestamp (unix s or nanos), value}."""
        payload = json.loads(self._body())
        samples = payload if isinstance(payload, list) else [payload]
        docs, ts, vals = [], [], []
        for s in samples:
            tags = {k.encode(): v.encode() for k, v in s["tags"].items()}
            docs.append(Document.from_tags(self._series_id(tags), tags))
            t = s["timestamp"]
            ts.append(int(t * 1e9) if t < 1e12 else int(t))
            vals.append(float(s["value"]))
        written, rejected = (self._ingest_tagged(docs, ts, vals)
                             if docs else (0, 0))
        body = {"status": "success", "written": written}
        if rejected:
            # partial acceptance: series churn hit the rate limit
            body.update(status="partial", rejected=rejected,
                        error="new-series rate limit exceeded")
            return self._json(429, body)
        return self._json(200, body)

    def _influx_write(self, q):
        """InfluxDB line-protocol write endpoint (reference
        `api/v1/handler/influxdb/write.go`); 204 on success like
        InfluxDB itself."""
        import time as _time

        from m3_tpu.server.influx import parse_lines, points_to_writes

        precision = q.get("precision", ["ns"])[0]
        points = parse_lines(self._body().decode(), precision,
                             now_nanos=int(_time.time() * 1e9))
        docs, ts, vals = points_to_writes(points)
        written, rejected = (self._ingest_tagged(docs, ts, vals)
                             if docs else (0, 0))
        self.send_response(429 if rejected else 204)
        self.send_header("X-Written", str(written))
        if rejected:
            self.send_header("X-Rejected", str(rejected))
        self.send_header("Content-Length", "0")
        self.end_headers()

    def _query(self, is_range: bool, q):
        query = q["query"][0]
        if is_range:
            start = _parse_time(q["start"][0])
            end = _parse_time(q["end"][0])
            step = _parse_step(q["step"][0])
        else:
            start = end = _parse_time(q["time"][0])
            step = 10**9
        dl = self._deadline(q)
        ctx = self.ctx
        # optional namespace override (e.g. namespace=_m3_selfmon: the
        # self-monitoring history is served by the SAME PromQL surface
        # as user data); unknown names 400 via the ValueError path
        engine = ctx.engine_for(q.get("namespace", [None])[0])
        try:
            # admission first (a shed query must not bind engine
            # resources), then the deadline rides the context into the
            # engine → fanout → wire
            with ctx.admission.admit(deadline=dl), xdeadline.bind(dl):
                block = engine.execute_range(query, start, end, step)
        except Exception as e:  # noqa: BLE001 — observed, then re-raised
            ctx.observe_query("promql", query, dl, error=e)
            raise
        result = []
        for i, meta in enumerate(block.series):
            values = [
                [t / 1e9, _fmt(v)]
                for t, v in zip(block.step_times.tolist(), block.values[i])
                if not math.isnan(v)
            ]
            if not values:
                continue
            metric = {k.decode(): v.decode() for k, v in meta.tags}
            if is_range:
                result.append({"metric": metric, "values": values})
            else:
                result.append({"metric": metric, "value": values[-1]})
        ctx.observe_query("promql", query, dl)
        payload = {
            "status": "success",
            "data": {
                "resultType": "matrix" if is_range else "vector",
                "result": result,
            },
        }
        if dl.warnings:
            # partial-result policy: non-required fanout sources that
            # failed/missed the deadline (Prometheus warnings field)
            payload["warnings"] = list(dl.warnings)
        return self._json(200, payload)

    def _fetch_docs(self, q):
        ctx = self.ctx
        start = _parse_time(q.get("start", ["0"])[0])
        # Prometheus API bounds are inclusive; index queries are
        # end-exclusive (same rule as Engine._fetch / remote read)
        end = _parse_time(q.get("end", [str(2**31)])[0]) + 1
        return ctx.db.query_ids(ctx.namespace, All(), start, end)

    def _labels(self, q):
        names = set()
        for d in self._fetch_docs(q):
            names.update(k.decode() for k in d.tags())
        return self._json(200, {"status": "success", "data": sorted(names)})

    def _label_values(self, name, q):
        values = set()
        for d in self._fetch_docs(q):
            v = d.tags().get(name.encode())
            if v is not None:
                values.add(v.decode())
        return self._json(200, {"status": "success", "data": sorted(values)})

    def _series(self, q):
        out = [
            {k.decode(): v.decode() for k, v in sorted(d.tags().items())}
            for d in self._fetch_docs(q)
        ]
        return self._json(200, {"status": "success", "data": out})


def _fmt(v: float) -> str:
    return repr(float(v)) if v == v else "NaN"


class ApiContext:
    def __init__(self, db: Database, namespace: str = "default",
                 downsampler=None, registry=None, tracer=None,
                 migrator=None, admission: AdmissionController | None = None,
                 query_timeout_s: float = 30.0,
                 slow_query_fraction: float = 0.75,
                 remotes=None, remotes_required: bool = False,
                 metrics_scope=None, checkpointer=None, selfmon=None,
                 controller=None):
        self.db = db
        self.namespace = namespace
        self.downsampler = downsampler
        self.registry = registry
        self.tracer = tracer
        self.migrator = migrator  # storage.migration.ShardMigrator | None
        self.checkpointer = checkpointer  # aggregator checkpoint driver
        self.selfmon = selfmon  # instrument.selfmon.SelfMonitor | None
        self.controller = controller  # x.controller.Controller | None
        # Per-namespace engine interning for the ``namespace=`` query
        # param (bounded: namespaces are config objects, not request
        # input — an unknown name 400s before anything is built).
        self._ns_engines: dict = {}
        self._ns_engines_mu = threading.Lock()
        # read-path overload controls (see module docstring); the
        # default AdmissionController(0) gates nothing
        self.admission = admission or AdmissionController()
        self.query_timeout_s = float(query_timeout_s)
        self.slow_query_fraction = float(slow_query_fraction)
        self.slow_query_total = 0
        self._slow_mu = threading.Lock()
        self.slow_queries = collections.deque(maxlen=32)
        # Hot-path latency histograms, interned ONCE (per-request
        # intern is the metric-hygiene waste): coordinator ingest, and
        # query end-to-end + per-phase (fetch = storage time recorded
        # by the deadline's phase accumulator, eval = the rest).
        self.hist_ingest = self.hist_query = None
        self._hist_query_phase = {}
        if registry is not None:
            # under the node's metrics prefix (assembly passes its
            # prefixed scope) so the series merge across a fleet
            base = (metrics_scope if metrics_scope is not None
                    else registry.scope(""))
            self.hist_ingest = base.scope("ingest").histogram("seconds")
            qscope = base.scope("query")
            self.hist_query = qscope.histogram("seconds")
            self._hist_query_phase = {
                "fetch": qscope.tagged({"phase": "fetch"}).histogram(
                    "phase_seconds"),
                "eval": qscope.tagged({"phase": "eval"}).histogram(
                    "phase_seconds"),
            }
        # cross-coordinator federation: remote stores (query/remote
        # RemoteStorage) merged best-effort with the local database
        # unless remotes_required
        self.remotes = list(remotes or [])
        local = DatabaseStorage(db, namespace)
        if self.remotes:
            stores = [local] + self.remotes
            required = [0] + (list(range(1, len(stores)))
                              if remotes_required else [])
            storage = FederatedStorage(stores, required=required)
        else:
            storage = local
        self.engine = Engine(storage, tracer=tracer)
        from m3_tpu.query.graphite import GraphiteEngine, GraphiteStorage

        self.graphite = GraphiteEngine(GraphiteStorage(db, namespace))

    def engine_for(self, namespace: str | None) -> Engine:
        """The engine serving one namespace: the default request path
        keeps the federated default-namespace engine; ``namespace=``
        (e.g. ``_m3_selfmon`` — how a stored fleet-health series is
        queried from outside) gets a LOCAL-storage engine over that
        namespace, interned per name."""
        if namespace is None or namespace == self.namespace:
            return self.engine
        if namespace not in self.db.namespaces:
            raise ValueError(f"unknown namespace {namespace!r}")
        with self._ns_engines_mu:
            eng = self._ns_engines.get(namespace)
            if eng is None:
                eng = self._ns_engines[namespace] = Engine(
                    DatabaseStorage(self.db, namespace), tracer=self.tracer)
            return eng

    def observe_query(self, kind: str, query: str, dl: Deadline,
                      error: Exception | None = None) -> None:
        """Slow-query log + latency histograms: every query lands in
        the windowed query histograms (end-to-end + fetch/eval phase
        split); queries that spent more than ``slow_query_fraction`` of
        their deadline (or died trying) additionally land in the
        slow-query log with matchers and per-phase timings — the
        operator's view of WHAT is eating the budget (`/health`
        ``query.slow``)."""
        elapsed = dl.elapsed()
        if self.hist_query is not None:
            self.hist_query.record(elapsed)
            fetch_s = dl.phases.get("fetch", 0.0)
            self._hist_query_phase["fetch"].record(fetch_s)
            self._hist_query_phase["eval"].record(max(0.0, elapsed - fetch_s))
        if self.slow_query_fraction <= 0 or dl.timeout_s <= 0:
            return
        frac = dl.elapsed() / dl.timeout_s
        if frac < self.slow_query_fraction:
            return  # fast queries — including fast failures — skip the log
        entry = {
            "kind": kind,
            "query": query,
            "timeout_s": round(dl.timeout_s, 3),
            "elapsed_s": round(dl.elapsed(), 3),
            "deadline_fraction": round(frac, 3),
            "phases": {k: round(v, 3) for k, v in dl.phases.items()},
            "time_unix": time.time(),
        }
        if dl.warnings:
            entry["warnings"] = list(dl.warnings)
        if error is not None:
            entry["error"] = f"{type(error).__name__}: {error}"
        with self._slow_mu:
            self.slow_query_total += 1
            self.slow_queries.append(entry)

    def query_status(self) -> dict:
        """The /health ``query`` document: admission gauges + the slow
        log tail."""
        out = self.admission.metrics()
        out["default_timeout_s"] = self.query_timeout_s
        with self._slow_mu:
            out["slow_query_total"] = self.slow_query_total
            out["slow"] = list(self.slow_queries)[-10:]
        return out


def make_server(ctx: ApiContext, host: str = "127.0.0.1", port: int = 0):
    """Returns a ThreadingHTTPServer bound to (host, port); port 0 picks
    a free one (server.server_address[1])."""
    handler = type("BoundHandler", (_Handler,), {"ctx": ctx})
    return ThreadingHTTPServer((host, port), handler)


def serve_background(ctx: ApiContext, host: str = "127.0.0.1", port: int = 0):
    srv = make_server(ctx, host, port)
    t = threading.Thread(target=srv.serve_forever, daemon=True)
    t.start()
    return srv
