"""Prometheus remote write/read: snappy + protobuf wire handling.

Equivalent of the reference's remote handlers
(`src/query/api/v1/handler/prometheus/remote/{write.go,read.go}`):
POST bodies are snappy-compressed `prompb.WriteRequest`/`ReadRequest`
messages.  No protobuf runtime is required — the prompb subset is four
tiny messages hand-decoded from the wire format (the schema is frozen
by the Prometheus remote-storage spec):

    WriteRequest { repeated TimeSeries timeseries = 1; }
    TimeSeries   { repeated Label labels = 1; repeated Sample samples = 2; }
    Label        { string name = 1; string value = 2; }
    Sample       { double value = 1; int64 timestamp = 2; }  # ms!

    ReadRequest  { repeated Query queries = 1; }
    Query        { int64 start_timestamp_ms = 1; int64 end_timestamp_ms = 2;
                   repeated LabelMatcher matchers = 3; }
    LabelMatcher { Type type = 1 (EQ/NEQ/RE/NRE); string name = 2;
                   string value = 3; }
    ReadResponse { repeated QueryResult results = 1; }
    QueryResult  { repeated TimeSeries timeseries = 1; }
"""

from __future__ import annotations

import struct
from dataclasses import dataclass, field

from m3_tpu.server import snappy

# ---------------------------------------------------------------------------
# Minimal protobuf wire reader/writer
# ---------------------------------------------------------------------------


class ProtoError(ValueError):
    pass


def _uvarint(data: bytes, pos: int) -> tuple[int, int]:
    out = 0
    shift = 0
    while True:
        if pos >= len(data):
            raise ProtoError("truncated varint")
        b = data[pos]
        pos += 1
        out |= (b & 0x7F) << shift
        if not b & 0x80:
            return out, pos
        shift += 7
        if shift > 70:
            raise ProtoError("varint too long")


def _fields(data: bytes):
    """Yield (field_number, wire_type, value) triples."""
    pos = 0
    while pos < len(data):
        key, pos = _uvarint(data, pos)
        fnum, wtype = key >> 3, key & 7
        if wtype == 0:  # varint
            val, pos = _uvarint(data, pos)
        elif wtype == 1:  # 64-bit
            val = data[pos : pos + 8]
            pos += 8
        elif wtype == 2:  # length-delimited
            ln, pos = _uvarint(data, pos)
            val = data[pos : pos + ln]
            if len(val) != ln:
                raise ProtoError("truncated length-delimited field")
            pos += ln
        elif wtype == 5:  # 32-bit
            val = data[pos : pos + 4]
            pos += 4
        else:
            raise ProtoError(f"unsupported wire type {wtype}")
        yield fnum, wtype, val


def _emit_varint(n: int) -> bytes:
    out = bytearray()
    while True:
        b = n & 0x7F
        n >>= 7
        out.append(b | (0x80 if n else 0))
        if not n:
            return bytes(out)


def _emit_field(fnum: int, wtype: int, payload: bytes) -> bytes:
    return _emit_varint((fnum << 3) | wtype) + payload


def _emit_len(fnum: int, payload: bytes) -> bytes:
    return _emit_field(fnum, 2, _emit_varint(len(payload)) + payload)


def _signed(v: int) -> int:
    """protobuf int64 varints are two's-complement in 64 bits."""
    return v - (1 << 64) if v >= 1 << 63 else v


# ---------------------------------------------------------------------------
# prompb messages
# ---------------------------------------------------------------------------


@dataclass
class PromTimeSeries:
    labels: dict            # bytes -> bytes
    samples: list           # [(timestamp_nanos, value)]


def _parse_label(data: bytes) -> tuple[bytes, bytes]:
    name = value = b""
    for fnum, _wt, val in _fields(data):
        if fnum == 1:
            name = val
        elif fnum == 2:
            value = val
    return name, value


def _parse_sample(data: bytes) -> tuple[int, float]:
    value = 0.0
    ts_ms = 0
    for fnum, wt, val in _fields(data):
        if fnum == 1 and wt == 1:
            value = struct.unpack("<d", val)[0]
        elif fnum == 2 and wt == 0:
            ts_ms = _signed(val)
    return ts_ms * 10**6, value  # ms → nanos


def _parse_timeseries(data: bytes) -> PromTimeSeries:
    labels = {}
    samples = []
    for fnum, _wt, val in _fields(data):
        if fnum == 1:
            n, v = _parse_label(val)
            labels[n] = v
        elif fnum == 2:
            samples.append(_parse_sample(val))
    return PromTimeSeries(labels, samples)


def parse_write_request(body: bytes) -> list[PromTimeSeries]:
    """snappy-compressed WriteRequest → series list."""
    raw = snappy.decompress(body)
    out = []
    for fnum, _wt, val in _fields(raw):
        if fnum == 1:
            out.append(_parse_timeseries(val))
    return out


@dataclass
class PromMatcher:
    type: int  # 0 EQ, 1 NEQ, 2 RE, 3 NRE
    name: bytes
    value: bytes


@dataclass
class PromQuery:
    start_nanos: int
    end_nanos: int
    matchers: list = field(default_factory=list)


def _parse_matcher(data: bytes) -> PromMatcher:
    t = 0
    name = value = b""
    for fnum, wt, val in _fields(data):
        if fnum == 1 and wt == 0:
            t = val
        elif fnum == 2:
            name = val
        elif fnum == 3:
            value = val
    return PromMatcher(t, name, value)


def parse_read_request(body: bytes) -> list[PromQuery]:
    raw = snappy.decompress(body)
    queries = []
    for fnum, _wt, val in _fields(raw):
        if fnum != 1:
            continue
        q = PromQuery(0, 0)
        for f2, w2, v2 in _fields(val):
            if f2 == 1 and w2 == 0:
                q.start_nanos = _signed(v2) * 10**6
            elif f2 == 2 and w2 == 0:
                q.end_nanos = _signed(v2) * 10**6
            elif f2 == 3:
                q.matchers.append(_parse_matcher(v2))
        queries.append(q)
    return queries


def _emit_timeseries(ts: PromTimeSeries) -> bytes:
    parts = []
    for name, value in sorted(ts.labels.items()):
        parts.append(_emit_len(1, _emit_len(1, name) + _emit_len(2, value)))
    for t_nanos, v in ts.samples:
        sample = _emit_field(1, 1, struct.pack("<d", v)) + _emit_field(
            2, 0, _emit_varint((t_nanos // 10**6) & ((1 << 64) - 1))
        )
        parts.append(_emit_len(2, sample))
    return b"".join(parts)


def build_read_response(results: list[list[PromTimeSeries]]) -> bytes:
    """QueryResult per query → snappy-compressed ReadResponse."""
    out = []
    for series_list in results:
        qr = b"".join(_emit_len(1, _emit_timeseries(s)) for s in series_list)
        out.append(_emit_len(1, qr))
    return snappy.compress(b"".join(out))


def build_write_request(series_list: list[PromTimeSeries]) -> bytes:
    """For clients/tests: series → snappy-compressed WriteRequest."""
    body = b"".join(_emit_len(1, _emit_timeseries(s)) for s in series_list)
    return snappy.compress(body)
