"""Node entry point: `python -m m3_tpu.server.node_main <config.yaml>`.

Equivalent of the reference's service mains
(`src/cmd/services/m3dbnode/main/main.go` — parse config, server.Run,
block on signals).  Writes a `<root>/node.json` status file (pid + HTTP
port) once serving, so harnesses (dtest) can discover the ephemeral
port; exits cleanly on SIGTERM, flushing the commitlog.
"""

from __future__ import annotations

import json
import os
import signal
import sys
import threading
from pathlib import Path


def main(argv=None) -> int:
    argv = argv if argv is not None else sys.argv[1:]
    if len(argv) != 1:
        print("usage: python -m m3_tpu.server.node_main <config.yaml>",
              file=sys.stderr)
        return 2
    # force the CPU backend before any jax import captures the env: a
    # node process must not grab the TPU tunnel for host-side serving
    if os.environ.get("M3_NODE_PLATFORM", "cpu") == "cpu":
        os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import jax

    if os.environ.get("JAX_PLATFORMS") == "cpu":
        jax.config.update("jax_platforms", "cpu")

    from m3_tpu.core.config import load_config
    from m3_tpu.instrument import logger
    from m3_tpu.server.assembly import run_node

    log = logger("node_main")
    cfg = load_config(argv[0])
    asm = run_node(cfg)
    status = {
        "pid": os.getpid(),
        "port": asm.port,
        "carbon_port": asm.carbon_port,
        "rpc_port": asm.rpc_port,
        "admin_port": asm.admin_port,
        "query_port": asm.query_port,
        "root": cfg.db.root,
    }
    status_path = Path(cfg.db.root) / "node.json"
    status_path.write_text(json.dumps(status))
    log.info("node up: %s", status)

    stop = threading.Event()

    def _term(signum, frame):
        stop.set()

    signal.signal(signal.SIGTERM, _term)
    signal.signal(signal.SIGINT, _term)
    stop.wait()
    # SIGTERM is a true drain, not a fast exit: stop the ingest front
    # doors, flush/snapshot everything persistable, wait (bounded) for
    # any LEAVING shards to cut over to their new owners, then close —
    # the RPC listener serves peer streams until the very end.  The
    # M3_DRAIN_TIMEOUT_S env knob bounds the handoff wait (dtest
    # harnesses shrink it; operators may extend it for big handoffs).
    log.info("node draining")
    asm.drain(handoff_timeout_s=float(
        os.environ.get("M3_DRAIN_TIMEOUT_S", "60")))
    log.info("node shut down")
    status_path.unlink(missing_ok=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
