"""TCP ingest server: the aggregator's wire front door.

Equivalent of the reference's rawtcp server
(`src/aggregator/server/rawtcp/server.go:52 struct, :125 handle loop`):
accept connections, iterate framed metric batches off each socket, and
feed them to the aggregator.  The reference's per-message protobuf
decode loop becomes one frame = one already-batched array payload — the
batching the reference does in its client queues happens in the wire
format itself.

Robustness (reference rawtcp sheds load on slow consumers): decoded
frames no longer run the sink inline on the handler thread — they land
in ONE bounded global ingest queue drained by a worker, and two budgets
guard it:

* a global high-watermark (``max_queue_frames``) — total decoded
  frames in flight across every connection;
* a per-connection inflight budget (``per_conn_inflight``) — one
  flooding client cannot own the whole queue.

A frame arriving over budget is REJECTED with an explicit
``INGEST_BACKOFF`` frame (retry-after hint) instead of silently
stalling the socket or dropping the connection; the connection stays
up and the shed is counted.  Clients that sent ``INGEST_HELLO`` with
the want-acks flag additionally receive ``INGEST_ACK`` after each
frame is FULLY ingested — the ack is the durability boundary, so a
well-behaved client never counts a sample as delivered that the server
then loses.  Legacy clients (no HELLO) see no reply traffic except
BACKOFF under overload — the pre-existing fire-and-forget contract.

A decode/protocol error still closes the connection (rawtcp's error
handling); the ``ingest_tcp.frame`` faultpoint (m3_tpu.x.fault) sits
between recv and decode so dtest can inject drop/delay/corrupt/error
at the exact socket boundary.
"""

from __future__ import annotations

import queue
import select
import socket
import socketserver
import threading
import time

import numpy as np

from m3_tpu.instrument import tracing
from m3_tpu.instrument.tracing import NOOP_TRACER, Tracepoint
from m3_tpu.metrics.policy import StoragePolicy
from m3_tpu.metrics.types import MetricType
from m3_tpu.msg import protocol as wire
from m3_tpu.x import fault


class _IngestMetrics:
    """The server's instruments, interned ONCE at construction: the
    handler/worker loops run per frame, and a per-call registry
    intern (name lookup under the registry lock) is exactly the
    hot-path waste m3lint's metric-hygiene rule rejects."""

    __slots__ = ("decode_errors", "unknown_frames", "fault_errors",
                 "shed_frames", "shed_samples", "sink_errors", "samples",
                 "queue_depth", "batch_seconds")

    def __init__(self, scope):
        self.decode_errors = scope.counter("decode_errors")
        self.unknown_frames = scope.counter("unknown_frames")
        self.fault_errors = scope.counter("fault_errors")
        self.shed_frames = scope.counter("shed_frames")
        self.shed_samples = scope.counter("shed_samples")
        self.sink_errors = scope.counter("sink_errors")
        self.samples = scope.counter("samples")
        self.queue_depth = scope.gauge("queue_depth")
        # hot-path latency: windowed log-bucket histogram (mergeable
        # across nodes), NOT a lifetime-reservoir Timer
        self.batch_seconds = scope.histogram("batch_seconds")


def aggregator_sink(aggregator, lock: threading.Lock | None = None,
                    clock=time.time_ns):
    """Standard sink: group a wire batch by metric type (the engine
    ingests one type per call, like the reference's per-union dispatch
    in AddUntimed) and feed the aggregator under `lock`.

    The returned sink handles all three ingest classes (reference
    aggregator.go AddUntimed :263 / AddTimed :77 / AddPassthrough :86)
    via its ``kind`` argument — the frame type dispatches in the
    handler."""
    lock = lock or threading.Lock()

    def sink(batch, kind: int = wire.METRIC_BATCH) -> None:
        with lock:
            if kind == wire.PASSTHROUGH_BATCH:
                policy, ids, values, times = batch
                aggregator.add_passthrough_batch(
                    ids, values, times, StoragePolicy.parse(policy))
                return
            if kind == wire.FORWARDED_BATCH:
                policy, entries = batch
                aggregator.add_forwarded_batch(
                    StoragePolicy.parse(policy), entries)
                return
            mts = np.asarray(batch.metric_types)
            for mt in np.unique(mts):
                sel = np.nonzero(mts == mt)[0]
                ids = [batch.ids[i] for i in sel]
                if kind == wire.TIMED_BATCH:
                    # The server clock anchors fresh window rings
                    # (entry.go addTimed validates against now±buffer).
                    aggregator.add_timed_batch(
                        MetricType(int(mt)), ids,
                        batch.values[sel], batch.times[sel],
                        now_nanos=clock())
                else:
                    aggregator.add_untimed_batch(
                        MetricType(int(mt)), ids,
                        batch.values[sel], batch.times[sel])

    return sink


_BATCH_FRAMES = (wire.METRIC_BATCH, wire.TIMED_BATCH,
                 wire.PASSTHROUGH_BATCH, wire.FORWARDED_BATCH)


class _ConnState:
    """Per-connection book-keeping shared by the handler thread (recv,
    shed replies) and the ingest worker (acks): the write lock keeps a
    BACKOFF and an ACK from interleaving mid-frame on the socket.
    ``pending_trace`` is handler-thread-only: set by an INGEST_TRACE
    preamble frame, attached to the NEXT batch frame enqueued."""

    __slots__ = ("want_acks", "inflight", "wlock", "pending_trace")

    def __init__(self):
        self.want_acks = False
        self.inflight = 0  # frames queued; guarded by server._q_lock
        self.wlock = threading.Lock()
        self.pending_trace = None


class _IngestHandler(socketserver.BaseRequestHandler):
    def handle(self):
        srv = self.server
        sock = self.request
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        conn = _ConnState()
        mx = srv.metrics
        while True:
            try:
                frame = wire.recv_frame(sock)
            except (wire.ProtocolError, OSError):
                if mx is not None:
                    mx.decode_errors.inc()
                break
            if frame is None:
                break
            ftype, payload = frame
            if ftype == wire.INGEST_HELLO:
                try:
                    conn.want_acks = bool(
                        wire.decode_ingest_hello(payload)
                        & wire.HELLO_WANT_ACKS)
                except Exception:  # noqa: BLE001
                    if mx is not None:
                        mx.decode_errors.inc()
                    break
                continue
            if ftype == wire.INGEST_TRACE:
                # sampled client: the context rides a preamble frame
                # and stitches the NEXT batch's span into its trace
                try:
                    conn.pending_trace = wire.decode_ingest_trace(payload)
                except Exception:  # noqa: BLE001
                    if mx is not None:
                        mx.decode_errors.inc()
                    break
                continue
            if ftype not in _BATCH_FRAMES:
                if mx is not None:
                    mx.unknown_frames.inc()
                break
            # Socket-boundary faultpoint: drop kills the connection
            # (the lost-frame case rawtcp clients must survive), error
            # acts like a transport failure, corrupt feeds the decode
            # path a flipped byte, delay models a slow server.
            try:
                act, payload = fault.mangle("ingest_tcp.frame", payload)
            except fault.FaultInjected:
                if mx is not None:
                    mx.fault_errors.inc()
                break
            if act == "drop":
                break
            try:
                if ftype == wire.PASSTHROUGH_BATCH:
                    batch = wire.decode_passthrough_batch(payload)
                    n = len(batch[1])
                elif ftype == wire.FORWARDED_BATCH:
                    batch = wire.decode_forwarded_batch(payload)
                    n = len(batch[1])
                else:
                    batch = wire.decode_metric_batch(payload)
                    n = len(batch.ids)
            except (wire.ProtocolError, Exception):  # noqa: BLE001
                if mx is not None:
                    mx.decode_errors.inc()
                break
            tctx, conn.pending_trace = conn.pending_trace, None
            if not srv._try_enqueue(conn, sock, ftype, batch, n, tctx):
                # Load shed: explicit BACKOFF, connection stays up.
                # Writability-probed: a fire-and-forget client that
                # never reads its socket eventually closes the TCP
                # window, and a blocking send here would wedge this
                # handler (it must keep reading) — such a client gets
                # dropped instead.
                if mx is not None:
                    mx.shed_frames.inc()
                    mx.shed_samples.inc(n)
                with conn.wlock:
                    try:
                        _, writable, _ = select.select(
                            [], [sock], [], srv.ack_send_timeout_s)
                        if not writable:
                            break
                        wire.send_frame(
                            sock, wire.INGEST_BACKOFF,
                            wire.encode_ingest_backoff(srv.backoff_hint_ms))
                    except OSError:
                        break
                continue


class IngestServer(socketserver.ThreadingTCPServer):
    """sink(MetricBatch) is called per decoded frame — typically
    `lambda b: aggregator.add_untimed_batch(b.metric_types, b.ids,
    b.values, b.times)` behind a lock.

    Decoded frames flow through a bounded global queue drained by one
    worker thread (frame order per connection is preserved); acks are
    sent only after the sink call returns, so an acked frame is an
    ingested frame."""

    allow_reuse_address = True
    daemon_threads = True

    def __init__(self, sink, host: str = "127.0.0.1", port: int = 0,
                 instrument=None, aggregator=None,
                 max_queue_frames: int = 256, per_conn_inflight: int = 64,
                 backoff_hint_ms: int = 50, ack_send_timeout_s: float = 5.0,
                 tracer=None):
        self.sink = sink
        self.ack_send_timeout_s = ack_send_timeout_s
        self._closing = False
        self.scope = (
            instrument.scope("ingest_tcp") if instrument is not None else None
        )
        # instruments interned once (hot path: per-frame loops)
        self.metrics = (_IngestMetrics(self.scope)
                        if self.scope is not None else None)
        self.tracer = tracer if tracer is not None else NOOP_TRACER
        self.max_queue_frames = max_queue_frames
        self.per_conn_inflight = per_conn_inflight
        self.backoff_hint_ms = backoff_hint_ms
        # Optional nullary admission gate (raises typed DiskCapacityError
        # to refuse a frame un-acked); assembly binds it to the disk
        # ledger's check_ingest when disk.enabled.
        self.ingest_gate = None
        self._queue: "queue.Queue" = queue.Queue()
        self._q_lock = threading.Lock()
        self._inflight = 0
        self._agg_collector = None
        self._registry = (
            instrument.registry if instrument is not None else None)
        super().__init__((host, port), _IngestHandler)
        self._worker = threading.Thread(target=self._drain, daemon=True)
        self._worker.start()
        if instrument is not None and aggregator is not None:
            # Surface the engine's plain-int counters (forwarded-tail
            # conflicts, timed rejects, series-limit rejects) on this
            # process's /metrics at scrape time.  After bind — a
            # failed construction must not leak the collector.
            from m3_tpu.aggregator.engine import instrument_aggregator

            self._agg_collector = instrument_aggregator(
                instrument, aggregator)

    # -- ingest queue ------------------------------------------------------

    def _try_enqueue(self, conn, sock, ftype, batch, n, tctx=None) -> bool:
        # Disk-pressure shed rides the SAME refuse-before-ack path as
        # queue overflow: at CRITICAL the frame is never enqueued, the
        # client gets the explicit BACKOFF hint, and since the ack is
        # the durability boundary nothing un-acked is lost.
        gate = self.ingest_gate
        if gate is not None:
            try:
                gate()
            except OSError:  # DiskCapacityError — typed capacity refuse
                return False
        with self._q_lock:
            # A server mid-shutdown sheds (explicit BACKOFF) rather
            # than enqueueing onto a queue whose worker is stopping —
            # clients get a prompt signal instead of an ack that never
            # comes.
            if (self._closing
                    or self._inflight >= self.max_queue_frames
                    or conn.inflight >= self.per_conn_inflight):
                return False
            self._inflight += 1
            conn.inflight += 1
            if self.metrics is not None:
                self.metrics.queue_depth.update(self._inflight)
            # put() under the lock (never blocks: the Queue is
            # unbounded; the watermark above is the real bound) so an
            # accepted frame can never land AFTER the shutdown
            # sentinel, which is enqueued under this same lock.
            self._queue.put((conn, sock, ftype, batch, n, tctx))
        return True

    def _drain(self) -> None:
        while True:
            item = self._queue.get()
            if item is None:
                return
            conn, sock, ftype, batch, n, tctx = item
            t0 = time.perf_counter()
            try:
                # The worker thread never inherits a binding
                # (contextvar rule): the frame's own context is bound
                # here — BEFORE the span opens, so the batch span
                # parents on the SENDER's span, joining its trace.
                with tracing.bind(tctx):
                    span = (self.tracer.start_span(
                        Tracepoint.INGEST_TCP_BATCH,
                        {"n": n, "frame": ftype})
                        if tctx is not None else tracing.NOOP_SPAN)
                    with span:
                        if ftype == wire.METRIC_BATCH:
                            # one-arg call: custom sinks keep working
                            self.sink(batch)
                        else:
                            self.sink(batch, ftype)
            except Exception:  # noqa: BLE001 — a sink fault (e.g. no
                # passthrough handler configured, or a one-arg custom
                # sink receiving a timed frame) must close THIS
                # connection with a counter, not kill the worker
                # thread with an unrecorded traceback.
                self._dec_inflight(conn)
                if self.metrics is not None:
                    self.metrics.sink_errors.inc()
                try:
                    sock.shutdown(socket.SHUT_RDWR)
                except OSError:
                    pass
                continue
            self._dec_inflight(conn)
            if self.metrics is not None:
                self.metrics.samples.inc(n)
                self.metrics.batch_seconds.record(time.perf_counter() - t0)
            if conn.want_acks:
                with conn.wlock:
                    # The lone drain worker must never wedge on one
                    # stalled client's full send buffer (it serves
                    # EVERY connection): probe writability first and
                    # drop the stalled connection instead of blocking.
                    try:
                        _, writable, _ = select.select(
                            [], [sock], [], self.ack_send_timeout_s)
                        if writable:
                            wire.send_frame(sock, wire.INGEST_ACK,
                                            wire.encode_ingest_ack(n))
                        else:
                            sock.shutdown(socket.SHUT_RDWR)
                    except OSError:
                        pass  # client went away; its loss is counted
                        # client-side by the missing ack

    def _dec_inflight(self, conn) -> None:
        with self._q_lock:
            self._inflight -= 1
            conn.inflight -= 1
            if self.metrics is not None:
                self.metrics.queue_depth.update(self._inflight)

    # -- lifecycle ---------------------------------------------------------

    def _drop_collector(self):
        if self._agg_collector is not None and self._registry is not None:
            self._registry.unregister_collector(self._agg_collector)
            self._agg_collector = None

    def _stop_worker(self):
        if self._worker is not None:
            with self._q_lock:
                # _closing is already observed by the gate under this
                # lock, so the sentinel lands strictly after every
                # accepted frame: the worker drains the backlog (acks
                # included) before exiting.
                self._queue.put(None)
            self._worker.join(timeout=30)
            self._worker = None

    def shutdown(self):
        # Every call site stops via shutdown() (server_close is rarer):
        # drop the collector on either path, or the registry pins this
        # server's aggregator and scrapes it forever.  Order: flag
        # closing (handlers shed new frames), stop the accept loop,
        # then the worker drains the backlog (acks included) and exits.
        # _closing flips under _q_lock: the shed gate reads it under
        # that lock, so no handler can observe the pre-closing state
        # after this releases (m3lint lock-discipline).
        self._drop_collector()
        with self._q_lock:
            self._closing = True
        super().shutdown()
        self._stop_worker()

    def server_close(self):
        self._drop_collector()
        with self._q_lock:
            self._closing = True
        self._stop_worker()
        super().server_close()

    @property
    def port(self) -> int:
        return self.server_address[1]


def serve_ingest_background(sink, host: str = "127.0.0.1", port: int = 0,
                            instrument=None, aggregator=None,
                            **kw) -> IngestServer:
    srv = IngestServer(sink, host, port, instrument, aggregator, **kw)
    t = threading.Thread(target=srv.serve_forever, daemon=True)
    t.start()
    return srv
