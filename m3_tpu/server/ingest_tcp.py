"""TCP ingest server: the aggregator's wire front door.

Equivalent of the reference's rawtcp server
(`src/aggregator/server/rawtcp/server.go:52 struct, :125 handle loop`):
accept connections, iterate framed metric batches off each socket, and
feed them to the aggregator.  The reference's per-message protobuf
decode loop becomes one frame = one already-batched array payload — the
batching the reference does in its client queues happens in the wire
format itself, so the server's hot loop is decode → add_untimed_batch.

A decode/protocol error closes the connection (rawtcp's error handling);
the client reconnects and retries its queue.
"""

from __future__ import annotations

import socket
import socketserver
import threading
import time

import numpy as np

from m3_tpu.metrics.policy import StoragePolicy
from m3_tpu.metrics.types import MetricType
from m3_tpu.msg import protocol as wire


def aggregator_sink(aggregator, lock: threading.Lock | None = None,
                    clock=time.time_ns):
    """Standard sink: group a wire batch by metric type (the engine
    ingests one type per call, like the reference's per-union dispatch
    in AddUntimed) and feed the aggregator under `lock`.

    The returned sink handles all three ingest classes (reference
    aggregator.go AddUntimed :263 / AddTimed :77 / AddPassthrough :86)
    via its ``kind`` argument — the frame type dispatches in the
    handler."""
    lock = lock or threading.Lock()

    def sink(batch, kind: int = wire.METRIC_BATCH) -> None:
        with lock:
            if kind == wire.PASSTHROUGH_BATCH:
                policy, ids, values, times = batch
                aggregator.add_passthrough_batch(
                    ids, values, times, StoragePolicy.parse(policy))
                return
            if kind == wire.FORWARDED_BATCH:
                policy, entries = batch
                aggregator.add_forwarded_batch(
                    StoragePolicy.parse(policy), entries)
                return
            mts = np.asarray(batch.metric_types)
            for mt in np.unique(mts):
                sel = np.nonzero(mts == mt)[0]
                ids = [batch.ids[i] for i in sel]
                if kind == wire.TIMED_BATCH:
                    # The server clock anchors fresh window rings
                    # (entry.go addTimed validates against now±buffer).
                    aggregator.add_timed_batch(
                        MetricType(int(mt)), ids,
                        batch.values[sel], batch.times[sel],
                        now_nanos=clock())
                else:
                    aggregator.add_untimed_batch(
                        MetricType(int(mt)), ids,
                        batch.values[sel], batch.times[sel])

    return sink


class _IngestHandler(socketserver.BaseRequestHandler):
    def handle(self):
        srv = self.server
        sock = self.request
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        while True:
            try:
                frame = wire.recv_frame(sock)
            except (wire.ProtocolError, OSError):
                if srv.scope is not None:
                    srv.scope.counter("decode_errors").inc()
                break
            if frame is None:
                break
            ftype, payload = frame
            if ftype not in (wire.METRIC_BATCH, wire.TIMED_BATCH,
                             wire.PASSTHROUGH_BATCH, wire.FORWARDED_BATCH):
                if srv.scope is not None:
                    srv.scope.counter("unknown_frames").inc()
                break
            try:
                if ftype == wire.PASSTHROUGH_BATCH:
                    batch = wire.decode_passthrough_batch(payload)
                    n = len(batch[1])
                elif ftype == wire.FORWARDED_BATCH:
                    batch = wire.decode_forwarded_batch(payload)
                    n = len(batch[1])
                else:
                    batch = wire.decode_metric_batch(payload)
                    n = len(batch.ids)
            except (wire.ProtocolError, Exception):  # noqa: BLE001
                if srv.scope is not None:
                    srv.scope.counter("decode_errors").inc()
                break
            try:
                if ftype == wire.METRIC_BATCH:
                    srv.sink(batch)  # one-arg call: custom sinks keep working
                else:
                    srv.sink(batch, ftype)
            except Exception:  # noqa: BLE001 — a sink fault (e.g. no
                # passthrough handler configured, or a one-arg custom
                # sink receiving a timed frame) must close THIS
                # connection with a counter, not kill the handler
                # thread with an unrecorded traceback.
                if srv.scope is not None:
                    srv.scope.counter("sink_errors").inc()
                break
            if srv.scope is not None:
                srv.scope.counter("samples").inc(n)


class IngestServer(socketserver.ThreadingTCPServer):
    """sink(MetricBatch) is called per decoded frame — typically
    `lambda b: aggregator.add_untimed_batch(b.metric_types, b.ids,
    b.values, b.times)` behind a lock."""

    allow_reuse_address = True
    daemon_threads = True

    def __init__(self, sink, host: str = "127.0.0.1", port: int = 0,
                 instrument=None, aggregator=None):
        self.sink = sink
        self.scope = (
            instrument.scope("ingest_tcp") if instrument is not None else None
        )
        self._agg_collector = None
        self._registry = (
            instrument.registry if instrument is not None else None)
        super().__init__((host, port), _IngestHandler)
        if instrument is not None and aggregator is not None:
            # Surface the engine's plain-int counters (forwarded-tail
            # conflicts, timed rejects, series-limit rejects) on this
            # process's /metrics at scrape time.  After bind — a
            # failed construction must not leak the collector.
            from m3_tpu.aggregator.engine import instrument_aggregator

            self._agg_collector = instrument_aggregator(
                instrument, aggregator)

    def _drop_collector(self):
        if self._agg_collector is not None and self._registry is not None:
            self._registry.unregister_collector(self._agg_collector)
            self._agg_collector = None

    def shutdown(self):
        # Every call site stops via shutdown() (server_close is rarer):
        # drop the collector on either path, or the registry pins this
        # server's aggregator and scrapes it forever.
        self._drop_collector()
        super().shutdown()

    def server_close(self):
        self._drop_collector()
        super().server_close()

    @property
    def port(self) -> int:
        return self.server_address[1]


def serve_ingest_background(sink, host: str = "127.0.0.1", port: int = 0,
                            instrument=None, aggregator=None) -> IngestServer:
    srv = IngestServer(sink, host, port, instrument, aggregator)
    t = threading.Thread(target=srv.serve_forever, daemon=True)
    t.start()
    return srv
