"""InfluxDB line-protocol ingest.

Equivalent of `src/query/api/v1/handler/influxdb/write.go`: parse the
line protocol (measurement,tags fields timestamp), emit one series per
(measurement, field) pair named ``measurement_field`` with the point's
tags (the reference's ingestIterator promotes each field to __name__
the same way, write.go:73,142-181), and feed the standard tagged-write
path.  Value handling follows the reference: floats and ints ingest as
float64, booleans as 1/0, string fields are skipped.

Line protocol grammar handled here: backslash-escaped characters in
identifiers, double-quoted string field values with escapes, integer suffix ``i``, and the s/ms/us/ns timestamp precisions
of the ?precision= query parameter.
"""

from __future__ import annotations

from dataclasses import dataclass

PRECISION_NANOS = {"h": 3600 * 10**9, "m": 60 * 10**9, "s": 10**9,
                   "ms": 10**6, "us": 10**3, "u": 10**3, "ns": 1, "n": 1}


class LineProtocolError(ValueError):
    pass


@dataclass(frozen=True)
class InfluxPoint:
    measurement: bytes
    tags: tuple  # ((name, value) bytes pairs, sorted)
    fields: tuple  # ((name, float value) pairs; strings dropped)
    timestamp_nanos: int


def _scan_sections(line: str) -> tuple[str, str, str]:
    """(measurement+tags, fields, timestamp) honoring escapes and quoted
    field strings: sections split on unescaped spaces outside quotes."""
    sections = []
    cur = []
    in_quote = False
    i = 0
    while i < len(line):
        c = line[i]
        if c == "\\" and i + 1 < len(line):
            cur.append(line[i : i + 2])
            i += 2
            continue
        if c == '"':
            in_quote = not in_quote
            cur.append(c)
        elif c == " " and not in_quote:
            if cur:
                sections.append("".join(cur))
                cur = []
            if len(sections) == 2:
                # rest is the timestamp
                rest = line[i + 1 :].strip()
                return sections[0], sections[1], rest
        else:
            cur.append(c)
        i += 1
    if in_quote:
        raise LineProtocolError("unterminated string field")
    if cur:
        sections.append("".join(cur))
    if len(sections) < 2:
        raise LineProtocolError(f"missing fields in line {line!r}")
    while len(sections) < 3:
        sections.append("")
    return sections[0], sections[1], sections[2]


def _unescape(s: str) -> str:
    out = []
    i = 0
    while i < len(s):
        if s[i] == "\\" and i + 1 < len(s):
            out.append(s[i + 1])
            i += 2
        else:
            out.append(s[i])
            i += 1
    return "".join(out)


def _parse_key(section: str):
    """measurement[,tag=value...] with escape handling."""
    parts = []
    cur = []
    i = 0
    while i < len(section):
        c = section[i]
        if c == "\\" and i + 1 < len(section):
            cur.append(section[i : i + 2])
            i += 2
            continue
        if c == ",":
            parts.append("".join(cur))
            cur = []
        else:
            cur.append(c)
        i += 1
    parts.append("".join(cur))
    measurement = _unescape(parts[0])
    if not measurement:
        raise LineProtocolError("empty measurement")
    tags = []
    for p in parts[1:]:
        eq = -1
        j = 0
        while j < len(p):
            if p[j] == "\\":
                j += 2
                continue
            if p[j] == "=":
                eq = j
                break
            j += 1
        if eq < 0:
            raise LineProtocolError(f"bad tag {p!r}")
        tags.append((_unescape(p[:eq]).encode(), _unescape(p[eq + 1 :]).encode()))
    return measurement.encode(), tuple(sorted(tags))


def _parse_fields(section: str):
    """field=value[,field=value...]; strings dropped, bools -> 1/0,
    trailing-i ints -> float (the reference ingests ints as float64)."""
    fields = []
    cur = []
    in_quote = False
    parts = []
    i = 0
    while i < len(section):
        c = section[i]
        if c == "\\" and i + 1 < len(section):
            cur.append(section[i : i + 2])
            i += 2
            continue
        if c == '"':
            in_quote = not in_quote
            cur.append(c)
        elif c == "," and not in_quote:
            parts.append("".join(cur))
            cur = []
        else:
            cur.append(c)
        i += 1
    parts.append("".join(cur))
    for p in parts:
        if not p:
            continue
        # first UNESCAPED '=' splits key from value ('\=' is legal in
        # field keys, same scan as the tag parser)
        eq = -1
        j = 0
        while j < len(p):
            if p[j] == "\\":
                j += 2
                continue
            if p[j] == "=":
                eq = j
                break
            j += 1
        if eq < 0:
            raise LineProtocolError(f"bad field {p!r}")
        name = _unescape(p[:eq]).encode()
        raw = p[eq + 1 :]
        if raw.startswith('"'):
            continue  # string field: skipped (reference write.go:142)
        if raw in ("t", "T", "true", "True", "TRUE"):
            fields.append((name, 1.0))
        elif raw in ("f", "F", "false", "False", "FALSE"):
            fields.append((name, 0.0))
        else:
            if raw.endswith(("i", "u")):
                raw = raw[:-1]
            try:
                fields.append((name, float(raw)))
            except ValueError:
                raise LineProtocolError(f"bad field value {p!r}") from None
    return tuple(fields)


def parse_lines(body: str, precision: str = "ns",
                now_nanos: int | None = None) -> list[InfluxPoint]:
    mult = PRECISION_NANOS.get(precision)
    if mult is None:
        raise LineProtocolError(f"bad precision {precision!r}")
    points = []
    for raw_line in body.splitlines():
        line = raw_line.strip()
        if not line or line.startswith("#"):
            continue
        key, fields_s, ts_s = _scan_sections(line)
        measurement, tags = _parse_key(key)
        fields = _parse_fields(fields_s)
        if ts_s:
            try:
                ts = int(ts_s) * mult
            except ValueError:
                raise LineProtocolError(f"bad timestamp {ts_s!r}") from None
        else:
            if now_nanos is None:
                raise LineProtocolError("missing timestamp")
            ts = now_nanos
        points.append(InfluxPoint(measurement, tags, fields, ts))
    return points


def points_to_writes(points: list[InfluxPoint]):
    """Flatten to the tagged-write arrays: one series per (measurement,
    field), named measurement_field (reference write.go name promotion).

    Returns (docs, ts (int64 list), values (float list))."""
    from m3_tpu.index.doc import Document

    docs, ts, vals = [], [], []
    for p in points:
        for fname, fval in p.fields:
            name = p.measurement + b"_" + fname if fname != b"value" else p.measurement
            # promoted name wins over any literal __name__ point tag so
            # the document's name and its series id always agree
            tags = {**dict(p.tags), b"__name__": name}
            sid = name + b"{" + b",".join(
                k + b"=" + v for k, v in sorted(p.tags)) + b"}"
            docs.append(Document.from_tags(sid, tags))
            ts.append(p.timestamp_nanos)
            vals.append(fval)
    return docs, ts, vals
