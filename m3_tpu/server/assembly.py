"""Config-driven server assembly: one call from NodeConfig to a running
node.

Equivalent of the reference's monolithic startup
(`src/dbnode/server/server.go:171 Run`: config → pools → topology →
storage.NewDatabase → servers → bootstrap; and the query side
`src/query/server/query.go:195`): build the instrument registry, the
Database (with namespaces from config), bootstrap it, open the mediator
loop, and serve the HTTP API.  `Assembly.close()` tears down in reverse
order.
"""

from __future__ import annotations

import dataclasses
import time as _time

from m3_tpu import instrument
from m3_tpu.core.config import NodeConfig, load_config, parse_duration
from m3_tpu.server.http_api import ApiContext, serve_background
from m3_tpu.storage.database import Database, DatabaseOptions, NamespaceOptions
from m3_tpu.storage.mediator import Mediator


@dataclasses.dataclass
class Assembly:
    config: NodeConfig
    registry: "instrument.Registry"
    db: Database
    mediator: Mediator | None
    http_server: object | None
    carbon_server: object | None = None
    tracer: object | None = None
    admin_server: object | None = None
    kv: object | None = None
    rpc_server: object | None = None
    peer_handles: list = dataclasses.field(default_factory=list)
    scrubber: object | None = None
    topology: object | None = None   # cluster.topology.TopologyWatcher
    migrator: object | None = None   # storage.migration.ShardMigrator
    query_server: object | None = None  # query.remote.QueryServer
    remote_stores: list = dataclasses.field(default_factory=list)
    downsampler: object | None = None   # coordinator.downsample.Downsampler
    checkpointer: object | None = None  # aggregator.checkpoint driver
    selfmon: object | None = None       # instrument.selfmon.SelfMonitor
    controller: object | None = None    # x.controller.Controller

    @property
    def port(self) -> int | None:
        return self.http_server.server_address[1] if self.http_server else None

    @property
    def rpc_port(self) -> int | None:
        return self.rpc_server.port if self.rpc_server else None

    @property
    def query_port(self) -> int | None:
        return self.query_server.port if self.query_server else None

    @property
    def carbon_port(self) -> int | None:
        return self.carbon_server.port if self.carbon_server else None

    @property
    def admin_port(self) -> int | None:
        return self.admin_server.server_address[1] if self.admin_server else None

    def close(self) -> None:
        for h in self.peer_handles:
            h.close()
        for r in self.remote_stores:
            r.close()
        if self.query_server is not None:
            self.query_server.shutdown()
            self.query_server.server_close()
        if self.rpc_server is not None:
            self.rpc_server.shutdown()
            self.rpc_server.server_close()
        if self.admin_server is not None:
            self.admin_server.shutdown()
            self.admin_server.server_close()
        if self.carbon_server is not None:
            self.carbon_server.shutdown()
            self.carbon_server.server_close()
        if self.http_server is not None:
            self.http_server.shutdown()
            self.http_server.server_close()
        if self.mediator is not None:
            self.mediator.close()
        if self.migrator is not None:
            self.migrator.close()
        if self.topology is not None:
            self.topology.close()
        # the KV client closes only after every server that used it is
        # down — a racing admin request must not reconnect a closed store
        if self.kv is not None and hasattr(self.kv, "close"):
            self.kv.close()
        self.db.close()

    def drain(self, handoff_timeout_s: float = 60.0) -> None:
        """True SIGTERM drain (the reference dbnode's graceful shutdown
        discipline): stop taking ingest → persist what we hold → wait
        for any LEAVING shards to cut over to their new owners → tear
        down.  The RPC listener stays up until the very end so peers
        can stream this node's blocks throughout the handoff window.

        Idempotent-ish with close(): the servers stopped here are
        nulled so close() skips them."""
        import time as _time

        from m3_tpu.instrument import logger as _logger

        log = _logger("server.assembly")
        for attr in ("carbon_server", "http_server"):
            srv = getattr(self, attr)
            if srv is not None:
                srv.shutdown()
                srv.server_close()
                setattr(self, attr, None)
        if self.mediator is not None:
            self.mediator.close()
            self.mediator = None
        # Persist everything persistable: seal+flush whatever left the
        # warm window, snapshot the still-open buffers, rotate the WAL
        # — a restart replays cleanly AND peers can stream every
        # flushed block.  (The active warm block cannot become a
        # fileset early; replicas + the snapshot cover it.)
        try:
            now = _time.time_ns()
            self.db.tick(now)
            self.db.snapshot()
        except Exception:  # noqa: BLE001 — drain must reach close()
            log.exception("drain: final flush/snapshot failed")
        if self.checkpointer is not None:
            # Final arena checkpoint: a SIGTERM'd aggregator resumes
            # its open windows on restart (aggregator/checkpoint.py)
            try:
                self.checkpointer.save()
            except Exception:  # noqa: BLE001 — drain must reach close()
                log.exception("drain: aggregator checkpoint failed")
        if self.migrator is not None:
            if not self.migrator.wait_handed_off(handoff_timeout_s):
                log.warning(
                    "drain: handoff incomplete after %.0fs "
                    "(LEAVING shards remain; replicas will repair)",
                    handoff_timeout_s,
                )
        self.close()


def namespace_options(ns_cfg) -> NamespaceOptions:
    kw = {}
    # cardinality sizing: 0 keeps the storage defaults; a node serving
    # million-series traffic raises slot_capacity per shard (the soak
    # found the default wall at 2^17 series/shard)
    if ns_cfg.slot_capacity:
        kw["slot_capacity"] = ns_cfg.slot_capacity
    if ns_cfg.sample_capacity:
        kw["sample_capacity"] = ns_cfg.sample_capacity
    return NamespaceOptions(
        block_size_nanos=parse_duration(ns_cfg.block_size),
        retention_nanos=parse_duration(ns_cfg.retention),
        buffer_past_nanos=parse_duration(ns_cfg.buffer_past),
        buffer_future_nanos=parse_duration(ns_cfg.buffer_future),
        cold_writes_enabled=ns_cfg.cold_writes_enabled,
        num_shards=ns_cfg.num_shards,
        **kw,
    )


def run_node(source, start_mediator: bool | None = None,
             serve_http: bool = True, ruleset=None) -> Assembly:
    """Boot a node from a YAML path/string or a NodeConfig.

    Mirrors server.Run's order: config validate → storage → bootstrap →
    background maintenance → front door.  `ruleset` (a
    metrics.rules.RuleSet) is required when the coordinator config sets
    `downsample: true` — rules are programmatic/KV objects in the
    reference too (`metrics/rules` in etcd), not static YAML.
    """
    from m3_tpu.core.config import ConfigError

    cfg = source if isinstance(source, NodeConfig) else load_config(source)
    cfg.validate()
    if (cfg.coordinator is not None and cfg.coordinator.downsample
            and ruleset is None):
        raise ConfigError(
            "coordinator.downsample=true requires run_node(..., ruleset=...)"
        )
    if cfg.coordinator is not None and cfg.coordinator.arena_ingest:
        from m3_tpu.aggregator import arena

        arena.set_ingest_impl(cfg.coordinator.arena_ingest)
    if cfg.coordinator is not None and cfg.coordinator.arena_layout:
        from m3_tpu.aggregator import arena

        # Must land BEFORE any MetricList is built: arenas bind their
        # layout at construction (aggregator/arena.py layout seam).
        arena.set_arena_layout(cfg.coordinator.arena_layout)
    # Device-boundary knobs FIRST: the memory budget must be installed
    # before any arena/buffer reserves against it, and the stage
    # breakers bind their thresholds at first guarded call.
    from m3_tpu.x import devguard as _devguard, membudget as _membudget

    _membudget.set_budget(cfg.device.mem_budget)
    _devguard.configure(
        failures=cfg.device.breaker_failures,
        reset_s=parse_duration(cfg.device.breaker_reset) / 1e9)
    # Disk ledger next (membudget's twin): armed before the Database
    # exists so the very first mediator tick refreshes real watermarks.
    # reset() when disabled — the ledger is process-global and a prior
    # in-process node's configuration must not leak into this one.
    from m3_tpu.x import diskbudget as _diskbudget

    if cfg.disk.enabled:
        _diskbudget.configure(
            cfg.db.root,
            capacity=cfg.disk.capacity,
            reserve=cfg.disk.reserve,
            low_ratio=cfg.disk.low_ratio,
            critical_ratio=cfg.disk.critical_ratio)
    else:
        _diskbudget.reset()
    registry = instrument.new_registry()
    scope = registry.scope(cfg.metrics_prefix)
    # Mirror the process-global fault/retry counters onto this node's
    # /metrics so dtest scenarios can assert injected faults and retry
    # activity from outside the process.
    from m3_tpu.x import register_metrics

    register_metrics(registry)
    # Process-level self-observation (RSS/CPU/threads/FDs/uptime): the
    # runtime facts debug.py only ever put in the on-demand debug zip
    # now ride every scrape — the selfmon loop and operator dashboards
    # see a node eating memory, not just the post-mortem.
    from m3_tpu.instrument.procstats import install_process_collector

    install_process_collector(registry, scope)
    tracer = None
    if cfg.coordinator is not None and cfg.coordinator.tracing:
        from m3_tpu.instrument.tracing import Tracer

        tracer = Tracer()

    from m3_tpu.storage.limits import LimitsOptions, QueryLimits

    limits = QueryLimits(
        LimitsOptions(
            max_docs_matched=cfg.db.limits.max_docs_matched,
            max_series_read=cfg.db.limits.max_series_read,
            max_bytes_read=cfg.db.limits.max_bytes_read,
            lookback_s=parse_duration(cfg.db.limits.lookback) / 1e9,
        ),
        instrument=scope,
    )
    namespaces = {
        name: namespace_options(ns) for name, ns in cfg.db.namespaces.items()
    }
    if cfg.selfmon.enabled and cfg.selfmon.namespace not in namespaces:
        # Auto-provision the reserved self-monitoring namespace as an
        # ordinary db.namespaces entry (declare it in config to tune
        # retention/blocks).  num_shards follows the serving namespace
        # so a placement installed by the topology watcher scopes it
        # identically — selfmon writes cross the same ownership gate
        # as user ingest.
        base = cfg.db.namespaces.get(
            cfg.coordinator.namespace if cfg.coordinator is not None
            else "default")
        namespaces[cfg.selfmon.namespace] = NamespaceOptions(
            num_shards=base.num_shards if base is not None else 4)
    db = Database(
        DatabaseOptions(
            root=cfg.db.root, commitlog_enabled=cfg.db.commitlog_enabled
        ),
        namespaces=namespaces,
        instrument=scope,
        tracer=tracer,
        limits=limits,
    )
    # Tear down everything already started if a later step fails (e.g.
    # the carbon port is taken) — a half-built node must not leak its
    # mediator thread or bound HTTP socket.
    asm = Assembly(cfg, registry, db, None, None, None, tracer)
    try:
        # Control plane FIRST: the topology watcher must install this
        # node's shard ownership before bootstrap so WAL replay and the
        # peers pass are placement-scoped from the very first byte.
        need_kv = (
            cfg.db.kv_endpoint is not None
            or cfg.db.instance_id is not None
            or (cfg.coordinator is not None
                and cfg.coordinator.admin_listen_port is not None)
        )
        if need_kv:
            if cfg.db.kv_endpoint:
                # shared external control plane (etcd role) — survives
                # this node and is visible to every replica
                from m3_tpu.cluster.kv_remote import RemoteKVStore

                h, _, p = cfg.db.kv_endpoint.rpartition(":")
                asm.kv = RemoteKVStore((h, int(p)))
            else:
                from m3_tpu.cluster.kv import KVStore

                asm.kv = KVStore(cfg.db.root)  # file-backed control plane
        if cfg.db.instance_id is not None and asm.kv is not None:
            from m3_tpu.cluster.placement import PlacementService
            from m3_tpu.cluster.topology import TopologyWatcher
            from m3_tpu.storage.migration import ShardMigrator

            asm.topology = TopologyWatcher(asm.kv, cfg.db.instance_id)
            asm.migrator = ShardMigrator(
                db, asm.topology, PlacementService(asm.kv),
                stream_blocks_per_tick=cfg.mediator.migrate_blocks,
                grace_ticks=cfg.mediator.migrate_grace_ticks,
                instrument=scope,
            )

        db.bootstrap()

        # Wire peers bootstrap: after local fs+commitlog recovery, pull
        # any (shard, block) filesets a replica peer has that this node
        # lacks, over the socket RPC (the bootstrap chain's final
        # `peers` stage — bootstrapper/peers/source.go).  Unreachable
        # peers are skipped; repair converges them later.  With a
        # topology watcher installed the pass is scoped to
        # placement-owned shards (peers_bootstrap reads the ownership
        # the watcher installed) — a restarting node pulls its shards,
        # never every peer's full dataset.
        if cfg.db.peers:
            from m3_tpu.server.rpc import RemoteDatabase

            asm.peer_handles = [
                RemoteDatabase((h, int(p)))
                for h, _, p in (a.rpartition(":") for a in cfg.db.peers)
            ]
            if cfg.db.bootstrap_peers:
                from m3_tpu.storage.repair import peers_bootstrap

                for ns_name in cfg.db.namespaces:
                    peers_bootstrap(db, asm.peer_handles, ns_name)

        if cfg.db.rpc_listen_port is not None:
            from m3_tpu.server.rpc import serve_rpc_background

            asm.rpc_server = serve_rpc_background(
                db, host=cfg.db.rpc_listen_host, port=cfg.db.rpc_listen_port
            )
            if cfg.disk.enabled:
                # CRITICAL watermark → refuse write batches un-acked
                # (typed RPC_ERR the session's consistency level
                # absorbs); reads/repair/ticks are never gated.
                asm.rpc_server.ingest_gate = _diskbudget.check_ingest

        # Query federation (query/remote): serve THIS node's storage to
        # peer coordinators over QUERY_FETCH, and/or federate peer
        # coordinators' stores into this node's engine.  Each remote
        # gets the process-shared per-peer circuit breaker so a dead
        # region fails fast for every query at once.
        ns0 = (cfg.coordinator.namespace if cfg.coordinator is not None
               else "default")
        if cfg.query.listen_port is not None:
            from m3_tpu.query.remote import serve_query_background
            from m3_tpu.query.storage_adapter import DatabaseStorage

            asm.query_server = serve_query_background(
                DatabaseStorage(db, ns0),
                host=(cfg.coordinator.listen_host
                      if cfg.coordinator is not None else "127.0.0.1"),
                port=cfg.query.listen_port,
                tracer=tracer,
            )
        if cfg.query.remotes:
            from m3_tpu.query.remote import RemoteStorage
            from m3_tpu.x.breaker import breaker_for

            breaker_reset_s = parse_duration(cfg.query.breaker_reset) / 1e9
            asm.remote_stores = [
                RemoteStorage(
                    (h, int(p)),
                    timeout_s=parse_duration(cfg.query.default_timeout) / 1e9,
                    breaker=breaker_for(
                        f"query:{h}:{p}",
                        failure_threshold=cfg.query.breaker_failures,
                        reset_timeout_s=breaker_reset_s),
                )
                for h, _, p in (a.rpartition(":") for a in cfg.query.remotes)
            ]

        # Corruption scrubber: always constructed (the admin endpoint
        # scrubs on demand); attached to the mediator loop only when a
        # per-tick budget is configured.  Peers double as the repair
        # source — a quarantined (shard, block) hole heals from a
        # replica on the next sweep.
        from m3_tpu.storage.scrub import Scrubber

        asm.scrubber = Scrubber(
            db, peers=asm.peer_handles,
            budget_volumes=cfg.mediator.scrub_volumes, instrument=scope,
        )

        # Downsampler BEFORE the mediator: its window drain and arena
        # checkpoint ride the mediator tick, and a checkpoint restore
        # must land before any traffic re-opens the windows.
        downsampler = None
        if (serve_http and cfg.coordinator is not None
                and cfg.coordinator.downsample):
            from m3_tpu.coordinator.downsample import Downsampler

            downsampler = Downsampler(
                db, ruleset, namespace=cfg.coordinator.namespace
            )
            asm.downsampler = downsampler
            if cfg.coordinator.checkpoint_every > 0:
                from pathlib import Path as _Path

                from m3_tpu.aggregator.checkpoint import (
                    AggregatorCheckpointer,
                )

                asm.checkpointer = AggregatorCheckpointer(
                    downsampler,
                    _Path(cfg.db.root) / "checkpoint" / "aggregator.ckpt",
                    instrument=scope,
                )
                # Resume open aggregation windows from the last
                # checkpoint (SIGKILL/SIGTERM recovery); a corrupt file
                # is moved aside and the node boots fresh.
                asm.checkpointer.restore()

        # Self-monitoring BEFORE the mediator: the scrape task rides
        # the tick loop, and its SLO evaluator binds the selfmon
        # namespace engine at construction.
        if cfg.selfmon.enabled:
            from m3_tpu.instrument.selfmon import SelfMonitor
            from m3_tpu.query.slo import default_rules, rule_from_dict

            rules = (default_rules(cfg.metrics_prefix)
                     if cfg.selfmon.default_rules else [])
            rules += [rule_from_dict(r) for r in cfg.selfmon.rules]
            asm.selfmon = SelfMonitor(
                db, registry,
                namespace=cfg.selfmon.namespace,
                instance=(cfg.selfmon.instance or cfg.db.instance_id
                          or "self"),
                budget=cfg.selfmon.budget,
                peers=cfg.selfmon.peers,
                scrape_timeout_s=parse_duration(
                    cfg.selfmon.scrape_timeout) / 1e9,
                slo_rules=rules,
                slo_deadline_s=parse_duration(
                    cfg.selfmon.slo_deadline) / 1e9,
                instrument=scope,
            )

        # Admission is shared by the HTTP front door and the
        # controller's query_slots actuator — build it before either
        # consumer exists.
        admission = None
        if cfg.coordinator is not None:
            from m3_tpu.x.admission import AdmissionController

            admission = AdmissionController(
                max_concurrent=cfg.query.max_concurrent,
                max_queue=cfg.query.max_queue,
                queue_timeout_s=parse_duration(cfg.query.queue_timeout) / 1e9,
            )

        # The self-healing control plane BEFORE the mediator (its pass
        # rides the tick loop right after the selfmon stage, acting on
        # the verdicts evaluated the same tick).  Bindings resolve by
        # rule NAME against the evaluator's configured rule set
        # (slo.rules()) — an unconfigured name simply does not bind.
        if (cfg.controller.enabled and asm.selfmon is not None
                and getattr(asm.selfmon, "slo", None) is not None):
            from m3_tpu.x import controller as xctl
            from m3_tpu.x import membudget as _mb

            ccfg = cfg.controller
            slo = asm.selfmon.slo
            known = set(slo.rules())
            reg = xctl.ActuatorRegistry()
            bindings: list = []

            def _bind(rule: str, acts: list, name: str = "", **kw) -> None:
                if rule and rule in known and acts:
                    bindings.append(xctl.Binding(
                        rule=rule, actuators=tuple(acts),
                        name=name or rule,
                        fire_ticks=ccfg.fire_ticks,
                        clear_ticks=ccfg.clear_ticks,
                        clear_burn=ccfg.clear_burn,
                        hold_ticks=ccfg.hold_ticks, **kw))

            slot_acts = []
            if admission is not None:
                reg.register(xctl.admission_actuator(
                    admission, floor=ccfg.query_floor,
                    step=ccfg.query_step))
                slot_acts = ["query_slots"]
            _bind(ccfg.query_rule, slot_acts, name="query-burn")
            _bind(ccfg.ingest_rule, slot_acts, name="ingest-burn")
            dev_acts = [reg.register(
                xctl.devguard_fallback_actuator()).name]
            if asm.checkpointer is not None:
                dev_acts.append(reg.register(
                    xctl.checkpoint_actuator(asm.checkpointer)).name)
            budget_b = _mb.budget()
            if budget_b > 0:
                floor_b = int(budget_b * ccfg.mem_floor_frac)
                step_b = max(1, (budget_b - floor_b) // ccfg.mem_steps)
                dev_acts.append(reg.register(xctl.membudget_actuator(
                    floor_bytes=floor_b, step_bytes=step_b)).name)
            _bind(ccfg.device_rule, dev_acts, name="device-burn")
            if asm.migrator is not None:
                reg.register(xctl.rebalance_actuator(asm.migrator))
                _bind(ccfg.node_rule, ["rebalance"], name="node-burn",
                      sustain_window=ccfg.sustain_window,
                      sustain_burn=ccfg.sustain_burn)
            if cfg.disk.enabled:
                # Disk-burn → a cleanup PULSE: the watermark gate sheds
                # ingest on its own; the controller's job is to force a
                # reclaim pass the cadence wouldn't run yet.
                reg.register(xctl.emergency_cleanup_actuator(
                    lambda: db.cleanup(_time.time_ns())))
                _bind(ccfg.disk_rule, ["emergency_cleanup"],
                      name="disk-burn")
            asm.controller = xctl.Controller(
                reg, bindings, burn_source=slo.status,
                instrument=scope,
                min_interval_s=parse_duration(
                    ccfg.min_action_interval) / 1e9,
                history=xctl.BurnHistory(
                    slo.engine,
                    metric=f"{cfg.metrics_prefix}_slo_burn",
                    deadline_s=parse_duration(
                        ccfg.history_deadline) / 1e9))

        # Disk-pressure stage for the mediator: refresh the ledger every
        # pass; at/above LOW run cleanup EAGERLY (superseded volumes,
        # stale snapshots, aged quarantine, flushed commitlog segments)
        # instead of waiting out the cleanup cadence.  Shedding itself
        # happens at the ingest gates off the cached level — this stage
        # is what keeps that cache fresh.
        _disk_stage = None
        if cfg.disk.enabled:
            def _disk_stage(now: int, _db=db) -> dict:
                dsnap = _diskbudget.refresh()
                out = {"level": dsnap["level"],
                       "free_ratio": round(dsnap["free_ratio"], 4)}
                if dsnap["level_value"] >= 1:
                    out["cleanup"] = _db.cleanup(now)
                return out

        if cfg.mediator.enabled if start_mediator is None else start_mediator:
            asm.mediator = Mediator(
                db,
                tick_interval_s=parse_duration(cfg.mediator.tick_interval) / 1e9,
                snapshot_every=cfg.mediator.snapshot_every,
                cleanup_every=cfg.mediator.cleanup_every,
                scrubber=(asm.scrubber
                          if cfg.mediator.scrub_volumes > 0 else None),
                scrub_every=cfg.mediator.scrub_every,
                migrator=asm.migrator,
                migrate_every=cfg.mediator.migrate_every,
                downsampler=downsampler,
                checkpointer=asm.checkpointer,
                checkpoint_every=(cfg.coordinator.checkpoint_every
                                  if cfg.coordinator is not None else 0),
                selfmon=asm.selfmon,
                selfmon_every=cfg.selfmon.every,
                controller=asm.controller,
                controller_every=cfg.controller.every,
                diskpressure=_disk_stage,
                instrument=scope,
            )
            asm.mediator.open()

        if serve_http and cfg.coordinator is not None:
            ctx = ApiContext(
                db, namespace=cfg.coordinator.namespace, registry=registry,
                metrics_scope=scope,
                downsampler=downsampler, tracer=tracer,
                migrator=asm.migrator,
                admission=admission,
                query_timeout_s=parse_duration(cfg.query.default_timeout) / 1e9,
                slow_query_fraction=cfg.query.slow_query_fraction,
                remotes=asm.remote_stores,
                remotes_required=cfg.query.remotes_required,
                checkpointer=asm.checkpointer,
                selfmon=asm.selfmon,
                controller=asm.controller,
            )

            # Admission/slow-query observability: query_active,
            # query_shed_total etc. ride the same scrape-time collector
            # pattern as the fault/retry/breaker mirrors.
            def collect_query(_ctx=ctx) -> None:
                m = _ctx.admission.metrics()
                scope.gauge("query_active").update(m["active"])
                scope.gauge("query_queued").update(m["waiting"])
                scope.gauge("query_shed_total").update(m["shed_total"])
                scope.gauge("query_admitted_total").update(m["admitted_total"])
                scope.gauge("slow_query_total").update(_ctx.slow_query_total)

            registry.register_collector(collect_query)
            asm.http_server = serve_background(
                ctx, cfg.coordinator.listen_host, cfg.coordinator.listen_port
            )
        if (serve_http and cfg.coordinator is not None
                and cfg.coordinator.carbon_listen_port is not None):
            from m3_tpu.metrics.carbon import serve_carbon_background

            ns_name = cfg.coordinator.namespace

            def carbon_sink(docs, ts, vals, _ds=downsampler):
                # Carbon rides the same downsample-then-write path as
                # HTTP writes (the reference's carbon ingester feeds the
                # downsampler too) so rules apply regardless of ingest
                # protocol.
                keep = None
                if _ds is not None:
                    keep = _ds.write_batch(docs, ts, vals)
                if keep is not None:
                    import numpy as _np

                    idx = _np.nonzero(keep)[0]
                    if not len(idx):
                        return
                    docs = [docs[i] for i in idx]
                    ts, vals = ts[idx], vals[idx]
                from m3_tpu.storage.database import ShardNotOwnedError

                try:
                    db.write_tagged_batch(ns_name, docs, ts, vals)
                except ShardNotOwnedError:
                    # Placement-scoped node fed carbon traffic for
                    # shards it does not own: carbon has no ack channel
                    # to push back on, and the connection thread must
                    # survive (mixed batches partial-accept inside
                    # write_batch; only an ALL-unowned flush lands
                    # here).  Counted via db's shard_not_owned.
                    pass

            asm.carbon_server = serve_carbon_background(
                carbon_sink,
                cfg.coordinator.listen_host, cfg.coordinator.carbon_listen_port,
                instrument=scope,
            )
        if (serve_http and cfg.coordinator is not None
                and cfg.coordinator.admin_listen_port is not None):
            from m3_tpu.server.admin_api import (
                AdminContext, serve_admin_background,
            )

            # asm.kv was built up front (the topology watcher shares it)
            admin_ctx = AdminContext(asm.kv, db, scrubber=asm.scrubber,
                                     migrator=asm.migrator,
                                     selfmon=asm.selfmon,
                                     controller=asm.controller)
            # live-tune query limits + cache budget through runtime
            # options (runtime_options_manager.go's role)
            def _limit_applier(lim):
                def apply(value, _lim=lim):
                    _lim.limit = int(value)
                return apply

            appliers = [
                ("max_docs_matched", _limit_applier(limits.docs)),
                ("max_series_read", _limit_applier(limits.series)),
                ("max_bytes_read", _limit_applier(limits.bytes)),
                ("block_cache_max_bytes",
                 lambda v: setattr(db.block_cache, "max_bytes", int(v))),
                ("write_new_series_limit_per_sec",
                 lambda v: db.new_series_limiter.set_rate(float(v))),
            ]
            for opt, apply in appliers:
                admin_ctx.runtime.on_change(opt, apply)
                # replay the persisted value: the KV watch fired during
                # AdminContext construction, BEFORE this listener existed
                # — a restart must re-apply tuned values, not report
                # them while running untuned
                persisted = admin_ctx.runtime.get(opt)
                if persisted:
                    apply(persisted)
            asm.admin_server = serve_admin_background(
                admin_ctx, cfg.coordinator.listen_host,
                cfg.coordinator.admin_listen_port,
            )
    except BaseException:
        asm.close()
        raise
    return asm
