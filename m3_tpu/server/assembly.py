"""Config-driven server assembly: one call from NodeConfig to a running
node.

Equivalent of the reference's monolithic startup
(`src/dbnode/server/server.go:171 Run`: config → pools → topology →
storage.NewDatabase → servers → bootstrap; and the query side
`src/query/server/query.go:195`): build the instrument registry, the
Database (with namespaces from config), bootstrap it, open the mediator
loop, and serve the HTTP API.  `Assembly.close()` tears down in reverse
order.
"""

from __future__ import annotations

import dataclasses

from m3_tpu import instrument
from m3_tpu.core.config import NodeConfig, load_config, parse_duration
from m3_tpu.server.http_api import ApiContext, serve_background
from m3_tpu.storage.database import Database, DatabaseOptions, NamespaceOptions
from m3_tpu.storage.mediator import Mediator


@dataclasses.dataclass
class Assembly:
    config: NodeConfig
    registry: "instrument.Registry"
    db: Database
    mediator: Mediator | None
    http_server: object | None

    @property
    def port(self) -> int | None:
        return self.http_server.server_address[1] if self.http_server else None

    def close(self) -> None:
        if self.http_server is not None:
            self.http_server.shutdown()
            self.http_server.server_close()
        if self.mediator is not None:
            self.mediator.close()
        self.db.close()


def namespace_options(ns_cfg) -> NamespaceOptions:
    return NamespaceOptions(
        block_size_nanos=parse_duration(ns_cfg.block_size),
        retention_nanos=parse_duration(ns_cfg.retention),
        buffer_past_nanos=parse_duration(ns_cfg.buffer_past),
        buffer_future_nanos=parse_duration(ns_cfg.buffer_future),
        cold_writes_enabled=ns_cfg.cold_writes_enabled,
        num_shards=ns_cfg.num_shards,
    )


def run_node(source, start_mediator: bool | None = None,
             serve_http: bool = True, ruleset=None) -> Assembly:
    """Boot a node from a YAML path/string or a NodeConfig.

    Mirrors server.Run's order: config validate → storage → bootstrap →
    background maintenance → front door.  `ruleset` (a
    metrics.rules.RuleSet) is required when the coordinator config sets
    `downsample: true` — rules are programmatic/KV objects in the
    reference too (`metrics/rules` in etcd), not static YAML.
    """
    from m3_tpu.core.config import ConfigError

    cfg = source if isinstance(source, NodeConfig) else load_config(source)
    cfg.validate()
    if (cfg.coordinator is not None and cfg.coordinator.downsample
            and ruleset is None):
        raise ConfigError(
            "coordinator.downsample=true requires run_node(..., ruleset=...)"
        )
    registry = instrument.new_registry()
    scope = registry.scope(cfg.metrics_prefix)

    db = Database(
        DatabaseOptions(
            root=cfg.db.root, commitlog_enabled=cfg.db.commitlog_enabled
        ),
        namespaces={
            name: namespace_options(ns) for name, ns in cfg.db.namespaces.items()
        },
        instrument=scope,
    )
    db.bootstrap()

    mediator = None
    if cfg.mediator.enabled if start_mediator is None else start_mediator:
        mediator = Mediator(
            db,
            tick_interval_s=parse_duration(cfg.mediator.tick_interval) / 1e9,
            snapshot_every=cfg.mediator.snapshot_every,
            cleanup_every=cfg.mediator.cleanup_every,
            instrument=scope,
        )
        mediator.open()

    http_server = None
    if serve_http and cfg.coordinator is not None:
        downsampler = None
        if cfg.coordinator.downsample:
            from m3_tpu.coordinator.downsample import Downsampler

            downsampler = Downsampler(
                db, ruleset, namespace=cfg.coordinator.namespace
            )
        ctx = ApiContext(
            db, namespace=cfg.coordinator.namespace, registry=registry,
            downsampler=downsampler,
        )
        http_server = serve_background(
            ctx, cfg.coordinator.listen_host, cfg.coordinator.listen_port
        )
    return Assembly(cfg, registry, db, mediator, http_server)
